"""Message envelope for the cross-silo comm layer.

Mirrors the reference's Message semantics (reference:
core/distributed/communication/message.py:5-83 — dict envelope with
MSG_ARG_KEY_TYPE/SENDER/RECEIVER + model-params payload), with the pickle
JSON+dict body replaced by the tensor-native wire format (serialization.py).
"""
from __future__ import annotations

import dataclasses
from typing import Any

from . import serialization

# canonical keys (reference: message.py:9-24)
ARG_TYPE = "msg_type"
ARG_SENDER = "sender"
ARG_RECEIVER = "receiver"
ARG_MODEL_PARAMS = "model_params"
ARG_NUM_SAMPLES = "num_samples"
ARG_CLIENT_STATUS = "client_status"
ARG_ROUND = "round_idx"


@dataclasses.dataclass
class Message:
    type: str
    sender_id: int
    receiver_id: int
    params: dict = dataclasses.field(default_factory=dict)

    def add(self, key: str, value: Any) -> "Message":
        self.params[key] = value
        return self

    def get(self, key: str, default=None) -> Any:
        return self.params.get(key, default)

    # reference API names (message.py:40-70)
    add_params = add
    get_params = get

    def encode(self) -> bytes:
        return serialization.encode({
            ARG_TYPE: self.type,
            ARG_SENDER: self.sender_id,
            ARG_RECEIVER: self.receiver_id,
            "params": self.params,
        })

    @classmethod
    def decode(cls, data: bytes) -> "Message":
        d = serialization.decode(data)
        return cls(d[ARG_TYPE], int(d[ARG_SENDER]), int(d[ARG_RECEIVER]),
                   d["params"])
