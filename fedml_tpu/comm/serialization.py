"""Tensor-native wire format — no pickle anywhere.

The reference serializes model payloads with pickle over every transport
(reference: core/distributed/communication/grpc/grpc_comm_manager.py:78-90
pickle.dumps(msg), mpi/com_manager.py:77 comm.send(python object), MQTT+S3
JSON + pickled S3 blobs). Pickle is slow for large tensors and unsafe across
trust boundaries; here the wire format is:

    [4B header_len][header JSON][raw tensor buffers, contiguous]

Pytrees are JSON with ndarray leaves swapped for {"__nd__": i, dtype, shape}
descriptors pointing into the buffer region — zero-copy on encode (tobytes of
C-contiguous arrays) and a single frombuffer per tensor on decode.

This layer is representation only (lossless framing + integrity). Payload
COMPRESSION lives one layer up: the wire codec plane (codec.py) rewrites a
message's training payloads into self-describing compressed trees before
they reach encode(), and this frame format carries them unchanged — sparse
index/value arrays are just more ndarray leaves.
"""
from __future__ import annotations

import json
import struct
from typing import Any

import numpy as np

Pytree = Any
_MAGIC = b"FT01"        # trailer-less frame
_MAGIC_CRC = b"FT02"    # frame with a CRC-32C trailer (last 8 bytes)


def _encode_obj(obj: Any, buffers: list[bytes]):
    if isinstance(obj, np.ndarray):
        idx = len(buffers)
        arr = np.ascontiguousarray(obj)
        buffers.append(arr.tobytes())
        return {"__nd__": idx, "dtype": str(arr.dtype), "shape": list(arr.shape)}
    if isinstance(obj, (np.integer,)):
        return int(obj)
    if isinstance(obj, (np.floating,)):
        return float(obj)
    if isinstance(obj, dict):
        for k in obj:
            if not isinstance(k, str):
                raise TypeError(
                    f"dict keys must be str for lossless JSON round-trip, got "
                    f"{type(k).__name__} key {k!r}"
                )
        return {k: _encode_obj(v, buffers) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        enc = [_encode_obj(v, buffers) for v in obj]
        return {"__tuple__": enc} if isinstance(obj, tuple) else enc
    if obj is None or isinstance(obj, (bool, int, float, str)):
        return obj
    # jax arrays and other array-likes
    if hasattr(obj, "__array__"):
        return _encode_obj(np.asarray(obj), buffers)
    raise TypeError(f"unserializable type {type(obj)!r} (no pickle fallback by design)")


def _decode_obj(obj: Any, buffers: list[memoryview]):
    if isinstance(obj, dict):
        if "__nd__" in obj:
            buf = buffers[obj["__nd__"]]
            return np.frombuffer(buf, dtype=np.dtype(obj["dtype"])).reshape(
                obj["shape"]
            ).copy()
        if "__tuple__" in obj:
            return tuple(_decode_obj(v, buffers) for v in obj["__tuple__"])
        return {k: _decode_obj(v, buffers) for k, v in obj.items()}
    if isinstance(obj, list):
        return [_decode_obj(v, buffers) for v in obj]
    return obj


_CRC_TAG = b"C32C"


def encode(tree: Pytree) -> bytes:
    """pytree (dict/list/scalars/ndarray/jax arrays) -> framed bytes.
    When the native tier is available, the frame is tagged FT02 and a
    CRC-32C trailer is appended (native/fedml_native.cpp crc32c) so
    transport corruption surfaces as a clean ValueError instead of
    silently-wrong tensors. The magic — not content sniffing — decides
    whether a trailer exists: a tensor payload that happens to end with the
    tag bytes can never be misparsed as a trailer. Senders without the
    native lib emit trailer-less FT01; FT02 receivers without it strip the
    trailer unverified."""
    buffers: list[bytes] = []
    header = _encode_obj(tree, buffers)
    sizes = [len(b) for b in buffers]
    head = json.dumps({"tree": header, "sizes": sizes}).encode()
    from ..native import crc32c

    frame = b"".join([_MAGIC_CRC, struct.pack("<I", len(head)), head]
                     + buffers)
    crc = crc32c(frame)
    if crc is None:
        # no native lib: emit trailer-less FT01 (same body, different magic)
        return _MAGIC + frame[4:]
    return frame + _CRC_TAG + struct.pack("<I", crc)


def decode(data: bytes | memoryview) -> Pytree:
    data = memoryview(data)
    magic = bytes(data[:4])
    if magic not in (_MAGIC, _MAGIC_CRC):
        raise ValueError("bad frame magic (not a fedml_tpu wire frame)")
    # integrity trailer FIRST: corruption anywhere (including the JSON
    # header) must surface as a CRC error, not a parse error
    if magic == _MAGIC_CRC:
        if len(data) < 16:
            raise ValueError("FT02 frame too short for its CRC trailer")
        if bytes(data[-8:-4]) != _CRC_TAG:
            raise ValueError("FT02 frame missing its CRC trailer tag")
        from ..native import crc32c

        (want,) = struct.unpack("<I", data[-4:])
        got = crc32c(data[:-8])  # memoryview: zero-copy into the kernel
        if got is not None and got != want:
            raise ValueError(
                f"wire frame CRC mismatch (got {got:#x}, want "
                f"{want:#x}) — payload corrupted in transit")
        data = data[:-8]
    (hlen,) = struct.unpack("<I", data[4:8])
    head = json.loads(bytes(data[8 : 8 + hlen]))
    buffers: list[memoryview] = []
    off = 8 + hlen
    for size in head["sizes"]:
        buffers.append(data[off : off + size])
        off += size
    return _decode_obj(head["tree"], buffers)
