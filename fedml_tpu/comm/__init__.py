"""Distributed communication backend — L0/L1 of the layer map (SURVEY.md §1).

Replaces the reference's transport zoo (reference:
core/distributed/communication/ — MPI/gRPC/TRPC/MQTT+S3 variants, all moving
pickled Messages) with two transports on a shared tensor-native wire format:
loopback (in-process, tests) and gRPC (cross-silo DCN). Intra-pod "messaging"
does not exist here at all — it's XLA collectives inside the round program
(parallel/round.py), per SURVEY.md §5.8.
"""
from .base import BaseTransport, Observer
from .chaos import ChaosTransport, FaultSpec
from .codec import CodecPolicy, validate_comm_codec
from .loopback import LoopbackTransport, get_router
from .manager import FedCommManager, create_transport
from .message import Message
from .reliable import DeliveryError, ReliableTransport, RetryPolicy
from .serialization import decode, encode
from .topology import AsymmetricTopologyManager, SymmetricTopologyManager

__all__ = [
    "BaseTransport", "Observer", "Message", "FedCommManager",
    "create_transport", "LoopbackTransport", "get_router", "encode", "decode",
    "SymmetricTopologyManager", "AsymmetricTopologyManager",
    "ChaosTransport", "FaultSpec", "ReliableTransport", "RetryPolicy",
    "DeliveryError", "CodecPolicy", "validate_comm_codec",
]
