"""FedCommManager — handler registry + event loop over a pluggable transport.

The reference's L1 (reference: core/distributed/fedml_comm_manager.py —
run() :25, send_message() :53, register_message_receive_handler() :63,
backend factory _init_manager() :131-207 selecting
MPI/gRPC/TRPC/MQTT_S3/...). Here the backend menu is:

- "loopback"  — in-process queues (tests/CI; ≙ the reference faking
                multi-node with multi-process, run_cross_silo.sh)
- "grpc"      — DCN messaging, tensor-native frames
- "xla"       — not a message transport at all: intra-pod aggregation happens
                as XLA collectives inside the round program (parallel/round.py);
                requesting it here raises with that explanation
- "mqtt_s3" / "trpc" / "mpi" — reference backends whose role is covered by
                grpc+loopback on TPU pods; raise with guidance
"""
from __future__ import annotations

import logging
import threading
from typing import Callable, Optional

from ..utils import metrics as _mx
from ..utils.events import recorder, trace_context
from .base import BaseTransport, Observer
from .loopback import LoopbackTransport
from .message import Message


def _backend_of(transport: BaseTransport) -> str:
    """Innermost transport's backend tag (unwraps the reliability/chaos
    stack): "grpc", "loopback", "broker", ... — stamped into comm span
    meta so the attribution plane can break transport time out by
    backend (utils/attribution.py)."""
    t = transport
    while hasattr(t, "inner"):
        t = t.inner
    name = type(t).__name__.lower()
    for tag in ("grpc", "loopback", "broker"):
        if tag in name:
            return tag
    return name.removesuffix("transport") or name


class FedCommManager(Observer):
    def __init__(self, transport: BaseTransport, rank: int = 0):
        self.transport = transport
        self.backend = _backend_of(transport)
        self.rank = rank
        self._handlers: dict[str, Callable[[Message], None]] = {}
        self.transport.add_observer(self)
        self._thread: Optional[threading.Thread] = None
        self._warned_unhandled: set[str] = set()

    # reference API (fedml_comm_manager.py:63)
    def register_message_receive_handler(
        self, msg_type: str, handler: Callable[[Message], None]
    ) -> None:
        self._handlers[msg_type] = handler

    def send_message(self, msg: Message) -> None:  # :53
        # the Message's own sender_id is authoritative (callers construct it
        # with their client id, which need not equal the transport rank).
        # The span puts a trace context on this thread; the transport's
        # _encode_frame stamps it into the headers, so the receiver's
        # handle span stitches to this one.
        with recorder.span(f"comm.send.{msg.type}", sender=msg.sender_id,
                           receiver=msg.receiver_id, backend=self.backend):
            self.transport.send_message(msg)

    def receive_message(self, msg_type: str, msg: Message) -> None:
        handler = self._handlers.get(msg_type)
        if handler is None:
            # an unknown type used to raise on the background receive loop,
            # silently killing ALL message delivery (ISSUE 4): a peer one
            # protocol version ahead could take down this process's comm.
            # Log once per type, count every occurrence, keep the loop.
            _mx.inc("comm.msgs_unhandled")
            if msg_type not in self._warned_unhandled:
                self._warned_unhandled.add(msg_type)
                logging.getLogger(__name__).warning(
                    "rank %d: no handler registered for %r (registered: %s) "
                    "— dropping; further occurrences counted in "
                    "comm.msgs_unhandled", self.rank, msg_type,
                    sorted(self._handlers))
            return
        tid, parent = msg.trace_context()
        _mx.inc("comm.msgs_handled")
        with trace_context(tid, parent):
            with recorder.span(f"comm.handle.{msg_type}",
                               sender=msg.sender_id,
                               receiver=msg.receiver_id,
                               backend=self.backend):
                handler(msg)

    def announce_metrics(self, process: str, url: str,
                         collector_rank: int = 0) -> None:
        """Self-register this process's /metrics endpoint with the fleet
        collector's host (ISSUE 18): one OBS_REGISTER frame over this
        manager's transport. The collector side routes the frame via
        `obsfleet.install_registration(manager, collector)`."""
        from ..utils.obsfleet import announce

        announce(self, process, url, collector_rank)

    def run(self, background: bool = False) -> None:
        """Enter the receive loop (reference: run() :25 →
        handle_receive_message). background=True runs it in a daemon thread
        (the in-process multi-role test topology)."""
        if background:
            self._thread = threading.Thread(
                target=self.transport.handle_receive_message, daemon=True
            )
            self._thread.start()
        else:
            self.transport.handle_receive_message()

    def stop(self) -> None:
        self.transport.stop_receive_message()
        # handlers run on the loop thread and may call stop() themselves
        if self._thread is not None and self._thread is not threading.current_thread():
            self._thread.join(timeout=5)


def _wrap_transport(t: BaseTransport, chaos, retry_policy) -> BaseTransport:
    """Apply the robustness stack (ISSUE 4): chaos INSIDE, reliability
    OUTSIDE — injected faults hit data frames, acks, and retransmits alike,
    and the retry/dedup machinery is what recovers from them."""
    if chaos is not None:
        from .chaos import ChaosTransport, FaultSpec

        spec = chaos if isinstance(chaos, FaultSpec) \
            else FaultSpec.from_dict(chaos)
        if spec.any_link_faults():
            t = ChaosTransport(t, spec)
    if retry_policy is not None:
        from .reliable import ReliableTransport

        t = ReliableTransport(t, retry_policy)
    return t


def create_transport(backend: str, rank: int, run_id: str = "default",
                     ip_table: Optional[dict] = None, chaos=None,
                     comm_retry=None, comm_codec=None, **kw) -> BaseTransport:
    """Backend factory (reference: _init_manager, fedml_comm_manager.py:131).

    chaos: FaultSpec or `common_args.extra.chaos` dict — wraps the transport
    in a fault-injecting ChaosTransport (comm/chaos.py).
    comm_retry: RetryPolicy, `common_args.extra.comm_retry` dict, or True
    for defaults — wraps the stack in a ReliableTransport (seq/ack/
    retransmit/dedup, comm/reliable.py); for grpc it also supplies the
    default per-RPC deadline.
    comm_codec: CodecPolicy or `comm_args.comm_codec` dict (ISSUE 14) —
    attaches the wire codec plane to the INNERMOST transport, so chaos
    injection and reliable retransmits both act on compressed frames.
    Enable it on BOTH ends of a link: delta frames decode against the
    receiving endpoint's anchor state.
    """
    policy = None
    if comm_retry is not None and comm_retry is not False:
        from .reliable import RetryPolicy

        policy = comm_retry if isinstance(comm_retry, RetryPolicy) \
            else RetryPolicy.from_dict(comm_retry)

    def _with_codec(t: BaseTransport) -> BaseTransport:
        if comm_codec is not None:
            from .codec import CodecPolicy

            t.set_codec(CodecPolicy.from_config(comm_codec))
        return t

    b = (backend or "loopback").lower()
    if b == "loopback":
        return _wrap_transport(_with_codec(LoopbackTransport(rank, run_id)),
                               chaos, policy)
    if b == "grpc":
        from .grpc_transport import GrpcTransport, load_ip_table
        if ip_table is None:
            raise ValueError("grpc backend needs ip_table={rank: 'host:port'} "
                             "or a csv path (reference: grpc_ipconfig.csv)")
        if isinstance(ip_table, str):
            ip_table = load_ip_table(ip_table)
        if policy is not None:
            kw.setdefault("rpc_timeout_s", policy.rpc_timeout_s)
        return _wrap_transport(_with_codec(GrpcTransport(rank, ip_table,
                                                         **kw)),
                               chaos, policy)
    if b == "xla":
        raise ValueError(
            "backend='xla' is the in-program collective path (simulation over "
            "a device mesh, parallel/round.py), not a message transport; use "
            "'grpc' or 'loopback' for the cross-silo message layer"
        )
    if b in ("broker", "mqtt_s3", "mqtt"):
        # the cross-org pub/sub plane: store-and-forward topics + blob
        # side-channel (comm/broker.py; reference MQTT+S3 shape)
        from .broker import BrokerTransport

        return _wrap_transport(_with_codec(BrokerTransport(rank, run_id,
                                                           **kw)),
                               chaos, policy)
    if b in ("mqtt_web3", "mqtt_thetastore", "web3"):
        # decentralized-storage shape: content-addressed, hash-verified,
        # deduplicating blob plane (reference: mqtt_web3/ + mqtt_thetastore/
        # comm managers)
        from .broker import BrokerTransport, get_cas_broker

        if "broker" not in kw:
            kw["broker"] = get_cas_broker(run_id)
        return _wrap_transport(_with_codec(BrokerTransport(rank, run_id,
                                                           **kw)),
                               chaos, policy)
    if b in ("trpc", "mpi"):
        raise ValueError(
            f"backend {b!r} is a reference transport not provided in the TPU "
            "build; 'grpc' covers cross-silo DCN messaging, 'broker' covers "
            "the MQTT+S3 cross-org role, and 'loopback' covers single-box "
            "testing"
        )
    raise ValueError(f"unknown comm backend {backend!r}")
