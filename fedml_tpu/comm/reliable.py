"""Reliable delivery — seq/ack/retransmit + receiver-side dedup.

Every transport in the stack was fire-and-forget: one dropped frame hangs a
sync round forever (the reference's only story — SURVEY §5.4 — and the
cross-silo comm-backends study's headline failure mode). This layer wraps
any `BaseTransport` with an at-least-once envelope made exactly-once at the
receiver:

- outbound messages carry a per-destination sequence number (`_rel_seq`
  header — inert to handlers, like the trace headers);
- the receiver acks every data frame (`rel.ack`, consumed by this layer,
  never dispatched to handlers) and drops already-seen sequence numbers
  inside a bounded dedup window, so retransmits and chaos-injected
  duplicates are idempotent;
- a background retransmitter resends unacked messages on an exponential
  backoff with seeded jitter until `max_attempts`/`deadline_s` is spent,
  then gives up loudly (`comm.rel.delivery_failed` counter + log +
  `comm.rel.giveup` span on the Chrome trace).

`send_message` stays non-blocking (first transmit inline, recovery in the
background): FSM handlers send from the receive-loop thread, and a blocking
ack wait there would deadlock against the very loop that must consume the
ack. Delivery failures therefore surface through metrics/logs and the
`failed` list, not exceptions — the same degrade-don't-die contract as the
telemetry sinks.

Integrity is the wire codec's job (serialization.py FT02 CRC trailer, or
the JSON parse without the native tier): a corrupted frame is rejected in
the transport pump (`comm.<backend>.decode_errors`), never acked, and this
layer retransmits it. Knobs ride `common_args.extra.comm_retry` and are
validated at config load.
"""
from __future__ import annotations

import dataclasses
import logging
import os
import queue
import random
import threading
import time
from collections import deque
from typing import Optional

from ..utils import metrics as _mx
from ..utils.events import recorder
from .base import BaseTransport, Observer
from .base import link_telemetry_enabled as _link_rtt_enabled
from .message import Message

log = logging.getLogger(__name__)

#: ack frame type — consumed by ReliableTransport, never reaches handlers
REL_ACK = "rel.ack"
#: envelope headers (underscore: visually apart from payload keys)
HDR_SEQ = "_rel_seq"
#: per-transport-incarnation id: a restarted sender's sequence numbers
#: restart at 1, and without an epoch the receiver's dedup window would
#: silently swallow its first `dedup_window` messages as duplicates. The
#: receiver keeps ONE window per sender, reset whenever the epoch changes,
#: and acks echo the epoch so a stale pre-restart ack can't satisfy a
#: post-restart send.
HDR_EPOCH = "_rel_epoch"
#: sender-clock transmit timestamp, echoed verbatim in the ack (ISSUE 18):
#: the sender measures link RTT against its OWN monotonic clock, so no
#: cross-process clock agreement is needed. Restamped on every transmit
#: (Karn's rule) — an ack always echoes the attempt that actually landed,
#: never an earlier attempt's stamp inflated by backoff.
HDR_TS = "_rel_ts"


class DeliveryError(RuntimeError):
    """A message exhausted its retry budget (raised only by explicit
    `flush(raise_on_failure=True)` calls — the send path never throws)."""


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Retry/backoff/dedup knobs (`common_args.extra.comm_retry`).

      max_attempts  — transmits per message before giving up (>= 1)
      ack_timeout_s — wait before the FIRST retransmit
      backoff_mult  — timeout multiplier per further attempt
      max_backoff_s — cap on the per-attempt wait
      jitter        — +/- fraction of each wait (decorrelates retry storms)
      deadline_s    — total wall-clock budget per message
      rpc_timeout_s — per-RPC deadline handed to deadline-capable transports
                      (grpc) so a black-holed peer fails fast instead of
                      hanging the sender
      dedup_window  — per-sender count of remembered sequence numbers
      seed          — jitter RNG seed (per-rank offset added internally)
    """

    max_attempts: int = 6
    ack_timeout_s: float = 0.25
    backoff_mult: float = 2.0
    max_backoff_s: float = 2.0
    jitter: float = 0.2
    deadline_s: float = 30.0
    rpc_timeout_s: float = 10.0
    dedup_window: int = 1024
    seed: int = 0

    def __post_init__(self):
        def bad(knob, why):
            raise ValueError(
                f"common_args.extra.comm_retry.{knob} {why}; got "
                f"{getattr(self, knob)!r}")

        if not isinstance(self.max_attempts, int) \
                or isinstance(self.max_attempts, bool) or self.max_attempts < 1:
            bad("max_attempts", "must be an integer >= 1")
        if not isinstance(self.dedup_window, int) \
                or isinstance(self.dedup_window, bool) or self.dedup_window < 1:
            bad("dedup_window", "must be an integer >= 1")
        if not isinstance(self.seed, int) or isinstance(self.seed, bool):
            bad("seed", "must be an integer")
        for knob, lo in (("ack_timeout_s", 1e-4), ("backoff_mult", 1.0),
                         ("max_backoff_s", 1e-4), ("deadline_s", 1e-3),
                         ("rpc_timeout_s", 1e-3)):
            v = getattr(self, knob)
            if not isinstance(v, (int, float)) or isinstance(v, bool) \
                    or float(v) < lo:
                bad(knob, f"must be a number >= {lo}")
        if not isinstance(self.jitter, (int, float)) \
                or isinstance(self.jitter, bool) \
                or not 0.0 <= float(self.jitter) < 1.0:
            bad("jitter", "must be a fraction in [0, 1)")

    @classmethod
    def from_dict(cls, d) -> "RetryPolicy":
        if d is True:  # `comm_retry: true` = defaults
            return cls()
        if not isinstance(d, dict):
            raise ValueError(
                "common_args.extra.comm_retry must be a mapping of retry "
                f"knobs (or `true` for defaults); got {d!r}")
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = sorted(set(d) - known)
        if unknown:
            raise ValueError(
                f"unknown common_args.extra.comm_retry keys {unknown} "
                f"(known: {sorted(known)})")
        return cls(**d)


class _Pending:
    __slots__ = ("msg", "attempts", "due", "deadline")

    def __init__(self, msg: Message, due: float, deadline: float):
        self.msg = msg
        self.attempts = 1
        self.due = due
        self.deadline = deadline


class ReliableTransport(BaseTransport, Observer):
    """At-least-once sender + exactly-once receiver over any transport.

    Stack order with chaos: `ReliableTransport(ChaosTransport(inner))` —
    faults are injected UNDER the retry machinery, so data frames, acks and
    retransmits all face the injected weather and recovery is end-to-end.

    Deployment contract: enable `comm_retry` on BOTH ends of a link.
    Inbound messages without a `_rel_seq` header pass straight through (a
    plain peer's sends are simply unprotected), but the reverse mix —
    reliable sender, plain receiver — is broken by construction: the plain
    side never acks and has no dedup, so every retransmit is dispatched to
    its handlers again. The give-up log calls this out.
    """

    def __init__(self, inner: BaseTransport,
                 policy: Optional[RetryPolicy] = None):
        super().__init__()
        self.inner = inner
        self.policy = policy if policy is not None else RetryPolicy()
        self.failed: list[dict] = []    # give-ups, for tests/introspection
        self._lock = threading.Lock()
        self._pending: dict[tuple[int, int], _Pending] = {}
        self._next_seq: dict[int, int] = {}
        #: sender -> (epoch, seen-set, insertion-order deque): one bounded
        #: dedup window per sender, reset when its incarnation changes
        self._seen: dict[int, tuple[str, set, deque]] = {}
        self._jitter_rng = random.Random(
            self.policy.seed * 7919 + getattr(inner, "rank", 0) * 104729)
        self._epoch = os.urandom(6).hex()   # this incarnation's identity
        self._stop = threading.Event()
        self._tick = max(0.005, self.policy.ack_timeout_s / 4.0)
        inner.add_observer(self)
        self._thread = threading.Thread(
            target=self._retransmit_loop, name="rel-retransmit", daemon=True)
        self._thread.start()
        # acks go out on their own thread: the receive path runs on the
        # transport's singleton pump thread, and a synchronous ack RPC to an
        # unreachable sender (grpc: up to rpc_timeout_s x retries) would
        # stall dispatch of every OTHER peer's queued frames behind it
        self._ack_q: queue.Queue = queue.Queue()
        self._ack_thread = threading.Thread(
            target=self._ack_loop, name="rel-acks", daemon=True)
        self._ack_thread.start()

    # ------------------------------------------------------------- plumbing
    @property
    def rank(self) -> int:
        return getattr(self.inner, "rank", 0)

    @property
    def backend_name(self) -> str:
        return self.inner.backend_name

    def set_codec(self, policy) -> None:
        # the wire codec must live on the INNERMOST transport (whose
        # _encode_frame/_decode_frame actually run); setting it here would
        # silently leave frames dense
        self.inner.set_codec(policy)

    def handle_receive_message(self) -> None:
        self.inner.handle_receive_message()

    def stop_receive_message(self) -> None:
        self._stop.set()
        self._ack_q.put(None)
        self.inner.stop_receive_message()
        for t in (self._thread, self._ack_thread):
            if t is not threading.current_thread():
                t.join(timeout=2.0)

    def __getattr__(self, item):
        return getattr(object.__getattribute__(self, "inner"), item)

    # ----------------------------------------------------------------- send
    def send_message(self, msg: Message) -> None:
        dst = msg.receiver_id
        with self._lock:
            seq = self._next_seq[dst] = self._next_seq.get(dst, 0) + 1
        msg.params[HDR_SEQ] = seq
        msg.params[HDR_EPOCH] = self._epoch
        now = time.monotonic()
        with self._lock:
            self._pending[(dst, seq)] = _Pending(
                msg, now + self._wait_for(1),
                now + self.policy.deadline_s)
        _mx.inc("comm.rel.sends")
        self._transmit(msg)

    def _wait_for(self, attempt: int) -> float:
        p = self.policy
        base = min(p.ack_timeout_s * p.backoff_mult ** (attempt - 1),
                   p.max_backoff_s)
        return base * (1.0 + p.jitter * (2.0 * self._jitter_rng.random() - 1.0))

    def _transmit(self, msg: Message) -> None:
        msg.params[HDR_TS] = time.perf_counter()
        try:
            self.inner.send_message(msg)
        except Exception as e:  # noqa: BLE001 — retried in the background
            _mx.inc("comm.rel.send_errors")
            log.warning("rank %s: transmit of %r seq %s to %s failed "
                        "(will retry): %s: %s", self.rank, msg.type,
                        msg.params.get(HDR_SEQ), msg.receiver_id,
                        type(e).__name__, e)

    def _retransmit_loop(self) -> None:
        p = self.policy
        while not self._stop.wait(self._tick):
            now = time.monotonic()
            resend: list[Message] = []
            give_up: list[tuple[tuple, _Pending]] = []
            with self._lock:
                for key, ent in list(self._pending.items()):
                    if ent.due > now:
                        continue
                    if ent.attempts >= p.max_attempts or now >= ent.deadline:
                        del self._pending[key]
                        give_up.append((key, ent))
                        continue
                    ent.attempts += 1
                    ent.due = now + self._wait_for(ent.attempts)
                    resend.append(ent.msg)
            for msg in resend:
                _mx.inc("comm.rel.retransmits")
                self._transmit(msg)
            for (dst, seq), ent in give_up:
                _mx.inc("comm.rel.delivery_failed")
                self.failed.append({"receiver": dst, "seq": seq,
                                    "type": ent.msg.type,
                                    "attempts": ent.attempts})
                log.warning(
                    "rank %s: giving up on %r seq %d to %s after %d "
                    "attempts (budget max_attempts=%d deadline_s=%g) — "
                    "peer down, or running without comm_retry (no acks)?",
                    self.rank, ent.msg.type, seq, dst, ent.attempts,
                    p.max_attempts, p.deadline_s)
                with recorder.span("comm.rel.giveup", receiver=dst, seq=seq,
                                   msg_type=ent.msg.type,
                                   attempts=ent.attempts):
                    pass

    # -------------------------------------------------------------- receive
    def _ack_loop(self) -> None:
        while True:
            item = self._ack_q.get()
            if item is None:
                return
            peer, seq, epoch, ts = item
            params = {HDR_SEQ: seq, HDR_EPOCH: epoch}
            if ts is not None:
                params[HDR_TS] = ts      # echo: RTT on the sender's clock
            try:
                self.inner.send_message(
                    Message(REL_ACK, self.rank, peer, params))
            except Exception as e:  # noqa: BLE001
                _mx.inc("comm.rel.ack_send_errors")
                log.debug("rank %s: ack %d to %s failed: %s: %s", self.rank,
                          seq, peer, type(e).__name__, e)

    def receive_message(self, msg_type: str, msg: Message) -> None:
        if msg_type == REL_ACK:
            seq = msg.get(HDR_SEQ)
            # the ack must echo THIS incarnation's epoch: a stale ack from
            # before a restart must not satisfy a post-restart send that
            # happens to reuse the sequence number
            fresh = msg.get(HDR_EPOCH) == self._epoch
            with self._lock:
                ent = self._pending.pop((msg.sender_id, int(seq)), None) \
                    if fresh and seq is not None else None
            _mx.inc("comm.rel.acked" if ent is not None
                    else "comm.rel.stale_acks")
            ts = msg.get(HDR_TS)
            if ent is not None and ts is not None and _link_rtt_enabled():
                # every acked frame yields a measured per-link RTT: the
                # echo is this process's own perf_counter stamp, so the
                # subtraction never crosses clock domains
                _mx.registry.histogram(
                    f"comm.link.{self.rank}.{msg.sender_id}.rtt_ms",
                    _mx.RTT_BUCKETS_MS).observe(
                    (time.perf_counter() - float(ts)) * 1e3)
            return
        seq = msg.get(HDR_SEQ)
        if seq is None:
            self._notify(msg)   # unprotected peer: pass through
            return
        seq = int(seq)
        epoch = str(msg.get(HDR_EPOCH, ""))
        # ack FIRST and ALWAYS — a duplicate means the previous ack was lost
        # (or chaos cloned the frame); re-acking is what makes retransmits
        # converge. Acks go through a dedicated sender thread so an
        # unreachable peer can't stall the transport pump this runs on.
        # The ack itself is unprotected: data-frame retransmission already
        # covers ack loss.
        self._ack_q.put((msg.sender_id, seq, epoch, msg.get(HDR_TS)))
        with self._lock:
            window = self._seen.get(msg.sender_id)
            if window is None or window[0] != epoch:
                # new sender incarnation: its seqs restart at 1, so the old
                # window would swallow them as duplicates — reset it
                window = (epoch, set(), deque())
                self._seen[msg.sender_id] = window
            _, seen, order = window
            if seq in seen:
                dup = True
            else:
                dup = False
                seen.add(seq)
                order.append(seq)
                while len(order) > self.policy.dedup_window:
                    seen.discard(order.popleft())
        if dup:
            _mx.inc("comm.rel.dedup_dropped")
            return
        self._notify(msg)

    # ------------------------------------------------------------ utilities
    def pending_count(self) -> int:
        with self._lock:
            return len(self._pending)

    def flush(self, timeout: float = 10.0,
              raise_on_failure: bool = False) -> bool:
        """Wait until every outstanding message is acked or given up.
        Returns True when the pending set drained in time."""
        end = time.monotonic() + timeout
        while time.monotonic() < end:
            if self.pending_count() == 0:
                if raise_on_failure and self.failed:
                    raise DeliveryError(
                        f"{len(self.failed)} message(s) exhausted their "
                        f"retry budget: {self.failed[:3]}")
                return True
            time.sleep(self._tick)
        return False
