"""Broker transport — pub/sub with store-and-forward + blob side-channel.

(reference: core/distributed/communication/mqtt_s3/mqtt_s3_multi_clients_
comm_manager.py — control messages ride an MQTT broker topic per receiver,
model payloads go to S3 and the MQTT message carries the object key; the
broker decouples sender and receiver lifetimes, which is what makes true
cross-org federation work: parties behind NATs/firewalls with independent
uptime.)

TPU-framework equivalent: the same two-plane design against a pluggable
broker. `InMemoryBroker` implements the broker contract in-process (tests,
single-host multi-org simulation); a real deployment points the same
transport at any store with topic-queue + blob semantics (one class to
implement, no changes above L0). Key semantics preserved from MQTT+S3:

- store-and-forward: publishing to an absent receiver's topic queues the
  frame; the receiver drains on (re)connect — senders never block on
  receiver liveness (contrast gRPC, which needs a live listener).
- payload split: frames above `blob_threshold` go to the blob store and
  the topic message carries only the key (the S3 plane).
"""
from __future__ import annotations

import hashlib
import threading
import time
import uuid
from collections import defaultdict, deque
from typing import Optional

from ..utils import metrics as _mx
from .base import BaseTransport
from .message import Message

_BLOB_KEY_PREFIX = b"BLOB:"


class InMemoryBroker:
    """Topic queues + blob store (the MQTT broker + S3 bucket pair)."""

    def __init__(self):
        self._topics: dict[str, deque] = defaultdict(deque)
        self._blobs: dict[str, bytes] = {}
        self._retained: dict[str, bytes] = {}
        self._cv = threading.Condition()

    # --- topic plane (MQTT)
    def publish(self, topic: str, frame: bytes) -> None:
        with self._cv:
            self._topics[topic].append(frame)
            self._cv.notify_all()

    # --- retained messages (MQTT retain flag: the broker keeps the LAST
    # frame per topic and hands it to any later reader — last-value-wins,
    # non-destructive reads; the publish/poll queues are unaffected). This
    # is what makes broker-published artifacts observable by parties that
    # attach after the publish (utils/artifacts.py BrokerArtifactStore).
    def retain(self, topic: str, frame: bytes) -> None:
        with self._cv:
            self._retained[topic] = frame

    def retained(self, topic: str) -> Optional[bytes]:
        with self._cv:
            return self._retained.get(topic)

    def unretain(self, topic: str) -> None:
        """Clear a retained frame (MQTT: publishing a zero-byte retained
        message deletes the retained value)."""
        with self._cv:
            self._retained.pop(topic, None)

    def poll(self, topic: str, timeout: float = 0.2) -> Optional[bytes]:
        with self._cv:
            if not self._topics[topic]:
                self._cv.wait(timeout)
            if self._topics[topic]:
                return self._topics[topic].popleft()
        return None

    def pending(self, topic: str) -> int:
        with self._cv:
            return len(self._topics[topic])

    # --- blob plane (S3)
    def put_blob(self, data: bytes) -> str:
        key = uuid.uuid4().hex
        with self._cv:
            self._blobs[key] = data
        return key

    def get_blob(self, key: str, delete: bool = True) -> bytes:
        with self._cv:
            return self._blobs.pop(key) if delete else self._blobs[key]


class ContentAddressedBroker(InMemoryBroker):
    """Broker whose blob plane is CONTENT-ADDRESSED — the MQTT+Web3/Theta
    transport shape (reference: core/distributed/communication/
    mqtt_web3/mqtt_web3_comm_manager.py and mqtt_thetastore/ — decentralized
    stores address blobs by content hash, not bucket key). Semantics gained
    over the S3-style plane:

    - dedup: broadcasting one model to n clients stores ONE blob (the key
      is sha256(content)); refcounts track outstanding readers.
    - integrity: get_blob re-hashes and refuses tampered content — the
      decentralized-storage trust model, where the store is not trusted.
    """

    def __init__(self):
        super().__init__()
        self._refs: dict[str, int] = {}

    def put_blob(self, data: bytes) -> str:
        key = hashlib.sha256(data).hexdigest()
        with self._cv:
            if key in self._blobs:
                self._refs[key] += 1          # dedup hit
            else:
                self._blobs[key] = bytes(data)
                self._refs[key] = 1
        return key

    def get_blob(self, key: str, delete: bool = True) -> bytes:
        with self._cv:
            data = self._blobs[key]
            if delete:
                self._refs[key] -= 1
                if self._refs[key] <= 0:
                    del self._blobs[key]
                    del self._refs[key]
        if hashlib.sha256(data).hexdigest() != key:
            raise ValueError(
                f"content-addressed blob {key[:12]}… failed hash "
                "verification — storage corrupted or tampered")
        return data


_brokers: dict[str, InMemoryBroker] = {}
_brokers_lock = threading.Lock()


def get_broker(broker_id: str = "default") -> InMemoryBroker:
    with _brokers_lock:
        if broker_id not in _brokers:
            _brokers[broker_id] = InMemoryBroker()
        return _brokers[broker_id]


def get_cas_broker(broker_id: str = "default") -> ContentAddressedBroker:
    """Shared content-addressed broker for a run (the web3 backend's
    registry; namespaced so a run can use both planes side by side)."""
    key = f"cas:{broker_id}"
    with _brokers_lock:
        if key not in _brokers:
            _brokers[key] = ContentAddressedBroker()
        return _brokers[key]  # type: ignore[return-value]


def release_broker(broker_id: str) -> None:
    """Drops BOTH planes of a run: the plain broker and its content-
    addressed companion (get_cas_broker registers under cas:<id>) — a
    survivor would hand stale store-and-forward frames to the next run
    that reuses the id."""
    with _brokers_lock:
        _brokers.pop(broker_id, None)
        _brokers.pop(f"cas:{broker_id}", None)


class BrokerTransport(BaseTransport):
    """MQTT+S3-style transport over a broker object (reference:
    mqtt_s3_multi_clients_comm_manager.py:  topic fedml_<run>_<rank>, S3 for
    model params). Messages survive receiver downtime in the topic queue."""

    backend_name = "broker"

    def __init__(self, rank: int, run_id: str = "default",
                 broker: Optional[InMemoryBroker] = None,
                 blob_threshold: int = 16 * 1024,
                 publish_retries: int = 2, retry_backoff_s: float = 0.05):
        super().__init__()
        self.rank = rank
        self.run_id = run_id
        self.broker = broker if broker is not None else get_broker(run_id)
        self.blob_threshold = blob_threshold
        # publish retry (ISSUE 4): the in-memory broker never fails, but the
        # broker contract exists to be pointed at a REAL store — a transient
        # publish/put failure there should cost a retry, not the run
        self.publish_retries = int(publish_retries)
        self.retry_backoff_s = float(retry_backoff_s)
        # out-of-band stop: an in-band sentinel could be left queued in the
        # topic and would kill the NEXT transport that reconnects to it,
        # stranding store-and-forward frames behind the stale marker
        self._stop_event = threading.Event()

    def _topic(self, rank: int) -> str:
        return f"fedml_{self.run_id}_{rank}"

    def _with_retry(self, what: str, fn):
        """Run a broker-store call with bounded retry + linear backoff;
        attempts beyond the first are counted as comm.broker.<what>_retries.
        The final failure propagates — callers see the same exception they
        always did, just after the transient window has been ridden out."""
        import logging

        for attempt in range(self.publish_retries + 1):
            try:
                return fn()
            except Exception as e:  # noqa: BLE001 — broker-store contract
                if attempt >= self.publish_retries:
                    raise
                _mx.inc(f"comm.broker.{what}_retries")
                logging.getLogger(__name__).warning(
                    "broker %s failed (attempt %d/%d, retrying): %s: %s",
                    what, attempt + 1, self.publish_retries + 1,
                    type(e).__name__, e)
                time.sleep(self.retry_backoff_s * (attempt + 1))

    def send_message(self, msg: Message) -> None:
        # encode the RECEIVER-CANONICAL frame first (receiver forced to -1):
        # on the blob path it is the ONLY full serialization (a broadcast of
        # one payload to n receivers hashes identically, so the content-
        # addressed plane stores ONE blob, refcounted n); below the
        # threshold the re-encode with the true receiver is cheap by
        # definition. Byte/msg counters and serialize time ride the
        # canonical encode (the frame that actually carries the payload).
        # stamp=False: per-send trace headers inside the canonical frame
        # would break the hash-identical-broadcast dedup — the trace
        # context rides the topic-plane key frame below instead.
        canonical = self._encode_frame(
            Message(msg.type, msg.sender_id, -1, msg.params), stamp=False)
        if len(canonical) > self.blob_threshold:
            key = self._with_retry(
                "blob_put", lambda: self.broker.put_blob(canonical))
            from ..utils.events import current_trace

            tid, sid = current_trace()
            frame = _BLOB_KEY_PREFIX + "|".join(
                (key, str(msg.receiver_id), tid or "", sid or "")).encode()
            _mx.inc("comm.broker.blob_puts")
            _mx.inc("comm.broker.bytes_sent", len(frame))  # topic-plane key
        else:
            # true-receiver re-encode (trace headers stamped here — inline
            # frames never reach the content-addressed plane); payload
            # bytes already counted above
            msg.stamp_trace()
            frame = msg.encode()
        t0 = time.perf_counter()
        self._with_retry(
            "publish",
            lambda: self.broker.publish(self._topic(msg.receiver_id), frame))
        _mx.observe("comm.broker.publish_s", time.perf_counter() - t0)

    def handle_receive_message(self) -> None:
        # NOTE: no clear() here — a stop() issued before this thread is
        # scheduled must win, or the loop would spin forever; a stopped
        # transport is done (build a new one to reconnect).
        topic = self._topic(self.rank)
        while not self._stop_event.is_set():
            # poll_s measures the DEQUEUE cost only: a non-blocking poll is
            # timed (pure transport work on a non-empty queue — the
            # store-and-forward backlog case); when the queue is empty the
            # blocking wait runs untimed, so idle/inter-arrival gaps never
            # pollute the histogram
            t0 = time.perf_counter()
            frame = self.broker.poll(topic, timeout=0)
            if frame is not None:
                _mx.observe("comm.broker.poll_s", time.perf_counter() - t0)
            else:
                frame = self.broker.poll(topic, timeout=0.2)
            if frame is None:
                continue
            if frame.startswith(_BLOB_KEY_PREFIX):
                parts = frame[len(_BLOB_KEY_PREFIX):].decode().split("|")
                key, receiver = parts[0], parts[1] if len(parts) > 1 else ""
                msg = self._decode_frame(self.broker.get_blob(key))
                msg.receiver_id = int(receiver) if receiver else self.rank
                # re-attach the trace context the dedup-friendly canonical
                # frame deliberately left out (it rode the key frame)
                if len(parts) > 2 and parts[2]:
                    from .message import ARG_PARENT_SPAN, ARG_TRACE_ID

                    msg.params[ARG_TRACE_ID] = parts[2]
                    if len(parts) > 3 and parts[3]:
                        msg.params[ARG_PARENT_SPAN] = parts[3]
                self._notify(msg)
                continue
            self._notify(self._decode_frame(frame))

    def stop_receive_message(self) -> None:
        self._stop_event.set()
