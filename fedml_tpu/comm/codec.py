"""Wire codec plane — negotiated compression for training frames (ISSUE 14).

Transport dominates cross-silo round time (PAPERS.md, arXiv:2604.10859), yet
until this module every training frame was a dense tensor tree: the
compression/ transforms only ever modeled loss in simulation and the sparse
wire codecs (`compression.encode_sparse/decode_sparse`) had no consumer on
the real comm path. This plane plugs into
`BaseTransport._encode_frame/_decode_frame` and compresses per message
*type*: training payloads (the C2S model upload, the masked secagg upload)
shrink, control/handshake/heartbeat frames stay BYTE-IDENTICAL to a
codec-less build.

Self-describing frames: a compressed payload is replaced in the message by a
`{"__wire_codec__": <kind>, ...}` header dict carrying the codec id and its
params, so a receiver decodes WITHOUT out-of-band config. An unknown codec
id, a wire-version bump, an out-of-range sparse index, or a delta frame
whose anchor digest matches nothing on the receiver is a loud ValueError —
the transport pump counts and drops the frame (`comm.<backend>.decode_errors`)
and the reliable layer's retransmit/give-up machinery surfaces the failure;
silent garbage is never dispatched.

Delta + anchor rings: the model stream is bidirectional (server broadcasts
G_r, client uploads its trained params P). Sparse top-k of FULL params would
zero most of the model, so the codec encodes the DELTA against an anchor both
ends already hold: every model-stream message (S2C init/sync, C2S upload)
pushes its RECONSTRUCTED payload into a small per-(peer, key) digest-keyed
anchor ring on BOTH sides — the sender's encode and the receiver's decode
insert the same values in the same order, so the rings never diverge. A delta
frame names its base by digest; the receiver looks the digest up in its ring,
which makes the scheme robust to chaos-injected duplicates, retransmits and
cross-round reordering (a frame deltas against *some* recent anchor, not
"whatever arrived last"). A digest that fell off the ring is the loud-error
case above: the frame is dropped and the next round's dense broadcast
re-anchors the pair.

Error feedback rides the sender-side per-(peer, key) stream state the same
way the anchors do — the residual (what top-k dropped) is added to the next
round's delta, the wire analog of `compression.wrap_algorithm_with_eftopk`'s
persistent client state. Encoding is idempotent per message object (a
retransmit re-entering `_encode_frame` sees the header marker and skips), so
the reliable layer's retries never double-spend a residual.

Secagg (quantize-then-mask): masked vectors are uniformly random field
elements — nothing lossy can touch them after masking. Compression must
happen BEFORE the mask (lossy sparsify of the float update, then the SHARED
finite-field quantization scale `mpc/finite.quantize(q_bits)` that every
client already uses), and the wire leg packs the masked int64 field vector
into lossless uint32 (`mpc/finite.pack_field`) for an exact 2x. Because the
quantization scale is shared and packing is bitwise-lossless, the masked
compressed aggregate unmasks to EXACTLY the plain quantize-sum-dequantize of
the same compressed vectors (pinned in tests/test_wire_codec.py).

DP ordering: client-side DP noise (dp.make_upload_dp) is applied to the
update BEFORE the transport encodes it, so the codec's lossy transform is
post-processing of the DP mechanism's output — the RDP accountant is
unchanged by compression. The reverse order (compress, then noise) would
need a fresh sensitivity analysis of the compressed mapping and is not
offered.

This module stays jax-free at import (config load validates `comm_codec`
through it) — the sparse kernels are the numpy wire codecs in compression/,
imported lazily inside the encode/decode paths.
"""
from __future__ import annotations

import hashlib
import threading
import time
from collections import OrderedDict
from typing import Any, Optional

import numpy as np

from ..utils import metrics as _mx
from .message import Message

Pytree = Any

#: wire-format version: bumped when the frame layout changes incompatibly;
#: a receiver seeing a newer version refuses loudly instead of misparsing
WIRE_VERSION = 1

#: the header key that marks an encoded payload (and makes encode idempotent)
MARKER = "__wire_codec__"

#: codec ids a receiver accepts — the registry the mismatch check consults
WIRE_KINDS = ("dense", "sparse_topk", "qsgd", "field_pack")

# ---------------------------------------------------------------- knob table
# THE comm_codec knob registry (same pattern as serving/knobs.py): a PURE
# LITERAL graftlint's knob-drift rule reads with ast.literal_eval and
# cross-checks against `make_policy` (consumer="policy") — a knob validated
# at config load but never consumed by the policy builder fails lint.
CODEC_KNOBS = {
    "kind":            {"kind": "choice",
                        "choices": ["dense", "sparse_topk", "qsgd"],
                        "consumer": "policy"},
    "ratio":           {"kind": "num", "max": 1.0,
                        "requires_kind": "sparse_topk",
                        "consumer": "policy"},
    "val_bits":        {"kind": "choice", "choices": [16, 32],
                        "requires_kind": "sparse_topk",
                        "consumer": "policy"},
    "bits":            {"kind": "int", "min": 2, "max": 8,
                        "requires_kind": "qsgd",
                        "consumer": "policy"},
    "error_feedback":  {"kind": "bool", "requires_kind": "sparse_topk",
                        "consumer": "policy"},
    "per_type":        {"kind": "map", "consumer": "policy"},
    "secagg_premask_ratio": {"kind": "num", "max": 1.0,
                             "consumer": "policy"},
}


def _kinds_in_play(extra: dict) -> set:
    """Every codec kind this config can select (default kind + overrides) —
    the gating check: a knob owned by a kind that can never run is refused."""
    kinds = {extra.get("kind")}
    per = extra.get("per_type")
    if isinstance(per, dict):
        kinds.update(per.values())
    kinds.discard(None)
    return kinds


def validate_comm_codec(extra: dict) -> None:
    """Validate a `comm_args.extra.comm_codec` knob dict at config load.

    Unknown keys are refused (a misspelled `ratio` must not silently run
    dense), kinds/bounds come from CODEC_KNOBS, and a knob whose owning
    codec kind is selected nowhere (e.g. `bits` without any `qsgd`) is
    refused rather than silently ignored — the same gating discipline as
    serving/knobs.py. Jax-free: config load calls this.
    """
    if not isinstance(extra, dict):
        raise ValueError(
            "comm_args.comm_codec must be a mapping of codec knobs; got "
            f"{extra!r}")
    unknown = set(extra) - set(CODEC_KNOBS)
    if unknown:
        raise ValueError(
            f"unknown comm_codec knob(s) {sorted(unknown)}; valid: "
            f"{sorted(CODEC_KNOBS)}")
    if "kind" not in extra:
        raise ValueError(
            "comm_codec needs a 'kind' (one of "
            f"{CODEC_KNOBS['kind']['choices']}) — the codec plane never "
            "guesses a default compressor")
    for knob, spec in CODEC_KNOBS.items():
        val = extra.get(knob)
        if val is None:
            continue
        if spec["kind"] == "bool":
            if not isinstance(val, bool):
                raise ValueError(
                    f"comm_codec.{knob} must be a boolean; got {val!r}")
        elif spec["kind"] == "int":
            lo, hi = spec["min"], spec["max"]
            ok = (isinstance(val, int) and not isinstance(val, bool)
                  and lo <= val <= hi)
            if not ok:
                raise ValueError(
                    f"comm_codec.{knob} must be an integer in [{lo}, {hi}]; "
                    f"got {val!r}")
        elif spec["kind"] == "num":
            hi = spec.get("max")
            try:
                ok = (not isinstance(val, bool) and float(val) > 0
                      and (hi is None or float(val) <= hi))
            except (TypeError, ValueError):
                ok = False
            if not ok:
                raise ValueError(
                    f"comm_codec.{knob} must be a number in (0, {hi}]; "
                    f"got {val!r}")
        elif spec["kind"] == "choice":
            if val not in spec["choices"]:
                raise ValueError(
                    f"comm_codec.{knob} must be one of {spec['choices']}; "
                    f"got {val!r}")
        elif spec["kind"] == "map":
            if not isinstance(val, dict):
                raise ValueError(
                    f"comm_codec.{knob} must be a mapping of message type "
                    f"-> codec kind; got {val!r}")
            for mt, k in val.items():
                if not isinstance(mt, str):
                    raise ValueError(
                        f"comm_codec.per_type keys must be message-type "
                        f"strings; got {mt!r}")
                if k not in WIRE_KINDS:
                    raise ValueError(
                        f"comm_codec.per_type[{mt!r}] must be one of "
                        f"{list(WIRE_KINDS)}; got {k!r}")
        # gating: a knob owned by a codec kind that can never run would be
        # silently dead — refuse at load (serve-knob discipline)
        owner = spec.get("requires_kind")
        if owner is not None and owner not in _kinds_in_play(extra):
            raise ValueError(
                f"comm_codec.{knob} requires kind: {owner} (or a per_type "
                f"override selecting it) — without {owner!r} anywhere the "
                "knob would be silently ignored")


def make_policy(d: dict) -> "CodecPolicy":
    """comm_codec config dict -> CodecPolicy — THE consumer the knob-drift
    rule cross-checks against CODEC_KNOBS (every registered knob must be
    read here; a read of an unregistered knob is dead code)."""
    validate_comm_codec(d)
    kind = d.get("kind")
    per_type = dict(d.get("per_type") or {})
    ef = d.get("error_feedback")
    type_map = {"c2s_send_model": kind, "c2s_sa_masked": "field_pack"}
    type_map.update(per_type)
    return CodecPolicy(
        type_map,
        ratio=float(d.get("ratio", 0.05)),
        bits=int(d.get("bits", 8)),
        val_bits=int(d.get("val_bits", 32)),
        error_feedback=bool(ef) if ef is not None else kind == "sparse_topk",
        secagg_premask_ratio=d.get("secagg_premask_ratio"),
    )


# ------------------------------------------------------------- tree plumbing
def _np_tree(obj):
    """Normalize a payload tree exactly the way serialization.py will: array
    leaves to ndarray, numpy scalars to python scalars — so the anchor a
    sender records equals, BIT FOR BIT, what the receiver decodes."""
    if isinstance(obj, np.ndarray):
        return obj
    if isinstance(obj, np.integer):
        return int(obj)
    if isinstance(obj, np.floating):
        return float(obj)
    if isinstance(obj, dict):
        return {k: _np_tree(v) for k, v in obj.items()}
    if isinstance(obj, tuple):
        return tuple(_np_tree(v) for v in obj)
    if isinstance(obj, list):
        return [_np_tree(v) for v in obj]
    if obj is None or isinstance(obj, (bool, int, float, str)):
        return obj
    if hasattr(obj, "__array__"):
        return np.asarray(obj)
    raise TypeError(f"wire codec cannot handle payload leaf of type "
                    f"{type(obj)!r}")


def _same_structure(a, b) -> bool:
    if isinstance(a, dict) and isinstance(b, dict):
        return set(a) == set(b) and all(_same_structure(a[k], b[k])
                                        for k in a)
    if isinstance(a, (list, tuple)) and isinstance(b, (list, tuple)):
        return (type(a) is type(b) and len(a) == len(b)
                and all(_same_structure(x, y) for x, y in zip(a, b)))
    if isinstance(a, np.ndarray) and isinstance(b, np.ndarray):
        return a.shape == b.shape and a.dtype == b.dtype
    return type(a) is type(b)


def tree_digest(tree) -> str:
    """16-hex-char blake2b over structure + leaf bytes — the anchor identity
    a delta frame names its base by."""
    h = hashlib.blake2b(digest_size=8)

    def walk(obj):
        if isinstance(obj, dict):
            h.update(b"d")
            for k in obj:            # serialization preserves dict order
                h.update(str(k).encode())
                walk(obj[k])
        elif isinstance(obj, (list, tuple)):
            h.update(b"l" if isinstance(obj, list) else b"t")
            for v in obj:
                walk(v)
        elif isinstance(obj, np.ndarray):
            h.update(str(obj.dtype).encode() + str(obj.shape).encode())
            h.update(np.ascontiguousarray(obj).tobytes())
        else:
            h.update(repr(obj).encode())

    walk(tree)
    return h.hexdigest()


def _walk_pair(payload, base, fn):
    """Map `fn(leaf, base_leaf)` -> (wire_leaf, recon_leaf) over the array
    leaves of `payload` (base_leaf is None in absolute mode); containers are
    rebuilt around the results. Returns (wire_tree, recon_tree)."""
    if isinstance(payload, dict):
        wire, recon = {}, {}
        for k, v in payload.items():
            wire[k], recon[k] = _walk_pair(v, base[k] if base is not None
                                           else None, fn)
        return wire, recon
    if isinstance(payload, (list, tuple)):
        pairs = [_walk_pair(v, base[i] if base is not None else None, fn)
                 for i, v in enumerate(payload)]
        typ = type(payload)
        return (typ(p[0] for p in pairs), typ(p[1] for p in pairs))
    if isinstance(payload, np.ndarray):
        return fn(payload, base)
    return payload, payload


# ------------------------------------------------------------- leaf codecs
def _sparse_leaf(ratio: float, val_dtype=np.float32):
    """Leaf encoder for sparse_topk: float leaves ride
    compression.encode_sparse (top-k idx/val), int/bool/empty leaves pass
    through dense — the codec plane is what makes those edge cases
    load-bearing (tests/test_compression.py pins them)."""
    from ..compression import decode_sparse, encode_sparse

    def fn(leaf: np.ndarray, base: Optional[np.ndarray]):
        if leaf.dtype.kind not in "f" or leaf.size == 0:
            return leaf, leaf          # dense passthrough, recon == payload
        d = leaf if base is None else leaf - base
        enc = encode_sparse(d.ravel(), ratio, val_dtype=val_dtype)
        recon_d = decode_sparse(enc).reshape(leaf.shape).astype(leaf.dtype)
        recon = recon_d if base is None else (base + recon_d).astype(leaf.dtype)
        wire = {"__sp__": enc, "shape": list(leaf.shape),
                "dtype": str(leaf.dtype)}
        nbytes = int(enc["idx"].nbytes + enc["val"].nbytes)
        return (wire, recon, int(leaf.nbytes), nbytes)

    return fn


def _qsgd_leaf(bits: int):
    """Leaf encoder for qsgd: norm-scaled deterministic quantization to
    `levels = 2^bits - 1` uint8 magnitudes + packed sign bits + one float32
    norm per leaf (~3.8x vs float32; the stochastic-rounding unbiasedness of
    the in-jit transform is traded for wire determinism)."""
    levels = float(2 ** bits - 1)

    def fn(leaf: np.ndarray, base: Optional[np.ndarray]):
        if leaf.dtype.kind not in "f" or leaf.size == 0:
            return leaf, leaf
        flat = np.asarray(leaf, np.float64).ravel()
        if not np.all(np.isfinite(flat)):
            raise ValueError(
                "qsgd codec: non-finite values in payload — refuse to "
                "quantize NaN/Inf into silently-wrong tensors")
        norm = float(np.linalg.norm(flat))
        if norm <= 0.0:
            q = np.zeros(flat.size, np.uint8)
        else:
            q = np.clip(np.round(np.abs(flat) / norm * levels), 0,
                        levels).astype(np.uint8)
        sgn = np.packbits((flat < 0).astype(np.uint8))
        recon = (np.where(flat < 0, -1.0, 1.0) * q * (norm / levels)) \
            .astype(leaf.dtype).reshape(leaf.shape)
        wire = {"__q__": {"mag": q, "sgn": sgn, "norm": norm,
                          "n": int(flat.size)},
                "shape": list(leaf.shape), "dtype": str(leaf.dtype)}
        return (wire, recon, int(leaf.nbytes),
                int(q.nbytes + sgn.nbytes + 4))

    return fn


def _field_pack_leaf(p: int):
    """Leaf encoder for field_pack: LOSSLESS uint32 packing of masked
    finite-field vectors via mpc/finite.pack_field — an exact 2x over the
    int64 representation, so the unmasked aggregate is bitwise unchanged."""
    from ..mpc.finite import pack_field

    def fn(leaf: np.ndarray, base: Optional[np.ndarray]):
        if leaf.dtype.kind not in "iu":
            raise ValueError(
                "field_pack codec expects integer field vectors (a masked "
                f"secagg upload); got dtype {leaf.dtype}")
        packed = pack_field(leaf, p)
        wire = {"__fp__": packed, "shape": list(leaf.shape)}
        return wire, leaf, int(leaf.nbytes), int(packed.nbytes)

    return fn


def _decode_tree(tree, kind: str, params: dict):
    """Replace wire leaf dicts with reconstructed arrays."""
    from ..compression import decode_sparse
    from ..mpc.finite import unpack_field

    def walk(obj):
        if isinstance(obj, dict):
            if "__sp__" in obj:
                return decode_sparse(obj["__sp__"]).reshape(
                    obj["shape"]).astype(np.dtype(obj["dtype"]))
            if "__q__" in obj:
                q = obj["__q__"]
                n = int(q["n"])
                mag = np.asarray(q["mag"], np.float64).ravel()
                if mag.size != n:
                    raise ValueError(
                        "qsgd frame: magnitude length mismatch")
                bits = int(params.get("bits", 8))
                levels = float(2 ** bits - 1)
                sgn = np.unpackbits(np.asarray(q["sgn"], np.uint8))
                if sgn.size < n:
                    raise ValueError("qsgd frame: sign bits truncated")
                sign = np.where(sgn[:n] > 0, -1.0, 1.0)
                norm = float(q["norm"])
                return (sign * mag * (norm / levels)).astype(
                    np.dtype(obj["dtype"])).reshape(obj["shape"])
            if "__fp__" in obj:
                return unpack_field(np.asarray(obj["__fp__"]),
                                    int(params["p"])).reshape(obj["shape"])
            return {k: walk(v) for k, v in obj.items()}
        if isinstance(obj, list):
            return [walk(v) for v in obj]
        if isinstance(obj, tuple):
            return tuple(walk(v) for v in obj)
        return obj

    return walk(tree)


def _tree_add(a, b):
    """a + b leafwise (anchor + decoded delta); non-array leaves take b."""
    if isinstance(a, dict):
        return {k: _tree_add(a[k], b[k]) for k in b}
    if isinstance(a, (list, tuple)):
        typ = type(a)
        return typ(_tree_add(x, y) for x, y in zip(a, b))
    if isinstance(a, np.ndarray) and isinstance(b, np.ndarray) \
            and b.dtype.kind == "f":
        return (a + b).astype(b.dtype)
    return b


def _tree_sub(a, b):
    """a - b leafwise for float leaves; others pass a through."""
    if isinstance(a, dict):
        return {k: _tree_sub(a[k], b[k]) for k in a}
    if isinstance(a, (list, tuple)):
        typ = type(a)
        return typ(_tree_sub(x, y) for x, y in zip(a, b))
    if isinstance(a, np.ndarray) and a.dtype.kind == "f":
        return a - b
    return a


# ----------------------------------------------------------------- policy
class CodecPolicy:
    """Per-message-type codec selection + the stream state (anchor rings,
    error-feedback residuals) one transport endpoint carries.

    Attach to the INNERMOST transport (`BaseTransport.set_codec`;
    `create_transport(comm_codec=...)` does this before wrapping) so the
    chaos/reliable wrappers see compressed frames — corrupt injection then
    exercises the sparse decoder's validation and retransmits carry the
    compressed bytes.

    THREAD OWNERSHIP: encode runs on whatever thread sends (FSM handlers,
    the reliable retransmitter) and decode runs on the transport pump —
    all anchor/residual state is accessed under `self._lock`.
    """

    #: message payload keys the codec may touch; everything else is inert
    PAYLOAD_KEYS = ("model_params", "sa_masked")
    #: model-stream types whose payloads anchor the delta codec (both ends
    #: push the reconstruction on encode AND decode, keeping rings in sync)
    ANCHOR_TYPES = frozenset(
        {"s2c_init_config", "s2c_sync_model", "c2s_send_model"})
    #: anchors remembered per (peer, key): large enough that a late
    #: straggler or chaos-reordered frame still finds its base by digest
    RING = 4

    def __init__(self, type_map: dict, ratio: float = 0.05, bits: int = 8,
                 val_bits: int = 32, error_feedback: bool = True,
                 secagg_premask_ratio: Optional[float] = None,
                 field_prime: Optional[int] = None):
        from ..mpc.finite import DEFAULT_PRIME

        self.type_map = {t: k for t, k in type_map.items() if k is not None}
        bad = sorted(set(self.type_map.values()) - set(WIRE_KINDS))
        if bad:
            raise ValueError(f"unknown codec kind(s) {bad}; valid: "
                             f"{list(WIRE_KINDS)}")
        self.ratio = float(ratio)
        self.bits = int(bits)
        self.val_dtype = np.float16 if int(val_bits) == 16 else np.float32
        self.error_feedback = bool(error_feedback)
        self.secagg_premask_ratio = secagg_premask_ratio
        self.field_prime = int(field_prime or DEFAULT_PRIME)
        # anchors exist ONLY for sparse_topk delta mode: a qsgd/dense-only
        # policy must not pay a full-model digest + 4-deep model ring per
        # peer on every broadcast for a codec that can never consume them
        self._wants_anchors = "sparse_topk" in self.type_map.values()
        self._lock = threading.Lock()
        #: (peer, key) -> OrderedDict[digest -> anchor tree], newest last
        self._anchors: dict = {}
        #: (peer, key) -> error-feedback residual tree (delta mode only)
        self._residuals: dict = {}

    @classmethod
    def from_config(cls, d) -> "CodecPolicy":
        return d if isinstance(d, cls) else make_policy(d)

    # ------------------------------------------------------------ anchors
    def _push_anchor(self, peer: int, key: str, recon) -> None:
        """Caller holds the lock."""
        ring = self._anchors.setdefault((peer, key), OrderedDict())
        dig = tree_digest(recon)
        ring.pop(dig, None)
        ring[dig] = recon
        while len(ring) > self.RING:
            ring.popitem(last=False)

    def _latest_anchor(self, peer: int, key: str):
        """Caller holds the lock. (digest, tree) of the newest anchor or
        (None, None)."""
        ring = self._anchors.get((peer, key))
        if not ring:
            return None, None
        dig = next(reversed(ring))
        return dig, ring[dig]

    # ------------------------------------------------------------- encode
    def kind_for(self, msg_type: str) -> Optional[str]:
        return self.type_map.get(msg_type)

    def encode_message(self, msg: Message, backend: str = "base") -> None:
        """Compress eligible payloads IN PLACE. Idempotent per message
        object: a retransmit re-entering `_encode_frame` sees the marker and
        skips, so stream state (residuals, anchors) advances exactly once
        per logical send."""
        t0 = time.perf_counter()
        touched = False
        for key in self.PAYLOAD_KEYS:
            val = msg.params.get(key)
            if val is None or (isinstance(val, dict) and MARKER in val):
                continue
            kind = self.kind_for(msg.type)
            anchored = (self._wants_anchors
                        and msg.type in self.ANCHOR_TYPES
                        and key == "model_params")
            if kind in (None, "dense"):
                if anchored:
                    # dense model-stream frames still advance the anchor
                    # ring (the broadcast IS the delta base) — the frame
                    # bytes are untouched, control stays byte-identical
                    with self._lock:
                        self._push_anchor(msg.receiver_id, key,
                                          _np_tree(val))
                continue
            wire, recon, raw, nb = self._encode_payload(
                kind, val, msg.receiver_id, key, anchored)
            msg.params[key] = wire
            touched = True
            pre = f"comm.codec.{backend}"
            _mx.inc(f"{pre}.bytes_raw", raw)
            _mx.inc(f"{pre}.bytes_wire", nb)
        if touched:
            _mx.observe(f"comm.codec.{backend}.encode_s",
                        time.perf_counter() - t0)

    def _encode_payload(self, kind: str, val, peer: int, key: str,
                        anchored: bool):
        payload = _np_tree(val)
        header = {MARKER: kind, "v": WIRE_VERSION}
        with self._lock:
            base_dig, base = (self._latest_anchor(peer, key)
                              if (anchored and kind == "sparse_topk")
                              else (None, None))
            if base is not None and not _same_structure(base, payload):
                base_dig = base = None      # model-shape change: go absolute
            residual = None
            if kind == "sparse_topk":
                leaf_fn = _sparse_leaf(self.ratio, self.val_dtype)
                header["ratio"] = self.ratio
                if base is not None:
                    header["mode"], header["anchor"] = "delta", base_dig
                    delta = _tree_sub(payload, base)
                    if self.error_feedback:
                        res = self._residuals.get((peer, key))
                        if res is not None and _same_structure(res, delta):
                            delta = _tree_add(res, delta)
                        residual = delta    # recon subtracted below
                    src, src_base = delta, None
                else:
                    header["mode"], header["anchor"] = "abs", None
                    src, src_base = payload, None
            elif kind == "qsgd":
                leaf_fn = _qsgd_leaf(self.bits)
                header["bits"] = self.bits
                header["mode"], header["anchor"] = "abs", None
                src, src_base = payload, None
            elif kind == "field_pack":
                leaf_fn = _field_pack_leaf(self.field_prime)
                header["p"] = self.field_prime
                src, src_base = payload, None
            else:  # pragma: no cover — constructor validated kinds
                raise ValueError(f"unknown codec kind {kind!r}")

            raw_total, wire_total = 0, 0

            def fn(leaf, b):
                nonlocal raw_total, wire_total
                out = leaf_fn(leaf, b)
                if isinstance(out, tuple) and len(out) == 4:
                    wire, recon, raw, nb = out
                    raw_total += raw
                    wire_total += nb
                    return wire, recon
                return out

            wire_tree, recon_src = _walk_pair(src, src_base, fn)
            if kind == "sparse_topk" and base is not None:
                recon = _tree_add(base, recon_src)
                if self.error_feedback:
                    self._residuals[(peer, key)] = _tree_sub(residual,
                                                             recon_src)
            else:
                recon = recon_src
            if anchored:
                self._push_anchor(peer, key, recon)
        header["tree"] = wire_tree
        return header, recon, raw_total, wire_total

    # ------------------------------------------------------------- decode
    def record_decoded_anchor(self, peer: int, key: str, recon) -> None:
        if not self._wants_anchors:
            return
        with self._lock:
            self._push_anchor(peer, key, recon)

    def lookup_anchor(self, peer: int, key: str, digest: str):
        with self._lock:
            ring = self._anchors.get((peer, key), {})
            if digest not in ring:
                raise ValueError(
                    f"wire codec anchor mismatch: delta frame names base "
                    f"{digest!r} but this endpoint holds "
                    f"{list(ring) or 'no anchors'} for peer {peer} — "
                    "sender and receiver disagree on the reference model "
                    "(enable comm_codec on both ends; a dense re-broadcast "
                    "re-anchors the pair)")
            return ring[digest]


def decode_message(msg: Message, policy: Optional[CodecPolicy],
                   backend: str = "base") -> None:
    """Reverse `encode_message` IN PLACE, keyed entirely off the frame's own
    codec header — no out-of-band config needed for stateless kinds. Delta
    frames need the receiving endpoint's anchor ring (`policy`); decoding
    one without a policy is a loud error, not garbage. Also advances the
    anchor ring for dense model-stream frames so both ends stay in sync."""
    t0 = time.perf_counter()
    touched = False
    for key in CodecPolicy.PAYLOAD_KEYS:
        val = msg.params.get(key)
        if val is None:
            continue
        anchored = (msg.type in CodecPolicy.ANCHOR_TYPES
                    and key == "model_params")
        if not (isinstance(val, dict) and MARKER in val):
            if anchored and policy is not None and policy._wants_anchors:
                policy.record_decoded_anchor(msg.sender_id, key,
                                             _np_tree(val))
            continue
        kind = val.get(MARKER)
        if kind not in WIRE_KINDS:
            raise ValueError(
                f"wire codec mismatch: frame names codec {kind!r} but this "
                f"build knows {list(WIRE_KINDS)} — version skew between "
                "sender and receiver")
        ver = int(val.get("v", 0))
        if ver != WIRE_VERSION:
            raise ValueError(
                f"wire codec version mismatch: frame is v{ver}, this build "
                f"speaks v{WIRE_VERSION}")
        recon = _decode_tree(val["tree"], kind, val)
        if val.get("mode") == "delta":
            if policy is None:
                raise ValueError(
                    "anchored delta frame but this transport has no codec "
                    "state — enable comm_codec on both ends of the link")
            base = policy.lookup_anchor(msg.sender_id, key, val["anchor"])
            recon = _tree_add(base, recon)
        msg.params[key] = recon
        if anchored and policy is not None:
            policy.record_decoded_anchor(msg.sender_id, key, recon)
        touched = True
    if touched:
        _mx.observe(f"comm.codec.{backend}.decode_s",
                    time.perf_counter() - t0)
