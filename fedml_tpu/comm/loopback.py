"""In-process loopback transport — the test/CI backend.

The reference fakes multi-node with multi-process on one box + a public MQTT
broker (reference: tests/cross-silo/run_cross_silo.sh:1-28); here the
equivalent is threads + queues in one process: same Message flow, no network.
Frames still round-trip through encode/decode so serialization is exercised.
"""
from __future__ import annotations

import queue
import threading
import time
from collections import defaultdict

from ..utils import metrics as _mx
from .base import BaseTransport
from .message import Message


class LoopbackRouter:
    """Shared mailbox set for one run: rank -> queue of frames."""

    def __init__(self):
        self._queues: dict[int, queue.Queue] = defaultdict(queue.Queue)
        self.lock = threading.Lock()

    def mailbox(self, rank: int) -> queue.Queue:
        with self.lock:
            return self._queues[rank]


_routers: dict[str, LoopbackRouter] = {}
_routers_lock = threading.Lock()


def get_router(run_id: str) -> LoopbackRouter:
    with _routers_lock:
        if run_id not in _routers:
            _routers[run_id] = LoopbackRouter()
        return _routers[run_id]


def release_router(run_id: str) -> None:
    """Drop a finished run's router (and any undrained frames). Long-lived
    processes that mint per-run ids must call this or the registry grows by
    one mailbox set — potentially holding encoded model payloads — per run."""
    with _routers_lock:
        _routers.pop(run_id, None)


class LoopbackTransport(BaseTransport):
    backend_name = "loopback"

    def __init__(self, rank: int, run_id: str = "default"):
        super().__init__()
        self.rank = rank
        self.router = get_router(run_id)
        self._inbox = self.router.mailbox(rank)
        self._running = False
        # per-INSTANCE stop sentinel: a restarted rank shares its dead
        # incarnation's mailbox (that is the point — stale in-flight frames
        # must survive, like a real process's unread sockets), so a class-
        # level sentinel left behind by the dead instance's stop() would
        # kill the NEW instance's receive loop on arrival (ISSUE 10)
        self._stop_token = object()

    def send_message(self, msg: Message) -> None:
        frame = self._encode_frame(msg)  # exercise the wire format in-process
        self._send_raw(frame, msg.receiver_id)

    def _send_raw(self, frame: bytes, receiver_id: int) -> None:
        """Raw-frame enqueue — the chaos plane's injection point (comm/
        chaos.py delivers tampered/duplicated/delayed frames through here)."""
        t0 = time.perf_counter()
        self.router.mailbox(receiver_id).put(frame)
        _mx.observe("comm.loopback.publish_s", time.perf_counter() - t0)

    def handle_receive_message(self) -> None:
        self._running = True
        while self._running:
            item = self._inbox.get()
            if item is self._stop_token:
                break
            if not isinstance(item, (bytes, bytearray)):
                continue    # a dead incarnation's stop token — not ours
            self._notify_frame(item)

    def stop_receive_message(self) -> None:
        self._running = False
        self._inbox.put(self._stop_token)


class JitterLoopbackTransport(LoopbackTransport):
    """Loopback with seeded per-send delays — the race-detection harness.

    Sleeping a random (seeded) interval before each enqueue varies the
    ARRIVAL ORDER across participants while preserving per-sender FIFO
    (what real transports guarantee), so repeated runs under different
    seeds systematically explore comm-FSM interleavings: late pk arrivals,
    unmask replies racing round timers, status messages crossing model
    syncs. Protocol outcomes must be timing-independent — tests assert
    bit-equal results across seeds (tests/test_race_interleaving.py;
    SURVEY §5.2 race-detection strategy)."""

    def __init__(self, rank: int, run_id: str = "default", seed: int = 0,
                 max_delay: float = 0.01):
        super().__init__(rank, run_id)
        import random

        self._rng = random.Random(seed * 7919 + rank * 104729)
        self.max_delay = max_delay

    def send_message(self, msg: Message) -> None:
        time.sleep(self._rng.random() * self.max_delay)
        super().send_message(msg)
