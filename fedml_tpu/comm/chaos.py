"""Chaos plane — deterministic fault injection for the comm stack.

The reference's sync runtimes simply hang or crash when a client or link
fails (SURVEY §5.4); before this layer the repo could not even *reproduce*
such a failure on demand. `FaultSpec` is a seeded, declarative fault plan;
`ChaosTransport` wraps any `BaseTransport` and injects per-link
drop/delay/duplicate/reorder/corrupt faults plus per-rank crash/flap
schedules on the send path. Injection is fully deterministic: each fault
draw is keyed by (seed, sender, receiver, per-link sequence number), so the
same plan against the same protocol run injects the same faults regardless
of thread timing — a failing chaos run replays.

Every injected fault is counted (`fed.chaos.*` — scraped by `/metrics` and
`fedml_tpu top`) and emitted as a zero-duration `comm.chaos.<fault>` span,
so faults land on the Chrome trace's comm track time-aligned with the sends
they perturbed.

`FaultSpec` also carries the CLIENT-fault rates (`client_dropout` /
`client_straggler`) consumed by the simulators: those masks are applied
inside the jitted round program (parallel/round.py), not here — this module
stays jax-free so config validation can load it without dragging a backend
in.

The spec rides config as `common_args.extra.chaos` and is validated at
config load (config.py), so a typo'd plan fails before a run starts.
"""
from __future__ import annotations

import dataclasses
import logging
import random
import struct
import threading
from typing import Optional

from ..utils import metrics as _mx
from ..utils.events import recorder
from .base import BaseTransport, Observer
from .message import Message

log = logging.getLogger(__name__)

# link-fault probability knobs (all in [0, 1])
_PROB_FIELDS = ("drop", "duplicate", "delay", "reorder", "corrupt")


@dataclasses.dataclass(frozen=True)
class FaultSpec:
    """Seeded fault plan. All probabilities are per-message and independent;
    `crash`/`flap` are per-rank schedules keyed by the SENDER's rank and
    counted in that rank's outbound sends.

      seed             — root of every fault draw (same seed => same faults)
      drop             — P(message silently dropped)
      duplicate        — P(message delivered twice)
      delay            — P(message held before delivery)
      delay_max_s      — uniform hold in [0, delay_max_s) when delayed
      reorder          — P(message held an EXTRA beat so later sends pass it)
      corrupt          — P(frame bytes tampered in flight; the wire codec's
                         CRC / parse rejects it at the receiver)
      crash            — {rank: n}: rank's outbound link goes permanently
                         dark after its n-th send
      flap             — {rank: {"up": u, "down": d}}: rank's outbound link
                         cycles u delivered sends then d dropped sends
      client_dropout   — P(a sampled client's update is lost this round)
                         (in-jit mask, parallel/round.py)
      client_straggler — P(a sampled client misses the round deadline; its
                         report is discarded like a timeout-closed round)
      replica_kill     — {replica_rank: n}: the SERVING-replica crash
                         schedule (ISSUE 9) — the replica's HTTP surface
                         dies (listening socket closed, in-flight
                         connections severed, no drain) the moment it has
                         streamed its n-th token. Consumed by
                         serving/inference_runner.py, which takes the
                         spec at construction; deterministic like every
                         other schedule here, so a mid-stream failover
                         test replays exactly.
      silo_kill        — {rank: round}: the cross-silo PROCESS-death
                         schedule (ISSUE 10) — rank 0 (the server) or a
                         client rank is SIGKILL-severed once the run has
                         completed `round` rounds, then restarted (the
                         server with `resume`). Consumed by
                         cross_silo/soak.py's kill–restart soak driver;
                         `crash`/`flap` above model the LINK dying while
                         the process lives, this models the process dying
                         while the link state (unread frames) survives.
    """

    seed: int = 0
    drop: float = 0.0
    duplicate: float = 0.0
    delay: float = 0.0
    delay_max_s: float = 0.05
    reorder: float = 0.0
    corrupt: float = 0.0
    crash: dict = dataclasses.field(default_factory=dict)
    flap: dict = dataclasses.field(default_factory=dict)
    client_dropout: float = 0.0
    client_straggler: float = 0.0
    replica_kill: dict = dataclasses.field(default_factory=dict)
    silo_kill: dict = dataclasses.field(default_factory=dict)

    def __post_init__(self):
        for f in _PROB_FIELDS + ("client_dropout", "client_straggler"):
            v = getattr(self, f)
            if not isinstance(v, (int, float)) or isinstance(v, bool) \
                    or not 0.0 <= float(v) <= 1.0:
                raise ValueError(
                    f"common_args.extra.chaos.{f} must be a probability in "
                    f"[0, 1]; got {v!r}")
        if not isinstance(self.delay_max_s, (int, float)) \
                or isinstance(self.delay_max_s, bool) or self.delay_max_s < 0:
            raise ValueError(
                "common_args.extra.chaos.delay_max_s must be a non-negative "
                f"number of seconds; got {self.delay_max_s!r}")
        if not isinstance(self.seed, int) or isinstance(self.seed, bool):
            raise ValueError(
                f"common_args.extra.chaos.seed must be an int; got "
                f"{self.seed!r}")
        for name, sched in (("crash", self.crash), ("flap", self.flap),
                            ("replica_kill", self.replica_kill),
                            ("silo_kill", self.silo_kill)):
            if not isinstance(sched, dict):
                raise ValueError(
                    f"common_args.extra.chaos.{name} must be a dict keyed by "
                    f"rank; got {sched!r}")
        for sched_name, sched in (("crash", self.crash),
                                  ("replica_kill", self.replica_kill),
                                  ("silo_kill", self.silo_kill)):
            # replica_kill fires AFTER the n-th streamed token, so 0 would
            # silently behave as 1 — refuse it (kill-before-first-byte is
            # a listening-socket kill, not a mid-stream schedule)
            floor = 1 if sched_name == "replica_kill" else 0
            for rank, n in sched.items():
                if not (isinstance(n, int) and not isinstance(n, bool)
                        and n >= floor):
                    raise ValueError(
                        f"common_args.extra.chaos.{sched_name} values must "
                        f"be counts >= {floor}; got {rank!r}: {n!r}")
        for rank, cyc in self.flap.items():
            ok = (isinstance(cyc, dict)
                  and isinstance(cyc.get("up"), int) and cyc["up"] >= 1
                  and isinstance(cyc.get("down"), int) and cyc["down"] >= 1)
            if not ok:
                raise ValueError(
                    "common_args.extra.chaos.flap values must be "
                    '{"up": >=1, "down": >=1} send-count cycles; got '
                    f"{rank!r}: {cyc!r}")

    @classmethod
    def from_config(cls, cfg) -> Optional["FaultSpec"]:
        """Resolve `common_args.extra.chaos` from a Config (None when no
        plan is set) — the single parse point the simulators share."""
        raw = cfg.common_args.extra.get("chaos")
        if not raw:
            return None
        return raw if isinstance(raw, cls) else cls.from_dict(raw)

    @classmethod
    def from_dict(cls, d: dict) -> "FaultSpec":
        if not isinstance(d, dict):
            raise ValueError(
                "common_args.extra.chaos must be a mapping of FaultSpec "
                f"knobs; got {d!r}")
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = sorted(set(d) - known)
        if unknown:
            raise ValueError(
                f"unknown common_args.extra.chaos keys {unknown} "
                f"(known: {sorted(known)})")
        # YAML keys arrive as strings; crash/flap/replica_kill schedules
        # are rank-keyed
        norm = dict(d)
        for sched in ("crash", "flap", "replica_kill", "silo_kill"):
            if isinstance(norm.get(sched), dict):
                norm[sched] = {int(k): v for k, v in norm[sched].items()}
        return cls(**norm)

    def any_link_faults(self) -> bool:
        return bool(self.crash or self.flap
                    or any(getattr(self, f) > 0.0 for f in _PROB_FIELDS))

    def any_client_faults(self) -> bool:
        return self.client_dropout > 0.0 or self.client_straggler > 0.0

    def link_rng(self, src: int, dst: int, seq: int) -> random.Random:
        """One fresh RNG per (sender, receiver, link-sequence) triple — the
        determinism backbone: fault draws never depend on wall clock, thread
        interleaving, or other links' traffic."""
        key = ((self.seed * 1000003 + src) * 1000003 + dst) * 1000003 + seq
        return random.Random(key)

    def crashed(self, rank: int, n_sends: int) -> bool:
        after = self.crash.get(rank)
        return after is not None and n_sends > after

    def flapped(self, rank: int, n_sends: int) -> bool:
        cyc = self.flap.get(rank)
        if cyc is None:
            return False
        u, d = int(cyc["up"]), int(cyc["down"])
        return (n_sends - 1) % (u + d) >= u

    def replica_killed(self, rank: int, n_tokens: int) -> bool:
        """True once serving replica `rank` has streamed `n_tokens` >= its
        scheduled kill count — the inference runner then dies mid-stream
        (serving/inference_runner.py consumes this)."""
        after = self.replica_kill.get(rank)
        return after is not None and n_tokens >= after

    def validate_tiers(self, silo_ranks=None, replica_ranks=None) -> None:
        """Cross-tier schedule validation (ISSUE 15): ONE FaultSpec can
        carry both the training-tier `silo_kill` (round-indexed) and the
        serving-tier `replica_kill` (streamed-token-indexed) timelines —
        the live-loop soak harness (soak/loop.py) consumes both from the
        same spec. A schedule naming a rank that does not exist in the
        topology it targets would silently never fire (the soak would
        pass without its kill); refuse it up front instead. Pass the
        known rank sets for whichever tier(s) the caller actually runs —
        `None` skips that tier's check (a serving-only consumer cannot
        know silo ranks, and vice versa)."""
        if silo_ranks is not None:
            unknown = sorted(set(self.silo_kill) - set(silo_ranks))
            if unknown:
                raise ValueError(
                    f"chaos.silo_kill names unknown rank(s) {unknown}; "
                    f"this federation has ranks "
                    f"{sorted(silo_ranks)} (0 = server)")
        if replica_ranks is not None:
            unknown = sorted(set(self.replica_kill) - set(replica_ranks))
            if unknown:
                raise ValueError(
                    f"chaos.replica_kill names unknown replica(s) "
                    f"{unknown}; this fleet has replicas "
                    f"{sorted(replica_ranks)}")


class ChaosTransport(BaseTransport, Observer):
    """Fault-injecting wrapper over any BaseTransport.

    Faults act on the SEND path only (the receive path forwards inner
    notifications unchanged): byte-level faults (corrupt) and out-of-band
    delivery (delay/duplicate/reorder) go through the inner transport's
    `_send_raw(frame, receiver_id)` raw-frame hook; a transport without one
    (the broker's two-plane send) still gets message-level drop/delay/
    duplicate/reorder, but a spec with corrupt > 0 is rejected at
    construction rather than silently skipped.

    On its own this wrapper makes runs FAIL — that is the point. Stack
    `ReliableTransport` (comm/reliable.py) outside it to make the same runs
    survive: reliable(chaos(transport)) injects faults under the
    retransmit/dedup machinery, so acks and retransmits face the same
    weather as data frames.
    """

    def __init__(self, inner: BaseTransport, spec: FaultSpec):
        super().__init__()
        self.inner = inner
        self.spec = spec
        self._raw = getattr(inner, "_send_raw", None)
        if spec.corrupt > 0.0 and self._raw is None:
            raise ValueError(
                f"chaos corrupt faults need a raw-frame transport; "
                f"{type(inner).__name__} has no _send_raw hook")
        self._lock = threading.Lock()
        self._sends = 0                      # this rank's outbound total
        self._link_seq: dict[int, int] = {}  # receiver -> per-link seq
        self._timers: set[threading.Timer] = set()
        self._stopped = False
        inner.add_observer(self)

    # ------------------------------------------------------------- plumbing
    @property
    def rank(self) -> int:
        return getattr(self.inner, "rank", 0)

    @property
    def backend_name(self) -> str:  # metric namespace stays the inner one's
        return self.inner.backend_name

    def receive_message(self, msg_type: str, msg: Message) -> None:
        self._notify(msg)        # inner -> our observers, unchanged

    def set_codec(self, policy) -> None:
        # raw-frame injection reads inner._encode_frame — the codec must
        # sit there so corrupt/duplicate faults act on COMPRESSED frames
        self.inner.set_codec(policy)

    def handle_receive_message(self) -> None:
        self.inner.handle_receive_message()

    def stop_receive_message(self) -> None:
        self._stopped = True
        with self._lock:
            timers, self._timers = list(self._timers), set()
        for t in timers:
            t.cancel()
        self.inner.stop_receive_message()

    def __getattr__(self, item):
        return getattr(object.__getattribute__(self, "inner"), item)

    # -------------------------------------------------------------- faults
    def _count(self, kind: str, msg: Message, seq: int) -> None:
        _mx.inc(f"fed.chaos.{kind}")
        # zero-duration span: the fault lands on the Chrome trace's comm
        # track, time-aligned with the sends it perturbed, and searchable
        with recorder.span(f"comm.chaos.{kind}", sender=msg.sender_id,
                           receiver=msg.receiver_id, seq=seq,
                           msg_type=msg.type):
            pass

    @staticmethod
    def _corrupt_frame(frame: bytes, rng: random.Random) -> bytes:
        """Tamper one byte of the JSON header region: rejected by the CRC
        trailer when the native tier is present, and by the UTF-8/JSON parse
        when it is not — detection never depends on optional native code."""
        ba = bytearray(frame)
        if len(ba) <= 8:
            return bytes(ba)
        (hlen,) = struct.unpack("<I", bytes(ba[4:8]))
        lo, hi = 8, min(8 + max(hlen, 1), len(ba))
        i = lo + rng.randrange(max(hi - lo, 1))
        ba[i] ^= 0xFF
        return bytes(ba)

    def _deliver(self, fn, delay_s: float) -> None:
        """Run `fn` now or after `delay_s` on a daemon timer; late timers
        firing into a stopped/closed inner transport are swallowed."""

        def guarded():
            if self._stopped:
                return
            try:
                fn()
            except Exception as e:  # noqa: BLE001 — injected-latency path
                log.debug("chaos delayed delivery failed: %s: %s",
                          type(e).__name__, e)

        if delay_s <= 0.0:
            guarded()
            return

        def fire():
            with self._lock:
                self._timers.discard(t)
            guarded()

        t = threading.Timer(delay_s, fire)
        t.daemon = True
        with self._lock:
            self._timers.add(t)
        t.start()

    def send_message(self, msg: Message) -> None:
        spec = self.spec
        dst = msg.receiver_id
        with self._lock:
            self._sends += 1
            n = self._sends
            seq = self._link_seq[dst] = self._link_seq.get(dst, 0) + 1
        if spec.crashed(self.rank, n):
            self._count("crash_drops", msg, seq)
            return
        if spec.flapped(self.rank, n):
            self._count("flap_drops", msg, seq)
            return
        rng = spec.link_rng(self.rank, dst, seq)
        # fixed draw order — determinism contract: drop, duplicate, corrupt,
        # delay, reorder (changing this order silently reshuffles every
        # seeded plan; tests/test_chaos.py pins seeds against it)
        if rng.random() < spec.drop:
            self._count("drop", msg, seq)
            return
        dup = rng.random() < spec.duplicate
        corrupt = rng.random() < spec.corrupt
        delay_s = 0.0
        if rng.random() < spec.delay:
            delay_s = rng.random() * spec.delay_max_s
            self._count("delay", msg, seq)
        if rng.random() < spec.reorder:
            # an extra hold long enough that in-flight later sends pass it
            delay_s += (0.5 + 0.5 * rng.random()) * max(spec.delay_max_s, 0.01)
            self._count("reorder", msg, seq)
        if dup:
            self._count("duplicate", msg, seq)
        if corrupt:
            self._count("corrupt", msg, seq)

        if self._raw is not None:
            frame = self.inner._encode_frame(msg)
            wire = self._corrupt_frame(frame, rng) if corrupt else frame
            self._deliver(lambda: self._raw(wire, dst), delay_s)
            if dup:
                # the duplicate is the CLEAN frame: a dup of a corrupt frame
                # would just be rejected twice and test nothing
                self._deliver(lambda: self._raw(frame, dst), delay_s)
        else:
            self._deliver(lambda: self.inner.send_message(msg), delay_s)
            if dup:
                self._deliver(lambda: self.inner.send_message(msg), delay_s)
