"""gRPC transport — cross-silo/DCN messaging with tensor-native frames.

Replaces the reference's gRPC backend (reference:
core/distributed/communication/grpc/grpc_comm_manager.py:30-130 — one server
per process at GRPC_BASE_PORT+rank, pickled Message inside a proto
CommRequest; proto/grpc_comm_manager.proto:1-17). Differences:
- no protoc/codegen: the service is registered with raw bytes
  (de)serializers via grpc.method_handlers_generic_handler — the frame IS
  the payload (serialization.py), so there's no pickle and no double-copy.
- ip table: {rank: "host:port"} dict or csv file (reference uses a csv,
  grpc_ipconfig.csv).
"""
from __future__ import annotations

import csv
import queue
import threading
import time
from concurrent import futures
from typing import Optional

import grpc

from ..utils import metrics as _mx
from .base import BaseTransport
from .message import Message

_SERVICE = "fedml_tpu.Comm"
_METHOD = "Send"
_FULL_METHOD = f"/{_SERVICE}/{_METHOD}"
BASE_PORT = 8890  # reference: grpc_comm_manager.py GRPC_BASE_PORT


def load_ip_table(path: str) -> dict[int, str]:
    """csv rows: receiver_id,ip[,port] (reference: grpc_ipconfig.csv)."""
    table = {}
    with open(path) as f:
        for row in csv.reader(f):
            if not row or row[0].strip().startswith("#") or row[0] == "receiver_id":
                continue
            rank = int(row[0])
            host = row[1].strip()
            port = int(row[2]) if len(row) > 2 else BASE_PORT + rank
            table[rank] = f"{host}:{port}"
    return table


class GrpcTransport(BaseTransport):
    backend_name = "grpc"

    def __init__(self, rank: int, ip_table: dict[int, str],
                 port: Optional[int] = None, max_workers: int = 4,
                 max_message_mb: int = 512,
                 rpc_timeout_s: Optional[float] = 30.0,
                 send_retries: int = 2, retry_backoff_s: float = 0.1):
        """rpc_timeout_s: per-RPC deadline (ISSUE 4) — a black-holed peer
        fails the send with DEADLINE_EXCEEDED instead of hanging a round
        forever; None restores the unbounded legacy behavior. The default
        comes from `common_args.extra.comm_retry.rpc_timeout_s` when the
        transport is built through `create_transport`.
        send_retries: connection-level retries (UNAVAILABLE only — the peer
        was provably never reached, so a resend cannot duplicate); the
        channel is rebuilt before each retry so a restarted peer is picked
        up. Deadline expiries are NOT retried here: the request may have
        been delivered with only the response lost, and only the reliable
        layer's dedup (comm/reliable.py) makes that resend safe."""
        super().__init__()
        self.rank = rank
        self.ip_table = dict(ip_table)
        self.port = port if port is not None else BASE_PORT + rank
        self.rpc_timeout_s = rpc_timeout_s
        self.send_retries = int(send_retries)
        self.retry_backoff_s = float(retry_backoff_s)
        self._ch_lock = threading.Lock()
        self._inbox: queue.Queue = queue.Queue()
        self._running = False
        opts = [
            ("grpc.max_send_message_length", max_message_mb * 1024 * 1024),
            ("grpc.max_receive_message_length", max_message_mb * 1024 * 1024),
        ]
        self._opts = opts

        def handle_send(request: bytes, context) -> bytes:
            self._inbox.put(request)
            return b"ok"

        handler = grpc.method_handlers_generic_handler(_SERVICE, {
            _METHOD: grpc.unary_unary_rpc_method_handler(
                handle_send,
                request_deserializer=None,   # raw bytes in
                response_serializer=None,    # raw bytes out
            )
        })
        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=max_workers), options=opts
        )
        self._server.add_generic_rpc_handlers((handler,))
        bound = self._server.add_insecure_port(f"[::]:{self.port}")
        if bound == 0:
            raise RuntimeError(
                f"rank {rank}: could not bind gRPC server to port {self.port} "
                "(already in use?)"
            )
        self._server.start()
        self._channels: dict[int, grpc.Channel] = {}

    def _stub(self, rank: int):
        with self._ch_lock:
            if rank not in self._channels:
                self._channels[rank] = grpc.insecure_channel(
                    self.ip_table[rank], options=self._opts
                )
            ch = self._channels[rank]
        return ch.unary_unary(
            _FULL_METHOD, request_serializer=None, response_deserializer=None
        )

    def _drop_channel(self, rank: int) -> None:
        with self._ch_lock:
            ch = self._channels.pop(rank, None)
        if ch is not None:
            ch.close()

    def send_message(self, msg: Message) -> None:
        frame = self._encode_frame(msg)
        self._send_raw(frame, msg.receiver_id)

    def _send_raw(self, frame: bytes, receiver_id: int) -> None:
        # publish latency here is the blocking unary RPC — wire + remote
        # handler enqueue, the comm study's transport-level latency term
        t0 = time.perf_counter()
        attempt = 0
        while True:
            try:
                self._stub(receiver_id)(frame, timeout=self.rpc_timeout_s)
                break
            except grpc.RpcError as e:
                _mx.inc("comm.grpc.send_errors")
                code = e.code() if hasattr(e, "code") else None
                if (code == grpc.StatusCode.UNAVAILABLE
                        and attempt < self.send_retries):
                    # reconnect-on-UNAVAILABLE: a dead subchannel stays dead
                    # until rebuilt; a restarted peer needs a fresh channel
                    attempt += 1
                    _mx.inc("comm.grpc.reconnects")
                    _mx.inc("comm.grpc.send_retries")
                    self._drop_channel(receiver_id)
                    time.sleep(self.retry_backoff_s * attempt)
                    continue
                raise
        _mx.observe("comm.grpc.publish_s", time.perf_counter() - t0)

    def handle_receive_message(self) -> None:
        self._running = True
        while self._running:
            try:
                frame = self._inbox.get(timeout=0.2)
            except queue.Empty:
                continue
            if frame is None:
                break
            self._notify_frame(frame)

    def stop_receive_message(self) -> None:
        self.shutdown(grace=1.0)

    def shutdown(self, grace: float = 1.0) -> None:
        """Release the server port and peer channels. grace=0 for bind-probes
        (`fedml_tpu diagnosis`); the default waits out in-flight RPCs —
        peers may still be sending their final acks (C2S_FINISHED), and
        tearing the executor down under an in-flight accept raises noisy
        "cannot schedule new futures after shutdown" on the serve thread."""
        self._running = False
        self._inbox.put(None)
        self._server.stop(grace=grace).wait(timeout=2.0)
        with self._ch_lock:
            channels, self._channels = list(self._channels.values()), {}
        for ch in channels:
            ch.close()
