"""MLOps facade — the reference's observability API surface, local-first.

(reference: python/fedml/core/mlops/__init__.py — `mlops.init(args)` :91,
`mlops.event(name, event_started, ...)` :153, `mlops.log(metrics)` :170,
`mlops.log_round_info(...)` :763, plus runtime-log redirection
(mlops_runtime_log.py) and the sys-perf reporters. The reference ships all
of it to the FedML cloud over MQTT+S3; here the same call names feed the
process-wide recorder, its sinks (JSONL/wandb — utils/sinks.py), a per-run
log file, and the sys-perf daemon.)

Usage parity with reference scripts:

    import fedml_tpu
    from fedml_tpu import mlops
    cfg = fedml_tpu.init(...)
    mlops.init(cfg)                      # sinks + log file + sysperf
    with mlops.event("train"):           # or event(..., started/ended)
        ...
    mlops.log({"acc": 0.9})
    mlops.log_round_info(rounds, r)
    mlops.finish()
"""
from __future__ import annotations

import logging
import os
import time
from typing import Optional

from .utils.events import recorder
from .utils.sysperf import SysPerfMonitor

_state: dict = {"sysperf": None, "log_handler": None, "events": {},
                "sinks": [], "prev_root_level": None, "artifacts": None,
                "trace_run": None}


def init(cfg, sysperf_interval: Optional[float] = None) -> None:
    """Attach sinks, redirect runtime logs to a per-run file (reference:
    mlops_runtime_log.init_logs), and start the sys-perf daemon when
    tracking is enabled."""
    from .utils.sinks import attach_from_config

    _state["sinks"].extend(attach_from_config(cfg))
    t = cfg.tracking_args
    if t.enable_tracking and _state["log_handler"] is None:
        os.makedirs(t.log_file_dir, exist_ok=True)
        h = logging.FileHandler(
            os.path.join(t.log_file_dir, f"{t.run_name}.log"))
        h.setFormatter(logging.Formatter(
            "%(asctime)s %(levelname)s %(name)s: %(message)s"))
        root = logging.getLogger()
        root.addHandler(h)
        # records must actually reach the file: lower (never raise) the root
        # level to INFO; finish() restores it
        if root.level > logging.INFO:
            _state["prev_root_level"] = root.level
            root.setLevel(logging.INFO)
        _state["log_handler"] = h
    if t.enable_tracking and _state["sysperf"] is None:
        interval = sysperf_interval if sysperf_interval is not None else \
            float(t.extra.get("sysperf_interval", 10.0))
        _state["sysperf"] = SysPerfMonitor(interval).start()
    if t.enable_tracking:
        # remembered for finish(): the Chrome-trace artifact lands next to
        # the run's log/events files (ISSUE 2 — a tracked run produces an
        # openable trace with zero user code)
        _state["trace_run"] = (t.log_file_dir, t.run_name)
        # crash flight recorder (ISSUE 18): arm it at the run dir so a
        # SIGTERM'd/crashed tracked run leaves <log_dir>/postmortem.json.
        # Respect an already-armed recorder — outer harnesses own it then.
        from .utils import postmortem

        if postmortem.flight.armed_dir is None:
            postmortem.arm(t.log_file_dir, process=str(t.run_name))
    # model-artifact store (reference: log_aggregated_model_info uploads to
    # S3; here tracking_args.extra picks the sink):
    #   artifact_store: "file" (default when artifact_dir set) | "broker"
    #   artifact_dir:   file-store root
    #   artifact_broker_id / artifact_keep_rounds: broker-store knobs
    if _state["artifacts"] is None:
        kind = t.extra.get("artifact_store")
        if kind not in (None, "file", "broker"):
            raise ValueError(
                f"tracking_args.extra.artifact_store={kind!r}: choose "
                "'file' or 'broker' (a typo here would silently disable "
                "model-artifact publishing)")
        if kind == "broker":
            from .utils.artifacts import BrokerArtifactStore

            _state["artifacts"] = BrokerArtifactStore(
                broker_id=str(t.extra.get("artifact_broker_id", "default")),
                run_id=str(t.run_name),
                keep_rounds=t.extra.get("artifact_keep_rounds"))
        elif kind == "file" or t.extra.get("artifact_dir"):
            from .utils.artifacts import FileArtifactStore

            root = t.extra.get("artifact_dir") or os.path.join(
                t.log_file_dir, f"{t.run_name}_artifacts")
            _state["artifacts"] = FileArtifactStore(root)


def event(name: str, event_started: Optional[bool] = None,
          event_value: Optional[str] = None, **meta):
    """Span event. Two call styles, both from the reference:
    - context manager: `with mlops.event("train"): ...`
    - paired calls:    `mlops.event("train", event_started=True)` then
                       `mlops.event("train", event_started=False)`
    (reference: mlops_profiler_event.py:74-121)."""
    if event_started is None:
        return recorder.span(name, **({"value": event_value} if event_value
                                      else {}), **meta)
    key = (name, event_value)
    if event_started:
        _state["events"][key] = time.perf_counter()
    else:
        t0 = _state["events"].pop(key, None)
        dur = (time.perf_counter() - t0) if t0 is not None else 0.0
        recorder.log({"event": name, "value": event_value, "duration": dur})
    return None


def log(metrics: dict) -> None:
    """reference: mlops.log(:170) — round/step metric row."""
    recorder.log(dict(metrics))


def log_round_info(total_rounds: int, round_index: int) -> None:
    """reference: mlops.log_round_info(:763)."""
    recorder.log({"round_index": round_index, "total_rounds": total_rounds})


def set_artifact_store(store) -> None:
    """Wire an artifact store directly (bypass config): any object with
    put(name, tree) / get(name) / list() — utils/artifacts.py ships the
    file- and broker-backed ones."""
    _state["artifacts"] = store


def artifact_store():
    return _state["artifacts"]


def log_aggregated_model_info(round_idx: int, model_params) -> None:
    """Publish the round's aggregated global model (reference:
    core/mlops/__init__.py:388 — uploaded every round; serving loads it
    back). No-op when no artifact store is configured, like the reference
    when tracking is off."""
    store = _state["artifacts"]
    if store is None:
        return
    from .utils.artifacts import aggregated_name

    store.put(aggregated_name(round_idx), model_params)


def log_client_model_info(round_idx: int, client_rank: int,
                          model_params) -> None:
    """Publish one client's locally-trained model (reference:
    core/mlops/__init__.py:475 — client models on cadence)."""
    store = _state["artifacts"]
    if store is None:
        return
    from .utils.artifacts import client_name

    store.put(client_name(round_idx, client_rank), model_params)


def fetch_aggregated_model(round_idx: int):
    """Collector side: load the round-N aggregated model back from the
    artifact store (the reference fetches the S3 object by round)."""
    store = _state["artifacts"]
    if store is None:
        raise RuntimeError("no artifact store configured — call mlops.init "
                           "with tracking_args.extra.artifact_dir/"
                           "artifact_store, or set_artifact_store()")
    from .utils.artifacts import aggregated_name

    return store.get(aggregated_name(round_idx))


def system_stats() -> dict:
    from .utils.sysperf import sample_sysperf

    return sample_sysperf()


def metrics_snapshot() -> dict:
    """One dict of every process-wide counter/gauge/histogram (comm bytes &
    latency, serving request histograms, XLA compile/retrace counts —
    utils/metrics.py). The quantitative companion to `system_stats()`."""
    from .utils import metrics

    return metrics.snapshot()


def prometheus_text() -> str:
    """The current metrics snapshot as Prometheus text exposition — what
    the /metrics endpoint (utils/prometheus.py, opt-in via
    common_args.extra.metrics_port) serves to scrapers and `fedml_tpu
    top`."""
    from .utils.prometheus import render_prometheus

    return render_prometheus()


def export_chrome_trace(path: str) -> str:
    """Write the recorder's spans as a Chrome-trace/Perfetto JSON
    (utils/events.py EventRecorder.export_chrome_trace)."""
    return recorder.export_chrome_trace(path)


def _finish_report() -> None:
    """End-of-run summary → sinks (the reference posts a run-summary row at
    release), plus the Chrome-trace artifact for tracked runs."""
    # attribution plane (ISSUE 17): land measured MFU (span wall over
    # cost-analysis FLOPs) and the round-time budget as gauges BEFORE the
    # snapshot below, so the report row and Prometheus both carry them
    try:
        from .utils import attribution, xla_ledger

        xla_ledger.measured_mfu()
        attribution.analyze_and_publish()
    except Exception as e:  # noqa: BLE001 — attribution must not block exit
        logging.getLogger(__name__).warning(
            "attribution publish failed: %s: %s", type(e).__name__, e)
    # gate on recorder.sinks, not _state["sinks"]: fedml_tpu.init attaches
    # the config sinks itself, so this run's JsonlSink may predate mlops.init
    if recorder.sinks:
        try:
            recorder.log({"report": {"spans": recorder.summary(),
                                     "metrics": metrics_snapshot()}})
        except Exception as e:  # noqa: BLE001 — a summary must not block exit
            logging.getLogger(__name__).warning(
                "end-of-run summary failed: %s: %s", type(e).__name__, e)
    run = _state["trace_run"]
    _state["trace_run"] = None
    if run is not None:
        try:
            recorder.export_chrome_trace(
                os.path.join(run[0], f"{run[1]}.trace.json"))
        except Exception as e:  # noqa: BLE001
            logging.getLogger(__name__).warning(
                "chrome-trace export failed: %s: %s", type(e).__name__, e)
        # a clean finish writes the final postmortem (reason "finish")
        # and stops the inflight spill — the run dir never keeps a stale
        # "inflight" document that report would misread as a hard kill
        try:
            from .utils import postmortem

            if postmortem.flight.armed_dir == run[0]:
                postmortem.flight.flush("finish")
                postmortem.flight.disarm()
        except Exception as e:  # noqa: BLE001 — never block run teardown
            logging.getLogger(__name__).warning(
                "postmortem flush failed: %s: %s", type(e).__name__, e)


def finish() -> None:
    """Emit the end-of-run summary + Chrome trace, stop daemons, detach this
    run's sinks and log handler, restore the root log level (reference:
    mlops release paths)."""
    from .utils.sinks import flush_sinks

    run = _state["trace_run"]     # before _finish_report clears it
    _finish_report()
    flush_sinks()   # BrokerLogSink batches; the tail batch must ship
    if _state["sysperf"] is not None:
        _state["sysperf"].stop()
        _state["sysperf"] = None
    for sink in _state["sinks"]:
        if sink in recorder.sinks:
            recorder.sinks.remove(sink)
        getattr(sink, "close", lambda: None)()
    _state["sinks"].clear()
    if run is not None:
        # this run's sinks may have been attached by fedml_tpu.init BEFORE
        # mlops.init (attach_from_config is idempotent, so _state["sinks"]
        # never saw them); leaving them on the recorder would keep writing
        # every later span to the finished run's (possibly deleted) file
        log_dir, run_name = os.path.abspath(run[0]), run[1]
        for sink in list(recorder.sinks):
            key = getattr(sink, "_attach_key", None)
            if (isinstance(key, tuple) and key and key[-1] == run_name
                    and (len(key) != 2 or key[0] in (log_dir, "wandb"))):
                recorder.sinks.remove(sink)
                getattr(sink, "close", lambda: None)()
    root = logging.getLogger()
    if _state["log_handler"] is not None:
        root.removeHandler(_state["log_handler"])
        _state["log_handler"].close()
        _state["log_handler"] = None
    if _state["prev_root_level"] is not None:
        root.setLevel(_state["prev_root_level"])
        _state["prev_root_level"] = None
    _state["artifacts"] = None
