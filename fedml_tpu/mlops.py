"""MLOps facade — the reference's observability API surface, local-first.

(reference: python/fedml/core/mlops/__init__.py — `mlops.init(args)` :91,
`mlops.event(name, event_started, ...)` :153, `mlops.log(metrics)` :170,
`mlops.log_round_info(...)` :763, plus runtime-log redirection
(mlops_runtime_log.py) and the sys-perf reporters. The reference ships all
of it to the FedML cloud over MQTT+S3; here the same call names feed the
process-wide recorder, its sinks (JSONL/wandb — utils/sinks.py), a per-run
log file, and the sys-perf daemon.)

Usage parity with reference scripts:

    import fedml_tpu
    from fedml_tpu import mlops
    cfg = fedml_tpu.init(...)
    mlops.init(cfg)                      # sinks + log file + sysperf
    with mlops.event("train"):           # or event(..., started/ended)
        ...
    mlops.log({"acc": 0.9})
    mlops.log_round_info(rounds, r)
    mlops.finish()
"""
from __future__ import annotations

import logging
import os
import time
from typing import Optional

from .utils.events import recorder
from .utils.sysperf import SysPerfMonitor

_state: dict = {"sysperf": None, "log_handler": None, "events": {},
                "sinks": [], "prev_root_level": None}


def init(cfg, sysperf_interval: Optional[float] = None) -> None:
    """Attach sinks, redirect runtime logs to a per-run file (reference:
    mlops_runtime_log.init_logs), and start the sys-perf daemon when
    tracking is enabled."""
    from .utils.sinks import attach_from_config

    _state["sinks"].extend(attach_from_config(cfg))
    t = cfg.tracking_args
    if t.enable_tracking and _state["log_handler"] is None:
        os.makedirs(t.log_file_dir, exist_ok=True)
        h = logging.FileHandler(
            os.path.join(t.log_file_dir, f"{t.run_name}.log"))
        h.setFormatter(logging.Formatter(
            "%(asctime)s %(levelname)s %(name)s: %(message)s"))
        root = logging.getLogger()
        root.addHandler(h)
        # records must actually reach the file: lower (never raise) the root
        # level to INFO; finish() restores it
        if root.level > logging.INFO:
            _state["prev_root_level"] = root.level
            root.setLevel(logging.INFO)
        _state["log_handler"] = h
    if t.enable_tracking and _state["sysperf"] is None:
        interval = sysperf_interval if sysperf_interval is not None else \
            float(t.extra.get("sysperf_interval", 10.0))
        _state["sysperf"] = SysPerfMonitor(interval).start()


def event(name: str, event_started: Optional[bool] = None,
          event_value: Optional[str] = None, **meta):
    """Span event. Two call styles, both from the reference:
    - context manager: `with mlops.event("train"): ...`
    - paired calls:    `mlops.event("train", event_started=True)` then
                       `mlops.event("train", event_started=False)`
    (reference: mlops_profiler_event.py:74-121)."""
    if event_started is None:
        return recorder.span(name, **({"value": event_value} if event_value
                                      else {}), **meta)
    key = (name, event_value)
    if event_started:
        _state["events"][key] = time.perf_counter()
    else:
        t0 = _state["events"].pop(key, None)
        dur = (time.perf_counter() - t0) if t0 is not None else 0.0
        recorder.log({"event": name, "value": event_value, "duration": dur})
    return None


def log(metrics: dict) -> None:
    """reference: mlops.log(:170) — round/step metric row."""
    recorder.log(dict(metrics))


def log_round_info(total_rounds: int, round_index: int) -> None:
    """reference: mlops.log_round_info(:763)."""
    recorder.log({"round_index": round_index, "total_rounds": total_rounds})


def system_stats() -> dict:
    from .utils.sysperf import sample_sysperf

    return sample_sysperf()


def finish() -> None:
    """Stop daemons, detach this run's sinks and log handler, restore the
    root log level (reference: mlops release paths)."""
    if _state["sysperf"] is not None:
        _state["sysperf"].stop()
        _state["sysperf"] = None
    for sink in _state["sinks"]:
        if sink in recorder.sinks:
            recorder.sinks.remove(sink)
        getattr(sink, "close", lambda: None)()
    _state["sinks"].clear()
    root = logging.getLogger()
    if _state["log_handler"] is not None:
        root.removeHandler(_state["log_handler"])
        _state["log_handler"].close()
        _state["log_handler"] = None
    if _state["prev_root_level"] is not None:
        root.setLevel(_state["prev_root_level"])
        _state["prev_root_level"] = None
