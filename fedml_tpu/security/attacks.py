"""Attacks for robustness testing — model poisoning, data poisoning, and
gradient-leakage reconstruction.

TPU-native replacement for the reference's attack zoo (reference:
core/security/attack/*.py, dispatched by core/security/fedml_attacker.py:29-41;
hooks: `poison_data` on dataset load, `attack_model` on the server's received
update list, `reconstruct_data` on raw gradients).

Model-poisoning attacks are pure transforms on the stacked flat update matrix
`U: [m, D]` with a boolean malicious mask (vs the reference's per-client loops,
e.g. byzantine_attack.py:37-55). Data poisoning transforms the host-side numpy
arrays before device upload. Reconstruction attacks (DLG / invert-gradient /
label reveal) are jax-native gradient-matching optimizations — the reference
needs an L-BFGS torch loop (dlg_attack.py:20); here the matching loss and its
gradient jit into one XLA program.
"""
from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

Pytree = Any


# --------------------------------------------------------- model poisoning
def byzantine_attack(U: jax.Array, malicious: jax.Array, rng: jax.Array,
                     mode: str = "random") -> jax.Array:
    """(reference: byzantine_attack.py:20-55) modes: zero | random | flip.
    `malicious`: [m] bool mask. U rows are *deltas* (w_local - w_global), so
    the reference's flip-around-the-global-model (w' = 2 w_g - w_l,
    extra_auxiliary_info) is exactly delta' = -delta here."""
    mask = malicious[:, None]
    if mode == "zero":
        evil = jnp.zeros_like(U)
    elif mode == "random":
        evil = jax.random.normal(rng, U.shape, U.dtype)
    elif mode == "flip":
        evil = -U
    else:
        raise ValueError(f"unknown byzantine attack_mode {mode!r}")
    return jnp.where(mask, evil, U)


def model_replacement_attack(U: jax.Array, malicious: jax.Array,
                             scale: float) -> jax.Array:
    """Model-replacement backdoor (reference:
    model_replacement_backdoor_attack.py:13-21, Bagdasaryan et al.): scale the
    malicious update by gamma = n_total/n_participants (or a chosen S) so it
    survives averaging and replaces the global model."""
    return jnp.where(malicious[:, None], U * scale, U)


def lazy_worker_attack(U: jax.Array, malicious: jax.Array,
                       prev_U: jax.Array) -> jax.Array:
    """Lazy worker (reference: lazy_worker.py): malicious clients replay their
    previous-round update instead of training."""
    return jnp.where(malicious[:, None], prev_U, U)


# ---------------------------------------------------------- data poisoning
def label_flip(y: np.ndarray, num_classes: int,
               original_class: Optional[int] = None,
               target_class: Optional[int] = None) -> np.ndarray:
    """(reference: label_flipping_attack.py) targeted flip original→target,
    or the all-class mirror y -> C-1-y when unspecified."""
    y = np.array(y, copy=True)
    if original_class is None or target_class is None:
        return (num_classes - 1 - y).astype(y.dtype)
    y[y == original_class] = target_class
    return y


def backdoor_trigger(x: np.ndarray, y: np.ndarray, target_class: int,
                     trigger_value: float = 1.0, patch: int = 3) -> tuple:
    """Pixel-pattern backdoor (reference: backdoor_attack.py,
    edge_case_backdoor_attack.py semantics): stamp a corner patch and relabel
    to the target class."""
    x = np.array(x, copy=True)
    if x.ndim >= 3:
        x[..., :patch, :patch, :] = trigger_value
    else:
        x[..., :patch] = trigger_value
    return x, np.full_like(y, target_class)


def poison_clients_data(data: dict, client_ids: list[int],
                        transform: Callable[[np.ndarray, np.ndarray], tuple]) -> dict:
    """Apply a (x, y) -> (x, y) poison to selected clients of a stacked
    federated dataset (the `poison_data` hook site — reference:
    fedml_attacker.py:98, wired at client_trainer.py:32-38)."""
    x = np.array(data["x"], copy=True)
    y = np.array(data["y"], copy=True)
    for cid in client_ids:
        x[cid], y[cid] = transform(x[cid], y[cid])
    return {**data, "x": x, "y": y}


# ------------------------------------------------- gradient reconstruction
def reveal_labels_from_gradients(fc_weight_grad: jax.Array) -> jax.Array:
    """Label restoration from the last-layer weight gradient (reference:
    revealing_labels_from_gradients_attack.py; Zhao et al. iDLG): for
    cross-entropy, the gradient row of the true class is the only negative
    one. Returns the inferred class id."""
    row_sums = fc_weight_grad.reshape(fc_weight_grad.shape[0], -1).sum(axis=1)
    return jnp.argmin(row_sums)


def _infer_label_from_grads(true_grads: Pytree, num_classes: int):
    """iDLG label inference: find a classifier-head gradient leaf (bias of
    size C, or kernel with C output columns) — the true-class entry is the
    only negative one under cross-entropy."""
    for leaf in jax.tree.leaves(true_grads):
        if leaf.ndim == 1 and leaf.shape[0] == num_classes:
            return jnp.argmin(leaf)
    for leaf in jax.tree.leaves(true_grads):
        if leaf.ndim == 2 and leaf.shape[-1] == num_classes:
            return jnp.argmin(leaf.sum(axis=0))
    return None


def dlg_attack(apply_fn: Callable, params: Pytree, true_grads: Pytree,
               data_shape: tuple, num_classes: int, rng: jax.Array,
               steps: int = 200, lr: float = 0.1,
               loss_type: str = "l2") -> tuple[jax.Array, jax.Array]:
    """Deep Leakage from Gradients (reference: dlg_attack.py; Zhu et al. 2019)
    and its cosine-similarity variant (reference: invert_gradient_attack.py;
    Geiping et al. 2020, loss_type="cosine").

    Improvement over the reference's joint (x, y) optimization (which is the
    DLG paper's known-unstable mode): the label is first recovered
    analytically from the classifier-head gradient (iDLG, Zhao et al. 2020 —
    the reference ships this separately as
    revealing_labels_from_gradients_attack.py), then only x is optimized by
    gradient matching. The whole optimization is one jitted lax.scan — no host
    round-trips (the reference calls torch L-BFGS per step, dlg_attack.py:20).
    Returns (x_reconstructed, y_probs).
    """
    label = _infer_label_from_grads(true_grads, num_classes)
    if label is None:
        label = jnp.asarray(0)
    y_onehot = jax.nn.one_hot(label[None], num_classes)
    dummy_x = jax.random.normal(rng, (1,) + tuple(data_shape))
    opt = optax.adam(lr)

    def model_grads(x):
        def loss_fn(p):
            logits = apply_fn({"params": p}, x)
            logp = jax.nn.log_softmax(logits, axis=-1)
            return -(y_onehot * logp).sum(axis=-1).mean()

        return jax.grad(loss_fn)(params)

    def match_loss(x):
        g = model_grads(x)
        gl, tl = jax.tree.leaves(g), jax.tree.leaves(true_grads)
        if loss_type == "cosine":
            num = sum(jnp.vdot(a, b) for a, b in zip(gl, tl))
            den = jnp.sqrt(sum(jnp.vdot(a, a) for a in gl)) * jnp.sqrt(
                sum(jnp.vdot(b, b) for b in tl)
            )
            return 1.0 - num / jnp.maximum(den, 1e-12)
        return sum(jnp.sum((a - b) ** 2) for a, b in zip(gl, tl))

    @jax.jit
    def run(x0):
        state = opt.init(x0)

        def step(carry, _):
            x, s = carry
            loss, grads = jax.value_and_grad(match_loss)(x)
            updates, s = opt.update(grads, s, x)
            x = optax.apply_updates(x, updates)
            return (x, s), loss

        (x, _), losses = jax.lax.scan(step, (x0, state), None, length=steps)
        return x, losses

    x_rec, _ = run(dummy_x)
    return x_rec, y_onehot


def invert_gradient_attack(apply_fn: Callable, params: Pytree,
                           true_grads: Pytree, data_shape: tuple,
                           num_classes: int, rng: jax.Array,
                           steps: int = 300, lr: float = 0.1,
                           tv_weight: float = 1e-2,
                           box: tuple = (0.0, 1.0)) -> tuple:
    """Inverting Gradients (reference: invert_gradient_attack.py; Geiping
    et al. 2020): reconstruct a training input from a shared gradient by
    maximizing per-layer cosine similarity, with a total-variation prior
    and signed-gradient ascent inside a box constraint — the three
    ingredients that distinguish it from plain DLG (dlg_attack above).
    Label is recovered analytically first (iDLG). One jitted lax.scan; the
    reference runs a torch Adam step per python-loop iteration.
    Returns (x_reconstructed, y_onehot)."""
    label = _infer_label_from_grads(true_grads, num_classes)
    if label is None:
        label = jnp.asarray(0)
    y_onehot = jax.nn.one_hot(label[None], num_classes)
    x0 = jax.random.uniform(rng, (1,) + tuple(data_shape),
                            minval=box[0], maxval=box[1])
    opt = optax.adam(lr)

    def model_grads(x):
        def loss_fn(p):
            logp = jax.nn.log_softmax(apply_fn({"params": p}, x), axis=-1)
            return -(y_onehot * logp).sum(axis=-1).mean()

        return jax.grad(loss_fn)(params)

    def total_variation(x):
        dh = jnp.abs(jnp.diff(x, axis=1)).mean() if x.ndim >= 3 else 0.0
        dw = jnp.abs(jnp.diff(x, axis=2)).mean() if x.ndim >= 4 else 0.0
        return dh + dw

    def objective(x):
        g, t = jax.tree.leaves(model_grads(x)), jax.tree.leaves(true_grads)
        # per-layer cosine (Geiping eq. 4 sums layerwise), not one global dot
        sims = [
            jnp.vdot(a, b) / jnp.maximum(
                jnp.linalg.norm(a.ravel()) * jnp.linalg.norm(b.ravel()),
                1e-12)
            for a, b in zip(g, t)
        ]
        return 1.0 - jnp.mean(jnp.asarray(sims)) + tv_weight * total_variation(x)

    @jax.jit
    def run(x0):
        state = opt.init(x0)

        def step(carry, _):
            x, s = carry
            loss, grads = jax.value_and_grad(objective)(x)
            updates, s = opt.update(jnp.sign(grads), s, x)  # signed ascent
            x = jnp.clip(optax.apply_updates(x, updates), box[0], box[1])
            return (x, s), loss

        (x, _), losses = jax.lax.scan(step, (x0, state), None, length=steps)
        return x, losses

    x_rec, _ = run(x0)
    return x_rec, y_onehot
