"""Security plugin dispatchers — config-driven attacker/defender wiring.

TPU-native replacement for the reference singletons `FedMLAttacker`
(reference: core/security/fedml_attacker.py:14-110) and `FedMLDefender`
(fedml_defender.py:40-120). The reference intercepts the server's
List[Tuple[weight, OrderedDict]]; here both plug into the round program as the
`aggregate_full(stacked, weights, ctx) -> (agg, hook_state)` hook
(parallel/round.py), operating on the flat update matrix U: [m, D].

Composition order inside the hook (mirrors the reference lifecycle,
core/alg_frame/server_aggregator.py:42-83):
    attack_model (poison U)  →  defense reweight/select  →  robust aggregate
    →  postprocess_agg (SLSGD/CRFL/weak-DP noise).

Stateful defenses (FoolsGold history, cross-round memory, lazy-worker replay)
keep their state in `hook_state`, a pytree threaded through the jitted round —
no host round-trips (the reference mutates python dicts on the server).
"""
from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..config import Config, SecurityArgs
from . import attacks as atk
from . import defenses as dfs

Pytree = Any

# name constants (reference: core/security/constants.py:1-23)
DEFENSES = (
    "krum", "multikrum", "bulyan", "wise_median", "trimmed_mean", "geo_median",
    "rfa", "cclip", "norm_diff_clipping", "diff_clipping", "weak_dp",
    "robust_learning_rate", "slsgd", "crfl", "foolsgold", "3sigma",
    "3sigma_geo", "3sigma_foolsgold", "cross_round", "residual_reweight",
    "outlier_detection", "wbc", "soteria",
)
ATTACKS = ("byzantine", "label_flipping", "backdoor", "model_replacement",
           "edge_case_backdoor", "lazy_worker", "dlg", "invert_gradient",
           "revealing_labels")


def _flat_dim(params: Pytree) -> int:
    return int(sum(np.prod(l.shape) for l in jax.tree.leaves(params)))


class FedAttacker:
    """Model/data poisoning injector for robustness testing (reference:
    fedml_attacker.py:29-41 reads attack_type + spec)."""

    def __init__(self, s: SecurityArgs, client_num_per_round: int):
        self.enabled = bool(s.enable_attack)
        self.type = (s.attack_type or "").lower()
        self.spec = dict(s.attack_spec)
        self.m = client_num_per_round

    def malicious_mask(self, m: int) -> np.ndarray:
        """First `byzantine_client_num` of the m sampled slots are malicious
        (the reference samples random slots per round, byzantine_attack.py:25;
        deterministic slots keep tests reproducible — sampling is already
        random over clients). m is taken from the update matrix actually
        presented to the hook, so mesh padding can never desync the mask."""
        n_mal = int(self.spec.get("byzantine_client_num", 1))
        mask = np.zeros(m, bool)
        mask[: min(n_mal, m)] = True
        return mask

    def poison_updates(self, U: jax.Array, w: jax.Array, ctx: dict,
                       state: Pytree) -> tuple[jax.Array, Pytree]:
        """The attack_model hook on the flat stacked updates."""
        if not self.enabled:
            return U, state
        mal = jnp.asarray(self.malicious_mask(U.shape[0]))
        rng = jax.random.fold_in(ctx["rng"], 0xA77)
        if self.type == "byzantine":
            mode = self.spec.get("attack_mode", "random")
            return atk.byzantine_attack(U, mal, rng, mode), state
        if self.type in ("model_replacement", "backdoor"):
            scale = float(self.spec.get("scale_factor", self.m))
            if self.type == "backdoor":
                # the scaled update must be the one trained on poisoned data:
                # mark the sampled slots whose *global id* is a poisoned client
                # (poison_dataset used the same ids), not the first slots
                pids = jnp.asarray(
                    list(self.spec.get("poisoned_client_ids", [0])), jnp.int32
                )
                mal = jnp.isin(ctx["ids"], pids)
            return atk.model_replacement_attack(U, mal, scale), state
        if self.type == "lazy_worker":
            prev = state if state is not None else jnp.zeros_like(U)
            out = atk.lazy_worker_attack(U, mal, prev)
            return out, U  # remember this round's honest updates
        return U, state  # data-level attacks don't touch updates

    def init_state(self, m: int, dim: int) -> Pytree:
        if self.enabled and self.type == "lazy_worker":
            return jnp.zeros((m, dim), jnp.float32)
        return None

    def poison_dataset(self, data: dict, num_classes: int) -> dict:
        """Data-poisoning hook applied host-side before device upload
        (reference: poison_data, fedml_attacker.py:98, called from
        client_trainer.py:32-38)."""
        if not self.enabled:
            return data
        cids = list(self.spec.get("poisoned_client_ids", [0]))
        if self.type == "label_flipping":
            return atk.poison_clients_data(
                data, cids,
                lambda x, y: (x, atk.label_flip(
                    y, num_classes,
                    self.spec.get("original_class"),
                    self.spec.get("target_class"),
                )),
            )
        if self.type == "backdoor":
            target = int(self.spec.get("target_class", 0))
            return atk.poison_clients_data(
                data, cids, lambda x, y: atk.backdoor_trigger(x, y, target)
            )
        if self.type == "edge_case_backdoor":
            # Attack of the Tails (reference: edge_case_backdoor_attack.py):
            # malicious clients swap a fraction of their data for low-density
            # edge-case examples labeled with the target class — no pixel
            # trigger, so norm/trigger-based defenses have less to see
            from ..data.poison import edge_case_pool, replace_with_edge_cases

            target = int(self.spec.get("target_class", 0))
            source = int(self.spec.get("source_class", num_classes - 1))
            frac = float(self.spec.get("sample_frac", 0.5))
            tail = float(self.spec.get("tail_frac", 0.1))
            real = data["mask"].reshape(-1) > 0
            pool = edge_case_pool(
                data["x"].reshape((-1,) + data["x"].shape[2:])[real],
                data["y"].reshape(-1)[real], source, tail)
            out = {k: np.array(v) for k, v in data.items()}
            for i, c in enumerate(cids):
                out["x"][c], out["y"][c] = replace_with_edge_cases(
                    out["x"][c], out["y"][c], out["mask"][c], pool,
                    target, frac, seed=1000 + i)
            return out
        return data


class FedDefender:
    """Robust-aggregation dispatcher (reference: fedml_defender.py:55-90 maps
    defense_type -> defense object; here -> a pure aggregate/reweight fn)."""

    def __init__(self, s: SecurityArgs, num_clients_total: int):
        self.enabled = bool(s.enable_defense)
        self.type = (s.defense_type or "").lower()
        self.spec = dict(s.defense_spec)
        self.n_total = num_clients_total
        if self.enabled and self.type not in DEFENSES:
            raise ValueError(f"unknown defense {self.type!r}; one of {DEFENSES}")

    @property
    def stateful(self) -> bool:
        return self.type in ("foolsgold", "3sigma_foolsgold", "cross_round")

    def init_state(self, dim: int) -> Pytree:
        """FoolsGold/cross-round keep per-global-client history [N, D]."""
        if self.enabled and self.stateful:
            return jnp.zeros((self.n_total, dim), jnp.float32)
        return None

    def _aggregate(self, U, w, ctx, state):
        sp = self.spec
        f = int(sp.get("byzantine_client_num", max(1, U.shape[0] // 4)))
        t = self.type
        rng = jax.random.fold_in(ctx["rng"], 0xDEF)
        if t == "krum":
            return dfs.krum(U, w, f, multi=False), state
        if t == "multikrum":
            return dfs.krum(U, w, f, multi=True, k=sp.get("krum_param_k")), state
        if t == "bulyan":
            return dfs.bulyan(U, w, f), state
        if t == "wise_median":
            return dfs.coordinate_median(U, w), state
        if t == "trimmed_mean":
            return dfs.trimmed_mean(U, w, int(sp.get("beta", f))), state
        if t in ("geo_median", "rfa"):
            return dfs.geometric_median(U, w, int(sp.get("iters", 10))), state
        if t == "cclip":
            return dfs.cclip(U, w, float(sp.get("tau", 10.0)),
                             int(sp.get("iters", 3))), state
        if t in ("norm_diff_clipping", "diff_clipping"):
            mx = float(sp.get("norm_bound", 3.0))
            Uc = jax.vmap(lambda u: dfs.norm_clip_update(u, mx))(U)
            return dfs._wmean(Uc, w), state
        if t == "weak_dp":
            return dfs.weak_dp_aggregate(
                U, w, rng, float(sp.get("clip", 1.0)),
                float(sp.get("stddev", 0.025))), state
        if t == "robust_learning_rate":
            return dfs.robust_learning_rate_aggregate(
                U, w, float(sp.get("threshold", 0.5))), state
        if t == "residual_reweight":
            return dfs.residual_reweight_aggregate(U, w), state
        if t == "outlier_detection":
            w2 = dfs.outlier_detection_weights(U, w)
            return dfs._wmean(U, w2), state
        if t == "3sigma":
            w2 = dfs.three_sigma_weights(U, w)
            return dfs._wmean(U, w2), state
        if t == "3sigma_geo":
            center = dfs.geometric_median(U, w)
            w2 = dfs.three_sigma_weights(U, w, center)
            return dfs._wmean(U, w2), state
        if t in ("foolsgold", "3sigma_foolsgold"):
            hist = state.at[ctx["ids"]].add(U)
            lr = dfs.foolsgold_weights(hist[ctx["ids"]])
            w2 = w * lr
            if t == "3sigma_foolsgold":
                w2 = dfs.three_sigma_weights(U, w2)
            return dfs._wmean(U, w2), hist
        if t == "cross_round":
            prev = state[ctx["ids"]]
            w2 = dfs.cross_round_weights(U, prev, w,
                                         float(self.spec.get("threshold", 0.0)))
            return dfs._wmean(U, w2), state.at[ctx["ids"]].set(U)
        if t == "slsgd":
            b = int(sp.get("trim_param_b", 0))
            agg = dfs.trimmed_mean(U, w, b) if b else dfs._wmean(U, w)
            return agg, state
        if t in ("wbc", "soteria"):  # client-side transforms; plain mean here
            return dfs._wmean(U, w), state
        raise ValueError(f"defense {t!r} not dispatchable")

    def update_transform(self) -> Optional[Callable]:
        """Client-side defenses → postprocess_update hook."""
        if not self.enabled:
            return None
        sp = self.spec
        if self.type == "wbc":
            def f(upd, rng):
                U, unflat = dfs.stack_flat(jax.tree.map(lambda x: x[None], upd))
                out = dfs.wbc_update_transform(
                    U[0], rng, float(sp.get("eta", 0.1)),
                    float(sp.get("noise_std", 0.1)))
                return unflat(out)
            return f
        if self.type == "soteria":
            def f(upd, rng):
                U, unflat = dfs.stack_flat(jax.tree.map(lambda x: x[None], upd))
                out = dfs.soteria_update_transform(
                    U[0], float(sp.get("prune_ratio", 0.5)))
                return unflat(out)
            return f
        return None

    def postprocess_agg(self) -> Optional[Callable[[Pytree, dict], Pytree]]:
        """Global-model post-processing (SLSGD moving average, CRFL)."""
        if not self.enabled:
            return None
        sp = self.spec
        if self.type == "slsgd":
            alpha = float(sp.get("alpha", 1.0))

            def f(agg, ctx):
                # agg is a *delta*; moving average on the delta scales it
                return jax.tree.map(lambda a: alpha * a, agg)
            return f
        if self.type == "crfl":
            clip, sigma = float(sp.get("clip", 15.0)), float(sp.get("sigma", 0.01))

            def f(agg, ctx):
                U, unflat = dfs.stack_flat(jax.tree.map(lambda x: x[None], agg))
                rng = jax.random.fold_in(ctx["rng"], 0xCF1)
                return unflat(dfs.crfl_postprocess(U[0], rng, clip, sigma))
            return f
        return None


def build_server_pipeline(
    attacker: FedAttacker, defender: FedDefender
) -> Optional[Callable]:
    """Compose attack→defense into the round engine's aggregate_full hook.
    Returns None when neither side needs the full update set."""
    need_full = (attacker.enabled and attacker.type in
                 ("byzantine", "model_replacement", "backdoor", "lazy_worker")) \
        or (defender.enabled and defender.type not in ("wbc", "soteria"))
    if not need_full:
        return None

    def aggregate_full(stacked: Pytree, weights: jax.Array, ctx: dict):
        U, unflat = dfs.stack_flat(stacked)
        if isinstance(ctx["state"], dict):
            atk_st, dfs_st = ctx["state"].get("atk"), ctx["state"].get("dfs")
        else:
            atk_st, dfs_st = None, ctx["state"]
        U, atk_st = attacker.poison_updates(U, weights, ctx, atk_st)
        if defender.enabled:
            agg, dfs_st = defender._aggregate(U, weights, ctx, dfs_st)
        else:
            agg = dfs._wmean(U, weights)
        return unflat(agg), {"atk": atk_st, "dfs": dfs_st}

    return aggregate_full


def init_pipeline_state(attacker: FedAttacker, defender: FedDefender,
                        params: Pytree, client_num_per_round: int) -> Pytree:
    dim = _flat_dim(params)
    return {
        "atk": attacker.init_state(client_num_per_round, dim),
        "dfs": defender.init_state(dim),
    }


def from_config(cfg: Config) -> tuple[FedAttacker, FedDefender]:
    t = cfg.train_args
    return (FedAttacker(cfg.security_args, t.client_num_per_round),
            FedDefender(cfg.security_args, t.client_num_in_total))
