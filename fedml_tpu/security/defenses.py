"""Robust-aggregation defenses as batched jnp ops over stacked client updates.

TPU-native replacement for the reference's per-client Python/torch loops
(reference: core/security/defense/*.py, 23 files, dispatched by
core/security/fedml_defender.py:55-90). The reference materializes a
`List[Tuple[weight, OrderedDict]]` and loops; here every defense is a pure
function over a stacked flat update matrix `U: [m, D]` + weights `[m]`, so it
jits, fuses into the round program, and runs on the MXU (pairwise-distance
matrices are one matmul).

Defense taxonomy (matches how FedMLDefender wires hooks,
core/alg_frame/server_aggregator.py:58-76):
- reweighting  (U, w) -> w'        : krum-select, 3-sigma family, foolsgold,
                                     outlier detection  — zero/adjust weights
- aggregating  (U, w) -> u_agg     : median, trimmed mean, geometric median/
                                     RFA, bulyan, cclip, robust-LR
- per-update   (u)    -> u'        : norm clipping, weak-DP clip, WBC noise
- post-agg     (u_agg, prev) -> u' : SLSGD moving average, CRFL clip+noise

All functions take/return flat [m, D]; `stack_flat`/`unstack_flat` convert
from/to stacked pytrees.
"""
from __future__ import annotations

import functools
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

Pytree = Any


# ------------------------------------------------------------- flat helpers
def stack_flat(stacked: Pytree) -> tuple[jax.Array, Callable[[jax.Array], Pytree]]:
    """Stacked pytree (leaves [m, ...]) -> (U [m, D], unflatten(u [D]) -> tree)."""
    leaves, treedef = jax.tree.flatten(stacked)
    m = leaves[0].shape[0]
    shapes = [l.shape[1:] for l in leaves]
    sizes = [int(np.prod(s)) if s else 1 for s in shapes]
    U = jnp.concatenate([l.reshape(m, -1) for l in leaves], axis=1)

    def unflatten(u: jax.Array) -> Pytree:
        out, off = [], 0
        for shape, size in zip(shapes, sizes):
            out.append(u[off : off + size].reshape(shape))
            off += size
        return jax.tree.unflatten(treedef, out)

    return U, unflatten


def _wmean(U: jax.Array, w: jax.Array) -> jax.Array:
    w = w / jnp.maximum(w.sum(), 1e-12)
    return w @ U


def _pairwise_sqdist(U: jax.Array) -> jax.Array:
    """[m, m] squared euclidean distances — one gram matmul on the MXU."""
    sq = jnp.sum(U * U, axis=1)
    return jnp.maximum(sq[:, None] + sq[None, :] - 2.0 * (U @ U.T), 0.0)


# ----------------------------------------------------------- krum / bulyan
def krum_scores(U: jax.Array, num_byzantine: int) -> jax.Array:
    """Krum score = sum of sq-dists to the m-f-2 nearest neighbors
    (reference: defense/krum_defense.py; Blanchard et al. 2017)."""
    m = U.shape[0]
    d2 = _pairwise_sqdist(U)
    d2 = d2.at[jnp.arange(m), jnp.arange(m)].set(jnp.inf)
    k = max(1, m - num_byzantine - 2)
    nearest = -jax.lax.top_k(-d2, k)[0]  # k smallest per row
    return nearest.sum(axis=1)


def krum(U: jax.Array, w: jax.Array, num_byzantine: int,
         multi: bool = False, k: Optional[int] = None) -> jax.Array:
    """krum / multikrum (reference: krum_defense.py; constants.py:3,15).
    Returns the aggregate: the single best update, or the mean of the k best."""
    scores = krum_scores(U, num_byzantine)
    if not multi:
        return U[jnp.argmin(scores)]
    k = k or max(1, U.shape[0] - num_byzantine)
    _, idx = jax.lax.top_k(-scores, k)
    return _wmean(U[idx], w[idx])


def bulyan(U: jax.Array, w: jax.Array, num_byzantine: int) -> jax.Array:
    """Bulyan (reference: bulyan_defense.py; Mhamdi et al. 2018): multikrum-
    select theta = m - 2f updates, then per-coordinate trimmed mean of the
    beta = theta - 2f values closest to the coordinate median."""
    m = U.shape[0]
    f = num_byzantine
    theta = max(1, m - 2 * f)
    scores = krum_scores(U, f)
    _, idx = jax.lax.top_k(-scores, theta)
    S = U[idx]
    beta = max(1, theta - 2 * f)
    med = jnp.median(S, axis=0)
    dist = jnp.abs(S - med[None, :])
    _, sel = jax.lax.top_k(-dist.T, beta)  # [D, beta] closest-to-median rows
    return jnp.take_along_axis(S.T, sel, axis=1).mean(axis=1)


# ------------------------------------------------- coordinate-wise statistics
def coordinate_median(U: jax.Array, w: jax.Array) -> jax.Array:
    """(reference: coordinate_wise_median_defense.py; Yin et al. 2018)"""
    return jnp.median(U, axis=0)


def trimmed_mean(U: jax.Array, w: jax.Array, trim_b: int) -> jax.Array:
    """Drop the b largest and b smallest per coordinate, mean the rest
    (reference: coordinate_wise_trimmed_mean_defense.py, common/utils.py
    trimmed_mean)."""
    m = U.shape[0]
    b = int(min(trim_b, (m - 1) // 2))
    if b == 0:
        return U.mean(axis=0)
    s = jnp.sort(U, axis=0)
    return s[b : m - b].mean(axis=0)


def geometric_median(U: jax.Array, w: jax.Array, iters: int = 10,
                     eps: float = 1e-6) -> jax.Array:
    """Smoothed Weiszfeld (reference: geometric_median_defense.py &
    RFA_defense.py; Pillutla et al. RFA). Fixed iteration count → lax.fori."""
    z0 = _wmean(U, w)

    def body(_, z):
        d = jnp.maximum(jnp.linalg.norm(U - z[None, :], axis=1), eps)
        beta = w / d
        return (beta @ U) / jnp.maximum(beta.sum(), 1e-12)

    return jax.lax.fori_loop(0, iters, body, z0)


rfa = geometric_median  # constants.py:9 DEFENSE_RFA


# ---------------------------------------------------------------- filtering
def three_sigma_weights(U: jax.Array, w: jax.Array,
                        center: Optional[jax.Array] = None) -> jax.Array:
    """3-sigma outlier filter (reference: three_sigma_defense.py): score each
    client by distance to the center (coordinate median by default,
    geometric median for '3sigma_geo'); zero the weight of clients whose
    score exceeds mean + 3*std."""
    c = coordinate_median(U, w) if center is None else center
    scores = jnp.linalg.norm(U - c[None, :], axis=1)
    # robust location/scale: median + 1.4826*MAD (the plain mean/std the name
    # suggests is itself corrupted by the outliers being filtered; the
    # reference's score pipeline has the same failure mode)
    med = jnp.median(scores)
    mad = jnp.maximum(1.4826 * jnp.median(jnp.abs(scores - med)), 1e-6)
    keep = (scores <= med + 3.0 * mad).astype(w.dtype)
    return w * keep


def outlier_detection_weights(U: jax.Array, w: jax.Array, k: int = 2) -> jax.Array:
    """k-NN-distance outlier score filter (reference: outlier_detection.py):
    clients whose mean distance to their k nearest neighbors exceeds
    mean + 2*std are dropped."""
    m = U.shape[0]
    d2 = _pairwise_sqdist(U)
    d2 = d2.at[jnp.arange(m), jnp.arange(m)].set(jnp.inf)
    k = min(k, m - 1)
    nearest = -jax.lax.top_k(-d2, k)[0]
    scores = jnp.sqrt(nearest).mean(axis=1)
    med = jnp.median(scores)
    mad = jnp.maximum(1.4826 * jnp.median(jnp.abs(scores - med)), 1e-6)
    keep = (scores <= med + 3.0 * mad).astype(w.dtype)
    return w * keep


def foolsgold_weights(history: jax.Array) -> jax.Array:
    """FoolsGold (reference: foolsgold_defense.py; Fung et al. 2020): cosine
    similarity of per-client *historical* aggregate updates -> sybil credit.
    `history`: [m, D] cumulative updates. Returns per-client lr in [0, 1]."""
    norms = jnp.maximum(jnp.linalg.norm(history, axis=1, keepdims=True), 1e-12)
    cs = (history / norms) @ (history / norms).T
    m = cs.shape[0]
    cs = cs.at[jnp.arange(m), jnp.arange(m)].set(0.0)
    maxcs = cs.max(axis=1)
    # pardoning: rescale similarities of honest clients
    pard = jnp.where(maxcs[None, :] > maxcs[:, None],
                     cs * (maxcs[:, None] / jnp.maximum(maxcs[None, :], 1e-12)), cs)
    wv = 1.0 - pard.max(axis=1)
    wv = jnp.clip(wv, 0.0, 1.0)
    wv = wv / jnp.maximum(wv.max(), 1e-12)
    # logit squashing, as in the paper
    wv = jnp.where(wv == 1.0, 0.99, wv)
    lr = jnp.log(wv / (1.0 - wv) + 1e-12) + 0.5
    return jnp.clip(lr, 0.0, 1.0)


def cross_round_weights(U: jax.Array, prev_U: jax.Array, w: jax.Array,
                        threshold: float = 0.0) -> jax.Array:
    """Cross-round consistency (reference: cross_round_defense.py): clients
    whose update flips direction vs their previous round (cosine below
    threshold) are down-weighted to zero this round."""
    num = jnp.sum(U * prev_U, axis=1)
    den = jnp.maximum(
        jnp.linalg.norm(U, axis=1) * jnp.linalg.norm(prev_U, axis=1), 1e-12
    )
    cos = num / den
    fresh = jnp.linalg.norm(prev_U, axis=1) < 1e-9  # no history yet
    keep = jnp.logical_or(cos >= threshold, fresh).astype(w.dtype)
    return w * keep


# ------------------------------------------------------------- clipping family
def norm_clip_update(u: jax.Array, max_norm: float) -> jax.Array:
    """(reference: norm_diff_clipping_defense.py — clips the client-global
    delta norm; constants.py:1,17)"""
    n = jnp.linalg.norm(u)
    return u * jnp.minimum(1.0, max_norm / jnp.maximum(n, 1e-12))


def weak_dp_aggregate(U: jax.Array, w: jax.Array, rng: jax.Array,
                      clip: float = 1.0, stddev: float = 0.025) -> jax.Array:
    """(reference: weak_dp_defense.py): clip each update, mean, add small
    gaussian noise to the aggregate."""
    Uc = jax.vmap(lambda u: norm_clip_update(u, clip))(U)
    agg = _wmean(Uc, w)
    return agg + stddev * jax.random.normal(rng, agg.shape)


def cclip(U: jax.Array, w: jax.Array, tau: float = 10.0, iters: int = 3,
          center: Optional[jax.Array] = None) -> jax.Array:
    """Centered clipping (reference: cclip_defense.py; Karimireddy et al.
    2021): iterate v <- v + mean_i clip(u_i - v, tau)."""
    v0 = jnp.zeros(U.shape[1], U.dtype) if center is None else center

    def body(_, v):
        diff = U - v[None, :]
        n = jnp.linalg.norm(diff, axis=1, keepdims=True)
        clipped = diff * jnp.minimum(1.0, tau / jnp.maximum(n, 1e-12))
        return v + _wmean(clipped, w)

    return jax.lax.fori_loop(0, iters, body, v0)


def robust_learning_rate_aggregate(U: jax.Array, w: jax.Array,
                                   threshold: float = 0.5) -> jax.Array:
    """Robust learning rate (reference: robust_learning_rate_defense.py;
    Ozdayi et al. 2021): per-coordinate sign vote; coordinates where the
    |weighted sign sum| is below threshold*sum(w) get a flipped sign."""
    wsum = jnp.maximum(w.sum(), 1e-12)
    vote = jnp.abs((w @ jnp.sign(U)) / wsum)
    lr = jnp.where(vote >= threshold, 1.0, -1.0)
    return lr * _wmean(U, w)


def residual_reweight_aggregate(U: jax.Array, w: jax.Array,
                                iters: int = 3, delta: float = 1e-6) -> jax.Array:
    """Residual-based reweighting (reference:
    residual_based_reweighting_defense.py; Fu et al. 2019). IRLS: repeatedly
    reweight clients by a Huber-style function of their residual to the
    current robust estimate. (The reference runs per-parameter repeated-median
    regression; this is the same estimator family, computed on the full
    update vector — one matmul per iteration instead of a python loop per
    scalar parameter.)"""
    z0 = coordinate_median(U, w)

    def body(_, z):
        r = jnp.linalg.norm(U - z[None, :], axis=1)
        med = jnp.median(r)
        s = jnp.maximum(1.4826 * med, delta)  # MAD scale
        ww = w / jnp.maximum(r / s, 1.0)      # Huber weight
        return _wmean(U, ww)

    return jax.lax.fori_loop(0, iters, body, z0)


# --------------------------------------------------------------- post-agg
def slsgd_postprocess(agg: jax.Array, prev_global: jax.Array,
                      alpha: float = 1.0) -> jax.Array:
    """SLSGD moving average (reference: slsgd_defense.py:60-70):
    new = (1-alpha)*old + alpha*agg. (Pair with trimmed_mean for option 2.)"""
    return (1.0 - alpha) * prev_global + alpha * agg


def crfl_postprocess(agg: jax.Array, rng: jax.Array, clip: float = 15.0,
                     sigma: float = 0.01) -> jax.Array:
    """CRFL certified robustness (reference: crfl_defense.py; Xie et al.
    2021): clip the global model norm, then perturb with gaussian noise."""
    return norm_clip_update(agg, clip) + sigma * jax.random.normal(rng, agg.shape)


def wbc_update_transform(u: jax.Array, rng: jax.Array, eta: float = 0.1,
                         noise_std: float = 0.1) -> jax.Array:
    """FL-WBC client-side perturbation (reference: wbc_defense.py:9-23; Sun
    et al. 2021): perturb the parameter subspace where the update is small
    (where long-lasting attack effects hide) with laplace noise."""
    noise = noise_std * jax.random.laplace(rng, u.shape)
    small = jnp.abs(u) - eta * jnp.abs(noise) <= 0.0
    return jnp.where(small, u + eta * noise, u)


def soteria_update_transform(u: jax.Array, prune_ratio: float = 0.5) -> jax.Array:
    """Soteria-style leakage defense (reference: soteria_defense.py; Sun et
    al. 2021 'Provable defense'): prune the smallest-magnitude fraction of
    the update so reconstruction attacks lose the low-signal coordinates the
    inversion relies on. (The reference perturbs the representation layer
    during training; on the update vector the equivalent sparsification is
    applied post-hoc.)"""
    k = max(1, int(u.size * (1.0 - prune_ratio)))
    _, idx = jax.lax.top_k(jnp.abs(u), k)
    return jnp.zeros_like(u).at[idx].set(u[idx])
