"""Cross-silo FL server — the message-driven FSM.

(reference: cross_silo/server/fedml_server_manager.py:82-246 — handlers for
connection_ready / client_status / model_from_client; round flow: check
status → all online → send_init_msg → collect models → aggregate → sync;
aggregation bookkeeping in server/fedml_aggregator.py:13-104
add_local_trained_result/check_whether_all_receive/aggregate.)

Aggregation runs on device: stacked numpy updates → tree_weighted_mean (or
the security pipeline's robust aggregate) in one jit call.

Beyond the reference: timeout-based partial aggregation. The reference's sync
server waits forever for every selected client
(fedml_aggregator.check_whether_all_receive, :68-75 — its only dropout story
is the separate async_fedavg runtime); here `round_timeout` + `quorum_frac`
let the round close on a quorum after a deadline, and stragglers simply
rejoin the next selection.
"""
from __future__ import annotations

import logging
import math
import threading
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..comm import FedCommManager, Message
from ..ops import tree as tu
from ..utils.events import recorder
from . import message_define as md

Pytree = Any
log = logging.getLogger(__name__)


class FedAggregator:
    """Result pool + merge (reference: server/fedml_aggregator.py:13-104)."""

    def __init__(self, aggregate_fn: Optional[Callable] = None):
        self.results: dict[int, tuple[Pytree, float]] = {}
        self.expected: set[int] = set()
        self.aggregate_fn = aggregate_fn

    def reset(self, client_ids) -> None:
        self.results.clear()
        self.expected = set(client_ids)

    def add_local_trained_result(self, client_id: int, params: Pytree,
                                 n_samples: float) -> None:
        self.results[client_id] = (params, n_samples)

    def check_whether_all_receive(self) -> bool:
        return self.expected.issubset(self.results)

    def aggregate(self) -> Pytree:
        with recorder.span("agg"):
            ids = sorted(self.results)
            stacked = tu.tree_stack([jax.tree.map(jnp.asarray, self.results[i][0])
                                     for i in ids])
            weights = jnp.asarray([self.results[i][1] for i in ids], jnp.float32)
            if self.aggregate_fn is not None:
                agg = self.aggregate_fn(stacked, weights)
            else:
                agg = tu.tree_weighted_mean(stacked, weights)
            return jax.tree.map(np.asarray, jax.device_get(agg))


class FedServerManager:
    """(reference: FedMLServerManager, fedml_server_manager.py:22-246)

    round_timeout: seconds to wait for selected clients before attempting a
    partial aggregate. None (default) = reference behavior, wait forever.
    quorum_frac: fraction of selected clients that must have reported for a
    timed-out round to close (ceil; at least 1). Below quorum the timer
    re-arms. Dropped clients stay in `client_ids` and rejoin later rounds.
    postprocess_agg_fn: (params, round_idx) -> params applied after
    aggregation — the on_after_aggregation hook site (reference:
    core/alg_frame/server_aggregator.py:79-83; central-DP noise lands here).
    """

    def __init__(self, comm: FedCommManager, client_ids: list[int],
                 init_params: Pytree, num_rounds: int,
                 aggregate_fn: Optional[Callable] = None,
                 eval_fn: Optional[Callable[[Pytree, int], dict]] = None,
                 client_num_per_round: Optional[int] = None,
                 sample_seed: int = 0,
                 round_timeout: Optional[float] = None,
                 quorum_frac: float = 1.0,
                 postprocess_agg_fn: Optional[Callable] = None):
        self.comm = comm
        self.client_ids = list(client_ids)
        self.m = client_num_per_round or len(self.client_ids)
        self.params = init_params
        self.num_rounds = num_rounds
        self.round_idx = 0
        self.aggregator = FedAggregator(aggregate_fn)
        self.eval_fn = eval_fn
        self.sample_seed = sample_seed
        self.round_timeout = round_timeout
        self.quorum_frac = float(quorum_frac)
        self.postprocess_agg_fn = postprocess_agg_fn
        self.client_online: dict[int, bool] = {}
        self.is_initialized = False
        self.done = threading.Event()
        self.history: list[dict] = []
        self.dropped_log: list[tuple[int, list[int]]] = []  # (round, dropped ids)
        self._lock = threading.Lock()
        self._timer: Optional[threading.Timer] = None

        comm.register_message_receive_handler(
            md.CONNECTION_IS_READY, self._on_connection_ready)
        comm.register_message_receive_handler(
            md.C2S_CLIENT_STATUS, self._on_client_status)
        comm.register_message_receive_handler(
            md.C2S_SEND_MODEL, self._on_model_from_client)
        # clients ack S2C_FINISH with C2S_FINISHED; an unregistered type
        # raises in the receive loop, so the ack gets a no-op handler (the
        # ack races the stop sentinel, especially over gRPC)
        comm.register_message_receive_handler(
            md.C2S_FINISHED, lambda _msg: None)

    # --- selection (reference: fedml_aggregator.client_selection — seeded by
    # round, matching fedavg_api.py:127-135)
    def _select_clients(self, round_idx: int) -> list[int]:
        # sample from clients that have reported ONLINE (the init status check
        # goes to every client, so later rounds can select any live one);
        # before any status arrives — round 0 — fall back to the full list
        pool = [c for c in self.client_ids if self.client_online.get(c, False)]
        if len(pool) < self.m:
            pool = list(self.client_ids)
        if self.m >= len(pool):
            return sorted(pool)
        rng = np.random.RandomState(self.sample_seed + round_idx)
        return sorted(rng.choice(pool, self.m, replace=False).tolist())

    # ------------------------------------------------------------- handlers
    def _on_connection_ready(self, msg: Message) -> None:
        if self.is_initialized:
            return
        self.round_clients = self._select_clients(0)
        # status-check EVERY client, not just round 0's selection — clients
        # selected in later rounds must be registered online too (the round-1
        # weakness: unselected clients never got a check)
        for cid in self.client_ids:
            self.comm.send_message(
                Message(md.S2C_CHECK_CLIENT_STATUS, 0, cid))

    def _on_client_status(self, msg: Message) -> None:
        status = msg.get(md.KEY_STATUS)
        if status == md.STATUS_FINISHED:
            return
        with self._lock:
            self.client_online[msg.sender_id] = True
            all_online = all(self.client_online.get(c, False)
                             for c in self.round_clients)
            if all_online and not self.is_initialized:
                self.is_initialized = True
                self._send_init()

    def _send_init(self) -> None:
        self.aggregator.reset(self.round_clients)
        for cid in self.round_clients:
            m = Message(md.S2C_INIT_CONFIG, 0, cid)
            m.add(md.KEY_MODEL_PARAMS, self.params)
            m.add(md.KEY_ROUND, self.round_idx)
            self.comm.send_message(m)
        self._arm_timer()

    # ------------------------------------------------------ dropout handling
    def _arm_timer(self) -> None:
        if self.round_timeout is None:
            return
        self._cancel_timer()
        # bind the timer to the round it guards: a timer that fires while its
        # round completes would otherwise run against the NEXT round's state
        # (cancel() is a no-op on an already-fired Timer)
        t = threading.Timer(
            self.round_timeout, self._on_round_timeout, args=(self.round_idx,))
        t.daemon = True
        t.start()
        self._timer = t

    def _cancel_timer(self) -> None:
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None

    def _quorum(self) -> int:
        n = len(self.aggregator.expected)
        return max(1, math.ceil(self.quorum_frac * n))

    def _on_round_timeout(self, armed_round: int) -> None:
        with self._lock:
            if self.done.is_set() or armed_round != self.round_idx:
                return  # stale timer from an already-completed round
            received = len(self.aggregator.results)
            if received >= self._quorum():
                dropped = sorted(self.aggregator.expected
                                 - set(self.aggregator.results))
                if dropped:
                    log.warning("round %d: aggregating %d/%d, dropped %s",
                                self.round_idx, received,
                                len(self.aggregator.expected), dropped)
                    self.dropped_log.append((self.round_idx, dropped))
                self._complete_round()
            else:
                # below quorum: keep waiting (re-arm), matching the spirit of
                # the reference's wait-for-all rather than failing the run
                self._arm_timer()

    def _on_model_from_client(self, msg: Message) -> None:
        with self._lock:
            # a straggler's model from a closed round must not leak into the
            # current one — clients echo the round index they trained on;
            # a missing echo is rejected rather than assumed current (a
            # defaulted value would bypass exactly this guard)
            msg_round = msg.get(md.KEY_ROUND)
            if msg_round is None:
                log.warning("dropping C2S_SEND_MODEL from %s without %s",
                            msg.sender_id, md.KEY_ROUND)
                return
            if int(msg_round) != self.round_idx or \
                    msg.sender_id not in self.aggregator.expected:
                return
            self.aggregator.add_local_trained_result(
                msg.sender_id, msg.get(md.KEY_MODEL_PARAMS),
                float(msg.get(md.KEY_NUM_SAMPLES, 1.0)),
            )
            if not self.aggregator.check_whether_all_receive():
                return
            self._complete_round()

    def _complete_round(self) -> None:
        """Aggregate what's in the pool and advance. Caller holds the lock."""
        self._cancel_timer()
        self.params = self.aggregator.aggregate()
        if self.postprocess_agg_fn is not None:
            self.params = self.postprocess_agg_fn(self.params, self.round_idx)
        # publish the round's aggregated model through the mlops artifact
        # path (reference: fedml_aggregator calls mlops.log_aggregated_
        # model_info every round, core/mlops/__init__.py:388); no-op unless
        # an artifact store is configured
        from .. import mlops

        mlops.log_aggregated_model_info(self.round_idx, self.params)
        row = {"round": self.round_idx,
               "n_received": len(self.aggregator.results)}
        if self.eval_fn is not None:
            row.update(self.eval_fn(self.params, self.round_idx))
        self.history.append(row)
        recorder.log(row)
        self.round_idx += 1
        if self.round_idx >= self.num_rounds:
            self._finish()
            return
        self.round_clients = self._select_clients(self.round_idx)
        self.aggregator.reset(self.round_clients)
        for cid in self.round_clients:
            m = Message(md.S2C_SYNC_MODEL, 0, cid)
            m.add(md.KEY_MODEL_PARAMS, self.params)
            m.add(md.KEY_ROUND, self.round_idx)
            self.comm.send_message(m)
        self._arm_timer()

    def _finish(self) -> None:
        self._cancel_timer()
        for cid in self.client_ids:
            self.comm.send_message(Message(md.S2C_FINISH, 0, cid))
        self.done.set()
        # callers hold self._lock; comm.stop() joins the receive thread, which
        # may itself be blocked on the lock in a handler — stop from a fresh
        # thread so the join can't deadlock/stall against our lock
        threading.Thread(target=self.comm.stop, daemon=True).start()

    def run(self, background: bool = False) -> None:
        self.comm.run(background=background)
