"""Cross-silo FL server — the message-driven FSM.

(reference: cross_silo/server/fedml_server_manager.py:82-246 — handlers for
connection_ready / client_status / model_from_client; round flow: check
status → all online → send_init_msg → collect models → aggregate → sync;
aggregation bookkeeping in server/fedml_aggregator.py:13-104
add_local_trained_result/check_whether_all_receive/aggregate.)

Aggregation runs on device: stacked numpy updates → tree_weighted_mean (or
the security pipeline's robust aggregate) in one jit call.

Beyond the reference: timeout-based partial aggregation. The reference's sync
server waits forever for every selected client
(fedml_aggregator.check_whether_all_receive, :68-75 — its only dropout story
is the separate async_fedavg runtime); here `round_timeout` + `quorum_frac`
let the round close on a quorum after a deadline, and stragglers simply
rejoin the next selection.

Durability (ISSUE 10): process death is a recoverable event on both sides.

- **Checkpoint/restore** — at round boundaries the server persists params,
  round index, sample seed, the client-liveness table, the dropped log and
  history through `utils/checkpoint.py` (same atomic meta.json contract as
  the Simulator's; JSON-able server state rides meta["extra"]). A restarted
  server (`resume=True`) loads the latest checkpoint, re-runs the status
  handshake, and resumes at round N+1.
- **Generation fencing** — every S2C/C2S training message carries a
  run-generation (incarnation) header. A resumed server re-runs the round
  that was in flight when it died, so a pre-restart straggler's round-echo
  can EQUAL the live round index; the transport's `_rel_epoch` fences
  delivery, not training semantics, so the FSM fences itself here.
- **Client re-attach** — a CONNECTION_IS_READY after `is_initialized` is a
  rejoin, not a no-op: the server re-runs the status handshake for that
  client and re-sends the current round's payload if it is selected and
  missing.
- **Liveness eviction** — any C2S message (status / model / heartbeat)
  refreshes a per-client last-seen stamp; a sweep flips `client_online`
  False after `liveness_timeout_s` of silence and `_select_clients` stops
  drafting evicted clients (previously each dead client cost a full
  `round_timeout` every round it was selected). A recovered client re-enters
  the pool on its next status/heartbeat.
"""
from __future__ import annotations

import logging
import math
import threading
import time
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..comm import FedCommManager, Message
from ..ops import tree as tu
from ..utils import metrics as _mx
from ..utils.events import recorder
from . import message_define as md

Pytree = Any
log = logging.getLogger(__name__)


class FedAggregator:
    """Result pool + merge (reference: server/fedml_aggregator.py:13-104)."""

    def __init__(self, aggregate_fn: Optional[Callable] = None):
        self.results: dict[int, tuple[Pytree, float]] = {}
        self.expected: set[int] = set()
        self.aggregate_fn = aggregate_fn

    def reset(self, client_ids) -> None:
        self.results.clear()
        self.expected = set(client_ids)

    def add_local_trained_result(self, client_id: int, params: Pytree,
                                 n_samples: float) -> None:
        self.results[client_id] = (params, n_samples)

    def check_whether_all_receive(self) -> bool:
        return self.expected.issubset(self.results)

    def aggregate(self) -> Pytree:
        with recorder.span("agg"):
            ids = sorted(self.results)
            stacked = tu.tree_stack([jax.tree.map(jnp.asarray, self.results[i][0])
                                     for i in ids])
            weights = jnp.asarray([self.results[i][1] for i in ids], jnp.float32)
            if self.aggregate_fn is not None:
                agg = self.aggregate_fn(stacked, weights)
            else:
                agg = tu.tree_weighted_mean(stacked, weights)
            return jax.tree.map(np.asarray, jax.device_get(agg))


class FedServerManager:
    """(reference: FedMLServerManager, fedml_server_manager.py:22-246)

    round_timeout: seconds to wait for selected clients before attempting a
    partial aggregate. None (default) = reference behavior, wait forever.
    quorum_frac: fraction of selected clients that must have reported for a
    timed-out round to close (ceil; at least 1). Below quorum the timer
    re-arms, at most `max_rearms` times — then the run FAILS loudly
    (`self.error` set, `fed.server.quorum_unreachable` counted, clients
    released) instead of hanging forever. Dropped clients stay selectable
    and rejoin later rounds.
    postprocess_agg_fn: (params, round_idx) -> params applied after
    aggregation — the on_after_aggregation hook site (reference:
    core/alg_frame/server_aggregator.py:79-83; central-DP noise lands here).

    Durability knobs (ISSUE 10 — module docstring):
    checkpoint_dir / checkpoint_every / checkpoint_keep — round-boundary
    checkpoints through utils/checkpoint.py (every N completed rounds plus
    the final one). resume=True loads the latest checkpoint at construction
    and restarts at round N+1 with generation bumped.
    liveness_timeout_s — evict clients silent for this long from selection
    (arm it alongside client heartbeats shorter than the budget; see README
    "Cross-silo durability" for tuning).
    """

    def __init__(self, comm: FedCommManager, client_ids: list[int],
                 init_params: Pytree, num_rounds: int,
                 aggregate_fn: Optional[Callable] = None,
                 eval_fn: Optional[Callable[[Pytree, int], dict]] = None,
                 client_num_per_round: Optional[int] = None,
                 sample_seed: int = 0,
                 round_timeout: Optional[float] = None,
                 quorum_frac: float = 1.0,
                 postprocess_agg_fn: Optional[Callable] = None,
                 checkpoint_dir: Optional[str] = None,
                 checkpoint_every: int = 1,
                 checkpoint_keep: Optional[int] = 3,
                 resume: bool = False,
                 liveness_timeout_s: Optional[float] = None,
                 max_rearms: int = 5):
        self.comm = comm
        self.client_ids = list(client_ids)
        self.m = client_num_per_round or len(self.client_ids)
        self.params = init_params
        self.num_rounds = num_rounds
        self.round_idx = 0
        self.aggregator = FedAggregator(aggregate_fn)
        self.eval_fn = eval_fn
        self.sample_seed = sample_seed
        self.round_timeout = round_timeout
        self.quorum_frac = float(quorum_frac)
        self.postprocess_agg_fn = postprocess_agg_fn
        self.checkpoint_dir = checkpoint_dir
        self.checkpoint_every = int(checkpoint_every)
        self.checkpoint_keep = checkpoint_keep
        self.liveness_timeout_s = liveness_timeout_s
        self.max_rearms = int(max_rearms)
        # tri-state liveness: absent = never heard from (selectable — round 0
        # has no information yet), True = online, False = evicted. Only an
        # explicit False is excluded from selection.
        self.client_online: dict[int, bool] = {}
        self.last_seen: dict[int, float] = {}
        self.generation = 0          # incarnation index; bumped per resume
        self.is_initialized = False
        self.done = threading.Event()
        self.error: Optional[str] = None
        self.history: list[dict] = []
        self.dropped_log: list[tuple[int, list[int]]] = []  # (round, dropped ids)
        self.round_clients: list[int] = []
        self._synced: set[int] = set()   # sent the CURRENT round's payload
        self._resumed = False
        self._rearm_count = 0
        self._lock = threading.Lock()
        self._timer: Optional[threading.Timer] = None
        self._liveness_timer: Optional[threading.Timer] = None
        self._liveness_ref = time.monotonic()

        comm.register_message_receive_handler(
            md.CONNECTION_IS_READY, self._on_connection_ready)
        comm.register_message_receive_handler(
            md.C2S_CLIENT_STATUS, self._on_client_status)
        comm.register_message_receive_handler(
            md.C2S_SEND_MODEL, self._on_model_from_client)
        comm.register_message_receive_handler(
            md.C2S_HEARTBEAT, self._on_heartbeat)
        # clients ack S2C_FINISH with C2S_FINISHED; an unregistered type
        # raises in the receive loop, so the ack gets a no-op handler (the
        # ack races the stop sentinel, especially over gRPC)
        comm.register_message_receive_handler(
            md.C2S_FINISHED, lambda _msg: None)

        if resume and checkpoint_dir is not None:
            from ..utils.checkpoint import latest_round

            if latest_round(checkpoint_dir) is not None:
                self._restore(checkpoint_dir)
            else:
                log.info("resume requested but no checkpoints under %r — "
                         "starting fresh", checkpoint_dir)

    # --- selection (reference: fedml_aggregator.client_selection — seeded by
    # round, matching fedavg_api.py:127-135)
    def _select_clients(self, round_idx: int) -> list[int]:
        # exclude only clients the liveness sweep has explicitly EVICTED
        # (client_online[c] is False); never-seen clients stay selectable so
        # round 0 — before any status arrives — draws from the full list.
        # When eviction shrinks the pool below m, run the round over the
        # survivors rather than padding with known-dead clients (each dead
        # draftee used to cost a full round_timeout every round).
        pool = [c for c in self.client_ids
                if self.client_online.get(c, True) is not False]
        if not pool:
            pool = list(self.client_ids)   # everyone evicted: last resort
        if self.m >= len(pool):
            return sorted(pool)
        rng = np.random.RandomState(self.sample_seed + round_idx)
        return sorted(rng.choice(pool, self.m, replace=False).tolist())

    # ------------------------------------------------------------- liveness
    def _mark_alive(self, cid: int) -> None:
        """Caller holds the lock. Any C2S traffic refreshes liveness; a
        previously-evicted client re-enters the pool here."""
        if cid not in self.client_ids:
            return
        self.last_seen[cid] = time.monotonic()
        was = self.client_online.get(cid)
        self.client_online[cid] = True
        if was is False:
            _mx.inc("fed.server.rejoins")
            log.info("client %d recovered — back in the selection pool", cid)
        self._publish_liveness()
        if self.is_initialized:
            self._maybe_send_round(cid)

    def _publish_liveness(self) -> None:
        _mx.set_gauge("fed.server.clients_online",
                      sum(1 for v in self.client_online.values() if v))
        _mx.set_gauge("fed.server.clients_total", len(self.client_ids))

    def _arm_liveness(self) -> None:
        if self.liveness_timeout_s is None or self.done.is_set():
            return
        t = threading.Timer(max(self.liveness_timeout_s / 2.0, 0.05),
                            self._liveness_sweep)
        t.daemon = True
        t.start()
        self._liveness_timer = t

    def _liveness_sweep(self) -> None:
        try:
            with self._lock:
                if self.done.is_set():
                    return
                now = time.monotonic()
                for cid in self.client_ids:
                    ref = self.last_seen.get(cid, self._liveness_ref)
                    if self.client_online.get(cid) is not False \
                            and now - ref > self.liveness_timeout_s:
                        self.client_online[cid] = False
                        _mx.inc("fed.server.evicted")
                        log.warning(
                            "client %d silent for %.1fs (> "
                            "liveness_timeout_s=%.1fs) — evicted from "
                            "selection", cid, now - ref,
                            self.liveness_timeout_s)
                self._publish_liveness()
                if not self.is_initialized:
                    # the init handshake may be blocked on an evicted
                    # draftee: re-select round 0 over survivors, re-check
                    self.round_clients = self._select_clients(0)
                    self._maybe_init()
        except Exception:  # noqa: BLE001 — one bad sweep must not end
            log.exception("liveness sweep failed (chain continues)")
        # re-arm OUTSIDE the guarded body: an exception above must not
        # silently kill the whole liveness chain (_arm_liveness itself
        # no-ops once done is set)
        self._arm_liveness()

    # ------------------------------------------------------------- handlers
    def _on_connection_ready(self, msg: Message) -> None:
        if self.is_initialized:
            # re-attach (restarted client, or any client after a server
            # resume): re-run the status handshake for the SENDER; its
            # status reply re-registers it online and pulls the current
            # round's payload if it is selected and missing
            _mx.inc("fed.server.reattach_announces")
            self.comm.send_message(
                Message(md.S2C_CHECK_CLIENT_STATUS, 0, msg.sender_id))
            return
        self.round_clients = self._select_clients(0)
        # status-check EVERY client, not just round 0's selection — clients
        # selected in later rounds must be registered online too (the round-1
        # weakness: unselected clients never got a check)
        for cid in self.client_ids:
            self.comm.send_message(
                Message(md.S2C_CHECK_CLIENT_STATUS, 0, cid))

    def _on_client_status(self, msg: Message) -> None:
        status = msg.get(md.KEY_STATUS)
        if status == md.STATUS_FINISHED:
            return
        with self._lock:
            self._mark_alive(msg.sender_id)
            self._maybe_init()

    def _on_heartbeat(self, msg: Message) -> None:
        with self._lock:
            self._mark_alive(msg.sender_id)

    def _maybe_init(self) -> None:
        """Caller holds the lock."""
        if self.is_initialized or not self.round_clients:
            return
        if all(self.client_online.get(c, False) for c in self.round_clients):
            self.is_initialized = True
            self._send_init()

    def _stamp(self, m: Message) -> Message:
        m.add(md.KEY_MODEL_PARAMS, self.params)
        m.add(md.KEY_ROUND, self.round_idx)
        m.add(md.KEY_GENERATION, self.generation)
        return m

    def _send_init(self) -> None:
        self.aggregator.reset(self.round_clients)
        self._broadcast_round()
        self._arm_timer()

    def _broadcast_round(self) -> None:
        """Caller holds the lock (or is pre-run single-threaded). Sends the
        current round's payload to every selected client and records them
        as synced (rejoin re-sends go through _maybe_send_round)."""
        self._synced = set()
        mtype = md.S2C_INIT_CONFIG if self.round_idx == 0 \
            else md.S2C_SYNC_MODEL
        for cid in self.round_clients:
            self.comm.send_message(self._stamp(Message(mtype, 0, cid)))
            self._synced.add(cid)

    def _maybe_send_round(self, cid: int) -> None:
        """Caller holds the lock. Re-send the in-flight round's payload to a
        (re)joined client that is selected, missing, and not yet served —
        the rejoin half of crash recovery: a restarted client (or every
        client, after a server restart) pulls its work back instead of
        waiting out the round."""
        if self.done.is_set() or cid not in self.aggregator.expected \
                or cid in self.aggregator.results or cid in self._synced:
            return
        mtype = md.S2C_INIT_CONFIG if self.round_idx == 0 \
            else md.S2C_SYNC_MODEL
        self.comm.send_message(self._stamp(Message(mtype, 0, cid)))
        self._synced.add(cid)
        _mx.inc("fed.server.rejoin_syncs")

    # ------------------------------------------------------ dropout handling
    def _arm_timer(self) -> None:
        if self.round_timeout is None:
            return
        self._cancel_timer()
        # bind the timer to the round it guards: a timer that fires while its
        # round completes would otherwise run against the NEXT round's state
        # (cancel() is a no-op on an already-fired Timer)
        t = threading.Timer(
            self.round_timeout, self._on_round_timeout, args=(self.round_idx,))
        t.daemon = True
        t.start()
        self._timer = t

    def _cancel_timer(self) -> None:
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None

    def _quorum(self) -> int:
        n = len(self.aggregator.expected)
        return max(1, math.ceil(self.quorum_frac * n))

    def _on_round_timeout(self, armed_round: int) -> None:
        with self._lock:
            if self.done.is_set() or armed_round != self.round_idx:
                return  # stale timer from an already-completed round
            received = len(self.aggregator.results)
            if received >= self._quorum():
                dropped = sorted(self.aggregator.expected
                                 - set(self.aggregator.results))
                if dropped:
                    log.warning("round %d: aggregating %d/%d, dropped %s",
                                self.round_idx, received,
                                len(self.aggregator.expected), dropped)
                    self.dropped_log.append((self.round_idx, dropped))
                self._complete_round()
            else:
                # below quorum: re-arm, but BOUNDED (the reference waits
                # forever; an unreachable quorum must fail the run loudly,
                # not hang it silently — same contract as secagg_manager)
                self._rearm_count += 1
                if self._rearm_count > self.max_rearms:
                    _mx.inc("fed.server.quorum_unreachable")
                    self._fail(
                        f"round {self.round_idx}: {received} received < "
                        f"quorum {self._quorum()} after {self.max_rearms} "
                        f"timeouts of {self.round_timeout}s — quorum "
                        "unreachable")
                    return
                log.warning("round %d: %d received < quorum %d — re-arming "
                            "(%d/%d)", self.round_idx, received,
                            self._quorum(), self._rearm_count,
                            self.max_rearms)
                self._arm_timer()

    def _fail(self, reason: str) -> None:
        """Caller holds the lock. Record the error and release everyone —
        clients get a FINISH so they exit instead of waiting on a server
        that has declared the run dead."""
        log.error("cross-silo run failed: %s", reason)
        self.error = reason
        self._finish()

    def _on_model_from_client(self, msg: Message) -> None:
        with self._lock:
            # generation fence FIRST: a straggler from a previous server
            # incarnation may echo the CURRENT round index (a resumed server
            # re-runs the round that was in flight when it died) — the round
            # echo alone cannot tell it apart
            gen = msg.get(md.KEY_GENERATION)
            if int(gen or 0) != self.generation:
                _mx.inc("fed.server.stale_gen_rejected")
                log.warning(
                    "dropping C2S_SEND_MODEL from %s: generation %s != "
                    "current %d (pre-restart straggler)", msg.sender_id,
                    gen, self.generation)
                return
            # a straggler's model from a closed round must not leak into the
            # current one — clients echo the round index they trained on;
            # a missing echo is rejected rather than assumed current (a
            # defaulted value would bypass exactly this guard)
            msg_round = msg.get(md.KEY_ROUND)
            if msg_round is None:
                log.warning("dropping C2S_SEND_MODEL from %s without %s",
                            msg.sender_id, md.KEY_ROUND)
                return
            self._mark_alive(msg.sender_id)
            if int(msg_round) != self.round_idx or \
                    msg.sender_id not in self.aggregator.expected:
                return
            self.aggregator.add_local_trained_result(
                msg.sender_id, msg.get(md.KEY_MODEL_PARAMS),
                float(msg.get(md.KEY_NUM_SAMPLES, 1.0)),
            )
            if not self.aggregator.check_whether_all_receive():
                return
            self._complete_round()

    def _complete_round(self) -> None:
        """Aggregate what's in the pool and advance. Caller holds the lock."""
        self._cancel_timer()
        self._rearm_count = 0
        self.params = self.aggregator.aggregate()
        if self.postprocess_agg_fn is not None:
            self.params = self.postprocess_agg_fn(self.params, self.round_idx)
        # publish the round's aggregated model through the mlops artifact
        # path (reference: fedml_aggregator calls mlops.log_aggregated_
        # model_info every round, core/mlops/__init__.py:388); no-op unless
        # an artifact store is configured
        from .. import mlops

        mlops.log_aggregated_model_info(self.round_idx, self.params)
        row = {"round": self.round_idx,
               "n_received": len(self.aggregator.results)}
        if self.eval_fn is not None:
            row.update(self.eval_fn(self.params, self.round_idx))
        self.history.append(row)
        recorder.log(row)
        _mx.set_gauge("fed.round", self.round_idx)
        if self._ckpt_due(self.round_idx):
            self._save_checkpoint(self.round_idx)
        self.round_idx += 1
        if self.round_idx >= self.num_rounds:
            self._finish()
            return
        self.round_clients = self._select_clients(self.round_idx)
        self.aggregator.reset(self.round_clients)
        self._broadcast_round()
        self._arm_timer()

    # ---------------------------------------------------- checkpoint/restore
    def _ckpt_due(self, r: int) -> bool:
        return self.checkpoint_dir is not None and self.checkpoint_every and (
            (r + 1) % self.checkpoint_every == 0 or r == self.num_rounds - 1)

    def _save_checkpoint(self, r: int) -> None:
        """Caller holds the lock. Round-boundary write: params + the
        JSON-able FSM state (meta["extra"]). Degrade, don't die — a full
        disk must not kill a healthy federation."""
        from ..utils import checkpoint as ckpt

        extra = {
            "kind": "cross_silo_server",
            "generation": self.generation,
            "sample_seed": self.sample_seed,
            "num_rounds": self.num_rounds,
            "client_ids": list(self.client_ids),
            "client_online": {str(c): bool(v)
                              for c, v in self.client_online.items()},
            "dropped_log": [[rr, list(ids)] for rr, ids in self.dropped_log],
        }
        try:
            with recorder.span("silo.checkpoint", round=r):
                ckpt.save_checkpoint(
                    self.checkpoint_dir, r, {"params": self.params},
                    history=self.history, keep=self.checkpoint_keep,
                    extra=extra)
            _mx.inc("fed.server.checkpoints")
        except Exception as e:  # noqa: BLE001 — durability must not kill runs
            _mx.inc("fed.server.checkpoint_errors")
            log.warning("round-%d checkpoint to %r failed (continuing): "
                        "%s: %s", r, self.checkpoint_dir,
                        type(e).__name__, e)

    def _restore(self, path: str) -> None:
        """Load the latest checkpoint and resume at round N+1 with the
        generation bumped. Liveness is NOT trusted across a restart — the
        table keeps only its keys' identities via the re-run status
        handshake (every client re-registers before it gets work)."""
        from ..utils import checkpoint as ckpt

        # pin ONE round for both the meta read and the tensor restore: a
        # dying incarnation's in-flight checkpoint write landing between
        # the two would otherwise pair round N's liveness/generation state
        # with round N+1's params
        r = ckpt.latest_round(path)
        meta = ckpt.read_meta(path, r)
        extra = meta.get("extra") or {}
        try:
            _r, server, _c, _h, hist = ckpt.restore_checkpoint(
                path, {"params": self.params}, round_idx=r)
            params = server["params"]
        except ckpt.CheckpointStructureError:
            # cross-runtime compatibility: a Simulator-written checkpoint
            # stores the full ServerState (params/opt_state/round/extra);
            # the server path needs only its params subtree
            raw = ckpt.restore_raw(path, round_idx=r)
            if not (isinstance(raw, dict) and "params" in raw):
                raise ckpt.CheckpointStructureError(
                    f"checkpoint under {path!r} has no 'params' subtree "
                    f"(top-level keys: {sorted(raw) if isinstance(raw, dict) else type(raw).__name__}) "
                    "— not restorable into the cross-silo server")
            try:
                params = jax.tree.map(lambda _t, rr: rr, self.params,
                                      raw["params"])
            except (ValueError, TypeError) as e:
                raise ckpt.CheckpointStructureError(
                    f"checkpoint 'params' under {path!r} does not match "
                    f"this server's model: {type(e).__name__}: "
                    f"{str(e)[:200]}") from e
            hist = meta.get("history", [])
        self.params = jax.tree.map(np.asarray, params)
        self.history = list(hist)
        self.round_idx = int(meta["round"]) + 1
        self.generation = int(extra.get("generation", 0)) + 1
        if "sample_seed" in extra:
            self.sample_seed = int(extra["sample_seed"])
        self.dropped_log = [(int(rr), list(ids))
                            for rr, ids in extra.get("dropped_log", [])]
        # keys only: every client must re-register through the handshake
        self.client_online = {}
        self.last_seen = {}
        self.is_initialized = True
        self._resumed = True
        if self.round_idx < self.num_rounds:
            self.round_clients = self._select_clients(self.round_idx)
        else:
            self.round_clients = []
        self.aggregator.reset(self.round_clients)
        self._synced = set()
        _mx.inc("fed.server.resumes")
        _mx.set_gauge("fed.server.generation", self.generation)
        _mx.set_gauge("fed.round", self.round_idx)
        log.info("resumed from %r: %d rounds done, continuing at round %d "
                 "as generation %d", path, len(self.history), self.round_idx,
                 self.generation)

    # ------------------------------------------------------------- shutdown
    def _finish(self) -> None:
        self._cancel_timer()
        if self._liveness_timer is not None:
            self._liveness_timer.cancel()
        for cid in self.client_ids:
            try:
                self.comm.send_message(
                    Message(md.S2C_FINISH, 0, cid)
                    .add(md.KEY_GENERATION, self.generation))
            except Exception:  # noqa: BLE001 — dead clients may be
                log.debug("S2C_FINISH to %s failed", cid, exc_info=True)
        self.done.set()
        # callers hold self._lock; comm.stop() joins the receive thread, which
        # may itself be blocked on the lock in a handler — stop from a fresh
        # thread so the join can't deadlock/stall against our lock
        threading.Thread(target=self.comm.stop, daemon=True).start()

    def run(self, background: bool = False) -> None:
        self._liveness_ref = time.monotonic()
        self._arm_liveness()
        if self._resumed and not self.done.is_set():
            if self.round_idx >= self.num_rounds:
                # checkpoint already covers the whole run: release clients
                with self._lock:
                    self._finish()
            else:
                # the resumed server INITIATES the re-handshake: clients
                # that survived the crash are idle in their receive loops
                # and (absent an optional watchdog) would never announce
                # on their own — recovery must not depend on client-side
                # knobs being set
                for cid in self.client_ids:
                    self.comm.send_message(
                        Message(md.S2C_CHECK_CLIENT_STATUS, 0, cid))
                if self.round_timeout is not None:
                    # guard the reconnect window the same way a live round
                    # is guarded: quorum math + bounded re-arms
                    self._arm_timer()
        self.comm.run(background=background)
        if not background and self.error:
            raise RuntimeError(self.error)
