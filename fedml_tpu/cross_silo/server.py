"""Cross-silo FL server — the message-driven FSM.

(reference: cross_silo/server/fedml_server_manager.py:82-246 — handlers for
connection_ready / client_status / model_from_client; round flow: check
status → all online → send_init_msg → collect models → aggregate → sync;
aggregation bookkeeping in server/fedml_aggregator.py:13-104
add_local_trained_result/check_whether_all_receive/aggregate.)

Aggregation runs on device: stacked numpy updates → tree_weighted_mean (or
the security pipeline's robust aggregate) in one jit call.
"""
from __future__ import annotations

import logging
import threading
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..comm import FedCommManager, Message
from ..ops import tree as tu
from ..utils.events import recorder
from . import message_define as md

Pytree = Any
log = logging.getLogger(__name__)


class FedAggregator:
    """Result pool + merge (reference: server/fedml_aggregator.py:13-104)."""

    def __init__(self, aggregate_fn: Optional[Callable] = None):
        self.results: dict[int, tuple[Pytree, float]] = {}
        self.expected: set[int] = set()
        self.aggregate_fn = aggregate_fn

    def reset(self, client_ids) -> None:
        self.results.clear()
        self.expected = set(client_ids)

    def add_local_trained_result(self, client_id: int, params: Pytree,
                                 n_samples: float) -> None:
        self.results[client_id] = (params, n_samples)

    def check_whether_all_receive(self) -> bool:
        return self.expected.issubset(self.results)

    def aggregate(self) -> Pytree:
        with recorder.span("agg"):
            ids = sorted(self.results)
            stacked = tu.tree_stack([jax.tree.map(jnp.asarray, self.results[i][0])
                                     for i in ids])
            weights = jnp.asarray([self.results[i][1] for i in ids], jnp.float32)
            if self.aggregate_fn is not None:
                agg = self.aggregate_fn(stacked, weights)
            else:
                agg = tu.tree_weighted_mean(stacked, weights)
            return jax.tree.map(np.asarray, jax.device_get(agg))


class FedServerManager:
    """(reference: FedMLServerManager, fedml_server_manager.py:22-246)"""

    def __init__(self, comm: FedCommManager, client_ids: list[int],
                 init_params: Pytree, num_rounds: int,
                 aggregate_fn: Optional[Callable] = None,
                 eval_fn: Optional[Callable[[Pytree, int], dict]] = None,
                 client_num_per_round: Optional[int] = None,
                 sample_seed: int = 0):
        self.comm = comm
        self.client_ids = list(client_ids)
        self.m = client_num_per_round or len(self.client_ids)
        self.params = init_params
        self.num_rounds = num_rounds
        self.round_idx = 0
        self.aggregator = FedAggregator(aggregate_fn)
        self.eval_fn = eval_fn
        self.sample_seed = sample_seed
        self.client_online: dict[int, bool] = {}
        self.is_initialized = False
        self.done = threading.Event()
        self.history: list[dict] = []
        self._lock = threading.Lock()

        comm.register_message_receive_handler(
            md.CONNECTION_IS_READY, self._on_connection_ready)
        comm.register_message_receive_handler(
            md.C2S_CLIENT_STATUS, self._on_client_status)
        comm.register_message_receive_handler(
            md.C2S_SEND_MODEL, self._on_model_from_client)

    # --- selection (reference: fedml_aggregator.client_selection — seeded by
    # round, matching fedavg_api.py:127-135)
    def _select_clients(self, round_idx: int) -> list[int]:
        if self.m >= len(self.client_ids):
            return list(self.client_ids)
        rng = np.random.RandomState(self.sample_seed + round_idx)
        return sorted(rng.choice(self.client_ids, self.m, replace=False).tolist())

    # ------------------------------------------------------------- handlers
    def _on_connection_ready(self, msg: Message) -> None:
        if self.is_initialized:
            return
        self.round_clients = self._select_clients(0)
        for cid in self.round_clients:
            self.comm.send_message(
                Message(md.S2C_CHECK_CLIENT_STATUS, 0, cid))

    def _on_client_status(self, msg: Message) -> None:
        status = msg.get(md.KEY_STATUS)
        if status == md.STATUS_FINISHED:
            return
        with self._lock:
            self.client_online[msg.sender_id] = True
            all_online = all(self.client_online.get(c, False)
                             for c in self.round_clients)
            if all_online and not self.is_initialized:
                self.is_initialized = True
                self._send_init()

    def _send_init(self) -> None:
        self.aggregator.reset(self.round_clients)
        for cid in self.round_clients:
            m = Message(md.S2C_INIT_CONFIG, 0, cid)
            m.add(md.KEY_MODEL_PARAMS, self.params)
            m.add(md.KEY_ROUND, self.round_idx)
            self.comm.send_message(m)

    def _on_model_from_client(self, msg: Message) -> None:
        with self._lock:
            self.aggregator.add_local_trained_result(
                msg.sender_id, msg.get(md.KEY_MODEL_PARAMS),
                float(msg.get(md.KEY_NUM_SAMPLES, 1.0)),
            )
            if not self.aggregator.check_whether_all_receive():
                return
            self.params = self.aggregator.aggregate()
            row = {"round": self.round_idx}
            if self.eval_fn is not None:
                row.update(self.eval_fn(self.params, self.round_idx))
            self.history.append(row)
            recorder.log(row)
            self.round_idx += 1
            if self.round_idx >= self.num_rounds:
                self._finish()
                return
            self.round_clients = self._select_clients(self.round_idx)
            self.aggregator.reset(self.round_clients)
            for cid in self.round_clients:
                m = Message(md.S2C_SYNC_MODEL, 0, cid)
                m.add(md.KEY_MODEL_PARAMS, self.params)
                m.add(md.KEY_ROUND, self.round_idx)
                self.comm.send_message(m)

    def _finish(self) -> None:
        for cid in self.client_ids:
            self.comm.send_message(Message(md.S2C_FINISH, 0, cid))
        self.done.set()
        self.comm.stop()

    def run(self, background: bool = False) -> None:
        self.comm.run(background=background)
