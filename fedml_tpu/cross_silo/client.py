"""Cross-silo FL client — master manager FSM.

(reference: cross_silo/client/fedml_client_master_manager.py:22-230 — handlers
for check_client_status / init / sync_model / finish; __train :227 calls the
TrainerDistAdapter; hierarchical slaves follow via dist.broadcast_object_list
:195-207. Here the silo's accelerators are one jax Mesh inside SiloTrainer, so
there is no slave manager at all.)

Durability (ISSUE 10): the reference client blocks in its receive loop
forever when the server dies. Here:

- `server_timeout_s` arms a silence watchdog: when nothing has arrived from
  the server for that long after the client's last interaction, the client
  either RE-ATTACHES (`reattach=True` — re-announces CONNECTION_IS_READY so
  a resumed server re-runs the handshake and re-sends the in-flight round)
  or EXITS with `self.error` set (a foreground `run()` raises, so the
  process exits nonzero instead of hanging).
- `heartbeat_s` sends lightweight C2S_HEARTBEAT beacons so the server's
  liveness sweep can tell a live-but-unselected client from a dead one.
- every trained upload echoes the server's run-generation header
  (KEY_GENERATION, learned from init/sync) so a restarted server can fence
  out pre-restart stragglers.
"""
from __future__ import annotations

import logging
import threading
import time

from ..comm import FedCommManager, Message
from ..utils import metrics as _mx
from ..utils.events import recorder
from . import message_define as md
from .trainer import SiloTrainer

log = logging.getLogger(__name__)


class FedClientManager:
    def __init__(self, comm: FedCommManager, client_id: int,
                 trainer: SiloTrainer, server_id: int = 0,
                 server_timeout_s: float = None,
                 reattach: bool = False,
                 heartbeat_s: float = None,
                 max_reattach: int = 10,
                 dp_upload=None):
        self.comm = comm
        self.client_id = client_id
        self.server_id = server_id
        self.trainer = trainer
        # client-side DP (dp.SiloUploadDP): clip+noise the local update
        # BEFORE the send — the wire codec then compresses the NOISED
        # payload (noise-then-compress; post-processing keeps the epsilon
        # accounting unchanged — see dp/__init__.py SiloUploadDP)
        self.dp_upload = dp_upload
        self.server_timeout_s = server_timeout_s
        self.reattach = reattach
        self.heartbeat_s = heartbeat_s
        self.max_reattach = int(max_reattach)
        self.run_gen = 0          # server incarnation, learned from S2C
        self.error = None
        self.done = threading.Event()
        self._stopped = threading.Event()   # done OR killed — stops aux loops
        self._last_contact = time.monotonic()
        self._reattach_count = 0
        self._aux_started = False
        self._training = False   # watchdog must not count local work

        comm.register_message_receive_handler(
            md.S2C_CHECK_CLIENT_STATUS, self._on_check_status)
        comm.register_message_receive_handler(md.S2C_INIT_CONFIG, self._on_init)
        comm.register_message_receive_handler(md.S2C_SYNC_MODEL, self._on_sync)
        comm.register_message_receive_handler(md.S2C_FINISH, self._on_finish)

    def _touch(self) -> None:
        """Reset the server-silence clock (any S2C arrival, or our own
        upload — the deadline measures silence while WAITING, not while the
        local trainer is busy)."""
        self._last_contact = time.monotonic()

    def _server_contact(self) -> None:
        """An actual S2C arrival: beyond the clock, it REFUNDS the
        re-attach budget — the budget bounds announcing into a void, and a
        server that answers is not a void. Without the refund a long run's
        sporadic slow rounds accumulate attempts until the watchdog
        declares a perfectly live server dead."""
        self._touch()
        self._reattach_count = 0

    def _on_check_status(self, msg: Message) -> None:
        self._server_contact()
        m = Message(md.C2S_CLIENT_STATUS, self.client_id, self.server_id)
        m.add(md.KEY_STATUS, md.STATUS_ONLINE)
        self.comm.send_message(m)

    def _train_and_send(self, params, round_idx: int, gen: int = 0) -> None:
        # the silence watchdog pauses while the local trainer runs: a round
        # whose training outlasts server_timeout_s is OUR work, not server
        # silence (the clock restarts at the post-send _touch below)
        self._training = True
        try:
            with recorder.span("train", round=round_idx,
                               client=self.client_id):
                new_params, n, metrics = self.trainer.train(params, round_idx)
        finally:
            self._training = False
        if self.dp_upload is not None:
            # DP noise FIRST, wire compression second (the transport codec
            # runs at send time, downstream of here) — the ordering the
            # accountant's post-processing argument depends on
            new_params = self.dp_upload.apply(new_params, params, round_idx)
        # client-model publish on cadence (reference: core/mlops/__init__.py
        # :475 log_client_model_info); no-op without an artifact store
        from .. import mlops

        mlops.log_client_model_info(round_idx, self.client_id, new_params)
        out = Message(md.C2S_SEND_MODEL, self.client_id, self.server_id)
        out.add(md.KEY_MODEL_PARAMS, new_params)
        out.add(md.KEY_NUM_SAMPLES, n)
        out.add(md.KEY_METRICS, metrics)
        # echo the round so a straggler's result can't leak into a later
        # round after a timeout-closed aggregation (server checks KEY_ROUND)
        out.add(md.KEY_ROUND, round_idx)
        # echo the incarnation that ISSUED this work (not the latest one we
        # know of): a stale pre-restart sync processed after a fresh one
        # must still be identifiable as stale at the server (ISSUE 10)
        out.add(md.KEY_GENERATION, gen)
        self.comm.send_message(out)
        self._touch()

    def _on_init(self, msg: Message) -> None:
        self._server_contact()
        gen = int(msg.get(md.KEY_GENERATION, 0) or 0)
        # run_gen tracks the HIGHEST incarnation seen (fences stale FINISH);
        # the per-message gen rides through to the upload echo
        self.run_gen = max(self.run_gen, gen)
        self._train_and_send(msg.get(md.KEY_MODEL_PARAMS),
                             int(msg.get(md.KEY_ROUND, 0)), gen=gen)

    _on_sync = _on_init

    def _on_finish(self, msg: Message) -> None:
        # a STALE finish (older generation than the one we are training
        # under) is a dead server's farewell delivered late — a live
        # resumed server still owns this client; ignore it
        gen = msg.get(md.KEY_GENERATION)
        if gen is not None and int(gen) < self.run_gen:
            log.warning("client %d: ignoring S2C_FINISH from stale "
                        "generation %s (current %d)", self.client_id,
                        gen, self.run_gen)
            return
        m = Message(md.C2S_FINISHED, self.client_id, self.server_id)
        m.add(md.KEY_STATUS, md.STATUS_FINISHED)
        try:
            self.comm.send_message(m)
        except Exception:  # server may already be gone
            pass
        self.done.set()
        self._stopped.set()
        self.comm.stop()

    # ------------------------------------------------------------ durability
    def _heartbeat_loop(self) -> None:
        while not self._stopped.wait(self.heartbeat_s):
            try:
                self.comm.send_message(
                    Message(md.C2S_HEARTBEAT, self.client_id, self.server_id)
                    .add(md.KEY_GENERATION, self.run_gen))
            except Exception as e:  # noqa: BLE001 — beacon, not critical
                log.debug("heartbeat send failed: %s: %s",
                          type(e).__name__, e)

    def _watchdog_loop(self) -> None:
        assert self.server_timeout_s is not None
        tick = max(self.server_timeout_s / 4.0, 0.05)
        while not self._stopped.wait(tick):
            if self._training:
                self._touch()   # local work is not server silence
                continue
            silent = time.monotonic() - self._last_contact
            if silent <= self.server_timeout_s:
                continue
            if self.reattach and self._reattach_count < self.max_reattach:
                self._reattach_count += 1
                _mx.inc("fed.client.reattaches")
                log.warning(
                    "client %d: server silent %.1fs (> server_timeout_s="
                    "%.1fs) — re-announcing (%d/%d)", self.client_id,
                    silent, self.server_timeout_s, self._reattach_count,
                    self.max_reattach)
                self._touch()    # a fresh deadline per attempt
                try:
                    self.announce_ready()
                except Exception as e:  # noqa: BLE001 — retried next lap
                    log.debug("re-announce failed: %s: %s",
                              type(e).__name__, e)
                continue
            _mx.inc("fed.client.server_silence_exits")
            self.error = (
                f"server silent for {silent:.1f}s (> server_timeout_s="
                f"{self.server_timeout_s}s)"
                + (f" after {self._reattach_count} re-attach attempts"
                   if self.reattach else "")
                + " — giving up instead of blocking in the receive loop "
                "forever")
            log.error("client %d: %s", self.client_id, self.error)
            self.done.set()
            self._stopped.set()
            self.comm.stop()
            return

    def _start_aux(self) -> None:
        if self._aux_started:
            return
        self._aux_started = True
        if self.heartbeat_s is not None:
            threading.Thread(target=self._heartbeat_loop,
                             name=f"hb-c{self.client_id}",
                             daemon=True).start()
        if self.server_timeout_s is not None:
            self._touch()
            threading.Thread(target=self._watchdog_loop,
                             name=f"watchdog-c{self.client_id}",
                             daemon=True).start()

    def run(self, background: bool = False) -> None:
        self._start_aux()
        self.comm.run(background=background)
        if not background and self.error:
            # foreground runs surface the failure as a nonzero exit instead
            # of a silent return (the CLI/driver contract)
            raise RuntimeError(self.error)

    def announce_ready(self) -> None:
        """Kick the FSM (the transport's CONNECTION_IS_READY event — reference
        transports synthesize it on connect; loopback/grpc need an explicit
        poke to the server)."""
        self.comm.send_message(
            Message(md.CONNECTION_IS_READY, self.client_id, self.server_id))
