"""Cross-silo FL client — master manager FSM.

(reference: cross_silo/client/fedml_client_master_manager.py:22-230 — handlers
for check_client_status / init / sync_model / finish; __train :227 calls the
TrainerDistAdapter; hierarchical slaves follow via dist.broadcast_object_list
:195-207. Here the silo's accelerators are one jax Mesh inside SiloTrainer, so
there is no slave manager at all.)
"""
from __future__ import annotations

import logging
import threading

from ..comm import FedCommManager, Message
from ..utils.events import recorder
from . import message_define as md
from .trainer import SiloTrainer

log = logging.getLogger(__name__)


class FedClientManager:
    def __init__(self, comm: FedCommManager, client_id: int,
                 trainer: SiloTrainer, server_id: int = 0):
        self.comm = comm
        self.client_id = client_id
        self.server_id = server_id
        self.trainer = trainer
        self.done = threading.Event()

        comm.register_message_receive_handler(
            md.S2C_CHECK_CLIENT_STATUS, self._on_check_status)
        comm.register_message_receive_handler(md.S2C_INIT_CONFIG, self._on_init)
        comm.register_message_receive_handler(md.S2C_SYNC_MODEL, self._on_sync)
        comm.register_message_receive_handler(md.S2C_FINISH, self._on_finish)

    def _on_check_status(self, msg: Message) -> None:
        m = Message(md.C2S_CLIENT_STATUS, self.client_id, self.server_id)
        m.add(md.KEY_STATUS, md.STATUS_ONLINE)
        self.comm.send_message(m)

    def _train_and_send(self, params, round_idx: int) -> None:
        with recorder.span("train", round=round_idx, client=self.client_id):
            new_params, n, metrics = self.trainer.train(params, round_idx)
        # client-model publish on cadence (reference: core/mlops/__init__.py
        # :475 log_client_model_info); no-op without an artifact store
        from .. import mlops

        mlops.log_client_model_info(round_idx, self.client_id, new_params)
        out = Message(md.C2S_SEND_MODEL, self.client_id, self.server_id)
        out.add(md.KEY_MODEL_PARAMS, new_params)
        out.add(md.KEY_NUM_SAMPLES, n)
        out.add(md.KEY_METRICS, metrics)
        # echo the round so a straggler's result can't leak into a later
        # round after a timeout-closed aggregation (server checks KEY_ROUND)
        out.add(md.KEY_ROUND, round_idx)
        self.comm.send_message(out)

    def _on_init(self, msg: Message) -> None:
        self._train_and_send(msg.get(md.KEY_MODEL_PARAMS),
                             int(msg.get(md.KEY_ROUND, 0)))

    _on_sync = _on_init

    def _on_finish(self, msg: Message) -> None:
        m = Message(md.C2S_FINISHED, self.client_id, self.server_id)
        m.add(md.KEY_STATUS, md.STATUS_FINISHED)
        try:
            self.comm.send_message(m)
        except Exception:  # server may already be gone
            pass
        self.done.set()
        self.comm.stop()

    def run(self, background: bool = False) -> None:
        self.comm.run(background=background)

    def announce_ready(self) -> None:
        """Kick the FSM (the transport's CONNECTION_IS_READY event — reference
        transports synthesize it on connect; loopback/grpc need an explicit
        poke to the server)."""
        self.comm.send_message(
            Message(md.CONNECTION_IS_READY, self.client_id, self.server_id))
