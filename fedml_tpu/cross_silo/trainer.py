"""Silo-side trainer — the ClientTrainer/TrainerDistAdapter analog.

(reference: cross_silo/client/fedml_trainer.py:66-77 FedMLTrainer.train runs
the torch ClientTrainer; fedml_trainer_dist_adapter.py:9 wraps it with DDP for
hierarchical silos, process_group_manager.py:8 builds the NCCL/Gloo group.)

TPU design: a silo is a host + its TPU slice. "DDP inside the silo" becomes
data parallelism over a local `jax.sharding.Mesh` — the batch is sharded over
the mesh's `data` axis inside one jitted train step; XLA inserts the gradient
all-reduce (the NCCL allreduce equivalent) automatically. No process groups,
no torchrun env: the mesh IS the process group.
"""
from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..core.algorithm import (
    local_sgd, make_batch_indices, make_client_optimizer, make_objective,
)
from ..config import TrainArgs

Pytree = Any


class SiloTrainer:
    """Local trainer over host-resident numpy shards; the hot loop is the
    same jitted lax.scan local_sgd the simulator uses."""

    def __init__(self, apply_fn, t: TrainArgs, x: np.ndarray, y: np.ndarray,
                 mesh: Optional[Mesh] = None, data_axis: str = "data",
                 seed: int = 0):
        self.apply_fn = apply_fn
        self.t = t
        self.mesh = mesh
        self.data_axis = data_axis
        if mesh is not None:
            # intra-silo data parallelism: pad the shard to the axis size
            d = int(np.prod([mesh.shape[a] for a in (data_axis,)]))
            pad = (-x.shape[0]) % d
            if pad:
                x = np.concatenate([x, np.zeros((pad,) + x.shape[1:], x.dtype)])
                y = np.concatenate([y, np.zeros((pad,), y.dtype)])
                self._mask = np.concatenate(
                    [np.ones(x.shape[0] - pad, np.float32), np.zeros(pad, np.float32)]
                )
            else:
                self._mask = np.ones(x.shape[0], np.float32)
            sh = NamedSharding(mesh, P(data_axis))
            self.x = jax.device_put(jnp.asarray(x), sh)
            self.y = jax.device_put(jnp.asarray(y), sh)
            self.mask = jax.device_put(jnp.asarray(self._mask), sh)
        else:
            self.x, self.y = jnp.asarray(x), jnp.asarray(y)
            self.mask = jnp.ones(x.shape[0], jnp.float32)
        self.n_samples = int(np.sum(np.asarray(self.mask)))
        self.opt = make_client_optimizer(
            t.client_optimizer, t.learning_rate, t.momentum, t.weight_decay
        )
        self.objective = make_objective(t.extra.get("task"))
        self.seed = seed
        self._jit_train = jax.jit(self._train_impl)
        # rejoin memo (ISSUE 10): a re-attach or server resume re-sends the
        # in-flight round, and the round's inputs are deterministic — same
        # round index + same incoming params ⇒ same local result. Caching
        # the last round turns the re-train into an equality check.
        self._memo: Optional[tuple] = None   # (round_idx, params_np, result)

    def _train_impl(self, params, rng):
        shard = {"x": self.x, "y": self.y, "mask": self.mask}
        idx = make_batch_indices(rng, self.x.shape[0], self.t.batch_size,
                                 self.t.epochs)
        new_params, metrics, _steps = local_sgd(
            self.apply_fn, params, shard, idx, self.opt,
            objective=self.objective,
        )
        return new_params, metrics

    def train(self, params_np: Pytree, round_idx: int):
        """(params numpy pytree) -> (new params numpy pytree, n, metrics) —
        the ClientTrainer.train contract (reference: client_trainer.py:52).
        A repeat of the memoized round with bit-identical incoming params
        (a durability re-send) returns the cached result."""
        if self._memo is not None and self._memo[0] == round_idx:
            try:
                same = all(jax.tree.leaves(jax.tree.map(
                    lambda a, b: bool(np.array_equal(np.asarray(a),
                                                     np.asarray(b))),
                    self._memo[1], params_np)))
            except (ValueError, TypeError):
                same = False
            if same:
                return self._memo[2]
        params = jax.tree.map(jnp.asarray, params_np)
        rng = jax.random.fold_in(jax.random.key(self.seed), round_idx)
        new_params, m = self._jit_train(params, rng)
        out = jax.tree.map(np.asarray, jax.device_get(new_params))
        cnt = float(m.count)
        metrics = {
            "train_loss": float(m.loss_sum) / max(cnt, 1.0),
            "train_acc": float(m.correct) / max(cnt, 1.0),
        }
        result = (out, self.n_samples, metrics)
        self._memo = (round_idx, params_np, result)
        return result
