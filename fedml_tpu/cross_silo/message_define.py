"""Cross-silo message protocol constants (reference:
cross_silo/server/message_define.py + client/message_define.py — the numeric
MSG_TYPE_* FSM alphabet; strings here for self-describing wire frames)."""

# connection info (reference: MSG_TYPE_CONNECTION_IS_READY = 0)
CONNECTION_IS_READY = "connection_ready"

# server -> client (reference: 1, 2, 6, 7)
S2C_INIT_CONFIG = "s2c_init_config"
S2C_SYNC_MODEL = "s2c_sync_model"
S2C_CHECK_CLIENT_STATUS = "s2c_check_client_status"
S2C_FINISH = "s2c_finish"

# client -> server (reference: 3, 4, 5, 8)
C2S_SEND_MODEL = "c2s_send_model"
C2S_CLIENT_STATUS = "c2s_client_status"
C2S_FINISHED = "c2s_finished"
# liveness beacon (ISSUE 10 — no reference analog: the reference server
# waits forever on dead clients). Lightweight, no payload beyond the
# generation echo; the server flips client_online off after a miss budget.
C2S_HEARTBEAT = "c2s_heartbeat"

# payload keys (reference: MSG_ARG_KEY_*)
KEY_MODEL_PARAMS = "model_params"
KEY_NUM_SAMPLES = "num_samples"
KEY_CLIENT_INDEX = "client_idx"
KEY_ROUND = "round_idx"
KEY_STATUS = "client_status"
KEY_METRICS = "metrics"
# run-generation (incarnation) fence (ISSUE 10): stamped on every S2C
# training message by the server and echoed on every C2S training message.
# A resumed server re-runs the round that was in flight when it died, so a
# pre-restart straggler's round-ECHO can equal the live round index — the
# transport's `_rel_epoch` fences *delivery*, not training semantics; this
# key fences the training FSM itself.
KEY_GENERATION = "run_gen"

STATUS_ONLINE = "ONLINE"
STATUS_FINISHED = "FINISHED"

# --- SecAgg extension (reference: cross_silo/secagg/sa_message_define.py —
# pk exchange 3/4, secret-share routing 5/6/11, active-client list 10)
C2S_SA_PK = "c2s_sa_pk"                    # MSG_TYPE_C2S_SEND_PK_TO_SERVER
S2C_SA_PKS = "s2c_sa_pks"                  # MSG_TYPE_S2C_OTHER_PK_TO_CLIENT
C2S_SA_SHARES = "c2s_sa_shares"            # MSG_TYPE_C2S_SEND_SS_TO_SERVER
S2C_SA_SHARES = "s2c_sa_shares"            # MSG_TYPE_S2C_OTHER_SS_TO_CLIENT
C2S_SA_MASKED = "c2s_sa_masked"            # masked model upload
S2C_SA_UNMASK_REQ = "s2c_sa_unmask_req"    # MSG_TYPE_S2C_ACTIVE_CLIENT_LIST
C2S_SA_UNMASK = "c2s_sa_unmask"            # MSG_TYPE_C2S_SEND_SS_OTHERS...

KEY_SA_PK = "sa_pk"
KEY_SA_PKS = "sa_pks"
KEY_SA_SHARES = "sa_shares"
KEY_SA_MASKED = "sa_masked"
KEY_SA_SURVIVORS = "sa_survivors"
KEY_SA_DROPPED = "sa_dropped"
KEY_SA_B_SHARES = "sa_b_shares"
KEY_SA_SK_SHARES = "sa_sk_shares"
KEY_SA_THRESHOLD = "sa_threshold"
KEY_SA_QBITS = "sa_q_bits"
# N = sum(n_i): broadcast with the pk list so clients mask normalized
# weights n_i/N (field budget stays count-scale-free)
KEY_SA_WEIGHT_NORM = "sa_weight_norm"
