"""Cross-silo message protocol constants (reference:
cross_silo/server/message_define.py + client/message_define.py — the numeric
MSG_TYPE_* FSM alphabet; strings here for self-describing wire frames)."""

# connection info (reference: MSG_TYPE_CONNECTION_IS_READY = 0)
CONNECTION_IS_READY = "connection_ready"

# server -> client (reference: 1, 2, 6, 7)
S2C_INIT_CONFIG = "s2c_init_config"
S2C_SYNC_MODEL = "s2c_sync_model"
S2C_CHECK_CLIENT_STATUS = "s2c_check_client_status"
S2C_FINISH = "s2c_finish"

# client -> server (reference: 3, 4, 5, 8)
C2S_SEND_MODEL = "c2s_send_model"
C2S_CLIENT_STATUS = "c2s_client_status"
C2S_FINISHED = "c2s_finished"

# payload keys (reference: MSG_ARG_KEY_*)
KEY_MODEL_PARAMS = "model_params"
KEY_NUM_SAMPLES = "num_samples"
KEY_CLIENT_INDEX = "client_idx"
KEY_ROUND = "round_idx"
KEY_STATUS = "client_status"
KEY_METRICS = "metrics"

STATUS_ONLINE = "ONLINE"
STATUS_FINISHED = "FINISHED"
