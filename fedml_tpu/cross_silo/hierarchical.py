"""Hierarchical cross-silo: per-silo device sub-meshes + DCN message layer.

The reference composes the two levels with processes: `fedml.init` spawns one
process per intra-silo GPU rank (reference: python/fedml/__init__.py:342-390,
`_init_cross_silo_hi` reading n_proc_in_silo / proc_rank_in_silo), the rank-0
"master" client talks MQTT/gRPC to the server while slave ranks follow via
torch.distributed broadcast (cross_silo/client/fedml_client_master_manager.py:
195-207), and DDP does the intra-silo gradient allreduce
(fedml_trainer_dist_adapter.py:9, process_group_manager.py:8).

TPU design: a silo's accelerators are one `jax.sharding.Mesh` — there are no
slave processes to manage, no process groups to bootstrap. Each silo's
SiloTrainer shards its local batch over the silo mesh's `data` axis (XLA
inserts the allreduce on ICI), and only silo masters exist at the message
layer. The outer level is the ordinary cross-silo FSM (server.py/client.py)
over loopback (tests) or gRPC (real DCN).

`run_hierarchical` is the in-process composition used by tests and
single-host demos: it partitions the host's devices into disjoint silo
meshes — the analog of the reference's one-box multi-process
run_cross_silo.sh. For a real deployment, build one SiloTrainer per host with
`silo_mesh(...)` over that host's local devices and gRPC transports.
"""
from __future__ import annotations

import uuid
from typing import Any, Callable, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

from ..comm import FedCommManager
from ..comm.loopback import LoopbackTransport
from ..config import TrainArgs
from .client import FedClientManager
from .server import FedServerManager
from .trainer import SiloTrainer

Pytree = Any


def silo_mesh(devices: Sequence, data_axis: str = "data") -> Mesh:
    """A silo's intra mesh: 1-D data-parallel over the silo's devices (the
    process-group analog, reference: process_group_manager.py:8)."""
    return Mesh(np.array(list(devices)), (data_axis,))


def partition_devices(n_silos: int, devices=None) -> list[list]:
    """Split the host's devices into n_silos disjoint contiguous groups —
    the single-host stand-in for "each silo owns its own hosts". Uneven
    counts give the first silos one extra device (no device is dropped)."""
    devices = list(devices if devices is not None else jax.devices())
    if n_silos > len(devices):
        raise ValueError(
            f"{n_silos} silos need at least {n_silos} devices, have "
            f"{len(devices)}")
    per, extra = divmod(len(devices), n_silos)
    groups, start = [], 0
    for i in range(n_silos):
        size = per + (1 if i < extra else 0)
        groups.append(devices[start:start + size])
        start += size
    return groups


def run_hierarchical(
    apply_fn: Callable,
    init_params_np: Pytree,
    t: TrainArgs,
    silo_data: Sequence[tuple[np.ndarray, np.ndarray]],  # per-silo (x, y)
    num_rounds: int,
    eval_fn: Optional[Callable[[Pytree, int], dict]] = None,
    run_id: Optional[str] = None,
    round_timeout: Optional[float] = None,
    quorum_frac: float = 1.0,
    aggregate_fn: Optional[Callable] = None,
    devices=None,
) -> FedServerManager:
    """End-to-end hierarchical cross-silo on one host: N silos, each with an
    intra-silo data-parallel mesh over its device share, FedAvg across silos
    over the loopback message layer (BASELINE config 4's shape). Returns the
    finished server manager (history, params)."""
    # a fresh run_id per invocation: loopback mailboxes are process-global per
    # run_id, so reusing one would hand run 2 the previous run's stale frames
    if run_id is None:
        run_id = f"hier-{uuid.uuid4().hex[:8]}"
    n_silos = len(silo_data)
    groups = partition_devices(n_silos, devices)
    trainers = [
        SiloTrainer(apply_fn, t, x, y, mesh=silo_mesh(groups[i]), seed=i)
        for i, (x, y) in enumerate(silo_data)
    ]
    server = FedServerManager(
        FedCommManager(LoopbackTransport(0, run_id), 0),
        client_ids=list(range(1, n_silos + 1)),
        init_params=init_params_np,
        num_rounds=num_rounds,
        eval_fn=eval_fn,
        round_timeout=round_timeout,
        quorum_frac=quorum_frac,
        aggregate_fn=aggregate_fn,
    )
    clients = [
        FedClientManager(
            FedCommManager(LoopbackTransport(cid, run_id), cid),
            cid, trainers[cid - 1])
        for cid in range(1, n_silos + 1)
    ]
    server.run(background=True)
    for c in clients:
        c.run(background=True)
    for c in clients:
        c.announce_ready()
    try:
        if not server.done.wait(timeout=600):
            raise TimeoutError("hierarchical cross-silo run did not finish")
        for c in clients:
            c.done.wait(timeout=30)
    finally:
        # per-run uuids would otherwise leak one mailbox set per invocation
        from ..comm.loopback import release_router
        release_router(run_id)
    return server
