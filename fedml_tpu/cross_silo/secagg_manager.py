"""Cross-silo secure aggregation over the message layer.

The reference's 5-file secagg manager set (reference:
cross_silo/secagg/sa_fedml_server_manager.py, sa_fedml_client_manager.py,
sa_fedml_aggregator.py, sa_fedml_api.py, sa_message_define.py) drives
core/mpc/secagg.py through an FSM: pk exchange (msg 3/4) → secret-share
routing via the server (5/6) → masked model upload (7) → active-client list
(10) → survivors' shares of others (11) → unmask. This module is the same
protocol over fedml_tpu's comm layer, driving mpc/secagg.py:

  setup (once):  C2S_SA_PK (+ n_i) → S2C_SA_PKS (+ weight norm) →
                 C2S_SA_SHARES (encrypted-to-holder, routed & DISCARDED) →
                 S2C_SA_SHARES (+ init model, starts round 0)
  per round:     train → C2S_SA_MASKED (masked normalized-weighted params)
                 all received → S2C_SA_UNMASK_REQ(survivors) →
                 C2S_SA_UNMASK (b-shares of survivors) → unmask → next round
  dropout:       round_timeout fires → S2C_SA_UNMASK_REQ(survivors, dropped)
                 → C2S_SA_UNMASK (b-shares of survivors + sk-shares of
                 dropped) → reconstruct sk_j → strip pairwise masks → next
                 round (dropped clients are excluded from later rounds; the
                 pairwise masks they would have contributed are stripped
                 every round thereafter via the reconstructed seeds).

Server-side privacy: routed setup shares are ENCRYPTED to their holder
(mpc/secagg.py encrypt_share, pad derived from the owner-holder DH secret)
and the server deletes each ciphertext batch right after forwarding — it
never holds t+1 shares of anyone's b_i or sk_i, so it cannot reconstruct a
client's masks and unmask an individual update. The b-shares it needs to
strip self-masks are collected fresh from t+1 survivors every round
(Bonawitz et al.'s round-4 disclosure: b_i of survivors is by-design safe to
reconstruct because their pairwise masks remain).

Weighted mean under masking: clients mask quantize(params * n_i / N) where
N = sum(n_i) is broadcast with the pk list, and send n_i in the clear
(weights are public in the reference too); the server divides the unmasked
sum by sum(n_i)/N. Normalizing by N keeps the field budget independent of
absolute sample counts (raw counts in the thousands would overflow the
default q_bits=16 x 31-bit-prime budget); SecAggClient.mask validates the
budget and raises rather than silently wrapping.

SECURITY SCOPE: inherits mpc/secagg.py's simulation-grade primitives (DH
over the field prime, non-cryptographic PRG); see that module's docstring
for the production substitution (X25519 + keyed PRF).
"""
from __future__ import annotations

import logging
import threading
from typing import Any, Callable, Optional

import jax
import numpy as np

from ..comm import FedCommManager, Message
from ..mpc.secagg import (
    SecAggClient, SecAggServer, decrypt_share, encrypt_share,
)
from ..utils.events import recorder
from . import message_define as md
from .trainer import SiloTrainer

Pytree = Any
log = logging.getLogger(__name__)


def flatten_params(params: Pytree) -> np.ndarray:
    """Deterministic pytree -> flat f64 vector (leaf order = jax.tree.leaves)."""
    leaves = jax.tree.leaves(params)
    return np.concatenate([np.asarray(l, np.float64).reshape(-1)
                           for l in leaves])


def unflatten_params(template: Pytree, vec: np.ndarray) -> Pytree:
    leaves, treedef = jax.tree_util.tree_flatten(template)
    out, off = [], 0
    for l in leaves:
        n = int(np.size(l))
        out.append(np.asarray(vec[off:off + n], np.float32).reshape(np.shape(l)))
        off += n
    return jax.tree_util.tree_unflatten(treedef, out)


class SecAggServerManager:
    """Server FSM (reference: sa_fedml_server_manager.py:65-315).

    round_timeout: like FedServerManager — after the deadline the round
    closes over the survivors, with mask recovery for the dropped. Without a
    timeout the server waits for every client (reference behavior)."""

    def __init__(self, comm: FedCommManager, client_ids: list[int],
                 init_params: Pytree, num_rounds: int,
                 threshold: Optional[int] = None,
                 eval_fn: Optional[Callable[[Pytree, int], dict]] = None,
                 round_timeout: Optional[float] = None,
                 q_bits: int = 16,
                 checkpoint_dir: Optional[str] = None,
                 checkpoint_every: int = 1,
                 checkpoint_keep: Optional[int] = 3,
                 resume: bool = False):
        self.comm = comm
        self.client_ids = list(client_ids)
        self.n = len(self.client_ids)
        self.t = threshold if threshold is not None else max(1, self.n // 2)
        self.params = init_params
        self.dim = flatten_params(init_params).size
        self.num_rounds = num_rounds
        self.q_bits = q_bits
        self.round_idx = 0
        self.eval_fn = eval_fn
        self.round_timeout = round_timeout
        self.server = SecAggServer(self.n, self.t, self.dim, q_bits=q_bits)

        self.pks: dict[int, int] = {}
        self.client_counts: dict[int, float] = {}   # n_i sent with the pk
        self._pks_broadcast = False
        self.weight_norm = 1.0                      # N = sum(n_i), set at pks
        # transient routing buffer: _route_buf[holder][owner] = ciphertext
        # {"b":..,"sk":..}; DELETED right after forwarding — the server must
        # never retain share material (see module docstring)
        self._route_buf: Optional[dict[int, dict[int, dict]]] = {
            c: {} for c in client_ids}
        self.masked: dict[int, tuple[np.ndarray, float]] = {}
        self.active: set[int] = set(client_ids)      # not yet dropped
        self.dropped_sk: dict[int, int] = {}         # dropped id -> sk
        self.unmask_b: dict[int, dict[int, np.ndarray]] = {}
        self.unmask_sk: dict[int, dict[int, np.ndarray]] = {}
        self._awaiting_unmask = False
        self.client_online: dict[int, bool] = {}
        self.is_initialized = False
        self.done = threading.Event()
        self.error: Optional[str] = None
        self.history: list[dict] = []
        self.dropped_log: list[tuple[int, list[int]]] = []
        self._lock = threading.Lock()
        self._timer: Optional[threading.Timer] = None
        self._timer_gen = 0
        self._rearm_count = 0
        self.max_rearms = 5   # below-quorum retries before declaring failure
        self.checkpoint_dir = checkpoint_dir
        self.checkpoint_every = int(checkpoint_every)
        self.checkpoint_keep = checkpoint_keep
        self._resumed = False
        self._resume_kicked = False

        h = comm.register_message_receive_handler
        h(md.CONNECTION_IS_READY, self._on_connection_ready)
        h(md.C2S_CLIENT_STATUS, self._on_client_status)
        h(md.C2S_SA_PK, self._on_pk)
        h(md.C2S_SA_SHARES, self._on_shares)
        h(md.C2S_SA_MASKED, self._on_masked)
        h(md.C2S_SA_UNMASK, self._on_unmask)
        # clients ack S2C_FINISH; an unregistered type raises in the receive
        # loop, so the ack needs an explicit (no-op) handler
        h(md.C2S_FINISHED, lambda _msg: None)

        if resume and checkpoint_dir is not None:
            from ..utils.checkpoint import latest_round

            if latest_round(checkpoint_dir) is not None:
                self._restore(checkpoint_dir)
            else:
                log.info("resume requested but no checkpoints under %r — "
                         "starting fresh", checkpoint_dir)

    # ------------------------------------------------------------ handlers
    def _on_connection_ready(self, msg: Message) -> None:
        if self.is_initialized:
            # a restarted server's clients re-announce (client watchdog);
            # re-run the status handshake for the sender so the resume
            # broadcast below can fire once everyone is back
            self.comm.send_message(
                Message(md.S2C_CHECK_CLIENT_STATUS, 0, msg.sender_id))
            return
        for cid in self.client_ids:
            self.comm.send_message(
                Message(md.S2C_CHECK_CLIENT_STATUS, 0, cid))

    def _on_client_status(self, msg: Message) -> None:
        if msg.get(md.KEY_STATUS) == md.STATUS_FINISHED:
            return
        with self._lock:
            self.client_online[msg.sender_id] = True
            if not self.is_initialized and all(
                    self.client_online.get(c) for c in self.client_ids):
                self.is_initialized = True
                for cid in self.client_ids:
                    m = Message(md.S2C_INIT_CONFIG, 0, cid)
                    # the server is authoritative for the protocol params —
                    # a silent t/q_bits mismatch would corrupt the unmasked
                    # model, so clients adopt these on init
                    m.add(md.KEY_SA_THRESHOLD, self.t)
                    m.add(md.KEY_SA_QBITS, self.q_bits)
                    self.comm.send_message(m)
                return
            if self._resumed and not self._resume_kicked and all(
                    self.client_online.get(c) for c in self.active):
                # round-boundary resume: the surviving clients still hold
                # their key material (only the SERVER died); restart the
                # in-flight round with a plain model sync — they re-mask
                # with the same round_salt, deterministically
                self._resume_kicked = True
                for cid in sorted(self.active):
                    m = Message(md.S2C_SYNC_MODEL, 0, cid)
                    m.add(md.KEY_MODEL_PARAMS, self.params)
                    m.add(md.KEY_ROUND, self.round_idx)
                    self.comm.send_message(m)
                self._arm_timer()

    def _on_pk(self, msg: Message) -> None:
        with self._lock:
            if self._pks_broadcast:
                # a redelivered pk after the broadcast must not trigger a
                # second S2C_SA_PKS: clients would re-draw fresh Shamir
                # polynomials and later reconstruction would silently mix
                # shares of different polynomials into a garbage seed
                return
            self.pks[msg.sender_id] = int(msg.get(md.KEY_SA_PK))
            self.client_counts[msg.sender_id] = float(
                msg.get(md.KEY_NUM_SAMPLES, 1.0))
            if len(self.pks) < self.n:
                return
            self._pks_broadcast = True
            # N = sum(n_i): clients normalize their mask weights by it so
            # the field budget is count-scale-free (module docstring)
            self.weight_norm = max(sum(self.client_counts.values()), 1.0)
            pks_wire = {str(c): self.pks[c] for c in self.client_ids}
            for cid in self.client_ids:
                m = Message(md.S2C_SA_PKS, 0, cid)
                m.add(md.KEY_SA_PKS, pks_wire)
                m.add(md.KEY_SA_WEIGHT_NORM, self.weight_norm)
                self.comm.send_message(m)

    def _on_shares(self, msg: Message) -> None:
        """Route each client's encrypted shares to their holders (the server
        is the relay, as in the reference: S2C_OTHER_SS_TO_CLIENT) and drop
        the ciphertexts immediately after forwarding."""
        owner = msg.sender_id
        shares = msg.get(md.KEY_SA_SHARES)  # {holder_str: enc {"b":.., "sk":..}}
        with self._lock:
            if self._route_buf is None:
                return  # late duplicate after setup completed
            for holder_s, sh in shares.items():
                self._route_buf[int(holder_s)][owner] = sh
            # n-1 per holder: each client keeps its own share locally
            ready = all(len(self._route_buf[c]) == self.n - 1
                        for c in self.client_ids)
            if not ready:
                return
            # deliver routed shares + initial model; training starts
            for cid in self.client_ids:
                m = Message(md.S2C_SA_SHARES, 0, cid)
                m.add(md.KEY_SA_SHARES,
                      {str(o): sh for o, sh in self._route_buf[cid].items()})
                m.add(md.KEY_MODEL_PARAMS, self.params)
                m.add(md.KEY_ROUND, self.round_idx)
                self.comm.send_message(m)
            self._route_buf = None  # never retain share material
            self._arm_timer()

    def _on_masked(self, msg: Message) -> None:
        with self._lock:
            if int(msg.get(md.KEY_ROUND, -1)) != self.round_idx:
                return
            # a just-dropped client's late upload must not close the round
            # while unmask shares are being collected — that would advance
            # twice and wipe the model with an empty survivor set
            if msg.sender_id not in self.active or self._awaiting_unmask:
                return
            self.masked[msg.sender_id] = (
                np.asarray(msg.get(md.KEY_SA_MASKED), np.int64),
                float(msg.get(md.KEY_NUM_SAMPLES, 1.0)),
            )
            if set(self.masked) >= self.active:
                self._begin_unmask()

    # ---------------------------------------------------- dropout recovery
    def _arm_timer(self) -> None:
        if self.round_timeout is None:
            return
        self._cancel_timer()
        # generation counter, not round index: a stale callback can already
        # be blocked on the lock when a phase transition (masked-complete ->
        # begin_unmask) re-arms within the same round; comparing round_idx
        # would let it fire into the new phase and spuriously fail the run
        t = threading.Timer(self.round_timeout, self._on_timeout,
                            args=(self._timer_gen,))
        t.daemon = True
        t.start()
        self._timer = t

    def _cancel_timer(self) -> None:
        self._timer_gen += 1   # invalidate any in-flight stale callback
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None

    def _on_timeout(self, gen: int) -> None:
        with self._lock:
            if self.done.is_set() or gen != self._timer_gen:
                return
            if self._awaiting_unmask:
                # survivors' unmask replies never reached t+1 — a survivor
                # died between its masked upload and its share reply; the
                # sum cannot be unmasked (that is SecAgg's privacy working
                # as intended). Fail loudly rather than hang forever.
                self._fail(f"round {self.round_idx}: unmask shares "
                           f"({len(self.unmask_b)}) below t+1={self.t + 1}")
                return
            dropped_now = self.active - set(self.masked)
            survivors = sorted(self.active - dropped_now)
            if len(survivors) < self.t + 1:
                self._rearm_count += 1
                if self._rearm_count > self.max_rearms:
                    self._fail(
                        f"round {self.round_idx}: only {len(survivors)} "
                        f"survivors < t+1={self.t + 1} after "
                        f"{self.max_rearms} timeouts — quorum unreachable")
                    return
                log.warning("round %d: %d survivors < t+1=%d — re-arming "
                            "(%d/%d)", self.round_idx, len(survivors),
                            self.t + 1, self._rearm_count, self.max_rearms)
                self._arm_timer()
                return
            self._rearm_count = 0
            if not dropped_now:
                return
            log.warning("round %d: dropping %s", self.round_idx,
                        sorted(dropped_now))
            self.dropped_log.append((self.round_idx, sorted(dropped_now)))
            self.active -= dropped_now
            self._begin_unmask(dropped_now)

    def _fail(self, reason: str) -> None:
        """Caller holds the lock. Record the error and shut down."""
        log.error("secagg run failed: %s", reason)
        self.error = reason
        self._finish()

    def _begin_unmask(self, dropped_now: Optional[set] = None) -> None:
        """Caller holds the lock. EVERY round ends with a fresh collection of
        b-shares from t+1 survivors (the server retains no share material);
        after a dropout the same request also gathers sk-shares of the
        newly-dropped."""
        self._cancel_timer()
        survivors = sorted(self.active & set(self.masked))
        self._awaiting_unmask = True
        self.unmask_b.clear()
        self.unmask_sk.clear()
        need_sk = sorted(j for j in (dropped_now or set())
                         if j not in self.dropped_sk)
        for cid in survivors:
            m = Message(md.S2C_SA_UNMASK_REQ, 0, cid)
            m.add(md.KEY_SA_SURVIVORS, survivors)
            m.add(md.KEY_SA_DROPPED, need_sk)
            self.comm.send_message(m)
        # guard the collection phase: a survivor can die before replying
        self._arm_timer()

    def _on_unmask(self, msg: Message) -> None:
        holder = msg.sender_id
        with self._lock:
            if not self._awaiting_unmask:
                return
            self.unmask_b[holder] = {
                int(o): np.asarray(v, np.int64)
                for o, v in msg.get(md.KEY_SA_B_SHARES, {}).items()}
            self.unmask_sk[holder] = {
                int(o): np.asarray(v, np.int64)
                for o, v in msg.get(md.KEY_SA_SK_SHARES, {}).items()}
            if len(self.unmask_b) >= self.t + 1:
                self._awaiting_unmask = False
                self._unmask_and_advance()

    # ------------------------------------------------------------- rounds
    def _proto(self, cid: int) -> int:
        """Client id -> protocol index 0..n-1. The MPC kernel's Shamir
        evaluation points and the +/- pairwise-mask convention both run on
        protocol indices; everything crosses this boundary here."""
        return self.client_ids.index(cid)

    def _unmask_and_advance(self) -> None:
        """Caller holds the lock. Unmask the survivor sum (b-shares freshly
        collected from survivors — _begin_unmask) and advance."""
        self._cancel_timer()
        survivors = sorted(self.masked)
        pr = self._proto
        b_shares = {pr(h): {pr(o): sh for o, sh in shares.items()}
                    for h, shares in self.unmask_b.items()}
        # reconstruct newly-dropped clients' sk from survivor shares
        per_owner: dict[int, dict[int, np.ndarray]] = {}
        for holder, shares in self.unmask_sk.items():
            for owner, sh in shares.items():
                per_owner.setdefault(owner, {})[pr(holder)] = sh
        for owner, shs in per_owner.items():
            if len(shs) >= self.t + 1:
                self.dropped_sk[owner] = SecAggServer.reconstruct_sk(
                    dict(sorted(shs.items())[: self.t + 1]))
        pair_seeds = {
            pr(j): {pr(i): SecAggServer.pairwise_seed(sk, self.pks[i])
                    for i in survivors}
            for j, sk in self.dropped_sk.items()}

        with recorder.span("secagg_unmask", round=self.round_idx):
            total = self.server.aggregate(
                {pr(i): y for i, (y, _n) in self.masked.items()},
                b_shares, pair_seeds, round_salt=self.round_idx)
        # clients masked params * (n_i / N): divide by sum(n_i)/N
        wsum = sum(n for (_y, n) in self.masked.values()) / self.weight_norm
        vec = total / max(wsum, 1e-9)
        self.params = unflatten_params(self.params, vec)

        row = {"round": self.round_idx, "n_received": len(self.masked)}
        if self.eval_fn is not None:
            row.update(self.eval_fn(self.params, self.round_idx))
        self.history.append(row)
        recorder.log(row)
        self.masked.clear()
        self.round_idx += 1
        self._maybe_checkpoint(self.round_idx - 1)
        if self.round_idx >= self.num_rounds:
            self._finish()
            return
        for cid in sorted(self.active):
            m = Message(md.S2C_SYNC_MODEL, 0, cid)
            m.add(md.KEY_MODEL_PARAMS, self.params)
            m.add(md.KEY_ROUND, self.round_idx)
            self.comm.send_message(m)
        self._arm_timer()

    # ---------------------------------------------------- checkpoint/restore
    # The secagg × resume CONTRACT (ISSUE 10, README "Cross-silo
    # durability"): restore is ROUND-BOUNDARY ONLY. A checkpoint is written
    # exactly once per completed round, from _unmask_and_advance, after the
    # unmask state is cleared and before the next round's syncs go out — it
    # is NEVER written mid-secagg-round (mid-setup, mid-masked-collection,
    # or mid-unmask), and a resume that would land inside one (a foreign or
    # hand-crafted checkpoint claiming a non-boundary phase) is refused
    # with a clear error. Only the SERVER may die and resume: surviving
    # clients keep their key material and re-mask the restarted round with
    # the same round_salt, so the resumed aggregate is deterministic.
    def _maybe_checkpoint(self, r: int) -> None:
        """Caller holds the lock, at a round boundary."""
        if self.checkpoint_dir is None or not self.checkpoint_every or not (
                (r + 1) % self.checkpoint_every == 0
                or r == self.num_rounds - 1):
            return
        # invariant, not input validation: the call site above IS the round
        # boundary — tripping this means a refactor moved the write
        assert not self._awaiting_unmask and not self.masked, \
            "secagg checkpoint attempted mid-round"
        from ..utils import checkpoint as ckpt

        extra = {
            "kind": "secagg_server",
            "phase": "boundary",
            "threshold": self.t,
            "q_bits": self.q_bits,
            "num_rounds": self.num_rounds,
            "client_ids": list(self.client_ids),
            "pks": {str(c): int(pk) for c, pk in self.pks.items()},
            "client_counts": {str(c): float(n)
                              for c, n in self.client_counts.items()},
            "weight_norm": float(self.weight_norm),
            "active": sorted(self.active),
            "dropped_sk": {str(c): int(sk)
                           for c, sk in self.dropped_sk.items()},
            "dropped_log": [[rr, list(ids)] for rr, ids in self.dropped_log],
        }
        try:
            ckpt.save_checkpoint(
                self.checkpoint_dir, r, {"params": self.params},
                history=self.history, keep=self.checkpoint_keep, extra=extra)
        except Exception as e:  # noqa: BLE001 — durability must not kill runs
            log.warning("secagg round-%d checkpoint failed (continuing): "
                        "%s: %s", r, type(e).__name__, e)

    def _restore(self, path: str) -> None:
        from ..utils import checkpoint as ckpt

        # one pinned round for meta + tensors (same TOCTOU guard as the
        # plain server: a late in-flight write must not split the pair)
        r = ckpt.latest_round(path)
        meta = ckpt.read_meta(path, r)
        extra = meta.get("extra") or {}
        if extra.get("kind") != "secagg_server":
            raise ValueError(
                f"refusing to resume secagg from {path!r}: checkpoint was "
                f"written by {extra.get('kind', 'a non-secagg runtime')!r}, "
                "and secagg restore needs the protocol state (pks, dropped "
                "client keys, weight norm) only a secagg server writes")
        if extra.get("phase") != "boundary":
            raise ValueError(
                f"refusing to resume secagg from {path!r}: checkpoint "
                f"claims phase {extra.get('phase')!r} — secagg restore is "
                "round-boundary only (a resume landing inside a round "
                "cannot recover the in-flight masked uploads or unmask "
                "shares; see README \"Cross-silo durability\")")
        _r, server, _c, _h, hist = ckpt.restore_checkpoint(
            path, {"params": self.params}, round_idx=r)
        self.params = jax.tree.map(np.asarray, server["params"])
        self.history = list(hist)
        self.round_idx = int(meta["round"]) + 1
        self.t = int(extra["threshold"])
        self.q_bits = int(extra["q_bits"])
        self.pks = {int(c): int(pk) for c, pk in extra["pks"].items()}
        self.client_counts = {int(c): float(n)
                              for c, n in extra["client_counts"].items()}
        self.weight_norm = float(extra["weight_norm"])
        self.active = set(int(c) for c in extra["active"])
        self.dropped_sk = {int(c): int(sk)
                           for c, sk in extra["dropped_sk"].items()}
        self.dropped_log = [(int(rr), list(ids))
                            for rr, ids in extra.get("dropped_log", [])]
        self._pks_broadcast = True
        self._route_buf = None      # setup completed before the checkpoint
        self.client_online = {}     # liveness re-established by handshake
        self.is_initialized = True
        self._resumed = True
        log.info("secagg resumed from %r: %d rounds done, continuing at "
                 "round %d over %d active clients", path, len(self.history),
                 self.round_idx, len(self.active))

    def _finish(self) -> None:
        self._cancel_timer()
        for cid in self.client_ids:
            try:
                self.comm.send_message(Message(md.S2C_FINISH, 0, cid))
            except Exception:
                # dropped clients are exactly who may be unreachable here;
                # a failed farewell must not prevent done from being set
                log.debug("S2C_FINISH to %s failed", cid, exc_info=True)
        self.done.set()
        threading.Thread(target=self.comm.stop, daemon=True).start()

    def run(self, background: bool = False) -> None:
        if self._resumed and not self.done.is_set():
            if self.round_idx >= self.num_rounds:
                # checkpoint already covers the whole run: release clients
                with self._lock:
                    self._finish()
            else:
                # the resumed server INITIATES the re-handshake — secagg
                # clients have no watchdog, so recovery cannot depend on
                # them announcing first; their status replies trigger the
                # resume broadcast in _on_client_status
                for cid in sorted(self.active):
                    self.comm.send_message(
                        Message(md.S2C_CHECK_CLIENT_STATUS, 0, cid))
                # bound the reconnect window like a live round: if the
                # survivors never come back, _on_timeout's below-threshold
                # path fails the run after max_rearms instead of hanging
                self._arm_timer()
        self.comm.run(background=background)
        if not background and self.error:
            raise RuntimeError(self.error)


class SecAggClientManager:
    """Client FSM (reference: sa_fedml_client_manager.py). Wraps a
    SiloTrainer; masks the weighted trained params before upload."""

    def __init__(self, comm: FedCommManager, client_id: int,
                 trainer: SiloTrainer, num_clients: int,
                 client_ids: list[int], threshold: Optional[int] = None,
                 server_id: int = 0, q_bits: int = 16, seed: int = 0,
                 premask_ratio: Optional[float] = None):
        self.comm = comm
        self.client_id = client_id
        self.server_id = server_id
        self.trainer = trainer
        # quantize-then-mask compression (ISSUE 14,
        # comm_codec.secagg_premask_ratio): lossy sparsify BEFORE the shared
        # field quantization + mask — after masking the vector is uniform
        # noise and nothing lossy may touch it (mpc/secagg.premask_sparsify)
        self.premask_ratio = premask_ratio
        self.client_ids = list(client_ids)
        self.n = num_clients
        self.t = threshold if threshold is not None else max(1, self.n // 2)
        self.q_bits = q_bits
        self._seed = seed
        # protocol index 0..n-1 (Shamir evaluation points), stable ordering
        self.proto_idx = self.client_ids.index(client_id)
        # key material is minted in _on_init, once the server's
        # authoritative threshold/q_bits arrive
        self.sa: Optional[SecAggClient] = None
        self.pks: dict[int, int] = {}          # protocol idx -> pk
        self.recv_shares: dict[int, dict] = {}  # owner proto idx -> {"b","sk"}
        self._self_share: dict = {}             # this client's own b/sk share
        self.weight_norm = 1.0                  # N = sum(n_i), from S2C_SA_PKS
        self.done = threading.Event()

        h = comm.register_message_receive_handler
        h(md.S2C_CHECK_CLIENT_STATUS, self._on_check_status)
        h(md.S2C_INIT_CONFIG, self._on_init)
        h(md.S2C_SA_PKS, self._on_pks)
        h(md.S2C_SA_SHARES, self._on_shares)
        h(md.S2C_SYNC_MODEL, self._on_sync)
        h(md.S2C_SA_UNMASK_REQ, self._on_unmask_req)
        h(md.S2C_FINISH, self._on_finish)

    def _cid_to_proto(self, cid: int) -> int:
        return self.client_ids.index(cid)

    def _on_check_status(self, msg: Message) -> None:
        m = Message(md.C2S_CLIENT_STATUS, self.client_id, self.server_id)
        m.add(md.KEY_STATUS, md.STATUS_ONLINE)
        self.comm.send_message(m)

    def _on_init(self, msg: Message) -> None:
        # adopt the server's protocol parameters (they must match on both
        # sides or reconstruction silently yields garbage)
        self.t = int(msg.get(md.KEY_SA_THRESHOLD, self.t))
        self.q_bits = int(msg.get(md.KEY_SA_QBITS, self.q_bits))
        self.sa = SecAggClient(self.proto_idx, self.n, self.t,
                               q_bits=self.q_bits,
                               seed=self._seed + self.client_id)
        m = Message(md.C2S_SA_PK, self.client_id, self.server_id)
        m.add(md.KEY_SA_PK, self.sa.public_key())
        # n_i rides with the pk so the server can broadcast N = sum(n_i)
        # (sample counts are public in this protocol, as in the reference)
        m.add(md.KEY_NUM_SAMPLES, self.trainer.n_samples)
        self.comm.send_message(m)

    def _on_pks(self, msg: Message) -> None:
        # wire pks keyed by client id; protocol works on 0..n-1 indices
        self.pks = {self._cid_to_proto(int(c)): int(pk)
                    for c, pk in msg.get(md.KEY_SA_PKS).items()}
        self.weight_norm = float(msg.get(md.KEY_SA_WEIGHT_NORM, 1.0))
        b_shares = self.sa.share_self_seed()    # [n, 1]
        sk_shares = self.sa.share_sk()
        # this client's own share never leaves the process: routing it
        # (even encrypted to itself) would hand the server one real Shamir
        # share of b_i/sk_i, weakening the reconstruction threshold by one
        self._self_share = {"b": b_shares[self.proto_idx],
                            "sk": sk_shares[self.proto_idx]}
        out = Message(md.C2S_SA_SHARES, self.client_id, self.server_id)
        # each holder's shares are encrypted with the owner-holder DH pad:
        # the routing server sees only ciphertext (module docstring)
        enc = {}
        for h in range(self.n):
            if h == self.proto_idx:
                continue
            sec = self.sa.agree(self.pks[h])
            enc[str(self.client_ids[h])] = {
                "b": encrypt_share(b_shares[h], sec, self.proto_idx, h, "b"),
                "sk": encrypt_share(sk_shares[h], sec, self.proto_idx, h,
                                    "sk")}
        out.add(md.KEY_SA_SHARES, enc)
        self.comm.send_message(out)

    def _on_shares(self, msg: Message) -> None:
        self.recv_shares = {self.proto_idx: self._self_share}
        for o, sh in msg.get(md.KEY_SA_SHARES).items():
            owner = self._cid_to_proto(int(o))
            sec = self.sa.agree(self.pks[owner])
            self.recv_shares[owner] = {
                "b": decrypt_share(sh["b"], sec, owner, self.proto_idx, "b"),
                "sk": decrypt_share(sh["sk"], sec, owner, self.proto_idx,
                                    "sk")}
        self._train_and_send(msg.get(md.KEY_MODEL_PARAMS),
                             int(msg.get(md.KEY_ROUND, 0)))

    def _on_sync(self, msg: Message) -> None:
        self._train_and_send(msg.get(md.KEY_MODEL_PARAMS),
                             int(msg.get(md.KEY_ROUND, 0)))

    def _train_and_send(self, params, round_idx: int) -> None:
        with recorder.span("sa_train", round=round_idx, client=self.client_id):
            new_params, n, _metrics = self.trainer.train(params, round_idx)
        # normalized weight n/N keeps the field budget count-scale-free
        vec = flatten_params(new_params) * (float(n) / self.weight_norm)
        if self.premask_ratio is not None:
            from ..mpc.secagg import premask_sparsify

            vec = premask_sparsify(vec, self.premask_ratio)
        masked = self.sa.mask(vec, self.pks, round_salt=round_idx)
        out = Message(md.C2S_SA_MASKED, self.client_id, self.server_id)
        out.add(md.KEY_SA_MASKED, masked)
        out.add(md.KEY_NUM_SAMPLES, n)
        out.add(md.KEY_ROUND, round_idx)
        self.comm.send_message(out)

    def _on_unmask_req(self, msg: Message) -> None:
        survivors = [int(c) for c in msg.get(md.KEY_SA_SURVIVORS)]
        dropped = [int(c) for c in msg.get(md.KEY_SA_DROPPED)]
        out = Message(md.C2S_SA_UNMASK, self.client_id, self.server_id)
        out.add(md.KEY_SA_B_SHARES, {
            str(c): self.recv_shares[self._cid_to_proto(c)]["b"]
            for c in survivors if self._cid_to_proto(c) in self.recv_shares})
        out.add(md.KEY_SA_SK_SHARES, {
            str(c): self.recv_shares[self._cid_to_proto(c)]["sk"]
            for c in dropped if self._cid_to_proto(c) in self.recv_shares})
        self.comm.send_message(out)

    def _on_finish(self, msg: Message) -> None:
        m = Message(md.C2S_FINISHED, self.client_id, self.server_id)
        m.add(md.KEY_STATUS, md.STATUS_FINISHED)
        try:
            self.comm.send_message(m)
        except Exception:
            pass
        self.done.set()
        self.comm.stop()

    def run(self, background: bool = False) -> None:
        self.comm.run(background=background)

    def announce_ready(self) -> None:
        self.comm.send_message(
            Message(md.CONNECTION_IS_READY, self.client_id, self.server_id))
