"""Cross-silo FL runtime — real multi-org training over the message layer.

(reference: python/fedml/cross_silo/ — Client/Server facade in __init__.py,
horizontal + hierarchical scenarios, 4,016 LoC.) Layer map position: L3
(SURVEY.md §1); rides comm/ (L0/L1) below and is driven by runner/init (L4).

Hierarchical scenario: the reference nests torch DDP inside each silo
(process_group_manager.py); here each silo's accelerators form a local
jax Mesh inside SiloTrainer — inner gradient all-reduce over ICI, outer
model exchange over DCN (SURVEY.md §5.8 mapping).
"""
from .client import FedClientManager
from .hierarchical import partition_devices, run_hierarchical, silo_mesh
from .message_define import *  # noqa: F401,F403
from .secagg_manager import SecAggClientManager, SecAggServerManager
from .server import FedAggregator, FedServerManager
from .trainer import SiloTrainer

__all__ = [
    "FedClientManager", "FedServerManager", "FedAggregator", "SiloTrainer",
    "run_hierarchical", "silo_mesh", "partition_devices",
    "SecAggClientManager", "SecAggServerManager",
]
