"""Kill–restart chaos soak harness for the cross-silo federation (ISSUE 10).

The chaos plane (comm/chaos.py) injects LINK faults under a live process;
this harness injects PROCESS DEATH: it runs a whole federation in-process
over loopback threads and severs a role the way SIGKILL would — receive
loop cut at the transport, timers cancelled, no farewell, no final
checkpoint flush — then restarts it as a fresh manager object on the same
rank. The loopback mailboxes keep whatever frames were in flight, exactly
like a real dead process's unread sockets, so stale pre-restart traffic
(the generation-fencing target) occurs naturally.

Kill schedules can ride the chaos plane's declarative spec
(`FaultSpec.silo_kill = {rank: round}` — rank 0 is the server): the soak
driver consults it the way the transports consult crash/flap.

Shared by tests/test_silo_durability.py, the `cross_silo_durability_smoke`
diagnosis probe, and bench.py's `cross_silo_durability_*` rows. The
subprocess SIGKILL recipe for real deployments is documented in README
"Cross-silo durability".
"""
from __future__ import annotations

import time
import uuid
from typing import Optional

import jax
import numpy as np

from ..comm import FedCommManager
from ..comm.loopback import LoopbackTransport, release_router
from ..config import TrainArgs
from ..models import hub
from ..utils import metrics as _mx
from .client import FedClientManager
from .server import FedServerManager
from .trainer import SiloTrainer


def _client_data(seed: int, n: int = 64, d: int = 8, classes: int = 3):
    rs = np.random.RandomState(seed)
    w_true = rs.randn(d, classes)
    x = rs.randn(n, d).astype(np.float32)
    y = np.argmax(x @ w_true, axis=1).astype(np.int32)
    return x, y


class SiloSoakHarness:
    """One in-process federation: a server and `n_clients` clients on a
    private loopback namespace, each startable, killable, and restartable
    independently. Deterministic end to end (seeded data, round-seeded
    trainers, sorted-id aggregation), so final params from any two runs
    with the same participation are bitwise-comparable."""

    def __init__(self, n_clients: int = 2, rounds: int = 4,
                 checkpoint_dir: Optional[str] = None, seed: int = 0,
                 run_id: Optional[str] = None,
                 server_kw: Optional[dict] = None,
                 client_kw: Optional[dict] = None,
                 comm_codec: Optional[dict] = None,
                 init_params=None, trainer_factory=None,
                 train_args: Optional[TrainArgs] = None):
        self.n_clients = n_clients
        self.rounds = rounds
        self.checkpoint_dir = checkpoint_dir
        self.run_id = run_id or f"soak-{uuid.uuid4().hex[:8]}"
        self.server_kw = dict(server_kw or {})
        self.client_kw = dict(client_kw or {})
        # wire codec plane (ISSUE 14): every (re)started rank gets a FRESH
        # CodecPolicy — exactly the process-death semantics (anchor rings
        # and EF residuals die with the process; the next dense broadcast
        # re-anchors, stale delta frames in the mailbox are loud-dropped)
        self.comm_codec = comm_codec
        # live-loop override points (ISSUE 15): the federation the soak
        # drives can be ANY (init_params, per-client trainer) pairing —
        # the live loop trains the serving model's LoRA adapter tree here
        # while this file's defaults keep the original lr federation for
        # the durability soaks
        self._trainer_factory = trainer_factory
        self.targs = train_args or TrainArgs(
            epochs=2, batch_size=16, learning_rate=0.3,
            client_num_in_total=n_clients, client_num_per_round=n_clients,
            comm_round=rounds)
        if init_params is not None:
            if trainer_factory is None:
                raise ValueError(
                    "SiloSoakHarness(init_params=...) requires "
                    "trainer_factory — the default lr trainers would "
                    "train a model those params do not fit")
            self.model = None
            self.init_params = init_params
        else:
            self.model = hub.create("lr", 3)
            self.init_params = jax.tree.map(
                np.asarray, hub.init_params(self.model, (8,),
                                            jax.random.key(seed)))
        self.server: Optional[FedServerManager] = None
        self.clients: dict[int, FedClientManager] = {}
        self._dead = []          # killed managers, kept so threads can drain

    # ------------------------------------------------------------- plumbing
    def _comm(self, rank: int) -> FedCommManager:
        t = LoopbackTransport(rank, self.run_id)
        if self.comm_codec is not None:
            from ..comm.codec import CodecPolicy

            t.set_codec(CodecPolicy.from_config(self.comm_codec))
        return FedCommManager(t, rank)

    def _trainer(self, cid: int) -> SiloTrainer:
        if self._trainer_factory is not None:
            return self._trainer_factory(cid)
        x, y = _client_data(cid)
        return SiloTrainer(self.model.apply, self.targs, x, y, seed=cid)

    # --------------------------------------------------------------- roles
    def start_server(self, resume: bool = False, **over) -> FedServerManager:
        kw = dict(self.server_kw)
        kw.update(over)
        if self.checkpoint_dir is not None:
            kw.setdefault("checkpoint_dir", self.checkpoint_dir)
            kw.setdefault("checkpoint_every", 1)
        self.server = FedServerManager(
            self._comm(0), client_ids=list(range(1, self.n_clients + 1)),
            init_params=self.init_params, num_rounds=self.rounds,
            resume=resume, **kw)
        self.server.run(background=True)
        return self.server

    def start_client(self, cid: int, **over) -> FedClientManager:
        kw = dict(self.client_kw)
        kw.update(over)
        c = FedClientManager(self._comm(cid), cid, self._trainer(cid), **kw)
        self.clients[cid] = c
        c.run(background=True)
        c.announce_ready()
        return c

    def start_all(self) -> "SiloSoakHarness":
        self.start_server()
        for cid in range(1, self.n_clients + 1):
            self.start_client(cid)
        return self

    # ---------------------------------------------------------------- kills
    def kill_server(self) -> None:
        """The in-process SIGKILL analog: sever the receive loop, wait for
        the pump thread to wind down, then cancel the timers. No FINISH,
        no checkpoint flush. The ordering matters: an in-flight handler
        may still complete its current transition (a real SIGKILL lands
        mid-instruction; thread semantics cannot) and that transition
        re-arms the round timer — cancelling BEFORE the join would leave a
        zombie timer driving the dead incarnation's FSM alongside the
        restarted one. The soak's invariants hold either way because
        resume is deterministic from whatever checkpoint last hit disk."""
        srv = self.server
        assert srv is not None
        srv.comm.transport.stop_receive_message()
        th = srv.comm._thread
        if th is not None:
            th.join(timeout=10)
        with srv._lock:
            srv._cancel_timer()
            if srv._liveness_timer is not None:
                srv._liveness_timer.cancel()
        # tier-distinguishing chaos accounting (ISSUE 15): training-tier
        # process deaths ride fed.chaos.silo_kills, the serving tier's
        # ride fed.chaos.replica_kills (inference_runner._chaos_tick)
        _mx.inc("fed.chaos.silo_kills")
        # chaos kill events leave postmortems too (ISSUE 18): when a
        # flight recorder is armed, the kill flushes the ring naming what
        # the process was doing when the timeline severed it
        from ..utils.postmortem import record_kill

        record_kill("server rank 0")
        self._dead.append(srv)
        self.server = None

    def kill_client(self, cid: int) -> None:
        c = self.clients.pop(cid)
        c._stopped.set()                 # halt heartbeat/watchdog loops
        c.comm.transport.stop_receive_message()
        th = c.comm._thread
        if th is not None:
            th.join(timeout=10)
        _mx.inc("fed.chaos.silo_kills")
        from ..utils.postmortem import record_kill

        record_kill(f"client rank {cid}")
        self._dead.append(c)

    # ------------------------------------------------------------- helpers
    def wait_history(self, n: int, timeout: float = 60.0) -> bool:
        """Block until the live server has completed >= n rounds."""
        end = time.monotonic() + timeout
        while time.monotonic() < end:
            srv = self.server
            if srv is not None and len(srv.history) >= n:
                return True
            time.sleep(0.01)
        return False

    def wait_done(self, timeout: float = 120.0) -> bool:
        srv = self.server
        assert srv is not None
        ok = srv.done.wait(timeout)
        for c in self.clients.values():
            c.done.wait(5)
        return ok

    def close(self) -> None:
        for obj in ([self.server] if self.server else []) \
                + list(self.clients.values()):
            try:
                if isinstance(obj, FedServerManager):
                    obj._cancel_timer()
                    if obj._liveness_timer is not None:
                        obj._liveness_timer.cancel()
                else:
                    obj._stopped.set()
                obj.comm.stop()
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass
        release_router(self.run_id)


def uninterrupted_final_params(n_clients: int = 2, rounds: int = 4,
                               seed: int = 0):
    """Reference run: same federation, no faults. Returns (params, history).
    The soak's bitwise bar compares against this."""
    h = SiloSoakHarness(n_clients=n_clients, rounds=rounds, seed=seed)
    try:
        h.start_all()
        if not h.wait_done(timeout=120):
            raise TimeoutError("uninterrupted reference run did not finish")
        return h.server.params, list(h.server.history)
    finally:
        h.close()


def chaos_kill_soak(spec, checkpoint_dir: str, n_clients: int = 2,
                    rounds: int = 5, seed: int = 0,
                    server_timeout_s: float = 0.5,
                    timeout: float = 180.0,
                    comm_codec: Optional[dict] = None) -> dict:
    """Drive a federation under a `FaultSpec.silo_kill` schedule
    ({rank: round} — rank 0 is the server): each scheduled rank is severed
    once the run has completed that many rounds, then restarted (the server
    with `resume=True`, clients as fresh manager objects on their rank).
    Kills land at round boundaries, where every scheduled client is idle
    between its upload and the next sync — so a full-participation run
    stays full-participation and the final params are bitwise-comparable
    to an uninterrupted run's.

    `comm_codec` (ISSUE 14) runs the same soak over compressed frames —
    restarted ranks start with empty codec state and re-anchor from the
    resumed round's dense broadcast (final params then compare against a
    codec-on uninterrupted run, not the dense one: lossy codecs change the
    trajectory by design).
    """
    kills = dict(spec.silo_kill) if hasattr(spec, "silo_kill") \
        else dict(spec or {})
    if hasattr(spec, "validate_tiers"):
        # a schedule naming a rank outside this federation would silently
        # never fire — refuse it before the run starts (ISSUE 15)
        spec.validate_tiers(silo_ranks=range(n_clients + 1))
    h = SiloSoakHarness(
        n_clients=n_clients, rounds=rounds, checkpoint_dir=checkpoint_dir,
        seed=seed, comm_codec=comm_codec,
        server_kw=dict(round_timeout=10.0, quorum_frac=1.0),
        # generous re-attach budget: on a loaded box the restarted
        # server's checkpoint restore can take seconds, and a client that
        # exhausts its budget into that window is dead for good
        client_kw=dict(server_timeout_s=server_timeout_s, reattach=True,
                       max_reattach=120))
    try:
        h.start_all()
        pending = sorted(kills.items(), key=lambda kv: (kv[1], kv[0]))
        executed = []
        end = time.monotonic() + timeout
        while time.monotonic() < end:
            srv = h.server
            done_rounds = len(srv.history) if srv is not None else 0
            fired = False
            for rank, after in list(pending):
                if srv is None or done_rounds < after:
                    continue
                pending.remove((rank, after))
                executed.append((rank, after))
                if rank == 0:
                    h.kill_server()
                    h.start_server(resume=True)
                else:
                    h.kill_client(rank)
                    h.start_client(rank)
                fired = True
                break       # one kill per poll; re-read state
            if not fired:
                if not pending and h.server is not None \
                        and h.server.done.wait(0.05):
                    break
                time.sleep(0.01)
        srv = h.server
        if srv is None or not srv.done.is_set():
            raise TimeoutError(
                f"chaos soak did not finish (kills executed: {executed}, "
                f"pending: {pending})")
        for c in h.clients.values():
            c.done.wait(10)
        from ..utils import metrics as _mx

        snap = _mx.snapshot()["counters"]
        return {
            "params": srv.params,
            "history": list(srv.history),
            "error": srv.error,
            "kills": executed,
            "generation": srv.generation,
            "resumes": int(snap.get("fed.server.resumes", 0)),
            "stale_gen_rejected": int(
                snap.get("fed.server.stale_gen_rejected", 0)),
        }
    finally:
        h.close()


def server_kill_restart_soak(checkpoint_dir: str, n_clients: int = 2,
                             rounds: int = 4, kill_after: int = 2,
                             seed: int = 0,
                             server_timeout_s: float = 0.5) -> dict:
    """The headline soak: SIGKILL the server once it has completed
    `kill_after` rounds (the next round is already in flight — clients are
    training against the dead incarnation), restart it with resume, and
    run to completion. Clients re-attach through their server-silence
    watchdog. Returns final params, history, the restart's recovery time,
    and the relevant counters for assertions."""
    from ..utils import metrics as _mx

    h = SiloSoakHarness(
        n_clients=n_clients, rounds=rounds, checkpoint_dir=checkpoint_dir,
        seed=seed,
        server_kw=dict(round_timeout=10.0, quorum_frac=1.0),
        client_kw=dict(server_timeout_s=server_timeout_s, reattach=True,
                       max_reattach=120))
    try:
        h.start_all()
        if not h.wait_history(kill_after, timeout=60):
            raise TimeoutError(
                f"server never completed {kill_after} rounds pre-kill")
        h.kill_server()
        t0 = time.perf_counter()
        srv = h.start_server(resume=True)
        recovered = h.wait_done(timeout=120)
        recovery_s = time.perf_counter() - t0
        if not recovered:
            raise TimeoutError("resumed run did not finish")
        snap = _mx.snapshot()["counters"]
        return {
            "params": srv.params,
            "history": list(srv.history),
            "generation": srv.generation,
            "error": srv.error,
            "recovery_s": recovery_s,
            "resumes": int(snap.get("fed.server.resumes", 0)),
            "stale_gen_rejected": int(
                snap.get("fed.server.stale_gen_rejected", 0)),
            "reattaches": int(snap.get("fed.client.reattaches", 0)),
        }
    finally:
        h.close()
