"""Generic name->factory registries.

Replace the reference's if/elif hubs (reference: python/fedml/model/model_hub.py:19-83,
python/fedml/data/data_loader.py:262-525) with open registries so user code can
plug in models/datasets/algorithms without forking the framework.
"""
from __future__ import annotations

from typing import Callable, Dict, Generic, TypeVar

T = TypeVar("T")


class Registry(Generic[T]):
    def __init__(self, kind: str):
        self.kind = kind
        self._items: Dict[str, T] = {}

    def register(self, name: str) -> Callable[[T], T]:
        def deco(obj: T) -> T:
            key = name.lower()
            if key in self._items:
                raise KeyError(f"{self.kind} {name!r} already registered")
            self._items[key] = obj
            return obj

        return deco

    def get(self, name: str) -> T:
        key = name.lower()
        if key not in self._items:
            raise KeyError(
                f"unknown {self.kind} {name!r}; available: {sorted(self._items)}"
            )
        return self._items[key]

    def __contains__(self, name: str) -> bool:
        return name.lower() in self._items

    def names(self) -> list[str]:
        return sorted(self._items)


MODELS: Registry = Registry("model")
DATASETS: Registry = Registry("dataset")
ALGORITHMS: Registry = Registry("federated_optimizer")
DEFENSES: Registry = Registry("defense")
ATTACKS: Registry = Registry("attack")
