"""Flow DSL — compose custom federated protocols as named stages.

(reference: core/distributed/flow/fedml_flow.py — FedMLAlgorithmFlow
registers (flow_name, executor_task) pairs bound to executor classes, wires
one message handler per transition, and drives the sequence over the comm
layer; fedml_executor.py holds params/context. The reference example builds
FedAvg as: init_global_model -> local_training -> server_aggregate, looped.)

TPU design: stages are pure functions on a params dict. The flow engine
derives the message plumbing from ROLE TRANSITIONS in the stage sequence:

    server -> client   broadcast (every client runs the next stage)
    client -> server   gather    (server waits for all clients; the stage
                                  receives params["client_results"])
    same role          local call, no message

A loop segment repeats `rounds` times (the reference's run_loop). Stage
payloads ride the ordinary wire codec, so a flow built on loopback runs
unchanged on gRPC.
"""
from __future__ import annotations

import dataclasses
import logging
import threading
from typing import Any, Callable, Optional

from ..comm import FedCommManager, Message

log = logging.getLogger(__name__)

ROLE_SERVER = "server"
ROLE_CLIENT = "client"
_FLOW_MSG = "flow_stage"
_KEY_SEQ = "flow_seq"
_KEY_PARAMS = "flow_params"
_FINISH = "flow_finish"


@dataclasses.dataclass
class _Stage:
    name: str
    task: Callable[[dict], dict]
    role: str


class FedMLAlgorithmFlow:
    """One instance per node; every node registers the SAME stage sequence
    (reference: fedml_flow.py add_flow on both server and client scripts).

    task signature: task(params: dict) -> dict. On a client, params
    additionally contains "client_id". On a gather stage (client->server
    transition), params["client_results"] is the list of every client's
    returned dict, ordered by client id.
    """

    def __init__(self, comm: FedCommManager, rank: int, role: str,
                 client_ids: list[int], server_id: int = 0):
        self.comm = comm
        self.rank = rank
        self.role = role
        self.client_ids = list(client_ids)
        self.server_id = server_id
        self.stages: list[_Stage] = []
        self.sequence: list[_Stage] = []
        self.done = threading.Event()
        self.final_params: Optional[dict] = None
        self._gather: dict[int, dict] = {}
        self._gather_seq = -1
        self._lock = threading.Lock()
        comm.register_message_receive_handler(_FLOW_MSG, self._on_stage_msg)
        comm.register_message_receive_handler(_FINISH, self._on_finish)

    # ------------------------------------------------------------- building
    def add_flow(self, name: str, task: Callable[[dict], dict],
                 role: str = ROLE_SERVER) -> "FedMLAlgorithmFlow":
        if role not in (ROLE_SERVER, ROLE_CLIENT):
            raise ValueError(f"role must be server|client, got {role!r}")
        self.stages.append(_Stage(name, task, role))
        return self

    def build(self, loop_start: Optional[str] = None, rounds: int = 1) -> None:
        """Expand the stage list into the executed sequence: stages before
        `loop_start` run once, the rest repeat `rounds` times (reference:
        run_loop)."""
        if loop_start is None:
            self.sequence = list(self.stages) * max(rounds, 1)
            return
        idx = [i for i, s in enumerate(self.stages) if s.name == loop_start]
        if not idx:
            raise ValueError(f"loop_start {loop_start!r} is not a stage")
        pre, loop = self.stages[: idx[0]], self.stages[idx[0]:]
        self.sequence = pre + loop * max(rounds, 1)

    # ------------------------------------------------------------- running
    def run(self, initial_params: Optional[dict] = None,
            background: bool = True) -> None:
        """Start the flow; the owner of stage 0 kicks it off. The kick-off
        happens BEFORE entering a blocking receive loop (transports queue
        outbound/inbound frames until the loop drains them), so
        background=False cannot deadlock."""
        if not self.sequence:
            self.build()
        starter = (
            (self.sequence[0].role == ROLE_SERVER
             and self.role == ROLE_SERVER and self.rank == self.server_id)
            or (self.sequence[0].role == ROLE_CLIENT
                and self.role == ROLE_CLIENT))
        if background:
            self.comm.run(background=True)
            if starter:
                self._execute(0, dict(initial_params or {}))
        else:
            if starter:
                self._execute(0, dict(initial_params or {}))
            self.comm.run(background=False)

    def _execute(self, seq: int, params: dict) -> None:
        stage = self.sequence[seq]
        if self.role == ROLE_CLIENT:
            params = {**params, "client_id": self.rank}
        log.debug("rank %s: stage %d %s", self.rank, seq, stage.name)
        out = stage.task(params) or {}
        self._advance(seq, out)

    def _advance(self, seq: int, out: dict) -> None:
        nxt = seq + 1
        if nxt >= len(self.sequence):
            if self.role == ROLE_SERVER:
                self.final_params = out
                for cid in self.client_ids:
                    try:
                        self.comm.send_message(
                            Message(_FINISH, self.rank, cid))
                    except Exception:
                        pass
                self.done.set()
                threading.Thread(target=self.comm.stop, daemon=True).start()
            else:
                # a client-final sequence: clients gather-report with an
                # out-of-range seq; the server finishes on full collection
                self._send(self.server_id, nxt, out, gather=True)
            return
        cur_role, nxt_role = self.sequence[seq].role, self.sequence[nxt].role
        if cur_role == nxt_role:
            self._execute(nxt, out)
        elif cur_role == ROLE_SERVER:        # broadcast to clients
            for cid in self.client_ids:
                self._send(cid, nxt, out)
        else:                                 # client -> server gather
            self._send(self.server_id, nxt, out, gather=True)

    def _send(self, to: int, seq: int, params: dict,
              gather: bool = False) -> None:
        m = Message(_FLOW_MSG, self.rank, to)
        m.add(_KEY_SEQ, seq)
        m.add(_KEY_PARAMS, params)
        m.add("gather", bool(gather))
        self.comm.send_message(m)

    def _on_stage_msg(self, msg: Message) -> None:
        seq = int(msg.get(_KEY_SEQ))
        params = msg.get(_KEY_PARAMS) or {}
        if not msg.get("gather"):
            self._execute(seq, params)
            return
        # gather: collect one result per client, then run the server stage
        with self._lock:
            if seq != self._gather_seq:
                self._gather_seq = seq
                self._gather = {}
            self._gather[msg.sender_id] = params
            if set(self._gather) != set(self.client_ids):
                return
            results = [self._gather[c] for c in sorted(self._gather)]
            self._gather = {}
            self._gather_seq = -1
        if seq >= len(self.sequence):
            self.final_params = {"client_results": results}
            for cid in self.client_ids:
                try:
                    self.comm.send_message(Message(_FINISH, self.rank, cid))
                except Exception:
                    pass
            self.done.set()
            threading.Thread(target=self.comm.stop, daemon=True).start()
            return
        self._execute(seq, {"client_results": results})

    def _on_finish(self, msg: Message) -> None:
        self.done.set()
        self.comm.stop()
