"""Functional federated-algorithm contract + shared local-training machinery.

TPU-native replacement for the reference operator ABCs:
- `ClientTrainer.train()` (reference: core/alg_frame/client_trainer.py:52 — a
  stateful torch loop) becomes `client_update`: a pure function
  (broadcast, shard, client_state, rng) -> (update, new_state, metrics) whose
  inner SGD loop is `lax.scan` over batch indices, so the whole local epoch
  compiles into one XLA program.
- `ServerAggregator.aggregate()` (reference: core/alg_frame/server_aggregator.py:67)
  becomes `server_update`: (ServerState, aggregated_update) -> ServerState.
- Aggregation itself is declared, not executed, by the algorithm: LINEAR means
  "weighted mean, psum-able over a mesh axis"; FULL means "needs every client
  update materialized" (robust defenses like Krum). The round engine
  (parallel/round.py) picks collectives accordingly.

Lifecycle hooks (`on_before/after_local_training`, `on_before/on/after_
aggregation` — reference: server_aggregator.py:42-83, client_trainer.py:32-59)
are composable pytree transforms whose sites live in the round engine
(parallel/round.py: postprocess_update / aggregate_full / postprocess_agg,
composed by simulation/simulator.py), so DP/security/compression stay
plugins, not forks (SURVEY.md §7.3).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import optax
from flax import struct

from ..ops import tree as tu

Pytree = Any

# Aggregation modes
LINEAR = "linear"   # update aggregates as a sample-count-weighted mean (psum)
FULL = "full"       # aggregator needs the full stacked update set (all_gather)

# jax<=0.4.x needs local_sgd's batches gathered before the scan (see there)
_PREGATHER_BATCHES = tuple(
    int(x) for x in jax.__version__.split(".")[:2]) < (0, 5)


@struct.dataclass
class ServerState:
    """Global state carried across rounds. `extra` holds algorithm-specific
    state (SCAFFOLD's c, FedDyn's h, Mime's broadcast optimizer state...)."""
    params: Pytree
    opt_state: Any
    round: jax.Array
    extra: Any = None


@struct.dataclass
class ClientMetrics:
    """Linear-aggregable training metrics (sums, not means)."""
    loss_sum: jax.Array
    correct: jax.Array
    count: jax.Array


def masked_softmax_ce(logits: jax.Array, y: jax.Array, mask: jax.Array):
    """Cross-entropy over a padded batch. Returns (loss_mean, correct, count).
    Padding rows (mask=0) contribute nothing; a fully-padded batch yields 0
    loss and 0 gradient, so SPMD-padded clients train correctly."""
    if logits.ndim == 3:  # sequence model: [B, T, V] vs y [B, T]
        logits = logits.reshape(-1, logits.shape[-1])
        y = y.reshape(-1)
        mask = jnp.repeat(mask, logits.shape[0] // mask.shape[0])
    ce = optax.softmax_cross_entropy_with_integer_labels(logits, y)
    denom = jnp.maximum(mask.sum(), 1.0)
    loss = (ce * mask).sum() / denom
    correct = ((jnp.argmax(logits, -1) == y) * mask).sum()
    return loss, correct, mask.sum()


NWP_PAD_ID = 0  # reference: nn.CrossEntropyLoss(ignore_index=0)


def nwp_softmax_ce(logits: jax.Array, y: jax.Array, mask: jax.Array):
    """Next-word-prediction head: per-token CE that excludes pad targets.

    The reference trains NWP with `nn.CrossEntropyLoss(ignore_index=0)` and
    masks accuracy the same way (ml/trainer/my_model_trainer_nwp.py:24,75), so
    a pad token (id 0) anywhere in a real sequence contributes to neither loss
    nor accuracy. The per-token mask is the per-sample pad mask [B] crossed
    with (y != pad_id) [B, T]; padded rows have all-zero targets, so the
    sample mask is subsumed but kept for clarity under SPMD padding.
    """
    tok = (mask[:, None] * (y != NWP_PAD_ID)).astype(logits.dtype).reshape(-1)
    logits = logits.reshape(-1, logits.shape[-1])
    y = y.reshape(-1)
    ce = optax.softmax_cross_entropy_with_integer_labels(logits, y)
    denom = jnp.maximum(tok.sum(), 1.0)
    loss = (ce * tok).sum() / denom
    correct = ((jnp.argmax(logits, -1) == y) * tok).sum()
    return loss, correct, tok.sum()


def masked_mse(pred: jax.Array, y: jax.Array, mask: jax.Array):
    """Regression objective: mean squared error over a padded batch;
    'correct' reports predictions within 0.5 of the target so the engine's
    accuracy plumbing stays meaningful (reference: the regression trainers
    report MSE/MAE — ml/trainer/my_model_trainer_regression.py)."""
    if pred.ndim == 2 and pred.shape[-1] == 1:
        pred = pred[:, 0]
    err = (pred - y.astype(pred.dtype)) ** 2
    denom = jnp.maximum(mask.sum(), 1.0)
    loss = (err * mask).sum() / denom
    close = ((jnp.abs(pred - y) < 0.5) * mask).sum()
    return loss, close, mask.sum()


def masked_bce_multilabel(logits: jax.Array, y: jax.Array, mask: jax.Array):
    """Multi-label objective (stackoverflow_lr tag prediction — reference:
    data/stackoverflow_lr + lr trainer with BCE): y is a [B, L] multi-hot
    matrix; 'correct' counts per-label hits so acc = label-wise accuracy."""
    yf = y.astype(logits.dtype)
    bce = optax.sigmoid_binary_cross_entropy(logits, yf).mean(-1)
    denom = jnp.maximum(mask.sum(), 1.0)
    loss = (bce * mask).sum() / denom
    hits = (((logits > 0) == (yf > 0.5)).mean(-1) * mask).sum()
    return loss, hits, mask.sum()


SEG_IGNORE_ID = 255  # reference: fedseg trainers pass ignore_index=255


def seg_softmax_ce(logits: jax.Array, y: jax.Array, mask: jax.Array):
    """Segmentation head: per-pixel CE with an ignore label (FedSeg parity —
    reference: simulation/mpi/fedseg/utils.py SegmentationLosses builds
    nn.CrossEntropyLoss(ignore_index=255)). logits [B, H, W, C], y
    [B, H, W] int labels; the per-pixel weight is the per-sample pad mask
    [B] crossed with (y != 255), so SPMD-padded samples and ignore pixels
    contribute to neither loss nor pixel accuracy."""
    valid = y != SEG_IGNORE_ID
    pix = (mask[:, None, None] * valid).astype(jnp.float32)
    # ignore pixels get a safe in-range label; their CE is masked out anyway
    y_safe = jnp.where(valid, y, 0)
    ce = optax.softmax_cross_entropy_with_integer_labels(logits, y_safe)
    denom = jnp.maximum(pix.sum(), 1.0)
    loss = (ce * pix).sum() / denom
    correct = ((jnp.argmax(logits, -1) == y_safe) * pix).sum()
    return loss, correct, pix.sum()


def _seg_confusion(logits: jax.Array, y: jax.Array, num_classes: int,
                   mask: jax.Array | None, ignore_id: int) -> jax.Array:
    """[true, pred] pixel confusion matrix over valid pixels (ignore-label
    and SPMD-padded samples excluded). Jit-safe: one-hot matmul, no
    data-dependent shapes. Additive across batches, so whole-set metrics
    accumulate it (seg_eval_fn) and one-shot metrics use it directly."""
    pred = jnp.argmax(logits, -1)
    valid = (y != ignore_id)
    if mask is not None:
        valid = valid & (mask[:, None, None] > 0)
    vf = valid.reshape(-1).astype(jnp.float32)
    py = jax.nn.one_hot(y.reshape(-1), num_classes) * vf[:, None]
    pp = jax.nn.one_hot(pred.reshape(-1), num_classes) * vf[:, None]
    return py.T @ pp


def _iou_from_confusion(confusion: jax.Array):
    """(miou, per_class_iou); classes absent from both prediction and
    target are excluded from the mean."""
    inter = jnp.diagonal(confusion)
    union = confusion.sum(0) + confusion.sum(1) - inter
    present = union > 0
    iou = jnp.where(present, inter / jnp.maximum(union, 1.0), 0.0)
    return iou.sum() / jnp.maximum(present.sum(), 1), iou


def miou_from_logits(logits: jax.Array, y: jax.Array, num_classes: int,
                     mask: jax.Array | None = None,
                     ignore_id: int = SEG_IGNORE_ID):
    """Mean intersection-over-union, the FedSeg eval metric (reference:
    fedseg/utils.py Evaluator.Mean_Intersection_over_Union — confusion-
    matrix based). Returns (miou, per_class_iou)."""
    return _iou_from_confusion(
        _seg_confusion(logits, y, num_classes, mask, ignore_id))


# default-aggregator task heads (VERDICT: reference ships classification,
# NWP, and regression aggregator variants — ml/aggregator/; segmentation
# closes the FedSeg runtime row, simulation/mpi/fedseg/FedSegAPI.py:1)
OBJECTIVES = {
    "classification": masked_softmax_ce,
    "nwp": nwp_softmax_ce,             # pad targets (id 0) excluded, ref parity
    "regression": masked_mse,
    "multilabel": masked_bce_multilabel,
    "segmentation": seg_softmax_ce,    # per-pixel CE, ignore label 255
}


def make_objective(task: Optional[str]):
    t = (task or "classification").lower()
    if t not in OBJECTIVES:
        raise ValueError(f"unknown task {t!r}; choose from "
                         f"{sorted(OBJECTIVES)}")
    return OBJECTIVES[t]


def make_batch_indices(rng: jax.Array, shard_size: int, batch_size: int, epochs: int):
    """Per-epoch permutations of a padded shard, reshaped to [epochs*nb, B].
    Equivalent to the reference's shuffling DataLoader per local epoch
    (reference: ml/trainer/my_model_trainer_classification.py:43)."""
    bs = min(batch_size, shard_size)
    nb = shard_size // bs
    perms = jax.vmap(lambda r: jax.random.permutation(r, shard_size))(
        jax.random.split(rng, epochs)
    )
    # truncate the tail when bs doesn't divide shard_size (user-supplied
    # FedDatasets aren't necessarily padded to a batch multiple)
    return perms[:, : nb * bs].reshape(epochs * nb, bs)


def make_client_optimizer(name: str, lr: float, momentum: float = 0.0,
                          weight_decay: float = 0.0) -> optax.GradientTransformation:
    """Client-side optimizer factory (reference: my_model_trainer_classification.py:30
    builds torch SGD/Adam from args.client_optimizer)."""
    txs = []
    if weight_decay:
        txs.append(optax.add_decayed_weights(weight_decay))
    name = name.lower()
    if name == "sgd":
        txs.append(optax.sgd(lr, momentum=momentum if momentum else None))
    elif name == "adam":
        txs.append(optax.adam(lr))
    elif name == "adamw":
        return optax.adamw(lr, weight_decay=weight_decay)
    else:
        raise ValueError(f"unknown client_optimizer {name!r}")
    return optax.chain(*txs)


def local_sgd(
    apply_fn: Callable,
    params: Pytree,
    shard: dict,                     # {"x": [S,...], "y": [S], "mask": [S]}
    batch_idx: jax.Array,            # [num_steps, B] int32
    opt: optax.GradientTransformation,
    grad_correction: Optional[Callable[[Pytree, Pytree], Pytree]] = None,
    objective: Optional[Callable] = None,
    opt_state: Optional[Any] = None,
    return_opt_state: bool = False,
) -> tuple[Pytree, ClientMetrics, jax.Array]:
    """The hot loop: lax.scan over batches; grads of the masked CE loss;
    optional per-step gradient correction (FedProx prox term, SCAFFOLD control
    variates, FedDyn linear terms — all are `g + f(params)` shapes).

    Returns (final_params, summed_metrics, effective_steps) where
    effective_steps counts batches containing >=1 real sample — FedNova's
    tau_i under padding.
    """
    if opt_state is None:
        opt_state = opt.init(params)
    obj = objective or masked_softmax_ce

    def loss_fn(p, batch):
        logits = apply_fn({"params": p}, batch["x"])
        return obj(logits, batch["y"], batch["mask"])

    def step(carry, batch):
        p, s = carry
        (loss, (correct, cnt)), grads = jax.value_and_grad(
            lambda pp, b: (lambda l, c, n: (l, (c, n)))(*loss_fn(pp, b))
        , has_aux=True)(p, batch)
        if grad_correction is not None:
            grads = grad_correction(grads, p)
        updates, s = opt.update(grads, s, p)
        p = optax.apply_updates(p, updates)
        nonempty = (cnt > 0).astype(jnp.float32)
        return (p, s), (loss * cnt, correct, cnt, nonempty)

    if _PREGATHER_BATCHES:
        # jax<=0.4.x: a dynamic row-gather inside the scan body miscompiles
        # under shard_map (the SPMD partitioner feeds devices >0 skewed rows
        # inside the while loop — caught by test_sp_and_xla_backends_agree);
        # gathering every batch BEFORE the scan produces a leading batch
        # axis that partitions correctly, at the cost of materializing
        # ~epochs× the shard inside the program — so it is gated to the jax
        # versions that need it
        xs = {k: v[batch_idx] for k, v in shard.items()}
        scan_step = step
    else:
        xs = batch_idx
        scan_step = lambda carry, idx: step(
            carry, {k: v[idx] for k, v in shard.items()})
    (params, opt_state), (losses, corrects, counts, steps) = jax.lax.scan(
        scan_step, (params, opt_state), xs
    )
    metrics = ClientMetrics(losses.sum(), corrects.sum(), counts.sum())
    if return_opt_state:
        return params, metrics, steps.sum(), opt_state
    return params, metrics, steps.sum()


@dataclasses.dataclass(frozen=True)
class FedAlgorithm:
    """The pluggable federated-optimizer contract (one instance per algorithm;
    registered in core.registry.ALGORITHMS by name, matching the reference's
    `federated_optimizer` config values)."""
    name: str
    server_init: Callable[[Pytree, Any], ServerState]
    client_update: Callable[..., tuple[Pytree, Pytree, ClientMetrics]]
    server_update: Callable[[ServerState, Pytree], ServerState]
    # broadcast: what clients see. Default: current global params + extra.
    broadcast: Callable[[ServerState], dict] = None  # type: ignore[assignment]
    # per-client persistent state (stacked [num_clients, ...] by the engine)
    client_state_init: Optional[Callable[[Pytree], Pytree]] = None
    agg_mode: str = LINEAR

    def __post_init__(self):
        if self.broadcast is None:
            object.__setattr__(
                self, "broadcast",
                lambda st: {"params": st.params, "extra": st.extra},
            )


def seg_eval_fn(apply_fn: Callable, num_classes: int,
                ignore_id: int = SEG_IGNORE_ID):
    """Segmentation eval: batched jittable pass returning loss, pixel acc,
    AND mIoU — the FedSeg server-side metric (reference: fedseg/utils.py
    Evaluator; the confusion matrix accumulates across batches so the mIoU
    is over the whole set, not a mean of per-batch IoUs)."""

    @jax.jit
    def eval_batches(params, x, y, mask):
        def one(conf, batch):
            logits = apply_fn({"params": params}, batch["x"])
            loss, correct, cnt = seg_softmax_ce(
                logits, batch["y"], batch["mask"])
            conf = conf + _seg_confusion(
                logits, batch["y"], num_classes, batch["mask"], ignore_id)
            return conf, (loss * cnt, correct, cnt)

        conf, (l, c, n) = jax.lax.scan(
            one, jnp.zeros((num_classes, num_classes), jnp.float32),
            {"x": x, "y": y, "mask": mask})
        miou, iou = _iou_from_confusion(conf)
        n_tot = jnp.maximum(n.sum(), 1.0)
        return {"loss": l.sum() / n_tot, "acc": c.sum() / n_tot,
                "miou": miou, "per_class_iou": iou, "n": n.sum()}

    return eval_batches


def make_eval_fn(apply_fn: Callable, task: Optional[str] = None,
                 num_classes: Optional[int] = None):
    """Task-aware eval factory — ONE dispatch shared by every engine
    (Simulator, AsyncSimulator, centralized Trainer), so a segmentation
    config gets the whole-set confusion-matrix evaluator (mIoU rides the
    eval row) everywhere instead of only where someone special-cased it.
    Returns eval(params, x, y, mask) over batched test arrays."""
    if (task or "").lower() == "segmentation":
        if num_classes is None:
            raise ValueError(
                "segmentation eval needs num_classes (the confusion matrix "
                "shape)")
        return seg_eval_fn(apply_fn, num_classes)
    return jax.jit(eval_step_fn(apply_fn, make_objective(task)))


def eval_step_fn(apply_fn: Callable, objective: Optional[Callable] = None):
    """Batched, jittable eval over the global test set (reference:
    `test_on_server_for_all_clients`, cross_silo/server/fedml_aggregator.py).
    `objective` picks the task head (classification default; regression /
    multilabel / nwp via make_objective)."""
    obj = objective or masked_softmax_ce

    def eval_batches(params, x, y, mask):
        def one(carry, batch):
            loss, correct, cnt = obj(
                apply_fn({"params": params}, batch["x"]), batch["y"], batch["mask"]
            )
            return carry, (loss * cnt, correct, cnt)

        _, (l, c, n) = jax.lax.scan(one, 0, {"x": x, "y": y, "mask": mask})
        n_tot = jnp.maximum(n.sum(), 1.0)
        return {"loss": l.sum() / n_tot, "acc": c.sum() / n_tot, "n": n.sum()}

    return eval_batches
