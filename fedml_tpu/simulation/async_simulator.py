"""Asynchronous FedAvg simulation — staleness-weighted server merging.

TPU-native redesign of the reference's async runtime (reference:
simulation/mpi/async_fedavg/ — 1,221 LoC of process-per-client messaging where
the server merges each arriving model immediately instead of waiting for the
cohort). Here the async *semantics* are kept but the execution is a host-side
discrete-event loop over two jitted programs:

  train_one(params, client_id, rng)  -> (client_params, metrics)   [device]
  merge(global, client, alpha_eff)   -> global'                    [device]

The event queue models heterogeneous client speeds (the reason async FL
exists): each client has a speed factor; completion events pop in time order;
the merge weight decays with staleness tau = server_version - start_version
(FedAsync, Xie et al. 2019: alpha_t = alpha * (1 + tau)^(-poly_a)).

Dropout tolerance is intrinsic: a client that never completes simply never
merges; nothing blocks on it (contrast the sync server's wait-for-all,
cross_silo/server/fedml_aggregator.py:68-75).
"""
from __future__ import annotations

import heapq
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..config import Config
from ..core.algorithm import (
    eval_step_fn, local_sgd, make_batch_indices, make_client_optimizer,
)
from ..data.fed_dataset import FedDataset
from ..data import loader as data_loader
from ..models import hub as model_hub
from ..utils import metrics as _mx
from ..utils.events import recorder
from ..utils.health import record_participation, record_staleness
from .simulator import _pad_test_batches


def staleness_weight(alpha: float, tau, a: float = 0.5, mode: str = "polynomial"):
    """FedAsync mixing weight. 'polynomial': alpha*(1+tau)^-a; 'constant':
    alpha. (reference async_fedavg uses constant mixing; polynomial is the
    paper's recommended variant and the default here.)"""
    if mode == "constant":
        return jnp.asarray(alpha, jnp.float32)
    return jnp.asarray(alpha, jnp.float32) * (1.0 + tau) ** jnp.asarray(-a)


class AsyncSimulator:
    """Event-driven async FL. Config knobs (train_args.extra):
      async_concurrency: clients training simultaneously (default 4)
      async_alpha: base mixing rate (default 0.6)
      async_staleness: 'polynomial' | 'constant' (default polynomial)
      async_poly_a: staleness decay exponent (default 0.5)
      async_speed_spread: lognormal sigma of client speed factors (default 1.0)

    Total updates = comm_round * client_num_per_round, so wall-clock work is
    comparable to the sync simulator's round budget.
    """

    def __init__(self, cfg: Config, dataset: Optional[FedDataset] = None,
                 model=None):
        self.cfg = cfg
        t = cfg.train_args
        # cohort chunking/streaming are SYNC-simulator features (the async
        # loop trains one client per event; there is no stacked cohort to
        # chunk) — refuse rather than silently ignore the knobs
        for knob in ("cohort_chunk", "ingest_prefetch"):
            if t.extra.get(knob) is not None:
                raise ValueError(
                    f"train_args.{knob} has no effect on the async "
                    "simulator (its event loop dispatches one client at a "
                    "time); remove it or run the sync simulator")
        self.dataset = dataset if dataset is not None else data_loader.load(cfg)
        self.model = model if model is not None else model_hub.create(
            cfg.model_args.model, self.dataset.num_classes,
            **cfg.model_args.extra)
        rng = jax.random.key(cfg.common_args.random_seed)
        self.params = model_hub.init_params(
            self.model, self.dataset.x_train.shape[2:], rng)

        self.concurrency = int(t.extra.get("async_concurrency", 4))
        self.alpha = float(t.extra.get("async_alpha", 0.6))
        self.staleness_mode = str(t.extra.get("async_staleness", "polynomial"))
        self.poly_a = float(t.extra.get("async_poly_a", 0.5))
        spread = float(t.extra.get("async_speed_spread", 1.0))
        # chaos plane (ISSUE 4): the async loop is host-driven, so client
        # faults inject at the event queue — a dropout's completion event is
        # discarded un-merged (the client "crashed" mid-round), a straggler
        # trains at a fraction of its speed (merges late, at higher
        # staleness). Draws come from a DEDICATED seeded stream so a
        # chaos-off run's sampling is untouched.
        from ..comm.chaos import FaultSpec

        self.fault_spec = FaultSpec.from_config(cfg)
        self.straggler_factor = float(t.extra.get("chaos_straggler_factor",
                                                  4.0))
        # live scrape surface (common_args.extra.metrics_port) — the async
        # loop's staleness/participation instruments feed `fedml_tpu top`
        from ..utils.prometheus import maybe_start_metrics_server

        self.metrics_exporter = maybe_start_metrics_server(cfg)
        rs = np.random.RandomState(cfg.common_args.random_seed)
        # per-client wall-clock per unit of work (lognormal heterogeneity)
        self.client_time = rs.lognormal(0.0, spread, self.dataset.num_clients)
        # Parrot cost model (ISSUE 8): the async loop OBSERVES true
        # per-client completion times (the event queue's whole point), so
        # it is the sharpest feed for the runtime estimator — each merged
        # client's duration is recorded per client, not amortized over a
        # dispatch like the sync simulator's rounds
        from .. import schedule as lpt_sched

        self.cost_model = lpt_sched.CostModel.from_config(
            t.extra.get("cost_model"),
            {i: int(c) for i, c in
             enumerate(np.asarray(self.dataset.counts))})

        self.data = {
            "x": jnp.asarray(self.dataset.x_train),
            "y": jnp.asarray(self.dataset.y_train),
            "mask": jnp.asarray(self.dataset.mask_train),
        }
        opt = make_client_optimizer(
            t.client_optimizer, t.learning_rate, t.momentum, t.weight_decay)
        shard_size = self.dataset.x_train.shape[1]
        from ..models.hub import mixed_precision_apply
        apply_fn = mixed_precision_apply(self.model.apply, t.compute_dtype)

        from ..core.algorithm import make_objective

        objective = make_objective(t.extra.get("task"))

        def train_one(params, cid, rng_):
            shard = jax.tree.map(lambda a: a[cid], self.data)
            idx = make_batch_indices(rng_, shard_size, t.batch_size, t.epochs)
            new_params, metrics, _ = local_sgd(
                apply_fn, params, shard, idx, opt, objective=objective)
            return new_params, metrics

        def merge(global_p, client_p, alpha_eff):
            return jax.tree.map(
                lambda g, c: (1.0 - alpha_eff) * g + alpha_eff * c,
                global_p, client_p)

        self._train_one = jax.jit(train_one)
        self._merge = jax.jit(merge)
        from ..core.algorithm import make_eval_fn

        self._eval = make_eval_fn(apply_fn, t.extra.get("task"),
                                  self.dataset.num_classes)
        xb, yb, mb = _pad_test_batches(
            self.dataset.x_test, self.dataset.y_test, max(t.batch_size, 64))
        self._test = (jnp.asarray(xb), jnp.asarray(yb), jnp.asarray(mb))
        self.version = 0
        self.history: list[dict] = []

    def _sample_client(self, rs: np.random.RandomState) -> int:
        return int(rs.randint(self.dataset.num_clients))

    def evaluate(self) -> dict:
        m = jax.device_get(self._eval(self.params, *self._test))
        out = {"test_loss": float(m["loss"]), "test_acc": float(m["acc"])}
        if "miou" in m:                    # segmentation task head
            out["test_miou"] = float(m["miou"])
        return out

    def run(self, num_updates: Optional[int] = None) -> list[dict]:
        t = self.cfg.train_args
        total = (num_updates if num_updates is not None
                 else t.comm_round * t.client_num_per_round)
        rs = np.random.RandomState(self.cfg.common_args.random_seed + 1)
        base_rng = jax.random.key(self.cfg.common_args.random_seed)
        spec = self.fault_spec
        rs_fault = np.random.RandomState(
            ((spec.seed if spec else 0)
             + self.cfg.common_args.random_seed + 0xFA17) % (2 ** 31))

        # (finish_time, seq, client_id, start_version, params_snapshot)
        heap: list = []
        seq = 0

        def launch(now: float):
            nonlocal seq
            cid = self._sample_client(rs)
            dur = self.client_time[cid] * max(
                float(self.dataset.counts[cid]), 1.0)
            if spec is not None and spec.client_straggler > 0.0 \
                    and rs_fault.rand() < spec.client_straggler:
                dur *= self.straggler_factor
                _mx.inc("fed.chaos.client_stragglers")
            # dur rides the event so the completion can feed the cost model
            # (ordering is decided by (finish, seq) — the tail never compares)
            heapq.heappush(heap,
                           (now + dur, seq, cid, self.version, self.params,
                            dur))
            seq += 1

        for _ in range(min(self.concurrency, total)):
            launch(0.0)

        eval_every = max(1, total // max(t.comm_round, 1))
        merged = 0
        with recorder.span("async_run"):
            while merged < total:
                finish, s, cid, v0, snap, dur = heapq.heappop(heap)
                if spec is not None and spec.client_dropout > 0.0 \
                        and rs_fault.rand() < spec.client_dropout:
                    # the client crashed mid-round: its completion never
                    # merges and never counts as participation — async
                    # dropout tolerance means the loop just keeps going
                    _mx.inc("fed.chaos.client_dropouts")
                    if merged + len(heap) < total:
                        launch(finish)
                    continue
                rng_ = jax.random.fold_in(base_rng, s)
                client_p, met = self._train_one(snap, cid, rng_)
                tau = self.version - v0
                a_eff = staleness_weight(
                    self.alpha, float(tau), self.poly_a, self.staleness_mode)
                self.params = self._merge(self.params, client_p, a_eff)
                self.version += 1
                merged += 1
                # run-health accounting (ISSUE 3): every merged update's
                # staleness was previously written into history rows only;
                # now it also lands in the fed.staleness histogram, and the
                # merging client's participation counter bumps — the inputs
                # `fedml_tpu top` and the health flags read
                record_staleness(tau)
                record_participation(cid)
                if self.cost_model is not None:
                    self.cost_model.record_dispatch([cid], float(dur))
                _mx.set_gauge("fed.version", float(self.version))
                if merged % eval_every == 0 or merged == total:
                    if self.cost_model is not None:
                        # refresh the fit on eval cadence so the
                        # fed.cost_model.* gauges (`top`, /metrics) track
                        # the estimator this loop is feeding; the async
                        # loop itself has no placement decision to flip —
                        # the fitted model serves sync-simulator LPT and
                        # operator introspection
                        self.cost_model.engaged()
                    row = {
                        "update": merged, "sim_time": finish, "staleness": tau,
                        "train_loss": float(met.loss_sum) / max(float(met.count), 1.0),
                        **self.evaluate(),
                    }
                    self.history.append(row)
                    recorder.log(row)
                if merged + len(heap) < total:
                    launch(finish)
        return self.history


def run_async_simulation(cfg: Config, dataset=None, model=None) -> list[dict]:
    return AsyncSimulator(cfg, dataset, model).run()
