"""Classical vertical FL — feature-partitioned logistic regression.

(reference: simulation/sp/classical_vertical_fl/vfl.py — guest party A holds
the labels, host parties hold disjoint feature slices; each step hosts send
partial logits ("components"), the guest sums them, computes the loss, and
broadcasts the common logit-gradient back; party_models.py holds the per-
party linear models.)

TPU design: parties are entries of a params list (heterogeneous feature
widths — a python list, not a stacked array). One jitted step computes all
partial logits, the guest-side loss, and every party's gradient in a single
program; the quantities that would cross the wire (components up, dL/dlogit
down) are exactly the intermediates of that program, so the federated math
is bit-identical to running the parties on separate hosts.
"""
from __future__ import annotations

from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import optax

Pytree = Any


class VerticalFL:
    """Multi-party vertical logistic regression (reference:
    VerticalMultiplePartyLogisticRegressionFederatedLearning, vfl.py:1).

    feature_dims: per-party feature widths; party 0 is the guest (labels).
    Binary classification (reference parity: BCE on a single logit)."""

    def __init__(self, feature_dims: Sequence[int], lr: float = 0.05,
                 seed: int = 0):
        self.dims = list(feature_dims)
        keys = jax.random.split(jax.random.key(seed), len(self.dims))
        # per-party linear model w [d_p, 1]; guest also holds the bias
        self.params = [
            {"w": 0.01 * jax.random.normal(k, (d, 1)),
             **({"b": jnp.zeros((1,))} if p == 0 else {})}
            for p, (d, k) in enumerate(zip(self.dims, keys))
        ]
        self.opt = optax.sgd(lr)
        self.opt_state = self.opt.init(self.params)
        self._step = jax.jit(self._make_step())
        self.loss_trace: list[float] = []

    def _make_step(self):
        opt = self.opt

        def step(params, opt_state, xs, y):
            def loss_fn(ps):
                # hosts' components + guest's own partial logit
                comps = [x @ p["w"] for p, x in zip(ps, xs)]
                logit = sum(comps)[:, 0] + ps[0]["b"]
                # BCE with logits (reference: party A's logistic loss)
                loss = jnp.mean(
                    jnp.maximum(logit, 0) - logit * y
                    + jnp.log1p(jnp.exp(-jnp.abs(logit))))
                return loss, logit

            (loss, logit), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params)
            updates, opt_state = opt.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            acc = jnp.mean((logit > 0).astype(jnp.float32) == y)
            return params, opt_state, loss, acc

        return step

    def fit_batch(self, xs: Sequence[np.ndarray], y: np.ndarray) -> float:
        """One federated step on a batch: xs[p] is party p's feature slice
        (same rows, vertically aligned), y the guest's labels in {0,1}."""
        xs = [jnp.asarray(x, jnp.float32) for x in xs]
        self.params, self.opt_state, loss, _acc = self._step(
            self.params, self.opt_state, xs, jnp.asarray(y, jnp.float32))
        self.loss_trace.append(float(loss))
        return float(loss)

    def fit(self, xs: Sequence[np.ndarray], y: np.ndarray,
            epochs: int = 10, batch_size: int = 64, seed: int = 0) -> None:
        n = y.shape[0]
        rs = np.random.RandomState(seed)
        for e in range(epochs):
            order = rs.permutation(n)
            for s in range(0, n, batch_size):
                rows = order[s:s + batch_size]
                self.fit_batch([x[rows] for x in xs], y[rows])

    def predict(self, xs: Sequence[np.ndarray]) -> np.ndarray:
        comps = [jnp.asarray(x, jnp.float32) @ p["w"]
                 for p, x in zip(self.params, xs)]
        logit = sum(comps)[:, 0] + self.params[0]["b"]
        return np.asarray(logit > 0, np.int32)
