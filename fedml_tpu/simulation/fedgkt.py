"""FedGKT — Group Knowledge Transfer (He et al. 2020, NeurIPS).

(reference: simulation/mpi/fedgkt/ — GKTClientTrainer trains a small
edge model (feature extractor + classifier) with CE + KD-from-server
loss, ships (features, logits, labels) to the server; GKTServerTrainer
trains a LARGE server model on the transferred features with CE +
KD-from-client loss and returns per-client server logits; utils.KL_Loss
is the temperature-scaled KD term. The point: edge devices never hold
the big model — they exchange knowledge, not weights.)

TPU design: both phases are jitted programs over the stacked client axis:

  client phase: vmap over clients — local epochs on the small net with
      loss = CE + alpha * KL(student || server_logits)   (server logits
      zero-signal in round 0), then one feature-extraction pass
  server phase: lax.scan SGD on the big net over the POOLED
      (features, client_logits, labels) with the mirrored loss, then one
      pass producing fresh per-client server logits

No per-client processes, no feature pickles over MPI: the transfer set
lives as one [N, S, ...] array that never leaves the device.
"""
from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax

from ..core.algorithm import make_batch_indices
from ..utils.events import recorder

Pytree = Any


class GKTClientNet(nn.Module):
    """Small edge model (reference: resnet-8 client; here a compact conv
    extractor + linear head sized for edge budgets). Submodules live in
    setup() so `extract` is independently apply-able (the transfer pass)."""
    num_classes: int
    features: int = 32

    def setup(self):
        self.conv1 = nn.Conv(self.features, (3, 3))
        self.conv2 = nn.Conv(self.features, (3, 3))
        self.head = nn.Dense(self.num_classes)

    def extract(self, x):
        h = nn.relu(self.conv1(x))
        h = nn.max_pool(h, (2, 2), strides=(2, 2))
        return nn.relu(self.conv2(h))

    def classify(self, h):
        return self.head(jnp.mean(h, axis=(1, 2)))

    def __call__(self, x, train: bool = False):
        return self.classify(self.extract(x))


class GKTServerNet(nn.Module):
    """Large server model consuming client FEATURE MAPS, not images
    (reference: resnet-55/109 server trained on transferred features)."""
    num_classes: int
    width: int = 64
    depth: int = 3

    @nn.compact
    def __call__(self, h, train: bool = False):
        for _ in range(self.depth):
            r = h
            h = nn.relu(nn.GroupNorm(num_groups=8)(
                nn.Conv(self.width, (3, 3))(h)))
            h = nn.GroupNorm(num_groups=8)(nn.Conv(self.width, (3, 3))(h))
            if r.shape[-1] != h.shape[-1]:
                r = nn.Conv(self.width, (1, 1))(r)
            h = nn.relu(h + r)
        h = jnp.mean(h, axis=(1, 2))
        h = nn.relu(nn.Dense(self.width * 2)(h))
        return nn.Dense(self.num_classes)(h)


def kd_kl(student_logits, teacher_logits, temperature: float,
          mask=None):
    """Temperature-scaled KL(teacher || student) (reference:
    fedgkt/utils.py KL_Loss). `mask` [B] excludes padded rows from the
    distillation mean (the CE term is mask-weighted; KD must be too)."""
    t = temperature
    p_t = jax.nn.softmax(teacher_logits / t, -1)
    log_s = jax.nn.log_softmax(student_logits / t, -1)
    per_row = -(p_t * log_s).sum(-1)
    if mask is None:
        return per_row.mean() * (t * t)
    return (per_row * mask).sum() / jnp.maximum(mask.sum(), 1.0) * (t * t)


class FedGKTRunner:
    """Alternating client/server knowledge transfer.

    data: {"x": [N, S, H, W, C], "y": [N, S], "mask": [N, S]}.
    """

    def __init__(self, data: dict, num_classes: int,
                 client_net: Optional[GKTClientNet] = None,
                 server_net: Optional[GKTServerNet] = None,
                 lr: float = 0.02, batch_size: int = 16,
                 client_epochs: int = 1, server_epochs: int = 2,
                 kd_alpha: float = 0.5, temperature: float = 3.0,
                 seed: int = 0):
        self.data = {k: jnp.asarray(v) for k, v in data.items()}
        self.n, self.s = self.data["y"].shape
        self.num_classes = num_classes
        self.kd_alpha, self.temperature = kd_alpha, temperature
        self.batch_size, self.client_epochs = batch_size, client_epochs
        self.server_epochs = server_epochs
        self.seed = seed

        self.client_net = client_net or GKTClientNet(num_classes)
        x0 = self.data["x"][0, :1]
        self.client_params = self.client_net.init(
            jax.random.key(seed), x0)["params"]
        h0 = self.client_net.apply({"params": self.client_params}, x0,
                                   method=GKTClientNet.extract)
        self.server_net = server_net or GKTServerNet(num_classes)
        self.server_params = self.server_net.init(
            jax.random.key(seed + 1), h0)["params"]
        self.c_opt = optax.sgd(lr, momentum=0.9)
        self.s_opt = optax.sgd(lr, momentum=0.9)
        # client optimizer state is per-round fresh (init inside one_client);
        # the server's persists across rounds
        self._s_state = self.s_opt.init(self.server_params)
        # server logits fed back to clients, [N, S, K]; zeros in round 0
        self.server_logits = jnp.zeros((self.n, self.s, num_classes))
        self.history: list[dict] = []

        self._client_phase = jax.jit(self._client_phase_impl)
        self._server_phase = jax.jit(self._server_phase_impl)

    # ---------------------------------------------------------- client side
    def _client_phase_impl(self, cparams, data, server_logits, rng):
        from ..core.algorithm import masked_softmax_ce

        cn, alpha, T = self.client_net, self.kd_alpha, self.temperature

        def one_client(cp, shard, s_logits, rng_i):
            idx = make_batch_indices(
                rng_i, self.s, self.batch_size, self.client_epochs)
            opt_state = self.c_opt.init(cp)

            def step(carry, bi):
                p, st = carry
                bx, by, bm = (shard["x"][bi], shard["y"][bi],
                              shard["mask"][bi])
                bt = s_logits[bi]

                def loss_fn(pp):
                    logits = cn.apply({"params": pp}, bx)
                    loss, correct, n = masked_softmax_ce(logits, by, bm)
                    # KD only once the server has spoken (round 0 teacher
                    # is all-zeros -> uniform; harmless but we gate anyway)
                    has_teacher = (jnp.abs(bt).sum() > 0).astype(loss.dtype)
                    loss = loss + alpha * has_teacher * kd_kl(
                        logits, bt, T, mask=bm)
                    return loss, (correct, n)

                (l, (c, n)), g = jax.value_and_grad(
                    loss_fn, has_aux=True)(p)
                up, st = self.c_opt.update(g, st, p)
                return (optax.apply_updates(p, up), st), (l * n, c, n)

            (cp, _), (ls, cs, ns) = jax.lax.scan(step, (cp, opt_state), idx)
            feats = cn.apply({"params": cp}, shard["x"],
                             method=GKTClientNet.extract)
            logits = cn.apply({"params": cp}, feats,
                              method=GKTClientNet.classify)
            return cp, feats, logits, (ls.sum(), cs.sum(), ns.sum())

        rngs = jax.vmap(lambda i: jax.random.fold_in(rng, i))(
            jnp.arange(self.n))
        cps, feats, logits, mets = jax.vmap(
            one_client, in_axes=(None, 0, 0, 0))(
            cparams, data, server_logits, rngs)
        # FedGKT clients keep their own weights; aggregate by mean for the
        # shared edge init of the next round (the reference keeps fully
        # per-client weights; a mean init speeds small-scale convergence
        # and keeps client state O(1) — per-client weights would also work)
        cparams = jax.tree.map(lambda a: a.mean(0), cps)
        return cparams, feats, logits, jax.tree.map(lambda a: a.sum(0), mets)

    # ---------------------------------------------------------- server side
    def _server_phase_impl(self, sparams, s_state, y, m, feats, c_logits,
                           rng):
        from ..core.algorithm import masked_softmax_ce

        sn, alpha, T = self.server_net, self.kd_alpha, self.temperature
        # pool the transfer set: [N*S, ...]
        flat = lambda a: a.reshape((-1,) + a.shape[2:])
        fx, fy, fm, fl = flat(feats), flat(y), flat(m), flat(c_logits)
        total = fx.shape[0]
        idx = make_batch_indices(rng, total, self.batch_size * 2,
                                 self.server_epochs)

        def step(carry, bi):
            p, st = carry

            def loss_fn(pp):
                logits = sn.apply({"params": pp}, fx[bi])
                loss, correct, n = masked_softmax_ce(logits, fy[bi], fm[bi])
                loss = loss + alpha * kd_kl(logits, fl[bi], T, mask=fm[bi])
                return loss, (correct, n)

            (l, (c, n)), g = jax.value_and_grad(loss_fn, has_aux=True)(p)
            up, st = self.s_opt.update(g, st, p)
            return (optax.apply_updates(p, up), st), (l * n, c, n)

        (sparams, s_state), (ls, cs, ns) = jax.lax.scan(
            step, (sparams, s_state), idx)
        # fresh teacher logits for every client sample
        new_logits = jax.vmap(
            lambda f: sn.apply({"params": sparams}, f))(feats)
        return sparams, s_state, new_logits, (ls.sum(), cs.sum(), ns.sum())

    # -------------------------------------------------------------- driving
    def run_round(self, round_idx: int) -> dict:
        rng = jax.random.fold_in(jax.random.key(self.seed), round_idx)
        with recorder.span("gkt_client", round=round_idx):
            self.client_params, feats, logits, cm = self._client_phase(
                self.client_params, self.data, self.server_logits, rng)
        with recorder.span("gkt_server", round=round_idx):
            (self.server_params, self._s_state, self.server_logits,
             sm) = self._server_phase(
                self.server_params, self._s_state, self.data["y"],
                self.data["mask"], feats, logits,
                jax.random.fold_in(rng, 0x5E))
        cn = max(float(cm[2]), 1.0)
        sn_ = max(float(sm[2]), 1.0)
        return {
            "round": round_idx,
            "client_loss": float(cm[0]) / cn,
            "client_acc": float(cm[1]) / cn,
            "server_loss": float(sm[0]) / sn_,
            "server_acc": float(sm[1]) / sn_,
        }

    def run(self, rounds: int) -> list[dict]:
        for r in range(rounds):
            row = self.run_round(r)
            self.history.append(row)
            recorder.log(row)
        return self.history

    def predict(self, x) -> jnp.ndarray:
        """End-to-end edge->server inference (the deployment pairing)."""
        h = self.client_net.apply({"params": self.client_params},
                                  jnp.asarray(x),
                                  method=GKTClientNet.extract)
        return jnp.argmax(
            self.server_net.apply({"params": self.server_params}, h), -1)
