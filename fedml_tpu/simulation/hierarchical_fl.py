"""Two-tier hierarchical FL (HierFL): group rounds inside global rounds.

(reference: simulation/sp/hierarchical_fl/trainer.py:10 — clients are
assigned to groups (random), each global round every group runs
`group_comm_round` local FedAvg rounds among its sampled clients
(group.py:train), then the server averages the group models weighted by
group sample counts. Distinct from cross-silo hierarchical (one silo = one
trainer with intra-silo data parallelism): here BOTH tiers are FedAvg.)

TPU design: the inner tier reuses the flat round engine (parallel/round.py)
— one jitted program per group round with the group's sampled clients as
ids; the outer tier is a weighted tree-mean of group states. No new device
code: the hierarchy is pure composition of the existing round program.
"""
from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core.algorithm import FedAlgorithm
from ..ops import tree as tu
from ..parallel.round import build_round_fn

Pytree = Any


def assign_groups(n_clients: int, n_groups: int, method: str = "random",
                  seed: int = 0) -> list[np.ndarray]:
    """Client -> group assignment (reference: trainer.py group_method ==
    'random'; np.random.randint over groups)."""
    if method != "random":
        raise ValueError(f"unknown group_method {method!r} (reference "
                         "supports 'random')")
    rs = np.random.RandomState(seed)
    idx = rs.randint(0, n_groups, n_clients)
    groups = [np.where(idx == g)[0].astype(np.int32)
              for g in range(n_groups)]
    return [g for g in groups if g.size]   # drop empty groups


class HierFLRunner:
    """Global rounds of (per-group FedAvg sub-rounds -> weighted merge)."""

    def __init__(self, alg: FedAlgorithm, params: Pytree, data: dict,
                 counts: np.ndarray, n_groups: int = 2,
                 group_comm_round: int = 2,
                 clients_per_group_round: Optional[int] = None,
                 seed: int = 0):
        self.alg = alg
        self.data = {k: jnp.asarray(v) for k, v in data.items()}
        self.counts = np.asarray(counts, np.float32)
        self.groups = assign_groups(len(counts), n_groups, seed=seed)
        self.group_comm_round = group_comm_round
        self.m = clients_per_group_round
        self.seed = seed
        self.params = params
        self.round_fn = build_round_fn(alg, mesh=None)
        self.history: list[dict] = []

    def _sample(self, group: np.ndarray, global_r: int, sub_r: int):
        m = self.m or len(group)
        if m >= len(group):
            return group
        rs = np.random.RandomState(self.seed + 1000 * global_r + sub_r)
        return np.sort(rs.choice(group, m, replace=False)).astype(np.int32)

    def run(self, global_rounds: int) -> list[dict]:
        for R in range(global_rounds):
            group_params, group_weights, losses = [], [], []
            for gi, group in enumerate(self.groups):
                # each group starts the global round from the global model
                st = self.alg.server_init(
                    jax.tree.map(jnp.array, self.params), None)
                for r in range(self.group_comm_round):
                    ids = self._sample(group, R, r)
                    w = jnp.asarray(self.counts[ids])
                    rng = jax.random.fold_in(
                        jax.random.fold_in(
                            jax.random.fold_in(
                                jax.random.key(self.seed), R), gi), r)
                    # fresh placeholder per call: the engine donates it
                    out = self.round_fn(
                        st, jnp.zeros((len(self.counts),)), self.data,
                        jnp.asarray(ids), w, rng, None)
                    st = out.server_state
                    losses.append(float(out.metrics["train_loss"]))
                group_params.append(st.params)
                group_weights.append(float(self.counts[group].sum()))
            stacked = tu.tree_stack(group_params)
            self.params = tu.tree_weighted_mean(
                stacked, jnp.asarray(group_weights))
            self.history.append(
                {"round": R, "train_loss": float(np.mean(losses))})
        return self.history
