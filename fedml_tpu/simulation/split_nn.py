"""Split learning (SplitNN) — model split at a cut layer between client and
server.

(reference: simulation/mpi/split_nn/SplitNNAPI.py:10-44 splits a torch model
into client bottom / server top; client.py + server.py exchange activations
and activation-gradients over MPI; clients train in a relay ring, handing
the bottom weights to the next client.)

TPU design: the communication boundary is preserved EXACTLY — the server
never sees client params or raw data, the client never sees labels' loss
internals, only dL/dh comes back:

    client:  h, vjp = jax.vjp(bottom_apply, client_params)   (activations up)
    server:  (loss, (server_grads, dh)) = value_and_grad over (sp, h)
    client:  client_grads = vjp(dh)                            (grads down)

Both directions are jitted; `jax.vjp` at the cut IS the activation-gradient
protocol, with none of the reference's manual autograd bookkeeping. The
relay ring (client k hands bottom weights to client k+1, reference
client_manager.py) becomes a fold over the stacked client shards.
"""
from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ..core.algorithm import make_batch_indices, masked_softmax_ce

Pytree = Any


def make_split_step(client_apply: Callable, server_apply: Callable,
                    client_opt: optax.GradientTransformation,
                    server_opt: optax.GradientTransformation):
    """One batch of split training; jitted by the runner. The boundary
    values (h up, dh down) are the ONLY cross-party tensors."""

    def step(cp, sp, c_opt_state, s_opt_state, batch):
        # --- client: forward to the cut
        h, vjp = jax.vjp(
            lambda p: client_apply({"params": p}, batch["x"]), cp)

        # --- server: loss + grads wrt (its params, the activations)
        def server_loss(p, hh):
            logits = server_apply({"params": p}, hh)
            loss, correct, cnt = masked_softmax_ce(
                logits, batch["y"], batch["mask"])
            return loss, (correct, cnt)

        (loss, (correct, cnt)), (s_grads, dh) = jax.value_and_grad(
            server_loss, argnums=(0, 1), has_aux=True)(sp, h)
        s_updates, s_opt_state = server_opt.update(s_grads, s_opt_state, sp)
        sp = optax.apply_updates(sp, s_updates)

        # --- client: backward from the cut
        (c_grads,) = vjp(dh)
        c_updates, c_opt_state = client_opt.update(c_grads, c_opt_state, cp)
        cp = optax.apply_updates(cp, c_updates)
        return cp, sp, c_opt_state, s_opt_state, (loss, correct, cnt)

    return step


class SplitNNRunner:
    """Relay-ring split training (reference: SplitNNAPI.py + the
    client/server managers): clients take turns; each trains `epochs` local
    epochs against the shared server top, then relays the bottom weights."""

    def __init__(self, client_net, server_net, data: dict,
                 lr: float = 0.1, batch_size: int = 16, epochs: int = 1,
                 seed: int = 0):
        self.client_net, self.server_net = client_net, server_net
        self.data = {k: jnp.asarray(v) for k, v in data.items()}
        if "mask" not in self.data:
            self.data["mask"] = jnp.ones(self.data["y"].shape, jnp.float32)
        self.n_clients = int(self.data["y"].shape[0])
        self.batch_size, self.epochs, self.seed = batch_size, epochs, seed

        x0 = self.data["x"][0, :1]
        self.client_params = client_net.init(jax.random.key(seed), x0)["params"]
        h0 = client_net.apply({"params": self.client_params}, x0)
        self.server_params = server_net.init(
            jax.random.key(seed + 1), h0)["params"]
        self.c_opt = optax.sgd(lr)
        self.s_opt = optax.sgd(lr)
        self._step = jax.jit(make_split_step(
            client_net.apply, server_net.apply, self.c_opt, self.s_opt))
        self.history: list[dict] = []

    def run(self, rounds: int = 1) -> list[dict]:
        cp, sp = self.client_params, self.server_params
        c_state, s_state = self.c_opt.init(cp), self.s_opt.init(sp)
        for r in range(rounds):
            for k in range(self.n_clients):   # the relay ring
                shard = {key: v[k] for key, v in self.data.items()}
                s = int(shard["y"].shape[0])
                rng = jax.random.fold_in(
                    jax.random.fold_in(jax.random.key(self.seed), r), k)
                idx = make_batch_indices(rng, s, self.batch_size, self.epochs)
                tot = np.zeros(3)
                for b in range(idx.shape[0]):
                    batch = {key: v[idx[b]] for key, v in shard.items()}
                    cp, sp, c_state, s_state, (l, c, n) = self._step(
                        cp, sp, c_state, s_state, batch)
                    tot += [float(l) * float(n), float(c), float(n)]
                self.history.append({
                    "round": r, "client": k,
                    "loss": tot[0] / max(tot[2], 1),
                    "acc": tot[1] / max(tot[2], 1)})
        self.client_params, self.server_params = cp, sp
        return self.history

    def predict(self, x) -> jnp.ndarray:
        h = self.client_net.apply({"params": self.client_params},
                                  jnp.asarray(x))
        return jnp.argmax(
            self.server_net.apply({"params": self.server_params}, h), -1)
