"""Host-driven federated simulation loop.

The TPU analog of the reference simulators (reference:
simulation/simulator.py:26-238 SimulatorSingleProcess/MPI/NCCL and the
canonical FedAvgAPI.train loop, simulation/sp/fedavg/fedavg_api.py:66-125).
The host does only what cannot be traced: client sampling (seeded by round for
reference parity — fedavg_api.py:127-135), eval cadence, logging, checkpoints.
Everything else — local training of every sampled client, aggregation, the
server step — is ONE jitted XLA program per round (parallel/round.py).
"""
from __future__ import annotations

import time
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .. import dp as dp_mod
from .. import security as sec_mod
from ..algorithms import build_algorithm
from ..compression import make_compression_transform
from ..config import BACKEND_XLA, Config
from ..core.algorithm import eval_step_fn
from ..data.fed_dataset import FedDataset
from ..data import loader as data_loader
from ..models import hub as model_hub
from ..ops import tree as tu
from ..parallel.mesh import make_mesh
from .. import schedule as lpt_sched
from ..parallel.round import build_block_fn, build_round_fn, shard_fed_data
from ..utils import maybe_enable_compilation_cache
from ..utils.events import recorder


def _compose(*fns):
    """Chain optional (upd, rng) -> upd transforms; None entries are skipped."""
    fns = [f for f in fns if f is not None]
    if not fns:
        return None

    def chained(upd, rng):
        for i, f in enumerate(fns):
            upd = f(upd, jax.random.fold_in(rng, i + 0x9A))
        return upd

    return chained


def _pad_test_batches(x: np.ndarray, y: np.ndarray, batch_size: int):
    n = x.shape[0]
    nb = (n + batch_size - 1) // batch_size
    pad = nb * batch_size - n
    xp = np.concatenate([x, np.zeros((pad,) + x.shape[1:], x.dtype)]) if pad else x
    # y may carry trailing dims (sequence targets [N, T], multilabel [N, L])
    yp = np.concatenate([y, np.zeros((pad,) + y.shape[1:], y.dtype)]) if pad else y
    mask = np.concatenate([np.ones(n, np.float32), np.zeros(pad, np.float32)])
    rs = lambda a: a.reshape((nb, batch_size) + a.shape[1:])
    return rs(xp), rs(yp), rs(mask)


class Simulator:
    """fedml.run_simulation equivalent for backend in {"sp", "xla"}.

    backend="sp": single-device program (still jit, vmap over clients).
    backend="xla": shard_map over the `clients` mesh axis — one FL client
    (or a scanned set of clients) per chip.
    """

    def __init__(self, cfg: Config, dataset: Optional[FedDataset] = None,
                 model=None, mesh=None):
        self.cfg = cfg
        t = cfg.train_args
        # before the first trace: repeated runs reuse on-disk compiled
        # programs when common_args.extra.compilation_cache_dir is set
        maybe_enable_compilation_cache(cfg)
        self.dataset = dataset if dataset is not None else data_loader.load(cfg)
        self.num_classes = self.dataset.num_classes

        self.model = model if model is not None else model_hub.create(
            cfg.model_args.model, self.num_classes, **cfg.model_args.extra
        )
        rng = jax.random.key(cfg.common_args.random_seed)
        self.params = model_hub.init_params(
            self.model, self.dataset.x_train.shape[2:], rng
        )

        use_mesh = cfg.comm_args.backend == BACKEND_XLA and len(jax.devices()) > 1
        if mesh is not None:
            self.mesh = mesh
        elif use_mesh:
            mapping = cfg.device_args.extra.get("mesh_mapping_file")
            if cfg.device_args.mesh_shape:
                self.mesh = make_mesh(cfg.device_args.mesh_shape)
            elif mapping:
                from ..parallel.mesh import mesh_from_file

                self.mesh = mesh_from_file(mapping)
            else:
                self.mesh = make_mesh({"clients": len(jax.devices())})
        else:
            self.mesh = None

        self.apply_fn = model_hub.mixed_precision_apply(
            self.model.apply, t.compute_dtype
        )
        self.alg = build_algorithm(
            t.federated_optimizer, self.apply_fn, t,
            t.client_num_in_total, t.client_num_per_round,
        )

        # -------- plugins: security, DP, compression (SURVEY.md §2.5/§2.4)
        self.attacker, self.defender = sec_mod.from_config(cfg)
        self.dp = dp_mod.from_config(cfg, counts=self.dataset.counts)
        comp_name = str(t.extra.get("compression", "none")).lower()
        comp_ratio = float(t.extra.get("compression_ratio", 0.05))
        if comp_name == "eftopk":
            # error feedback carries per-client residual state — it rides the
            # engine's client-state mechanism, not the stateless hook. The
            # defender's update transform moves inside the wrapper (before
            # sparsification) so the pipeline order matches every other
            # compressor: defender -> compress -> dp.
            from ..compression import wrap_algorithm_with_eftopk
            self.alg = wrap_algorithm_with_eftopk(
                self.alg, comp_ratio,
                pre_transform=self.defender.update_transform(),
            )
            post_update = _compose(self.dp.client_transform())
        else:
            comp = make_compression_transform(
                comp_name, comp_ratio, int(t.extra.get("quantize_bits", 8)),
            )
            post_update = _compose(
                self.defender.update_transform(), comp, self.dp.client_transform()
            )
        agg_full = sec_mod.build_server_pipeline(self.attacker, self.defender)
        from ..core.algorithm import FULL as _FULL
        self._use_full = agg_full is not None or self.alg.agg_mode == _FULL
        dp_server = self.dp.server_transform()
        dfs_post = self.defender.postprocess_agg()
        post_agg = None
        if dp_server is not None or dfs_post is not None:
            def post_agg(agg, ctx):  # noqa: E306
                if dfs_post is not None:
                    agg = dfs_post(agg, ctx)
                if dp_server is not None:
                    agg = dp_server(agg, jax.random.fold_in(ctx["rng"], 0xD9))
                return agg

        self._schedule = bool(t.extra.get("heterogeneity_schedule", True))
        group = int(t.extra.get("clients_per_device_parallel", 1))
        # run-health plane (ISSUE 3): per-client health stats ride the round
        # program's existing metrics transfer (default on — measured under
        # the telemetry budget; train_args.extra.health_stats=False opts
        # out of the IN-JIT stats only). The tracker itself is always on:
        # participation, round gauges, and straggler detection need no
        # device outputs, and observe_round accepts health=None.
        self._health_enabled = bool(t.extra.get("health_stats", True))
        from ..utils.health import HealthTracker

        self.health = HealthTracker.from_config(cfg)
        # opt-in live scrape surface (common_args.extra.metrics_port)
        from ..utils.prometheus import maybe_start_metrics_server

        self.metrics_exporter = maybe_start_metrics_server(cfg)
        # chaos plane (ISSUE 4): seeded client-fault injection runs INSIDE
        # the round/block programs (parallel/round.py) so the aggregate
        # reweights over survivors with no host round-trip; the spec is the
        # same one the comm stack's ChaosTransport consumes
        from ..comm.chaos import FaultSpec

        self.fault_spec = FaultSpec.from_config(cfg)
        # one kwargs dict drives BOTH engines: the per-round program and the
        # K-round scanned block program trace the identical round body
        self._round_kwargs = dict(
            mesh=self.mesh, group_size=group,
            aggregate_full=agg_full, postprocess_update=post_update,
            postprocess_agg=post_agg,
            num_real_clients=t.client_num_per_round,
            health_stats=self._health_enabled,
            client_dropout=(self.fault_spec.client_dropout
                            if self.fault_spec else 0.0),
            client_straggler=(self.fault_spec.client_straggler
                              if self.fault_spec else 0.0),
        )
        # ---- Parrot-scale cohort chunking (ISSUE 8): when cohort_chunk is
        # set, an m-client round streams through HBM-bounded chunk programs
        # (parallel/round.build_chunk_fns) with the partial aggregate riding
        # a donated carry — m is bounded by host RAM, not device memory.
        cc = int(t.extra.get("cohort_chunk", 0) or 0)
        self._cohort_chunk = cc
        self._ingest_prefetch = int(t.extra.get("ingest_prefetch", 1) or 0)
        self.chunk_fn = self.finalize_fn = self._make_carry = None
        if cc:
            d = self.mesh.devices.size if self.mesh is not None else 1
            if cc % d:
                raise ValueError(
                    f"train_args.cohort_chunk ({cc}) must be a multiple of "
                    f"the mesh size ({d}): a chunk splits into per-device "
                    "sub-batches")
            if group > 1 and (cc // d) % group:
                # a group that does not divide the per-device chunk would
                # change the scan's group boundaries vs the single-shot
                # program — the bitwise guarantee would silently degrade
                # to float tolerance (README "Scale-out simulation")
                raise ValueError(
                    f"train_args.clients_per_device_parallel ({group}) "
                    f"must divide the per-device chunk "
                    f"(cohort_chunk/mesh = {cc // d}): unaligned client "
                    "groups break chunked == single-shot bit-identity")
            if self._health_enabled:
                import logging

                logging.getLogger(__name__).info(
                    "cohort_chunk=%d: in-jit per-client health stats do not "
                    "ride chunked rounds (cosine-to-aggregate needs the "
                    "full update stack); participation/straggler tracking "
                    "stays on", cc)
            self._health_enabled = False
            self._round_kwargs["health_stats"] = False
            from ..parallel.round import build_chunk_fns

            self.chunk_fn, self.finalize_fn, self._make_carry = \
                build_chunk_fns(self.alg, **self._round_kwargs)
            self.round_fn = None
        else:
            self.round_fn = build_round_fn(self.alg, **self._round_kwargs)
        self.block_fn = None   # built lazily on the first blocked dispatch
        self.hook_state = sec_mod.init_pipeline_state(
            self.attacker, self.defender, self.params, t.client_num_per_round
        ) if agg_full is not None else None

        self.server_state = self.alg.server_init(self.params, cfg)
        if self.alg.client_state_init is not None:
            one = self.alg.client_state_init(self.params)
            self.client_states = jax.tree.map(
                lambda a: jnp.broadcast_to(a, (self.dataset.num_clients,) + a.shape).copy(),
                one,
            )
        else:
            self.client_states = jnp.zeros((self.dataset.num_clients,))
        if self._cohort_chunk and self.mesh is not None:
            # pin replicated layouts up front: the chunk/finalize jit caches
            # key on input shardings, and uncommitted first-round state
            # would buy one throwaway compile per program before settling
            from jax.sharding import NamedSharding, PartitionSpec as P

            rep = NamedSharding(self.mesh, P())
            self.server_state = jax.device_put(self.server_state, rep)
            self.client_states = jax.device_put(self.client_states, rep)
            if self.hook_state is not None:
                self.hook_state = jax.device_put(self.hook_state, rep)

        raw = {
            "x": self.dataset.x_train,
            "y": self.dataset.y_train,
            "mask": self.dataset.mask_train,
        }
        # data-poisoning attacks mutate host arrays before upload (reference:
        # fedml_attacker.poison_data hook, client_trainer.py:32-38)
        raw = self.attacker.poison_dataset(raw, self.num_classes)
        counts = np.asarray(self.dataset.counts, np.float32)
        if self._cohort_chunk:
            # chunked rounds stream per-chunk cohort slices from HOST
            # memory (simulation/ingest.py): the full stacked dataset never
            # lands on device, and ghost-client mesh padding is unnecessary
            # because only sampled cohorts ever ship
            self._host_data = {k: np.asarray(v) for k, v in raw.items()}
            self.data = None
            from .ingest import IngestPipeline

            self._ingest = IngestPipeline(self._ingest_prefetch)
        else:
            self._host_data = None
            self._ingest = None
            if self.mesh is not None:
                # the stacked client axis must divide the mesh; pad with
                # zero-mask ghost clients (never sampled — sample_clients
                # draws < num_clients)
                d = self.mesh.devices.size
                pad = (-raw["x"].shape[0]) % d
                if pad:
                    raw = {
                        k: np.concatenate(
                            [v, np.zeros((pad,) + v.shape[1:], v.dtype)]
                        ) for k, v in raw.items()
                    }
                    counts = np.concatenate([counts, np.zeros(pad, np.float32)])
            self.data = shard_fed_data(raw, self.mesh)
        self.counts = jnp.asarray(counts)
        # Parrot cost model (ISSUE 8 leg 3): dispatch wall times feed a
        # runtime~samples fit; once trustworthy, LPT costs switch from raw
        # sample counts to predicted runtimes (schedule.CostModel)
        self._cost_model = lpt_sched.CostModel.from_config(
            t.extra.get("cost_model"),
            {i: int(c) for i, c in
             enumerate(np.asarray(self.dataset.counts))})
        # the first dispatch's wall time is dominated by the XLA compile
        # (orders of magnitude above steady state) — recording it would
        # poison the per-client empirical means and the fit error
        self._cold_dispatch = True

        xb, yb, mb = _pad_test_batches(
            self.dataset.x_test, self.dataset.y_test, max(t.batch_size, 64)
        )
        self._test = (jnp.asarray(xb), jnp.asarray(yb), jnp.asarray(mb))
        from ..core.algorithm import make_eval_fn

        # task-aware: segmentation evaluates through the whole-set
        # confusion-matrix evaluator so mIoU rides the eval row (FedSeg
        # parity — the reference server evaluates mIoU every round).
        # track_jit: eval retraces surface as xla.compiles/retraces.eval_fn
        # like the round/block programs (ISSUE 2 always-on retrace metric)
        from ..utils.metrics import track_jit

        self._eval = track_jit(
            make_eval_fn(self.apply_fn, t.extra.get("task"),
                         self.num_classes), "eval_fn")
        # device-memory ledger (ISSUE 17): the simulator's resident trees
        # — global params and the per-client optimizer/state stack — so
        # `report`'s xla.ledger.* rows account for training HBM too
        from ..utils import xla_ledger as _ledger

        _ledger.register_buffers("fed_params", self.params)
        _ledger.register_buffers("client_states", self.client_states)
        self.history: list[dict] = []

    # reference parity: sampling seeded by round index (fedavg_api.py:127-135
    # does np.random.seed(round_idx); a LOCAL RandomState(round_idx) draws
    # the bit-identical ids — same MT19937 seeding — without perturbing the
    # process-global numpy RNG that chaos/async/data code shares)
    def sample_clients(self, round_idx: int) -> np.ndarray:
        t = self.cfg.train_args
        n, m = self.dataset.num_clients, t.client_num_per_round
        if n == m:
            return np.arange(m, dtype=np.int32)
        rs = np.random.RandomState(round_idx)
        return np.sort(rs.choice(range(n), m, replace=False)).astype(np.int32)

    def _pad_only(self, ids: np.ndarray):
        """Pad sampled ids to a multiple of the mesh size — of the cohort
        chunk when chunking, so every chunk program sees full static shapes
        — with zero-weight duplicates so shard shapes stay static. Returns
        (padded_ids, weights, pad)."""
        weights = np.asarray(self.counts)[ids].astype(np.float32)
        mult = self._cohort_chunk or (
            self.mesh.devices.size if self.mesh is not None else 0)
        if not mult:
            return ids, weights, 0
        pad = (-len(ids)) % mult
        if pad:
            # pad with a duplicate of an already-sampled client (weight 0):
            # its recompute is identical, so the client-state scatter-back is a
            # harmless rewrite — padding with id 0 would corrupt client 0's
            # persistent state (SCAFFOLD c_i / FedDyn h_i) on unsampled rounds
            ids = np.concatenate([ids, np.full(pad, ids[0], np.int32)])
            weights = np.concatenate([weights, np.zeros(pad, np.float32)])
        return ids, weights, pad

    def _lpt_applies(self, weights: np.ndarray, pad: int) -> bool:
        """Whether one round's padded id row gets the balanced-LPT permute.
        FULL-mode aggregation slices the real clients back out as a prefix
        (round.py call_full, num_real_clients); a permutation that moves pad
        duplicates into that prefix would silently drop real updates — skip
        scheduling whenever both padding and FULL hooks are in play."""
        if self.mesh is None:
            return False
        d = self.mesh.devices.size
        schedulable = pad == 0 or not self._use_full
        varied = (len(np.unique(weights)) > 1
                  or (self._cost_model is not None
                      and self._cost_model.engaged()))
        return bool(self._schedule and schedulable and len(weights) > d
                    and varied)

    def _sched_costs(self, ids: np.ndarray, weights: np.ndarray) -> np.ndarray:
        """Per-slot LPT costs for one padded id row: raw sample counts
        (== weights) until the runtime cost model engages, then predicted
        per-client runtimes (Parrot's heterogeneity-aware switch —
        schedule.CostModel). Pad duplicates keep cost 0 so the scheduler
        never treats them as load."""
        cm = self._cost_model
        if cm is None or not cm.engaged():
            return weights
        costs = cm.predict_costs(ids)
        return np.where(weights > 0, costs, 0.0).astype(float)

    def _record_dispatch(self, ids, weights, duration_s: float) -> None:
        """The wall-time recording hook feeding the cost model: one
        dispatch covering this id row took duration_s (pad duplicates
        excluded — their recompute is not schedulable load). The cold
        dispatch (jit compile riding the wall clock) is dropped."""
        if self._cost_model is None:
            return
        if self._cold_dispatch:
            return
        real = np.asarray(ids)[np.asarray(weights) > 0]
        self._cost_model.record_dispatch(real.tolist(), duration_s)
        # refresh the fit + fed.cost_model.* gauges every observation, not
        # only when the mesh scheduler consults engaged(): a mesh-less run
        # still fits and exports (LPT placement is mesh-only, but the
        # estimator must be observable wherever it records)
        self._cost_model.engaged()

    def _pad_ids(self, ids: np.ndarray):
        """Pad sampled ids to a multiple of the mesh size with zero-weight
        duplicates so shard_map shapes stay static, then balance per-device
        load with the Parrot scheduler (reference:
        FedAVGAggregator.generate_client_schedule, fedavg_seq:126-187 —
        uniform chunks would put all heavy clients on one chip when the
        dataset is skewed; balanced LPT permutes clients among the equal-size
        device slots so per-chip useful-sample load is even)."""
        ids, weights, pad = self._pad_only(ids)
        if self._lpt_applies(weights, pad):
            blocks = lpt_sched.balanced_lpt(self._sched_costs(ids, weights),
                                            self.mesh.devices.size)
            perm = np.concatenate([np.asarray(b, int) for b in blocks])
            ids, weights = ids[perm], weights[perm]
        return ids, weights

    def _schedule_block(self, rounds):
        """The host half of round-block execution: the [K, m] id/weight
        schedule for a block of rounds. Per-round seeded sampling and mesh
        padding run exactly as `_pad_ids` (reference parity is bit-for-bit),
        then ONE vectorized balanced-LPT pass (schedule.balanced_lpt_block)
        permutes every schedulable row at once — the host's only remaining
        per-round job, amortized to one numpy pass per block."""
        trips = [self._pad_only(self.sample_clients(r)) for r in rounds]
        ids = np.stack([i for i, _, _ in trips])
        weights = np.stack([w for _, w, _ in trips])
        rows = np.flatnonzero([self._lpt_applies(w, p) for _, w, p in trips])
        if rows.size:
            costs = np.stack([self._sched_costs(ids[i], weights[i])
                              for i in rows])
            perms = lpt_sched.balanced_lpt_block(
                costs, self.mesh.devices.size)
            ids[rows] = np.take_along_axis(ids[rows], perms, axis=1)
            weights[rows] = np.take_along_axis(weights[rows], perms, axis=1)
        return ids, weights

    def run_round(self, round_idx: int) -> dict:
        if self._cohort_chunk:
            return self._run_round_chunked(round_idx)
        ids, weights = self._pad_ids(self.sample_clients(round_idx))
        rng = jax.random.fold_in(
            jax.random.key(self.cfg.common_args.random_seed), round_idx
        )
        t0 = time.perf_counter()
        with recorder.span("train", round=round_idx):
            out = self.round_fn(
                self.server_state, self.client_states, self.data,
                jnp.asarray(ids), jnp.asarray(weights), rng, self.hook_state,
            )
            fetched = jax.device_get(out.metrics)
        # the per-client health arrays rode the SAME transfer as the scalar
        # metrics; peel them off before the history row is float-mapped
        health = fetched.pop("health", None)
        faults = fetched.pop("faults", None)
        metrics = jax.tree.map(float, fetched)
        self.server_state = out.server_state
        self.client_states = out.client_states
        self.hook_state = out.hook_state
        dur = time.perf_counter() - t0
        self.health.observe_round(round_idx, ids, weights, health,
                                  duration_s=dur, faults=faults)
        self._record_dispatch(ids, weights, dur)
        self._cold_dispatch = False
        self.dp.step_round()
        if self.dp.enabled and self.dp.accountant is not None:
            metrics["dp_epsilon"] = self.dp.get_epsilon()
        return metrics

    # ------------------------------------------- chunked cohort execution
    def _chunk_plan(self, ids: np.ndarray, weights: np.ndarray):
        """Split the padded, scheduled [m] id row into per-device/per-chunk
        sub-batches: chunk j takes rows [k*m_d + j*c, ..+c) of every device
        block k, so each device walks ITS schedule slice in order and the
        per-device accumulation order matches the single-shot program —
        the bit-identity invariant (parallel/round.chunk_body)."""
        m = len(ids)
        d = self.mesh.devices.size if self.mesh is not None else 1
        c = self._cohort_chunk // d
        m_d = m // d
        plan = []
        for j in range(m // self._cohort_chunk):
            rows = np.concatenate([
                np.arange(k * m_d + j * c, k * m_d + (j + 1) * c)
                for k in range(d)])
            plan.append((j, ids[rows], weights[rows]))
        return plan, c

    def _chunk_thunk(self, cids: np.ndarray, cw: np.ndarray):
        """One ingest unit: host-gather the chunk's client rows, ship them
        client-sharded. Runs on the ingest pipeline's worker thread."""
        def put():
            chunk = {k: v[cids] for k, v in self._host_data.items()}
            nbytes = sum(a.nbytes for a in chunk.values())
            dev = (shard_fed_data(chunk, self.mesh),
                   jnp.asarray(cids), jnp.asarray(cw))
            return dev, nbytes
        return put

    def _dispatch_chunked(self, round_idx: int):
        """Dispatch one chunk-streamed round — nothing here blocks on the
        device: chunk k+1's gather+transfer overlaps chunk k's compute
        (IngestPipeline), the partial aggregate rides the donated carry,
        and finalize closes the round. Returns (ids, weights, RoundOutput)."""
        ids, weights = self._pad_ids(self.sample_clients(round_idx))
        rng = jax.random.fold_in(
            jax.random.key(self.cfg.common_args.random_seed), round_idx)
        plan, c_local = self._chunk_plan(ids, weights)
        chunk_struct = {
            k: jax.ShapeDtypeStruct((len(plan[0][1]),) + v.shape[1:], v.dtype)
            for k, v in self._host_data.items()}
        carry = self._make_carry(self.server_state, self.client_states,
                                 ids, chunk_struct)
        thunks = [self._chunk_thunk(cids, cw) for _, cids, cw in plan]
        for (j, _, _), (cdata, cids_dev, cw_dev) in zip(
                plan, self._ingest.stream(thunks)):
            carry = self.chunk_fn(
                carry, self.server_state, cdata, cids_dev, cw_dev, rng,
                jnp.asarray(j * c_local, jnp.int32))
        out = self.finalize_fn(
            self.server_state, carry, jnp.asarray(ids),
            jnp.asarray(weights), rng, self.hook_state)
        self.server_state = out.server_state
        self.client_states = out.client_states
        self.hook_state = out.hook_state
        return ids, weights, out

    def _run_round_chunked(self, round_idx: int) -> dict:
        t0 = time.perf_counter()
        with recorder.span("train", round=round_idx) as sp:
            ids, weights, out = self._dispatch_chunked(round_idx)
            sp.meta["chunks"] = len(ids) // self._cohort_chunk
            fetched = jax.device_get(out.metrics)
        faults = fetched.pop("faults", None)
        metrics = jax.tree.map(float, fetched)
        dur = time.perf_counter() - t0
        # chunked rounds run the in-jit health stats off (see __init__);
        # participation/straggler accounting still observes every round
        self.health.observe_round(round_idx, ids, weights, None,
                                  duration_s=dur, faults=faults)
        self._record_dispatch(ids, weights, dur)
        self._cold_dispatch = False
        self.dp.step_round()
        if self.dp.enabled and self.dp.accountant is not None:
            metrics["dp_epsilon"] = self.dp.get_epsilon()
        return metrics

    def _eval_dispatch(self):
        """Enqueue the test-set eval program; returns un-materialized device
        values (JAX async dispatch — the caller fetches them later, so the
        blocked driver can keep training blocks in flight behind an eval)."""
        return self._eval(self.server_state.params, *self._test)

    @staticmethod
    def _eval_finish(m) -> dict:
        m = jax.device_get(m)
        out = {"test_loss": float(m["loss"]), "test_acc": float(m["acc"])}
        if "miou" in m:                    # segmentation task head
            out["test_miou"] = float(m["miou"])
        return out

    def evaluate(self) -> dict:
        with recorder.span("eval"):
            return self._eval_finish(self._eval_dispatch())

    # ---------------------------------------------------- checkpoint/resume
    # (beyond the reference: a killed reference run restarts from round 0 —
    # SURVEY.md §5.4; here all cross-round state round-trips through orbax)
    def save(self, ckpt_dir: str, keep: Optional[int] = 3) -> str:
        from ..utils import checkpoint as ckpt

        rounds_done = len(self.history)
        if rounds_done == 0:
            raise ValueError(
                "nothing to checkpoint: no rounds have completed (a "
                "round_-1 directory would be invisible to restore)")
        return ckpt.save_checkpoint(
            ckpt_dir, rounds_done - 1, self.server_state,
            client_states=self.client_states, hook_state=self.hook_state,
            history=self.history, keep=keep)

    def restore(self, ckpt_dir: str) -> int:
        """Load the latest checkpoint; returns the next round to run.
        The sampler is round-seeded and the DP accountant is fast-forwarded,
        so the resumed run continues exactly where the dead one stopped."""
        from ..utils import checkpoint as ckpt

        r, server, clients, hook, history = ckpt.restore_checkpoint(
            ckpt_dir, self.server_state, self.client_states, self.hook_state)
        self.server_state = server
        if clients is not None:
            self.client_states = clients
        if hook is not None:
            self.hook_state = hook
        self.history = list(history)
        rounds_done = r + 1
        if self.dp.enabled and self.dp.accountant is not None:
            # the accountant must reflect exactly the restored number of
            # compositions — whether this instance is fresh (fast-forward)
            # or live and rolling BACK to an earlier checkpoint
            self.dp.accountant.steps = rounds_done
        return rounds_done

    # ------------------------------------------------------------ run loop
    def _eval_due(self, r: int, rounds: int) -> bool:
        f = self.cfg.validation_args.frequency_of_the_test
        return bool(f) and (r % f == 0 or r == rounds - 1)

    @staticmethod
    def _ckpt_due(r: int, rounds: int, checkpoint_dir, checkpoint_every) -> bool:
        return checkpoint_dir is not None and bool(checkpoint_every) and (
            (r + 1) % checkpoint_every == 0 or r == rounds - 1)

    def _publish_model(self, r: int, params) -> None:
        """Aggregated-model publish (reference: the aggregator calls
        mlops.log_aggregated_model_info every round —
        core/mlops/__init__.py:388); no-op unless an artifact store is
        configured via mlops.init/set_artifact_store. Degrade, don't die:
        like the telemetry sinks, a store hiccup must not kill a long
        training run."""
        from .. import mlops

        try:
            mlops.log_aggregated_model_info(r, params)
        except Exception as e:  # noqa: BLE001
            import logging

            logging.getLogger(__name__).warning(
                "round-%d model-artifact publish failed (continuing): "
                "%s: %s", r, type(e).__name__, e)

    def _run_one(self, r: int, rounds: int) -> None:
        """One host-synchronous round: train, eval on cadence, log, publish."""
        row = {"round": r, **self.run_round(r)}
        if self._eval_due(r, rounds):
            row.update(self.evaluate())
        recorder.log(row)
        self.history.append(row)
        self._publish_model(r, self.server_state.params)

    # ------------------------------------------------- round-block pipeline
    def _dispatch_block(self, blk: list[int], base_rng, rounds: int):
        """Enqueue one K-round block program plus whatever must read its
        output params (eval, artifact snapshot) BEFORE the next dispatch
        donates them. Nothing here blocks on the device."""
        if self._cohort_chunk:
            # chunked + blocked: every round in the block streams its chunk
            # programs (all async-dispatched — the carry chain and donation
            # keep the device busy) and the block defers ALL metric fetches
            # to drain time. Same programs, same keys as per-round chunked
            # mode, so blocked == per-round stays bit-identical.
            t0 = time.perf_counter()
            ids_l, w_l, mets = [], [], []
            for r in blk:
                ids_r, w_r, out_r = self._dispatch_chunked(r)
                ids_l.append(ids_r)
                w_l.append(w_r)
                mets.append(out_r.metrics)
            ids, weights, metrics = np.stack(ids_l), np.stack(w_l), mets
        else:
            if self.block_fn is None:
                self.block_fn = build_block_fn(self.alg, **self._round_kwargs)
            ids, weights = self._schedule_block(blk)
            t0 = time.perf_counter()
            out = self.block_fn(
                self.server_state, self.client_states, self.data,
                jnp.asarray(ids), jnp.asarray(weights), base_rng,
                jnp.asarray(blk, dtype=jnp.int32), self.hook_state,
            )
            self.server_state = out.server_state
            self.client_states = out.client_states
            self.hook_state = out.hook_state
            metrics = out.metrics
        eval_out = (self._eval_dispatch()
                    if self._eval_due(blk[-1], rounds) else None)
        # per-round publishes degrade to one per block in blocked mode
        # (intermediate params never materialize); snapshot on device so the
        # next block's donation can't free the buffers under the store
        from .. import mlops

        snap = (jax.tree.map(jnp.copy, self.server_state.params)
                if mlops.artifact_store() is not None else None)
        return (blk, ids, weights, metrics, eval_out, snap, t0)

    def _drain_block(self, pending) -> None:
        """Materialize one dispatched block: ONE host transfer for the
        stacked [K] metrics, then per-round history rows exactly as the
        per-round driver writes them (DP accountant advanced K times, each
        round's epsilon computed at its own composition count). The block's
        "train" span covers dispatch→materialization — the async dispatch
        returns in microseconds, so timing the dispatch alone would report
        near-zero per-round durations to the sinks."""
        blk, ids, weights, metrics, eval_out, snap, t0 = pending
        if isinstance(metrics, list):
            # chunked dispatch returns one metrics pytree PER ROUND; stack
            # them into the same [K]-leading layout the block program emits
            fetched = [jax.device_get(x) for x in metrics]
            m = jax.tree.map(lambda *xs: np.stack(xs), *fetched)
        else:
            m = jax.device_get(metrics)
        block_s = time.perf_counter() - t0
        # stacked [K, m] health arrays rode the block's single transfer;
        # peel them off before the scalar rows are built, then feed the
        # tracker one round at a time (same cadence as per-round mode, with
        # the block's wall time amortized for straggler detection)
        health = m.pop("health", None)
        faults = m.pop("faults", None)
        recorder.log_block_span("train", blk, block_s)
        for j, r in enumerate(blk):
            row = {"round": r}
            row.update({k: float(v[j]) for k, v in m.items()})
            h_j = ({k: v[j] for k, v in health.items()}
                   if health is not None else None)
            f_j = ({k: v[j] for k, v in faults.items()}
                   if faults is not None else None)
            self.health.observe_round(
                r, ids[j], weights[j], h_j,
                duration_s=block_s / max(len(blk), 1), faults=f_j)
            self._record_dispatch(ids[j], weights[j],
                                  block_s / max(len(blk), 1))
            self.dp.step_round()
            if self.dp.enabled and self.dp.accountant is not None:
                row["dp_epsilon"] = self.dp.get_epsilon()
            if eval_out is not None and r == blk[-1]:
                # keep the "eval" span series alive in blocked mode: the
                # program was async-dispatched back in _dispatch_block, so
                # what's measurable here is the host's materialization wait
                # (flagged block:true like the train rows)
                te = time.perf_counter()
                row.update(self._eval_finish(eval_out))
                recorder.log_block_span("eval", [r],
                                        time.perf_counter() - te)
            recorder.log(row)
            self.history.append(row)
        # the whole first block rode the compile: only after it drains do
        # dispatch times become steady-state observations
        self._cold_dispatch = False
        if snap is not None:
            self._publish_model(blk[-1], snap)

    def _run_blocked(self, start: int, rounds: int, block_size: int,
                     checkpoint_dir, checkpoint_every) -> None:
        """Pipelined round-block driver: K rounds per XLA dispatch, block
        i+1 dispatched before block i's metrics are fetched (JAX async
        dispatch keeps the device busy across the host's schedule/LPT work).
        Blocks never span an eval/checkpoint round, so blocked and per-round
        runs produce identical history; ragged tails (cadence not a multiple
        of K, end of horizon) fall back to the per-round program instead of
        minting one block compile per distinct length."""
        from collections import deque

        t = self.cfg.train_args
        depth = max(1, int(t.extra.get("block_pipeline_depth", 2) or 1))
        # a barrier cadence shorter than the block size means no block ever
        # fills — the whole run would silently execute the per-round program
        # at 1x while the config claims blocked mode; say so once up front
        cadences = [c for c in (
            self.cfg.validation_args.frequency_of_the_test,
            checkpoint_every if checkpoint_dir is not None else 0,
        ) if c]
        if cadences and min(cadences) < block_size:
            import logging

            logging.getLogger(__name__).warning(
                "rounds_per_block=%d exceeds the eval/checkpoint cadence "
                "(%d): blocks between barriers never fill, so most or all "
                "rounds will run the per-round program; lower "
                "rounds_per_block or raise the cadence to get blocked "
                "throughput", block_size, min(cadences))
        base_rng = jax.random.key(self.cfg.common_args.random_seed)
        pending: deque = deque()

        def drain_all():
            while pending:
                self._drain_block(pending.popleft())

        blk: list[int] = []
        for r in range(start, rounds):
            blk.append(r)
            barrier = self._eval_due(r, rounds) or self._ckpt_due(
                r, rounds, checkpoint_dir, checkpoint_every)
            if not barrier and len(blk) < block_size:
                continue
            if len(blk) == block_size:
                pending.append(self._dispatch_block(blk, base_rng, rounds))
                while len(pending) >= depth:
                    self._drain_block(pending.popleft())
            else:
                drain_all()
                for rr in blk:
                    self._run_one(rr, rounds)
            blk = []
            if self._ckpt_due(r, rounds, checkpoint_dir, checkpoint_every):
                drain_all()
                self.save(checkpoint_dir)
        if blk:   # ragged tail with no barrier at the horizon end
            drain_all()
            for rr in blk:
                self._run_one(rr, rounds)
        drain_all()

    def run(self, num_rounds: Optional[int] = None,
            checkpoint_dir: Optional[str] = None,
            checkpoint_every: int = 0) -> list[dict]:
        t = self.cfg.train_args
        rounds = num_rounds if num_rounds is not None else t.comm_round
        start = 0
        if checkpoint_dir is not None:
            from ..utils.checkpoint import latest_round

            if latest_round(checkpoint_dir) is not None:
                start = self.restore(checkpoint_dir)
        block_size = max(1, int(t.extra.get("rounds_per_block", 1) or 1))
        if block_size > 1:
            self._run_blocked(start, rounds, block_size,
                              checkpoint_dir, checkpoint_every)
        else:
            for r in range(start, rounds):
                self._run_one(r, rounds)
                if self._ckpt_due(r, rounds, checkpoint_dir,
                                  checkpoint_every):
                    self.save(checkpoint_dir)
        from ..utils.sinks import flush_sinks

        flush_sinks()  # ship any buffered telemetry (BrokerLogSink batches)
        return self.history


def run_simulation(cfg: Config, dataset=None, model=None) -> list[dict]:
    # config-driven checkpointing: train_args.extra.checkpoint_dir enables
    # save+auto-resume (every round by default; checkpoint_every overrides)
    ckpt_dir = cfg.train_args.extra.get("checkpoint_dir")
    every = int(cfg.train_args.extra.get("checkpoint_every", 1) or 0)
    return Simulator(cfg, dataset, model).run(
        checkpoint_dir=ckpt_dir, checkpoint_every=every if ckpt_dir else 0)
