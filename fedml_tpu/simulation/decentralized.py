"""Decentralized FL: DSGD + PushSum as gossip-matrix programs.

(reference: simulation/sp/decentralized/ — ClientDSGD/ClientPushsum objects
exchange neighbor weights through per-client dicts each iteration,
decentralized_fl_api.py drives them; topologies from
core/distributed/topology/.)

TPU design: there are no client objects. All N clients' params live as one
stacked pytree [N, ...]; an iteration is

    vmap local SGD step  ->  gossip:  params' = W @ params  (one einsum)

with W the row-stochastic mixing matrix from comm/topology.py. The einsum
contracts the client axis on the MXU — the entire neighbor exchange that the
reference does with python dict passing is a single [N, N] x [N, D] matmul.
The full T-iteration run is one lax.scan under jit.

PushSum (Nedic & Olshevsky; reference: client_pushsum.py) handles DIRECTED
graphs where W is not doubly stochastic: each node pushes mass to its
out-neighbors with a COLUMN-stochastic matrix P, carries a scalar weight
omega, and de-biases its estimate as z = x / omega. Same einsum shape.
"""
from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ..comm.topology import AsymmetricTopologyManager, SymmetricTopologyManager
from ..core.algorithm import masked_softmax_ce

Pytree = Any


def column_stochastic(topology: np.ndarray) -> np.ndarray:
    """Push matrix for PushSum: every node splits its mass evenly among the
    nodes that listen to it (adjacency columns normalized to 1). Derived
    from the same matrix as the listen graph, so push and listen can never
    disagree (the round-1 asymmetric-topology bug class)."""
    adj = (topology > 0).astype(np.float64)
    return adj / adj.sum(axis=0, keepdims=True)


def _gossip(stacked: Pytree, W: jax.Array) -> Pytree:
    """params' = W @ params over the leading client axis, per leaf."""
    return jax.tree.map(
        lambda a: jnp.einsum(
            "ij,j...->i...", W.astype(a.dtype), a), stacked)


def _build_run(apply_fn: Callable, W: jax.Array, lr: float,
               batch_size: int, weight_decay: float, pushsum: bool):
    opt = optax.sgd(lr)

    def local_step(p, shard, rng):
        s = shard["y"].shape[0]
        idx = jax.random.choice(rng, s, (min(batch_size, s),), replace=False)
        batch = {k: v[idx] for k, v in shard.items()}

        def loss_fn(pp):
            logits = apply_fn({"params": pp}, batch["x"])
            loss, _c, _n = masked_softmax_ce(
                logits, batch["y"], batch["mask"])
            return loss

        loss, grads = jax.value_and_grad(loss_fn)(p)
        if weight_decay:
            grads = jax.tree.map(lambda g, pp: g + weight_decay * pp,
                                 grads, p)
        updates, _ = opt.update(grads, opt.init(p), p)
        return optax.apply_updates(p, updates), loss

    def run(stacked0: Pytree, data: dict, rng: jax.Array, iters: int):
        n = data["y"].shape[0]
        omega0 = jnp.ones((n,))

        def body(carry, it):
            x, omega = carry
            # de-biased estimate: PushSum trains on z = x/omega, DSGD on x
            if pushsum:
                z = jax.tree.map(
                    lambda a: a / omega.reshape((-1,) + (1,) * (a.ndim - 1)),
                    x)
            else:
                z = x
            rngs = jax.vmap(
                lambda i: jax.random.fold_in(jax.random.fold_in(rng, it), i)
            )(jnp.arange(n))
            new_z, losses = jax.vmap(local_step, in_axes=(0, 0, 0))(
                z, data, rngs)
            if pushsum:
                # fold the gradient step back into the biased iterate, then
                # push x and omega with the column-stochastic matrix
                delta = jax.tree.map(lambda a, b: a - b, new_z, z)
                x = jax.tree.map(
                    lambda xv, d: xv + d * omega.reshape(
                        (-1,) + (1,) * (d.ndim - 1)), x, delta)
                x = _gossip(x, W)
                omega = W.astype(omega.dtype) @ omega
            else:
                x = _gossip(new_z, W)
            return (x, omega), losses.mean()

        (x, omega), losses = jax.lax.scan(
            body, (stacked0, omega0), jnp.arange(iters))
        z = jax.tree.map(
            lambda a: a / omega.reshape((-1,) + (1,) * (a.ndim - 1)), x
        ) if pushsum else x
        return z, losses

    return jax.jit(run, static_argnames="iters")


def consensus_distance(stacked: Pytree) -> float:
    """Mean squared distance of each client's params to the client mean —
    the convergence-of-consensus metric (0 == full agreement)."""
    leaves = jax.tree.leaves(stacked)
    tot, cnt = 0.0, 0
    for a in leaves:
        mean = a.mean(0, keepdims=True)
        tot += float(jnp.sum((a - mean) ** 2))
        cnt += int(np.prod(a.shape[1:])) * a.shape[0]
    return tot / max(cnt, 1)


def run_dsgd(apply_fn: Callable, params0: Pytree, data: dict,
             topology: Optional[SymmetricTopologyManager] = None,
             iters: int = 100, lr: float = 0.1, batch_size: int = 8,
             weight_decay: float = 0.0, neighbor_num: int = 2,
             seed: int = 0):
    """Decentralized SGD over an undirected gossip graph (reference:
    client_dsgd.py). Returns (stacked final params [N, ...], loss curve).
    params0 may be a single pytree (replicated to all clients) or already
    stacked."""
    n = data["y"].shape[0]
    topo = topology or SymmetricTopologyManager(n, neighbor_num=neighbor_num)
    W = jnp.asarray(topo.topology, jnp.float32)
    stacked = _ensure_stacked(params0, n)
    run = _build_run(apply_fn, W, lr, batch_size, weight_decay,
                     pushsum=False)
    return run(stacked, _with_mask(data), jax.random.key(seed), iters)


def run_pushsum(apply_fn: Callable, params0: Pytree, data: dict,
                topology: Optional[AsymmetricTopologyManager] = None,
                iters: int = 100, lr: float = 0.1, batch_size: int = 8,
                weight_decay: float = 0.0, in_num: int = 2, out_num: int = 1,
                seed: int = 0):
    """PushSum over a directed gossip graph (reference: client_pushsum.py):
    column-stochastic pushes + omega de-biasing, so consensus converges to
    the uniform average even though the digraph is not doubly stochastic."""
    n = data["y"].shape[0]
    topo = topology or AsymmetricTopologyManager(n, in_num=in_num,
                                                 out_num=out_num)
    P = jnp.asarray(column_stochastic(topo.topology), jnp.float32)
    stacked = _ensure_stacked(params0, n)
    run = _build_run(apply_fn, P, lr, batch_size, weight_decay,
                     pushsum=True)
    return run(stacked, _with_mask(data), jax.random.key(seed), iters)


def _ensure_stacked(params: Pytree, n: int) -> Pytree:
    leaves = jax.tree.leaves(params)
    if leaves and hasattr(leaves[0], "shape") and leaves[0].shape[:1] == (n,):
        return params
    return jax.tree.map(
        lambda a: jnp.broadcast_to(a, (n,) + a.shape).copy(), params)


def _with_mask(data: dict) -> dict:
    if "mask" not in data:
        data = dict(data)
        data["mask"] = jnp.ones(data["y"].shape[:2], jnp.float32)
    return {k: jnp.asarray(v) for k, v in data.items()}
