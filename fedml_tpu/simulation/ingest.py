"""Double-buffered host→device streaming for cohort chunks (ISSUE 8 leg 2).

The chunked round engine (parallel/round.build_chunk_fns) turns the one big
synchronous `device_put` of a round's stacked cohort into a sequence of
per-chunk transfers — which would serialize gather→transfer→compute per
chunk if the host did them inline. This pipeline runs the host side (numpy
fancy-index gather + `jax.device_put` + block-until-resident) on a worker
thread, `prefetch` chunks ahead of the consumer, so chunk k+1's transfer
overlaps chunk k's compute exactly the way the decode engine's
dispatch-ahead fetches overlap its steps (serving/engine.py).

Observability (`fed.ingest.*`, all surfaced by `report`/`top` and the
Chrome trace):
  fed.ingest.chunks       — chunks transferred
  fed.ingest.bytes        — host bytes shipped to device
  fed.ingest.prefetched   — chunks already resident when the consumer asked
                            (the overlap-observed signal the diagnosis
                            `cohort_sharded_smoke` probe checks)
  fed.ingest.put_s        — per-chunk gather+transfer latency (histogram)
  fed.ingest.wait_s       — consumer stall waiting for a chunk (histogram);
                            ~0 when the pipeline keeps up
  span "fed.ingest.put"   — one recorder span per transfer (lands on the
                            Chrome trace, so overlap is visible next to the
                            round spans)
"""
from __future__ import annotations

import queue
import threading
import time
from typing import Callable, Iterable, Iterator

from ..utils import metrics as mx
from ..utils.events import recorder


class IngestPipeline:
    """Streams the results of ordered thunks with a bounded prefetch depth.

    Each thunk returns `(payload, nbytes)`: the payload is yielded to the
    consumer in order; nbytes feeds the byte counter. `prefetch=0` degrades
    to synchronous inline execution (same metrics, no thread) — the knob the
    ingest-overhead bench row flips.
    """

    def __init__(self, prefetch: int = 1):
        self.prefetch = max(0, int(prefetch))

    def _run(self, thunk: Callable, idx: int):
        import jax

        t0 = time.perf_counter()
        with recorder.span("fed.ingest.put", chunk=idx):
            payload, nbytes = thunk()
            # the transfer is async; block HERE (worker side) so "resident
            # before the consumer asks" is real, and the latency honest
            jax.block_until_ready(payload)
        mx.observe("fed.ingest.put_s", time.perf_counter() - t0)
        mx.inc("fed.ingest.chunks")
        mx.inc("fed.ingest.bytes", int(nbytes))
        return payload

    def stream(self, thunks: Iterable[Callable]) -> Iterator:
        """Yield each thunk's payload in order, running up to `prefetch`
        thunks ahead on a worker thread. A thunk exception re-raises at the
        consumer's next pull; abandoning the generator stops the worker."""
        thunks = list(thunks)
        if self.prefetch == 0:
            for i, t in enumerate(thunks):
                yield self._run(t, i)
            return
        q: queue.Queue = queue.Queue(maxsize=self.prefetch)
        stop = threading.Event()

        def worker():
            for i, t in enumerate(thunks):
                if stop.is_set():
                    return
                try:
                    item = ("ok", self._run(t, i))
                except BaseException as e:  # noqa: BLE001 — relayed below
                    item = ("err", e)
                while not stop.is_set():
                    try:
                        q.put(item, timeout=0.1)
                        break
                    except queue.Full:
                        continue
                if item[0] == "err":
                    return

        th = threading.Thread(target=worker, name="fed-ingest", daemon=True)
        th.start()
        try:
            for _ in range(len(thunks)):
                try:
                    kind, item = q.get_nowait()
                    # already resident: the transfer fully overlapped compute
                    mx.inc("fed.ingest.prefetched")
                except queue.Empty:
                    t0 = time.perf_counter()
                    kind, item = q.get()
                    mx.observe("fed.ingest.wait_s",
                               time.perf_counter() - t0)
                if kind == "err":
                    raise item
                yield item
        finally:
            stop.set()
