"""SecAgg — pairwise-mask secure aggregation with dropout recovery.

Protocol (Bonawitz et al. 2017), the reference's cross-silo SecAgg kernel
(reference: core/mpc/secagg.py — key agreement my_pk_gen/my_key_agreement
:329-342, masking model_masking :83-116, additive shares Gen_Additive_SS
:316-327; driven by cross_silo/secagg/sa_fedml_* managers):

1. each client i has a DH keypair; pairwise seed s_ij = agree(sk_i, pk_j).
2. client i uploads  y_i = x_i + b_i + sum_{j>i} PRG(s_ij) - sum_{j<i} PRG(s_ji)
   (all in the field); pairwise masks cancel in the sum.
3. self-mask seed b_i is Shamir-shared to all clients; if i drops out, t+1
   survivors reconstruct b_i's *pairwise* seeds instead; if i survives, they
   reconstruct b_i and subtract it.

Host-side crypto (numpy mod-p); the masked vectors are ordinary int64 arrays
that ride the normal comm layer. TPU note: masking/unmasking is elementwise
add mod p — O(D) on CPU is fine; the heavy part (the sum) stays on device.

SECURITY SCOPE: this module implements the *protocol structure* for
simulation and testing, not production-grade cryptography. Key agreement is
DH over the 31-bit field prime with generator 5 and the masks come from a
non-cryptographic PRG (np.random.Philox) — trivially breakable by a real
adversary. For real cross-silo deployments swap the `agree`/`prg_mask`
primitives for X25519 key agreement + a keyed PRF (e.g. HKDF + ChaCha20)
behind the same interface; the message flow and dropout recovery are
unchanged by that substitution.
"""
from __future__ import annotations

import dataclasses
import hashlib
from typing import Optional

import numpy as np

from .finite import (
    DEFAULT_PRIME, dequantize, prg_mask, quantize, shamir_reconstruct,
    shamir_share,
)

_G = 5  # public DH generator (reference: my_pk_gen uses g**sk mod p)


def premask_sparsify(x: np.ndarray, ratio: float) -> np.ndarray:
    """Quantize-then-mask compression leg (ISSUE 14): keep the top-k |values|
    of the float vector and zero the rest, BEFORE quantize+mask. Masked
    vectors are uniformly random field elements, so lossy compression can
    only live on this side of the mask; the kept coordinates then ride the
    shared field scale (finite.quantize(q_bits)) unchanged, which is what
    makes the masked compressed aggregate unmask to EXACTLY the plain
    quantize-sum-dequantize of the same sparsified vectors. Numpy-only so
    mpc/ stays jax-free."""
    flat = np.asarray(x, np.float64).ravel()
    if not 0.0 < float(ratio) <= 1.0:
        raise ValueError(f"premask_sparsify ratio must be in (0, 1]; got "
                         f"{ratio!r}")
    if flat.size == 0:
        return flat.reshape(np.shape(x))
    if not np.all(np.isfinite(flat)):
        raise ValueError("premask_sparsify: non-finite values in the update")
    k = max(1, int(flat.size * float(ratio)))
    if k >= flat.size:
        return flat.reshape(np.shape(x))
    idx = np.argpartition(np.abs(flat), -k)[-k:]
    out = np.zeros_like(flat)
    out[idx] = flat[idx]
    return out.reshape(np.shape(x))


def derive_round_key(seed: int, round_salt: int, label: bytes = b"mask") -> int:
    """Per-round PRG key: SHA-256(label || seed || salt) truncated to 62 bits.

    Additive salting (seed + salt) lets distinct (seed, salt) pairs collide
    and produce related keystreams across rounds; hashing makes the per-round
    key derivation a drop-in for a production PRF substitution (HKDF would
    slot in here unchanged)."""
    h = hashlib.sha256(
        label + int(seed).to_bytes(16, "little", signed=False)
        + int(round_salt).to_bytes(8, "little", signed=True)
    ).digest()
    return int.from_bytes(h[:8], "little") >> 2


def _share_pad(pair_secret: int, owner: int, holder: int, field: str,
               size: int, p: int = DEFAULT_PRIME) -> np.ndarray:
    """Deterministic field-element pad for encrypting one routed share
    payload: both endpoints of the (owner, holder) pair derive it from their
    DH secret; the routing server cannot. `field` (e.g. "b" vs "sk")
    domain-separates the keystream — reusing one pad for both payloads would
    be a two-time pad leaking their difference (shares of b_i - sk_i) to
    the router."""
    key = derive_round_key(pair_secret, owner * 0x10001 + holder,
                           label=b"share-enc:" + field.encode())
    return prg_mask(key, size, p)


def encrypt_share(share: np.ndarray, pair_secret: int, owner: int,
                  holder: int, field: str, p: int = DEFAULT_PRIME
                  ) -> np.ndarray:
    """Encrypt a Shamir share (field elements) to its holder so the routing
    server never sees plaintext shares (a server holding t+1 plaintext sk
    shares could reconstruct any client's masks and unmask individual
    updates — the aggregator is SecAgg's primary adversary)."""
    s = np.mod(np.asarray(share, np.int64), p)
    return (s + _share_pad(pair_secret, owner, holder, field, s.size, p)) % p


def decrypt_share(cipher: np.ndarray, pair_secret: int, owner: int,
                  holder: int, field: str, p: int = DEFAULT_PRIME
                  ) -> np.ndarray:
    c = np.mod(np.asarray(cipher, np.int64), p)
    return (c - _share_pad(pair_secret, owner, holder, field, c.size, p)) % p


@dataclasses.dataclass
class SecAggClient:
    """One participant's key material + masking logic."""
    idx: int
    num_clients: int
    threshold: int                      # Shamir t (t+1 reconstructors needed)
    p: int = DEFAULT_PRIME
    q_bits: int = 16
    seed: Optional[int] = None

    def __post_init__(self):
        rng = np.random.default_rng(self.seed)
        self.sk = int(rng.integers(2, self.p - 2))
        self.pk = pow(_G, self.sk, self.p)
        # the self-mask seed is Shamir-shared, i.e. reconstructed mod p —
        # it must live in the field or reconstruction returns seed mod p
        self.self_seed = int(rng.integers(0, self.p))
        self._rng = rng

    # --- round 0: keys
    def public_key(self) -> int:
        return self.pk

    def agree(self, peer_pk: int) -> int:
        """DH shared secret -> PRG seed (reference: my_key_agreement,
        secagg.py:337-342)."""
        return pow(peer_pk, self.sk, self.p) % (2**62)

    # --- round 1: share the self-mask seed (and sk, for dropout recovery)
    def share_self_seed(self) -> np.ndarray:
        """Shamir shares [n, 1] of the self-mask seed, one per client."""
        return shamir_share(
            np.asarray([self.self_seed], np.int64),
            self.num_clients, self.threshold, self._rng, self.p,
        )

    def share_sk(self) -> np.ndarray:
        """Shamir shares [n, 1] of the DH secret key. If this client drops
        mid-round, t+1 survivors' shares let the server reconstruct sk and
        derive the pairwise seeds to strip (reference:
        sa_fedml_server_manager.py's ss_others flow)."""
        return shamir_share(
            np.asarray([self.sk], np.int64),
            self.num_clients, self.threshold, self._rng, self.p,
        )

    # --- round 2: masked input
    def mask(self, x: np.ndarray, peer_pks: dict[int, int],
             round_salt: int = 0) -> np.ndarray:
        """y_i = quantize(x_i) + PRG(H(b_i,salt)) + sum_{j>i} PRG(H(s_ij,salt))
        - sum_{j<i}. `round_salt` rotates every mask per round (hash-derived
        key, see derive_round_key) so the same key material serves many
        rounds without mask reuse.

        Validates the field magnitude budget before masking: the unmasked
        SUM over all n clients must stay below p/2 after the 2^q_bits
        quantization scale, or it silently wraps mod p and corrupts the
        aggregate. Raises with remediation instead of wrapping."""
        x = np.asarray(x, np.float64)
        max_abs = float(np.max(np.abs(x))) if x.size else 0.0
        budget = (self.p / 2.0) / (1 << self.q_bits)
        if max_abs * self.num_clients >= budget:
            raise ValueError(
                f"secagg field overflow: max|x|={max_abs:.4g} x n="
                f"{self.num_clients} clients exceeds the aggregate budget "
                f"p/2^(q_bits+1)={budget:.4g}. Lower q_bits, or send "
                f"normalized weights (n_i/n_total) instead of raw sample "
                f"counts (SecAggClientManager does this when weight_norm "
                f"is set).")
        D = x.size
        y = quantize(x, self.q_bits, self.p)
        key = derive_round_key(self.self_seed, round_salt)
        y = (y + prg_mask(key, D, self.p)) % self.p
        for j, pk in peer_pks.items():
            if j == self.idx:
                continue
            pair = prg_mask(derive_round_key(self.agree(pk), round_salt),
                            D, self.p)
            y = (y + pair) % self.p if j > self.idx else (y - pair) % self.p
        return y


class SecAggServer:
    """Aggregates masked inputs; recovers from dropouts with survivor shares
    (reference flow: cross_silo/secagg/sa_fedml_server_manager.py)."""

    def __init__(self, num_clients: int, threshold: int, dim: int,
                 p: int = DEFAULT_PRIME, q_bits: int = 16):
        self.n, self.t, self.D = num_clients, threshold, dim
        self.p, self.q_bits = p, q_bits

    def aggregate(
        self,
        masked: dict[int, np.ndarray],             # surviving i -> y_i
        self_seed_shares: dict[int, dict[int, np.ndarray]],
        # self_seed_shares[holder][owner] = holder's share of owner's b seed
        pairwise_seeds_of_dropped: dict[int, dict[int, int]],
        # dropped j -> {peer i: s_ij} reconstructed by survivors
        weights: Optional[np.ndarray] = None,
        round_salt: int = 0,
    ) -> np.ndarray:
        """Sum surviving masked vectors, strip surviving clients' self-masks
        (reconstructed from shares) and dropped clients' pairwise masks.
        `round_salt` must match the salt the clients masked with."""
        survivors = sorted(masked)
        agg = np.zeros(self.D, np.int64)
        for i in survivors:
            agg = (agg + masked[i]) % self.p

        # subtract each survivor's self-mask b_i
        for i in survivors:
            share_rows = []
            holders = []
            for h in survivors:
                if i in self_seed_shares.get(h, {}):
                    holders.append(h)
                    share_rows.append(self_seed_shares[h][i])
                if len(holders) == self.t + 1:
                    break
            if len(holders) < self.t + 1:
                raise ValueError(f"not enough shares to unmask client {i}")
            seed = int(shamir_reconstruct(
                np.stack([r.reshape(-1) for r in share_rows]), holders, self.p
            )[0])
            agg = (agg - prg_mask(derive_round_key(seed, round_salt),
                                  self.D, self.p)) % self.p

        # strip pairwise masks involving dropped clients
        for j, seeds in pairwise_seeds_of_dropped.items():
            for i in survivors:
                if i not in seeds:
                    continue
                pair = prg_mask(derive_round_key(seeds[i], round_salt),
                                self.D, self.p)
                # client i applied +pair if j > i else -pair; remove it
                agg = (agg - pair) % self.p if j > i else (agg + pair) % self.p

        return dequantize(agg, self.q_bits, self.p)

    @staticmethod
    def reconstruct_sk(sk_shares: dict[int, np.ndarray],
                       p: int = DEFAULT_PRIME) -> int:
        """Reconstruct a dropped client's DH secret from t+1 survivors'
        shares ({holder: share})."""
        holders = sorted(sk_shares)
        return int(shamir_reconstruct(
            np.stack([np.asarray(sk_shares[h]).reshape(-1) for h in holders]),
            holders, p)[0])

    @staticmethod
    def pairwise_seed(sk_j: int, pk_i: int, p: int = DEFAULT_PRIME) -> int:
        """s_ij from a reconstructed sk_j and a survivor's public key —
        the same value SecAggClient.agree computes on the other side."""
        return pow(pk_i, sk_j, p) % (2 ** 62)


def secagg_roundtrip(vectors: list[np.ndarray], threshold: Optional[int] = None,
                     drop: Optional[list[int]] = None, seed: int = 0) -> np.ndarray:
    """Reference-style end-to-end driver (the shape of
    cross_silo/secagg/*'s message exchange, in-process): returns the sum of
    the surviving clients' vectors, computed only from masked data."""
    n, D = len(vectors), vectors[0].size
    t = threshold if threshold is not None else max(1, n // 2)
    drop = set(drop or [])
    clients = [SecAggClient(i, n, t, seed=seed + i) for i in range(n)]
    pks = {i: c.public_key() for i, c in enumerate(clients)}

    shares = {}  # holder -> owner -> share
    all_shares = {i: c.share_self_seed() for i, c in enumerate(clients)}
    for holder in range(n):
        if holder in drop:
            continue
        shares[holder] = {owner: all_shares[owner][holder]
                          for owner in range(n) if owner not in drop}

    masked = {i: c.mask(vectors[i], pks)
              for i, c in enumerate(clients) if i not in drop}

    # survivors reconstruct the *pairwise* seeds of dropped clients (in the
    # real protocol these come from shares of sk_j; the math is identical)
    pair_seeds = {j: {i: clients[j].agree(pks[i])
                      for i in range(n) if i not in drop}
                  for j in drop}

    server = SecAggServer(n, t, D)
    return server.aggregate(masked, shares, pair_seeds)
