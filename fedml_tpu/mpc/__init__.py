"""Secure multi-party computation kernels for federated aggregation.

TPU-native replacement for the reference's `core/mpc/` (reference:
core/mpc/secagg.py 395 LoC, core/mpc/lightsecagg.py 205 LoC, used by the
cross_silo/{secagg,lightsecagg}/ manager variants and the Android C++
LightSecAgg). Crypto runs host-side on vectorized numpy mod-p arrays; masked
updates flow through the normal comm/aggregation path.
"""
from .finite import (
    DEFAULT_PRIME, dequantize, lagrange_coeffs, lcc_decode, lcc_encode,
    modular_inv, prg_mask, quantize, shamir_reconstruct, shamir_share,
)
from .lightsecagg import (
    aggregate_encoded_masks, decode_aggregate_mask, lightsecagg_roundtrip,
    mask_encoding,
)
from .secagg import SecAggClient, SecAggServer, secagg_roundtrip

__all__ = [
    "DEFAULT_PRIME", "quantize", "dequantize", "modular_inv", "prg_mask",
    "shamir_share", "shamir_reconstruct", "lagrange_coeffs", "lcc_encode",
    "lcc_decode", "SecAggClient", "SecAggServer", "secagg_roundtrip",
    "mask_encoding", "aggregate_encoded_masks", "decode_aggregate_mask",
    "lightsecagg_roundtrip",
]
