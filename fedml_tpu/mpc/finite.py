"""Finite-field primitives for secure aggregation — vectorized numpy mod-p.

Replaces the reference's scalar/python finite-field toolkit (reference:
core/mpc/secagg.py:8-79 modular_inv/divmod/PI/Lagrange-coefficients;
quantization my_q/my_q_inv :344-383; Shamir/BGW :164-212, additive shares
:316-327). All arithmetic here is batched numpy int64 with explicit mod-p
reductions, so share generation/reconstruction over million-parameter vectors
is array ops, not per-coefficient python loops.

The default prime fits signed int64 products via Python-int fallback where
needed; 2**31-1 (Mersenne) keeps products within int64 exactly.
"""
from __future__ import annotations

import numpy as np

DEFAULT_PRIME = 2**31 - 1  # Mersenne prime: a*b fits in int64 before reduction


def modular_inv(a: np.ndarray | int, p: int = DEFAULT_PRIME):
    """Fermat inverse a^(p-2) mod p (reference: secagg.py:8-22 uses an
    iterative EEA per scalar). Arrays take the native C++ batch kernel when
    available (native/fedml_native.cpp ff_modinv_batch — 128-bit mulmod, no
    per-element python pow); python-int pow is the fallback."""
    if isinstance(a, (int, np.integer)):
        return pow(int(a), p - 2, p)
    from ..native import modinv_batch

    out = modinv_batch(a, p)
    if out is not None:
        return out
    return np.array([pow(int(x), p - 2, p) for x in np.asarray(a).ravel()],
                    dtype=np.int64).reshape(np.shape(a))


def quantize(x: np.ndarray, q_bits: int = 16, p: int = DEFAULT_PRIME) -> np.ndarray:
    """Float -> field element: round(x * 2^q), negatives wrap to p - |.|
    (reference: my_q, secagg.py:344-349)."""
    scaled = np.round(np.asarray(x, np.float64) * (1 << q_bits)).astype(np.int64)
    return np.mod(scaled, p)


def dequantize(xq: np.ndarray, q_bits: int = 16,
               p: int = DEFAULT_PRIME) -> np.ndarray:
    """Field element -> float: values above p//2 are negative wrap-arounds
    (reference: my_q_inv + transform_finite_to_tensor, secagg.py:359-383).
    The p//2 split supports sums whose magnitude stays below p/2^(q_bits+1)."""
    xq = np.mod(np.asarray(xq, np.int64), p)
    half = p // 2
    signed = np.where(xq > half, xq - p, xq)
    return signed.astype(np.float64) / (1 << q_bits)


def _powers(points: np.ndarray, deg: int, p: int) -> np.ndarray:
    """Vandermonde rows [len(points), deg+1] mod p."""
    out = np.ones((len(points), deg + 1), dtype=np.int64)
    for j in range(1, deg + 1):
        out[:, j] = (out[:, j - 1] * points) % p
    return out


def shamir_share(secret: np.ndarray, n: int, t: int, rng: np.random.Generator,
                 p: int = DEFAULT_PRIME) -> np.ndarray:
    """Shamir t-of-n sharing of a vector secret (reference: BGW_encoding,
    secagg.py:164-178). Returns shares [n, D]; share i evaluates the degree-t
    polynomial at point i+1."""
    secret = np.mod(np.asarray(secret, np.int64), p)
    D = secret.size
    coeffs = np.concatenate(
        [secret.reshape(1, D),
         rng.integers(0, p, size=(t, D), dtype=np.int64)], axis=0
    )  # [t+1, D]
    points = np.arange(1, n + 1, dtype=np.int64)
    V = _powers(points, t, p)  # [n, t+1]
    # mod-p matmul: accumulate per degree to stay in int64
    shares = np.zeros((n, D), dtype=np.int64)
    for j in range(t + 1):
        shares = (shares + V[:, j : j + 1] * coeffs[j : j + 1]) % p
    return shares


def shamir_reconstruct(shares: np.ndarray, idxs: list[int],
                       p: int = DEFAULT_PRIME) -> np.ndarray:
    """Reconstruct the secret from >= t+1 shares via Lagrange at 0
    (reference: BGW_decoding + gen_BGW_lambda_s, secagg.py:180-212).
    The basis coefficients come from the native C++ kernel when available
    (native/fedml_native.cpp ff_lagrange_at_zero) — reconstruction over many
    holders is the SecAgg server's per-round hot loop."""
    points = np.asarray([i + 1 for i in idxs], dtype=np.int64)
    k = len(points)
    from ..native import lagrange_at_zero

    lam = lagrange_at_zero(points, p)
    if lam is None:  # pure-python fallback
        lam = np.ones(k, dtype=np.int64)
        for i in range(k):
            num, den = 1, 1
            for j in range(k):
                if i == j:
                    continue
                num = (num * (-points[j] % p)) % p
                den = (den * ((points[i] - points[j]) % p)) % p
            lam[i] = (num * modular_inv(int(den), p)) % p
    out = np.zeros(shares.shape[1], dtype=np.int64)
    for i in range(k):
        out = (out + int(lam[i]) * shares[i]) % p
    return out


def lagrange_coeffs(alpha_s: np.ndarray, beta_s: np.ndarray,
                    p: int = DEFAULT_PRIME) -> np.ndarray:
    """U[i,j] = prod_{l!=j} (alpha_i - beta_l) / (beta_j - beta_l) mod p
    (reference: gen_Lagrange_coeffs, secagg.py:59-80)."""
    a = np.asarray(alpha_s, np.int64)
    b = np.asarray(beta_s, np.int64)
    U = np.zeros((len(a), len(b)), dtype=np.int64)
    for i in range(len(a)):
        for j in range(len(b)):
            num, den = 1, 1
            for l in range(len(b)):
                if l == j:
                    continue
                num = (num * ((int(a[i]) - int(b[l])) % p)) % p
                den = (den * ((int(b[j]) - int(b[l])) % p)) % p
            U[i, j] = (num * modular_inv(den, p)) % p
    return U


def lcc_encode(X: np.ndarray, alpha_s: np.ndarray, beta_s: np.ndarray,
               p: int = DEFAULT_PRIME) -> np.ndarray:
    """Lagrange-coded computing encode: X [K, D] chunks -> evaluations at
    alpha points [N, D] (reference: LCC_encoding_with_points, secagg.py:41-48)."""
    U = lagrange_coeffs(alpha_s, beta_s, p)  # [N, K]
    N, D = U.shape[0], X.shape[1]
    out = np.zeros((N, D), dtype=np.int64)
    for j in range(U.shape[1]):
        out = (out + U[:, j : j + 1] * X[j : j + 1]) % p
    return out


def lcc_decode(f_eval: np.ndarray, eval_points: np.ndarray,
               target_points: np.ndarray, p: int = DEFAULT_PRIME) -> np.ndarray:
    """Decode evaluations back to values at target points (reference:
    LCC_decoding_with_points, secagg.py:50-57)."""
    U = lagrange_coeffs(target_points, eval_points, p)
    K, D = U.shape[0], f_eval.shape[1]
    out = np.zeros((K, D), dtype=np.int64)
    for j in range(U.shape[1]):
        out = (out + U[:, j : j + 1] * f_eval[j : j + 1]) % p
    return out


def prg_mask(seed: int, size: int, p: int = DEFAULT_PRIME) -> np.ndarray:
    """Deterministic pseudo-random field vector from a shared seed (the
    reference uses np.random masks keyed by agreed secrets)."""
    return np.random.default_rng(seed % (2**63)).integers(
        0, p, size=size, dtype=np.int64
    )


# --------------------------------------------------- wire packing (ISSUE 14)
# THE shared quantize-then-mask contract for the codec plane: compression of
# a secagg upload must happen BEFORE masking (lossy sparsify of the float
# vector, then `quantize(q_bits)` — the ONE field scale every client already
# shares), because a masked vector is uniformly random in [0, p) and nothing
# lossy can touch it without corrupting the unmasked sum. What the wire CAN
# do losslessly is representation: the default prime fits 31 bits, so the
# int64 field vectors that ride C2S_SA_MASKED pack into uint32 for an exact
# 2x (comm/codec.py's `field_pack` codec consumes these two functions; the
# roundtrip is bitwise, so the unmasked aggregate is bitwise unchanged —
# pinned in tests/test_wire_codec.py).
def pack_field(xq: np.ndarray, p: int = DEFAULT_PRIME) -> np.ndarray:
    """Lossless uint32 wire packing of a field vector (values in [0, p),
    p <= 2^32). Out-of-range values mean the input is NOT a reduced field
    vector — refuse rather than truncate bits silently."""
    if p > 2**32:
        raise ValueError(
            f"pack_field: prime {p} exceeds 32 bits — uint32 packing would "
            "truncate; use the dense int64 representation")
    a = np.asarray(xq)
    if a.dtype.kind not in "iu":
        raise ValueError(
            f"pack_field expects integer field elements; got dtype {a.dtype}")
    if a.size and (int(a.min()) < 0 or int(a.max()) >= p):
        raise ValueError(
            f"pack_field: values outside [0, {p}) — not a mod-p reduced "
            "vector (mask before packing)")
    return a.astype(np.uint32)


def unpack_field(buf: np.ndarray, p: int = DEFAULT_PRIME) -> np.ndarray:
    """Inverse of pack_field: uint32 wire form -> int64 field vector."""
    a = np.asarray(buf)
    if a.dtype != np.uint32:
        raise ValueError(
            f"unpack_field expects the uint32 wire form; got {a.dtype}")
    out = a.astype(np.int64)
    if out.size and int(out.max()) >= p:
        raise ValueError(
            f"unpack_field: values outside [0, {p}) — corrupted frame or "
            "prime mismatch between sender and receiver")
    return out
