"""LightSecAgg — MDS-coded mask sharing (one-shot dropout tolerance).

Protocol (So et al. 2021), the reference's second MPC kernel (reference:
core/mpc/lightsecagg.py — mask_encoding :97-124, compute_aggregate_encoded_mask
:126-132, aggregate_models_in_finite :134-148; C++ twin in the Android SDK,
android/fedmlsdk/MobileNN/src/security/LightSecAgg.cpp):

1. client i draws mask z_i, splits into K chunks, pads with T random chunks,
   LCC-encodes to N shares; sends share j to client j.
2. client i uploads x_i + z_i (quantized, mod p).
3. each surviving client j sends the server sum_i(encoded share_ij) over the
   surviving set U; from any K+T of these the server LCC-decodes
   sum_{i in U} z_i and subtracts it.

vs SecAgg: dropout recovery costs ONE decode instead of per-client Shamir
reconstructions.
"""
from __future__ import annotations

import numpy as np

from .finite import DEFAULT_PRIME, dequantize, lcc_decode, lcc_encode, quantize


def _chunk(z: np.ndarray, K: int) -> np.ndarray:
    """Pad to a K multiple and reshape to [K, D/K]."""
    d = z.size
    per = -(-d // K)
    padded = np.zeros(K * per, np.int64)
    padded[:d] = z
    return padded.reshape(K, per)


def mask_encoding(d: int, N: int, K: int, T: int, rng: np.random.Generator,
                  p: int = DEFAULT_PRIME) -> tuple[np.ndarray, np.ndarray]:
    """Draw mask z [d] and produce its N encoded shares [N, ceil(d/K)]
    (reference: mask_encoding, lightsecagg.py:97-124: [z chunks; T random]
    LCC-encoded at N points)."""
    z = rng.integers(0, p, size=d, dtype=np.int64)
    chunks = _chunk(z, K)                                     # [K, per]
    noise = rng.integers(0, p, size=(T, chunks.shape[1]), dtype=np.int64)
    X = np.concatenate([chunks, noise], axis=0)               # [K+T, per]
    alpha = np.arange(1, N + 1, dtype=np.int64)               # eval points
    beta = np.arange(N + 1, N + 1 + K + T, dtype=np.int64)    # interp points
    shares = lcc_encode(X, alpha, beta, p)                    # [N, per]
    return z, shares


def aggregate_encoded_masks(shares_held: list[np.ndarray],
                            p: int = DEFAULT_PRIME) -> np.ndarray:
    """Client j sums the shares it holds over the surviving set (reference:
    compute_aggregate_encoded_mask, lightsecagg.py:126-132)."""
    out = np.zeros_like(shares_held[0])
    for s in shares_held:
        out = (out + s) % p
    return out


def decode_aggregate_mask(agg_shares: dict[int, np.ndarray], N: int, K: int,
                          T: int, d: int, p: int = DEFAULT_PRIME) -> np.ndarray:
    """From any K+T clients' aggregate-encoded masks, decode sum(z_i)
    (reference: the server-side decode in lsa_fedml_server_manager)."""
    idxs = sorted(agg_shares)[: K + T]
    if len(idxs) < K + T:
        raise ValueError(f"need {K + T} surviving shares, got {len(agg_shares)}")
    f_eval = np.stack([agg_shares[j] for j in idxs])          # [K+T, per]
    eval_points = np.asarray([j + 1 for j in idxs], np.int64)
    beta = np.arange(N + 1, N + 1 + K + T, dtype=np.int64)
    decoded = lcc_decode(f_eval, eval_points, beta[:K + T], p)  # values at beta
    return decoded[:K].reshape(-1)[:d]


def lightsecagg_roundtrip(vectors: list[np.ndarray], K: int = 2, T: int = 1,
                          drop: list[int] | None = None, q_bits: int = 16,
                          seed: int = 0, p: int = DEFAULT_PRIME) -> np.ndarray:
    """End-to-end in-process protocol run; returns sum over surviving clients
    computed only from masked uploads + encoded mask shares."""
    n, d = len(vectors), vectors[0].size
    drop = set(drop or [])
    survivors = [i for i in range(n) if i not in drop]
    if len(survivors) < K + T:
        raise ValueError("too many dropouts for (K, T)")

    rngs = [np.random.default_rng(seed + i) for i in range(n)]
    masks, shares = {}, {}
    for i in range(n):
        masks[i], shares[i] = mask_encoding(d, n, K, T, rngs[i], p)

    # masked uploads from survivors
    agg = np.zeros(d, np.int64)
    for i in survivors:
        y = (quantize(vectors[i], q_bits, p) + masks[i]) % p
        agg = (agg + y) % p

    # each survivor j sends sum over survivors of share_ij
    agg_shares = {
        j: aggregate_encoded_masks([shares[i][j] for i in survivors], p)
        for j in survivors
    }
    z_sum = decode_aggregate_mask(agg_shares, n, K, T, d, p)
    agg = (agg - z_sum) % p
    return dequantize(agg, q_bits, p)
