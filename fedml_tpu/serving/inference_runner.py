"""HTTP inference runner — /predict (+SSE streaming), /ready, /info,
/swap over stdlib http.server.

(reference: serving/fedml_inference_runner.py:4-24 — FastAPI + uvicorn
exposing POST /predict -> {"generated_text": ...} and GET /ready. FastAPI
is not in this image, so the same contract rides ThreadingHTTPServer: every
request handled on its own thread, the predictor itself serializes device
work through jit.)

Fleet surface (ISSUE 9):
- POST /predict with `"stream": true` answers `text/event-stream`: one
  `data: {"token": t, "index": i}` event per generated token AS the
  decode engine retires it, then a final `data: {"done": true,
  "generated_tokens": [...]}` event. Time to the first streamed token
  lands in the `serving.stream_ttft` histogram. Errors BEFORE the first
  event keep their status codes (400/409/500); an error after the stream
  opened is surfaced as a terminal `data: {"error": ...}` event — a cut
  or error-terminated stream NEVER carries `done`, so a client (or the
  gateway's failover relay) can always tell a half-stream from a
  complete one.
- GET /info reports `{"model_version", "queue_depth", "slots_active",
  "decode_queue", "draining", "kv_page_size", "prefix_digests"}` — the
  version signal the gateway's rolling updater converges on, plus the
  load snapshot operators and telemetry read (routing itself is
  least-loaded over the GATEWAY's own per-replica in-flight accounting,
  not /info polls). `kv_page_size`/`prefix_digests` are the
  prefix-affinity residency advert (ISSUE 16): which first-page
  prefix-cache keys this replica's engine holds. The same advert rides
  every /predict response as `X-KV-Page-Size`/`X-Prefix-Digest`
  headers, so the gateway's hint stays fresh off the warm path alone.
- POST /swap `{"store": <utils.artifacts.store_spec>, "name": ...,
  "version": N}` fetches round-N adapters from the artifact store and
  hot-swaps them into the live predictor (no restart; engine story in
  serving/engine.py swap_adapters). A version conflict or layout
  mismatch is a 400; success returns the new `model_version`.
- `stop()` drains first: the engine finishes in-flight decodes (bounded
  by the predictor's `drain_timeout_s`) before teardown, so scale-down
  never errors a request that was already decoding. `kill()` is the
  CHAOS path — the process-death simulation (socket closed now,
  in-flight connections severed, nothing drained) that the
  `FaultSpec.replica_kill` schedule (comm/chaos.py) triggers mid-stream.
"""
from __future__ import annotations

import json
import logging
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from ..utils import metrics as _mx
from ..utils.events import recorder
from .predictor import Predictor

log = logging.getLogger(__name__)
DEFAULT_PORT = 2345  # reference: fedml_inference_runner.py port


class FedMLInferenceRunner:
    """Serve a Predictor over HTTP.

    run() blocks (reference behavior); start()/stop() run it on a daemon
    thread for embedding in tests and larger processes.

    `chaos` (a comm.chaos.FaultSpec) + `chaos_rank` arm this replica's
    `replica_kill` schedule: after streaming its n-th token the replica
    dies abruptly (kill()), which is how the mid-stream failover tests
    and the chaos bench make a replica fail at a deterministic point."""

    def __init__(self, predictor: Predictor, host: str = "127.0.0.1",
                 port: int = DEFAULT_PORT, chaos=None, chaos_rank: int = 0):
        self.predictor = predictor
        self._chaos = chaos
        self._chaos_rank = int(chaos_rank)
        self._chaos_tokens = 0
        self._chaos_lock = threading.Lock()
        runner = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # quiet the default stderr spam
                log.debug("serving: " + fmt, *args)

            def _send(self, code: int, payload: dict,
                      headers: Optional[dict] = None) -> None:
                # a chaos-killed replica runs no cleanup: connections that
                # were in flight when the kill landed are severed before
                # any response byte (real process death answers nobody)
                if runner._killed:
                    raise ConnectionError("replica killed")
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def _residency_headers(self) -> Optional[dict]:
                """Prefix-affinity advert for the routing gateway: the
                engine's page geometry + its resident first-page prefix
                digests, stamped on every /predict response (and the SSE
                head) so the gateway learns residency off the warm path
                without polling /info. None for non-engine predictors
                and contiguous/prefix-off engines — the headers' absence
                IS the "no affinity signal" case."""
                eng = getattr(runner.predictor, "engine", None)
                if eng is None or not getattr(eng, "kv_page_size", 0):
                    return None
                return {"X-KV-Page-Size": str(eng.kv_page_size),
                        "X-Prefix-Digest": ",".join(eng.prefix_digests())}

            def do_GET(self):
                if runner._killed:
                    self.close_connection = True
                    return      # severed: no response, socket closes
                if self.path == "/ready":
                    self._send(200, {"status": "Success"})
                elif self.path == "/info":
                    # the fleet-control signal: version for the rolling
                    # updater's convergence check, load for operators and
                    # telemetry — the gateway routes on its own in-flight
                    # counts, it does not poll this (engine attrs read
                    # lock-free — a snapshot, not a transaction)
                    eng = getattr(runner.predictor, "engine", None)
                    self._send(200, {
                        "model_version": getattr(
                            runner.predictor, "model_version", None),
                        "queue_depth": runner._inflight.value(),
                        "slots_active": (
                            sum(s is not None for s in eng._slots)
                            if eng is not None else None),
                        "decode_queue": (len(eng._waiting)
                                         if eng is not None else None),
                        "draining": (bool(eng._draining)
                                     if eng is not None else False),
                        "kv_page_size": (getattr(eng, "kv_page_size", 0)
                                         if eng is not None else 0),
                        "prefix_digests": (eng.prefix_digests()
                                           if eng is not None else []),
                    })
                elif self.path == "/metrics":
                    # replicas expose the process registry (request latency,
                    # queue depth, compile-vs-serve) in Prometheus text
                    from ..utils.prometheus import write_metrics_response

                    write_metrics_response(self)
                else:
                    self._send(404, {"error": f"no route {self.path}"})

            def _error_code(self, e: BaseException) -> int:
                # input errors are the CLIENT's (400); anything else is
                # this replica failing (500). The split matters to the
                # gateway both ways: a 4xx never kills a replica (so
                # hostile input can't drain the pool), and internal
                # failures must be 5xx so failover happens. Only the
                # dedicated InvalidRequest (raised at the predictors'
                # validation sites) and a missing-field KeyError count
                # as client errors — matching builtin ValueError/
                # TypeError would misfile internal JAX shape errors.
                # StaleVersion gets its own 409: the replica is healthy,
                # the request just pinned a model_version a SIBLING
                # serves — the gateway reroutes instead of surfacing.
                # A body that isn't JSON is likewise the client's (the
                # decode error can only come from the request body here);
                # 500 would let one garbage request suspect every replica
                # it is retried on and drain the ready pool.
                from .predictor import InvalidRequest, StaleVersion

                if isinstance(e, StaleVersion):
                    return 409
                return (400 if isinstance(e, (InvalidRequest, KeyError,
                                              json.JSONDecodeError))
                        else 500)

            def do_POST(self):
                if runner._killed:
                    self.close_connection = True
                    return      # severed: no response, socket closes
                if self.path == "/swap":
                    self._do_swap()
                    return
                if self.path != "/predict":
                    self._send(404, {"error": f"no route {self.path}"})
                    return
                # queue depth = requests in flight on the threading server
                # (each request holds a thread; a per-request predictor
                # serializes device work through jit so depth > 1 means
                # queueing; an engine-backed predictor blocks each request
                # on its own ticket instead, so depth counts slots+queue).
                # AtomicCounter with the gauge bound: += on a
                # ThreadingHTTPServer would race and drift permanently, and
                # publishing the gauge outside the counter's lock would let
                # two finishing threads reorder their writes.
                t0 = time.perf_counter()
                runner._inflight.inc()
                _mx.inc("serving.requests")
                try:
                    with recorder.span("serving.request", path=self.path):
                        n = int(self.headers.get("Content-Length", 0))
                        input_json = json.loads(self.rfile.read(n) or b"{}")
                        if not isinstance(input_json, dict):
                            from .predictor import InvalidRequest

                            raise InvalidRequest(
                                "request body must be a JSON object; got "
                                f"{type(input_json).__name__}")
                        if input_json.get("stream"):
                            self._do_stream(input_json, t0)
                            return
                        result = runner.predictor.predict(input_json)
                        if not isinstance(result, dict):
                            result = {"generated_text": str(result)}
                        # residency read AFTER the predict: this
                        # prompt's own first page is already registered,
                        # so the advert includes it
                        self._send(200, result,
                                   headers=self._residency_headers())
                except ConnectionError as e:
                    # the peer can't receive another byte: the client hung
                    # up, or a chaos kill severed this replica mid-stream.
                    # A _send here would write a SECOND status line into an
                    # already-open SSE body (protocol garbage); just return
                    # — the socket closes and the gateway sees a cut stream
                    log.warning("connection lost mid-request: %s", e)
                    _mx.inc("serving.conn_lost")
                except Exception as e:  # noqa: BLE001 — surface to caller
                    log.exception("predict failed")
                    _mx.inc("serving.errors")
                    payload = {"error": f"{type(e).__name__}: {e}"}
                    code = self._error_code(e)
                    if code == 409:
                        # tell the router what this replica DOES serve
                        payload["model_version"] = getattr(
                            runner.predictor, "model_version", None)
                    self._send(code, payload)
                finally:
                    runner._inflight.dec()
                    _mx.observe("serving.request_s",
                                time.perf_counter() - t0)

            def _do_stream(self, input_json: dict, t0: float) -> None:
                """SSE branch of /predict. The FIRST chunk is pulled
                before any byte is written, so validation errors (and a
                stale version pin) still travel as proper status codes;
                from the second chunk on, failures become a terminal
                `data: {"error": ...}` event — never a fake `done`."""
                from .predictor import InvalidRequest

                ps = getattr(runner.predictor, "predict_stream", None)
                if ps is None:
                    raise InvalidRequest(
                        "this replica's predictor does not stream "
                        "(LM replicas do; classification replicas "
                        "answer /predict without stream)")
                gen = ps(input_json)
                first = next(gen)
                self.send_response(200)
                self.send_header("Content-Type", "text/event-stream")
                self.send_header("Cache-Control", "no-cache")
                # the first chunk was pulled above, so admission already
                # registered this prompt's prefix — the SSE head can
                # advertise residency like the non-stream path
                for k, v in (self._residency_headers() or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                _mx.inc("serving.stream_responses")
                _mx.observe("serving.stream_ttft",
                            time.perf_counter() - t0)
                try:
                    self._emit(first)
                    for chunk in gen:
                        self._emit(chunk)
                except (BrokenPipeError, ConnectionError):
                    raise           # client (or chaos kill) went away
                except Exception as e:  # noqa: BLE001 — headers are sent
                    log.exception("stream failed mid-flight")
                    _mx.inc("serving.errors")
                    # a pinned stream that straddled a hot swap carries
                    # its 409 so the gateway reroutes to a sibling
                    # instead of suspecting this (healthy) replica;
                    # every other mid-flight failure stays a 503
                    code = self._error_code(e)
                    self.wfile.write(
                        b"data: " + json.dumps(
                            {"error": f"{type(e).__name__}: {e}",
                             "code": code if code == 409 else 503}
                        ).encode() + b"\n\n")
                    self.wfile.flush()

            def _emit(self, chunk: dict) -> None:
                # concurrent streams on a killed replica die at their next
                # emit, not just the stream whose token tripped the kill
                if runner._killed:
                    raise ConnectionError("replica killed")
                self.wfile.write(b"data: " + json.dumps(chunk).encode()
                                 + b"\n\n")
                self.wfile.flush()
                if "token" in chunk:
                    runner._chaos_tick()

            def _do_swap(self) -> None:
                """Hot adapter swap: fetch the named artifact from the
                named store and swap it into the live predictor. The
                store handle rides the request (utils/artifacts.py
                store_spec) — the gateway never relays tensor bytes."""
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    body = json.loads(self.rfile.read(n) or b"{}")
                    if not isinstance(body, dict):
                        self._send(400, {"error": "swap body must be a "
                                                  "JSON object"})
                        return
                    swap = getattr(runner.predictor, "swap_adapters", None)
                    if swap is None:
                        self._send(400, {
                            "error": "this replica's predictor has no "
                                     "adapter plane to swap"})
                        return
                    from ..utils.artifacts import store_from_spec

                    store = store_from_spec(dict(body.get("store") or {}))
                    tree = store.get(body["name"])
                    ver = body.get("version")
                    with recorder.span("serving.swap.http",
                                       artifact=body.get("name")):
                        new_ver = swap(
                            tree, version=None if ver is None else int(ver))
                    self._send(200, {"model_version": new_ver})
                except (KeyError, ValueError, TypeError) as e:
                    _mx.inc("serving.errors")
                    self._send(400, {"error": f"{type(e).__name__}: {e}"})
                except Exception as e:  # noqa: BLE001 — replica failing
                    log.exception("swap failed")
                    _mx.inc("serving.errors")
                    self._send(500, {"error": f"{type(e).__name__}: {e}"})

        self._server = ThreadingHTTPServer((host, port), Handler)
        self.port = self._server.server_address[1]  # resolved when port=0
        self._thread: Optional[threading.Thread] = None
        self._serving = False
        self._killed = False
        self._inflight = _mx.AtomicCounter(gauge="serving.queue_depth")

    def _chaos_tick(self) -> None:
        """Count one streamed token against this replica's kill schedule;
        dying means: server down NOW, this connection severed (the raise
        propagates out of the handler and closes the socket abruptly)."""
        if self._chaos is None:
            return
        with self._chaos_lock:
            self._chaos_tokens += 1
            n = self._chaos_tokens
        if self._chaos.replica_killed(self._chaos_rank, n):
            _mx.inc("fed.chaos.replica_kills")
            with recorder.span("serving.chaos.replica_kill",
                               rank=self._chaos_rank, tokens=n):
                self.kill()
            raise ConnectionError(
                f"chaos: replica {self._chaos_rank} killed after "
                f"{n} streamed tokens")

    @property
    def metrics_url(self) -> str:
        """This replica's /metrics scrape URL — what a FleetCollector
        roster entry (utils/obsfleet.py) wants for this process."""
        host = self._server.server_address[0]
        return f"http://{host}:{self.port}/metrics"

    def run(self) -> None:
        log.info("serving on :%d (/predict, /ready, /info, /swap)",
                 self.port)
        self._serving = True
        self._server.serve_forever()

    def start(self) -> "FedMLInferenceRunner":
        self._thread = threading.Thread(target=self.run, daemon=True)
        self._thread.start()
        return self

    def kill(self) -> None:
        """CHAOS: simulate replica process death. The listening socket
        closes immediately and /ready stops answering; the connection
        that tripped the kill is severed (its handler raises); the
        predictor/engine is NOT stopped or drained (a real process death
        runs no cleanup). The deterministic fault the mid-stream
        failover tests aim at."""
        if self._killed:
            return
        self._killed = True
        if self._serving:
            # shutdown() from a handler thread would deadlock only if
            # called synchronously from serve_forever's own thread — these
            # handlers run on their own threads, but be safe and fire it
            # from a dedicated one; server_close() severs the socket now
            threading.Thread(target=self._server.shutdown,
                             daemon=True).start()
        self._server.server_close()

    def stop(self) -> None:
        # shutdown() blocks on an event only serve_forever sets — calling
        # it on a never-started server would deadlock. A chaos-killed
        # server is already down — only the predictor cleanup remains
        # (test teardown; a real dead process has nothing to clean).
        if not self._killed:
            if self._serving:
                self._server.shutdown()
            self._server.server_close()
            if self._thread is not None:
                self._thread.join(timeout=5)
        # an engine-backed predictor owns a decode thread — DRAIN it first
        # (in-flight decodes finish, bounded by the predictor's
        # drain_timeout_s), then shut it down with the HTTP surface, so a
        # scale-down or rolling replacement never kills a request that
        # was already decoding
        stop = getattr(self.predictor, "stop", None)
        if callable(stop):
            # probe the signature instead of catching TypeError — a
            # TypeError raised INSIDE stop(drain=True) must surface, not
            # trigger a second, drainless teardown
            import inspect

            try:
                drains = "drain" in inspect.signature(stop).parameters
            except (TypeError, ValueError):   # builtins/C callables
                drains = False
            stop(drain=True) if drains else stop()
