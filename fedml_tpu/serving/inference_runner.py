"""HTTP inference runner — /predict + /ready over stdlib http.server.

(reference: serving/fedml_inference_runner.py:4-24 — FastAPI + uvicorn
exposing POST /predict -> {"generated_text": ...} and GET /ready. FastAPI
is not in this image, so the same contract rides ThreadingHTTPServer: every
request handled on its own thread, the predictor itself serializes device
work through jit.)
"""
from __future__ import annotations

import json
import logging
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from ..utils import metrics as _mx
from ..utils.events import recorder
from .predictor import Predictor

log = logging.getLogger(__name__)
DEFAULT_PORT = 2345  # reference: fedml_inference_runner.py port


class FedMLInferenceRunner:
    """Serve a Predictor over HTTP.

    run() blocks (reference behavior); start()/stop() run it on a daemon
    thread for embedding in tests and larger processes."""

    def __init__(self, predictor: Predictor, host: str = "127.0.0.1",
                 port: int = DEFAULT_PORT):
        self.predictor = predictor
        runner = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # quiet the default stderr spam
                log.debug("serving: " + fmt, *args)

            def _send(self, code: int, payload: dict) -> None:
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/ready":
                    self._send(200, {"status": "Success"})
                elif self.path == "/metrics":
                    # replicas expose the process registry (request latency,
                    # queue depth, compile-vs-serve) in Prometheus text
                    from ..utils.prometheus import write_metrics_response

                    write_metrics_response(self)
                else:
                    self._send(404, {"error": f"no route {self.path}"})

            def do_POST(self):
                if self.path != "/predict":
                    self._send(404, {"error": f"no route {self.path}"})
                    return
                # queue depth = requests in flight on the threading server
                # (each request holds a thread; a per-request predictor
                # serializes device work through jit so depth > 1 means
                # queueing; an engine-backed predictor blocks each request
                # on its own ticket instead, so depth counts slots+queue).
                # AtomicCounter with the gauge bound: += on a
                # ThreadingHTTPServer would race and drift permanently, and
                # publishing the gauge outside the counter's lock would let
                # two finishing threads reorder their writes.
                t0 = time.perf_counter()
                runner._inflight.inc()
                _mx.inc("serving.requests")
                try:
                    with recorder.span("serving.request", path=self.path):
                        n = int(self.headers.get("Content-Length", 0))
                        input_json = json.loads(self.rfile.read(n) or b"{}")
                        result = runner.predictor.predict(input_json)
                        if not isinstance(result, dict):
                            result = {"generated_text": str(result)}
                        self._send(200, result)
                except Exception as e:  # noqa: BLE001 — surface to caller
                    log.exception("predict failed")
                    _mx.inc("serving.errors")
                    # input errors are the CLIENT's (400); anything else is
                    # this replica failing (500). The split matters to the
                    # gateway both ways: a 4xx never kills a replica (so
                    # hostile input can't drain the pool), and internal
                    # failures must be 5xx so failover happens. Only the
                    # dedicated InvalidRequest (raised at the predictors'
                    # validation sites) and a missing-field KeyError count
                    # as client errors — matching builtin ValueError/
                    # TypeError would misfile internal JAX shape errors.
                    from .predictor import InvalidRequest

                    client_err = isinstance(e, (InvalidRequest, KeyError))
                    self._send(400 if client_err else 500,
                               {"error": f"{type(e).__name__}: {e}"})
                finally:
                    runner._inflight.dec()
                    _mx.observe("serving.request_s",
                                time.perf_counter() - t0)

        self._server = ThreadingHTTPServer((host, port), Handler)
        self.port = self._server.server_address[1]  # resolved when port=0
        self._thread: Optional[threading.Thread] = None
        self._serving = False
        self._inflight = _mx.AtomicCounter(gauge="serving.queue_depth")

    def run(self) -> None:
        log.info("serving on :%d (/predict, /ready)", self.port)
        self._serving = True
        self._server.serve_forever()

    def start(self) -> "FedMLInferenceRunner":
        self._thread = threading.Thread(target=self.run, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        # shutdown() blocks on an event only serve_forever sets — calling
        # it on a never-started server would deadlock
        if self._serving:
            self._server.shutdown()
        self._server.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
        # an engine-backed predictor owns a decode thread — shut it down
        # with the HTTP surface so replicas stop cleanly
        stop = getattr(self.predictor, "stop", None)
        if callable(stop):
            stop()
