"""THE serve-knob registry (ISSUE 13).

One table for every `serve_args` knob: its kind/bounds, its gating
prerequisite, and WHICH surface consumes it — "predictor" knobs must be
mapped by `predictor.lm_predictor_from_serve_knobs` (the shared mapping
`start_replica` and `serving.lm_predictor_from_config` both ride),
"fleet" knobs by `scheduler.fleet_knobs`. Before this registry, the key
set lived three times (config.py's validated set, the predictor mapping,
the fleet mapping) and drifted in PRs 5, 9, and 11 — a knob validated at
load then silently dropped on the deploy path. Now config validation
iterates THIS table, and graftlint's `knob-drift` rule cross-checks the
two consumer functions against it, so a validated-but-unmapped knob
fails lint instead of a review pass.

`KNOBS` stays a PURE LITERAL: graftlint reads it with
`ast.literal_eval`, so the linter never has to import this package (the
Docker build hook lints before any jax wheel exists). This module must
also stay import-light itself — config.py pulls it in at load time and
config load is deliberately jax-free.
"""
from __future__ import annotations

# knob -> spec. Kinds: "int" (min), "num" (strict: >0 vs >=0), "bool",
# "choice" (choices). "requires" names the gating knob whose absence makes
# this one silently dead (refused at config load). "consumer" names the
# mapping that must read the knob: "predictor" =
# predictor.lm_predictor_from_serve_knobs, "fleet" =
# scheduler.fleet_knobs.
KNOBS = {
    "decode_slots":       {"kind": "int", "min": 0,
                           "consumer": "predictor"},
    "engine_max_len":     {"kind": "int", "min": 1,
                           "consumer": "predictor"},
    "engine_fetch_chunk": {"kind": "int", "min": 1,
                           "consumer": "predictor"},
    "engine_eos_id":      {"kind": "int", "min": 0,
                           "consumer": "predictor"},
    "sampler_cache_size": {"kind": "int", "min": 1,
                           "consumer": "predictor"},
    "kv_cache":           {"kind": "bool", "consumer": "predictor"},
    "engine_mp":          {"kind": "int", "min": 1,
                           "consumer": "predictor",
                           "requires": "decode_slots"},
    "kv_page_size":       {"kind": "int", "min": 1,
                           "consumer": "predictor",
                           "requires": "decode_slots"},
    "kv_n_pages":         {"kind": "int", "min": 2,
                           "consumer": "predictor",
                           "requires": "kv_page_size"},
    "prefill_chunk":      {"kind": "int", "min": 0,
                           "consumer": "predictor",
                           "requires": "kv_page_size"},
    "prefix_cache":       {"kind": "bool", "consumer": "predictor",
                           "requires": "kv_page_size"},
    "paged_kernel":       {"kind": "bool", "consumer": "predictor",
                           "requires": "kv_page_size"},
    "spec_decode":        {"kind": "choice", "choices": ["off", "ngram"],
                           "consumer": "predictor",
                           "requires": "kv_page_size"},
    "spec_k":             {"kind": "int", "min": 1,
                           "consumer": "predictor",
                           "requires": "spec_decode"},
    "kv_quant":           {"kind": "choice", "choices": ["off", "int8"],
                           "consumer": "predictor",
                           "requires": "kv_page_size"},
    "admit_batch":        {"kind": "int", "min": 1,
                           "consumer": "predictor",
                           "requires": "decode_slots"},
    "drain_timeout_s":    {"kind": "num", "strict": False,
                           "consumer": "predictor"},
    "affinity_routing":   {"kind": "bool", "consumer": "fleet",
                           "requires": "prefix_cache"},
    "shed_watermark":     {"kind": "num", "strict": False,
                           "consumer": "fleet"},
    "retry_after_s":      {"kind": "num", "strict": True,
                           "consumer": "fleet"},
    "probation_deadline_s": {"kind": "num", "strict": True,
                             "consumer": "fleet"},
    "probe_backoff_s":    {"kind": "num", "strict": True,
                           "consumer": "fleet"},
}


def knob_names() -> set[str]:
    return set(KNOBS)


def consumer_knobs(consumer: str) -> set[str]:
    """Knob names owned by one consumer surface ("predictor"/"fleet")."""
    return {k for k, spec in KNOBS.items() if spec["consumer"] == consumer}


def validate_serve_args(extra: dict) -> None:
    """Validate (and normalize, in place) a `serve_args` knob dict.

    Moved here from config.Config.validate so the key set, kinds, and
    gating live NEXT TO the registry they iterate — config.py calls this
    at load time and cannot drift from the consumer surfaces. Raises
    ValueError with the exact messages the config tests pin.

    serve_args is fully owned by this framework (no reference-YAML
    grab-bag to stay compatible with), so UNKNOWN keys are rejected too —
    a misspelled decode_slots must not pass silently.
    """
    unknown = set(extra) - set(KNOBS)
    if unknown:
        raise ValueError(
            f"unknown serve_args knob(s) {sorted(unknown)}; valid: "
            f"{sorted(KNOBS)}")
    for knob, spec in KNOBS.items():
        val = extra.get(knob)
        if val is None:
            continue
        if spec["kind"] == "bool":
            if not isinstance(val, bool):
                raise ValueError(
                    f"serve_args.{knob} must be a boolean; got {val!r}")
        elif spec["kind"] == "int":
            lo = spec["min"]
            try:
                ok = (not isinstance(val, bool)
                      and int(val) == float(val) and int(val) >= lo)
            except (TypeError, ValueError):
                ok = False
            if not ok:
                raise ValueError(
                    f"serve_args.{knob} must be an integer >= {lo}; "
                    f"got {val!r}")
        elif spec["kind"] == "num":
            strict = spec["strict"]
            try:
                ok = (not isinstance(val, bool)
                      and (float(val) > 0 if strict else float(val) >= 0))
            except (TypeError, ValueError):
                ok = False
            if not ok:
                raise ValueError(
                    f"serve_args.{knob} must be a "
                    f"{'positive' if strict else 'non-negative'} number; "
                    f"got {val!r}")
    # engine_mp only takes effect inside the engine (decode_slots > 0):
    # a config asking for tensor-parallel serving without the engine
    # would silently run single-chip per-request — refuse at load
    # instead (the other engine_* knobs double as per-request knobs,
    # e.g. engine_max_len sizes both paths, so only this one is gated)
    mp_knob = extra.get("engine_mp")
    if mp_knob is not None and int(mp_knob) > 1 \
            and not extra.get("decode_slots"):
        raise ValueError(
            "serve_args.engine_mp > 1 requires decode_slots > 0 — "
            "tensor-parallel serving runs inside the decode engine; "
            "without slots the knob would be silently ignored")
    # paged-cache knobs (serving/engine.py page_size > 0) are gated
    # the same way: each only takes effect inside the paged engine,
    # so a config naming one without its prerequisite would silently
    # serve contiguous/per-request — refuse at load instead
    if extra.get("kv_page_size") and not extra.get("decode_slots"):
        raise ValueError(
            "serve_args.kv_page_size requires decode_slots > 0 — the "
            "paged KV cache lives inside the decode engine; without "
            "slots the knob would be silently ignored")
    for knob in ("kv_n_pages", "prefill_chunk", "prefix_cache"):
        if extra.get(knob) is not None and not extra.get("kv_page_size"):
            raise ValueError(
                f"serve_args.{knob} requires kv_page_size > 0 (the "
                "paged KV cache) — without paging the knob would be "
                "silently ignored")
    # decode-speed knobs (ISSUE 11): the Pallas paged-attention kernel
    # and n-gram speculative decoding both live inside the PAGED engine
    # — same gating discipline, a knob that would be silently ignored
    # is refused at load
    if extra.get("paged_kernel") and not extra.get("kv_page_size"):
        raise ValueError(
            "serve_args.paged_kernel requires kv_page_size > 0 — the "
            "fused kernel reads the paged KV pool in place; without "
            "paging the knob would be silently ignored")
    sd = extra.get("spec_decode")
    if sd is not None:
        # YAML 1.1 reads an unquoted `off` as boolean False — that IS
        # the documented disable spelling, so normalize it instead of
        # rejecting the user's own docs back at them (True has no
        # mode to normalize to: name the quoting problem)
        if sd is False:
            sd = extra["spec_decode"] = "off"
        if sd is True:
            raise ValueError(
                "serve_args.spec_decode: true is not a mode — use "
                "'ngram' (YAML parses unquoted off/on as booleans; "
                "quote the value)")
        if sd not in KNOBS["spec_decode"]["choices"]:
            raise ValueError(
                "serve_args.spec_decode must be 'off' or 'ngram'; "
                f"got {sd!r}")
        if sd != "off" and not extra.get("kv_page_size"):
            raise ValueError(
                "serve_args.spec_decode requires kv_page_size > 0 — "
                "speculative verify-and-rollback rides the paged KV "
                "cache's page table; without paging the knob would "
                "be silently ignored")
    if extra.get("spec_k") is not None and sd in (None, "off"):
        raise ValueError(
            "serve_args.spec_k requires spec_decode: ngram — "
            "the draft length only exists under speculation; "
            "without it the knob would be silently ignored")
    # serving-density knobs (ISSUE 16): int8 KV pages, batched
    # admission, and gateway prefix-affinity routing — same discipline
    kq = extra.get("kv_quant")
    if kq is not None:
        # YAML 1.1 reads unquoted `off` as False — the documented
        # disable spelling, same normalization as spec_decode
        if kq is False:
            kq = extra["kv_quant"] = "off"
        if kq is True:
            raise ValueError(
                "serve_args.kv_quant: true is not a mode — use 'int8' "
                "(YAML parses unquoted off/on as booleans; quote the "
                "value)")
        if kq not in KNOBS["kv_quant"]["choices"]:
            raise ValueError(
                f"serve_args.kv_quant must be 'off' or 'int8'; got {kq!r}")
        if kq != "off" and not extra.get("kv_page_size"):
            raise ValueError(
                "serve_args.kv_quant requires kv_page_size > 0 — int8 "
                "KV storage is a property of the paged pool (per-page-"
                "per-head scales ride the page table); without paging "
                "the knob would be silently ignored")
    ab = extra.get("admit_batch")
    if ab is not None and int(ab) > 1 and not extra.get("decode_slots"):
        raise ValueError(
            "serve_args.admit_batch > 1 requires decode_slots > 0 — "
            "batched admission groups the decode engine's prefill "
            "chunks; without slots the knob would be silently ignored")
    if extra.get("affinity_routing"):
        if not extra.get("kv_page_size") \
                or extra.get("prefix_cache") is False:
            raise ValueError(
                "serve_args.affinity_routing requires the engine prefix "
                "cache (kv_page_size > 0, prefix_cache not disabled) — "
                "affinity routes requests to the replica whose cache "
                "already holds their prefix; without one the knob would "
                "be silently ignored")
