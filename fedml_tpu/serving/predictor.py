"""Predictors: the servable model contract + JAX implementations.

(reference: serving/fedml_predictor.py:10 — FedMLPredictor ABC with one
`predict(input_json)` method; user subclasses wrap their model.)

TPU-first details in JaxPredictor:
- the forward pass is jitted ONCE per batch bucket: inputs are padded up to
  the nearest power-of-two batch so arbitrary request sizes reuse a handful
  of compiled programs instead of recompiling per shape (XLA static-shape
  rule; SURVEY §7 design stance).
- bf16 compute via models/hub.mixed_precision_apply composes here too —
  pass the wrapped apply_fn.

GreedyLMPredictor serves the FedLLM slice (llm/TransformerLM + merged LoRA):
greedy argmax decoding with a jitted single-step; the KV recompute per step
is O(T^2) but fine for the smoke-serving path (a cached-KV decode loop is a
perf follow-up, not a correctness one).
"""
from __future__ import annotations

from typing import Any, Callable, Optional, Protocol

import jax
import jax.numpy as jnp
import numpy as np

Pytree = Any


class Predictor(Protocol):
    """reference: serving/fedml_predictor.py FedMLPredictor.predict."""

    def predict(self, input_json: dict) -> Any: ...


def _bucket(n: int, pow2_cap: int = 1024) -> int:
    """Power-of-two buckets up to the cap, then multiples of the cap — every
    batch size maps to a bounded set of compiled programs."""
    if n > pow2_cap:
        return ((n + pow2_cap - 1) // pow2_cap) * pow2_cap
    b = 1
    while b < n:
        b *= 2
    return b


class JaxPredictor:
    """Classification predictor over (apply_fn, params).

    predict({"inputs": [[...], ...]}) -> {"predictions": [...],
    "probabilities": [[...], ...]} — batch padded to a power-of-two bucket,
    one jitted program per bucket."""

    def __init__(self, apply_fn: Callable, params: Pytree,
                 return_probs: bool = True):
        self.params = params
        self.return_probs = return_probs

        @jax.jit
        def fwd(params, x):
            logits = apply_fn({"params": params}, x)
            return jnp.argmax(logits, -1), jax.nn.softmax(logits, -1)

        self._fwd = fwd

    def predict(self, input_json: dict) -> dict:
        x = np.asarray(input_json["inputs"], np.float32)
        n = x.shape[0]
        b = _bucket(n)
        if b > n:
            x = np.concatenate([x, np.zeros((b - n,) + x.shape[1:], x.dtype)])
        labels, probs = self._fwd(self.params, jnp.asarray(x))
        out = {"predictions": np.asarray(labels)[:n].tolist()}
        if self.return_probs:
            out["probabilities"] = np.asarray(probs)[:n].round(6).tolist()
        return out


class GreedyLMPredictor:
    """Causal-LM predictor for llm/TransformerLM (optionally with LoRA
    merged via llm.lora.lora_merge before construction).

    predict({"tokens": [...], "max_new_tokens": k}) ->
    {"generated_tokens": [...], "generated_text": "..."} (text only when a
    detokenizer fn is supplied)."""

    def __init__(self, model, params: Pytree,
                 detokenize: Optional[Callable[[list[int]], str]] = None,
                 max_len: int = 256):
        self.model = model
        self.params = params
        self.detokenize = detokenize
        self.max_len = max_len

        @jax.jit
        def step(params, tokens, length):
            logits = model.apply({"params": params}, tokens)
            # next token = argmax at the last REAL position
            return jnp.argmax(logits[0, length - 1])

        self._step = step

    def predict(self, input_json: dict) -> dict:
        toks = list(int(t) for t in input_json["tokens"])
        if not toks:
            raise ValueError("tokens must contain at least one prompt token")
        new = int(input_json.get("max_new_tokens", 16))
        # fixed-size buffer => one compiled program for every request
        buf = np.zeros((1, self.max_len), np.int32)
        if len(toks) + new > self.max_len:
            raise ValueError(
                f"prompt {len(toks)} + max_new_tokens {new} exceeds "
                f"max_len {self.max_len}")
        buf[0, : len(toks)] = toks
        length = len(toks)
        for _ in range(new):
            nxt = int(self._step(self.params, jnp.asarray(buf),
                                 jnp.int32(length)))
            buf[0, length] = nxt
            length += 1
        gen = buf[0, len(toks):length].tolist()
        out = {"generated_tokens": gen}
        if self.detokenize is not None:
            out["generated_text"] = self.detokenize(gen)
        return out
