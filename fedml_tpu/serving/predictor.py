"""Predictors: the servable model contract + JAX implementations.

(reference: serving/fedml_predictor.py:10 — FedMLPredictor ABC with one
`predict(input_json)` method; user subclasses wrap their model.)

TPU-first details in JaxPredictor:
- the forward pass is jitted ONCE per batch bucket: inputs are padded up to
  the nearest power-of-two batch so arbitrary request sizes reuse a handful
  of compiled programs instead of recompiling per shape (XLA static-shape
  rule; SURVEY §7 design stance).
- bf16 compute via models/hub.mixed_precision_apply composes here too —
  pass the wrapped apply_fn.

GreedyLMPredictor serves the FedLLM slice (llm/TransformerLM + merged LoRA):
greedy argmax decoding as ONE jitted lax.scan over decode steps (bucketed
step counts), so a request costs one device dispatch instead of one per
token — the per-token host round trip is the first-order latency term on a
tunneled TPU. kv_cache=True additionally swaps the per-step full-buffer
recompute for the KV-cached functional decode (llm/decode.py): measured
3.5x on the v5e at d1024/L8/max_len 2048 (118 -> 416 tok/s), identical
tokens (parity-pinned in tests/test_kv_decode.py).
"""
from __future__ import annotations

import functools
import threading
import time
from collections import OrderedDict
from typing import Any, Callable, Optional, Protocol

import jax
import jax.numpy as jnp
import numpy as np

from ..utils import metrics as _mx
from ..utils.events import recorder

Pytree = Any


class InvalidRequest(ValueError):
    """Client-side request error. The HTTP layer maps this (plus missing-
    field KeyErrors) to 400; every OTHER exception is a 500. The split
    matters twice over at the gateway: a 4xx must never kill a healthy
    replica (hostile input can't take replicas out of rotation), and a
    genuine internal failure must be a 5xx so failover actually happens —
    classifying by builtin ValueError/TypeError would misfile internal
    JAX shape/dtype errors as client errors."""


class StaleVersion(InvalidRequest):
    """The request PINNED a `model_version` this replica does not serve
    (rolling update in progress). The HTTP layer maps this to 409 — its
    own code because the gateway's contract differs from both 4xx and
    5xx: the replica is healthy (never marked dead) but the request
    should be RETRIED on a sibling replica that already (or still)
    serves the pinned version."""


def _req_int(input_json: dict, key: str, default) -> int:
    try:
        return int(input_json.get(key, default))
    except (TypeError, ValueError):
        raise InvalidRequest(
            f"{key} must be an integer; got {input_json.get(key)!r}"
        ) from None


class Predictor(Protocol):
    """reference: serving/fedml_predictor.py FedMLPredictor.predict."""

    def predict(self, input_json: dict) -> Any: ...


class _InstrumentedPredictor:
    """Telemetry shim shared by the JAX predictors (ISSUE 2): `predict`
    wraps the subclass's `_predict(input_json) -> (out, compile_key)` in a
    `serving.predict` span and separates compile-vs-serve time — the first
    call for a given compile key (bucket signature → one XLA program) lands
    in the `serving.predict.compile_s` histogram, warm calls in
    `serving.predict.serve_s`. The split is what makes a cold p99 legible:
    a 2 s first-bucket compile and a 2 ms steady serve must not share a
    histogram."""

    def predict(self, input_json: dict) -> dict:
        compiled = self.__dict__.setdefault("_compiled_keys", set())
        t0 = time.perf_counter()
        with recorder.span("serving.predict",
                           kind=type(self).__name__) as sp:
            out, key = self._predict(input_json)
            first = key not in compiled
            sp.meta["compile"] = first
        # the program compiled whether or not the pin below 409s —
        # record it first, or the next same-shape request would land its
        # serve-time latency in the compile histogram
        compiled.add(key)
        # pin is re-checked AFTER compute: a hot swap that lands while
        # this request decodes makes the engine finish in-flight slots on
        # the NEW adapters — returning that output under an old-version
        # pin would be the spliced mixed-version answer pinning exists to
        # prevent. The 409 reroutes to a sibling (decode cost is the
        # price of the read-your-round contract).
        chk = getattr(self, "_check_pin", None)
        if chk is not None:
            chk(input_json)
        _mx.inc("serving.predictions")
        _mx.observe("serving.predict.compile_s" if first
                    else "serving.predict.serve_s",
                    time.perf_counter() - t0)
        return out


def lm_predictor_from_serve_knobs(sv: dict, model, params,
                                  adapters=None, detokenize=None,
                                  default_max_len: int = 256
                                  ) -> "GreedyLMPredictor":
    """THE serve-knob -> GreedyLMPredictor mapping for every knob
    serving/knobs.py tags `consumer: predictor` (the registry is the one
    authoritative key list; graftlint's knob-drift rule fails the build
    if this function and the registry disagree). Shared by the config
    route (serving.lm_predictor_from_config reads
    Config.serve_args.extra) and the deploy route
    (scheduler.start_replica reads the spec's serve dict) — one mapping,
    so the two surfaces cannot drift."""
    eos = sv.get("engine_eos_id")
    n_pages = sv.get("kv_n_pages")
    return GreedyLMPredictor(
        model, params, adapters=adapters, detokenize=detokenize,
        max_len=int(sv.get("engine_max_len", default_max_len)),
        kv_cache=bool(sv.get("kv_cache", True)),
        decode_slots=int(sv.get("decode_slots", 0)),
        eos_id=None if eos is None else int(eos),
        engine_fetch_chunk=int(sv.get("engine_fetch_chunk", 2)),
        sampler_cache_size=int(sv.get("sampler_cache_size", 4)),
        engine_mp=int(sv.get("engine_mp", 0)),
        kv_page_size=int(sv.get("kv_page_size", 0)),
        kv_n_pages=None if n_pages is None else int(n_pages),
        prefill_chunk=int(sv.get("prefill_chunk", 0)),
        prefix_cache=bool(sv.get("prefix_cache", True)),
        paged_kernel=bool(sv.get("paged_kernel", False)),
        # a YAML-1.1 deploy spec reads unquoted `off` as False — the
        # documented disable spelling; normalize like config.validate
        spec_decode=("off" if sv.get("spec_decode") in (None, False)
                     else str(sv.get("spec_decode"))),
        spec_k=int(sv.get("spec_k", 4)),
        # same YAML-1.1 normalization: unquoted `off` parses as False
        kv_quant=("off" if sv.get("kv_quant") in (None, False)
                  else str(sv.get("kv_quant"))),
        admit_batch=int(sv.get("admit_batch", 1)),
        drain_timeout_s=float(sv.get("drain_timeout_s", 30.0)))


def _bucket(n: int, pow2_cap: int = 1024) -> int:
    """Power-of-two buckets up to the cap, then multiples of the cap — every
    batch size maps to a bounded set of compiled programs."""
    if n > pow2_cap:
        return ((n + pow2_cap - 1) // pow2_cap) * pow2_cap
    b = 1
    while b < n:
        b *= 2
    return b


class JaxPredictor(_InstrumentedPredictor):
    """Classification predictor over (apply_fn, params).

    predict({"inputs": [[...], ...]}) -> {"predictions": [...],
    "probabilities": [[...], ...]} — batch padded to a power-of-two bucket,
    one jitted program per bucket."""

    def __init__(self, apply_fn: Callable, params: Pytree,
                 return_probs: bool = True):
        self.params = params
        self.return_probs = return_probs

        @jax.jit
        def fwd(params, x):
            logits = apply_fn({"params": params}, x)
            return jnp.argmax(logits, -1), jax.nn.softmax(logits, -1)

        self._fwd = fwd

    def _predict(self, input_json: dict) -> tuple[dict, tuple]:
        try:
            x = np.asarray(input_json["inputs"], np.float32)
        except (TypeError, ValueError):
            raise InvalidRequest(
                "inputs must be a rectangular numeric array") from None
        n = x.shape[0]
        b = _bucket(n)
        if b > n:
            x = np.concatenate([x, np.zeros((b - n,) + x.shape[1:], x.dtype)])
        labels, probs = self._fwd(self.params, jnp.asarray(x))
        out = {"predictions": np.asarray(labels)[:n].tolist()}
        if self.return_probs:
            out["probabilities"] = np.asarray(probs)[:n].round(6).tolist()
        return out, (b, x.shape[1:])


class GreedyLMPredictor(_InstrumentedPredictor):
    """Causal-LM predictor for llm/TransformerLM (optionally with LoRA
    merged via llm.lora.lora_merge before construction).

    predict({"tokens": [...], "max_new_tokens": k}) ->
    {"generated_tokens": [...], "generated_text": "..."} (text only when a
    detokenizer fn is supplied).

    The WHOLE generation is one jitted program: a lax.scan over decode
    steps on a fixed-size token buffer, with the step count bucketed to
    powers of two (one compiled program per bucket). The naive alternative
    — one jit call per token — costs a host↔device round trip per token,
    which on a tunneled TPU dominates decode latency; the scanned form
    dispatches once per REQUEST.

    kv_cache=True (default-dense-attention models only) replaces the
    per-step full-buffer recompute with the KV-cached functional decode
    (llm/decode.py): O(D² + T·D) per token instead of O(T·D²), computed
    in the params' own dtype so numerics match the recompute path (same
    tokens; parity-pinned). Prompts are bucketed and the real length
    rides traced, so the compile cache stays bounded on both paths.

    decode_slots=S (requires kv_cache=True) additionally starts the
    continuous-batching DecodeEngine (serving/engine.py): S slots share
    one persistent donated KV cache and concurrent requests decode in the
    SAME device steps instead of serializing — single-prompt requests
    without top_k route there (greedy output token-identical to the
    per-request path); batched and top_k requests keep the per-request
    path. stop() shuts the engine down.

    kv_page_size=P (requires decode_slots) swaps the engine's cache for
    the block/PAGED layout — kv_n_pages sizes the pool, prefill_chunk
    enables chunked-prefill admission, prefix_cache reuses identical
    prompt-prefix pages (engine module docstring has the full story);
    engine capacity then becomes the page budget, consulted through
    engine.admissible() so routing and the 400/degrade contracts follow
    the real constraint.

    paged_kernel=True / spec_decode="ngram" (+ spec_k) turn on the
    paged engine's decode-speed legs (serving/engine.py: fused Pallas
    paged attention; greedy-exact self-drafted speculation). Neither
    changes routing or the degrade contract: both are token-identical
    to the plain engine — speculation keeps the engine's per-position
    rng schedule, so even seeded sampling degrades/surfaces exactly as
    before (the per-request path's schedule is the one that differs,
    which _must_surface_engine_failure already covers)."""

    def __init__(self, model, params: Pytree,
                 detokenize: Optional[Callable[[list[int]], str]] = None,
                 max_len: int = 256, kv_cache: bool = False,
                 adapters: Optional[Pytree] = None,
                 compute_dtype: Optional[str] = None,
                 decode_slots: int = 0, eos_id: Optional[int] = None,
                 sampler_cache_size: int = 4, engine_fetch_chunk: int = 2,
                 engine_mp: int = 0, kv_page_size: int = 0,
                 kv_n_pages: Optional[int] = None, prefill_chunk: int = 0,
                 prefix_cache: bool = True, paged_kernel: bool = False,
                 spec_decode: str = "off", spec_k: int = 4,
                 kv_quant: str = "off", admit_batch: int = 1,
                 drain_timeout_s: float = 30.0):
        self.model = model
        self.params = params
        self.detokenize = detokenize
        self.max_len = max_len
        self.kv_cache = kv_cache
        self.adapters = adapters
        self.engine = None
        self.eos_id = eos_id
        self.drain_timeout_s = float(drain_timeout_s)
        self._version = 0

        if decode_slots and not kv_cache:
            raise ValueError(
                "decode_slots (the continuous-batching engine, "
                "serving/engine.py) needs kv_cache=True — the engine IS "
                "the KV-cached decode with a slot axis")
        if (kv_page_size or kv_n_pages or prefill_chunk) \
                and not decode_slots:
            raise ValueError(
                "kv_page_size/kv_n_pages/prefill_chunk configure the "
                "PAGED decode engine — they need decode_slots > 0 "
                "(otherwise they would be silently ignored)")
        if (paged_kernel or spec_decode != "off") and not kv_page_size:
            # both decode-speed legs live on the paged layout (the
            # kernel reads the page pool in place; speculation rolls
            # write positions back through the page table) — without it
            # they would be silently ignored
            raise ValueError(
                "paged_kernel/spec_decode need the PAGED engine "
                "(kv_page_size > 0, which itself needs decode_slots) — "
                "otherwise they would be silently ignored")
        if kv_quant != "off" and not kv_page_size:
            # int8 KV is a property of the PAGED pool (per-page-per-head
            # scales ride the page table) — without it the knob would be
            # silently ignored
            raise ValueError(
                "kv_quant stores the PAGED KV pool in int8 — it needs "
                "kv_page_size > 0 (which itself needs decode_slots); "
                "otherwise it would be silently ignored")
        if int(admit_batch) > 1 and not decode_slots:
            raise ValueError(
                "admit_batch batches the decode ENGINE's admissions — "
                "it needs decode_slots > 0 (otherwise it would be "
                "silently ignored)")

        if adapters is not None and not kv_cache:
            # the recompute path drives model.apply, which knows nothing of
            # adapter trees or int8 {q,s} leaves; the kv decode handles both
            raise ValueError(
                "adapters (the QLoRA serving layout: frozen base + LoRA) "
                "need kv_cache=True — the functional decode merges them "
                "per layer; or pre-merge with llm.lora.lora_merge and pass "
                "plain params")
        if compute_dtype is not None and not kv_cache:
            raise ValueError(
                "compute_dtype only applies to kv_cache=True (the "
                "recompute path runs model.apply in the params' own "
                "dtype); cast the params instead, e.g. "
                "jax.tree.map(lambda a: a.astype(dtype), params)")
        if kv_cache:
            # O(D² + T·D) per token via llm/decode.py instead of a full
            # O(T·D²) recompute — parity-pinned in tests/test_kv_decode.py.
            # Needs the model's own dense attention (a custom attn_fn is
            # not replicated by the functional decode body).
            if model.attn_fn is not None:
                raise ValueError(
                    "kv_cache=True supports the default dense attention "
                    "only (custom attn_fn is not replicated by the "
                    "functional decode body)")
            from ..llm.decode import (
                make_greedy_generate, stack_adapter_blocks, stack_blocks,
            )

            # unrolled-layout adapters restack alongside the params —
            # block_i/... keys would otherwise be silently ignored by
            # split_adapters' blocks/ routing
            self.adapters = stack_adapter_blocks(adapters, model.n_layers)
            # the kv path never touches the unrolled tree again — keep ONE
            # copy resident (stack_blocks materializes a full stacked copy
            # for unrolled inputs; holding both would double parameter
            # HBM), and self.params IS the tree the kv path serves
            self.params = stack_blocks(params, model.n_layers)
            # decode in the params' own compute dtype, so kv and recompute
            # paths see the same numerics (float params stay float32; a
            # bf16-cast tree decodes in bf16, matching model.apply).
            # compute_dtype overrides — e.g. "bfloat16" for an int8 base
            # whose float leaves are the f32 scales
            if compute_dtype is not None:
                kv_dtype = jnp.dtype(compute_dtype)
            else:
                float_leaves = [l for l in jax.tree.leaves(self.params)
                                if jnp.issubdtype(l.dtype, jnp.floating)]
                kv_dtype = (float_leaves[0].dtype if float_leaves
                            else jnp.float32)
            kv_gen = make_greedy_generate(model.n_heads, dtype=kv_dtype)

            # prompts are right-padded to a power-of-two bucket and the
            # real length rides as a traced arg, so compiled programs are
            # keyed by (prompt bucket, step bucket) — bounded, like the
            # recompute path's fixed buffer
            @functools.partial(jax.jit, static_argnums=(4, 5))
            def generate_kv(params, adapters, tokens, length, max_len,
                            n_steps):
                return kv_gen(params, adapters, tokens, max_len, n_steps,
                              length=length)

            self._generate_kv = generate_kv
            self._kv_dtype = kv_dtype
            # top_k -> jitted sampling generate, LRU-BOUNDED: a hostile or
            # merely diverse stream of top_k values would otherwise grow
            # one jitted wrapper (and its compile cache) per bucket without
            # limit. Evicting the oldest drops its XLA executables with it;
            # evictions are counted so a thrashing cache is visible.
            self._samplers: "OrderedDict[int, Any]" = OrderedDict()
            self._samplers_cap = max(1, int(sampler_cache_size))
            # FedMLInferenceRunner serves via ThreadingHTTPServer, so two
            # first requests for the same top_k bucket can race here; without
            # the lock each would build + jit its own generate wrapper — a
            # duplicate multi-minute XLA compile at large model scale
            self._samplers_lock = threading.Lock()
            if decode_slots:
                # continuous batching (serving/engine.py): S slots share
                # one persistent donated KV cache; requests stream through
                # the engine thread instead of serializing on this
                # predictor's jit calls. engine_mp > 1 runs the engine
                # tensor-parallel over an {"mp": N} device mesh (weights +
                # KV cache sharded via the parallel/partition.py registry).
                from .engine import DecodeEngine

                mesh = None
                if int(engine_mp) > 1:
                    from ..parallel.mesh import make_mesh

                    mesh = make_mesh({"mp": int(engine_mp)})
                self.engine = DecodeEngine(
                    model, self.params, adapters=self.adapters,
                    n_slots=int(decode_slots), max_len=max_len,
                    eos_id=eos_id, dtype=kv_dtype,
                    fetch_chunk=engine_fetch_chunk, mesh=mesh,
                    page_size=kv_page_size, n_pages=kv_n_pages,
                    prefill_chunk=prefill_chunk,
                    prefix_cache=prefix_cache,
                    paged_kernel=paged_kernel, spec_decode=spec_decode,
                    spec_k=spec_k, kv_quant=kv_quant,
                    admit_batch=int(admit_batch)).start()
            return

        # n_steps is a Python int at trace time (scan length must be
        # static) -> one compiled program per power-of-two bucket
        @functools.partial(jax.jit, static_argnums=(3,))
        def generate(params, buf, length, n_steps):
            def step(carry, _):
                buf, pos = carry
                logits = model.apply({"params": params}, buf)
                nxt = jnp.argmax(logits[0, pos - 1]).astype(jnp.int32)
                buf = jax.lax.dynamic_update_slice(
                    buf, nxt[None, None], (0, pos))
                return (buf, pos + 1), nxt

            (_buf, _pos), toks = jax.lax.scan(
                step, (buf, length), None, length=n_steps)
            return toks

        self._generate = generate

    def stop(self, drain: bool = False) -> None:
        """Shut down the continuous-batching engine, if one was started.
        `drain=True` lets in-flight engine requests finish first, bounded
        by this predictor's `drain_timeout_s` — the runner's stop() path
        uses it so a scale-down or rolling replica replacement never
        kills a request that was already decoding."""
        if self.engine is not None:
            self.engine.stop(drain=drain,
                             drain_timeout_s=self.drain_timeout_s)

    # ------------------------------------------------------ fleet surface
    @property
    def model_version(self) -> int:
        """The adapter version this replica serves (monotonic; bumped by
        swap_adapters). The engine's counter when one runs — the
        per-request degrade path swaps in lockstep, so the version is
        honest on both paths."""
        return (self.engine.model_version if self.engine is not None
                else self._version)

    def swap_adapters(self, adapters: Pytree,
                      version: Optional[int] = None) -> int:
        """Hot-swap the LoRA adapter values this predictor serves — the
        rolling-update primitive (serving/engine.py swap_adapters has the
        atomicity story). The per-request fallback path swaps in the SAME
        call, so an engine that later dies degrades to a path serving the
        same version, not stale weights. Returns the new model_version."""
        if not self.kv_cache:
            raise ValueError(
                "adapter hot swap needs kv_cache=True — the recompute "
                "path serves pre-merged params (llm.lora.lora_merge); "
                "redeploy the replica instead")
        if self.adapters is None:
            raise ValueError(
                "this predictor was built without adapters — hot swap "
                "replaces adapter VALUES only; deploy with adapters "
                "(zero-initialized LoRA serves the base model exactly)")
        if self.engine is not None:
            ver = self.engine.swap_adapters(adapters, version=version)
            # the degrade path must serve the same weights the engine does
            self.adapters = self.engine.adapters
            self._version = ver
            return ver
        from .engine import prepare_adapter_swap

        stacked, ver = prepare_adapter_swap(
            self.adapters, adapters, self.model.n_layers,
            self._version, version, who="this replica")
        with recorder.span("serving.swap", version=ver):
            self.adapters = stacked
            self._version = ver
        _mx.set_gauge("serving.model_version", ver)
        # the serving tier's ONE swap counter (top's fleet line reads
        # it): engine-backed and degraded-path swaps both count
        _mx.inc("serving.engine.swaps")
        return ver

    def _check_pin(self, input_json: dict) -> None:
        """Per-request version pinning: a request naming `model_version`
        is answered ONLY by a replica serving exactly that version — the
        contract that lets the gateway keep a mixed-version fleet honest
        mid-rolling-update (a 409 reroutes to a sibling; it never kills
        the replica)."""
        pin = input_json.get("model_version")
        if pin is None:
            return
        try:
            pin = int(pin)
        except (TypeError, ValueError):
            raise InvalidRequest(
                f"model_version must be an integer; got {pin!r}") from None
        if pin != self.model_version:
            raise StaleVersion(
                f"request pinned model_version {pin}; this replica "
                f"serves {self.model_version}")

    def _parse_request(self, input_json: dict, batched: bool
                       ) -> tuple[list, float, list, int]:
        """The validation contract /predict and its streaming form MUST
        share (one helper so the two paths can't drift): integer tokens,
        numeric sampling knobs, non-empty rows, sampling-needs-kv_cache,
        and knob/temperature consistency. Returns (rows, temperature,
        knobs, max_new_tokens)."""
        raw = input_json["tokens"]
        try:
            rows = [[int(t) for t in r]
                    for r in (raw if batched else [raw])]
            temperature = float(input_json.get("temperature", 0.0))
            knobs = [k for k in ("top_k", "seed")
                     if int(input_json.get(k) or 0) != 0]
        except (TypeError, ValueError):
            raise InvalidRequest(
                "tokens must be integers and temperature/top_k/seed "
                "numeric") from None
        if not rows or any(not r for r in rows):
            raise InvalidRequest(
                "tokens must contain at least one prompt token"
                " (per row, for a batch)")
        # a knob at its documented disabled default (top_k=0, seed=0) is
        # equivalent to omitting it — client SDKs that serialize defaults
        # must not be rejected on greedy requests
        if (temperature > 0 or knobs) and not self.kv_cache:
            raise InvalidRequest(
                "sampling (temperature/top_k/seed) needs kv_cache=True; "
                "the recompute path is greedy-only")
        if temperature <= 0 and knobs:
            raise InvalidRequest(
                f"{'/'.join(knobs)} only apply when temperature > 0 "
                "(temperature omitted or 0 means greedy decoding — the "
                "knobs would be silently ignored)")
        return (rows, temperature, knobs,
                _req_int(input_json, "max_new_tokens", 16))

    def _must_surface_engine_failure(self, prompt_len: int, new: int,
                                     temperature: float,
                                     seed: Optional[int]) -> bool:
        """Degrade contract, shared by both paths: True when the
        per-request fallback could NOT honor what the engine promised, so
        an engine failure must surface (500 -> gateway failover) instead
        of silently degrading:
        - seeded sampling: the per-request rng schedule differs, same
          seed would return different tokens with no signal
        - engine_eos_id: the per-request path has no eos support,
          degraded output would include post-eos tokens
        - engine-only capacity: prompt + bucket(max_new) over max_len
          would turn a previously-valid request into a permanent,
          misleading 400"""
        return ((temperature > 0 and seed is not None)
                or self.eos_id is not None
                or prompt_len + _bucket(max(new, 1), pow2_cap=self.max_len)
                > self.max_len)

    def _predict(self, input_json: dict) -> tuple[dict, tuple]:
        self._check_pin(input_json)
        raw = input_json["tokens"]
        # {"tokens": [[...], [...]]} = a BATCH of prompts decoded in
        # lockstep through one program (kv_cache only; rows may differ in
        # length); {"tokens": [...]} = one prompt
        batched = bool(raw) and isinstance(raw[0], (list, tuple))
        rows, temperature, knobs, new = self._parse_request(
            input_json, batched)
        if batched and not self.kv_cache:
            raise InvalidRequest(
                "batched prompts need kv_cache=True (the recompute path "
                "decodes one prompt per program)")
        toks = max(rows, key=len)     # longest row drives capacity checks
        # continuous-batching route (serving/engine.py): single prompts
        # without a top_k cutoff stream through the slot engine — the
        # request blocks on its ticket while OTHER requests decode in the
        # same device steps. Batched rows (already one program) and top_k
        # requests (need a static-k compiled cutoff) stay on the
        # per-request path. Capacity rides the ENGINE's oracle
        # (engine.admissible — exact prompt + max_new <= max_len, plus
        # the page budget in paged mode), not static max_len math: a
        # request the page budget refuses falls through to the
        # per-request path below when that path can serve it honestly,
        # instead of 400ing a request this replica could answer. Routing
        # is deterministic per (prompt_len, max_new) — admissible() is
        # budget math, not current occupancy — so a given request shape
        # always takes the same path (seeded sampling stays reproducible).
        if (self.engine is not None and not batched
                and int(input_json.get("top_k", 0) or 0) == 0
                and not self.engine.admissible(len(rows[0]), max(new, 1))):
            if self.eos_id is not None or len(rows[0]) + _bucket(
                    max(new, 1), pow2_cap=self.max_len) > self.max_len:
                # neither path can serve this honestly (the per-request
                # path has no eos support / its bucketed capacity is also
                # exceeded) — surface the ENGINE's contract, page math
                # included, rather than the per-request message
                raise InvalidRequest(
                    self.engine.capacity_error(len(rows[0]), max(new, 1)))
        elif (self.engine is not None and not batched
                and int(input_json.get("top_k", 0) or 0) == 0):
            seed = int(input_json["seed"]) if "seed" in input_json else None
            gen = None
            try:
                # engine stopped/died (at submit, or mid-flight after
                # admission — the crash handler errors live tickets):
                # degrade to the per-request path below instead of erroring
                # the request — the replica keeps serving, just without
                # batching. A ticket TIMEOUT is not degraded: 600s have
                # already passed, re-decoding would double it.
                gen = self.engine.submit(
                    rows[0], max(new, 1), temperature=temperature,
                    seed=seed).result(timeout=600.0)[:new]
            except RuntimeError:
                # Degrade ONLY when the per-request path honors the same
                # contract the engine did; otherwise surface the failure
                # (a 500; the gateway fails the replica over) — the
                # shared _must_surface_engine_failure predicate
                if self._must_surface_engine_failure(
                        len(rows[0]), new, temperature, seed):
                    raise
            if gen is not None:
                out = {"generated_tokens": gen}
                if self.detokenize is not None:
                    out["generated_text"] = self.detokenize(gen)
                return out, ("engine",
                             min(_bucket(len(toks), pow2_cap=self.max_len),
                                 self.max_len))
        # fixed-size buffer + bucketed step count => a BOUNDED set of
        # compiled programs (log2(max_len) step buckets). The capacity
        # contract is prompt + bucket(max_new_tokens) <= max_len — clamping
        # the bucket to the remaining space instead would mint one static
        # scan length (= one fresh XLA compile) per distinct prompt length
        # near the buffer edge.
        steps = _bucket(max(new, 1), pow2_cap=self.max_len)
        if len(toks) + steps > self.max_len:
            raise InvalidRequest(
                f"prompt {len(toks)} + max_new_tokens {new} (bucketed to "
                f"{steps} decode steps) exceeds max_len {self.max_len}; "
                "shorten the prompt, lower max_new_tokens, or raise "
                "max_len")
        if self.kv_cache:
            pbucket = min(_bucket(len(toks), pow2_cap=self.max_len),
                          self.max_len)
            # the row count is ALSO bucketed (dummy rows repeat row 0,
            # sliced off below): batch sizes 3 and 4 share one compiled
            # program instead of each minting a fresh prefill+scan compile
            n_rows = len(rows)
            bbucket = _bucket(n_rows) if batched else 1
            prompt = np.zeros((bbucket, pbucket), np.int32)
            row_lens = []
            for i in range(bbucket):
                r = rows[i] if i < n_rows else rows[0]
                prompt[i, : len(r)] = r
                row_lens.append(len(r))
            lengths = (jnp.asarray(row_lens, jnp.int32) if batched
                       else jnp.int32(len(toks)))
            if temperature > 0:
                # sampling: softmax(logits/T) with optional static top-k —
                # T and the seed ride traced (the HF generate() knobs the
                # reference's serving surface inherits). top_k is a
                # compile-time shape knob, so it is VALIDATED and rounded
                # up to a power of two: the compile cache stays bounded at
                # log2(vocab) programs instead of one per raw client value
                top_k = int(input_json.get("top_k", 0))
                vocab = int(self.model.vocab_size)
                if top_k < 0 or top_k > vocab:
                    raise InvalidRequest(
                        f"top_k must be in [0, vocab_size={vocab}]; got "
                        f"{top_k} (0 disables the cutoff)")
                if top_k:
                    top_k = min(_bucket(top_k, pow2_cap=vocab), vocab)
                with self._samplers_lock:
                    gen = self._samplers.get(top_k)
                    if gen is not None:
                        self._samplers.move_to_end(top_k)  # LRU touch
                    else:
                        from ..llm.decode import make_generate

                        kv_gen = make_generate(self.model.n_heads,
                                               dtype=self._kv_dtype,
                                               sample=True, top_k=top_k)

                        @functools.partial(jax.jit, static_argnums=(4, 5))
                        def gen(params, adapters, tokens, length, max_len,
                                n_steps, rng, temp):
                            return kv_gen(params, adapters, tokens, max_len,
                                          n_steps, length=length, rng=rng,
                                          temperature=temp)

                        self._samplers[top_k] = gen
                        while len(self._samplers) > self._samplers_cap:
                            # evict coldest bucket — its jitted wrapper
                            # (and compiled programs) go with it; visible
                            # as a counter so thrash is diagnosable
                            self._samplers.popitem(last=False)
                            _mx.inc("serving.sampler_evictions")
                # no client seed -> a fresh one per request, so repeated
                # sampling requests VARY (the normal serving contract);
                # pass "seed" explicitly for reproducible generations
                if "seed" in input_json:
                    seed = int(input_json["seed"])
                else:
                    import random as _random

                    seed = _random.getrandbits(31)
                key = ("kv", pbucket, bbucket, steps, top_k)
                out_toks = gen(
                    self.params, self.adapters, jnp.asarray(prompt),
                    lengths, int(self.max_len), int(steps),
                    jax.random.key(seed), jnp.float32(temperature))
            else:
                key = ("kv", pbucket, bbucket, steps, -1)
                out_toks = self._generate_kv(
                    self.params, self.adapters, jnp.asarray(prompt),
                    lengths, int(self.max_len), int(steps))
        else:
            key = ("recompute", steps)
            buf = np.zeros((1, self.max_len), np.int32)
            buf[0, : len(toks)] = toks
            out_toks = self._generate(self.params, jnp.asarray(buf),
                                      jnp.int32(len(toks)), int(steps))
        arr = np.asarray(out_toks)
        if batched:
            # generate() returns 1-D for a single row; normalize, then
            # drop the bucket-padding dummy rows
            arr = np.atleast_2d(arr)[:n_rows]
            gen = arr[:, :new].tolist()
            out = {"generated_tokens": gen}
            if self.detokenize is not None:
                out["generated_text"] = [self.detokenize(g) for g in gen]
        else:
            gen = arr[:new].tolist()
            out = {"generated_tokens": gen}
            if self.detokenize is not None:
                out["generated_text"] = self.detokenize(gen)
        return out, key

    # ---------------------------------------------------------- streaming
    def predict_stream(self, input_json: dict):
        """Generator form of predict() for single-prompt requests: yields
        one {"token": t, "index": i} per generated token, then a final
        {"done": True, "generated_tokens": [...]} (plus generated_text
        with a detokenizer) — the payload the runner's SSE surface
        relays chunk by chunk.

        Engine-backed predictors stream LIVE: tokens surface as the
        engine's retirement frames land (granularity = fetch_chunk), so
        time-to-first-token is an engine iteration, not the whole
        request. Requests the engine can't take (top_k, page budget,
        dead engine within the degrade contract) compute through
        predict() in one program and then emit — degenerate timing,
        identical payload contract. Greedy streams are deterministic:
        re-running the same request yields the same token sequence,
        which is what lets the gateway re-serve a cut stream from token
        0 on a survivor replica."""
        self._check_pin(input_json)
        raw = input_json["tokens"]
        if raw and isinstance(raw[0], (list, tuple)):
            raise InvalidRequest(
                "streaming serves one prompt per request (batched rows "
                "return a single response; use /predict without stream)")
        rows_w, temperature, knobs, new = self._parse_request(
            input_json, batched=False)
        rows = rows_w[0]
        top_k = int(input_json.get("top_k", 0) or 0)
        pin = input_json.get("model_version")
        pin = int(pin) if pin is not None else None   # _check_pin validated
        ticket = None
        if (self.engine is not None and top_k == 0
                and self.engine.admissible(len(rows), max(new, 1))):
            seed = int(input_json["seed"]) if "seed" in input_json else None
            try:
                ticket = self.engine.submit(
                    rows, max(new, 1), temperature=temperature, seed=seed)
            except RuntimeError:
                # same degrade contract as predict(): greedy/unseeded
                # falls through to the one-shot path below
                if self._must_surface_engine_failure(
                        len(rows), new, temperature, seed):
                    raise
        if ticket is not None:
            _mx.inc("serving.stream_requests")
            out: list[int] = []
            for tok in ticket.stream(timeout=600.0):
                # a hot swap that lands mid-stream finishes this slot on
                # the NEW adapters — a pinned stream must fail (terminal
                # error event; the gateway reroutes/replays) rather than
                # silently splice model versions
                if pin is not None and self.model_version != pin:
                    raise StaleVersion(
                        f"request pinned model_version {pin}; this "
                        f"replica swapped to {self.model_version} "
                        "mid-stream")
                if len(out) >= new:
                    break       # new == 0: the engine still decoded one
                out.append(int(tok))
                yield {"token": int(tok), "index": len(out) - 1}
            final = {"done": True, "generated_tokens": out}
            if self.detokenize is not None:
                final["generated_text"] = self.detokenize(out)
            yield final
            return
        res = self.predict(dict(input_json))
        gen = res["generated_tokens"]
        _mx.inc("serving.stream_requests")
        for i, t in enumerate(gen):
            yield {"token": int(t), "index": i}
        yield {"done": True, **res}
