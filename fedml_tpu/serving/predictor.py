"""Predictors: the servable model contract + JAX implementations.

(reference: serving/fedml_predictor.py:10 — FedMLPredictor ABC with one
`predict(input_json)` method; user subclasses wrap their model.)

TPU-first details in JaxPredictor:
- the forward pass is jitted ONCE per batch bucket: inputs are padded up to
  the nearest power-of-two batch so arbitrary request sizes reuse a handful
  of compiled programs instead of recompiling per shape (XLA static-shape
  rule; SURVEY §7 design stance).
- bf16 compute via models/hub.mixed_precision_apply composes here too —
  pass the wrapped apply_fn.

GreedyLMPredictor serves the FedLLM slice (llm/TransformerLM + merged LoRA):
greedy argmax decoding as ONE jitted lax.scan over decode steps (bucketed
step counts), so a request costs one device dispatch instead of one per
token — the per-token host round trip is the first-order latency term on a
tunneled TPU. Per-step attention still recomputes over the buffer (a
cached-KV decode is a further perf follow-up, not a correctness one).
"""
from __future__ import annotations

import functools
from typing import Any, Callable, Optional, Protocol

import jax
import jax.numpy as jnp
import numpy as np

Pytree = Any


class Predictor(Protocol):
    """reference: serving/fedml_predictor.py FedMLPredictor.predict."""

    def predict(self, input_json: dict) -> Any: ...


def _bucket(n: int, pow2_cap: int = 1024) -> int:
    """Power-of-two buckets up to the cap, then multiples of the cap — every
    batch size maps to a bounded set of compiled programs."""
    if n > pow2_cap:
        return ((n + pow2_cap - 1) // pow2_cap) * pow2_cap
    b = 1
    while b < n:
        b *= 2
    return b


class JaxPredictor:
    """Classification predictor over (apply_fn, params).

    predict({"inputs": [[...], ...]}) -> {"predictions": [...],
    "probabilities": [[...], ...]} — batch padded to a power-of-two bucket,
    one jitted program per bucket."""

    def __init__(self, apply_fn: Callable, params: Pytree,
                 return_probs: bool = True):
        self.params = params
        self.return_probs = return_probs

        @jax.jit
        def fwd(params, x):
            logits = apply_fn({"params": params}, x)
            return jnp.argmax(logits, -1), jax.nn.softmax(logits, -1)

        self._fwd = fwd

    def predict(self, input_json: dict) -> dict:
        x = np.asarray(input_json["inputs"], np.float32)
        n = x.shape[0]
        b = _bucket(n)
        if b > n:
            x = np.concatenate([x, np.zeros((b - n,) + x.shape[1:], x.dtype)])
        labels, probs = self._fwd(self.params, jnp.asarray(x))
        out = {"predictions": np.asarray(labels)[:n].tolist()}
        if self.return_probs:
            out["probabilities"] = np.asarray(probs)[:n].round(6).tolist()
        return out


class GreedyLMPredictor:
    """Causal-LM predictor for llm/TransformerLM (optionally with LoRA
    merged via llm.lora.lora_merge before construction).

    predict({"tokens": [...], "max_new_tokens": k}) ->
    {"generated_tokens": [...], "generated_text": "..."} (text only when a
    detokenizer fn is supplied).

    The WHOLE generation is one jitted program: a lax.scan over decode
    steps on a fixed-size token buffer, with the step count bucketed to
    powers of two (one compiled program per bucket). The naive alternative
    — one jit call per token — costs a host↔device round trip per token,
    which on a tunneled TPU dominates decode latency; the scanned form
    dispatches once per REQUEST. Per-step compute is still a full-buffer
    forward (O(max_len²) attention; a KV-cache would make it O(max_len)
    — a perf follow-up, the dispatch overhead was the first-order term)."""

    def __init__(self, model, params: Pytree,
                 detokenize: Optional[Callable[[list[int]], str]] = None,
                 max_len: int = 256):
        self.model = model
        self.params = params
        self.detokenize = detokenize
        self.max_len = max_len

        # n_steps is a Python int at trace time (scan length must be
        # static) -> one compiled program per power-of-two bucket
        @functools.partial(jax.jit, static_argnums=(3,))
        def generate(params, buf, length, n_steps):
            def step(carry, _):
                buf, pos = carry
                logits = model.apply({"params": params}, buf)
                nxt = jnp.argmax(logits[0, pos - 1]).astype(jnp.int32)
                buf = jax.lax.dynamic_update_slice(
                    buf, nxt[None, None], (0, pos))
                return (buf, pos + 1), nxt

            (_buf, _pos), toks = jax.lax.scan(
                step, (buf, length), None, length=n_steps)
            return toks

        self._generate = generate

    def predict(self, input_json: dict) -> dict:
        toks = list(int(t) for t in input_json["tokens"])
        if not toks:
            raise ValueError("tokens must contain at least one prompt token")
        new = int(input_json.get("max_new_tokens", 16))
        # fixed-size buffer + bucketed step count => a BOUNDED set of
        # compiled programs (log2(max_len) step buckets). The capacity
        # contract is prompt + bucket(max_new_tokens) <= max_len — clamping
        # the bucket to the remaining space instead would mint one static
        # scan length (= one fresh XLA compile) per distinct prompt length
        # near the buffer edge.
        steps = _bucket(max(new, 1), pow2_cap=self.max_len)
        if len(toks) + steps > self.max_len:
            raise ValueError(
                f"prompt {len(toks)} + max_new_tokens {new} (bucketed to "
                f"{steps} decode steps) exceeds max_len {self.max_len}; "
                "shorten the prompt, lower max_new_tokens, or raise "
                "max_len")
        buf = np.zeros((1, self.max_len), np.int32)
        buf[0, : len(toks)] = toks
        out_toks = self._generate(self.params, jnp.asarray(buf),
                                  jnp.int32(len(toks)), int(steps))
        gen = np.asarray(out_toks)[:new].tolist()
        out = {"generated_tokens": gen}
        if self.detokenize is not None:
            out["generated_text"] = self.detokenize(gen)
        return out
