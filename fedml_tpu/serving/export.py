"""Framework-neutral model export — the deploy pipeline's interchange format.

(reference: computing/scheduler/model_scheduler/device_model_deployment.py
:720 `convert_model_to_onnx` + :172,263 — the reference's deploy path
converts trained torch models to ONNX and lays out a Triton model
repository so serving does not depend on the training framework. The
TPU-native analog is a flat-tensor manifest: jax/flax adds nothing to an
inference contract that is just named arrays + a model recipe, and a flat
npz is readable by ANY consumer with a numpy-compatible loader — torch,
TF, C++ via cnpy, or a Triton python backend.)

LAYOUT CONTRACT (format "fedml-tpu-export/1"):

    <export_dir>/
      manifest.json      UTF-8 JSON, two sections:
        "format":  "fedml-tpu-export/1"
        "tensors": {flat_name: {"shape": [ints], "dtype": numpy-name,
                    "cast_from": original-dtype (only when the stored
                    dtype differs, e.g. bfloat16 stored as float32)}}
        "model":   optional recipe {"model": hub name, "num_classes": int,
                   "model_args": {...}, "input_shape": [ints],
                   "compute_dtype": str} — enough for
                   predictor_from_export to rebuild the apply_fn
      tensors.npz        numpy zip archive; one entry per manifest tensor,
                         SAME flat names, row-major (C-order) arrays

Flat names are the "/"-joined path through the params pytree
("block_0/wq/kernel"), so the nested tree round-trips losslessly and a
non-JAX consumer sees self-describing names. Tensors not representable in
portable npz (bfloat16) are stored as float32 and flagged via "cast_from";
load_export restores the original dtype.
"""
from __future__ import annotations

import json
import os
from typing import Any, Callable, Optional

import numpy as np

Pytree = Any

FORMAT = "fedml-tpu-export/1"
_MANIFEST = "manifest.json"
_TENSORS = "tensors.npz"


def _flatten(params: Pytree, prefix: str = "") -> dict:
    out = {}
    if isinstance(params, dict):
        for k, v in params.items():
            out.update(_flatten(v, f"{prefix}{k}/"))
        return out
    out[prefix[:-1]] = np.asarray(params)
    return out


def _unflatten(flat: dict) -> Pytree:
    tree: dict = {}
    for name, v in flat.items():
        node = tree
        *parents, leaf = name.split("/")
        for p in parents:
            node = node.setdefault(p, {})
        node[leaf] = v
    return tree


def export_model(path: str, params: Pytree,
                 model_name: Optional[str] = None,
                 num_classes: Optional[int] = None,
                 model_args: Optional[dict] = None,
                 input_shape: Optional[tuple] = None,
                 compute_dtype: str = "float32") -> dict:
    """Write the flat-tensor export (layout contract above). Returns the
    manifest dict. `model_name` etc. are optional — without them the export
    is a pure tensor interchange; with them predictor_from_export can
    rebuild a live predictor."""
    os.makedirs(path, exist_ok=True)
    flat = _flatten(jax_device_get(params))
    tensors, table = {}, {}
    for name, arr in sorted(flat.items()):
        entry = {"shape": [int(d) for d in arr.shape],
                 "dtype": str(arr.dtype)}
        # portable = a dtype any stock-numpy reader parses (bool/int/uint/
        # float/complex); bfloat16 & friends register with kind 'V'
        if arr.dtype.kind not in "biufc":   # store widened, flag it
            entry["cast_from"] = str(arr.dtype)
            arr = arr.astype(np.float32)
            entry["dtype"] = "float32"
        tensors[name] = np.ascontiguousarray(arr)
        table[name] = entry
    manifest = {"format": FORMAT, "tensors": table}
    if model_name is not None:
        if num_classes is None:
            raise ValueError(
                "export_model: model_name without num_classes would write a "
                "manifest whose model recipe disagrees with the exported "
                "head tensors; pass the model's num_classes explicitly")
        manifest["model"] = {
            "model": model_name,
            "num_classes": int(num_classes),
            "model_args": dict(model_args or {}),
            "compute_dtype": compute_dtype,
        }
        if input_shape is not None:
            manifest["model"]["input_shape"] = [int(d) for d in input_shape]
    np.savez(os.path.join(path, _TENSORS), **tensors)
    with open(os.path.join(path, _MANIFEST), "w") as f:
        json.dump(manifest, f, indent=2, sort_keys=True)
    return manifest


def jax_device_get(params: Pytree) -> Pytree:
    """Host numpy view of a (possibly device-resident, possibly sharded)
    pytree; plain numpy trees pass through untouched."""
    try:
        import jax

        return jax.tree.map(np.asarray, jax.device_get(params))
    except ImportError:  # pure-numpy consumer of this module
        return params


def load_export(path: str) -> tuple[Pytree, dict]:
    """(params_pytree, manifest) from an export dir. Validates the format
    tag and every tensor's shape/dtype against the manifest — a truncated
    or hand-edited artifact fails loudly, not at inference time."""
    with open(os.path.join(path, _MANIFEST)) as f:
        manifest = json.load(f)
    if manifest.get("format") != FORMAT:
        raise ValueError(
            f"{path!r} is not a {FORMAT} export "
            f"(format={manifest.get('format')!r})")
    with np.load(os.path.join(path, _TENSORS)) as z:
        names = set(z.files)
        want = set(manifest["tensors"])
        if names != want:
            raise ValueError(
                f"export {path!r} tensor set mismatch: manifest has "
                f"{sorted(want - names)[:4]} missing, archive has "
                f"{sorted(names - want)[:4]} extra")
        flat = {}
        for name, entry in manifest["tensors"].items():
            arr = z[name]
            if list(arr.shape) != entry["shape"] or \
                    str(arr.dtype) != entry["dtype"]:
                raise ValueError(
                    f"tensor {name!r} does not match its manifest entry: "
                    f"archive {arr.shape}/{arr.dtype} vs manifest "
                    f"{entry['shape']}/{entry['dtype']}")
            src = entry.get("cast_from")
            if src:
                try:
                    import ml_dtypes  # noqa: F401 — registers bfloat16

                    arr = arr.astype(np.dtype(src))
                except (ImportError, TypeError):
                    pass   # numpy-only consumer keeps the widened dtype
            flat[name] = arr
    return _unflatten(flat), manifest


def predictor_from_export(path: str, return_probs: bool = True):
    """Live JaxPredictor from an export that carries a model recipe —
    the serving-side load-back (counterpart of predictor_from_artifact,
    reference: device_model_deployment.py model-package unpack)."""
    from ..models import hub as model_hub
    from .predictor import JaxPredictor

    params, manifest = load_export(path)
    spec = manifest.get("model")
    if not spec:
        raise ValueError(
            f"export {path!r} has no 'model' recipe — it is a pure tensor "
            "interchange; pass model_name/num_classes to export_model to "
            "make it servable")
    model = model_hub.create(spec["model"], int(spec["num_classes"]),
                             **dict(spec.get("model_args", {})))
    apply_fn = model_hub.mixed_precision_apply(
        model.apply, spec.get("compute_dtype", "float32"))
    return JaxPredictor(apply_fn, params, return_probs=return_probs)
