"""Model serving — predictors + HTTP inference runner.

(reference: python/fedml/serving/ — 1,990 LoC: FedMLPredictor ABC,
FedMLInferenceRunner FastAPI app, fedml_server.py reusing cross-silo init
for federated serving.)

Layer map position: L3 runtime (SURVEY.md §1). The compute path is a jitted
bucketed forward (serving/predictor.py); LLM requests can opt into the
continuous-batching slot engine (serving/engine.py — one persistent donated
KV cache, concurrent requests share device steps); the HTTP surface mirrors
the reference's /predict + /ready contract (serving/inference_runner.py).
`serve_simulator` is the federated-serving bridge: serve the global model a
Simulator trained (or a checkpoint directory it saved).
"""
from __future__ import annotations

import importlib
from typing import Callable

__all__ = [
    "Predictor", "JaxPredictor", "GreedyLMPredictor",
    "DecodeEngine", "Ticket", "lm_predictor_from_config",
    "FedMLInferenceRunner", "DEFAULT_PORT", "serve_simulator",
    "predictor_from_checkpoint", "predictor_from_artifact",
    "export_model", "load_export", "predictor_from_export",
]

# Lazy re-exports (PEP 562, same pattern as the package root): the heavy
# submodules import jax, but `fedml_tpu.serving.knobs` — the serve-knob
# registry config.py validates against at load time — must be importable
# without dragging a backend in. Importing THIS package therefore stays
# jax-free; the first access to an engine/predictor symbol pays the
# submodule import.
_LAZY = {
    "DecodeEngine": "engine", "Ticket": "engine",
    "export_model": "export", "load_export": "export",
    "predictor_from_export": "export",
    "DEFAULT_PORT": "inference_runner",
    "FedMLInferenceRunner": "inference_runner",
    "GreedyLMPredictor": "predictor", "JaxPredictor": "predictor",
    "Predictor": "predictor",
}


def __getattr__(name):
    mod = _LAZY.get(name)
    if mod is None:
        raise AttributeError(name)
    return getattr(importlib.import_module(f".{mod}", __name__), name)


def lm_predictor_from_config(cfg, model, params, adapters=None,
                             detokenize=None) -> "GreedyLMPredictor":
    """Build the LM serving predictor from a Config's `serve_args` section
    (YAML key `serve_args`, alias `serve` — validated at load,
    config.py): `decode_slots` > 0 starts the continuous-batching engine,
    `engine_max_len`/`engine_eos_id`/`engine_fetch_chunk`/
    `sampler_cache_size`/`kv_cache` tune it; `kv_page_size` > 0 selects
    the paged KV cache with `kv_n_pages`/`prefill_chunk`/`prefix_cache`
    (engine module docstring). This is the config-side
    consumer of cfg.serve_args; the deploy path (scheduler.start_replica)
    feeds the serve-spec dict through the SAME knob mapping
    (predictor.lm_predictor_from_serve_knobs)."""
    from .predictor import lm_predictor_from_serve_knobs

    return lm_predictor_from_serve_knobs(
        cfg.serve_args.extra, model, params, adapters=adapters,
        detokenize=detokenize)


def predictor_from_artifact(store, round_idx: int,
                            apply_fn: Callable) -> "JaxPredictor":
    """Serve the round-N aggregated model published through the mlops
    artifact path (reference shape: serving loads the S3 model the
    aggregator uploaded with log_aggregated_model_info — core/mlops/
    __init__.py:388). `store` is a utils/artifacts.py store (or anything
    with .get(name))."""
    from ..utils.artifacts import aggregated_name
    from .predictor import JaxPredictor

    return JaxPredictor(apply_fn, store.get(aggregated_name(round_idx)))


def predictor_from_checkpoint(ckpt_dir: str, apply_fn: Callable,
                              server_template) -> JaxPredictor:
    """Load the latest orbax checkpoint's global model and wrap it as a
    predictor (reference analog: fedml_server.py serving the aggregated
    model; here the source of truth is utils/checkpoint.py state)."""
    from ..utils.checkpoint import restore_checkpoint
    from .predictor import JaxPredictor

    _r, server, _c, _h, _hist = restore_checkpoint(ckpt_dir, server_template)
    return JaxPredictor(apply_fn, server.params)


def serve_simulator(sim, host: str = "127.0.0.1", port: int = 0,
                    background: bool = True) -> FedMLInferenceRunner:
    """Serve a (trained) Simulator's global model over HTTP. Params are
    copied: the round engine donates its server state, so serving by
    reference would break if training continues after this call."""
    import jax
    import jax.numpy as jnp

    from .inference_runner import FedMLInferenceRunner
    from .predictor import JaxPredictor

    pred = JaxPredictor(
        sim.apply_fn, jax.tree.map(jnp.array, sim.server_state.params))
    runner = FedMLInferenceRunner(pred, host=host, port=port)
    if background:
        runner.start()
    else:
        runner.run()
    return runner
