"""Model-serving scheduler — deploy FSM, inference gateway, autoscaler.

(reference: computing/scheduler/model_scheduler/ ~8k LoC —
device_model_deployment.py:37 start_deployment packages a model and brings
up per-device inference containers with readiness polling;
device_model_inference.py:32-143 is the gateway that routes /predict to
ready devices; autoscaling rides the SaaS. Here the same three roles are
local-first over fedml_tpu's own scheduler agents:)

- Deployment.deploy(): package (model spec + params/checkpoint) → submit one
  "serve" job per replica through the MasterAgent → workers start in-process
  HTTP replicas (serving/inference_runner.py) → poll /ready until live.
  FSM per replica: DISPATCHED → STARTING → READY | DEAD.
- InferenceGateway: HTTP /predict facade; round-robins over READY replicas,
  retries the next replica when one dies mid-request (and marks it DEAD so
  the autoscaler replaces it). /ready reports deployment health.
- Autoscaler: queue-depth scaling — the gateway tracks in-flight requests;
  above high_water x replicas it submits another serve job, below low_water
  it retires one (min/max bounds). The same policy shape as the reference's
  target-concurrency autoscaler, with XLA-friendly in-process replicas
  instead of docker containers.

TPU note: replicas on one host share the chip; scale-out here exists for
fault tolerance and request pipelining (host-side pre/post-processing
overlaps device steps). Cross-host replicas ride the same job spec over a
broker/grpc comm backend unchanged.
"""
from __future__ import annotations

import json
import logging
import threading
import time
import urllib.error
import urllib.request
import uuid
from typing import Any, Optional

from ..utils import metrics as _mx
from ..utils.events import recorder

log = logging.getLogger(__name__)

R_DISPATCHED = "DISPATCHED"
R_READY = "READY"
R_DEAD = "DEAD"


def start_replica(spec: dict):
    """Worker-side: build a predictor from a deployment spec and serve it.
    Spec sources (first match wins):
      - "export_dir": framework-neutral flat-tensor export (serving/
        export.py — the reference's ONNX/Triton model-repo analog,
        device_model_deployment.py:720 convert_model_to_onnx); the export's
        own manifest carries the model recipe, so no other spec keys needed
      - "checkpoint_dir": orbax checkpoint from utils/checkpoint.py
      - "params": inline pytree of ndarrays (rides the tensor wire format)
    plus "model"/"num_classes"/"input_shape"/"model_args" to rebuild the
    apply_fn (reference: start_deployment's model-package unpack)."""
    import jax.numpy as jnp

    from ..models import hub as model_hub
    from .inference_runner import FedMLInferenceRunner
    from .predictor import JaxPredictor

    if spec.get("export_dir"):
        from .export import predictor_from_export

        pred = predictor_from_export(spec["export_dir"])
        runner = FedMLInferenceRunner(pred, port=int(spec.get("port", 0)))
        runner.start()
        return uuid.uuid4().hex[:10], runner

    if spec.get("model_kind") == "lm":
        # LLM replica: llm/TransformerLM + GreedyLMPredictor. "lm" carries
        # the model recipe, "serve" the ServeArgs.extra knobs (config.py) —
        # decode_slots > 0 brings the replica up on the continuous-batching
        # engine (serving/engine.py), otherwise per-request decode;
        # kv_page_size > 0 selects the engine's paged KV cache (with
        # kv_n_pages/prefill_chunk/prefix_cache riding the same dict).
        from ..llm.transformer import TransformerLM
        from .predictor import lm_predictor_from_serve_knobs

        lm = dict(spec.get("lm", {}))
        model = TransformerLM(
            vocab_size=int(lm["vocab_size"]),
            d_model=int(lm["d_model"]), n_layers=int(lm["n_layers"]),
            n_heads=int(lm["n_heads"]), d_ff=int(lm["d_ff"]),
            scan_layers=bool(lm.get("scan_layers", False)))
        # serve knobs go through the SAME mapping as the config route
        # (predictor.lm_predictor_from_serve_knobs) — one source of
        # defaults, the two surfaces cannot drift
        pred = lm_predictor_from_serve_knobs(
            dict(spec.get("serve", {})), model, spec["params"],
            adapters=spec.get("adapters"),
            default_max_len=int(lm.get("max_len", 256)))
        runner = FedMLInferenceRunner(pred, port=int(spec.get("port", 0)))
        runner.start()
        return uuid.uuid4().hex[:10], runner

    model = model_hub.create(spec["model"], int(spec.get("num_classes", 10)),
                             **dict(spec.get("model_args", {})))
    apply_fn = model_hub.mixed_precision_apply(
        model.apply, spec.get("compute_dtype", "float32"))
    if spec.get("checkpoint_dir"):
        import jax

        from ..algorithms import build_algorithm
        from ..config import TrainArgs
        from ..utils.checkpoint import restore_checkpoint

        # the saved server-state STRUCTURE depends on the algorithm that
        # trained it; rebuild the same template the Simulator used
        init = model_hub.init_params(
            model, tuple(spec["input_shape"]), jax.random.key(0))
        alg = build_algorithm(spec.get("federated_optimizer", "FedAvg"),
                              apply_fn, TrainArgs(), 1, 1)
        _r, server, _c, _h, _hist = restore_checkpoint(
            spec["checkpoint_dir"], alg.server_init(init))
        params = server.params
    else:
        params = jnp.asarray(spec["params"]) if not isinstance(
            spec["params"], dict) else spec["params"]
    pred = JaxPredictor(apply_fn, params)
    runner = FedMLInferenceRunner(pred, port=int(spec.get("port", 0)))
    runner.start()
    return uuid.uuid4().hex[:10], runner


class _Replica:
    def __init__(self, job_id: str):
        self.job_id = job_id
        self.state = R_DISPATCHED
        self.replica_id: Optional[str] = None
        self.endpoint: Optional[str] = None
        self.worker_id: Optional[int] = None


class Deployment:
    """Deploy FSM over a MasterAgent (reference:
    device_model_deployment.py:37 start_deployment)."""

    def __init__(self, master, serve_spec: dict, min_replicas: int = 1,
                 max_replicas: int = 4):
        self.master = master
        self.spec = dict(serve_spec)
        self.spec["type"] = "serve"
        self.min_replicas = min_replicas
        self.max_replicas = max_replicas
        self.replicas: list[_Replica] = []
        self._lock = threading.Lock()
        self._rr = 0

    # ------------------------------------------------------------ deploy
    def deploy(self, n_replicas: Optional[int] = None,
               timeout: float = 60.0) -> "Deployment":
        n = n_replicas if n_replicas is not None else self.min_replicas
        for _ in range(n):
            self._dispatch_one(timeout)
        self.wait_ready(n, timeout)
        return self

    def _dispatch_one(self, timeout: float = 60.0) -> _Replica:
        jid = self.master.submit(dict(self.spec))
        rep = _Replica(jid)
        with self._lock:
            self.replicas.append(rep)
        threading.Thread(target=self._track, args=(rep, timeout),
                         daemon=True).start()
        return rep

    def _track(self, rep: _Replica, timeout: float = 60.0) -> None:
        """DISPATCHED -> (job result with endpoint) -> poll /ready -> READY."""
        job = self.master.wait(rep.job_id, timeout=timeout)
        if job.status != "FINISHED" or not isinstance(job.result, dict):
            rep.state = R_DEAD
            log.warning("replica job %s failed: %s", rep.job_id, job.result)
            return
        rep.replica_id = job.result["replica_id"]
        rep.worker_id = job.result.get("worker_id")
        rep.endpoint = f"http://{job.result['host']}:{job.result['port']}"
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            try:
                with urllib.request.urlopen(rep.endpoint + "/ready",
                                            timeout=2) as r:
                    if r.status == 200:
                        rep.state = R_READY
                        return
            except (urllib.error.URLError, OSError):
                pass
            time.sleep(0.05)
        rep.state = R_DEAD

    def wait_ready(self, n: int, timeout: float = 60.0) -> bool:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if len(self.ready_replicas()) >= n:
                return True
            time.sleep(0.05)
        return False

    def ready_replicas(self) -> list[_Replica]:
        with self._lock:
            return [r for r in self.replicas if r.state == R_READY]

    # ------------------------------------------------------------ routing
    def pick(self) -> Optional[_Replica]:
        ready = self.ready_replicas()
        if not ready:
            return None
        with self._lock:
            self._rr += 1
            return ready[self._rr % len(ready)]

    def mark_dead(self, rep: _Replica) -> None:
        rep.state = R_DEAD

    # ------------------------------------------------------------ scaling
    def scale_up(self) -> Optional[_Replica]:
        with self._lock:
            live = [r for r in self.replicas if r.state != R_DEAD]
            if len(live) >= self.max_replicas:
                return None
        log.info("autoscale: +1 replica")
        return self._dispatch_one()

    def scale_down(self) -> bool:
        ready = self.ready_replicas()
        if len(ready) <= self.min_replicas:
            return False
        rep = ready[-1]
        rep.state = R_DEAD  # drains immediately: pick() skips it
        log.info("autoscale: -1 replica (%s)", rep.replica_id)
        # pin the stop job to the worker hosting the replica — any other
        # worker's active_servers has no such replica_id and the HTTP
        # server would leak for the life of the right worker's process
        req = dict(self.spec.get("requirements", {}))
        req["worker_id"] = rep.worker_id
        self.master.submit({"type": "serve_stop",
                            "replica_id": rep.replica_id,
                            "requirements": req})
        return True

    def reap_and_heal(self) -> None:
        """Replace dead replicas down to min_replicas (the reference gateway
        reports unhealthy endpoints back to the deployment FSM)."""
        with self._lock:
            live = [r for r in self.replicas
                    if r.state in (R_READY, R_DISPATCHED)]
            need = self.min_replicas - len(live)
        for _ in range(max(0, need)):
            self._dispatch_one()


class InferenceGateway:
    """HTTP /predict facade with failover routing + queue-depth autoscaling
    (reference: device_model_inference.py:32-143)."""

    def __init__(self, deployment: Deployment, host: str = "127.0.0.1",
                 port: int = 0, high_water: float = 2.0,
                 low_water: float = 0.25, scale_interval: float = 0.5,
                 retry_backoff_s: float = 0.05):
        self.dep = deployment
        # AtomicCounter (utils/metrics.py): += on the threading server
        # would race and drift the autoscaler's load signal; the gauge is
        # bound so it publishes under the counter's own lock
        self._inflight = _mx.AtomicCounter(gauge="serving.gateway_inflight")
        self.high_water = high_water
        self.low_water = low_water
        self.scale_interval = scale_interval
        self.retry_backoff_s = retry_backoff_s
        self._stop = threading.Event()
        gateway = self

        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                log.debug("gateway: " + fmt, *args)

            def _send(self, code: int, payload: dict) -> None:
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/ready":
                    n = len(gateway.dep.ready_replicas())
                    self._send(200 if n else 503,
                               {"ready_replicas": n})
                elif self.path == "/metrics":
                    # the gateway is the serving tier's scrape point:
                    # inflight/forward/failover gauges + the whole registry
                    from ..utils.prometheus import write_metrics_response

                    write_metrics_response(self)
                else:
                    self._send(404, {"error": f"no route {self.path}"})

            def do_POST(self):
                if self.path != "/predict":
                    self._send(404, {"error": f"no route {self.path}"})
                    return
                n = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(n)
                gateway._inflight.inc()
                try:
                    code, payload = gateway.forward(body)
                    self._send(code, payload)
                finally:
                    gateway._inflight.dec()

        self._server = ThreadingHTTPServer((host, port), Handler)
        self.port = self._server.server_address[1]
        self._thread: Optional[threading.Thread] = None
        self._scaler: Optional[threading.Thread] = None

    @property
    def inflight(self) -> int:
        return self._inflight.value()

    # ---------------------------------------------------------- routing
    def forward(self, body: bytes, tries: int = 3) -> tuple[int, dict]:
        """Round-robin with failover: a replica that errors at the transport
        level is marked DEAD and the request retries elsewhere."""
        t0 = time.perf_counter()
        try:
            with recorder.span("serving.forward"):
                return self._forward(body, tries)
        finally:
            _mx.observe("serving.gateway_forward_s",
                        time.perf_counter() - t0)

    def _forward(self, body: bytes, tries: int) -> tuple[int, dict]:
        for attempt in range(tries):
            if attempt:
                # short exponential backoff between failover attempts — a
                # replacement replica needs a beat to come READY, and
                # hammering the next pick during a correlated outage just
                # burns the retry budget in microseconds
                time.sleep(self.retry_backoff_s * (2 ** (attempt - 1)))
            rep = self.dep.pick()
            if rep is None:
                return 503, {"error": "no ready replicas"}
            req = urllib.request.Request(
                rep.endpoint + "/predict", data=body,
                headers={"Content-Type": "application/json"})
            try:
                with urllib.request.urlopen(req, timeout=30) as r:
                    return r.status, json.loads(r.read() or b"{}")
            except urllib.error.HTTPError as e:
                if e.code < 500:
                    # the replica is alive and rejected the request (bad
                    # input): surface the error, don't kill the replica —
                    # a client-side 4xx must never take a healthy replica
                    # out of rotation
                    try:
                        return e.code, json.loads(e.read() or b"{}")
                    except (json.JSONDecodeError, OSError):
                        return e.code, {"error": f"replica returned {e.code}"}
                # 5xx: the replica itself is failing — treat like a
                # transport error: mark DEAD, heal, retry elsewhere
                log.warning("replica %s returned %d; rerouting",
                            rep.replica_id, e.code)
                _mx.inc("serving.gateway_failovers")
                self.dep.mark_dead(rep)
                self.dep.reap_and_heal()
            except (urllib.error.URLError, OSError, json.JSONDecodeError):
                log.warning("replica %s unreachable; rerouting",
                            rep.replica_id)
                _mx.inc("serving.gateway_failovers")
                self.dep.mark_dead(rep)
                self.dep.reap_and_heal()
        return 502, {"error": "all replicas failed"}

    # ------------------------------------------------------- autoscaling
    def _scale_loop(self) -> None:
        while not self._stop.wait(self.scale_interval):
            ready = len(self.dep.ready_replicas())
            load = self._inflight.value()
            if ready == 0:
                self.dep.reap_and_heal()
            elif load / ready > self.high_water:
                self.dep.scale_up()
            elif load / ready < self.low_water:
                self.dep.scale_down()

    # ---------------------------------------------------------- lifecycle
    def start(self) -> "InferenceGateway":
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True)
        self._thread.start()
        self._scaler = threading.Thread(target=self._scale_loop, daemon=True)
        self._scaler.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        self._server.shutdown()
        self._server.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
