"""Model-serving scheduler — deploy FSM, inference gateway, autoscaler.

(reference: computing/scheduler/model_scheduler/ ~8k LoC —
device_model_deployment.py:37 start_deployment packages a model and brings
up per-device inference containers with readiness polling;
device_model_inference.py:32-143 is the gateway that routes /predict to
ready devices; autoscaling rides the SaaS. Here the same three roles are
local-first over fedml_tpu's own scheduler agents:)

- Deployment.deploy(): package (model spec + params/checkpoint) → submit one
  "serve" job per replica through the MasterAgent → workers start in-process
  HTTP replicas (serving/inference_runner.py) → poll /ready until live.
  FSM per replica: DISPATCHED → READY | SUSPECT | DEAD. SUSPECT is the
  probation state (ISSUE 9): a replica that failed a request window is
  re-probed (/ready with exponential backoff) instead of being removed
  forever — a transient stall rejoins the pool; only a probation that
  times out goes DEAD and triggers healing.
- InferenceGateway: HTTP /predict facade. Routing is LOAD-AWARE: among
  READY replicas the one with the fewest gateway-tracked in-flight
  requests wins (round-robin breaks ties), so a replica with a long
  decode queued doesn't keep collecting traffic. Above the configured
  `shed_watermark` (fleet-wide in-flight per ready replica) the gateway
  SHEDS with 429 + Retry-After — overload degrades to fast refusal, not
  piled-up timeouts. Streams (`"stream": true`) relay SSE events
  chunk-by-chunk; a stream cut by replica death mid-response is
  transparently re-served from token 0 on a survivor for deterministic
  (greedy) requests — already-relayed tokens are deduped so the client's
  total stream is byte-identical to an unkilled run, and an unpinned
  stream whose replay diverges (the survivor swapped mid-rolling-update)
  is continued via a prompt+delivered-prefix re-issue instead of erroring
  — and surfaced as a terminal error event for sampled requests
  (re-running them would change the tokens; a half-stream must never
  look complete).
- Deployment.rolling_update(): the federated model-churn path — round-N
  LoRA adapters published through utils/artifacts.py are hot-swapped
  into each replica IN TURN via its /swap endpoint (no restart, no
  KV-cache teardown; engine story in serving/engine.py), with /info
  polled until the replica reports the new model_version before the
  next one swaps. Requests keep flowing the whole time; per-request
  `model_version` pinning (409 → gateway reroutes to a sibling) keeps a
  mixed-version window honest for callers that care.
- Autoscaler: queue-depth scaling — the gateway tracks in-flight requests;
  above high_water x replicas it submits another serve job, below low_water
  it retires one (min/max bounds). The same policy shape as the reference's
  target-concurrency autoscaler, with XLA-friendly in-process replicas
  instead of docker containers.

TPU note: replicas on one host share the chip; scale-out here exists for
fault tolerance and request pipelining (host-side pre/post-processing
overlaps device steps). Cross-host replicas ride the same job spec over a
broker/grpc comm backend unchanged.
"""
from __future__ import annotations

import json
import logging
import math
import threading
import time
import urllib.error
import urllib.request
import uuid
from typing import Any, Optional

from ..utils import metrics as _mx
from ..utils.events import recorder

log = logging.getLogger(__name__)

R_DISPATCHED = "DISPATCHED"
R_READY = "READY"
R_SUSPECT = "SUSPECT"
R_DEAD = "DEAD"


class _StreamCut(RuntimeError):
    """An upstream SSE stream died before its terminal event."""


class _ClientGone(RuntimeError):
    """The DOWNSTREAM client hung up mid-relay (a write to the handler's
    socket failed). Distinct from _StreamCut on purpose: the replica is
    healthy, so the gateway must not suspect it or burn a failover
    re-decode on a socket nobody is reading."""


class _StalePin(RuntimeError):
    """A pinned stream straddled its replica's hot swap (the replica
    emitted a terminal 409-coded error event): the replica is HEALTHY
    and now serves a newer version — reroute to a sibling like the
    HTTP-level 409, never suspect."""


class _ReplayDiverged(RuntimeError):
    """A greedy failover replay produced a DIFFERENT token inside the
    already-relayed prefix — the survivor serves other weights (e.g. a
    rolling update swapped it between the cut and the retry). The
    survivor is healthy — never suspected. For an UNPINNED stream the
    gateway recovers by re-issuing a CONTINUATION (prompt + the tokens
    the client already has, remaining budget) — the same
    prefix-from-old-weights/suffix-under-new semantics an in-place hot
    swap already gives unpinned in-flight streams, so nothing is
    fabricated. Splicing the diverged replay itself (a suffix continuing
    the SURVIVOR's prefix, not the client's) would fabricate output, and
    a PINNED stream's pin was the version guarantee — those surface a
    terminal error."""


def fleet_knobs(sv: dict) -> tuple[dict, dict]:
    """serve_args/serve-spec dict -> (Deployment kwargs, InferenceGateway
    kwargs): the fleet-side half of THE serve-knob mapping (predictor-side
    knobs ride predictor.lm_predictor_from_serve_knobs) — config and
    operator surfaces build fleets through one translation, so knob names
    cannot drift between YAML and constructors."""
    dep_kw = {}
    if sv.get("probation_deadline_s") is not None:
        dep_kw["probation_deadline_s"] = float(sv["probation_deadline_s"])
    if sv.get("probe_backoff_s") is not None:
        dep_kw["probe_backoff_s"] = float(sv["probe_backoff_s"])
    gw_kw = {}
    if sv.get("shed_watermark") is not None:
        gw_kw["shed_watermark"] = float(sv["shed_watermark"])
    if sv.get("retry_after_s") is not None:
        gw_kw["retry_after_s"] = float(sv["retry_after_s"])
    if sv.get("affinity_routing") is not None:
        gw_kw["affinity"] = bool(sv["affinity_routing"])
    return dep_kw, gw_kw


def start_replica(spec: dict):
    """Worker-side: build a predictor from a deployment spec and serve it.
    Spec sources (first match wins):
      - "export_dir": framework-neutral flat-tensor export (serving/
        export.py — the reference's ONNX/Triton model-repo analog,
        device_model_deployment.py:720 convert_model_to_onnx); the export's
        own manifest carries the model recipe, so no other spec keys needed
      - "checkpoint_dir": orbax checkpoint from utils/checkpoint.py
      - "params": inline pytree of ndarrays (rides the tensor wire format)
    plus "model"/"num_classes"/"input_shape"/"model_args" to rebuild the
    apply_fn (reference: start_deployment's model-package unpack).
    A "chaos" dict (comm/chaos.py FaultSpec knobs) + "chaos_rank" arm the
    replica's deterministic kill schedule — the fault-injection surface
    the mid-stream failover tests drive."""
    import jax.numpy as jnp

    from ..models import hub as model_hub
    from .inference_runner import FedMLInferenceRunner
    from .predictor import JaxPredictor

    chaos = None
    if spec.get("chaos"):
        from ..comm.chaos import FaultSpec

        chaos = (spec["chaos"] if isinstance(spec["chaos"], FaultSpec)
                 else FaultSpec.from_dict(spec["chaos"]))
    chaos_kw = {"chaos": chaos, "chaos_rank": int(spec.get("chaos_rank", 0))}

    if spec.get("export_dir"):
        from .export import predictor_from_export

        pred = predictor_from_export(spec["export_dir"])
        runner = FedMLInferenceRunner(pred, port=int(spec.get("port", 0)),
                                      **chaos_kw)
        runner.start()
        return uuid.uuid4().hex[:10], runner

    if spec.get("model_kind") == "lm":
        # LLM replica: llm/TransformerLM + GreedyLMPredictor. "lm" carries
        # the model recipe, "serve" the ServeArgs.extra knobs (config.py) —
        # decode_slots > 0 brings the replica up on the continuous-batching
        # engine (serving/engine.py), otherwise per-request decode;
        # kv_page_size > 0 selects the engine's paged KV cache (with
        # kv_n_pages/prefill_chunk/prefix_cache riding the same dict).
        from ..llm.transformer import TransformerLM
        from .predictor import lm_predictor_from_serve_knobs

        lm = dict(spec.get("lm", {}))
        model = TransformerLM(
            vocab_size=int(lm["vocab_size"]),
            d_model=int(lm["d_model"]), n_layers=int(lm["n_layers"]),
            n_heads=int(lm["n_heads"]), d_ff=int(lm["d_ff"]),
            scan_layers=bool(lm.get("scan_layers", False)))
        # serve knobs go through the SAME mapping as the config route
        # (predictor.lm_predictor_from_serve_knobs) — one source of
        # defaults, the two surfaces cannot drift
        pred = lm_predictor_from_serve_knobs(
            dict(spec.get("serve", {})), model, spec["params"],
            adapters=spec.get("adapters"),
            default_max_len=int(lm.get("max_len", 256)))
        runner = FedMLInferenceRunner(pred, port=int(spec.get("port", 0)),
                                      **chaos_kw)
        runner.start()
        return uuid.uuid4().hex[:10], runner

    model = model_hub.create(spec["model"], int(spec.get("num_classes", 10)),
                             **dict(spec.get("model_args", {})))
    apply_fn = model_hub.mixed_precision_apply(
        model.apply, spec.get("compute_dtype", "float32"))
    if spec.get("checkpoint_dir"):
        import jax

        from ..algorithms import build_algorithm
        from ..config import TrainArgs
        from ..utils.checkpoint import restore_checkpoint

        # the saved server-state STRUCTURE depends on the algorithm that
        # trained it; rebuild the same template the Simulator used
        init = model_hub.init_params(
            model, tuple(spec["input_shape"]), jax.random.key(0))
        alg = build_algorithm(spec.get("federated_optimizer", "FedAvg"),
                              apply_fn, TrainArgs(), 1, 1)
        _r, server, _c, _h, _hist = restore_checkpoint(
            spec["checkpoint_dir"], alg.server_init(init))
        params = server.params
    else:
        params = jnp.asarray(spec["params"]) if not isinstance(
            spec["params"], dict) else spec["params"]
    pred = JaxPredictor(apply_fn, params)
    runner = FedMLInferenceRunner(pred, port=int(spec.get("port", 0)),
                                  **chaos_kw)
    runner.start()
    return uuid.uuid4().hex[:10], runner


class _Replica:
    def __init__(self, job_id: str):
        self.job_id = job_id
        self.state = R_DISPATCHED
        self.replica_id: Optional[str] = None
        self.endpoint: Optional[str] = None
        self.worker_id: Optional[int] = None
        # gateway-tracked outstanding requests (the least-loaded routing
        # signal; mutated under the Deployment lock)
        self.inflight = 0
        # last model_version this replica reported (/info; rolling update)
        self.model_version: Optional[int] = None
        # prefix-affinity residency hint: the first-page prefix digests
        # this replica's engine advertised (X-Prefix-Digest response
        # header / the /info "prefix_digests" field) and its page
        # geometry. Written by the gateway off successful responses,
        # read lock-free at routing time — a HINT, never correctness
        self.page_size = 0
        self.prefix_digests: frozenset = frozenset()


class Deployment:
    """Deploy FSM over a MasterAgent (reference:
    device_model_deployment.py:37 start_deployment).

    `probation_deadline_s` bounds how long a SUSPECT replica gets to
    answer /ready again before it is declared DEAD and healed over;
    `probe_backoff_s` seeds the exponential re-probe interval."""

    def __init__(self, master, serve_spec: dict, min_replicas: int = 1,
                 max_replicas: int = 4, probation_deadline_s: float = 10.0,
                 probe_backoff_s: float = 0.05):
        self.master = master
        self.spec = dict(serve_spec)
        self.spec["type"] = "serve"
        self.min_replicas = min_replicas
        self.max_replicas = max_replicas
        self.probation_deadline_s = probation_deadline_s
        self.probe_backoff_s = probe_backoff_s
        self.replicas: list[_Replica] = []
        self._lock = threading.Lock()
        self._rr = 0
        # (swap body, version) of the last rolling_update that walked the
        # WHOLE fleet — probation recovery re-drives it so a replica that
        # was SUSPECT during the update can't rejoin serving stale weights
        self._adapter_target: Optional[tuple[bytes, int]] = None

    @classmethod
    def adopt(cls, endpoints: list[str], **kwargs) -> "Deployment":
        """A deployment over ALREADY-RUNNING replicas (no MasterAgent):
        the single-host shape where replicas are started in-process —
        tests, the diagnosis probe, the bench — and any setup where
        replica lifecycle is managed elsewhere. Healing/scaling are
        no-ops (there is no scheduler to submit to); probation and
        routing work unchanged."""
        dep = cls(None, {}, min_replicas=len(endpoints),
                  max_replicas=len(endpoints), **kwargs)
        for ep in endpoints:
            dep.adopt_endpoint(ep)
        return dep

    def adopt_endpoint(self, endpoint: str) -> _Replica:
        """Adopt ONE already-running replica into the pool mid-flight —
        the live-loop harness's replica-revival path (soak/loop.py): a
        chaos-killed replica's replacement runner is brought up out of
        band and joins routing here. The caller is responsible for the
        replica's model version (swap it to the fleet target BEFORE
        adopting, or the next rolling update's post-walk sweep converges
        it)."""
        with self._lock:
            i = len(self.replicas)
            rep = _Replica(f"adopted-{i}")
            rep.replica_id = f"adopted-{i}"
            rep.endpoint = endpoint.rstrip("/")
            rep.state = R_READY
            self.replicas.append(rep)
            self.max_replicas = max(self.max_replicas, len(self.replicas))
        self._publish_gauges()
        return rep

    # ------------------------------------------------------------ deploy
    def deploy(self, n_replicas: Optional[int] = None,
               timeout: float = 60.0) -> "Deployment":
        n = n_replicas if n_replicas is not None else self.min_replicas
        for _ in range(n):
            self._dispatch_one(timeout)
        self.wait_ready(n, timeout)
        return self

    def _dispatch_one(self, timeout: float = 60.0) -> Optional[_Replica]:
        if self.master is None:
            return None          # adopted deployment: nothing to dispatch
        jid = self.master.submit(dict(self.spec))
        rep = _Replica(jid)
        with self._lock:
            self.replicas.append(rep)
        threading.Thread(target=self._track, args=(rep, timeout),
                         daemon=True).start()
        return rep

    def _track(self, rep: _Replica, timeout: float = 60.0) -> None:
        """DISPATCHED -> (job result with endpoint) -> poll /ready -> READY."""
        job = self.master.wait(rep.job_id, timeout=timeout)
        if job.status != "FINISHED" or not isinstance(job.result, dict):
            rep.state = R_DEAD
            log.warning("replica job %s failed: %s", rep.job_id, job.result)
            return
        rep.replica_id = job.result["replica_id"]
        rep.worker_id = job.result.get("worker_id")
        rep.endpoint = f"http://{job.result['host']}:{job.result['port']}"
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self._probe_ready(rep):
                rep.state = R_READY
                self._publish_gauges()
                return
            time.sleep(0.05)
        rep.state = R_DEAD
        self._publish_gauges()

    def _probe_ready(self, rep: _Replica) -> bool:
        try:
            with urllib.request.urlopen(rep.endpoint + "/ready",
                                        timeout=2) as r:
                return r.status == 200
        except (urllib.error.URLError, OSError):
            return False

    def wait_ready(self, n: int, timeout: float = 60.0) -> bool:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if len(self.ready_replicas()) >= n:
                return True
            time.sleep(0.05)
        return False

    def ready_replicas(self) -> list[_Replica]:
        with self._lock:
            return [r for r in self.replicas if r.state == R_READY]

    def _publish_gauges(self) -> None:
        with self._lock:
            states = [r.state for r in self.replicas]
        _mx.set_gauge("serving.replicas_ready", states.count(R_READY))
        _mx.set_gauge("serving.replicas_suspect", states.count(R_SUSPECT))

    # ------------------------------------------------------------ routing
    def acquire(self, exclude: Optional[set] = None,
                prefer: Optional[frozenset] = None) -> Optional[_Replica]:
        """Least-loaded pick: among READY replicas, the one with the
        fewest gateway-tracked in-flight requests (round-robin breaks
        ties), with its inflight count already incremented — the caller
        MUST release(). First-ready routing piled new work onto a
        replica whose slots were already saturated while its siblings
        idled; in-flight depth is the signal the gateway actually has.
        `exclude` skips replica_ids the caller already ruled out this
        request (the 409 version-pin reroute: an idle stale replica
        would otherwise win least-loaded on every retry). `prefer`
        (prefix-affinity routing) restricts the pick to those
        replica_ids when any of them is READY and not excluded —
        otherwise the full pool competes, so affinity can only ever
        REORDER healthy candidates, never starve a request behind a
        SUSPECT/DEAD/stale preferred replica."""
        with self._lock:
            ready = [r for r in self.replicas if r.state == R_READY
                     and (not exclude or r.replica_id not in exclude)]
            if not ready:
                return None
            if prefer:
                hot = [r for r in ready if r.replica_id in prefer]
                if hot:
                    ready = hot
            self._rr += 1
            rep = min(
                (r for r in ready),
                key=lambda r: (r.inflight,
                               (self.replicas.index(r) - self._rr)
                               % max(len(self.replicas), 1)))
            rep.inflight += 1
            return rep

    def release(self, rep: _Replica) -> None:
        with self._lock:
            rep.inflight = max(0, rep.inflight - 1)

    # ----------------------------------------------------- failure states
    def mark_suspect(self, rep: _Replica) -> None:
        """A replica failed a request window: pull it from rotation and
        PROBE it instead of killing it — one bad window (GC pause, a
        long compile, a dropped connection) used to remove a replica
        permanently. Probation polls /ready with exponential backoff; an
        answer within `probation_deadline_s` returns the replica to
        READY (counted in serving.replica_recoveries), a timeout goes
        DEAD and triggers healing."""
        with self._lock:
            if rep.state != R_READY:
                return           # already suspect/dead/still starting
            rep.state = R_SUSPECT
        _mx.inc("serving.replica_suspects")
        self._publish_gauges()
        threading.Thread(target=self._probation, args=(rep,),
                         daemon=True).start()

    def _probation(self, rep: _Replica) -> None:
        deadline = time.monotonic() + self.probation_deadline_s
        backoff = self.probe_backoff_s
        while time.monotonic() < deadline:
            # read the current update target under the lock: this probe
            # thread races rolling_update's write, and the lock (not GIL
            # reference atomicity) is what makes the later
            # `is not target` re-check under the same lock coherent
            # (graftlint lock-discipline, ISSUE 13)
            with self._lock:
                target = self._adapter_target
            if self._probe_ready(rep) and self._converge_version(rep, target):
                with self._lock:
                    if rep.state != R_SUSPECT:   # scale_down won the race
                        return
                    if self._adapter_target is not target:
                        # a rolling update completed between the version
                        # check and this rejoin — loop to converge on the
                        # NEW target before returning to rotation
                        continue
                    rep.state = R_READY
                _mx.inc("serving.replica_recoveries")
                self._publish_gauges()
                log.info("replica %s recovered from probation",
                         rep.replica_id)
                return
            time.sleep(backoff)
            backoff = min(backoff * 2, 1.0)
        with self._lock:
            if rep.state != R_SUSPECT:
                return
            rep.state = R_DEAD
        _mx.inc("serving.replica_deaths")
        self._publish_gauges()
        log.warning("replica %s failed probation; healing", rep.replica_id)
        self.reap_and_heal()

    def _converge_version(self, rep: _Replica,
                          target: Optional[tuple[bytes, int]]) -> bool:
        """A replica rejoining from probation may have been SUSPECT while
        a rolling update walked the fleet (the update only swaps the
        replicas READY at entry) — returning it to rotation on the old
        adapters would silently serve stale weights behind a fleet gauge
        that says otherwise. Re-drive the last successful swap before it
        rejoins; True = replica is at the fleet version (or no update has
        ever succeeded). `target` is the (swap body, version) the caller
        read, passed in so the check and the rejoin decide against the
        SAME update. A replica AT OR AHEAD of the target counts as
        converged: ahead just means a newer update already reached it,
        and re-driving the older body would only bounce off the engine's
        monotonic-version guard (400) until probation killed a healthy
        replica."""
        if target is None:
            return True
        body, version = target
        info = self.replica_info(rep)
        if info is None:
            return False
        have = info.get("model_version")
        if have is not None and int(have) >= version:
            return True
        req = urllib.request.Request(
            rep.endpoint + "/swap", data=body,
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=10) as r:
                got = json.loads(r.read() or b"{}")
        except (urllib.error.URLError, OSError, json.JSONDecodeError):
            return False
        if int(got.get("model_version", -1)) != version:
            return False
        rep.model_version = version
        _mx.inc("serving.probation_reswaps")
        log.info("replica %s re-swapped to fleet version %d",
                 rep.replica_id, version)
        return True

    def mark_dead(self, rep: _Replica) -> None:
        """Immediate, probation-less removal — the scale-down/teardown
        path. Failure handling should go through mark_suspect."""
        rep.state = R_DEAD
        self._publish_gauges()

    # ------------------------------------------------------ rolling update
    def rolling_update(self, store, name: str, version: int,
                       timeout: float = 60.0) -> list[str]:
        """Drive a zero-downtime model update across the fleet: for each
        READY replica IN TURN, POST /swap (the replica fetches round-N
        adapters from the artifact store itself and hot-swaps them
        between decode iterations — no restart, no dropped requests),
        then poll /info until it reports `version` before touching the
        next replica. Serializing the fleet bounds the blast radius of a
        bad artifact to one replica; the mixed-version window in between
        is what per-request `model_version` pinning exists for. Returns
        the updated replica_ids; raises on the first replica that fails
        to swap or converge (after marking it SUSPECT)."""
        from ..utils.artifacts import store_spec

        body = json.dumps({"store": store_spec(store), "name": name,
                           "version": int(version)}).encode()
        updated: list[str] = []
        with recorder.span("serving.rolling_update", artifact=name,
                           version=int(version)):
            for rep in list(self.ready_replicas()):
                req = urllib.request.Request(
                    rep.endpoint + "/swap", data=body,
                    headers={"Content-Type": "application/json"})
                try:
                    with urllib.request.urlopen(req, timeout=timeout) as r:
                        got = json.loads(r.read() or b"{}")
                except (urllib.error.URLError, OSError,
                        json.JSONDecodeError) as e:
                    self.mark_suspect(rep)
                    raise RuntimeError(
                        f"rolling update: replica {rep.replica_id} failed "
                        f"to swap to {name!r}: {e}") from e
                if int(got.get("model_version", -1)) != int(version):
                    self.mark_suspect(rep)
                    raise RuntimeError(
                        f"rolling update: replica {rep.replica_id} "
                        f"reports version {got.get('model_version')} after "
                        f"swapping to {version}")
                # verify through the replica's own /info gauge — the swap
                # response could lie; the poll is what the recipe
                # documents operators check
                deadline = time.monotonic() + timeout
                while time.monotonic() < deadline:
                    info = self.replica_info(rep)
                    if info and info.get("model_version") == int(version):
                        rep.model_version = int(version)
                        break
                    time.sleep(0.05)
                else:
                    self.mark_suspect(rep)
                    raise RuntimeError(
                        f"rolling update: replica {rep.replica_id} never "
                        f"reported version {version} on /info")
                updated.append(rep.replica_id)
                _mx.inc("serving.rolling_swaps")
        # record the target only after the whole walk succeeded: a bad
        # artifact that raised above must not be re-driven onto replicas
        # recovering from probation (blast radius stays one replica)
        with self._lock:
            self._adapter_target = (body, int(version))
        _mx.set_gauge("serving.fleet_version", int(version))
        # a replica that recovered from probation DURING the walk
        # converged against the PREVIOUS target and rejoined on old
        # adapters — and the walk's entry snapshot never saw it. Sweep
        # the pool once more under the new target; a straggler that
        # cannot converge goes back through probation.
        for rep in self.ready_replicas():
            if rep.model_version == int(version):
                continue
            if not self._converge_version(rep, (body, int(version))):
                self.mark_suspect(rep)
        return updated

    def converge(self, store, name: str, version: int) -> bool:
        """Idempotent convergence sweep: bring every READY replica AT OR
        ABOVE `version` by re-driving the swap where needed — the tail of
        rolling_update as a standalone verb, for replicas that joined the
        pool OUT OF BAND after the last update walked (the live-loop
        harness's revived replicas, soak/loop.py). Unlike rolling_update
        it never bumps the fleet version and treats already-ahead
        replicas as done, so calling it twice is harmless. Returns True
        when every ready replica reports `version` or newer."""
        from ..utils.artifacts import store_spec

        body = json.dumps({"store": store_spec(store), "name": name,
                           "version": int(version)}).encode()
        ok = True
        for rep in self.ready_replicas():
            if rep.model_version is not None \
                    and rep.model_version >= int(version):
                continue
            ok = self._converge_version(rep, (body, int(version))) and ok
        return ok

    def replica_info(self, rep: _Replica) -> Optional[dict]:
        try:
            with urllib.request.urlopen(rep.endpoint + "/info",
                                        timeout=5) as r:
                return json.loads(r.read() or b"{}")
        except (urllib.error.URLError, OSError, json.JSONDecodeError):
            return None

    def versions(self) -> dict:
        """replica_id -> model_version over the live fleet (/info poll)."""
        out = {}
        for rep in self.ready_replicas():
            info = self.replica_info(rep)
            out[rep.replica_id] = (info or {}).get("model_version")
        return out

    # ------------------------------------------------------------ scaling
    def scale_up(self) -> Optional[_Replica]:
        with self._lock:
            live = [r for r in self.replicas if r.state != R_DEAD]
            if len(live) >= self.max_replicas:
                return None
        if self.master is None:
            return None
        log.info("autoscale: +1 replica")
        return self._dispatch_one()

    def scale_down(self) -> bool:
        ready = self.ready_replicas()
        if len(ready) <= self.min_replicas:
            return False
        rep = ready[-1]
        self.mark_dead(rep)  # drains immediately: routing skips it
        log.info("autoscale: -1 replica (%s)", rep.replica_id)
        if self.master is None:
            return True
        # pin the stop job to the worker hosting the replica — any other
        # worker's active_servers has no such replica_id and the HTTP
        # server would leak for the life of the right worker's process
        req = dict(self.spec.get("requirements", {}))
        req["worker_id"] = rep.worker_id
        self.master.submit({"type": "serve_stop",
                            "replica_id": rep.replica_id,
                            "requirements": req})
        return True

    def reap_and_heal(self) -> None:
        """Replace dead replicas down to min_replicas (the reference gateway
        reports unhealthy endpoints back to the deployment FSM). SUSPECT
        replicas count as live — probation decides their fate; healing
        over them would over-provision every transient stall."""
        if self.master is None:
            return
        with self._lock:
            live = [r for r in self.replicas
                    if r.state in (R_READY, R_SUSPECT, R_DISPATCHED)]
            need = self.min_replicas - len(live)
        for _ in range(max(0, need)):
            self._dispatch_one()


class InferenceGateway:
    """HTTP /predict facade with load-aware failover routing, load
    shedding, SSE stream relay with mid-stream failover, and queue-depth
    autoscaling (reference: device_model_inference.py:32-143).

    `shed_watermark` > 0 arms admission control: once fleet-wide
    in-flight requests exceed `shed_watermark × ready_replicas`, new
    requests are refused with 429 + a Retry-After header (`retry_after_s`)
    instead of queueing toward timeout — overload degrades to fast
    refusal the client can act on. Sheds ride `serving.shed_total`.

    `affinity` arms PREFIX-AFFINITY routing (ISSUE 16): replicas
    advertise which first-page prefix-cache keys are resident
    (X-Prefix-Digest/X-KV-Page-Size response headers, harvested off
    every successful forward; also on /info). The gateway hashes each
    prompt's leading page-aligned block with the engine's own chain
    hash and PREFERS a replica already holding that page — under a
    many-user Zipf mix this turns N independent prefix caches into one
    fleet-wide cache instead of N-way-diluting every hot prefix. The
    preference composes with (never overrides) the existing discipline:
    shed fires first, SUSPECT/excluded replicas are never preferred
    into, and when no advertiser is routable the pick falls back to
    plain least-loaded. Outcomes ride serving.affinity.{hits,misses,
    fallbacks}, counted once per request at its first placement."""

    def __init__(self, deployment: Deployment, host: str = "127.0.0.1",
                 port: int = 0, high_water: float = 2.0,
                 low_water: float = 0.25, scale_interval: float = 0.5,
                 retry_backoff_s: float = 0.05,
                 shed_watermark: float = 0.0, retry_after_s: float = 1.0,
                 affinity: bool = False):
        self.dep = deployment
        self.affinity = bool(affinity)
        # AtomicCounter (utils/metrics.py): += on the threading server
        # would race and drift the autoscaler's load signal; the gauge is
        # bound so it publishes under the counter's own lock
        self._inflight = _mx.AtomicCounter(gauge="serving.gateway_inflight")
        self.high_water = high_water
        self.low_water = low_water
        self.scale_interval = scale_interval
        self.retry_backoff_s = retry_backoff_s
        self.shed_watermark = float(shed_watermark)
        self.retry_after_s = float(retry_after_s)
        self._stop = threading.Event()
        gateway = self

        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                log.debug("gateway: " + fmt, *args)

            def _send(self, code: int, payload: dict,
                      headers: Optional[dict] = None) -> None:
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/ready":
                    n = len(gateway.dep.ready_replicas())
                    self._send(200 if n else 503,
                               {"ready_replicas": n})
                elif self.path == "/metrics":
                    # the gateway is the serving tier's scrape point:
                    # inflight/forward/failover gauges + the whole registry
                    from ..utils.prometheus import write_metrics_response

                    write_metrics_response(self)
                else:
                    self._send(404, {"error": f"no route {self.path}"})

            def do_POST(self):
                if self.path != "/predict":
                    self._send(404, {"error": f"no route {self.path}"})
                    return
                n = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(n)
                try:
                    # parsed once here, shared with forward_stream — the
                    # hot routing path must not decode the body twice
                    parsed = json.loads(body or b"{}")
                except json.JSONDecodeError:
                    parsed = None    # replicas 400 malformed JSON themselves
                gateway._inflight.inc()
                try:
                    if gateway._overloaded():
                        # overload degrades to FAST refusal the client
                        # can schedule around — never to a request that
                        # queues toward a timeout
                        _mx.inc("serving.shed_total")
                        self._send(
                            429,
                            {"error": "gateway overloaded; retry later",
                             "retry_after_s": gateway.retry_after_s},
                            headers={"Retry-After": str(max(1, math.ceil(
                                gateway.retry_after_s)))})
                        return
                    if isinstance(parsed, dict) and parsed.get("stream"):
                        gateway.forward_stream(body, self, parsed=parsed)
                        return
                    code, payload = gateway.forward(body, parsed=parsed)
                    self._send(code, payload)
                finally:
                    gateway._inflight.dec()

        self._server = ThreadingHTTPServer((host, port), Handler)
        self.port = self._server.server_address[1]
        self._thread: Optional[threading.Thread] = None
        self._scaler: Optional[threading.Thread] = None

    @property
    def inflight(self) -> int:
        return self._inflight.value()

    def fleet_roster(self) -> dict:
        """{process: /metrics url} for this gateway and every replica it
        knows — the serving tier's contribution to the fleet-observability
        roster (utils/obsfleet.FleetCollector consumes it directly). The
        gateway already knows its replicas' endpoints; a FleetCollector
        pointed here sees the whole serving fleet without extra config."""
        host = self._server.server_address[0]
        roster = {"gateway": f"http://{host}:{self.port}/metrics"}
        with self.dep._lock:
            reps = list(self.dep.replicas)
        for i, rep in enumerate(reps):
            if rep.endpoint:
                name = rep.replica_id or f"replica{i}"
                roster[name] = rep.endpoint.rstrip("/") + "/metrics"
        return roster

    # --------------------------------------------------- admission control
    def _overloaded(self) -> bool:
        """True when fleet-wide depth has crossed the shed watermark.
        Depth counts the CURRENT request too (it was inc'd on entry), so
        watermark W admits exactly W in-flight per ready replica."""
        if not self.shed_watermark:
            return False
        ready = len(self.dep.ready_replicas())
        if not ready:
            return False     # no-replica case stays a 503, not a shed
        return self._inflight.value() > self.shed_watermark * ready

    # ------------------------------------------------- prefix affinity
    def _affinity_prefer(self, parsed,
                         body: bytes) -> Optional[frozenset]:
        """replica_ids advertising THIS prompt's first page as resident,
        or None when affinity routing is off. Hashes the prompt's
        leading page-aligned block with the engine's own chain hash
        (engine._page_key, parent b"\\x00" — the same key the replica's
        prefix cache registered), per distinct advertised page size, so
        the probe can never drift from what replicas actually store. An
        empty frozenset means no routable advertiser (cold prefix,
        prompt shorter than a page, or a non-token request) — the
        caller counts it a miss and routes least-loaded."""
        if not self.affinity:
            return None
        if parsed is None:
            try:
                parsed = json.loads(body or b"{}")
            except json.JSONDecodeError:
                parsed = None
        toks = parsed.get("tokens") if isinstance(parsed, dict) else None
        if not isinstance(toks, list) or not toks:
            return frozenset()
        from .engine import _page_key
        digest: dict = {}        # page_size -> first-page hex digest
        pref = set()
        for rep in self.dep.ready_replicas():
            ps = rep.page_size
            if ps <= 0 or not rep.prefix_digests or len(toks) < ps:
                continue
            if ps not in digest:
                try:
                    digest[ps] = _page_key(b"\x00", toks[:ps]).hex()
                except (TypeError, ValueError, OverflowError):
                    digest[ps] = None    # non-int tokens: replica 400s it
            if digest[ps] is not None \
                    and digest[ps] in rep.prefix_digests:
                pref.add(rep.replica_id)
        return frozenset(pref)

    def _count_affinity(self, rep: _Replica,
                        prefer: Optional[frozenset]) -> None:
        """Outcome counter, called once per request at its FIRST
        placement (retries re-place the same request — counting them
        would double-weight failovers): hit = landed on an advertiser,
        fallback = an advertiser existed but was not routable
        (SUSPECT/excluded/not READY), miss = nothing advertised the
        prefix."""
        if prefer is None:
            return
        if not prefer:
            _mx.inc("serving.affinity.misses")
        elif rep.replica_id in prefer:
            _mx.inc("serving.affinity.hits")
        else:
            _mx.inc("serving.affinity.fallbacks")

    def _note_residency(self, rep: _Replica, headers) -> None:
        """Harvest a replica's residency advert off a successful
        response's X-KV-Page-Size / X-Prefix-Digest headers — the warm
        path keeps the hint fresh without an /info poll per request.
        Whole-set replacement (not a merge): the replica advertises its
        CURRENT resident first pages, and eviction must be able to
        retire stale digests."""
        if not self.affinity:
            return
        try:
            ps = int(headers.get("X-KV-Page-Size") or 0)
        except (TypeError, ValueError):
            return
        if ps <= 0:
            return
        dg = headers.get("X-Prefix-Digest")
        rep.page_size = ps
        rep.prefix_digests = frozenset(
            d for d in (dg or "").split(",") if d)

    # ---------------------------------------------------------- routing
    def forward(self, body: bytes, tries: int = 3,
                parsed: Optional[dict] = None) -> tuple[int, dict]:
        """Least-loaded with failover: a replica that errors at the
        transport level (or 5xx) goes to PROBATION and the request
        retries elsewhere; a 409 (stale version pin) reroutes to a
        sibling without suspecting anyone. With affinity routing on,
        the least-loaded pick is restricted to replicas advertising the
        prompt's first prefix page whenever one is routable. `parsed`
        is the decoded body when do_POST already parsed it."""
        t0 = time.perf_counter()
        try:
            with recorder.span("serving.forward"):
                return self._forward(body, tries, parsed)
        finally:
            _mx.observe("serving.gateway_forward_s",
                        time.perf_counter() - t0)

    def _note_409(self, e, rep, stale: set) -> tuple[int, dict]:
        """A version-pinned request hit a replica not serving the pin:
        healthy, just mid-rolling-update — never suspected. Exclude it
        for this request (an idle stale replica would win least-loaded
        again) and keep its payload for the out-of-tries tail, so the
        409 surfaces only when no replica serves the pin."""
        _mx.inc("serving.gateway_pin_reroutes")
        stale.add(rep.replica_id)
        try:
            return 409, json.loads(e.read() or b"{}")
        except (json.JSONDecodeError, OSError):
            return 409, {"error": "stale model_version"}

    def _forward(self, body: bytes, tries: int,
                 parsed: Optional[dict] = None) -> tuple[int, dict]:
        last_409: Optional[tuple[int, dict]] = None
        stale: set = set()       # replicas that 409'd this request's pin
        prefer = self._affinity_prefer(parsed, body)
        counted = False
        for attempt in range(tries):
            if attempt:
                # short exponential backoff between failover attempts — a
                # recovering/replacement replica needs a beat, and
                # hammering the next pick during a correlated outage just
                # burns the retry budget in microseconds
                time.sleep(self.retry_backoff_s * (2 ** (attempt - 1)))
            rep = self.dep.acquire(exclude=stale, prefer=prefer)
            if rep is None:
                return last_409 or (503, {"error": "no ready replicas"})
            if not counted:
                self._count_affinity(rep, prefer)
                counted = True
            req = urllib.request.Request(
                rep.endpoint + "/predict", data=body,
                headers={"Content-Type": "application/json"})
            try:
                with urllib.request.urlopen(req, timeout=30) as r:
                    self._note_residency(rep, r.headers)
                    return r.status, json.loads(r.read() or b"{}")
            except urllib.error.HTTPError as e:
                if e.code == 409:
                    last_409 = self._note_409(e, rep, stale)
                    continue
                if e.code < 500:
                    # the replica is alive and rejected the request (bad
                    # input): surface the error, don't kill the replica —
                    # a client-side 4xx must never take a healthy replica
                    # out of rotation
                    try:
                        return e.code, json.loads(e.read() or b"{}")
                    except (json.JSONDecodeError, OSError):
                        return e.code, {"error": f"replica returned {e.code}"}
                # 5xx: the replica itself is failing — probation, retry
                # elsewhere (probation re-probes and either returns it to
                # READY or declares it DEAD and heals)
                log.warning("replica %s returned %d; rerouting",
                            rep.replica_id, e.code)
                _mx.inc("serving.gateway_failovers")
                self.dep.mark_suspect(rep)
            except (urllib.error.URLError, OSError, json.JSONDecodeError):
                log.warning("replica %s unreachable; rerouting",
                            rep.replica_id)
                _mx.inc("serving.gateway_failovers")
                self.dep.mark_suspect(rep)
            finally:
                self.dep.release(rep)
        return last_409 or (502, {"error": "all replicas failed"})

    # --------------------------------------------------------- streaming
    def forward_stream(self, body: bytes, handler, tries: int = 3,
                       parsed: Optional[dict] = None) -> None:
        """Relay an SSE stream from a replica to the client, surviving
        replica death mid-response. Failover semantics (ISSUE 9):

        - DETERMINISTIC requests (greedy: no temperature) are re-served
          from token 0 on a survivor; tokens the client already received
          are skipped AFTER verifying they match the replay, so a
          completed stream is byte-identical to an unkilled run. When
          the replay DIVERGES (the survivor swapped mid-rolling-update
          and decodes different tokens), an UNPINNED stream is recovered
          by a CONTINUATION re-issue — prompt + the delivered tokens,
          remaining budget — which greedily continues the CLIENT's
          prefix under the current fleet, the same semantics an
          in-place hot swap gives unpinned in-flight streams
          (serving.stream_continuations); a version-PINNED stream
          surfaces the divergence as a terminal error instead (the pin
          was the guarantee, and the replay itself is never spliced).
        - NON-REPLAYABLE requests (sampling — rerunning draws different
          tokens, seeded or not: the survivor's slot/seed schedule is
          the engine's, but a half-relayed stream spliced with a rerun
          would interleave two draws) surface a terminal error event
          (code 503) — the client sees a clean failure, never a stream
          that looks complete but isn't.
        Errors before the first relayed byte keep proper status codes.
        `parsed` is the decoded request dict when do_POST already parsed
        the body (one decode on the hot path); direct callers omit it."""
        if parsed is None:
            try:
                parsed = json.loads(body or b"{}")
            except json.JSONDecodeError:
                handler._send(400, {"error": "body must be JSON"})
                return
        try:
            greedy = float(parsed.get("temperature", 0) or 0) <= 0
        except (TypeError, ValueError):
            # the replica's own validation would 400 this on the
            # non-stream path; match it instead of severing the socket
            handler._send(400, {"error": "temperature must be a number; "
                                         f"got {parsed.get('temperature')!r}"})
            return
        delivered: list = []    # token values the CLIENT has, in order
        # client index where the CURRENT upstream request's token 0 lands
        # (> 0 after a divergence-recovery continuation re-issue)
        cur_start = 0
        headers_out = False
        last_409: Optional[tuple[int, dict]] = None
        stale: set = set()      # replicas that 409'd this request's pin
        attempts = 0
        # a divergence-recovery continuation re-issue is FREE: it is not
        # a failed placement (the survivor is healthy and about to serve)
        # so it must neither consume the retry budget nor pay a backoff —
        # otherwise the canonical cut+skew recovery always lands on the
        # last attempt with nothing left for a second fault
        cont_dispatch = False
        # affinity preference from the ORIGINAL prompt: a continuation
        # re-issue extends the same prefix, so the hint stays valid
        prefer = self._affinity_prefer(parsed, body)
        counted = False
        while True:
            if not cont_dispatch:
                if attempts >= tries:
                    break
                attempts += 1
                if attempts > 1:
                    time.sleep(self.retry_backoff_s * (2 ** (attempts - 2)))
            cont_dispatch = False
            rep = self.dep.acquire(exclude=stale, prefer=prefer)
            if rep is None:
                break
            if not counted:
                self._count_affinity(rep, prefer)
                counted = True
            req = urllib.request.Request(
                rep.endpoint + "/predict", data=body,
                headers={"Content-Type": "application/json"})
            try:
                with urllib.request.urlopen(req, timeout=120) as r:
                    self._note_residency(rep, r.headers)
                    for ev in self._sse_events(r):
                        if "token" in ev:
                            # indices are the UPSTREAM request's frame;
                            # delivered[cur_start:] is that frame's
                            # already-relayed prefix
                            local = len(delivered) - cur_start
                            idx = int(ev.get("index", local))
                            if idx < local:
                                # replayed prefix: dedupe — but VERIFY it
                                # matches what the client already has (a
                                # survivor swapped mid-rolling-update
                                # decodes different tokens; splicing the
                                # replay itself would fabricate a
                                # cross-version stream)
                                if ev.get("token") != delivered[
                                        cur_start + idx]:
                                    raise _ReplayDiverged(
                                        f"token {idx} differs on replay")
                                continue
                            if not headers_out:
                                self._open_sse(handler)
                                headers_out = True
                            if cur_start:
                                ev = {**ev, "index": cur_start + idx}
                            self._relay(handler, ev)
                            delivered.append(ev.get("token"))
                        elif ev.get("done"):
                            if not headers_out:
                                self._open_sse(handler)
                                headers_out = True
                            if cur_start and "generated_tokens" in ev:
                                # a continuation's done event only knows
                                # its own suffix; the client's stream is
                                # the whole delivered sequence
                                ev = {**ev,
                                      "generated_tokens": list(delivered)}
                            self._relay(handler, ev)
                            return
                        elif "error" in ev:
                            if ev.get("code") == 409:
                                # pinned stream straddled that replica's
                                # hot swap: replica healthy, just newer —
                                # reroute like the HTTP-level 409
                                raise _StalePin(
                                    ev.get("error", "stale model_version"))
                            # replica-side terminal error event: the
                            # stream is dead on that replica — treat like
                            # a cut (failover if replayable)
                            raise _StreamCut(ev.get("error", "replica error"))
                    # upstream closed without done/error: a cut stream
                    raise _StreamCut("stream ended without done")
            except _ClientGone:
                # OUR client went away, not the replica — no suspect, no
                # retry: nothing downstream can receive another byte
                log.info("client hung up mid-stream (served by %s); "
                         "aborting relay", rep.replica_id)
                _mx.inc("serving.client_disconnects")
                return
            except _ReplayDiverged as e:
                # the survivor is HEALTHY and serves a different model
                # version than the one that produced the client's prefix
                # (a rolling update landed between the cut and the
                # replay) — never suspected either way
                _mx.inc("serving.stream_replay_divergences")
                cont = self._continuation_body(parsed, delivered)
                if cont is not None:
                    # UNPINNED greedy stream: continue the CLIENT's
                    # prefix under the current fleet — re-issue with
                    # prompt + delivered tokens and the remaining
                    # budget. This is exactly what an in-place hot swap
                    # mid-stream already gives unpinned streams (prefix
                    # from the old weights, greedy suffix under the
                    # new), so nothing is fabricated. ISSUE 15's soak
                    # bar (zero non-2xx through kills DURING rolling
                    # updates) rides this path.
                    log.warning(
                        "stream failover replay diverged via %s (%s); "
                        "continuing the delivered prefix under the "
                        "current fleet", rep.replica_id, e)
                    _mx.inc("serving.stream_continuations")
                    body, done_ev = cont
                    if body is None:
                        # budget already fully delivered — only the
                        # terminal event was lost with the dead replica
                        try:
                            self._relay(handler, done_ev)
                        except (_ClientGone, OSError):
                            pass
                        return
                    cur_start = len(delivered)
                    cont_dispatch = True
                    continue
                # PINNED (the pin WAS the version guarantee) or a body
                # without tokens/budget to rebuild from: clean terminal
                # error, no further retries
                log.warning("stream failover replay diverged via %s: %s",
                            rep.replica_id, e)
                try:
                    if headers_out:
                        self._relay(handler, {
                            "error": "replica lost mid-stream and the "
                                     "failover replay diverged (model "
                                     "version changed?)", "code": 503})
                    else:
                        handler._send(503, {
                            "error": "replica lost mid-stream and the "
                                     "failover replay diverged"})
                except (_ClientGone, OSError):
                    pass
                return
            except _StalePin as e:
                # mid-stream 409 event: the replica swapped under a
                # pinned stream — healthy, never suspected; retry a
                # sibling (greedy replay-verify dedupes any prefix the
                # client already has)
                _mx.inc("serving.gateway_pin_reroutes")
                stale.add(rep.replica_id)
                last_409 = (409, {"error": str(e)})
                continue
            except urllib.error.HTTPError as e:
                if e.code == 409:
                    last_409 = self._note_409(e, rep, stale)
                    continue
                if e.code < 500:
                    try:
                        payload = json.loads(e.read() or b"{}")
                    except (json.JSONDecodeError, OSError):
                        payload = {"error": f"replica returned {e.code}"}
                    try:
                        if headers_out:
                            # a post-failover 4xx after bytes went out:
                            # a second status line would corrupt the open
                            # SSE body — terminal error event instead
                            self._relay(handler,
                                        {"error": payload.get(
                                            "error", f"replica returned "
                                                     f"{e.code}"),
                                         "code": e.code})
                        else:
                            handler._send(e.code, payload)
                    except (_ClientGone, OSError):
                        pass
                    return
                _mx.inc("serving.gateway_failovers")
                self.dep.mark_suspect(rep)
            except (_StreamCut, urllib.error.URLError, OSError,
                    ConnectionError, json.JSONDecodeError) as e:
                log.warning("stream via %s cut: %s; %s", rep.replica_id, e,
                            "re-serving on a survivor"
                            if greedy or not (headers_out or delivered)
                            else "surfacing")
                _mx.inc("serving.gateway_failovers")
                _mx.inc("serving.stream_failovers")
                self.dep.mark_suspect(rep)
                if not greedy and (headers_out or delivered):
                    # non-replayable AND bytes already reached the
                    # client: clean failure, never a fake done. A
                    # sampled stream cut BEFORE its first byte retries
                    # fresh on a survivor — nothing was relayed, so
                    # there is nothing to splice
                    try:
                        if headers_out:
                            self._relay(handler, {
                                "error": "replica lost mid-stream; sampled "
                                         "request is not replayable",
                                "code": 503})
                        else:
                            handler._send(
                                503, {"error": "replica lost mid-stream; "
                                               "sampled request is not "
                                               "replayable"})
                    except (_ClientGone, OSError):
                        pass
                    return
            finally:
                self.dep.release(rep)
        # out of tries / no replicas (a mid-stream pin reroute that ran
        # out of siblings keeps its 409, not a generic 502)
        try:
            if headers_out:
                code, payload = last_409 or (
                    502, {"error": "all replicas failed mid-stream"})
                self._relay(handler,
                            {"error": payload.get("error", "replica error"),
                             "code": code})
            else:
                code, payload = (last_409
                                 or (503, {"error": "no ready replicas"}))
                handler._send(code, payload)
        except (_ClientGone, OSError):
            pass

    @staticmethod
    def _continuation_body(parsed, delivered):
        """Divergence recovery for an UNPINNED stream: (new request
        body, None) to re-issue — prompt grown by the tokens the client
        already has, budget shrunk to the remainder — or (None, done
        event) when the budget was already fully delivered and only the
        terminal event was lost, or None when the stream cannot be
        continued (version-pinned, or no tokens/max_new_tokens fields
        to rebuild from)."""
        toks = parsed.get("tokens")
        mn = parsed.get("max_new_tokens")
        if parsed.get("model_version") is not None \
                or not isinstance(toks, list) \
                or not isinstance(mn, int) or isinstance(mn, bool):
            return None
        remaining = mn - len(delivered)
        if remaining <= 0:
            return None, {"done": True,
                          "generated_tokens": list(delivered)}
        return json.dumps({**parsed,
                           "tokens": list(toks) + list(delivered),
                           "max_new_tokens": remaining}).encode(), None

    @staticmethod
    def _open_sse(handler) -> None:
        """Send the SSE response head; a failed write means the CLIENT is
        gone (the replica is not involved) — raised as _ClientGone so the
        relay loop aborts instead of failing over."""
        try:
            handler.send_response(200)
            handler.send_header("Content-Type", "text/event-stream")
            handler.send_header("Cache-Control", "no-cache")
            handler.end_headers()
        except OSError as e:
            raise _ClientGone(str(e)) from e

    @staticmethod
    def _relay(handler, ev: dict) -> None:
        try:
            handler.wfile.write(b"data: " + json.dumps(ev).encode()
                                + b"\n\n")
            handler.wfile.flush()
        except OSError as e:
            raise _ClientGone(str(e)) from e

    @staticmethod
    def _sse_events(resp):
        """Incremental SSE parse: yield each `data: {...}` event as a
        dict the moment its blank-line terminator arrives."""
        buf = b""
        while True:
            chunk = resp.readline()
            if not chunk:
                return
            buf += chunk
            if not buf.endswith(b"\n"):
                continue
            line = buf.strip()
            buf = b""
            if not line or not line.startswith(b"data:"):
                continue
            try:
                yield json.loads(line[len(b"data:"):].strip())
            except json.JSONDecodeError:
                continue

    # ------------------------------------------------------- autoscaling
    def _scale_loop(self) -> None:
        while not self._stop.wait(self.scale_interval):
            ready = len(self.dep.ready_replicas())
            load = self._inflight.value()
            if ready == 0:
                self.dep.reap_and_heal()
            elif load / ready > self.high_water:
                self.dep.scale_up()
            elif load / ready < self.low_water:
                self.dep.scale_down()

    # ---------------------------------------------------------- lifecycle
    def start(self) -> "InferenceGateway":
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True)
        self._thread.start()
        self._scaler = threading.Thread(target=self._scale_loop, daemon=True)
        self._scaler.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        self._server.shutdown()
        self._server.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
