"""Shared 2-replica fleet harness — the scaffolding bench.py's
bench_serving_fleet rows and the `fleet_rolling_update_smoke` diagnosis
probe both drive (precedent: the `_forced_2dev_subprocess` helper the
device-forcing diagnosis probes share): an engine-backed LM deployment
with v1 LoRA adapters live and a deliberately-different v2 tree ready to
publish, plus the closed-loop load helpers whose 599-on-connection-failure
accounting the zero-dropped-request bars rely on. Changing the /swap body
shape or the dropped-request accounting is ONE edit here, not a lockstep
pair. Not a production surface — fleets are built through
api.model_deploy / api.model_gateway."""
from __future__ import annotations

import json
import tempfile
import threading
import time
import urllib.error
import urllib.request


def post(url: str, payload: dict,
         timeout: float = 120.0) -> tuple[int, float]:
    """POST JSON -> (status, latency_s). A connection-level failure IS a
    dropped request: it returns 599 so it counts against a zero-non-2xx
    bar (and keeps the calling load thread alive) instead of vanishing
    with an exception."""
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    t0 = time.perf_counter()
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            r.read()
            return r.status, time.perf_counter() - t0
    except urllib.error.HTTPError as e:
        e.read()
        return e.code, time.perf_counter() - t0
    except (urllib.error.URLError, OSError):
        return 599, time.perf_counter() - t0


class FleetHarness:
    """N engine-backed LM replicas adopted into a Deployment. Gateways
    opened through gateway() are tracked and torn down with the replicas
    by close()."""

    def __init__(self, *, vocab_size: int = 64, d_model: int = 32,
                 n_layers: int = 1, n_heads: int = 2, d_ff: int = 64,
                 slots: int = 2, max_len: int = 32, lora_rank: int = 2,
                 prompt_len: int = 6, n_replicas: int = 2):
        import jax
        import jax.numpy as jnp
        import numpy as np

        from ..llm.lora import lora_init
        from ..llm.transformer import TransformerLM
        from .inference_runner import FedMLInferenceRunner
        from .predictor import GreedyLMPredictor
        from .scheduler import Deployment

        self.model = TransformerLM(
            vocab_size=vocab_size, d_model=d_model, n_layers=n_layers,
            n_heads=n_heads, d_ff=d_ff, scan_layers=True)
        self.params = self.model.init(
            jax.random.key(0), jnp.zeros((1, 8), jnp.int32))["params"]
        self.adapters_v1 = lora_init(jax.random.key(1), self.params,
                                     rank=lora_rank, a_std=0.2)
        # v2 = a deliberately different tree, so a completed swap is
        # observable in the decoded tokens, not just the version gauge
        self.adapters_v2 = jax.tree.map(
            lambda a: a * -1.1 + 0.05, self.adapters_v1)
        self.prompt = np.random.RandomState(0).randint(
            1, vocab_size, prompt_len).tolist()
        self.runners = [FedMLInferenceRunner(
            GreedyLMPredictor(self.model, self.params,
                              adapters=self.adapters_v1, max_len=max_len,
                              kv_cache=True, decode_slots=slots),
            port=0).start() for _ in range(n_replicas)]
        self.dep = Deployment.adopt(
            [f"http://127.0.0.1:{r.port}" for r in self.runners])
        self._gateways: list = []
        self._load_stops: list = []
        self._store_dir: str | None = None

    def gateway(self, **kw):
        from .scheduler import InferenceGateway

        gw = InferenceGateway(self.dep, scale_interval=30, **kw).start()
        self._gateways.append(gw)
        return gw

    def sustained_load(self, url: str, n_threads: int, payload: dict):
        """Closed-loop load until the returned stop() runs; the results
        list of (status, latency_s) grows live."""
        results: list = []
        stop = threading.Event()
        lock = threading.Lock()

        def hit():
            while not stop.is_set():
                res = post(url, dict(payload))
                with lock:
                    results.append(res)

        threads = [threading.Thread(target=hit, daemon=True)
                   for _ in range(n_threads)]
        for t in threads:
            t.start()

        def stop_load(timeout: float = 30.0):
            stop.set()
            for t in threads:
                t.join(timeout=timeout)

        self._load_stops.append(stop)
        return results, stop_load

    def burst(self, url: str, n_threads: int, payload: dict,
              duration_s: float) -> list:
        """n_threads clients in closed loop for duration_s ->
        [(status, latency_s), ...]."""
        results, stop_load = self.sustained_load(url, n_threads, payload)
        time.sleep(duration_s)
        stop_load()
        return results

    def publish_and_roll(self, version: int = 2,
                         timeout: float = 60.0) -> tuple[list, float]:
        """Publish the v2 adapter tree under `version` to a temp
        FileArtifactStore and drive Deployment.rolling_update ->
        (updated replica_ids, swap wall seconds)."""
        import jax
        import numpy as np

        from ..utils.artifacts import FileArtifactStore, adapter_name

        # the store must OUTLIVE this call: the Deployment records it as
        # its adapter target, and a replica recovering from probation
        # AFTER the walk converges by re-driving /swap from that root —
        # a deleted tempdir would turn every probe into a 400 and
        # probation would declare the healthy replica DEAD
        if self._store_dir is None:
            self._store_dir = tempfile.mkdtemp(prefix="fleet-adapters-")
        store = FileArtifactStore(self._store_dir)
        store.put(adapter_name(version),
                  jax.tree.map(np.asarray, self.adapters_v2))
        t0 = time.perf_counter()
        updated = self.dep.rolling_update(
            store, adapter_name(version), version=version,
            timeout=timeout)
        swap_s = time.perf_counter() - t0
        return updated, swap_s

    def close(self) -> None:
        # a caller that raised before its stop_load() must not leave
        # closed-loop threads spinning 599s against a dead gateway
        for stop in self._load_stops:
            stop.set()
        for gw in self._gateways:
            try:
                gw.stop()
            except Exception:  # noqa: BLE001 — teardown is best-effort
                pass
        for r in self.runners:
            r.stop()
        if self._store_dir is not None:
            import shutil

            shutil.rmtree(self._store_dir, ignore_errors=True)
            self._store_dir = None
