"""Continuous-batching decode engine: slot-based LLM serving on one
persistent, donated KV cache.

Why: the per-request serving path (serving/predictor.py GreedyLMPredictor)
runs each request's prefill+decode as its own device program end-to-end, so
N concurrent users get N serialized programs — aggregate tokens/sec is flat
in concurrency while the chip idles between requests. The decode plumbing
already supports per-row write positions (llm/decode.py `step(params,
adapters, cache, pos, token)` with `pos: [B]`), which is exactly the
primitive continuous batching needs; this module turns it into an engine
(the vLLM-style iteration-level scheduler, minus paging: slots are
fixed-stride rows of one cache).

Shape of the thing:

- The engine owns S decode *slots* backed by ONE persistent KV cache
  (`{"k","v"}: [L, S, max_len, H, Dh]`) that stays device-resident across
  requests — no per-request cache allocation, and every jitted call
  DONATES the carry so XLA updates it in place.
- Admission: a free slot + a waiting request -> one bucketed prefill
  (prompts right-padded to a power-of-two bucket, real length traced; same
  bucketing contract as the per-request path) whose K/V rows are written
  into the persistent cache at the slot index via `dynamic_update_slice`
  over the slot axis. The prefill's last-position logits yield the
  request's FIRST token inside the same program.
- Every engine iteration advances ALL slots one token through a single
  jitted step with per-slot `pos`, per-slot traced temperature + rng seed,
  and an active-mask so idle slots are inert (their K/V writes land on
  frozen positions and are fully overwritten by the next admission's
  prefill row).
- Retirement is decided ON DEVICE: a slot deactivates when it hits its
  per-request token budget (`limit`) or emits `eos_id`; the host merely
  observes the mask in fetched frames, completes the ticket, and returns
  the slot to the free list.
- The host loop dispatches ahead: step/admit outputs queue as device
  arrays and are fetched in small chunks (`fetch_chunk`), so admission and
  retirement bookkeeping overlap device execution — no per-step
  `device_get` barrier.

Compiled-program set stays BOUNDED: one step program (all S slots, every
temperature/seed traced) + one admit program per prompt bucket
(log2(max_len) of them at most). `program_counts()` exposes the live jit
cache sizes; tests pin them.

Capacity contract per slot: `prompt_len + max_new_tokens <= max_len`
(no step bucketing — the engine emits exactly the tokens asked for, so
unlike the per-request path max_new_tokens is not rounded up).

Equivalence contract: for identical prompts, greedy engine output is
token-identical to the per-request path — the slot axis is data-parallel
through the decode math (pinned in tests/test_serving_engine.py).

Telemetry rides the existing planes: `serving.ttft` / `serving.tbt`
histograms, `serving.slots_active` gauge, `serving.tokens_total` counter,
`serving.engine.*` counters, and `serving.engine.admit` / `.fetch` spans
on the Chrome trace — all visible in `/metrics` and `python -m fedml_tpu
top`.
"""
from __future__ import annotations

import logging
import threading
import time
from collections import deque
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..utils import metrics as _mx
from ..utils.events import recorder
from .predictor import InvalidRequest, _bucket

log = logging.getLogger(__name__)
Pytree = Any


class Ticket:
    """Per-request handle: the HTTP handler blocks on `result()` while the
    engine thread decodes — requests no longer serialize through one
    global jit call; concurrency is bounded by slots, not threads."""

    __slots__ = ("_done", "_tokens", "_error", "t_submit", "t_first")

    def __init__(self):
        self._done = threading.Event()
        self._tokens: list[int] = []
        self._error: Optional[BaseException] = None
        self.t_submit = time.perf_counter()
        self.t_first: Optional[float] = None

    def result(self, timeout: Optional[float] = None) -> list[int]:
        """Block until the request retires; returns the generated tokens
        (the eos token, when one ended generation, is included)."""
        if not self._done.wait(timeout):
            raise TimeoutError("decode engine ticket not done "
                               f"after {timeout}s")
        if self._error is not None:
            raise self._error
        return list(self._tokens)

    def done(self) -> bool:
        return self._done.is_set()


class _Request:
    __slots__ = ("tokens", "max_new", "temperature", "seed", "ticket")

    def __init__(self, tokens, max_new, temperature, seed):
        self.tokens = tokens
        self.max_new = max_new
        self.temperature = temperature
        self.seed = seed
        self.ticket = Ticket()


class _SlotState:
    """Host-side view of an occupied slot (the device mask is the source
    of truth for retirement; this mirrors it frame-by-frame)."""

    __slots__ = ("req", "out", "t_first")

    def __init__(self, req: _Request):
        self.req = req
        self.out: list[int] = []
        self.t_first: Optional[float] = None


class DecodeEngine:
    """S-slot continuous-batching decoder over llm/decode.py's functional
    prefill/step.

    `model` is a llm.TransformerLM (its n_layers/n_heads/d_model size the
    cache); `params`/`adapters` may be unrolled or scan-layout (stacked
    here, pass-through if already stacked) and float or int8 {q,s}.
    `eos_id=None` disables eos retirement (requests always run their full
    max_new_tokens — the mode the greedy-equivalence contract is pinned
    in). Sampling: per-slot traced temperature; temperature <= 0 means
    greedy; full-vocab categorical (top_k requests stay on the
    per-request path, which compiles a static-k cutoff).

    `mesh` (a jax Mesh with an `mp` axis) runs the engine TENSOR-PARALLEL:
    weights and the persistent KV cache shard over `mp` via the
    parallel/partition.py rule registry (`partition_rules` overrides the
    default `transformer_lm` table) — the scale-out path for models whose
    KV cache + weights exceed one chip's HBM. Greedy output is
    token-identical across mp sizes (pinned at mp=1 vs mp=2 in tests)."""

    def __init__(self, model, params: Pytree,
                 adapters: Optional[Pytree] = None, *,
                 n_slots: int = 4, max_len: int = 256,
                 eos_id: Optional[int] = None,
                 dtype=None, fetch_chunk: int = 2,
                 mesh=None, partition_rules=None):
        from ..llm.decode import (
            make_kv_decode, stack_adapter_blocks, stack_blocks,
        )

        if n_slots < 1:
            raise ValueError(f"n_slots must be >= 1; got {n_slots}")
        self.model = model
        self.max_len = int(max_len)
        self.n_slots = int(n_slots)
        self.fetch_chunk = max(1, int(fetch_chunk))
        # -1 never matches a token id, so eos retirement is inert
        self._eos = -1 if eos_id is None else int(eos_id)
        self.adapters = stack_adapter_blocks(adapters, model.n_layers)
        self.params = stack_blocks(params, model.n_layers)
        if dtype is not None:
            kv_dtype = jnp.dtype(dtype)
        else:
            floats = [l for l in jax.tree.leaves(self.params)
                      if jnp.issubdtype(l.dtype, jnp.floating)]
            kv_dtype = floats[0].dtype if floats else jnp.float32
        self._kv_dtype = kv_dtype

        # ------------------------------------------ tensor-parallel layout
        # `mesh` with an `mp` axis runs the engine tensor-parallel: weights
        # take the Megatron column/row layout from the ONE partition-rule
        # registry (parallel/partition.py — the SAME table the round
        # programs and CentralizedTrainer resolve, so train and serve
        # layouts cannot drift), adapters replicate (they are the round
        # payload), and the persistent KV cache [L, S, max_len, H, Dh]
        # shards its HEADS axis (partition.kv_cache_spec) — the decode-side
        # continuation of the column-split attention projections. GSPMD
        # inserts the one all-reduce per block at the wo row matmul; with
        # mp=1 the placement is a no-op and the engine stays token-
        # identical to the unmeshed path (pinned in tests).
        self.mesh = mesh
        self.param_specs = None
        self.kv_spec = None
        kv_sharding = rep_sharding = None
        if mesh is not None:
            from jax.sharding import NamedSharding
            from ..parallel import partition

            if "mp" not in mesh.axis_names:
                raise ValueError(
                    f"DecodeEngine mesh axes {mesh.axis_names} have no "
                    "'mp' axis (the tensor-parallel axis the rule tables "
                    "shard over)")
            mp = mesh.shape["mp"]
            if model.n_heads % mp:
                raise ValueError(
                    f"n_heads {model.n_heads} is not divisible by mp={mp}"
                    " — the KV cache shards the heads axis")
            rules = (partition_rules
                     if partition_rules is not None
                     else partition.transformer_lm_rules("mp"))
            self.param_specs = partition.match_partition_rules(
                rules, self.params)
            self.params = partition.shard_params(
                self.params, mesh, specs=self.param_specs)
            if self.adapters is not None:
                self.adapters = partition.shard_params(
                    self.adapters, mesh, "lora")
            self.kv_spec = partition.kv_cache_spec("mp")
            kv_sharding = NamedSharding(mesh, self.kv_spec)
            rep_sharding = NamedSharding(
                mesh, jax.sharding.PartitionSpec())

        prefill, step = make_kv_decode(model.n_heads, dtype=kv_dtype)
        S, eos, max_len_ = self.n_slots, self._eos, self.max_len

        def pick(logits, temp, key):
            """Greedy/sampled select with temperature TRACED (one program
            covers both): softmax sampling computes alongside and a where
            picks — the greedy lane is bit-identical to the per-request
            path's argmax."""
            greedy = jnp.argmax(logits, -1).astype(jnp.int32)
            l = logits.astype(jnp.float32) / jnp.maximum(temp, 1e-6)[
                ..., None]
            if logits.ndim == 1:
                sampled = jax.random.categorical(key, l, -1)
            else:
                sampled = jax.vmap(
                    lambda k, row: jax.random.categorical(k, row, -1))(
                        key, l)
            return jnp.where(temp > 0.0, sampled.astype(jnp.int32), greedy)

        def _admit(params, adapters, carry, tokens, length, slot, temp,
                   seed, limit):
            """Prefill one request into slot `slot` of the donated carry:
            K/V rows land at the slot index of the persistent cache, the
            prompt's last-position logits yield the first token, and the
            slot's pos/tok/active/temp/seed/limit rows are set."""
            row, logits = prefill(params, adapters, tokens, max_len_,
                                  length=length)
            key = jax.random.fold_in(jax.random.key(seed), length)
            first = pick(logits[0], temp, key)
            start = (0, slot, 0, 0, 0)
            cache = {
                "k": jax.lax.dynamic_update_slice(
                    carry["cache"]["k"], row["k"], start),
                "v": jax.lax.dynamic_update_slice(
                    carry["cache"]["v"], row["v"], start),
            }
            # active iff the first token did not end it and there is
            # budget left (limit = length + max_new - 1: the position
            # after which no further step token is owed)
            active = (first != eos) & (length < limit)
            return {
                "cache": cache,
                "pos": carry["pos"].at[slot].set(length),
                "tok": carry["tok"].at[slot].set(first),
                "active": carry["active"].at[slot].set(active),
                "temp": carry["temp"].at[slot].set(temp),
                "seed": carry["seed"].at[slot].set(seed),
                "limit": carry["limit"].at[slot].set(limit),
            }, first

        def _step_all(params, adapters, carry):
            """Advance every slot one token through ONE program. Inactive
            slots are inert: pos frozen, tok unchanged, their (garbage)
            K/V write lands on a frozen position that the next admission's
            full prefill row overwrites."""
            cache, logits = step(params, adapters, carry["cache"],
                                 carry["pos"], carry["tok"])
            active, temp = carry["active"], carry["temp"]
            keys = jax.vmap(
                lambda s, p: jax.random.fold_in(jax.random.key(s), p + 1))(
                    carry["seed"], carry["pos"])
            nxt = pick(logits, temp, keys)
            pos2 = jnp.where(active, carry["pos"] + 1, carry["pos"])
            act2 = active & (pos2 < carry["limit"]) & (nxt != eos)
            out = {
                "cache": cache,
                "pos": pos2,
                "tok": jnp.where(active, nxt, carry["tok"]),
                "active": act2,
                "temp": temp,
                "seed": carry["seed"],
                "limit": carry["limit"],
            }
            # emitted token per slot + the entry mask saying which are real
            return out, (nxt, active)

        # the carry is DONATED: the cache never round-trips host<->device
        # and XLA may update the slot rows in place. On an mp mesh the
        # carry's output shardings are PINNED (cache on the heads split,
        # scalars-per-slot replicated): donation requires the output
        # buffer to reuse the input's layout, and an XLA-chosen resharding
        # would silently turn the in-place update into a full copy.
        if mesh is None:
            self._admit_jit = jax.jit(_admit, donate_argnums=(2,))
            self._step_jit = jax.jit(_step_all, donate_argnums=(2,))
            carry_sh = None
        else:
            # ONE carry-layout dict, used for the jit out_shardings AND the
            # initial placement below — two copies drifting apart (a new
            # carry key updated in only one) would silently turn the
            # donated in-place update into a full cache copy
            carry_sh = {
                "cache": {"k": kv_sharding, "v": kv_sharding},
                "pos": rep_sharding, "tok": rep_sharding,
                "active": rep_sharding, "temp": rep_sharding,
                "seed": rep_sharding, "limit": rep_sharding,
            }
            self._admit_jit = jax.jit(
                _admit, donate_argnums=(2,),
                out_shardings=(carry_sh, rep_sharding))
            self._step_jit = jax.jit(
                _step_all, donate_argnums=(2,),
                out_shardings=(carry_sh, (rep_sharding, rep_sharding)))

        head = model.d_model // model.n_heads
        z = (model.n_layers, S, self.max_len, model.n_heads, head)
        self._carry = {
            "cache": {"k": jnp.zeros(z, kv_dtype),
                      "v": jnp.zeros(z, kv_dtype)},
            "pos": jnp.zeros((S,), jnp.int32),
            "tok": jnp.zeros((S,), jnp.int32),
            "active": jnp.zeros((S,), bool),
            "temp": jnp.zeros((S,), jnp.float32),
            "seed": jnp.zeros((S,), jnp.uint32),
            "limit": jnp.zeros((S,), jnp.int32),
        }
        if carry_sh is not None:
            # place the persistent carry on the mesh up front — every later
            # call donates it back in the same layout
            self._carry = jax.tree.map(
                lambda a, s: jax.device_put(a, s), self._carry, carry_sh)

        self._cond = threading.Condition()
        self._waiting: deque[_Request] = deque()
        self._free: list[int] = list(range(S))
        self._slots: list[Optional[_SlotState]] = [None] * S
        self._stopping = False
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------ lifecycle
    def start(self) -> "DecodeEngine":
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="decode-engine")
        self._thread.start()
        return self

    def stop(self) -> None:
        with self._cond:
            self._stopping = True
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=10)
        self._fail_outstanding(RuntimeError("decode engine stopped"))

    # ------------------------------------------------------------ admission
    def submit(self, tokens, max_new_tokens: int,
               temperature: float = 0.0,
               seed: Optional[int] = None) -> Ticket:
        """Queue one prompt; returns the Ticket its tokens stream to.
        Capacity contract: prompt + max_new_tokens <= max_len (exact — the
        engine never buckets the token budget)."""
        tokens = [int(t) for t in tokens]
        if not tokens:
            raise InvalidRequest(
                "tokens must contain at least one prompt token")
        max_new = int(max_new_tokens)
        if max_new < 1:
            raise InvalidRequest(
                f"max_new_tokens must be >= 1; got {max_new}")
        if len(tokens) + max_new > self.max_len:
            raise InvalidRequest(
                f"prompt {len(tokens)} + max_new_tokens {max_new} exceeds "
                f"max_len {self.max_len} (engine slot capacity contract: "
                "prompt + max_new_tokens <= max_len)")
        if seed is None:
            import random as _random

            seed = _random.getrandbits(31)
        # the per-slot seed rides as a device uint32 — mask client-supplied
        # values into range instead of letting jnp.uint32 overflow on the
        # engine thread (still deterministic per seed)
        seed = int(seed) & 0xFFFFFFFF
        req = _Request(tokens, max_new, float(temperature), seed)
        with self._cond:
            if self._stopping or (self._thread is not None
                                  and not self._thread.is_alive()):
                raise RuntimeError("decode engine is stopped")
            if self._thread is None:
                raise RuntimeError("decode engine not started "
                                   "(call .start())")
            self._waiting.append(req)
            _mx.set_gauge("serving.engine.queue", len(self._waiting))
            self._cond.notify_all()
        _mx.inc("serving.engine.requests")
        return req.ticket

    # ------------------------------------------------------- introspection
    def program_counts(self) -> dict:
        """Live compiled-program counts: {"step": 1, "admit": <=
        log2(max_len)} in steady state — the retrace guard tests pin."""
        out = {}
        for name, fn in (("step", self._step_jit),
                         ("admit", self._admit_jit)):
            try:
                out[name] = fn._cache_size()
            except Exception:  # jax without the introspection hook
                out[name] = None
        return out

    # ------------------------------------------------------------ engine loop
    def _loop(self) -> None:
        # frames: ("admit", slot, first_token_dev) | ("step", toks, mask)
        pending: deque[tuple] = deque()
        try:
            while True:
                with self._cond:
                    if self._stopping:
                        break
                    idle = (not self._waiting and not pending
                            and all(s is None for s in self._slots))
                    if idle:
                        self._cond.wait(0.2)
                        continue
                self._admit_ready(pending)
                if any(s is not None for s in self._slots):
                    self._carry, (toks, mask) = self._step_jit(
                        self.params, self.adapters, self._carry)
                    pending.append(("step", toks, mask))
                # drain: normally keep `fetch_chunk` frames in flight so
                # host bookkeeping overlaps device steps; drain eagerly
                # when requests are starved for a slot (a completion frees
                # one) or nothing new was dispatched
                with self._cond:
                    starved = bool(self._waiting) and not self._free
                eager = starved or all(s is None for s in self._slots)
                while pending and (eager
                                   or len(pending) >= self.fetch_chunk):
                    self._drain(pending.popleft())
        except BaseException as e:  # noqa: BLE001 — fail tickets, not silently
            log.exception("decode engine loop died")
            _mx.inc("serving.engine.errors")
            # mark stopped FIRST so submit() refuses (and the predictor
            # falls back to the per-request path) instead of queueing
            # tickets nothing will ever complete
            with self._cond:
                self._stopping = True
            self._fail_outstanding(
                RuntimeError(f"decode engine failed: {type(e).__name__}: {e}"))

    def _admit_ready(self, pending: deque) -> None:
        while True:
            with self._cond:
                if not (self._free and self._waiting):
                    return
                req = self._waiting.popleft()
                slot = self._free.pop()
                # claim the slot in the SAME critical section as the pop:
                # a stop() racing a long admit compile must find the
                # request either in _waiting or in _slots — never in
                # between (its ticket would hang its HTTP thread 600s)
                self._slots[slot] = _SlotState(req)
                _mx.set_gauge("serving.engine.queue", len(self._waiting))
            with recorder.span("serving.engine.admit", slot=slot,
                               prompt=len(req.tokens)):
                # the SAME bucket fn as the per-request path, so both
                # paths share one bounded prompt-bucket set
                pb = min(_bucket(len(req.tokens), pow2_cap=self.max_len),
                         self.max_len)
                buf = np.zeros((1, pb), np.int32)
                buf[0, :len(req.tokens)] = req.tokens
                limit = len(req.tokens) + req.max_new - 1
                self._carry, first = self._admit_jit(
                    self.params, self.adapters, self._carry,
                    jnp.asarray(buf), jnp.int32(len(req.tokens)),
                    jnp.int32(slot), jnp.float32(req.temperature),
                    jnp.uint32(req.seed), jnp.int32(limit))
            pending.append(("admit", slot, first))
            _mx.inc("serving.engine.admissions")

    # -------------------------------------------------------------- draining
    def _drain(self, frame: tuple) -> None:
        """Materialize one queued frame and route its tokens. This is the
        only host<->device sync point; the span measures the actual wait."""
        if frame[0] == "admit":
            _kind, slot, first = frame
            with recorder.span("serving.engine.fetch", kind="admit"):
                tok = int(np.asarray(first))
            self._deliver(slot, tok, first=True)
            _mx.set_gauge("serving.slots_active",
                          sum(s is not None for s in self._slots))
            return
        _kind, toks_dev, mask_dev = frame
        with recorder.span("serving.engine.fetch", kind="step"):
            toks = np.asarray(toks_dev)
            mask = np.asarray(mask_dev)
        for slot in np.nonzero(mask)[0]:
            self._deliver(int(slot), int(toks[slot]), first=False)
        # publish the POST-delivery host occupancy, not the frame's entry
        # mask: with fetch_chunk=1 the final completing frame's entry mask
        # is >= 1 and no trailing all-inactive frame is ever dispatched —
        # an entry-mask gauge would read busy forever at idle
        _mx.set_gauge("serving.slots_active",
                      sum(s is not None for s in self._slots))

    def _deliver(self, slot: int, tok: int, first: bool) -> None:
        st = self._slots[slot]
        if st is None:
            # a frame for a slot the host already retired would mean the
            # device/host retirement conditions diverged — loud beats wrong
            log.warning("engine: token for free slot %d dropped", slot)
            return
        st.out.append(tok)
        _mx.inc("serving.tokens_total")
        now = time.perf_counter()
        if first:
            st.t_first = now
            st.req.ticket.t_first = now
            _mx.observe("serving.ttft", now - st.req.ticket.t_submit)
        done = (tok == self._eos) or (len(st.out) >= st.req.max_new)
        if done:
            # avg time-between-tokens over the request's decode phase (the
            # chunked fetch makes per-token host deltas bursty; the
            # request-level mean is the honest figure)
            if len(st.out) > 1 and st.t_first is not None:
                _mx.observe("serving.tbt",
                            (now - st.t_first) / (len(st.out) - 1))
            st.req.ticket._tokens = st.out
            st.req.ticket._done.set()
            with self._cond:
                self._slots[slot] = None
                # a stop() may have reset the free list already — don't
                # re-add the slot on top of the reset
                if not self._stopping:
                    self._free.append(slot)
                self._cond.notify_all()
            _mx.inc("serving.engine.completions")

    def _fail_outstanding(self, err: BaseException) -> None:
        with self._cond:
            reqs = list(self._waiting)
            self._waiting.clear()
            slots = [s for s in self._slots if s is not None]
            self._slots = [None] * self.n_slots
            self._free = list(range(self.n_slots))
        # last-value-wins gauges would otherwise report the pre-crash
        # depth/occupancy forever
        _mx.set_gauge("serving.engine.queue", 0)
        _mx.set_gauge("serving.slots_active", 0)
        for r in reqs:
            r.ticket._error = err
            r.ticket._done.set()
        for s in slots:
            s.req.ticket._error = err
            s.req.ticket._done.set()
