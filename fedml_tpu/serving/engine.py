"""Continuous-batching decode engine: slot-based LLM serving on one
persistent, donated KV cache.

Why: the per-request serving path (serving/predictor.py GreedyLMPredictor)
runs each request's prefill+decode as its own device program end-to-end, so
N concurrent users get N serialized programs — aggregate tokens/sec is flat
in concurrency while the chip idles between requests. The decode plumbing
already supports per-row write positions (llm/decode.py `step(params,
adapters, cache, pos, token)` with `pos: [B]`), which is exactly the
primitive continuous batching needs; this module turns it into an engine
(the vLLM-style iteration-level scheduler, minus paging: slots are
fixed-stride rows of one cache).

Shape of the thing:

- The engine owns S decode *slots* backed by ONE persistent KV cache
  (`{"k","v"}: [L, S, max_len, H, Dh]`) that stays device-resident across
  requests — no per-request cache allocation, and every jitted call
  DONATES the carry so XLA updates it in place.
- Admission: a free slot + a waiting request -> one bucketed prefill
  (prompts right-padded to a power-of-two bucket, real length traced; same
  bucketing contract as the per-request path) whose K/V rows are written
  into the persistent cache at the slot index via `dynamic_update_slice`
  over the slot axis. The prefill's last-position logits yield the
  request's FIRST token inside the same program.
- Every engine iteration advances ALL slots one token through a single
  jitted step with per-slot `pos`, per-slot traced temperature + rng seed,
  and an active-mask so idle slots are inert (their K/V writes land on
  frozen positions and are fully overwritten by the next admission's
  prefill row).
- Retirement is decided ON DEVICE: a slot deactivates when it hits its
  per-request token budget (`limit`) or emits `eos_id`; the host merely
  observes the mask in fetched frames, completes the ticket, and returns
  the slot to the free list.
- The host loop dispatches ahead: step/admit outputs queue as device
  arrays and are fetched in small chunks (`fetch_chunk`), so admission and
  retirement bookkeeping overlap device execution — no per-step
  `device_get` barrier.

Compiled-program set stays BOUNDED: one step program (all S slots, every
temperature/seed traced) + one admit program per prompt bucket
(log2(max_len) of them at most). `program_counts()` exposes the live jit
cache sizes; tests pin them.

PAGED MODE (`page_size > 0`, ISSUE 7) rebuilds the KV storage as block
allocation — the production serving memory + latency plane:

- The cache becomes a POOL `[L, kv_n_pages, page_size, H, Dh]` plus an
  int32 `[S, max_pages]` page table INSIDE the donated carry (the jitted
  step gathers each slot's pages into a virtually-contiguous sequence;
  llm/decode.py make_paged_kv_decode). Persistent HBM is
  `kv_n_pages x page_size` token rows — sized to LIVE tokens — instead
  of `S x max_len` whether slots use it or not; page 0 is the reserved
  null page that absorbs inactive/padded writes.
- Admission allocates a request's pages (ceil((prompt+max_new)/page_size),
  reserved up front so a mid-decode slot can never hit page exhaustion)
  from a host free list; retirement returns them. The free list + prefix
  map are host state — the page TABLE is the device-side structure the
  kernels consume; allocation is a host decision because prefix sharing
  keys on token content the device never sees.
- CHUNKED PREFILL: admission writes the prompt in `prefill_chunk`-sized
  pieces, ONE chunk per engine iteration, round-robin across in-flight
  admissions — decode slots advance between chunks, so a long prompt no
  longer stalls all S slots for its full prefill, and a short prompt
  admitted alongside a long one reaches its first token in time
  proportional to its OWN length.
- PREFIX CACHE: full pages of a prompt are registered in a content-hash
  chain map (hash over token IDS per page, chained — resident pages are
  ref-counted; refs==0 entries stay resident and evict LRU, leaf-first,
  only under allocation pressure). A request whose prompt prefix is
  already resident starts its chunked prefill AFTER the hit (capped at
  prompt_len - 1 so the first-token logits are always computed), so
  identical system prompts — the dominant traffic shape — stop
  recomputing K/V and their TTFT goes ~flat in prompt length.

Paged greedy output is TOKEN-IDENTICAL to the contiguous engine and the
per-request path (pinned in tests/test_paged_engine.py), and the program
set stays bounded: one paged step program + one chunk program per chunk
bucket (log2(prefill_chunk) of them at most).

DECODE RAW SPEED (ISSUE 11) — two paged-mode legs, both token-identity
pinned (tests/test_decode_kernel_spec.py):

- `paged_kernel=True` swaps the step's gather-then-attend for the fused
  Pallas paged-attention kernel (ops/paged_attention.py): pages are read
  IN PLACE through the device-side page table, the virtually-contiguous
  copy never materializes, per-token attention HBM traffic halves. The
  gather path stays as the test oracle; CPU runs the same kernel under
  interpret mode, so tier-1 exercises the real kernel body.
- `spec_decode="ngram"` attacks per-token latency itself: each
  iteration self-drafts `spec_k` tokens from the slot's OWN history
  (prompt-lookup n-gram — no second model), verifies the whole window
  in ONE batched target forward over the paged cache, and accepts the
  longest prefix the target itself would have produced. Greedy-exact by
  construction (a token is only accepted when every input before it was
  the target's own pick), and the same argument covers seeded sampling
  because the per-position rng schedule is the plain step's. Rollback
  is positional: pos advances only past accepted tokens, so the next
  window re-writes rejected positions' pages before anything reads
  them. Accept telemetry: `serving.spec.proposed` / `.accepted`
  counters, accept-rate on the `top` engine line.

Capacity contract per slot: `prompt_len + max_new_tokens <= max_len`
(no step bucketing — the engine emits exactly the tokens asked for, so
unlike the per-request path max_new_tokens is not rounded up). Paged
mode ADDS the page-budget term: ceil((prompt + max_new) / page_size)
must fit the usable pool (kv_n_pages - 1 — page 0 is reserved);
`admissible()` is the one capacity oracle the predictor's routing and
degrade refusal consult, and the submit error message states the page
math.

Equivalence contract: for identical prompts, greedy engine output is
token-identical to the per-request path — the slot axis is data-parallel
through the decode math (pinned in tests/test_serving_engine.py).

FLEET ROBUSTNESS (ISSUE 9) — the three production failure shapes a
federated deployment meets are model churn, overload, and mid-request
replica death; the engine carries the first and last:

- HOT ADAPTER SWAP: `swap_adapters(tree, version=)` replaces the LoRA
  adapter values ATOMICALLY between decode iterations — no KV-cache
  teardown, no restart, no recompile. The compiled step/admit programs
  are layout-stable because adapters are replicated (the
  `partition.TABLES["lora"]` contract), so only the VALUES may change:
  a swap whose tree structure/shapes/dtypes differ from the serving
  tree is refused (that change needs a redeploy, and silently accepting
  it would retrace every program). In-flight requests finish on the NEW
  adapters from their next step — the federated rolling-update
  semantic: round N+1's adapters take effect mid-decode rather than
  holding traffic. `model_version` is monotonic and rides the
  `serving.model_version` gauge + a `serving.swap` span.
- STREAMING TICKETS: `Ticket.stream()` yields tokens AS the host
  observes their retirement frames (granularity = `fetch_chunk`), so
  the HTTP tier can emit SSE chunks while the request still decodes;
  `result()` is unchanged.
- GRACEFUL DRAIN: `stop(drain=True)` refuses new submits and lets every
  accepted request finish (bounded by `drain_timeout_s`) before
  teardown — a scale-down or rolling replica replacement never errors a
  ticket that was already decoding.

Telemetry rides the existing planes: `serving.ttft` / `serving.tbt`
histograms, `serving.slots_active` gauge, `serving.tokens_total` counter,
`serving.engine.*` counters, and `serving.engine.admit` / `.fetch` spans
on the Chrome trace — all visible in `/metrics` and `python -m fedml_tpu
top`.
"""
from __future__ import annotations

import hashlib
import logging
import threading
import time
from collections import deque
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..utils import metrics as _mx
from ..utils import xla_ledger as _ledger
from ..utils.events import recorder
from .predictor import InvalidRequest, _bucket

log = logging.getLogger(__name__)
Pytree = Any


def _page_key(parent: bytes, tokens) -> bytes:
    """Chain hash for one prefix page: keyed on the page's TOKEN IDS (an
    int32 byte view — [12, 3] and [1, 23] must not collide the way naive
    string concatenation would) chained through the parent page's key, so
    a key identifies the FULL token prefix up to and including this page."""
    h = hashlib.blake2b(parent, digest_size=16)
    h.update(np.asarray(tokens, np.int32).tobytes())
    return h.digest()


class _PrefixEntry:
    """One resident prefix page: refs counts live users (slots decoding
    over it); kids counts resident chain extensions. Evictable only at
    refs == 0 AND kids == 0 (evicting a mid-chain page would strand its
    extensions — resident but unreachable by the incremental hash walk)."""

    __slots__ = ("page", "parent", "refs", "kids", "tick")

    def __init__(self, page: int, parent: Optional[bytes], tick: int):
        self.page = page
        self.parent = parent
        self.refs = 1
        self.kids = 0
        self.tick = tick


class _Admission:
    """One in-flight chunked admission: `row` is the slot's full page-table
    row (prefix-hit pages + freshly allocated ones), `t0` the next prompt
    position to prefill (starts at the page-aligned hit length), `keys`
    the chain hashes for every FULL prompt page (computed once at lookup,
    reused at registration)."""

    __slots__ = ("req", "slot", "row", "t0", "keys", "hit_pages", "total")

    def __init__(self, req, slot, row, t0, keys, hit_pages, total):
        self.req = req
        self.slot = slot
        self.row = row
        self.t0 = t0
        self.keys = keys
        self.hit_pages = hit_pages
        self.total = total


class Ticket:
    """Per-request handle: the HTTP handler blocks on `result()` while the
    engine thread decodes — requests no longer serialize through one
    global jit call; concurrency is bounded by slots, not threads.

    Tokens are PUSHED as the host observes their retirement frames, so
    `stream()` can relay them while the request still decodes (the SSE
    serving surface); `result()` keeps the block-until-done contract."""

    __slots__ = ("_cv", "_done", "_tokens", "_error", "t_submit", "t_first",
                 "t_done")

    def __init__(self):
        self._cv = threading.Condition()
        self._done = threading.Event()
        self._tokens: list[int] = []
        self._error: Optional[BaseException] = None
        self.t_submit = time.perf_counter()
        self.t_first: Optional[float] = None
        self.t_done: Optional[float] = None

    # engine-thread side -------------------------------------------------
    def _push(self, tok: int) -> None:
        with self._cv:
            self._tokens.append(tok)
            self._cv.notify_all()

    def _finish(self, error: Optional[BaseException] = None) -> None:
        with self._cv:
            if error is not None and self._error is None:
                self._error = error
            self._done.set()
            self._cv.notify_all()

    # caller side --------------------------------------------------------
    def result(self, timeout: Optional[float] = None) -> list[int]:
        """Block until the request retires; returns the generated tokens
        (the eos token, when one ended generation, is included)."""
        if not self._done.wait(timeout):
            raise TimeoutError("decode engine ticket not done "
                               f"after {timeout}s")
        if self._error is not None:
            raise self._error
        with self._cv:
            return list(self._tokens)

    def stream(self, timeout: Optional[float] = None):
        """Yield tokens as the engine retires them (granularity = the
        engine's `fetch_chunk` frames). `timeout` bounds the wait for
        EACH next token, not the whole request. Raises the ticket's
        error (engine crash / stop) after yielding whatever tokens
        arrived before it — the caller decides how a half-stream is
        surfaced."""
        i = 0
        while True:
            with self._cv:
                while i >= len(self._tokens) and not self._done.is_set():
                    if not self._cv.wait(timeout):
                        raise TimeoutError(
                            f"no token from the decode engine in {timeout}s")
                if i >= len(self._tokens):
                    if self._error is not None:
                        raise self._error
                    return
                tok = self._tokens[i]
            yield tok           # outside the lock: the consumer may block
            i += 1

    def done(self) -> bool:
        return self._done.is_set()


class _Request:
    __slots__ = ("tokens", "max_new", "temperature", "seed", "ticket")

    def __init__(self, tokens, max_new, temperature, seed):
        self.tokens = tokens
        self.max_new = max_new
        self.temperature = temperature
        self.seed = seed
        self.ticket = Ticket()


class _Swap:
    """One queued hot adapter swap, applied by the engine thread between
    decode iterations; `applied` releases the waiting caller."""

    __slots__ = ("adapters", "version", "applied", "error")

    def __init__(self, adapters, version: int):
        self.adapters = adapters
        self.version = version
        self.applied = threading.Event()
        self.error: Optional[BaseException] = None


def check_adapter_swap(current: Pytree, new: Pytree) -> None:
    """The layout-stability contract behind hot swap: the replacement
    adapter tree must match the serving tree's STRUCTURE, shapes, and
    dtypes exactly — those are baked into every compiled program (and,
    on a mesh, into the pinned shardings), so a mismatch would force a
    retrace (or worse, silently serve garbage). Raises ValueError naming
    the first offending leaf."""
    cur_flat = jax.tree_util.tree_flatten_with_path(current)[0]
    new_flat = jax.tree_util.tree_flatten_with_path(new)[0]
    cur_td = jax.tree_util.tree_structure(current)
    new_td = jax.tree_util.tree_structure(new)
    if cur_td != new_td:
        raise ValueError(
            "adapter swap tree structure differs from the serving tree — "
            "a structural change retraces every compiled program; "
            "redeploy the replica instead (hot swap replaces VALUES of "
            "the layout the engine was built with)")
    for (path, a), (_p, b) in zip(cur_flat, new_flat):
        if a.shape != b.shape or a.dtype != b.dtype:
            name = "/".join(str(getattr(p, "key", p)) for p in path)
            raise ValueError(
                f"adapter swap leaf {name!r} is {b.shape}/{b.dtype}; the "
                f"serving tree has {a.shape}/{a.dtype} — shapes and dtypes "
                "are compile-time constants of the decode programs")


def prepare_adapter_swap(current: Pytree, adapters: Pytree, n_layers: int,
                         current_version: int, version: Optional[int],
                         who: str = "the engine") -> tuple[Pytree, int]:
    """The validate-and-version step shared by DecodeEngine.swap_adapters
    and GreedyLMPredictor's no-engine fallback: stack the per-block
    adapter tree, refuse empty trees and layout changes
    (check_adapter_swap), and compute the monotonic target version.
    Returns (stacked_tree, new_version)."""
    from ..llm.decode import stack_adapter_blocks

    stacked = stack_adapter_blocks(adapters, n_layers)
    if not stacked:
        raise ValueError("swap_adapters needs a non-empty adapter tree")
    check_adapter_swap(current, stacked)
    ver = current_version + 1 if version is None else int(version)
    if ver <= current_version:
        raise ValueError(
            f"model_version must be monotonic: swap to {ver} but "
            f"{who} already serves {current_version}")
    return stacked, ver


class _SlotState:
    """Host-side view of an occupied slot (the device mask is the source
    of truth for retirement; this mirrors it frame-by-frame). Paged mode
    additionally tracks what retirement must release: `entries` (prefix
    pages this slot holds a ref on) and `private` (pages owned outright —
    the prompt tail, the decode budget, and any page whose registration
    lost a race to a concurrent identical prompt)."""

    __slots__ = ("req", "out", "t_first", "entries", "private")

    def __init__(self, req: _Request):
        self.req = req
        self.out: list[int] = []
        self.t_first: Optional[float] = None
        self.entries: list[_PrefixEntry] = []
        self.private: list[int] = []


class DecodeEngine:
    """S-slot continuous-batching decoder over llm/decode.py's functional
    prefill/step.

    `model` is a llm.TransformerLM (its n_layers/n_heads/d_model size the
    cache); `params`/`adapters` may be unrolled or scan-layout (stacked
    here, pass-through if already stacked) and float or int8 {q,s}.
    `eos_id=None` disables eos retirement (requests always run their full
    max_new_tokens — the mode the greedy-equivalence contract is pinned
    in). Sampling: per-slot traced temperature; temperature <= 0 means
    greedy; full-vocab categorical (top_k requests stay on the
    per-request path, which compiles a static-k cutoff).

    `mesh` (a jax Mesh with an `mp` axis) runs the engine TENSOR-PARALLEL:
    weights and the persistent KV cache shard over `mp` via the
    parallel/partition.py rule registry (`partition_rules` overrides the
    default `transformer_lm` table) — the scale-out path for models whose
    KV cache + weights exceed one chip's HBM. Greedy output is
    token-identical across mp sizes (pinned at mp=1 vs mp=2 in tests).

    `page_size > 0` selects the PAGED KV cache (module docstring):
    `n_pages` sizes the pool (default = contiguous capacity + the null
    page; pass less to trade peak concurrency for HBM), `prefill_chunk`
    bounds how many prompt tokens one admission program processes
    (0 = whole prompt in one chunk), `prefix_cache` toggles content-hash
    prefix page reuse. Composes with `mesh` (pages replicate; the pool
    shards its heads axis). Paged greedy output is token-identical to
    contiguous (pinned in tests/test_paged_engine.py).

    `paged_kernel=True` (paged only) runs decode attention through the
    fused Pallas kernel (ops/paged_attention.py — pages read in place,
    no gather copy); `spec_decode="ngram"` + `spec_k` (paged only) turns
    each iteration into a self-drafted speculative verify window that
    emits up to spec_k + 1 tokens, greedy-exact (module docstring).
    Both compose with each other and with `mesh`.

    `kv_quant="int8"` (paged only) stores the persistent pool in int8
    with per-(page, head) scales riding the carry — half the KV HBM per
    slot, so ~2x decode slots at a fixed pool budget, for a <1pt greedy
    match-rate delta (quantize-at-write / dequantize-at-gather; the
    Pallas kernel dequants each slab in VMEM). `admit_batch` > 1 (paged
    only) admits up to that many same-bucket pending prompts per engine
    iteration through ONE batched chunk program — burst TTFT p99 stops
    paying one dispatch per request. Both compose with each other, the
    kernel, spec decode, and `mesh`."""

    def __init__(self, model, params: Pytree,
                 adapters: Optional[Pytree] = None, *,
                 n_slots: int = 4, max_len: int = 256,
                 eos_id: Optional[int] = None,
                 dtype=None, fetch_chunk: int = 2,
                 mesh=None, partition_rules=None,
                 page_size: int = 0, n_pages: Optional[int] = None,
                 prefill_chunk: int = 0, prefix_cache: bool = True,
                 paged_kernel: bool = False, spec_decode: str = "off",
                 spec_k: int = 4, kv_quant: str = "off",
                 admit_batch: int = 1):
        from ..llm.decode import (
            make_kv_decode, make_paged_kv_decode, ngram_propose,
            stack_adapter_blocks, stack_blocks,
        )

        if n_slots < 1:
            raise ValueError(f"n_slots must be >= 1; got {n_slots}")
        self.model = model
        self.max_len = int(max_len)
        self.n_slots = int(n_slots)
        self.fetch_chunk = max(1, int(fetch_chunk))
        # ---------------------------------------------------- paged layout
        # page_size > 0 selects the block/paged KV cache; 0 keeps the
        # contiguous [L, S, max_len, H, Dh] layout (still preferable when
        # every request genuinely runs to ~max_len: no gather, no page
        # bookkeeping). The paged knobs are refused in contiguous mode so
        # a config asking for them is never silently ignored.
        self._paged = int(page_size or 0) > 0
        if self._paged:
            self._page_size = int(page_size)
            self._max_pages = -(-self.max_len // self._page_size)
            # default pool = contiguous capacity + the reserved null page;
            # the memory win comes from passing a SMALLER kv_n_pages
            self._n_pages = (int(n_pages) if n_pages
                             else self.n_slots * self._max_pages + 1)
            self._usable = self._n_pages - 1   # page 0 is the null page
            if self._n_pages < 2:
                raise ValueError(
                    f"kv_n_pages must be >= 2 (page 0 is the reserved "
                    f"null page); got {self._n_pages}")
            if int(prefill_chunk) < 0:
                raise ValueError(
                    f"prefill_chunk must be >= 0 (0 = whole-prompt "
                    f"chunks); got {prefill_chunk}")
            self._prefill_chunk = int(prefill_chunk)
            self._prefix_on = bool(prefix_cache)
            self._free_pages: list[int] = list(range(1, self._n_pages))
            self._prefix: dict[bytes, _PrefixEntry] = {}
            self._ticks = 0
            _mx.set_gauge("serving.kv_pages_budget", self._usable)
            _mx.set_gauge("serving.kv_pages_free", len(self._free_pages))
        elif n_pages or prefill_chunk:
            raise ValueError(
                "kv_n_pages/prefill_chunk configure the PAGED cache — "
                "set page_size > 0 (they would be silently ignored in "
                "contiguous mode)")
        # ------------------------------------------- decode-speed knobs
        # Both legs live on the paged layout: the kernel reads the page
        # pool in place, and speculation's verify-and-rollback rides the
        # page table (rejected positions are re-written by the next
        # verify window). Asking for either without paging would be
        # silently ignored — refuse instead.
        self._kernel_on = bool(paged_kernel)
        if self._kernel_on and not self._paged:
            raise ValueError(
                "paged_kernel fuses attention over the PAGED KV pool — "
                "set page_size > 0 (in contiguous mode the knob would be "
                "silently ignored)")
        if spec_decode not in ("off", "ngram"):
            raise ValueError(
                f"spec_decode must be 'off' or 'ngram'; got {spec_decode!r}")
        self._spec_on = spec_decode == "ngram"
        self._spec_k = int(spec_k)
        if self._spec_on and not self._paged:
            raise ValueError(
                "spec_decode verifies draft windows over the PAGED KV "
                "cache (write positions roll back through the page "
                "table) — set page_size > 0")
        if self._spec_on and self._spec_k < 1:
            raise ValueError(
                f"spec_k must be >= 1 draft tokens; got {spec_k}")
        if kv_quant not in ("off", "int8"):
            raise ValueError(
                f"kv_quant must be 'off' or 'int8'; got {kv_quant!r}")
        self._quant = kv_quant == "int8"
        if self._quant and not self._paged:
            raise ValueError(
                "kv_quant stores the PAGED KV pool in int8 (per-page-"
                "per-head scales ride the page table) — set page_size "
                "> 0 (in contiguous mode the knob would be silently "
                "ignored)")
        self._admit_batch = int(admit_batch)
        if self._admit_batch < 1:
            raise ValueError(
                f"admit_batch must be >= 1; got {admit_batch}")
        if self._admit_batch > 1 and not self._paged:
            raise ValueError(
                "admit_batch groups PAGED admission chunks into one "
                "batched prefill program — set page_size > 0 (in "
                "contiguous mode the knob would be silently ignored)")
        self._admissions: deque[_Admission] = deque()
        # -1 never matches a token id, so eos retirement is inert
        self._eos = -1 if eos_id is None else int(eos_id)
        self.adapters = stack_adapter_blocks(adapters, model.n_layers)
        self.params = stack_blocks(params, model.n_layers)
        if dtype is not None:
            kv_dtype = jnp.dtype(dtype)
        else:
            floats = [l for l in jax.tree.leaves(self.params)
                      if jnp.issubdtype(l.dtype, jnp.floating)]
            kv_dtype = floats[0].dtype if floats else jnp.float32
        self._kv_dtype = kv_dtype

        # ------------------------------------------ tensor-parallel layout
        # `mesh` with an `mp` axis runs the engine tensor-parallel: weights
        # take the Megatron column/row layout from the ONE partition-rule
        # registry (parallel/partition.py — the SAME table the round
        # programs and CentralizedTrainer resolve, so train and serve
        # layouts cannot drift), adapters replicate (they are the round
        # payload), and the persistent KV cache [L, S, max_len, H, Dh]
        # shards its HEADS axis (partition.kv_cache_spec) — the decode-side
        # continuation of the column-split attention projections. GSPMD
        # inserts the one all-reduce per block at the wo row matmul; with
        # mp=1 the placement is a no-op and the engine stays token-
        # identical to the unmeshed path (pinned in tests).
        self.mesh = mesh
        self.param_specs = None
        self.kv_spec = None
        kv_sharding = rep_sharding = None
        if mesh is not None:
            from jax.sharding import NamedSharding
            from ..parallel import partition

            if "mp" not in mesh.axis_names:
                raise ValueError(
                    f"DecodeEngine mesh axes {mesh.axis_names} have no "
                    "'mp' axis (the tensor-parallel axis the rule tables "
                    "shard over)")
            mp = mesh.shape["mp"]
            if model.n_heads % mp:
                raise ValueError(
                    f"n_heads {model.n_heads} is not divisible by mp={mp}"
                    " — the KV cache shards the heads axis")
            rules = (partition_rules
                     if partition_rules is not None
                     else partition.transformer_lm_rules("mp"))
            self.param_specs = partition.match_partition_rules(
                rules, self.params)
            self.params = partition.shard_params(
                self.params, mesh, specs=self.param_specs)
            if self.adapters is not None:
                self.adapters = partition.shard_params(
                    self.adapters, mesh, "lora")
            # both layouts are 5-D with heads at axis 3; the paged spec is
            # its own registry entry so the page axes are named, not
            # incidentally covered
            self.kv_spec = (partition.paged_kv_cache_spec("mp")
                            if self._paged else partition.kv_cache_spec("mp"))
            kv_sharding = NamedSharding(mesh, self.kv_spec)
            rep_sharding = NamedSharding(
                mesh, jax.sharding.PartitionSpec())

        if self._paged:
            (chunk_fn, paged_step, paged_verify,
             chunk_batch_fn) = make_paged_kv_decode(
                model.n_heads, self._page_size, dtype=kv_dtype,
                kernel=self._kernel_on, mesh=mesh, quant=self._quant)
        else:
            prefill, step = make_kv_decode(model.n_heads, dtype=kv_dtype)
        S, eos, max_len_ = self.n_slots, self._eos, self.max_len

        def pick(logits, temp, key):
            """Greedy/sampled select with temperature TRACED (one program
            covers both): softmax sampling computes alongside and a where
            picks — the greedy lane is bit-identical to the per-request
            path's argmax."""
            greedy = jnp.argmax(logits, -1).astype(jnp.int32)
            l = logits.astype(jnp.float32) / jnp.maximum(temp, 1e-6)[
                ..., None]
            if logits.ndim == 1:
                sampled = jax.random.categorical(key, l, -1)
            else:
                sampled = jax.vmap(
                    lambda k, row: jax.random.categorical(k, row, -1))(
                        key, l)
            return jnp.where(temp > 0.0, sampled.astype(jnp.int32), greedy)

        def _decode_tail(carry, cache, logits, extra=None):
            """Shared post-forward step logic: sample/argmax the next
            token per slot, advance active positions, retire on budget or
            eos — ON DEVICE. `extra` carries layout-specific keys (the
            paged page table) through unchanged."""
            active, temp = carry["active"], carry["temp"]
            keys = jax.vmap(
                lambda s, p: jax.random.fold_in(jax.random.key(s), p + 1))(
                    carry["seed"], carry["pos"])
            nxt = pick(logits, temp, keys)
            pos2 = jnp.where(active, carry["pos"] + 1, carry["pos"])
            act2 = active & (pos2 < carry["limit"]) & (nxt != eos)
            out = {
                "cache": cache,
                "pos": pos2,
                "tok": jnp.where(active, nxt, carry["tok"]),
                "active": act2,
                "temp": temp,
                "seed": carry["seed"],
                "limit": carry["limit"],
            }
            if extra:
                out.update(extra)
            # emitted token per slot + the entry mask saying which are real
            return out, (nxt, active)

        if self._paged:
            def _admit(params, adapters, carry, tokens, t0, clen, slot,
                       row, temp, seed, limit, final, plen):
                """ONE chunk of one request's prefill into the paged
                carry: the slot's page-table row is (re)written, the
                chunk's K/V land in its pages, and — on the FINAL chunk —
                the last-position logits yield the first token and the
                slot's rows arm. Non-final chunks set the same rows
                (harmless while active stays False) so one program covers
                every chunk; everything but the token buffer is traced."""
                pages = carry["pages"].at[slot].set(row)
                cache, logits = chunk_fn(params, adapters, carry["cache"],
                                         row, tokens, t0, clen)
                key = jax.random.fold_in(jax.random.key(seed), plen)
                first = pick(logits[0], temp, key)
                # active iff this was the last chunk, the first token did
                # not end it, and there is budget left (limit = plen +
                # max_new - 1, as in contiguous mode)
                active = final & (first != eos) & (plen < limit)
                out = {
                    "cache": cache,
                    "pages": pages,
                    "pos": carry["pos"].at[slot].set(plen),
                    "tok": carry["tok"].at[slot].set(first),
                    "active": carry["active"].at[slot].set(active),
                    "temp": carry["temp"].at[slot].set(temp),
                    "seed": carry["seed"].at[slot].set(seed),
                    "limit": carry["limit"].at[slot].set(limit),
                }
                if self._spec_on:
                    # the chunk's real tokens land in the slot's history
                    # row (the n-gram draft source); padded tail indices
                    # point past max_len and are dropped by the scatter
                    cidx = jnp.arange(tokens.shape[1])
                    hidx = jnp.where(cidx < clen, t0 + cidx, max_len_)
                    out["hist"] = carry["hist"].at[slot, hidx].set(
                        tokens[0])
                return out, first

            def _admit_many(params, adapters, carry, tokens, t0s, clens,
                            slots, rows, temps, seeds, limits, finals,
                            plens):
                """admit_batch > 1: B same-bucket prefill chunks through
                ONE batched chunk program (llm/decode.py chunk_batch) —
                page reservations were already claimed host-side in one
                critical section; this is the device half. PAD rows
                (batch padded to its pow2 bucket) carry slot == n_slots,
                which every per-slot scatter DROPS (out-of-range scatter
                indices are discarded under jit), an all-zero page row
                (writes land on the null page) and clen 0."""
                pages = carry["pages"].at[slots].set(rows)
                cache, logits = chunk_batch_fn(
                    params, adapters, carry["cache"], rows, tokens,
                    t0s, clens)
                keys = jax.vmap(
                    lambda s, p: jax.random.fold_in(jax.random.key(s), p))(
                        seeds, plens)
                firsts = pick(logits, temps, keys)
                actives = finals & (firsts != eos) & (plens < limits)
                out = {
                    "cache": cache,
                    "pages": pages,
                    "pos": carry["pos"].at[slots].set(plens),
                    "tok": carry["tok"].at[slots].set(firsts),
                    "active": carry["active"].at[slots].set(actives),
                    "temp": carry["temp"].at[slots].set(temps),
                    "seed": carry["seed"].at[slots].set(seeds),
                    "limit": carry["limit"].at[slots].set(limits),
                }
                if self._spec_on:
                    cidx = jnp.arange(tokens.shape[1])[None, :]
                    hidx = jnp.where(cidx < clens[:, None],
                                     t0s[:, None] + cidx, max_len_)
                    out["hist"] = carry["hist"].at[
                        slots[:, None], hidx].set(tokens)
                return out, firsts

            def _step_all(params, adapters, carry):
                """Advance every slot one token. The active mask rides
                INTO the kernel: an inactive slot's stale page-table entry
                may point at a page re-allocated to another request, so
                its garbage write is redirected to the null page instead
                of parking on a frozen position."""
                cache, logits = paged_step(
                    params, adapters, carry["cache"], carry["pages"],
                    carry["pos"], carry["tok"], carry["active"])
                extra = {"pages": carry["pages"]}
                if self._spec_on:
                    extra["hist"] = carry["hist"]
                return _decode_tail(carry, cache, logits, extra=extra)

            spec_c = self._spec_k + 1

            def _spec_all(params, adapters, carry):
                """Speculative iteration, ALL slots: self-draft spec_k
                tokens from each slot's own history (ngram_propose),
                verify the whole window [tok, d1..dk] in ONE target
                forward over the paged cache, emit the longest prefix
                the target itself would have produced. By construction
                the emitted stream is token-identical to plain decode:
                token i is only accepted when every input before it was
                the target's own pick, so its logits — and therefore
                its pick, greedy or seeded — are exactly the plain
                path's. Rejected positions' K/V writes are garbage, and
                the rollback is positional: pos advances only past
                accepted tokens, so the NEXT window re-writes those
                very pages before anything can attend to them."""
                s_idx = jnp.arange(S)
                pos, tok = carry["pos"], carry["tok"]
                active, temp = carry["active"], carry["temp"]
                # the current token is real history at its write position
                # — anchor it before drafting so the trailing n-gram
                # includes it. INACTIVE slots write nothing (index
                # max_len drops): their pos/tok are stale, and a slot
                # mid-chunked-admission shares this hist buffer — a
                # stale write could corrupt the incoming prompt's
                # history and poison its draft anchors (never its
                # output; drafts are proposals)
                hist = carry["hist"].at[
                    s_idx, jnp.where(active, pos, max_len_)].set(tok)
                drafts = ngram_propose(hist, pos, spec_c - 1)
                inputs = jnp.concatenate([tok[:, None], drafts], axis=1)
                widx = pos[:, None] + jnp.arange(spec_c)
                # record the window inputs (accepted ones are permanent
                # history; rejected ones sit past the new pos and are
                # overwritten before the draft matcher can anchor on
                # them); inactive slots and out-of-range indices drop
                hist = hist.at[
                    s_idx[:, None],
                    jnp.where(active[:, None] & (widx < max_len_),
                              widx, max_len_)].set(inputs)
                cache, logits = paged_verify(
                    params, adapters, carry["cache"], carry["pages"],
                    pos, inputs, active)
                # the SAME rng schedule as the plain step (fold_in at
                # write-position + 1) — seeded sampling stays pinned
                # across spec on/off
                keys = jax.vmap(
                    lambda s, p: jax.vmap(
                        lambda q: jax.random.fold_in(
                            jax.random.key(s), q + 1))(
                                p + jnp.arange(spec_c)))(
                                    carry["seed"], pos)
                # THE pick (greedy/sampled select), vmapped over the
                # window axis — one selection implementation, so the
                # spec-on == spec-off identity can't drift from a
                # future pick() edit
                g = jax.vmap(pick, in_axes=(1, None, 1),
                             out_axes=1)(logits, temp, keys)
                # token i is emitted iff every input before it was the
                # target's own pick, nothing before it ended the
                # request, and the budget has room — the in-jit
                # statement of greedy-exact acceptance
                emits = [active]
                for i in range(1, spec_c):
                    emits.append(emits[-1]
                                 & (inputs[:, i] == g[:, i - 1])
                                 & (g[:, i - 1] != eos)
                                 & (pos + i < carry["limit"]))
                emit = jnp.stack(emits, axis=1)
                n_acc = emit.sum(axis=1).astype(jnp.int32)
                last = g[s_idx, jnp.maximum(n_acc - 1, 0)]
                pos2 = jnp.where(active, pos + n_acc, pos)
                tok2 = jnp.where(active, last, tok)
                act2 = active & (pos2 < carry["limit"]) & (last != eos)
                out = {"cache": cache, "pages": carry["pages"],
                       "pos": pos2, "tok": tok2, "active": act2,
                       "temp": temp, "seed": carry["seed"],
                       "limit": carry["limit"], "hist": hist}
                return out, (g, jnp.where(active, n_acc, 0))
        else:
            def _admit(params, adapters, carry, tokens, length, slot, temp,
                       seed, limit):
                """Prefill one request into slot `slot` of the donated
                carry: K/V rows land at the slot index of the persistent
                cache, the prompt's last-position logits yield the first
                token, and the slot's pos/tok/active/temp/seed/limit rows
                are set."""
                row, logits = prefill(params, adapters, tokens, max_len_,
                                      length=length)
                key = jax.random.fold_in(jax.random.key(seed), length)
                first = pick(logits[0], temp, key)
                start = (0, slot, 0, 0, 0)
                cache = {
                    "k": jax.lax.dynamic_update_slice(
                        carry["cache"]["k"], row["k"], start),
                    "v": jax.lax.dynamic_update_slice(
                        carry["cache"]["v"], row["v"], start),
                }
                # active iff the first token did not end it and there is
                # budget left (limit = length + max_new - 1: the position
                # after which no further step token is owed)
                active = (first != eos) & (length < limit)
                return {
                    "cache": cache,
                    "pos": carry["pos"].at[slot].set(length),
                    "tok": carry["tok"].at[slot].set(first),
                    "active": carry["active"].at[slot].set(active),
                    "temp": carry["temp"].at[slot].set(temp),
                    "seed": carry["seed"].at[slot].set(seed),
                    "limit": carry["limit"].at[slot].set(limit),
                }, first

            def _step_all(params, adapters, carry):
                """Advance every slot one token through ONE program.
                Inactive slots are inert: pos frozen, tok unchanged, their
                (garbage) K/V write lands on a frozen position that the
                next admission's full prefill row overwrites."""
                cache, logits = step(params, adapters, carry["cache"],
                                     carry["pos"], carry["tok"])
                return _decode_tail(carry, cache, logits)

        # the carry is DONATED: the cache never round-trips host<->device
        # and XLA may update the slot rows in place. On an mp mesh the
        # carry's output shardings are PINNED (cache on the heads split,
        # scalars-per-slot replicated): donation requires the output
        # buffer to reuse the input's layout, and an XLA-chosen resharding
        # would silently turn the in-place update into a full copy.
        self._spec_jit = None
        self._admit_many_jit = None
        # track_jit: retrace telemetry + the XLA cost/memory ledger — each
        # program's cost_analysis/memory_analysis lands in xla.program.*
        # gauges on first compile (utils/xla_ledger.py)
        if mesh is None:
            self._admit_jit = _mx.track_jit(
                jax.jit(_admit, donate_argnums=(2,)), "engine_admit")
            self._step_jit = _mx.track_jit(
                jax.jit(_step_all, donate_argnums=(2,)), "engine_step")
            if self._spec_on:
                self._spec_jit = _mx.track_jit(
                    jax.jit(_spec_all, donate_argnums=(2,)), "engine_spec")
            if self._paged and self._admit_batch > 1:
                self._admit_many_jit = _mx.track_jit(jax.jit(
                    _admit_many, donate_argnums=(2,)), "engine_admit_many")
            carry_sh = None
        else:
            # ONE carry-layout dict, used for the jit out_shardings AND the
            # initial placement below — two copies drifting apart (a new
            # carry key updated in only one) would silently turn the
            # donated in-place update into a full cache copy
            carry_sh = {
                "cache": {"k": kv_sharding, "v": kv_sharding},
                "pos": rep_sharding, "tok": rep_sharding,
                "active": rep_sharding, "temp": rep_sharding,
                "seed": rep_sharding, "limit": rep_sharding,
            }
            if self._quant:
                scale_sharding = NamedSharding(
                    mesh, partition.paged_kv_scale_spec("mp"))
                carry_sh["cache"]["ks"] = scale_sharding
                carry_sh["cache"]["vs"] = scale_sharding
            if self._paged:
                carry_sh["pages"] = rep_sharding
            if self._spec_on:
                carry_sh["hist"] = rep_sharding
            self._admit_jit = _mx.track_jit(jax.jit(
                _admit, donate_argnums=(2,),
                out_shardings=(carry_sh, rep_sharding)), "engine_admit")
            self._step_jit = _mx.track_jit(jax.jit(
                _step_all, donate_argnums=(2,),
                out_shardings=(carry_sh, (rep_sharding, rep_sharding))),
                "engine_step")
            if self._spec_on:
                self._spec_jit = _mx.track_jit(jax.jit(
                    _spec_all, donate_argnums=(2,),
                    out_shardings=(carry_sh,
                                   (rep_sharding, rep_sharding))),
                    "engine_spec")
            if self._paged and self._admit_batch > 1:
                self._admit_many_jit = _mx.track_jit(jax.jit(
                    _admit_many, donate_argnums=(2,),
                    out_shardings=(carry_sh, rep_sharding)),
                    "engine_admit_many")

        head = model.d_model // model.n_heads
        if self._paged:
            z = (model.n_layers, self._n_pages, self._page_size,
                 model.n_heads, head)
        else:
            z = (model.n_layers, S, self.max_len, model.n_heads, head)
        pool_dtype = jnp.int8 if self._quant else kv_dtype
        cache = {"k": jnp.zeros(z, pool_dtype),
                 "v": jnp.zeros(z, pool_dtype)}
        if self._quant:
            zs = (model.n_layers, self._n_pages, model.n_heads)
            cache["ks"] = jnp.zeros(zs, jnp.float32)
            cache["vs"] = jnp.zeros(zs, jnp.float32)
        # persistent KV bytes amortized per decode slot — THE density
        # figure int8 paging halves (scales included: they are the
        # quantized layout's real, small, overhead)
        kv_bytes = sum(int(np.prod(a.shape)) * a.dtype.itemsize
                       for a in cache.values())
        _mx.set_gauge("serving.kv_bytes_per_slot", kv_bytes // S)
        self._carry = {
            "cache": cache,
            "pos": jnp.zeros((S,), jnp.int32),
            "tok": jnp.zeros((S,), jnp.int32),
            "active": jnp.zeros((S,), bool),
            "temp": jnp.zeros((S,), jnp.float32),
            "seed": jnp.zeros((S,), jnp.uint32),
            "limit": jnp.zeros((S,), jnp.int32),
        }
        if self._paged:
            self._carry["pages"] = jnp.zeros((S, self._max_pages),
                                             jnp.int32)
        if self._spec_on:
            # per-slot token history (prompt + generated): the draft
            # source, written by admission chunks and the verify
            # windows. Prefix-HIT positions are skipped by chunked
            # prefill and may retain a previous occupant's tokens —
            # draft anchors landing there cost acceptance, never
            # correctness (the verify forward decides)
            self._carry["hist"] = jnp.zeros((S, self.max_len), jnp.int32)
        if carry_sh is not None:
            # place the persistent carry on the mesh up front — every later
            # call donates it back in the same layout
            self._carry = jax.tree.map(
                lambda a, s: jax.device_put(a, s), self._carry, carry_sh)

        # device-memory ledger: the engine's three resident pytrees. The
        # kv_pool entry must agree with the kv_bytes_per_slot math above
        # within 1% (pinned in tests) — they sum the same buffers
        _ledger.register_buffers("serving_params", self.params)
        _ledger.register_buffers("kv_pool", self._carry["cache"])
        _ledger.register_buffers("engine_carry",
                                 {k: v for k, v in self._carry.items()
                                  if k != "cache"})

        self._cond = threading.Condition()
        self._waiting: deque[_Request] = deque()
        self._free: list[int] = list(range(S))
        self._slots: list[Optional[_SlotState]] = [None] * S
        self._stopping = False
        self._draining = False
        self._thread: Optional[threading.Thread] = None
        self._version = 0
        self._pending_swap: Optional[_Swap] = None
        _mx.set_gauge("serving.model_version", 0)

    # ------------------------------------------------------------ lifecycle
    def start(self) -> "DecodeEngine":
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="decode-engine")
        self._thread.start()
        return self

    def drain(self, timeout_s: float = 30.0) -> bool:
        """Refuse new submits and wait (bounded) for every ACCEPTED
        request — decoding slots and queued ones — to finish. One-way:
        a drained engine only goes on to stop(). Returns False when the
        deadline expired with work still in flight (stop() then errors
        those tickets as before — the drain was best-effort, bounded)."""
        with self._cond:
            self._draining = True
            self._cond.notify_all()
            deadline = time.monotonic() + timeout_s
            while self._waiting or any(s is not None for s in self._slots):
                if (self._stopping or self._thread is None
                        or not self._thread.is_alive()):
                    break
                left = deadline - time.monotonic()
                if left <= 0:
                    _mx.inc("serving.engine.drain_timeouts")
                    return False
                self._cond.wait(min(0.1, left))
        return True

    def stop(self, drain: bool = False,
             drain_timeout_s: float = 30.0) -> None:
        """Tear the engine down. `drain=True` first lets in-flight slots
        finish (bounded by `drain_timeout_s`) so a scale-down or rolling
        replica swap never errors a request that was already decoding;
        whatever is still in flight when the deadline expires is errored
        as before."""
        if drain and self._thread is not None and self._thread.is_alive():
            self.drain(drain_timeout_s)
        with self._cond:
            self._stopping = True
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=10)
        self._fail_outstanding(RuntimeError("decode engine stopped"))

    # ------------------------------------------------------------- hot swap
    @property
    def model_version(self) -> int:
        return self._version

    def swap_adapters(self, adapters: Pytree,
                      version: Optional[int] = None,
                      timeout: float = 60.0) -> int:
        """Hot-swap the LoRA adapter VALUES the engine serves — applied
        by the engine thread between decode iterations, so no step ever
        mixes versions, the persistent KV cache survives untouched, and
        no program retraces (adapters are replicated per the
        partition.TABLES["lora"] contract; structure/shape/dtype changes
        are refused — see check_adapter_swap). In-flight requests finish
        on the new adapters from their next step. Returns the new
        monotonic `model_version` (default: current + 1)."""
        if self.adapters is None:
            raise ValueError(
                "this engine was built without adapters — hot swap "
                "replaces adapter VALUES only (the compiled programs' "
                "signature is fixed at construction); deploy the replica "
                "with adapters (zero-initialized LoRA serves the base "
                "model exactly) to enable rolling updates")
        with self._cond:
            stacked, ver = prepare_adapter_swap(
                self.adapters, adapters, self.model.n_layers,
                self._version, version)
            if self.mesh is not None:
                from ..parallel import partition

                stacked = partition.shard_params(stacked, self.mesh,
                                                 "lora")
            if self._pending_swap is not None:
                raise RuntimeError(
                    "an adapter swap is already pending — serialize "
                    "swaps (the rolling updater does)")
            swap = _Swap(stacked, ver)
            running = (self._thread is not None and self._thread.is_alive()
                       and not self._stopping)
            if running:
                self._pending_swap = swap
                self._cond.notify_all()
        if not running:
            # no decode thread -> no iteration boundary to respect; the
            # per-request degrade path still serves the new values
            self._apply_swap(swap)
            return self._version
        if not swap.applied.wait(timeout):
            raise TimeoutError(f"adapter swap not applied in {timeout}s")
        if swap.error is not None:
            raise swap.error
        return self._version

    def _apply_swap(self, swap: _Swap) -> None:
        """Engine-thread (or stopped-engine) application point: ONE
        attribute assignment between jit dispatches — the next admit/step
        call reads the new tree; nothing about the carry changes."""
        with recorder.span("serving.swap", version=swap.version):
            self.adapters = swap.adapters
            self._version = swap.version
        _mx.set_gauge("serving.model_version", swap.version)
        _mx.inc("serving.engine.swaps")
        swap.applied.set()

    # ------------------------------------------------------------ admission
    def submit(self, tokens, max_new_tokens: int,
               temperature: float = 0.0,
               seed: Optional[int] = None) -> Ticket:
        """Queue one prompt; returns the Ticket its tokens stream to.
        Capacity contract: prompt + max_new_tokens <= max_len (exact — the
        engine never buckets the token budget)."""
        tokens = [int(t) for t in tokens]
        if not tokens:
            raise InvalidRequest(
                "tokens must contain at least one prompt token")
        max_new = int(max_new_tokens)
        if max_new < 1:
            raise InvalidRequest(
                f"max_new_tokens must be >= 1; got {max_new}")
        if not self.admissible(len(tokens), max_new):
            raise InvalidRequest(self.capacity_error(len(tokens), max_new))
        if seed is None:
            import random as _random

            seed = _random.getrandbits(31)
        # the per-slot seed rides as a device uint32 — mask client-supplied
        # values into range instead of letting jnp.uint32 overflow on the
        # engine thread (still deterministic per seed)
        seed = int(seed) & 0xFFFFFFFF
        req = _Request(tokens, max_new, float(temperature), seed)
        with self._cond:
            if self._stopping or (self._thread is not None
                                  and not self._thread.is_alive()):
                raise RuntimeError("decode engine is stopped")
            if self._draining:
                raise RuntimeError(
                    "decode engine is draining (replica stopping) — "
                    "request refused")
            if self._thread is None:
                raise RuntimeError("decode engine not started "
                                   "(call .start())")
            self._waiting.append(req)
            _mx.set_gauge("serving.engine.queue", len(self._waiting))
            self._cond.notify_all()
        _mx.inc("serving.engine.requests")
        return req.ticket

    # -------------------------------------------------------------- capacity
    def admissible(self, prompt_len: int, max_new: int) -> bool:
        """THE engine capacity oracle: True iff a (prompt_len, max_new)
        request can ever be admitted. Contiguous: prompt + max_new <=
        max_len. Paged: additionally ceil((prompt + max_new) / page_size)
        <= the usable page budget. The predictor's routing consults this
        (not static max_len math) so a request the page budget refuses
        falls back to the per-request path instead of 400ing, and one
        paging admits is never degraded into a per-request 400."""
        prompt_len, max_new = int(prompt_len), int(max_new)
        if prompt_len + max_new > self.max_len:
            return False
        if self._paged:
            need = -(-(prompt_len + max_new) // self._page_size)
            return need <= self._usable
        return True

    def capacity_error(self, prompt_len: int, max_new: int) -> str:
        """The message submit() raises for an inadmissible request —
        states the page math in paged mode so a 400 is actionable."""
        if not self._paged:
            return (f"prompt {prompt_len} + max_new_tokens {max_new} "
                    f"exceeds max_len {self.max_len} (engine slot capacity "
                    "contract: prompt + max_new_tokens <= max_len)")
        tot = prompt_len + max_new
        need = -(-tot // self._page_size)
        return (f"prompt {prompt_len} + max_new_tokens {max_new} = {tot} "
                f"tokens needs ceil({tot}/{self._page_size}) = {need} KV "
                f"pages, but the engine budget is {self._usable} usable "
                f"pages (kv_n_pages {self._n_pages} minus the reserved "
                f"null page) with per-request cap max_len {self.max_len} "
                "(paged capacity contract: prompt + max_new_tokens <= "
                "max_len AND ceil((prompt + max_new_tokens) / "
                "kv_page_size) <= kv_n_pages - 1)")

    # ------------------------------------------------------- introspection
    @property
    def kv_page_size(self) -> int:
        """Page size of the paged KV cache (0 = contiguous layout) —
        advertised on /info so the gateway's prefix-affinity hash uses
        the replica's real page geometry."""
        return self._page_size if self._paged else 0

    def prefix_digests(self, limit: int = 64) -> list:
        """Hex digests of resident FIRST-page prefix-cache keys — the
        residency summary replicas advertise for gateway prefix-affinity
        routing (serving/scheduler.py). First-page keys only: the
        gateway hashes a prompt's leading page-aligned block, so deeper
        chain keys could never match its probe. Read lock-free off the
        engine-thread-owned prefix map: the advertised set is a routing
        HINT — a stale entry costs one least-loaded fallback, never
        correctness."""
        if not (self._paged and self._prefix_on):
            return []
        out = []
        for key, ent in list(self._prefix.items()):
            if ent.parent is None:
                out.append(key.hex())
                if len(out) >= limit:
                    break
        return out

    def program_counts(self) -> dict:
        """Live compiled-program counts: {"step": 1, "admit": <=
        log2(max_len)} in steady state — the retrace guard tests pin.
        In paged mode "admit" is the chunk program (<= log2(prefill_chunk)
        + 1 buckets: chunks are prefill_chunk-sized except a final
        pow2-bucketed remainder)."""
        out = {}
        pairs = [("step", self._step_jit), ("admit", self._admit_jit)]
        if self._spec_jit is not None:
            # spec mode replaces the step dispatch with ONE verify-window
            # program; "step" then stays 0 and "verify" must stay 1
            pairs.append(("verify", self._spec_jit))
        if self._admit_many_jit is not None:
            # admit_batch > 1 replaces the per-admission chunk dispatch:
            # bounded by chunk buckets x pow2 batch buckets
            pairs.append(("admit_batch", self._admit_many_jit))
        for name, fn in pairs:
            try:
                out[name] = fn._cache_size()
            except Exception:  # jax without the introspection hook
                out[name] = None
        return out

    # ------------------------------------------------------------ engine loop
    def _loop(self) -> None:
        # frames: ("admit", slot, first_token_dev) | ("step", toks, mask)
        pending: deque[tuple] = deque()
        try:
            while True:
                with self._cond:
                    if self._stopping:
                        break
                    swap, self._pending_swap = self._pending_swap, None
                    idle = (swap is None and not self._waiting and not pending
                            and all(s is None for s in self._slots))
                    if idle:
                        self._cond.wait(0.2)
                        continue
                if swap is not None:
                    # between iterations, by construction: the previous
                    # iteration's dispatches hold their own references,
                    # every later one reads the new tree
                    self._apply_swap(swap)
                if self._paged:
                    self._advance_admissions(pending)
                else:
                    self._admit_ready(pending)
                # step when any occupied slot is past admission — a slot
                # mid-chunked-prefill is inert on device, and a step over
                # ONLY such slots would be a wasted dispatch
                admitting = {a.slot for a in self._admissions}
                if any(s is not None and i not in admitting
                       for i, s in enumerate(self._slots)):  # graftlint: disable=lock-discipline (engine-thread owned; see ownership note above _next_tick)
                    if self._spec_on:
                        # one verify window advances every slot up to
                        # spec_k + 1 tokens — the speculative analog of
                        # the plain step, same dispatch-ahead contract
                        self._carry, (toks, counts) = self._spec_jit(
                            self.params, self.adapters, self._carry)
                        pending.append(("spec", toks, counts))
                    else:
                        self._carry, (toks, mask) = self._step_jit(
                            self.params, self.adapters, self._carry)
                        pending.append(("step", toks, mask))
                # drain: normally keep `fetch_chunk` frames in flight so
                # host bookkeeping overlaps device steps; drain eagerly
                # when requests are starved for a slot (a completion frees
                # one) or nothing new was dispatched
                with self._cond:
                    starved = bool(self._waiting) and not self._free
                eager = starved or all(s is None for s in self._slots)  # graftlint: disable=lock-discipline (engine-thread owned; see ownership note above _next_tick)
                while pending and (eager
                                   or len(pending) >= self.fetch_chunk):
                    self._drain(pending.popleft())
        except BaseException as e:  # noqa: BLE001 — fail tickets, not silently
            log.exception("decode engine loop died")
            _mx.inc("serving.engine.errors")
            # mark stopped FIRST so submit() refuses (and the predictor
            # falls back to the per-request path) instead of queueing
            # tickets nothing will ever complete
            with self._cond:
                self._stopping = True
            self._fail_outstanding(
                RuntimeError(f"decode engine failed: {type(e).__name__}: {e}"))

    def _admit_ready(self, pending: deque) -> None:
        while True:
            with self._cond:
                if not (self._free and self._waiting):
                    return
                req = self._waiting.popleft()
                slot = self._free.pop()
                # claim the slot in the SAME critical section as the pop:
                # a stop() racing a long admit compile must find the
                # request either in _waiting or in _slots — never in
                # between (its ticket would hang its HTTP thread 600s)
                self._slots[slot] = _SlotState(req)
                _mx.set_gauge("serving.engine.queue", len(self._waiting))
            with recorder.span("serving.engine.admit", slot=slot,
                               prompt=len(req.tokens)):
                # the SAME bucket fn as the per-request path, so both
                # paths share one bounded prompt-bucket set
                pb = min(_bucket(len(req.tokens), pow2_cap=self.max_len),
                         self.max_len)
                buf = np.zeros((1, pb), np.int32)
                buf[0, :len(req.tokens)] = req.tokens
                limit = len(req.tokens) + req.max_new - 1
                self._carry, first = self._admit_jit(
                    self.params, self.adapters, self._carry,
                    jnp.asarray(buf), jnp.int32(len(req.tokens)),
                    jnp.int32(slot), jnp.float32(req.temperature),
                    jnp.uint32(req.seed), jnp.int32(limit))
            pending.append(("admit", slot, first))
            _mx.inc("serving.engine.admissions")

    # ----------------------------------------------- paged admission plane
    # All of the page machinery below runs on the ENGINE THREAD only
    # (_advance_admissions from the loop, _release_slot_pages via _drain's
    # _deliver) — the free list and prefix map need no lock; _cond still
    # guards the _waiting/_free/_slots handoff with submit()/stop().
    # THREAD-OWNERSHIP NOTE (the justification behind the per-line
    # lock-discipline suppressions in this file): `_slots` ENTRIES are
    # read and replaced only by the engine thread; the one lock-guarded
    # cross-thread writer, _fail_outstanding, runs after the loop has
    # exited (crash path) or after stop() joined the thread — the _cond
    # handoff in stop()/submit() is the happens-before edge. graftlint
    # still flags every bare access so a NEW cross-thread writer cannot
    # creep in unreviewed (ISSUE 13).

    def _next_tick(self) -> int:
        self._ticks += 1
        return self._ticks

    def _prefix_lookup(self, toks: list[int]):
        """(chain keys for every FULL prompt page, resident hit entries).
        The hit walk is capped at (prompt_len - 1) // page_size pages so
        at least the prompt's last token is always prefilled — the
        first-token logits must be computed, not remembered."""
        ps = self._page_size
        keys: list[bytes] = []
        key = b"\x00"
        for i in range(len(toks) // ps):
            key = _page_key(key, toks[i * ps:(i + 1) * ps])
            keys.append(key)
        hits: list[_PrefixEntry] = []
        if self._prefix_on:
            for i in range((len(toks) - 1) // ps):
                e = self._prefix.get(keys[i])
                if e is None:
                    break
                hits.append(e)
        return keys, hits

    def _alloc(self, n: int) -> Optional[list[int]]:
        """Pop `n` pages from the free list, evicting LRU leaf prefix
        entries (refs == 0, kids == 0) under pressure. None = the pool is
        pinned by in-flight requests right now — the caller re-queues and
        retries after a retirement frees pages."""
        while len(self._free_pages) < n:
            victim, vkey = None, None
            for k, e in self._prefix.items():
                if e.refs == 0 and e.kids == 0 and (
                        victim is None or e.tick < victim.tick):
                    victim, vkey = e, k
            if victim is None:
                return None
            del self._prefix[vkey]
            if victim.parent is not None and victim.parent in self._prefix:
                self._prefix[victim.parent].kids -= 1
            self._free_pages.append(victim.page)
            _mx.inc("serving.prefix_evictions")
        pages = [self._free_pages.pop() for _ in range(n)]
        _mx.set_gauge("serving.kv_pages_free", len(self._free_pages))
        return pages

    def _release_slot_pages(self, st: _SlotState) -> None:
        """Retirement's page bookkeeping: drop this slot's refs on shared
        prefix pages (they STAY resident — evictable, reusable) and return
        its private pages to the free list."""
        for e in st.entries:
            e.refs -= 1
        self._free_pages.extend(st.private)
        st.entries, st.private = [], []
        _mx.set_gauge("serving.kv_pages_free", len(self._free_pages))

    def _start_admissions(self) -> None:
        """Claim (slot, pages) for waiting requests, FIFO. A request whose
        pages are currently pinned goes back to the queue HEAD — later
        requests do not overtake it (starvation beats reordering), and
        liveness holds because submit() already proved the request fits
        the total budget: whatever is pinned now retires eventually."""
        while True:
            with self._cond:
                if not (self._free and self._waiting):
                    return
                req = self._waiting.popleft()
                slot = self._free.pop()
                # claim in the SAME critical section as the pop (stop()
                # racing an admission must find the request somewhere)
                self._slots[slot] = _SlotState(req)
                _mx.set_gauge("serving.engine.queue", len(self._waiting))
            ps = self._page_size
            # with the prefix cache off there is nothing to look up OR
            # register — skip the per-page hashing entirely, and leave
            # the hit/miss counters untouched (a disabled cache reporting
            # a 0% hit rate on `top` reads as a cache problem, not a knob)
            keys, hits = (self._prefix_lookup(req.tokens)
                          if self._prefix_on else ([], []))
            total = -(-(len(req.tokens) + req.max_new) // ps)
            # hold the hit refs BEFORE allocating: _alloc evicts refs==0
            # entries under pressure, and evicting the very pages this
            # admission just looked up would leave its page row pointing
            # at freed (soon re-owned) pages — cross-request contamination
            now = self._next_tick()
            for e in hits:
                e.refs += 1
                e.tick = now
            fresh = self._alloc(total - len(hits))
            if fresh is None:
                for e in hits:
                    e.refs -= 1
                with self._cond:
                    self._slots[slot] = None
                    self._free.append(slot)
                    self._waiting.appendleft(req)
                    _mx.set_gauge("serving.engine.queue",
                                  len(self._waiting))
                return
            st = self._slots[slot]  # graftlint: disable=lock-discipline (engine-thread owned; see ownership note above _next_tick)
            st.entries = list(hits)
            st.private = list(fresh)
            row = np.zeros(self._max_pages, np.int32)
            row[:len(hits)] = [e.page for e in hits]
            row[len(hits):total] = fresh
            if hits:
                _mx.inc("serving.prefix_hits")
                _mx.inc("serving.prefix_hit_pages", len(hits))
            elif self._prefix_on:
                _mx.inc("serving.prefix_misses")
            self._admissions.append(_Admission(
                req, slot, row, len(hits) * ps, keys, len(hits), total))
            _mx.inc("serving.engine.admissions")

    def _advance_admissions(self, pending: deque) -> None:
        """ONE prefill chunk per engine iteration, round-robin across
        in-flight admissions — decode steps interleave between chunks
        (active slots keep advancing through a long prompt's prefill) and
        a short prompt admitted beside a long one reaches its first token
        after its OWN chunks, not the long one's. With admit_batch > 1,
        up to that many SAME-BUCKET admissions advance through one
        batched chunk program instead."""
        self._start_admissions()
        if not self._admissions:
            return
        if self._admit_batch > 1:
            self._advance_admissions_batched(pending)
            return
        adm = self._admissions.popleft()
        req = adm.req
        plen = len(req.tokens)
        cap = self._prefill_chunk or self.max_len
        clen = min(cap, plen - adm.t0)
        # chunk buffers bucket to powers of two below the chunk cap, so
        # the remainder chunk reuses a bounded program set
        cb = min(_bucket(clen, pow2_cap=cap), cap)
        buf = np.zeros((1, cb), np.int32)
        buf[0, :clen] = req.tokens[adm.t0:adm.t0 + clen]
        final = adm.t0 + clen == plen
        limit = plen + req.max_new - 1
        with recorder.span("serving.engine.admit", slot=adm.slot,
                           prompt=plen, t0=adm.t0, chunk=clen,
                           final=final):
            self._carry, first = self._admit_jit(
                self.params, self.adapters, self._carry,
                jnp.asarray(buf), jnp.int32(adm.t0), jnp.int32(clen),
                jnp.int32(adm.slot), jnp.asarray(adm.row),
                jnp.float32(req.temperature), jnp.uint32(req.seed),
                jnp.int32(limit), jnp.bool_(final), jnp.int32(plen))
        _mx.inc("serving.engine.prefill_chunks")
        if final:
            self._register_prefix(adm)
            pending.append(("admit", adm.slot, first))
        else:
            adm.t0 += clen
            self._admissions.append(adm)

    def _advance_admissions_batched(self, pending: deque) -> None:
        """Batched admission (admit_batch > 1): pop up to admit_batch
        admissions whose NEXT chunk lands in the SAME pow2 chunk bucket
        and prefill them through ONE batched program — a burst of
        arrivals reaches first tokens in one device dispatch instead of
        one per request, which is where the TTFT p99 win lives. The
        batch axis pads to its own pow2 bucket so the program set stays
        bounded (chunk buckets x batch buckets); differently-bucketed
        admissions go back ahead of the queue, keeping round-robin
        order."""
        cap = self._prefill_chunk or self.max_len

        def next_bucket(adm):
            clen = min(cap, len(adm.req.tokens) - adm.t0)
            return min(_bucket(clen, pow2_cap=cap), cap)

        group = [self._admissions.popleft()]
        cb = next_bucket(group[0])
        skipped = []
        while self._admissions and len(group) < self._admit_batch:
            adm = self._admissions.popleft()
            if next_bucket(adm) == cb:
                group.append(adm)
            else:
                skipped.append(adm)
        self._admissions.extendleft(reversed(skipped))
        b = len(group)
        bb = 1
        while bb < b:
            bb *= 2
        toks = np.zeros((bb, cb), np.int32)
        rows = np.zeros((bb, self._max_pages), np.int32)
        t0s = np.zeros((bb,), np.int32)
        clens = np.zeros((bb,), np.int32)
        # PAD rows: slot n_slots — dropped by every scatter in the jit
        slots = np.full((bb,), self.n_slots, np.int32)
        temps = np.zeros((bb,), np.float32)
        seeds = np.zeros((bb,), np.uint32)
        limits = np.zeros((bb,), np.int32)
        finals = np.zeros((bb,), bool)
        plens = np.zeros((bb,), np.int32)
        for i, adm in enumerate(group):
            req = adm.req
            plen = len(req.tokens)
            clen = min(cap, plen - adm.t0)
            toks[i, :clen] = req.tokens[adm.t0:adm.t0 + clen]
            rows[i] = adm.row
            t0s[i], clens[i], slots[i] = adm.t0, clen, adm.slot
            temps[i], seeds[i] = req.temperature, req.seed
            limits[i] = plen + req.max_new - 1
            finals[i] = adm.t0 + clen == plen
            plens[i] = plen
        with recorder.span("serving.engine.admit", batch=b, chunk=cb):
            self._carry, firsts = self._admit_many_jit(
                self.params, self.adapters, self._carry,
                jnp.asarray(toks), jnp.asarray(t0s), jnp.asarray(clens),
                jnp.asarray(slots), jnp.asarray(rows),
                jnp.asarray(temps), jnp.asarray(seeds),
                jnp.asarray(limits), jnp.asarray(finals),
                jnp.asarray(plens))
        _mx.inc("serving.engine.prefill_chunks", b)
        _mx.observe("serving.engine.admit_batch", b)
        for i, adm in enumerate(group):
            if finals[i]:
                self._register_prefix(adm)
                pending.append(("admit", adm.slot, firsts[i]))
            else:
                adm.t0 += int(clens[i])
                self._admissions.append(adm)

    def _register_prefix(self, adm: _Admission) -> None:
        """Publish the request's full prompt pages into the prefix map AT
        ADMISSION (not retirement): a concurrent identical prompt hits
        while this one still decodes — the system-prompt traffic shape.
        Full pages are immutable from here on (decode writes start at
        pos >= prompt_len, which lands strictly past them). A page whose
        key already exists (two identical prompts admitted concurrently)
        stays private — content-identical, so the resident entry serves
        future hits and ours is simply freed at retirement."""
        if not self._prefix_on:
            return
        st = self._slots[adm.slot]  # graftlint: disable=lock-discipline (engine-thread owned; see ownership note above _next_tick)
        if st is None:   # raced a crash/stop reset
            return
        full = len(adm.req.tokens) // self._page_size
        for i in range(adm.hit_pages, full):
            if adm.keys[i] in self._prefix:
                continue
            page = int(adm.row[i])
            parent = adm.keys[i - 1] if i else None
            ent = _PrefixEntry(page, parent, self._next_tick())
            self._prefix[adm.keys[i]] = ent
            if parent is not None and parent in self._prefix:
                self._prefix[parent].kids += 1
            st.entries.append(ent)
            st.private.remove(page)

    # -------------------------------------------------------------- draining
    def _drain(self, frame: tuple) -> None:
        """Materialize one queued frame and route its tokens. This is the
        only host<->device sync point; the span measures the actual wait."""
        if frame[0] == "admit":
            _kind, slot, first = frame
            with recorder.span("serving.engine.fetch", kind="admit"):
                tok = int(np.asarray(first))
            self._deliver(slot, tok, first=True)
            _mx.set_gauge("serving.slots_active",
                          sum(s is not None for s in self._slots))  # graftlint: disable=lock-discipline (engine-thread owned; see ownership note above _next_tick)
            return
        if frame[0] == "spec":
            # one verify window's yield: toks [S, spec_k+1] target picks,
            # counts [S] accepted lengths (0 = slot was inert)
            _kind, toks_dev, counts_dev = frame
            with recorder.span("serving.engine.fetch", kind="spec"):
                toks = np.asarray(toks_dev)
                counts = np.asarray(counts_dev)
            live = counts > 0
            if live.any():
                # every live slot consumed spec_k drafts and banked
                # count - 1 beyond the guaranteed token — the accept
                # rate `top` and the bench report
                _mx.inc("serving.spec.proposed",
                        int(live.sum()) * (toks.shape[1] - 1))
                _mx.inc("serving.spec.accepted",
                        int((counts[live] - 1).sum()))
            for slot in np.nonzero(live)[0]:
                for t in toks[slot, :counts[slot]]:
                    self._deliver(int(slot), int(t), first=False)
            _mx.set_gauge("serving.slots_active",
                          sum(s is not None for s in self._slots))  # graftlint: disable=lock-discipline (engine-thread owned; see ownership note above _next_tick)
            return
        _kind, toks_dev, mask_dev = frame
        with recorder.span("serving.engine.fetch", kind="step"):
            toks = np.asarray(toks_dev)
            mask = np.asarray(mask_dev)
        for slot in np.nonzero(mask)[0]:
            self._deliver(int(slot), int(toks[slot]), first=False)
        # publish the POST-delivery host occupancy, not the frame's entry
        # mask: with fetch_chunk=1 the final completing frame's entry mask
        # is >= 1 and no trailing all-inactive frame is ever dispatched —
        # an entry-mask gauge would read busy forever at idle
        _mx.set_gauge("serving.slots_active",
                      sum(s is not None for s in self._slots))  # graftlint: disable=lock-discipline (engine-thread owned; see ownership note above _next_tick)

    def _deliver(self, slot: int, tok: int, first: bool) -> None:
        st = self._slots[slot]  # graftlint: disable=lock-discipline (engine-thread owned; see ownership note above _next_tick)
        if st is None:
            # a frame for a slot the host already retired would mean the
            # device/host retirement conditions diverged — loud beats wrong
            log.warning("engine: token for free slot %d dropped", slot)
            return
        st.out.append(tok)
        _mx.inc("serving.tokens_total")
        now = time.perf_counter()
        if first:
            st.t_first = now
            st.req.ticket.t_first = now
            _mx.observe("serving.ttft", now - st.req.ticket.t_submit)
        # push BEFORE the done decision: a stream() consumer sees every
        # token, including the one that retires the slot
        st.req.ticket._push(tok)
        done = (tok == self._eos) or (len(st.out) >= st.req.max_new)
        if done:
            # avg time-between-tokens over the request's decode phase (the
            # chunked fetch makes per-token host deltas bursty; the
            # request-level mean is the honest figure)
            if len(st.out) > 1 and st.t_first is not None:
                _mx.observe("serving.tbt",
                            (now - st.t_first) / (len(st.out) - 1))
            st.req.ticket.t_done = now
            if self._paged:
                # release BEFORE the done event: a waiter returning from
                # result() (the diagnosis probe, capacity tests) must
                # observe the pool already reclaimed — releasing after
                # set() leaves a window where free+resident < budget
                self._release_slot_pages(st)
            st.req.ticket._finish()
            with self._cond:
                self._slots[slot] = None
                # a stop() may have reset the free list already — don't
                # re-add the slot on top of the reset
                if not self._stopping:
                    self._free.append(slot)
                self._cond.notify_all()
            _mx.inc("serving.engine.completions")

    def _fail_outstanding(self, err: BaseException) -> None:
        with self._cond:
            reqs = list(self._waiting)
            self._waiting.clear()
            slots = [s for s in self._slots if s is not None]
            self._slots = [None] * self.n_slots
            self._free = list(range(self.n_slots))
            swap, self._pending_swap = self._pending_swap, None
        if swap is not None:
            # release the waiting swapper with the failure, not a timeout
            swap.error = err
            swap.applied.set()
        if self._paged:
            # the device cache is garbage after a crash — every page and
            # every cached prefix goes with it
            self._admissions.clear()
            self._free_pages = list(range(1, self._n_pages))
            self._prefix.clear()
            _mx.set_gauge("serving.kv_pages_free", len(self._free_pages))
        # last-value-wins gauges would otherwise report the pre-crash
        # depth/occupancy forever
        _mx.set_gauge("serving.engine.queue", 0)
        _mx.set_gauge("serving.slots_active", 0)
        for r in reqs:
            r.ticket._finish(err)
        for s in slots:
            s.req.ticket._finish(err)
