"""graftlint (ISSUE 13): the static-analysis plane.

Two contracts per rule, driven by the staged fixtures under
tests/lint_fixtures/:
  - POSITIVE: every fixture line marked `# FINDING` produces exactly one
    finding of the rule (the fixture fails without the rule), and nothing
    else in the fixture does;
  - SUPPRESSED-NEGATIVE: the fixture's `# graftlint: disable=<rule>`
    lines stage the same defect and are counted suppressed, not reported.

Plus the gate that makes the plane self-enforcing: graftlint over the
WHOLE package tree (README doc surfaces included) reports zero findings
— tier-1's version of the Docker build hook and the `lint_clean`
diagnosis probe.

Everything here is pure stdlib-ast — no jax, so the file costs ~2s of
the tier-1 budget.
"""
import json
import os
import re

import pytest

from fedml_tpu.analysis import render_json, render_text, run_lint
from fedml_tpu.analysis.core import all_rules, edit_distance

FIXTURES = os.path.join(os.path.dirname(__file__), "lint_fixtures")


def _marked_lines(*relpath) -> set:
    """1-indexed lines carrying a `# FINDING` marker in a fixture file."""
    with open(os.path.join(FIXTURES, *relpath)) as f:
        return {i for i, line in enumerate(f, 1) if "# FINDING" in line}


def _lint_fixture(tree, rule, extra_docs=None):
    return run_lint([os.path.join(FIXTURES, tree)], rules=[rule],
                    extra_docs=extra_docs or {})


# ------------------------------------------------------------ per-rule
def test_donation_after_use_fixture():
    findings, stats = _lint_fixture("trace/donation.py",
                                    "donation-after-use")
    assert {f.line for f in findings} == _marked_lines("trace",
                                                       "donation.py")
    assert all(f.rule == "donation-after-use" for f in findings)
    # the suppressed twin of `bad` stages the same defect
    assert stats["suppressed"] == 1
    # the self-attribute variant names the donated attribute
    assert any("`self._carry`" in f.message for f in findings)


def test_retrace_hazard_fixture():
    findings, stats = _lint_fixture("trace/retrace.py", "retrace-hazard")
    assert {f.line for f in findings} == _marked_lines("trace",
                                                       "retrace.py")
    assert stats["suppressed"] == 1
    assert any("shard_map" in f.message for f in findings)


def test_in_trace_purity_fixture():
    findings, stats = _lint_fixture("trace/purity.py", "in-trace-purity")
    assert {f.line for f in findings} == _marked_lines("trace",
                                                       "purity.py")
    assert stats["suppressed"] == 1
    msgs = " ".join(f.message for f in findings)
    # transitive reach (called helper), direct clock, scanned body
    assert "_noise" in msgs and "traced_step" in msgs \
        and "scan_body" in msgs


def test_lock_discipline_fixture():
    findings, stats = _lint_fixture("locks", "lock-discipline")
    assert {f.line for f in findings} == _marked_lines("locks", "serving",
                                                       "pool.py")
    assert stats["suppressed"] == 1
    kinds = {f.message.split()[1] for f in findings}   # read / written
    assert kinds == {"read", "written"}


def test_lock_discipline_scoped_to_threaded_tiers():
    # the same class OUTSIDE serving/ or comm/ is out of scope — copy the
    # fixture to a neutral dir name and expect silence
    import shutil
    import tempfile

    with tempfile.TemporaryDirectory() as d:
        os.makedirs(os.path.join(d, "utils"))
        shutil.copy(os.path.join(FIXTURES, "locks", "serving", "pool.py"),
                    os.path.join(d, "utils", "pool.py"))
        findings, _ = run_lint([d], rules=["lock-discipline"],
                               extra_docs={})
    assert findings == []


def test_lock_discipline_survives_subset_scans():
    # scanning the serving dir itself (or one file in it) must NOT
    # silently disable the rule: scoping rides the absolute path, so the
    # engine's 8 justified suppressions are still counted — the exact
    # workflow of a developer lint-checking only the file they edited
    pkg = os.path.join(os.path.dirname(__file__), "..", "fedml_tpu")
    findings, stats = run_lint([os.path.join(pkg, "serving")],
                               rules=["lock-discipline"], extra_docs={})
    assert findings == [] and stats["suppressed"] >= 8
    findings, stats = run_lint(
        [os.path.join(pkg, "serving", "engine.py")],
        rules=["lock-discipline"], extra_docs={})
    assert findings == [] and stats["suppressed"] >= 8


def test_missing_scan_path_is_loud():
    # a typo'd CI path must not produce a vacuous "0 findings over
    # 0 files" green
    with pytest.raises(OSError, match="does not exist"):
        run_lint([os.path.join(FIXTURES, "no_such_dir")])
    from fedml_tpu.__main__ import main

    assert main(["lint", os.path.join(FIXTURES, "no_such_dir")]) == 2


def test_knob_drift_fixture():
    findings, stats = _lint_fixture("knobs", "knob-drift")
    assert len(findings) == 5 and stats["suppressed"] == 0
    msgs = [f.message for f in findings]
    assert any("`beta` is validated at config load" in m
               and "validated-then-dropped" in m for m in msgs)
    assert any("knob `delta`" in m and "does not register" in m
               for m in msgs)
    assert any("start_replica" in m and "shared knob mapping" in m
               for m in msgs)
    assert any("does not validate serve_args through serving/knobs.py" in m
               for m in msgs)
    assert any("hand-synced copy" in m for m in msgs)


def test_knob_drift_codec_leg_fixture():
    """The wire-codec half of knob-drift (ISSUE 14): a registered knob
    `make_policy` never reads, an unregistered knob it does read, a config
    that bypasses validate_comm_codec, and a resurrected hand-synced key
    list all surface. The real tree's codec plane passes via the
    zero-findings gate."""
    findings, _stats = _lint_fixture("codec_knobs", "knob-drift")
    msgs = [f.message for f in findings]
    assert len(findings) == 4, msgs
    assert any("knob `gamma`" in m and "validated-then-dropped" in m
               and "comm/codec.py CODEC_KNOBS" in m for m in msgs)
    assert any("knob `delta_knob`" in m and "does not register" in m
               for m in msgs)
    assert any("does not validate comm_codec through comm/codec.py" in m
               for m in msgs)
    assert any("hand-synced copy" in m and "CODEC_KNOBS" in m for m in msgs)


def test_knob_drift_soak_leg_fixture():
    """The live-loop soak half of knob-drift (ISSUE 15): a registered
    soak knob `soak_plan` never reads, an unregistered knob it does
    read, a config that bypasses validate_soak, and a resurrected
    hand-synced key list all surface. The real tree's soak plane passes
    via the zero-findings gate."""
    findings, _stats = _lint_fixture("soak_knobs", "knob-drift")
    msgs = [f.message for f in findings]
    assert len(findings) == 4, msgs
    assert any("knob `zipf_s`" in m and "validated-then-dropped" in m
               and "soak/knobs.py SOAK_KNOBS" in m for m in msgs)
    assert any("knob `surge_rps`" in m and "does not register" in m
               for m in msgs)
    assert any("does not validate the soak section through soak/knobs.py"
               in m for m in msgs)
    assert any("hand-synced copy" in m and "SOAK_KNOBS" in m for m in msgs)


def test_knob_drift_suppressed_and_clean():
    findings, stats = _lint_fixture("knobs_suppressed", "knob-drift")
    assert findings == [] and stats["suppressed"] == 5
    findings, stats = _lint_fixture("knobs_clean", "knob-drift")
    assert findings == [] and stats["suppressed"] == 0


def test_metric_registry_fixture():
    docs = {"FIXTURE.md": "\n".join([
        "counters: `fed.rounds_total` and the `fed.participation.*`",
        "family; trace spans: `serving.swap.fixture`.",
        "stale claim: `serving.ghost_series` was renamed away.",  # FINDING
    ])}
    findings, stats = _lint_fixture("metrics", "metric-registry",
                                    extra_docs=docs)
    by_path = {}
    for f in findings:
        by_path.setdefault(os.path.basename(f.path), set()).add(f.line)
    # typo findings anchor at the emit literals, consumer findings at the
    # miniature top / doc line
    assert by_path.pop("emit.py") == _marked_lines("metrics", "emit.py")
    assert by_path.pop("__main__.py") == _marked_lines("metrics",
                                                       "__main__.py")
    assert by_path.pop("FIXTURE.md") == {3}
    assert not by_path
    assert stats["suppressed"] == 3
    msgs = " ".join(f.message for f in findings)
    assert "one edit from the established" in msgs
    assert "no emit site produces it" in msgs


def test_metric_registry_slo_events_families():
    """The attribution plane's families (ISSUE 17): `slo.*` / `events.*`
    names are first-class to the rule — f-string prefix emits
    (`slo.burn.<name>`, `events.dropped.<track>`) satisfy prefix reads,
    a near-miss `slo.alert_total` typo and ghost consumer reads
    (`slo_budget_remaining`, `events.evicted_total`) all surface —
    while reads landing UNDER a prefix emit (`slo_burn_*`) don't."""
    findings, _stats = _lint_fixture("slo_events", "metric-registry")
    by_path = {}
    for f in findings:
        by_path.setdefault(os.path.basename(f.path), set()).add(f.line)
    assert by_path.pop("emit.py") == _marked_lines("slo_events", "emit.py")
    assert by_path.pop("__main__.py") == _marked_lines("slo_events",
                                                       "__main__.py")
    assert not by_path
    msgs = " ".join(f.message for f in findings)
    assert "slo.alert_total" in msgs and "slo.alerts_total" in msgs
    assert "slo_budget_remaining" in msgs
    assert "events.evicted_total" in msgs


def test_metric_registry_obs_fleet_families():
    """The fleet-observability families (ISSUE 18): `obs.*` names are
    first-class to the rule — prefix emits (`obs.clock_skew_ms.<a>.<b>`,
    `comm.link.<src>.<dst>.*`) satisfy prefix reads, a near-miss
    `obs.fleet.scrape_error` typo and ghost reads (`obs_fleet_lag_s` in
    a top frame, `obs.postmortem.spills` in a raw snapshot read) all
    surface."""
    findings, _stats = _lint_fixture("obs_fleet", "metric-registry")
    by_path = {}
    for f in findings:
        by_path.setdefault(os.path.basename(f.path), set()).add(f.line)
    assert by_path.pop("emit.py") == _marked_lines("obs_fleet", "emit.py")
    assert by_path.pop("__main__.py") == _marked_lines("obs_fleet",
                                                       "__main__.py")
    assert not by_path
    msgs = " ".join(f.message for f in findings)
    assert "obs.fleet.scrape_error" in msgs \
        and "obs.fleet.scrape_errors" in msgs
    assert "obs_fleet_lag_s" in msgs
    assert "obs.postmortem.spills" in msgs


def test_metric_registry_spans_do_not_satisfy_scrape_reads():
    # a span name must NOT satisfy a `top`/snapshot consumer — spans never
    # reach /metrics. The doc surface (where span names are legitimate)
    # accepts it; the scrape surface flags it.
    import tempfile

    src_emit = "def f(recorder):\n    recorder.span('serving.only_span')\n"
    src_main = ("def _top_frame(snap):\n    g = snap['gauges']\n"
                "    return g.get('serving_only_span')\n")
    with tempfile.TemporaryDirectory() as d:
        with open(os.path.join(d, "emit.py"), "w") as f:
            f.write(src_emit)
        with open(os.path.join(d, "__main__.py"), "w") as f:
            f.write(src_main)
        findings, _ = run_lint([d], rules=["metric-registry"],
                               extra_docs={
                                   "DOC.md": "`serving.only_span` span"})
    assert len(findings) == 1
    assert findings[0].path == "__main__.py"
    assert "serving_only_span" in findings[0].message


# ------------------------------------------------- the self-enforcing gate
def test_tree_zero_findings():
    """THE gate (acceptance bar): graftlint over the whole fedml_tpu tree
    — README consumer surfaces included — reports zero findings. Every
    suppression in the tree is a reviewed, justified exception."""
    findings, stats = run_lint()
    assert findings == [], "\n" + "\n".join(f.format() for f in findings)
    assert stats["files"] > 100    # really scanned the package
    # the engine's documented thread-ownership suppressions exist; a
    # wholesale deletion of the comments (or of the rule) would show here
    assert stats["suppressed"] >= 8


def test_rule_catalog_and_unknown_rule():
    names = [r.name for r in all_rules()]
    assert names == ["donation-after-use", "retrace-hazard", "knob-drift",
                     "metric-registry", "lock-discipline",
                     "in-trace-purity"]
    with pytest.raises(ValueError, match="unknown rule"):
        run_lint([FIXTURES], rules=["no-such-rule"])


def test_parse_error_is_a_finding_not_a_crash():
    import tempfile

    with tempfile.TemporaryDirectory() as d:
        with open(os.path.join(d, "broken.py"), "w") as f:
            f.write("def oops(:\n")
        findings, _ = run_lint([d], extra_docs={})
    assert [f.rule for f in findings] == ["parse-error"]


# ------------------------------------------------------------ reporters/CLI
def test_reporters_schema():
    findings, stats = _lint_fixture("trace/retrace.py", "retrace-hazard")
    text = render_text(findings, stats)
    assert re.search(r"retrace\.py:\d+:\d+: retrace-hazard: ", text)
    assert "finding(s)" in text
    doc = json.loads(render_json(findings, stats))
    assert set(doc) == {"findings", "count", "files", "suppressed",
                        "rules"}
    assert doc["count"] == len(findings) == len(doc["findings"])
    assert set(doc["findings"][0]) == {"rule", "path", "line", "col",
                                       "message"}


def test_cli_lint_verb(capsys):
    from fedml_tpu.__main__ import main

    # findings -> exit 1, json schema on stdout
    rc = main(["lint", "--format", "json", "--rules", "retrace-hazard",
               os.path.join(FIXTURES, "trace", "retrace.py")])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 1 and doc["count"] == 3
    # clean subset -> exit 0
    rc = main(["lint", "--rules", "donation-after-use",
               os.path.join(FIXTURES, "knobs_clean")])
    assert rc == 0
    assert "0 finding(s)" in capsys.readouterr().out
    # unknown rule -> usage error, exit 2
    rc = main(["lint", "--rules", "bogus", FIXTURES])
    assert rc == 2
    # rule catalog
    rc = main(["lint", "--list-rules"])
    assert rc == 0
    assert "knob-drift" in capsys.readouterr().out


def test_diagnosis_lint_clean_probe(capsys):
    from fedml_tpu.__main__ import main

    rc = main(["diagnosis", "--only", "lint_clean"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 0 and out["ok"] is True
    probe = out["checks"]["lint_clean"]
    assert probe["ok"] and probe["files"] > 100
    assert probe["scan_s"] < 20     # the CI-budget bar the probe enforces


# --------------------------------------------------------------- helpers
def test_edit_distance():
    assert edit_distance("fed.rounds_total", "fed.round_total", 1) == 1
    assert edit_distance("serving.ttft", "serving.tbt", 1) > 1
    assert edit_distance("a", "a", 1) == 0
    assert edit_distance("abc", "xyz", 1) > 1


def test_knob_registry_is_literal_and_matches_config():
    """The real registry parses as a pure literal (the import-free Docker
    hook depends on it) and config.validate really consumes it: an
    unknown knob is rejected naming the registry's key set."""
    import ast as _ast

    import fedml_tpu
    from fedml_tpu.serving.knobs import KNOBS

    src = open(os.path.join(os.path.dirname(__file__), "..", "fedml_tpu",
                            "serving", "knobs.py")).read()
    tree = _ast.parse(src)
    lit = next(n.value for n in _ast.walk(tree)
               if isinstance(n, _ast.Assign)
               and any(getattr(t, "id", None) == "KNOBS"
                       for t in n.targets))
    assert _ast.literal_eval(lit) == KNOBS
    with pytest.raises(ValueError, match="unknown serve_args knob"):
        fedml_tpu.init(config={"serve_args": {"decode_slotz": 1}})
    # the registry-driven validator still normalizes YAML-1.1 `off`
    cfg = fedml_tpu.init(config={"serve_args": {
        "decode_slots": 2, "kv_page_size": 4, "spec_decode": False}})
    assert cfg.serve_args.extra["spec_decode"] == "off"
