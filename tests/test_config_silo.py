"""Per-client (data-silo) config overrides — reference:
python/fedml/__init__.py:188-214 `_update_client_specific_args` +
arguments.py `data_silo_config`: a `client_specific_args` section lists one
override YAML per client rank; client rank r merges file [r-1] over the base
config."""
import pytest
import yaml

import fedml_tpu


def _write_configs(tmp_path):
    (tmp_path / "silo_1.yaml").write_text(yaml.safe_dump({
        "train_args": {"batch_size": 8, "learning_rate": 0.5}}))
    # silo 2 uses the reference's FLAT key style (attr-bag sets them flat)
    (tmp_path / "silo_2.yaml").write_text(yaml.safe_dump({
        "batch_size": 64}))
    base = {
        "common_args": {"training_type": "cross_silo"},
        "train_args": {"client_num_in_total": 2, "client_num_per_round": 2,
                       "batch_size": 32, "learning_rate": 0.1},
        "client_specific_args": {
            "data_silo_config": ["silo_1.yaml", "silo_2.yaml"]},
    }
    p = tmp_path / "fedml_config.yaml"
    p.write_text(yaml.safe_dump(base))
    return p


def test_two_silos_get_different_batch_sizes(tmp_path):
    p = _write_configs(tmp_path)
    c1 = fedml_tpu.init(config_path=str(p), rank=1, role="client")
    assert c1.train_args.batch_size == 8
    assert c1.train_args.learning_rate == 0.5
    c2 = fedml_tpu.init(config_path=str(p), rank=2, role="client")
    assert c2.train_args.batch_size == 64
    assert c2.train_args.learning_rate == 0.1   # untouched by silo_2.yaml


def test_server_rank_keeps_base_config(tmp_path):
    p = _write_configs(tmp_path)
    c0 = fedml_tpu.init(config_path=str(p))
    assert c0.rank == 0
    assert c0.train_args.batch_size == 32


def test_rank_beyond_silo_list_raises(tmp_path):
    p = _write_configs(tmp_path)
    with pytest.raises(ValueError, match="no data_silo_config entry"):
        fedml_tpu.init(config_path=str(p), rank=3)


def test_data_silo_config_in_train_args_extra(tmp_path):
    """The list may also live in train_args (unknown keys land in extra) —
    the flat attr-bag location the reference reads."""
    (tmp_path / "s1.yaml").write_text(yaml.safe_dump({"epochs": 7}))
    cfg = fedml_tpu.init(config={
        "train_args": {"data_silo_config": [str(tmp_path / "s1.yaml")]},
        "rank": 1,
    })
    assert cfg.train_args.epochs == 7


def test_override_cannot_break_validation(tmp_path):
    (tmp_path / "bad.yaml").write_text(yaml.safe_dump(
        {"train_args": {"client_num_per_round": 99}}))
    with pytest.raises(ValueError, match="client_num_per_round"):
        fedml_tpu.init(config={
            "train_args": {"client_num_in_total": 2, "client_num_per_round": 2,
                           "data_silo_config": [str(tmp_path / "bad.yaml")]},
            "rank": 1,
        })


def test_flat_override_keys_route_to_owning_section(tmp_path):
    """Reference-style FLAT overrides must reach the section that owns the
    field: data_cache_dir -> data_args (the canonical per-silo data path),
    model -> model_args, batch_size -> train_args."""
    (tmp_path / "s1.yaml").write_text(yaml.safe_dump({
        "data_cache_dir": "/silo1/data", "model": "cnn", "batch_size": 4}))
    cfg = fedml_tpu.init(config={
        "train_args": {"data_silo_config": [str(tmp_path / "s1.yaml")]},
        "rank": 1,
    })
    assert cfg.data_args.data_cache_dir == "/silo1/data"
    assert cfg.model_args.model == "cnn"
    assert cfg.train_args.batch_size == 4


def test_flat_key_routing_train_args_wins_collisions():
    """Pin the _FLAT_KEY_SECTION precedence mechanism: sections are written
    in order and train_args LAST, so every train_args field name routes to
    train_args even when another section declares the same field (round-4
    advisor: the old comment claimed first-wins; a reorder would silently
    re-route flat keys — this test makes that loud)."""
    import dataclasses

    from fedml_tpu.config import _FLAT_KEY_SECTION, Config

    train_fields = {
        f.name for f in dataclasses.fields(Config.SECTION_TYPES["train_args"])
        if f.name != "extra"}
    assert train_fields, "train_args lost its fields?"
    for name in train_fields:
        assert _FLAT_KEY_SECTION[name] == "train_args", (
            name, _FLAT_KEY_SECTION[name])
