"""Split learning, vertical FL, two-tier HierFL (reference:
simulation/mpi/split_nn/, simulation/sp/classical_vertical_fl/,
simulation/sp/hierarchical_fl/)."""
import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu.algorithms.builtin import make_fedavg
from fedml_tpu.config import TrainArgs
from fedml_tpu.models import hub
from fedml_tpu.simulation.hierarchical_fl import HierFLRunner, assign_groups
from fedml_tpu.simulation.split_nn import SplitNNRunner
from fedml_tpu.simulation.vertical import VerticalFL


class Bottom(nn.Module):
    @nn.compact
    def __call__(self, x):
        return nn.relu(nn.Dense(16)(x))


class Top(nn.Module):
    num_classes: int = 3

    @nn.compact
    def __call__(self, h):
        return nn.Dense(self.num_classes)(nn.relu(nn.Dense(16)(h)))


def _clients_data(n_clients=3, s=64, d=8, k=3, seed=0):
    rs = np.random.RandomState(seed)
    w = rs.randn(d, k)
    x = rs.randn(n_clients, s, d).astype(np.float32)
    y = np.argmax(x @ w, axis=-1).astype(np.int32)
    return {"x": x, "y": y}


# ------------------------------------------------------------------ split NN
def test_splitnn_trains_and_split_boundary_holds():
    data = _clients_data()
    runner = SplitNNRunner(Bottom(), Top(3), data, lr=0.2, batch_size=16,
                           epochs=2)
    hist = runner.run(rounds=3)
    first = np.mean([h["loss"] for h in hist[:3]])
    last = np.mean([h["loss"] for h in hist[-3:]])
    assert last < first * 0.5, (first, last)
    acc = float((runner.predict(data["x"][0]) == data["y"][0]).mean())
    assert acc > 0.8
    # the relay trained every client
    assert {h["client"] for h in hist} == {0, 1, 2}


# ---------------------------------------------------------------- vertical FL
def test_vertical_fl_three_parties():
    rs = np.random.RandomState(1)
    n, d1, d2, d3 = 400, 5, 4, 3
    xs = [rs.randn(n, d).astype(np.float32) for d in (d1, d2, d3)]
    w_true = [rs.randn(d) for d in (d1, d2, d3)]
    logit = sum(x @ w for x, w in zip(xs, w_true))
    y = (logit > 0).astype(np.float32)

    vfl = VerticalFL([d1, d2, d3], lr=0.5)
    vfl.fit(xs, y, epochs=20, batch_size=64)
    assert vfl.loss_trace[-1] < vfl.loss_trace[0] * 0.4
    acc = (vfl.predict(xs) == y.astype(np.int32)).mean()
    assert acc > 0.9, acc


def test_vertical_fl_needs_all_parties():
    """Dropping a party's features must hurt: the label depends on every
    slice (the point of vertical federation)."""
    rs = np.random.RandomState(2)
    n = 400
    xs = [rs.randn(n, 4).astype(np.float32) for _ in range(2)]
    w = [rs.randn(4) * 3 for _ in range(2)]
    y = ((xs[0] @ w[0] + xs[1] @ w[1]) > 0).astype(np.float32)
    full = VerticalFL([4, 4], lr=0.5)
    full.fit(xs, y, epochs=15)
    acc_full = (full.predict(xs) == y.astype(np.int32)).mean()
    solo = VerticalFL([4], lr=0.5)
    solo.fit(xs[:1], y, epochs=15)
    acc_solo = (solo.predict(xs[:1]) == y.astype(np.int32)).mean()
    assert acc_full > acc_solo + 0.1, (acc_full, acc_solo)


# ------------------------------------------------------------------- HierFL
def test_assign_groups_partition():
    groups = assign_groups(20, 4, seed=0)
    allc = np.concatenate(groups)
    assert sorted(allc.tolist()) == list(range(20))


def test_hierfl_two_tier_convergence():
    n_clients, s = 8, 48
    data = _clients_data(n_clients=n_clients, s=s, seed=3)
    data["mask"] = np.ones((n_clients, s), np.float32)
    model = hub.create("lr", 3)
    t = TrainArgs(epochs=1, batch_size=16, learning_rate=0.3)
    alg = make_fedavg(model.apply, t)
    params = hub.init_params(model, (8,), jax.random.key(0))
    runner = HierFLRunner(alg, params, data,
                          counts=np.full(n_clients, float(s)),
                          n_groups=3, group_comm_round=2, seed=5)
    hist = runner.run(global_rounds=5)
    assert hist[-1]["train_loss"] < hist[0]["train_loss"] * 0.6
    # global model classifies client 0's data
    logits = model.apply({"params": runner.params}, jnp.asarray(data["x"][0]))
    acc = float((jnp.argmax(logits, -1) == jnp.asarray(data["y"][0])).mean())
    assert acc > 0.8
