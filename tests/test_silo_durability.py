"""Cross-silo crash durability (ISSUE 10): server checkpoint/restore with
generation fencing, client rejoin, liveness-aware selection, bounded quorum
re-arms, the secagg × resume contract, and the kill–restart chaos soak.

The kill–restart soaks run in-process over loopback (cross_silo/soak.py —
the SIGKILL analog severs the receive loop with no farewell and leaves
stale frames in the mailboxes, like a dead process's unread sockets). The
bitwise bar: a killed-and-resumed run must end with final params
bit-identical to an uninterrupted run's."""
import functools
import os
import threading
import time

import jax
import numpy as np
import pytest

from fedml_tpu.comm import FedCommManager, Message
from fedml_tpu.comm.chaos import FaultSpec
from fedml_tpu.comm.loopback import LoopbackTransport, release_router
from fedml_tpu.config import Config, TrainArgs
from fedml_tpu.cross_silo import (
    FedClientManager, FedServerManager, SecAggClientManager,
    SecAggServerManager, SiloTrainer, message_define as md,
)
from fedml_tpu.cross_silo.soak import (
    SiloSoakHarness, chaos_kill_soak, uninterrupted_final_params,
)
from fedml_tpu.models import hub
from fedml_tpu.utils import metrics as mx


def _bitwise_equal(a, b) -> bool:
    return all(jax.tree.leaves(jax.tree.map(
        lambda x, y: bool(np.array_equal(np.asarray(x), np.asarray(y))),
        a, b)))


@functools.lru_cache(maxsize=4)
def _reference(n_clients: int, rounds: int):
    params, hist = uninterrupted_final_params(n_clients=n_clients,
                                              rounds=rounds)
    return params, tuple(r["round"] for r in hist)


# ------------------------------------------------------------ kill–restart
def test_chaos_soak_server_and_each_client_killed_once(tmp_path):
    """THE acceptance soak, driven by the chaos plane's silo_kill schedule:
    the server is SIGKILLed mid-run (round 3 is in flight when it dies
    after 2 completed rounds) and EACH client dies once; everyone
    restarts (the server with resume — it re-handshakes as generation 1;
    the client watchdog is the slow-restart backstop, so
    fed.client.reattaches may legitimately stay 0 on a fast restart); the
    run completes with full participation and final params bitwise-equal
    to an uninterrupted run's. (`server_kill_restart_soak`, the
    server-only variant, stays covered by the required
    cross_silo_durability_smoke diagnosis probe and the bench rows.)"""
    ref, ref_rounds = _reference(2, 4)
    spec = FaultSpec(silo_kill={0: 2, 1: 1, 2: 3})
    out = chaos_kill_soak(spec, str(tmp_path / "ckpt"), n_clients=2,
                          rounds=4)
    assert out["error"] is None
    assert sorted(r for r, _ in out["kills"]) == [0, 1, 2]
    assert tuple(h["round"] for h in out["history"]) == ref_rounds
    assert all(h["n_received"] == 2 for h in out["history"]), \
        f"participation dropped: {out['history']}"
    assert out["generation"] == 1 and out["resumes"] >= 1
    assert _bitwise_equal(ref, out["params"]), \
        "resumed final params differ from the uninterrupted run"


def test_generation_fencing_rejects_crafted_stale_frame(tmp_path):
    """A crafted C2S_SEND_MODEL carrying the CURRENT round index but a
    PREVIOUS incarnation's generation must be rejected (the round echo
    alone cannot fence a straggler whose round the resumed server is
    re-running)."""
    h = SiloSoakHarness(n_clients=2, rounds=3,
                        checkpoint_dir=str(tmp_path / "ckpt"),
                        server_kw=dict(round_timeout=10.0))
    try:
        h.start_all()
        assert h.wait_history(1, timeout=60)
        h.kill_server()
        srv = h.start_server(resume=True)
        assert srv.generation == 1
        before = mx.snapshot()["counters"].get(
            "fed.server.stale_gen_rejected", 0)
        stale = Message(md.C2S_SEND_MODEL, 1, 0)
        stale.add(md.KEY_MODEL_PARAMS, h.init_params)
        stale.add(md.KEY_NUM_SAMPLES, 64)
        stale.add(md.KEY_ROUND, srv.round_idx)     # the LIVE round index
        stale.add(md.KEY_GENERATION, 0)            # …from the dead gen
        srv._on_model_from_client(stale)
        assert 1 not in srv.aggregator.results, \
            "stale-generation model entered the aggregation pool"
        after = mx.snapshot()["counters"]["fed.server.stale_gen_rejected"]
        assert after >= before + 1
        # same frame with the live generation IS accepted (fence, not wall)
        fresh = Message(md.C2S_SEND_MODEL, 1, 0)
        fresh.add(md.KEY_MODEL_PARAMS, h.init_params)
        fresh.add(md.KEY_NUM_SAMPLES, 64)
        fresh.add(md.KEY_ROUND, srv.round_idx)
        fresh.add(md.KEY_GENERATION, srv.generation)
        srv._on_model_from_client(fresh)
        assert 1 in srv.aggregator.results
    finally:
        h.close()


# ------------------------------------------------- liveness + rejoin paths
def test_dead_client_evicted_then_recovered_rejoins(tmp_path):
    """A silent client is evicted from selection after its miss budget (no
    more round_timeout stalls on its account); once it comes back, its
    first status re-enters it into the pool."""
    # round_timeout must cover the rejoined client's cold jit compile
    # (~1s) or its first post-recovery round is timeout-dropped; 8 rounds
    # (not 5) so recovery on a loaded box still has rounds LEFT to be
    # re-selected into (warm 2-client rounds close in ~0.1s — all five
    # used to finish before a slow cold start even announced)
    h = SiloSoakHarness(
        n_clients=3, rounds=8,
        server_kw=dict(round_timeout=1.5, quorum_frac=0.5,
                       liveness_timeout_s=0.9))
    try:
        h.start_server()
        for cid in (1, 2):       # client 3 absent from the start
            h.start_client(cid, heartbeat_s=0.2)
        # pre-init eviction: the round-0 handshake would block on client 3
        # forever; the liveness sweep must evict it and re-select
        assert h.wait_history(2, timeout=60)
        snap = mx.snapshot()["counters"]
        assert snap.get("fed.server.evicted", 0) >= 1
        assert h.server.client_online.get(3) is False
        assert 3 not in h.server.round_clients, \
            "evicted client still being drafted"
        rejoins_before = snap.get("fed.server.rejoins", 0)
        # recovery: client 3 appears, announces, and must be re-selected
        h.start_client(3, heartbeat_s=0.2)
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline and \
                h.server.client_online.get(3) is not True:
            time.sleep(0.02)
        assert h.server.client_online.get(3) is True, "client 3 never rejoined"
        assert mx.snapshot()["counters"]["fed.server.rejoins"] \
            >= rejoins_before + 1
        # deterministic core: the selection pool itself re-includes the
        # recovered client (independent of how many rounds remain)
        round_at_recovery = h.server.round_idx
        assert 3 in h.server._select_clients(round_at_recovery + 1), \
            "recovered client missing from the selection pool"
        assert h.wait_done(timeout=60)
        # end-to-end: every round selected AFTER recovery drafts all 3 —
        # conditional on such a round existing (on a loaded box the run
        # can complete before a slow recovery; the pool assertion above
        # is the invariant either way, and with 8 rounds the window is
        # wide enough that this leg exercises in practice)
        post = [r for r in h.server.history
                if r["round"] > round_at_recovery]
        if post:
            assert any(r["n_received"] == 3 for r in post), \
                f"recovered client never re-selected: {h.server.history}"
    finally:
        h.close()


def test_killed_client_restarts_and_rejoins_midrun():
    """Kill a client mid-run and restart it on the same rank: the restarted
    incarnation re-attaches (stale mailbox frames are fenced by the round
    echo) and participates again; the run completes fully."""
    h = SiloSoakHarness(n_clients=2, rounds=4,
                        server_kw=dict(round_timeout=5.0, quorum_frac=0.5),
                        client_kw=dict(server_timeout_s=0.5, reattach=True))
    try:
        h.start_all()
        assert h.wait_history(1, timeout=60)
        h.kill_client(2)
        h.start_client(2)
        assert h.wait_done(timeout=90)
        assert h.server.error is None
        assert [r["round"] for r in h.server.history] == list(range(4))
        # the restarted client participated post-restart
        assert h.server.history[-1]["n_received"] == 2
    finally:
        h.close()


# ------------------------------------------------ bounded failure surfaces
def test_quorum_unreachable_fails_loudly():
    """The below-quorum timeout re-arm loop is BOUNDED: max_rearms
    exhausted -> run fails with a clear error + counter instead of
    re-arming forever (the reference's silent eternal hang)."""
    run = "t-quorum-bounded"
    model = hub.create("lr", 3)
    params = jax.tree.map(
        np.asarray, hub.init_params(model, (8,), jax.random.key(0)))
    srv = FedServerManager(
        FedCommManager(LoopbackTransport(0, run), 0), client_ids=[1],
        init_params=params, num_rounds=2, round_timeout=0.1, max_rearms=2)
    stub = FedCommManager(LoopbackTransport(1, run), 1)
    stub.register_message_receive_handler(
        md.S2C_CHECK_CLIENT_STATUS,
        lambda m: stub.send_message(
            Message(md.C2S_CLIENT_STATUS, 1, 0)
            .add(md.KEY_STATUS, md.STATUS_ONLINE)))
    for t in (md.S2C_INIT_CONFIG, md.S2C_FINISH):
        stub.register_message_receive_handler(t, lambda m: None)
    before = mx.snapshot()["counters"].get("fed.server.quorum_unreachable", 0)
    try:
        srv.run(background=True)
        stub.run(background=True)
        stub.send_message(Message(md.CONNECTION_IS_READY, 1, 0))
        assert srv.done.wait(20), "bounded re-arm never declared failure"
        assert srv.error and "quorum unreachable" in srv.error
        assert mx.snapshot()["counters"]["fed.server.quorum_unreachable"] \
            == before + 1
    finally:
        stub.stop()
        release_router(run)


def _mk_trainer(model, seed=0):
    rs = np.random.RandomState(seed)
    t = TrainArgs(epochs=1, batch_size=8)
    return SiloTrainer(model.apply, t,
                       rs.randn(16, 8).astype(np.float32),
                       rs.randint(0, 3, 16).astype(np.int32), seed=seed)


def test_client_server_silence_exits_nonzero():
    """A client whose server died pre-FINISH exits with error set (and a
    foreground run() raises -> nonzero process exit) instead of blocking in
    the receive loop forever."""
    run = "t-silence-exit"
    model = hub.create("lr", 3)
    c = FedClientManager(
        FedCommManager(LoopbackTransport(5, run), 5), 5, _mk_trainer(model),
        server_timeout_s=0.3, reattach=False)
    raised = []

    def fg():
        try:
            c.run(background=False)
            raised.append(None)
        except RuntimeError as e:
            raised.append(str(e))

    th = threading.Thread(target=fg, daemon=True)
    th.start()
    c.announce_ready()
    assert c.done.wait(10), "watchdog never fired"
    th.join(10)
    assert c.error and "server silent" in c.error
    assert raised and raised[0], "foreground run() did not raise"
    release_router(run)


def test_watchdog_ignores_local_training_time():
    """Local training longer than server_timeout_s is OUR work, not server
    silence — the watchdog must not declare a live server dead (or exit)
    mid-round."""
    class SlowTrainer:
        n_samples = 1

        def train(self, params, r):
            time.sleep(0.7)
            return params, 1, {}

    run = "t-busy-train"
    c = FedClientManager(
        FedCommManager(LoopbackTransport(9, run), 9), 9, SlowTrainer(),
        server_timeout_s=0.2, reattach=False)
    try:
        c.run(background=True)
        c._on_init(Message(md.S2C_INIT_CONFIG, 0, 9)
                   .add(md.KEY_MODEL_PARAMS, {"w": np.zeros(2)})
                   .add(md.KEY_ROUND, 0))     # blocks ~0.7s training
        assert c.error is None and not c.done.is_set(), \
            f"watchdog fired during local training: {c.error}"
    finally:
        c._stopped.set()
        c.comm.stop()
        release_router(run)


def test_chaos_soak_accepts_empty_schedule(tmp_path):
    """A FaultSpec with no silo_kill entries is a no-kill baseline run,
    not a TypeError."""
    out = chaos_kill_soak(FaultSpec(), str(tmp_path / "ck"), n_clients=2,
                          rounds=2)
    assert out["kills"] == [] and out["error"] is None
    assert [h["round"] for h in out["history"]] == [0, 1]


def test_client_reattach_reannounces_and_budget_refunds():
    """With reattach=True the watchdog re-announces instead of exiting; a
    real server response refunds the attempt budget (a slow-but-live
    server must never be declared dead by accumulation)."""
    run = "t-reattach"
    model = hub.create("lr", 3)
    got = []
    stub = FedCommManager(LoopbackTransport(0, run), 0)
    stub.register_message_receive_handler(
        md.CONNECTION_IS_READY, lambda m: got.append(time.monotonic()))
    stub.register_message_receive_handler(md.C2S_HEARTBEAT, lambda m: None)
    c = FedClientManager(
        FedCommManager(LoopbackTransport(7, run), 7), 7, _mk_trainer(model),
        server_timeout_s=0.2, reattach=True, max_reattach=3)
    try:
        stub.run(background=True)
        c.run(background=True)
        c.announce_ready()
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and len(got) < 3:
            time.sleep(0.02)
        assert len(got) >= 3, "watchdog never re-announced"
        assert not c.done.is_set()
        # budget refund: a server contact resets the attempt counter
        assert c._reattach_count >= 2
        c._on_check_status(Message(md.S2C_CHECK_CLIENT_STATUS, 0, 7))
        assert c._reattach_count == 0
    finally:
        c._stopped.set()
        c.comm.stop()
        stub.stop()
        release_router(run)


# ------------------------------------------------- secagg × resume contract
def _secagg_pair(run_id, ckpt=None, resume=False, rounds=3):
    model = hub.create("lr", 3)
    t = TrainArgs(epochs=1, batch_size=16, learning_rate=0.3,
                  client_num_in_total=2, client_num_per_round=2,
                  comm_round=rounds)

    def trainer(seed):
        rs = np.random.RandomState(seed)
        w = rs.randn(8, 3)
        x = rs.randn(64, 8).astype(np.float32)
        y = np.argmax(x @ w, axis=1).astype(np.int32)
        return SiloTrainer(model.apply, t, x, y, seed=seed)

    params = jax.tree.map(
        np.asarray, hub.init_params(model, (8,), jax.random.key(0)))
    srv = SecAggServerManager(
        FedCommManager(LoopbackTransport(0, run_id), 0), client_ids=[1, 2],
        init_params=params, num_rounds=rounds, checkpoint_dir=ckpt,
        resume=resume, round_timeout=10.0)
    clients = [
        SecAggClientManager(
            FedCommManager(LoopbackTransport(cid, run_id), cid), cid,
            trainer(cid), num_clients=2, client_ids=[1, 2])
        for cid in (1, 2)]
    return srv, clients


def test_secagg_round_boundary_resume_bitwise(tmp_path):
    """Server kill + round-boundary resume under secagg: surviving clients
    keep their key material, the restarted round re-masks with the same
    round_salt, and the final params match an uninterrupted secagg run
    bitwise. Every checkpoint on disk claims phase=boundary (one is never
    written mid-secagg-round)."""
    ckpt = str(tmp_path / "sa")
    ref_srv, ref_clients = _secagg_pair("sa-ref-dur")
    ref_srv.run(background=True)
    for c in ref_clients:
        c.run(background=True)
        c.announce_ready()
    assert ref_srv.done.wait(90)

    srv, clients = _secagg_pair("sa-soak-dur", ckpt=ckpt)
    srv.run(background=True)
    for c in clients:
        c.run(background=True)
        c.announce_ready()
    deadline = time.monotonic() + 60
    while len(srv.history) < 1 and time.monotonic() < deadline:
        time.sleep(0.01)
    assert srv.history, "no secagg round completed pre-kill"
    # the in-process SIGKILL analog (same ordering as SiloSoakHarness:
    # sever, drain the pump, then cancel timers)
    srv.comm.transport.stop_receive_message()
    if srv.comm._thread is not None:
        srv.comm._thread.join(timeout=10)
    with srv._lock:
        srv._cancel_timer()
    srv2 = SecAggServerManager(
        FedCommManager(LoopbackTransport(0, "sa-soak-dur"), 0),
        client_ids=[1, 2], init_params=jax.tree.map(np.zeros_like,
                                                    srv.params),
        num_rounds=3, checkpoint_dir=ckpt, resume=True, round_timeout=10.0)
    # NO client re-announce here: the resumed server must INITIATE the
    # re-handshake itself (secagg clients have no watchdog to lean on)
    srv2.run(background=True)
    assert srv2.done.wait(90), "resumed secagg run did not finish"
    assert srv2.error is None
    assert [h["round"] for h in srv2.history] == [0, 1, 2]
    assert _bitwise_equal(ref_srv.params, srv2.params)
    # the on-disk contract: every checkpoint is a boundary checkpoint
    from fedml_tpu.utils.checkpoint import read_meta

    for name in os.listdir(ckpt):
        r = int(name.split("_")[1])
        extra = read_meta(ckpt, r)["extra"]
        assert extra["kind"] == "secagg_server"
        assert extra["phase"] == "boundary"
    for cm in clients:
        cm.done.wait(10)
    release_router("sa-ref-dur")
    release_router("sa-soak-dur")


def test_secagg_resume_refuses_foreign_and_midround_checkpoints(tmp_path):
    """The pinned refusals: a non-secagg checkpoint (no protocol state) and
    a crafted checkpoint claiming a mid-round phase both refuse resume with
    a clear error, not an orbax traceback."""
    from fedml_tpu.utils.checkpoint import save_checkpoint

    model = hub.create("lr", 3)
    params = jax.tree.map(
        np.asarray, hub.init_params(model, (8,), jax.random.key(0)))
    # (a) plain-server checkpoint into the secagg server
    plain = str(tmp_path / "plain")
    save_checkpoint(plain, 0, {"params": params},
                    extra={"kind": "cross_silo_server", "generation": 0})
    with pytest.raises(ValueError, match="non-secagg|cross_silo_server"):
        SecAggServerManager(
            FedCommManager(LoopbackTransport(0, "sa-refuse-a"), 0),
            client_ids=[1, 2], init_params=params, num_rounds=3,
            checkpoint_dir=plain, resume=True)
    # (b) crafted mid-round phase
    crafted = str(tmp_path / "crafted")
    save_checkpoint(crafted, 1, {"params": params},
                    extra={"kind": "secagg_server", "phase": "unmask",
                           "threshold": 1, "q_bits": 16, "pks": {},
                           "client_counts": {}, "weight_norm": 1.0,
                           "active": [1, 2], "dropped_sk": {}})
    with pytest.raises(ValueError, match="round-boundary only"):
        SecAggServerManager(
            FedCommManager(LoopbackTransport(0, "sa-refuse-b"), 0),
            client_ids=[1, 2], init_params=params, num_rounds=3,
            checkpoint_dir=crafted, resume=True)
    release_router("sa-refuse-a")
    release_router("sa-refuse-b")


# --------------------------------------------------- config + runner wiring
def test_config_validates_durability_knobs(tmp_path):
    base = {"common_args": {"training_type": "cross_silo"}}

    def cfg(**extra):
        d = dict(base)
        d["train_args"] = {"client_num_in_total": 2,
                           "client_num_per_round": 2, "extra": extra}
        return Config.from_dict(d)

    cfg(checkpoint_dir=str(tmp_path), resume=True,
        heartbeat_s=1.0, liveness_timeout_s=5.0, server_timeout_s=30.0,
        max_rearms=3, quorum_frac=0.5)    # all valid
    with pytest.raises(ValueError, match="resume requires checkpoint_dir"):
        cfg(resume=True)
    with pytest.raises(ValueError, match="heartbeat_s"):
        cfg(heartbeat_s=-1)
    with pytest.raises(ValueError, match="liveness_timeout_s"):
        cfg(liveness_timeout_s="soon")
    with pytest.raises(ValueError, match="quorum_frac"):
        cfg(quorum_frac=1.5)
    with pytest.raises(ValueError, match="max_rearms"):
        cfg(max_rearms=0)
    with pytest.raises(ValueError, match="resume must be a boolean"):
        cfg(checkpoint_dir=str(tmp_path), resume="yes")
    # chaos-plane silo_kill schedule validation
    FaultSpec(silo_kill={0: 2, 1: 0})
    with pytest.raises(ValueError, match="silo_kill"):
        FaultSpec(silo_kill={0: -1})
    with pytest.raises(ValueError, match="silo_kill"):
        FaultSpec(silo_kill=[0])
    assert FaultSpec.from_dict(
        {"silo_kill": {"0": 2}}).silo_kill == {0: 2}


def test_runner_wires_durability_knobs(tmp_path):
    from fedml_tpu.runner import FedMLRunner

    model = hub.create("lr", 3)
    cfg = Config.from_dict({
        "common_args": {"training_type": "cross_silo"},
        "train_args": {"client_num_in_total": 2, "client_num_per_round": 2,
                       "comm_round": 3,
                       "extra": {"checkpoint_dir": str(tmp_path / "ck"),
                                 "checkpoint_every": 2, "checkpoint_keep": 5,
                                 "resume": True,
                                 "liveness_timeout_s": 9.0, "max_rearms": 4,
                                 "server_timeout_s": 7.0, "heartbeat_s": 2.0,
                                 "run_id": "wire-dur"}},
        "comm_args": {"extra": {"transport": "loopback",
                                "run_id": "wire-dur"}},
    })
    srv = FedMLRunner(cfg, model=model, role="server",
                      input_shape=(8,)).runner
    assert isinstance(srv, FedServerManager)
    assert srv.checkpoint_dir == str(tmp_path / "ck")
    assert srv.checkpoint_every == 2 and srv.checkpoint_keep == 5
    assert srv.liveness_timeout_s == 9.0 and srv.max_rearms == 4
    rs = np.random.RandomState(0)
    cli = FedMLRunner(cfg, dataset=(rs.randn(16, 8).astype(np.float32),
                                    rs.randint(0, 3, 16).astype(np.int32)),
                      model=model, role="client", rank=1).runner
    assert isinstance(cli, FedClientManager)
    assert cli.server_timeout_s == 7.0 and cli.heartbeat_s == 2.0
    assert cli.reattach is True      # implied by resume
    # an EXPLICIT checkpoint_every: 0 (cadence disabled) must survive the
    # runner plumbing, not be coerced back to every-round
    cfg0 = Config.from_dict({
        "common_args": {"training_type": "cross_silo"},
        "train_args": {"client_num_in_total": 2, "client_num_per_round": 2,
                       "extra": {"checkpoint_dir": str(tmp_path / "ck0"),
                                 "checkpoint_every": 0,
                                 "run_id": "wire-dur0"}},
        "comm_args": {"extra": {"transport": "loopback",
                                "run_id": "wire-dur0"}},
    })
    srv0 = FedMLRunner(cfg0, model=model, role="server",
                       input_shape=(8,)).runner
    assert srv0.checkpoint_every == 0
    release_router("wire-dur")
    release_router("wire-dur0")


# ------------------------------------------------------------ observability
def test_top_renders_silo_line():
    from fedml_tpu.__main__ import _top_frame

    snap = {"counters": {"fed_server_resumes_total": 1,
                         "fed_server_checkpoints_total": 4,
                         "fed_server_evicted_total": 2,
                         "fed_server_rejoins_total": 1,
                         "fed_server_stale_gen_rejected_total": 3},
            "gauges": {"fed_server_clients_online": 2,
                       "fed_server_clients_total": 3,
                       "fed_server_generation": 1},
            "histograms": {}}
    frame = _top_frame(snap, "test")
    silo = [l for l in frame.splitlines() if l.startswith("silo:")]
    assert silo, frame
    line = silo[0]
    assert "online 2/3" in line and "gen 1" in line
    assert "resumes 1" in line and "evicted 2" in line
    assert "stale_gen 3" in line
