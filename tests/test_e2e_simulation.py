"""End-to-end simulation tests: the parrot quick-start workload reimagined
(SURVEY.md §7.2). Success bar: FedAvg on separable synthetic data must learn
(accuracy well above chance), and sp vs xla backends must agree.
"""
import numpy as np
import pytest

import fedml_tpu
from fedml_tpu.config import Config
from fedml_tpu.simulation.simulator import Simulator


def make_cfg(**train_overrides):
    d = {
        "common_args": {"training_type": "simulation", "random_seed": 0},
        "data_args": {"dataset": "synthetic", "partition_method": "hetero",
                      "partition_alpha": 0.5},
        "model_args": {"model": "lr"},
        "train_args": {
            "federated_optimizer": "FedAvg",
            "client_num_in_total": 8,
            "client_num_per_round": 4,
            "comm_round": 10,
            "epochs": 1,
            "batch_size": 16,
            "learning_rate": 0.1,
            **train_overrides,
        },
        "comm_args": {"backend": "sp"},
    }
    return fedml_tpu.init(config=d)


def test_fedavg_sp_learns():
    cfg = make_cfg()
    hist = fedml_tpu.run_simulation(cfg)
    assert len(hist) == 10
    final = hist[-1]
    assert final["test_acc"] > 0.6, f"FedAvg failed to learn: {final}"
    assert final["test_acc"] > hist[0]["test_acc"]


def test_sp_and_xla_backends_agree():
    """Same seed, same workload: the single-device vmap path and the 8-device
    shard_map path must produce (numerically close) identical global models."""
    cfg_sp = make_cfg()
    cfg_sp.comm_args.backend = "sp"
    sim_sp = Simulator(cfg_sp)
    sim_sp.run(3)

    cfg_x = make_cfg()
    cfg_x.comm_args.backend = "xla"
    sim_x = Simulator(cfg_x)
    assert sim_x.mesh is not None and sim_x.mesh.devices.size == 8
    sim_x.run(3)

    import jax
    p1 = jax.device_get(sim_sp.server_state.params)
    p2 = jax.device_get(sim_x.server_state.params)
    flat1 = jax.tree.leaves(p1)
    flat2 = jax.tree.leaves(p2)
    for a, b in zip(flat1, flat2):
        np.testing.assert_allclose(a, b, rtol=2e-4, atol=2e-5)


def test_sampling_matches_reference_semantics():
    """Client sampling is np.random seeded by round index
    (reference: fedavg_api.py:127-135) — deterministic across runs."""
    cfg = make_cfg()
    sim = Simulator(cfg)
    ids_a = sim.sample_clients(3)
    np.random.seed(999)  # pollute global state; must not matter
    ids_b = sim.sample_clients(3)
    np.testing.assert_array_equal(ids_a, ids_b)
    assert len(ids_a) == 4 and len(set(ids_a.tolist())) == 4


@pytest.mark.parametrize("opt", ["FedProx", "FedNova", "SCAFFOLD", "FedDyn", "Mime", "FedOpt"])
def test_algorithm_family_learns(opt):
    over = {"federated_optimizer": opt}
    if opt == "FedOpt":
        over.update(server_optimizer="adam", server_lr=0.03)
    cfg = make_cfg(**over)
    hist = fedml_tpu.run_simulation(cfg)
    assert hist[-1]["test_acc"] > 0.5, f"{opt} failed: {hist[-1]}"
