"""SecAgg over the comm layer (reference: cross_silo/secagg/sa_fedml_*
manager set). The secagg run must equal plain FedAvg (up to quantization),
and a mid-run dropout must recover via survivor shares."""
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fedml_tpu.comm import FedCommManager
from fedml_tpu.comm.loopback import LoopbackTransport, release_router
from fedml_tpu.config import TrainArgs
from fedml_tpu.cross_silo import (
    SecAggClientManager, SecAggServerManager, SiloTrainer,
)
from fedml_tpu.cross_silo.secagg_manager import flatten_params, unflatten_params
from fedml_tpu.models import hub
from fedml_tpu.ops import tree as tu


def _mk_data(seed, n=48, d=8, k=3):
    rs = np.random.RandomState(seed)
    w = rs.randn(d, k)
    x = rs.randn(n, d).astype(np.float32)
    y = np.argmax(x @ w, axis=1).astype(np.int32)
    return x, y


def _plain_fedavg(model, t, datasets, params_np, rounds, active_from=None):
    """Hand-rolled weighted FedAvg over SiloTrainers; active_from[r] gives
    the participating client indices in round r (default: all)."""
    trainers = [SiloTrainer(model.apply, t, x, y, seed=100 + i)
                for i, (x, y) in enumerate(datasets)]
    p = params_np
    for r in range(rounds):
        idxs = (active_from[r] if active_from is not None
                else list(range(len(trainers))))
        outs = [trainers[i].train(p, r) for i in idxs]
        stacked = tu.tree_stack([jax.tree.map(jnp.asarray, o[0]) for o in outs])
        w = jnp.asarray([o[1] for o in outs], jnp.float32)
        p = jax.tree.map(np.asarray, tu.tree_weighted_mean(stacked, w))
    return p


def test_flatten_roundtrip():
    model = hub.create("lr", 3)
    params = jax.tree.map(
        np.asarray, hub.init_params(model, (8,), jax.random.key(0)))
    vec = flatten_params(params)
    back = unflatten_params(params, vec)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(a, b), params, back)


def _run_secagg(n_clients, rounds, run_id, dropper=None, round_timeout=None):
    model = hub.create("lr", 3)
    t = TrainArgs(epochs=1, batch_size=16, learning_rate=0.2)
    datasets = [_mk_data(i) for i in range(n_clients)]
    params_np = jax.tree.map(
        np.asarray, hub.init_params(model, (8,), jax.random.key(0)))
    client_ids = list(range(1, n_clients + 1))

    server = SecAggServerManager(
        FedCommManager(LoopbackTransport(0, run_id), 0),
        client_ids=client_ids, init_params=params_np, num_rounds=rounds,
        round_timeout=round_timeout)
    clients = []
    for i, cid in enumerate(client_ids):
        tr = SiloTrainer(model.apply, t, *datasets[i], seed=100 + i)
        # warm the jit cache now so a first-compile stall can't eat into the
        # round timeout (the dropout test relies on live clients replying
        # well inside the deadline)
        tr.train(params_np, 0)
        if dropper is not None:
            tr = dropper(cid, tr)
        clients.append(SecAggClientManager(
            FedCommManager(LoopbackTransport(cid, run_id), cid),
            cid, tr, num_clients=n_clients, client_ids=client_ids))
    server.run(background=True)
    for c in clients:
        c.run(background=True)
    for c in clients:
        c.announce_ready()
    assert server.done.wait(timeout=180), "secagg server did not finish"
    release_router(run_id)
    return server, params_np, model, t, datasets


def test_secagg_matches_plain_fedavg():
    rounds = 3
    server, params_np, model, t, datasets = _run_secagg(
        3, rounds, "sa-parity")
    assert len(server.history) == rounds
    expected = _plain_fedavg(model, t, datasets, params_np, rounds)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, atol=2e-3),
        server.params, expected)


class _DroppingTrainer:
    """Trains normally in round 0, goes silent from `drop_round` on (the
    client process 'dies' mid-run)."""

    def __init__(self, inner, drop_round):
        self.inner = inner
        self.drop_round = drop_round
        self.n_samples = inner.n_samples

    def train(self, params, round_idx):
        if round_idx >= self.drop_round:
            # simulate death: block forever (daemon thread, reaped at exit)
            threading.Event().wait()
        return self.inner.train(params, round_idx)


@pytest.mark.slow
def test_secagg_unmask_quorum_failure_is_loud():
    """If survivors' unmask replies can't reach t+1 (a survivor dies between
    masked upload and share reply), the server fails with error set instead
    of hanging — SecAgg privacy means the sum is unrecoverable."""
    import fedml_tpu.cross_silo.secagg_manager as sam

    class MuteUnmaskClient(sam.SecAggClientManager):
        def _on_unmask_req(self, msg):
            pass  # died before replying

    run_id = "sa-fail"
    model = hub.create("lr", 3)
    t = TrainArgs(epochs=1, batch_size=16, learning_rate=0.2)
    datasets = [_mk_data(i) for i in range(3)]
    params_np = jax.tree.map(
        np.asarray, hub.init_params(model, (8,), jax.random.key(0)))
    client_ids = [1, 2, 3]
    server = SecAggServerManager(
        FedCommManager(LoopbackTransport(0, run_id), 0),
        client_ids=client_ids, init_params=params_np, num_rounds=3,
        round_timeout=2.0)
    clients = []
    for i, cid in enumerate(client_ids):
        tr = SiloTrainer(model.apply, t, *datasets[i], seed=100 + i)
        tr.train(params_np, 0)
        if cid == 3:
            tr = _DroppingTrainer(tr, drop_round=1)
        cls = MuteUnmaskClient if cid == 2 else SecAggClientManager
        clients.append(cls(
            FedCommManager(LoopbackTransport(cid, run_id), cid),
            cid, tr, num_clients=3, client_ids=client_ids))
    server.run(background=True)
    for c in clients:
        c.run(background=True)
    for c in clients:
        c.announce_ready()
    assert server.done.wait(timeout=60), "server should fail loudly, not hang"
    release_router(run_id)
    assert server.error is not None and "unmask" in server.error


@pytest.mark.slow
def test_secagg_dropout_recovery():
    """Client 3 dies after round 0; the server reconstructs its sk from
    survivor shares, strips its pairwise masks, and the run matches plain
    FedAvg with client 3 absent from rounds >= 1."""
    rounds = 3
    n = 3

    def dropper(cid, tr):
        return _DroppingTrainer(tr, drop_round=1) if cid == 3 else tr

    server, params_np, model, t, datasets = _run_secagg(
        n, rounds, "sa-drop", dropper=dropper, round_timeout=6.0)
    assert len(server.history) == rounds
    assert server.dropped_log and server.dropped_log[0][1] == [3]
    active = [[0, 1, 2]] + [[0, 1]] * (rounds - 1)
    expected = _plain_fedavg(model, t, datasets, params_np, rounds,
                             active_from=active)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, atol=2e-3),
        server.params, expected)
