"""Paged KV cache, prefix reuse, and chunked prefill (ISSUE 7).

The contracts the paged engine lives by:
- TOKEN IDENTITY: paged greedy (and seeded-sampling) output equals the
  contiguous engine's and the per-request path's, including mid-flight
  admission/retirement over shared prefix pages and on an mp=2 mesh;
- bounded programs: one paged step program + pow2 chunk buckets, no
  matter how many requests stream through;
- prefix-cache hygiene: refs released on retirement, no cross-request
  contamination after eviction, hashes keyed on token IDS not rendered
  text;
- chunked prefill actually interleaves: active decode slots make
  progress (and can finish) while a long prompt is mid-admission;
- capacity is the PAGE BUDGET: submit's 400 states the page math, and
  the predictor falls back to the per-request path for requests the
  budget refuses instead of wrongly 400ing them.

Jitted programs dominate this file's wall clock, so engines and the
per-request reference are MODULE-scoped and shared across tests (the
conftest still swaps a fresh metrics registry per test — counter
assertions below are deltas or per-test absolutes, both safe). Tests
that need a bespoke pool (eviction pressure, tiny budgets) construct
their own; capacity-only checks use UNSTARTED engines (submit validates
capacity before the started check, and construction never compiles).
"""
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fedml_tpu.llm.transformer import TransformerLM
from fedml_tpu.serving.engine import DecodeEngine, _page_key
from fedml_tpu.serving.predictor import GreedyLMPredictor, InvalidRequest
from fedml_tpu.utils import metrics as _mx

V, D, L, H, FF = 96, 64, 2, 4, 128
MAXLEN = 32
PS = 4          # page size used throughout


@pytest.fixture(scope="module")
def setup():
    model = TransformerLM(vocab_size=V, d_model=D, n_layers=L, n_heads=H,
                          d_ff=FF, scan_layers=True)
    params = model.init(jax.random.key(0),
                        jnp.zeros((1, 10), jnp.int32))["params"]
    return model, params


@pytest.fixture(scope="module")
def per_req(setup):
    model, params = setup
    return GreedyLMPredictor(model, params, max_len=MAXLEN, kv_cache=True)


@pytest.fixture(scope="module")
def eng_paged(setup):
    """THE shared paged engine: 3 slots, 4-token pages, chunked prefill,
    prefix cache on, default (ample) pool."""
    model, params = setup
    eng = DecodeEngine(model, params, n_slots=3, max_len=MAXLEN,
                       page_size=PS, prefill_chunk=4).start()
    yield eng
    eng.stop()


@pytest.fixture(scope="module")
def eng_cont(setup):
    """Contiguous reference engine (the seeded-sampling identity pin —
    the per-request path's rng schedule differs, so contiguous-vs-paged
    is the comparison that proves the paged layout changes nothing)."""
    model, params = setup
    eng = DecodeEngine(model, params, n_slots=2, max_len=MAXLEN).start()
    yield eng
    eng.stop()


def _prompts(ns, seed=0):
    rs = np.random.RandomState(seed)
    return [rs.randint(1, V, n).tolist() for n in ns]


def _want(per_req, prompts, budgets):
    return [per_req.predict({"tokens": p, "max_new_tokens": b})
            ["generated_tokens"] for p, b in zip(prompts, budgets)]


# ----------------------------------------------------------- equivalence
def test_paged_greedy_token_identical_mid_flight_shared_pages(
        setup, per_req, eng_paged):
    """PINNED: 6 prompts — two sharing an 8-token prefix (shared pages +
    a prefix hit mid-run) — through 3 paged slots with chunked prefill,
    vs the per-request path (itself pinned equal to the contiguous
    engine in test_serving_engine.py). Admissions and retirements
    interleave mid-flight; every output must match token for token."""
    shared = _prompts((8,), seed=9)[0]
    prompts = _prompts((6, 10, 8, 5)) + [shared + p
                                         for p in _prompts((3, 5), seed=2)]
    budgets = [4, 7, 5, 6, 4, 5]
    want = _want(per_req, prompts, budgets)
    tickets = [eng_paged.submit(p, b) for p, b in zip(prompts, budgets)]
    assert [t.result(timeout=120) for t in tickets] == want


def test_paged_seeded_sampling_identical_to_contiguous(eng_cont, eng_paged):
    """Sampling equivalence: the paged engine draws the exact tokens the
    contiguous engine draws for the same (seed, temperature) — the rng
    schedule (fold_in(key(seed), pos)) is layout-independent — and the
    usual same-seed/diff-seed contract holds within the paged engine."""
    prompt = _prompts((8,), seed=11)[0]
    w7 = eng_cont.submit(prompt, 8, temperature=2.0, seed=7)
    w8 = eng_cont.submit(prompt, 8, temperature=2.0, seed=8)
    a = eng_paged.submit(prompt, 8, temperature=2.0, seed=7)
    b = eng_paged.submit(prompt, 8, temperature=2.0, seed=7)
    c = eng_paged.submit(prompt, 8, temperature=2.0, seed=8)
    w7, w8, a, b, c = (t.result(timeout=120) for t in (w7, w8, a, b, c))
    assert a == w7
    assert c == w8
    assert a == b
    assert a != c


def test_paged_program_set_bounded_retrace_guard(eng_paged):
    """One paged step program; chunk programs bounded by pow2 buckets
    below prefill_chunk. A fresh wave over the warm engine (sampling on,
    new seeds/temps, prefix hits and misses) must not add a compile."""
    counts = eng_paged.program_counts()
    assert counts["step"] == 1, counts
    # chunks of 4 plus pow2 remainders {1, 2}: <= 3 programs ever
    assert counts["admit"] is None or counts["admit"] <= 3, counts
    for t in [eng_paged.submit(p, 4, temperature=1.3, seed=i)
              for i, p in enumerate(_prompts((6, 10, 3, 12), seed=4))]:
        t.result(timeout=120)
    assert eng_paged.program_counts() == counts, "retrace"


def test_paged_mp2_token_identical(setup, per_req):
    """Paged engine on an {"mp": 2} mesh (conftest forces 8 virtual CPU
    devices): weights Megatron-split, the page POOL sharded on its heads
    axis (partition.paged_kv_cache_spec), page table replicated — greedy
    output token-identical to the unmeshed paths (per-request pinned ==
    contiguous == paged mp=1, the other links in the chain above)."""
    from fedml_tpu.parallel.mesh import make_mesh

    model, params = setup
    prompts = _prompts((6, 10, 8))
    want = _want(per_req, prompts, [5] * 3)
    eng = DecodeEngine(model, params, n_slots=2, max_len=MAXLEN,
                       page_size=PS, prefill_chunk=4,
                       mesh=make_mesh({"mp": 2})).start()
    try:
        tickets = [eng.submit(p, 5) for p in prompts]
        assert [t.result(timeout=120) for t in tickets] == want
    finally:
        eng.stop()


# ----------------------------------------------------------- prefix cache
def test_prefix_refcount_release_on_retirement(eng_paged):
    """Full prompt pages register at admission (refs held by the slot),
    refs drop to zero at retirement while the entries STAY resident, and
    a resubmission hits them (counters + fewer chunks prefilled). All
    deltas — the engine is shared and warm."""
    prompt = _prompts((12,), seed=21)[0]     # 12 tokens = 3 full pages
    # the chain keys this prompt's pages register under, computed
    # independently of the engine (eviction churn from the shared
    # engine's history cannot fake these)
    keys, key = [], b"\x00"
    for i in range(3):
        key = _page_key(key, prompt[i * PS:(i + 1) * PS])
        keys.append(key)
    first = eng_paged.submit(prompt, 5).result(timeout=120)
    mine = [eng_paged._prefix[k] for k in keys]      # KeyError = not registered
    assert all(e.refs == 0 for e in mine)            # released on retirement
    # resident means NOT in the free pool (and not handed to anyone else)
    assert not {e.page for e in mine} & set(eng_paged._free_pages)
    snap0 = _mx.snapshot()["counters"]
    again = eng_paged.submit(prompt, 5).result(timeout=120)
    assert again == first
    snap = _mx.snapshot()["counters"]
    # hit capped at (12-1)//4 = 2 pages -> only the last page's worth of
    # prompt re-prefills (1 chunk of 4 vs 3 cold chunks)
    assert snap["serving.prefix_hits"] == snap0.get(
        "serving.prefix_hits", 0) + 1
    assert snap["serving.engine.prefill_chunks"] == \
        snap0["serving.engine.prefill_chunks"] + 1
    assert all(e.refs == 0 for e in eng_paged._prefix.values())


def test_prefix_hash_keyed_on_token_ids_not_text(per_req, eng_paged):
    """[12, 3] and [1, 23] render to the same digit string — a text-keyed
    hash would alias them. The chain key is over the int32 byte view."""
    assert _page_key(b"x", [12, 3]) != _page_key(b"x", [1, 23])
    tail = _prompts((6,), seed=3)[0]
    pa, pb = [12, 3, 7, 7] + tail, [1, 23, 7, 7] + tail
    want_b = per_req.predict({"tokens": pb, "max_new_tokens": 5})
    eng_paged.submit(pa, 5).result(timeout=120)
    misses0 = _mx.snapshot()["counters"]["serving.prefix_misses"]
    hits0 = _mx.snapshot()["counters"].get("serving.prefix_hits", 0)
    got_b = eng_paged.submit(pb, 5).result(timeout=120)
    # pb must MISS pa's entries (no alias) and decode correctly
    snap = _mx.snapshot()["counters"]
    assert snap["serving.prefix_misses"] == misses0 + 1
    assert snap.get("serving.prefix_hits", 0) == hits0
    assert got_b == want_b["generated_tokens"]


def test_prefix_eviction_no_cross_request_contamination(setup):
    """Fill a TINY pool with one prompt's resident prefix, force eviction
    via allocation pressure from different requests, then resubmit the
    first prompt: its pages were reused and overwritten by others, the
    map must not serve them — output equals the cold run exactly."""
    model, params = setup
    pa = _prompts((12,), seed=1)[0]
    # 6 usable pages; pa needs ceil((12+4)/4) = 4
    eng = DecodeEngine(model, params, n_slots=1, max_len=MAXLEN,
                       page_size=PS, n_pages=7, prefill_chunk=4).start()
    try:
        cold = eng.submit(pa, 4).result(timeout=120)
        assert len(eng._prefix) == 3
        # different prompts whose pages must come from evicting pa's
        for p in _prompts((12, 12), seed=2):
            eng.submit(p, 4).result(timeout=120)
        assert _mx.snapshot()["counters"].get(
            "serving.prefix_evictions", 0) > 0
        warm = eng.submit(pa, 4).result(timeout=120)
        assert warm == cold
    finally:
        eng.stop()


# -------------------------------------------------------- chunked prefill
def test_chunked_prefill_interleaves_with_decode(eng_paged):
    """An ACTIVE slot keeps decoding — and completes — while a long
    prompt admits chunk by chunk: the short request's completion lands
    strictly before the long request's first token. (With monolithic
    admission the engine loop admits the whole prompt before any further
    step dispatch.)"""
    short = _prompts((6,), seed=31)[0]
    long_p = _prompts((24,), seed=5)[0]
    ta = eng_paged.submit(short, 4)
    # wait until the short request is ACTIVE (first token delivered)
    deadline = time.monotonic() + 60
    while ta.t_first is None and time.monotonic() < deadline:
        time.sleep(0.005)
    assert ta.t_first is not None
    chunks0 = _mx.snapshot()["counters"]["serving.engine.prefill_chunks"]
    tb = eng_paged.submit(long_p, 4)
    a_out = ta.result(timeout=120)
    b_out = tb.result(timeout=120)
    assert len(a_out) == 4 and len(b_out) == 4
    # 24-token prompt, chunk 4 -> 6 chunk programs
    assert _mx.snapshot()["counters"][
        "serving.engine.prefill_chunks"] == chunks0 + 6
    # the short request finished while the long one was still admitting:
    # its completion precedes the long one's FIRST token
    assert ta.t_done < tb.t_first, (ta.t_done, tb.t_first)


# ------------------------------------------------- capacity + page budget
def test_paged_capacity_contract_and_page_math_message(setup):
    """admissible()/capacity_error() and submit's capacity 400 need no
    started engine (validation precedes the started check) and no
    compile (jits are lazy) — so bespoke budgets are free to check."""
    model, params = setup
    prompt = _prompts((9,))[0]
    # 5 usable pages of 4 = 20 tokens
    eng = DecodeEngine(model, params, n_slots=2, max_len=MAXLEN,
                       page_size=PS, n_pages=6, prefill_chunk=4)
    assert eng.admissible(9, 11)            # 20 tokens = 5 pages
    assert not eng.admissible(9, 12)        # 21 tokens = 6 pages
    with pytest.raises(InvalidRequest, match=r"KV\s+pages") as ei:
        eng.submit(prompt, 12)
    # the message states the page math
    assert "ceil(21/4) = 6" in str(ei.value)
    assert "5 usable" in str(ei.value)
    # default pool (no n_pages) admits exactly what contiguous does
    eng = DecodeEngine(model, params, n_slots=2, max_len=MAXLEN,
                       page_size=PS)
    assert eng.admissible(9, MAXLEN - 9)
    assert not eng.admissible(9, MAXLEN - 8)


def test_predictor_page_budget_falls_back_instead_of_400(setup, per_req):
    """Satellite 1: with paging, engine capacity is the page budget — a
    request it refuses but the per-request path can serve FALLS THROUGH
    (no wrong 400); a request neither path can serve honestly gets the
    page-math message; an eos-configured predictor never silently
    degrades into post-eos tokens."""
    model, params = setup
    prompt = _prompts((9,))[0]
    pred = GreedyLMPredictor(model, params, max_len=MAXLEN, kv_cache=True,
                             decode_slots=2, kv_page_size=PS,
                             kv_n_pages=5, prefill_chunk=4)  # 16 tokens
    try:
        # 9 + 8 = 17 tokens > page budget, but per-request serves it
        req = {"tokens": prompt, "max_new_tokens": 8}
        before = _mx.snapshot()["counters"].get(
            "serving.engine.requests", 0)
        assert pred.predict(req) == per_req.predict(req)
        assert _mx.snapshot()["counters"].get(
            "serving.engine.requests", 0) == before  # engine untouched
        # neither path: per-request bucket also over max_len -> page math
        with pytest.raises(InvalidRequest, match="KV pages"):
            pred.predict({"tokens": prompt, "max_new_tokens": 24})
    finally:
        pred.stop()
    # eos-configured predictor: page-budget refusal must NOT degrade to
    # the (eos-less) per-request path — surfaced as the page-math 400
    eosp = GreedyLMPredictor(model, params, max_len=MAXLEN, kv_cache=True,
                             decode_slots=2, kv_page_size=PS,
                             kv_n_pages=5, prefill_chunk=4, eos_id=1)
    try:
        with pytest.raises(InvalidRequest, match="KV pages"):
            eosp.predict({"tokens": prompt, "max_new_tokens": 8})
    finally:
        eosp.stop()


def test_paged_pool_reclaimed_after_retirement(eng_paged):
    """Every page is either free or resident in the prefix map once all
    requests retire — nothing leaks across the whole module's churn of
    admissions, retirements, prefix hits and shared pages. (One request
    runs first so the free-pages gauge publishes into THIS test's
    registry — the conftest swaps a fresh one per test.)"""
    eng_paged.submit(_prompts((7,), seed=41)[0], 3).result(timeout=120)
    assert len(eng_paged._free_pages) + len(eng_paged._prefix) == \
        eng_paged._usable
    assert _mx.snapshot()["gauges"]["serving.kv_pages_free"] == \
        len(eng_paged._free_pages)


# ------------------------------------------------------------- satellites
def test_paged_knob_gating(setup):
    model, params = setup
    with pytest.raises(ValueError, match="page_size > 0"):
        DecodeEngine(model, params, n_slots=2, max_len=MAXLEN, n_pages=8)
    with pytest.raises(ValueError, match="kv_n_pages must be >= 2"):
        DecodeEngine(model, params, n_slots=2, max_len=MAXLEN,
                     page_size=PS, n_pages=1)
    with pytest.raises(ValueError, match="decode_slots"):
        GreedyLMPredictor(model, params, max_len=MAXLEN, kv_cache=True,
                          kv_page_size=PS)


def test_serve_args_paged_config_validation():
    from fedml_tpu.config import Config

    cfg = Config.from_dict({"serve": {
        "decode_slots": 4, "kv_page_size": 16, "kv_n_pages": 65,
        "prefill_chunk": 32, "prefix_cache": True}})
    assert cfg.serve_args.extra["kv_page_size"] == 16
    # prefill_chunk: 0 is the documented whole-prompt-admission setting —
    # the validator must accept the value the README names
    Config.from_dict({"serve": {"decode_slots": 4, "kv_page_size": 16,
                                "prefill_chunk": 0}})
    for bad, msg in (
            ({"decode_slots": 2, "kv_page_size": 0}, "kv_page_size"),
            ({"kv_page_size": 8}, "requires decode_slots"),
            ({"decode_slots": 2, "kv_n_pages": 8}, "requires kv_page_size"),
            ({"decode_slots": 2, "prefill_chunk": 8},
             "requires kv_page_size"),
            ({"decode_slots": 2, "prefix_cache": False},
             "requires kv_page_size"),
            ({"decode_slots": 2, "kv_page_size": 8, "prefix_cache": "y"},
             "boolean"),
            ({"decode_slots": 2, "kv_page_size": 8, "kv_n_pages": 1},
             ">= 2")):
        with pytest.raises(ValueError, match=msg):
            Config.from_dict({"serve": bad})


def test_lm_predictor_from_config_paged_knobs(setup):
    """The config bridge builds a PAGED engine from YAML (structural —
    engine output identity is pinned above; predict here would only
    re-compile the same programs)."""
    from fedml_tpu.config import Config
    from fedml_tpu.serving import lm_predictor_from_config

    model, params = setup
    cfg = Config.from_dict({"serve": {
        "decode_slots": 2, "engine_max_len": MAXLEN, "kv_page_size": PS,
        "kv_n_pages": 20, "prefill_chunk": 4, "prefix_cache": False}})
    pred = lm_predictor_from_config(cfg, model, params)
    try:
        assert pred.engine is not None and pred.engine._paged
        assert pred.engine._page_size == PS
        assert pred.engine._n_pages == 20
        assert pred.engine._prefill_chunk == 4
        assert pred.engine._prefix_on is False
    finally:
        pred.stop()


def test_top_line_shows_page_occupancy_and_prefix_rate():
    from fedml_tpu.__main__ import _top_frame
    from fedml_tpu.utils.prometheus import (
        parse_prometheus, render_prometheus,
    )

    _mx.inc("serving.tokens_total", 42)
    _mx.set_gauge("serving.kv_pages_budget", 20)
    _mx.set_gauge("serving.kv_pages_free", 15)
    _mx.inc("serving.prefix_hits", 3)
    _mx.inc("serving.prefix_misses", 1)
    snap = parse_prometheus(render_prometheus(_mx.snapshot()))
    frame = _top_frame(snap, "test")
    assert "pages 5/20 (25%)" in frame
    assert "prefix 75%" in frame
