"""KV-cache decode (llm/decode.py) parity vs the full-recompute forward:
prefill+step must reproduce the module's logits exactly-ish, and greedy
generation must emit the identical token sequence, for f32 and int8 bases,
with and without LoRA adapters.

Tier-1 budget: the shared model/params/reference builds are memoized at
module scope and the jitted generate closures are shared across tests
(every test was paying its own XLA compiles of the identical programs —
the PR 7 module-fixture discipline, see memory/tier1-run-recipe); every
assertion is unchanged."""
import functools

import jax
import pytest
import jax.numpy as jnp
import numpy as np

from fedml_tpu.llm.decode import (
    make_generate, make_greedy_generate, make_kv_decode, stack_blocks,
)
from fedml_tpu.llm.lora import lora_init
from fedml_tpu.llm.quant import make_inscan_quant_apply, quantize_tree_int8
from fedml_tpu.llm.transformer import TransformerLM

V, D, L, H, FF, TP = 96, 64, 3, 4, 128, 10   # TP = prompt length
MAXLEN = 24


@functools.lru_cache(maxsize=None)
def _setup(quant=False, adapters=False):
    """Deterministic (seeded) per-config fixtures, built once per module —
    tests treat every returned tree as read-only."""
    model = TransformerLM(vocab_size=V, d_model=D, n_layers=L, n_heads=H,
                          d_ff=FF, scan_layers=True)
    base = model.init(jax.random.key(0),
                      jnp.zeros((1, TP), jnp.int32))["params"]
    ads = None
    if adapters:
        ads = lora_init(jax.random.key(1), base, rank=4, a_std=0.3)
        ads = jax.tree.map(lambda a: a + 0.05 * jnp.ones_like(a), ads)
    params = quantize_tree_int8(base) if quant else base
    toks = jnp.asarray(
        np.random.RandomState(0).randint(1, V, (1, TP)), jnp.int32)
    # reference forward: the in-scan apply (itself parity-pinned against
    # the flax module in test_fedllm_scale) works for BOTH float and int8
    # trees and merges the same adapters
    ref_apply = make_inscan_quant_apply(H, dtype=jnp.float32)
    ref_ads = ads if ads is not None else lora_init(
        jax.random.key(9), base, rank=2, a_std=0.0)  # zero-impact adapters
    if ads is None:
        ref_ads = jax.tree.map(jnp.zeros_like, ref_ads)
    return model, params, ads, ref_apply, ref_ads, toks


# one jitted program per (closure, shape) shared by every test — the
# greedy/sampling generate closures are pure functions of H
@functools.lru_cache(maxsize=None)
def _jit_greedy():
    return jax.jit(make_greedy_generate(H), static_argnums=(3, 4))


@functools.lru_cache(maxsize=None)
def _jit_generate(sample=False):
    return jax.jit(make_generate(H, sample=sample), static_argnums=(3, 4))


_REF_JIT: dict = {}


def _ref_greedy(ref_apply, params, ref_ads, toks, n_new):
    """Greedy reference loop over the recompute forward. The buffer is
    padded to its FINAL length up front so ONE compiled forward serves all
    n_new steps (the model is causal: tokens after position p cannot
    change the logits at p, so the trailing zeros are inert)."""
    tp = toks.shape[1]
    buf = np.zeros((1, tp + n_new), np.int32)
    buf[:, :tp] = np.asarray(toks)
    japply = _REF_JIT.setdefault(id(ref_apply), jax.jit(ref_apply))
    out = []
    for i in range(n_new):
        logits = japply(params, ref_ads, jnp.asarray(buf))
        nxt = int(jnp.argmax(logits[0, tp + i - 1]))
        out.append(nxt)
        buf[0, tp + i] = nxt
    return out


def test_prefill_and_step_match_full_forward():
    for quant, ads_on in ((False, False), (True, True)):
        model, params, ads, ref_apply, ref_ads, toks = _setup(quant, ads_on)
        prefill, step = make_kv_decode(H)
        cache, logits0 = prefill(params, ads, toks, MAXLEN)
        full = ref_apply(params, ref_ads, toks)
        np.testing.assert_allclose(np.asarray(logits0),
                                   np.asarray(full[:, -1]),
                                   atol=2e-4, rtol=2e-3)
        # one cached step == full recompute with the token appended
        nxt = jnp.argmax(logits0, -1).astype(jnp.int32)
        cache, logits1 = step(params, ads, cache, jnp.int32(TP), nxt)
        toks2 = jnp.concatenate([toks, nxt[:, None]], axis=1)
        full2 = ref_apply(params, ref_ads, toks2)
        np.testing.assert_allclose(np.asarray(logits1),
                                   np.asarray(full2[:, -1]),
                                   atol=5e-4, rtol=5e-3)


def test_greedy_generate_matches_recompute_sequences():
    for quant, ads_on in ((False, False), (False, True), (True, True)):
        model, params, ads, ref_apply, ref_ads, toks = _setup(quant, ads_on)
        n_new = 8
        got = _jit_greedy()(params, ads, toks, MAXLEN, n_new)
        want = _ref_greedy(ref_apply, params, ref_ads, toks, n_new)
        assert np.asarray(got).tolist() == want, (quant, ads_on)


def test_stack_blocks_roundtrip():
    model = TransformerLM(vocab_size=V, d_model=D, n_layers=L, n_heads=H,
                          d_ff=FF)                     # unrolled layout
    p = model.init(jax.random.key(0),
                   jnp.zeros((1, TP), jnp.int32))["params"]
    stacked = stack_blocks(p, L)
    assert stacked["blocks"]["wq"]["kernel"].shape == (L, D, D)
    assert "block_0" not in stacked
    # already-stacked trees pass through
    assert stack_blocks(stacked, L) is stacked
    # the stacked tree drives the decode path and matches the unrolled
    # module's greedy choice on the first generated token
    toks = jnp.asarray(
        np.random.RandomState(1).randint(1, V, (1, TP)), jnp.int32)
    prefill, _step = make_kv_decode(H)
    _cache, logits = prefill(stacked, None, toks, MAXLEN)
    full = model.apply({"params": p}, toks)
    assert int(jnp.argmax(logits, -1)[0]) == int(
        jnp.argmax(full[0, -1]))


def test_generate_with_padded_prompt_and_traced_length():
    """The predictor's bucketed-prompt path: tokens right-padded to a
    bucket with the real length traced must emit the same sequence as the
    exact-shape path (padded K/V entries are masked until overwritten)."""
    _model, params, ads, ref_apply, ref_ads, toks = _setup(True, True)
    gen = _jit_greedy()
    n_new = 6
    want = np.asarray(gen(params, ads, toks, MAXLEN, n_new)).tolist()
    pbucket = 16                                  # TP=10 padded up
    padded = jnp.zeros((1, pbucket), jnp.int32).at[:, :TP].set(toks)
    got = gen(params, ads, padded, MAXLEN, n_new, length=jnp.int32(TP))
    assert np.asarray(got).tolist() == want


def test_predictor_kv_cache_matches_recompute_path():
    from fedml_tpu.serving.predictor import GreedyLMPredictor

    model = TransformerLM(vocab_size=V, d_model=D, n_layers=L, n_heads=H,
                          d_ff=FF)                 # unrolled layout
    params = model.init(jax.random.key(0),
                        jnp.zeros((1, TP), jnp.int32))["params"]
    prompt = np.random.RandomState(2).randint(1, V, TP).tolist()
    slow = GreedyLMPredictor(model, params, max_len=MAXLEN)
    fast = GreedyLMPredictor(model, params, max_len=MAXLEN, kv_cache=True)
    req = {"tokens": prompt, "max_new_tokens": 7}
    assert fast.predict(req)["generated_tokens"] == \
        slow.predict(req)["generated_tokens"]
    # custom attn_fn refuses the kv path loudly
    from fedml_tpu.parallel.seq import dense_causal_attention

    m2 = TransformerLM(vocab_size=V, d_model=D, n_layers=L, n_heads=H,
                       d_ff=FF, attn_fn=dense_causal_attention)
    with pytest.raises(ValueError, match="dense attention only"):
        GreedyLMPredictor(m2, params, max_len=MAXLEN, kv_cache=True)


def test_predictor_kv_cache_bf16_params_match_recompute():
    """bf16-served params: the kv path decodes in the params' own dtype,
    so its tokens match the recompute path's (both compute bf16)."""
    from fedml_tpu.serving.predictor import GreedyLMPredictor

    model = TransformerLM(vocab_size=V, d_model=D, n_layers=L, n_heads=H,
                          d_ff=FF)
    p32 = model.init(jax.random.key(3),
                     jnp.zeros((1, TP), jnp.int32))["params"]
    p16 = jax.tree.map(lambda a: a.astype(jnp.bfloat16), p32)
    prompt = np.random.RandomState(4).randint(1, V, TP).tolist()
    req = {"tokens": prompt, "max_new_tokens": 6}
    slow = GreedyLMPredictor(model, p16, max_len=MAXLEN)
    fast = GreedyLMPredictor(model, p16, max_len=MAXLEN, kv_cache=True)
    assert fast.predict(req)["generated_tokens"] == \
        slow.predict(req)["generated_tokens"]


def test_generate_single_token_costs_prefill_only():
    """max_new_tokens=1: the first token comes from prefill; the scan runs
    zero decode steps (a trailing wasted step was review-flagged)."""
    _model, params, ads, ref_apply, ref_ads, toks = _setup(False, False)
    got = _jit_greedy()(params, ads, toks, MAXLEN, 1)
    want = _ref_greedy(ref_apply, params, ref_ads, toks, 1)
    assert np.asarray(got).tolist() == want


def test_predictor_serves_qlora_layout_directly():
    """The QLoRA serving layout end-to-end through the predictor: int8
    frozen base + LoRA adapters, kv_cache decode, tokens match the
    reference in-scan forward's greedy loop; the recompute path refuses
    adapters loudly."""
    from fedml_tpu.serving.predictor import GreedyLMPredictor

    model, qparams, ads, ref_apply, ref_ads, toks = _setup(True, True)
    pred = GreedyLMPredictor(model, qparams, max_len=MAXLEN, kv_cache=True,
                             adapters=ads)
    out = pred.predict({"tokens": np.asarray(toks)[0].tolist(),
                        "max_new_tokens": 6})
    want = _ref_greedy(ref_apply, qparams, ref_ads, toks, 6)
    assert out["generated_tokens"] == want
    with pytest.raises(ValueError, match="need kv_cache=True"):
        GreedyLMPredictor(model, qparams, max_len=MAXLEN, adapters=ads)


def test_predictor_restacks_unrolled_adapters():
    """Regression for the silent-drop the review caught: an unrolled base
    with unrolled-keyed adapters must actually serve the ADAPTED model."""
    from fedml_tpu.serving.predictor import GreedyLMPredictor

    model = TransformerLM(vocab_size=V, d_model=D, n_layers=L, n_heads=H,
                          d_ff=FF)
    p = model.init(jax.random.key(5),
                   jnp.zeros((1, TP), jnp.int32))["params"]
    ads = lora_init(jax.random.key(6), p, rank=4, a_std=0.4)
    ads = jax.tree.map(lambda a: a + 0.2 * jnp.ones_like(a), ads)
    assert any(k.startswith("block_0/") for k in ads)   # unrolled keys
    prompt = np.random.RandomState(7).randint(1, V, TP).tolist()
    req = {"tokens": prompt, "max_new_tokens": 6}
    with_ads = GreedyLMPredictor(model, p, max_len=MAXLEN, kv_cache=True,
                                 adapters=ads).predict(req)
    without = GreedyLMPredictor(model, p, max_len=MAXLEN,
                                kv_cache=True).predict(req)
    assert with_ads["generated_tokens"] != without["generated_tokens"]
    # and the adapted tokens match merging the adapters into the base
    from fedml_tpu.llm.lora import lora_merge

    merged = lora_merge(p, ads)
    ref = GreedyLMPredictor(model, merged, max_len=MAXLEN,
                            kv_cache=True).predict(req)
    assert with_ads["generated_tokens"] == ref["generated_tokens"]


def test_predictor_compute_dtype_needs_kv_cache():
    from fedml_tpu.serving.predictor import GreedyLMPredictor

    model = TransformerLM(vocab_size=V, d_model=D, n_layers=L, n_heads=H,
                          d_ff=FF)
    p = model.init(jax.random.key(0),
                   jnp.zeros((1, TP), jnp.int32))["params"]
    with pytest.raises(ValueError, match="compute_dtype only applies"):
        GreedyLMPredictor(model, p, max_len=MAXLEN,
                          compute_dtype="bfloat16")


def test_sampling_decode_temperature_and_topk():
    """Sampling knobs (llm/decode.py make_generate): top_k=1 reduces to
    greedy regardless of temperature; same seed is deterministic; near-zero
    temperature matches greedy; different seeds at high temperature
    diverge; sampling without kv_cache refuses."""
    from fedml_tpu.llm.decode import make_generate
    from fedml_tpu.serving.predictor import GreedyLMPredictor

    model, params, ads, ref_apply, ref_ads, toks = _setup(False, False)
    greedy = _jit_greedy()(params, ads, toks, MAXLEN, 8)

    top1 = make_generate(H, sample=True, top_k=1)
    got = jax.jit(top1, static_argnums=(3, 4))(
        params, ads, toks, MAXLEN, 8, rng=jax.random.key(7),
        temperature=jnp.float32(5.0))
    assert np.asarray(got).tolist() == np.asarray(greedy).tolist()

    samp = _jit_generate(True)
    cold = samp(params, ads, toks, MAXLEN, 8, rng=jax.random.key(1),
                temperature=jnp.float32(1e-4))
    assert np.asarray(cold).tolist() == np.asarray(greedy).tolist()
    a = samp(params, ads, toks, MAXLEN, 8, rng=jax.random.key(2),
             temperature=jnp.float32(3.0))
    b = samp(params, ads, toks, MAXLEN, 8, rng=jax.random.key(2),
             temperature=jnp.float32(3.0))
    c = samp(params, ads, toks, MAXLEN, 8, rng=jax.random.key(3),
             temperature=jnp.float32(3.0))
    assert np.asarray(a).tolist() == np.asarray(b).tolist()   # same seed
    assert np.asarray(a).tolist() != np.asarray(c).tolist()   # new seed

    # predictor surface: request-level knobs, deterministic per seed
    m2 = TransformerLM(vocab_size=V, d_model=D, n_layers=L, n_heads=H,
                       d_ff=FF, scan_layers=True)
    pred = GreedyLMPredictor(m2, params, max_len=MAXLEN, kv_cache=True,
                             adapters=ads)
    prompt = np.asarray(toks)[0].tolist()
    r1 = pred.predict({"tokens": prompt, "max_new_tokens": 6,
                       "temperature": 2.0, "seed": 11})
    r2 = pred.predict({"tokens": prompt, "max_new_tokens": 6,
                       "temperature": 2.0, "seed": 11})
    assert r1["generated_tokens"] == r2["generated_tokens"]
    slow = GreedyLMPredictor(m2, params, max_len=MAXLEN)
    with pytest.raises(ValueError, match="needs kv_cache=True"):
        slow.predict({"tokens": prompt, "max_new_tokens": 4,
                      "temperature": 1.0})


def test_sampling_knob_validation():
    """Request knobs fail loudly, never silently: top_k out of range,
    top_k/seed without temperature, and the top_k compile cache is keyed
    by power-of-two buckets, not raw client values."""
    from fedml_tpu.serving.predictor import GreedyLMPredictor

    _m, params, ads, _ra, _rads, toks = _setup(False, False)
    model = TransformerLM(vocab_size=V, d_model=D, n_layers=L, n_heads=H,
                          d_ff=FF, scan_layers=True)
    pred = GreedyLMPredictor(model, params, max_len=MAXLEN, kv_cache=True)
    prompt = np.asarray(toks)[0].tolist()
    with pytest.raises(ValueError, match="top_k must be in"):
        pred.predict({"tokens": prompt, "max_new_tokens": 2,
                      "temperature": 1.0, "top_k": -1})
    with pytest.raises(ValueError, match="top_k must be in"):
        pred.predict({"tokens": prompt, "max_new_tokens": 2,
                      "temperature": 1.0, "top_k": V + 1})
    with pytest.raises(ValueError, match="only apply when temperature"):
        pred.predict({"tokens": prompt, "max_new_tokens": 2, "top_k": 5})
    with pytest.raises(ValueError, match="only apply when temperature"):
        pred.predict({"tokens": prompt, "max_new_tokens": 2, "seed": 3})
    # raw top_k values 5 and 7 share the pow2-bucket-8 program
    pred.predict({"tokens": prompt, "max_new_tokens": 2,
                  "temperature": 1.0, "top_k": 5})
    pred.predict({"tokens": prompt, "max_new_tokens": 2,
                  "temperature": 1.0, "top_k": 7})
    assert list(pred._samplers) == [8]


def test_prefill_with_flash_attention_matches_dense():
    """Long-prompt prefill can ride the Pallas flash kernel (interpret
    mode on CPU): logits and cache-driven generation match the dense
    prefill."""
    from fedml_tpu.llm.decode import make_generate
    from fedml_tpu.ops.flash_attention import flash_attn_fn

    _m, params, ads, _ra, _rads, toks = _setup(False, False)
    dense_gen = _jit_generate(False)
    flash_gen = jax.jit(make_generate(H, prefill_attn_fn=flash_attn_fn),
                        static_argnums=(3, 4))
    want = np.asarray(dense_gen(params, ads, toks, MAXLEN, 6)).tolist()
    got = np.asarray(flash_gen(params, ads, toks, MAXLEN, 6)).tolist()
    assert got == want


def test_sampling_default_knobs_and_fresh_seeds():
    """Knob defaults serialize harmlessly (top_k=0/seed=0 on a greedy
    request pass through), and sampling without an explicit seed varies
    across requests instead of repeating key(0)."""
    from fedml_tpu.serving.predictor import GreedyLMPredictor

    _m, params, ads, _ra, _rads, toks = _setup(False, False)
    model = TransformerLM(vocab_size=V, d_model=D, n_layers=L, n_heads=H,
                          d_ff=FF, scan_layers=True)
    pred = GreedyLMPredictor(model, params, max_len=MAXLEN, kv_cache=True)
    prompt = np.asarray(toks)[0].tolist()
    # SDK-style defaults on a greedy request must not be rejected
    out = pred.predict({"tokens": prompt, "max_new_tokens": 3,
                        "top_k": 0, "seed": 0})
    assert len(out["generated_tokens"]) == 3
    # unseeded sampling varies across requests (fresh server-side seed)
    req = {"tokens": prompt, "max_new_tokens": 8, "temperature": 5.0}
    gens = {tuple(pred.predict(req)["generated_tokens"])
            for _ in range(4)}
    assert len(gens) > 1, gens


def test_batched_decode_matches_per_row_generation():
    """A batch of prompts with DIFFERENT lengths decodes in lockstep
    through one program; every row must match its own batch-1 exact-shape
    generation (per-row RoPE positions, cache writes, masks, logit
    reads)."""
    from fedml_tpu.llm.decode import make_generate

    _m, params, ads, _ra, _rads, _t = _setup(True, True)
    rs = np.random.RandomState(3)
    rows = [rs.randint(1, V, n).tolist() for n in (6, 10, 8)]
    n_new = 5
    jgen = _jit_generate(False)

    want = []
    for r in rows:
        got = jgen(params, ads, jnp.asarray([r], jnp.int32), MAXLEN, n_new)
        want.append(np.asarray(got).tolist())

    pb = 16
    padded = np.zeros((len(rows), pb), np.int32)
    for i, r in enumerate(rows):
        padded[i, : len(r)] = r
    lengths = jnp.asarray([len(r) for r in rows], jnp.int32)
    got = jgen(params, ads, jnp.asarray(padded), MAXLEN, n_new,
               length=lengths)
    assert np.asarray(got).shape == (3, n_new)
    assert np.asarray(got).tolist() == want


def test_batched_sampling_matches_per_row_generation():
    """Sampling analog of the greedy batched test: with PER-ROW rng keys
    ([B] key array) every batched row must draw the exact tokens decoding
    that prompt alone with its own key would — per-row gumbel streams, not
    one [B, V] field (seeded; rows of different real lengths)."""
    from fedml_tpu.llm.decode import make_generate

    _m, params, ads, _ra, _rads, _t = _setup(True, True)
    rs = np.random.RandomState(5)
    rows = [rs.randint(1, V, n).tolist() for n in (6, 10, 8)]
    n_new = 5
    temp = jnp.float32(1.5)
    jgen = _jit_generate(True)
    keys = jax.random.split(jax.random.key(42), len(rows))

    want = []
    for i, r in enumerate(rows):
        got = jgen(params, ads, jnp.asarray([r], jnp.int32), MAXLEN, n_new,
                   rng=keys[i:i + 1], temperature=temp)
        want.append(np.asarray(got).tolist())

    pb = 16
    padded = np.zeros((len(rows), pb), np.int32)
    for i, r in enumerate(rows):
        padded[i, : len(r)] = r
    lengths = jnp.asarray([len(r) for r in rows], jnp.int32)
    got = jgen(params, ads, jnp.asarray(padded), MAXLEN, n_new,
               length=lengths, rng=keys, temperature=temp)
    assert np.asarray(got).tolist() == want
    # and the single-key form still works (shared stream, batch shape)
    shared = jgen(params, ads, jnp.asarray(padded), MAXLEN, n_new,
                  length=lengths, rng=jax.random.key(42), temperature=temp)
    assert np.asarray(shared).shape == (3, n_new)
    # a LEGACY uint32[2] PRNGKey (ndim 1 but NOT a key array) must route
    # to the shared-stream path, not crash in the per-row vmap
    legacy = jgen(params, ads, jnp.asarray(padded), MAXLEN, n_new,
                  length=lengths, rng=jax.random.PRNGKey(42),
                  temperature=temp)
    assert np.asarray(legacy).tolist() == np.asarray(shared).tolist()


def test_predictor_batched_request():
    from fedml_tpu.serving.predictor import GreedyLMPredictor

    model, params, ads, _ra, _rads, toks = _setup(False, False)
    pred = GreedyLMPredictor(model, params, max_len=MAXLEN, kv_cache=True)
    # three rows -> bucket-4 batch with one dummy row, sliced off
    rows = [np.asarray(toks)[0, :6].tolist(),
            np.asarray(toks)[0].tolist(),
            np.asarray(toks)[0, :4].tolist()]
    out = pred.predict({"tokens": rows, "max_new_tokens": 4})
    assert len(out["generated_tokens"]) == 3
    # a single-row batch stays a (1-row) batch, not a crash or a flatten
    one = pred.predict({"tokens": rows[:1], "max_new_tokens": 4})
    assert one["generated_tokens"] == [out["generated_tokens"][0]]
    # each batched row equals its solo request
    for r, g in zip(rows, out["generated_tokens"]):
        solo = pred.predict({"tokens": r, "max_new_tokens": 4})
        assert g == solo["generated_tokens"]
    # batched prompts refuse the recompute path loudly
    slow = GreedyLMPredictor(model, params, max_len=MAXLEN)
    with pytest.raises(ValueError, match="batched prompts need kv_cache"):
        slow.predict({"tokens": rows, "max_new_tokens": 2})
