"""Comm-layer microbenchmark harness (scripts/comm_bench.py) — the analog
of the reference's grpc_benchmark tests (python/tests/grpc_benchmark/,
SURVEY §6 row 2): every transport measures echo latency and bulk goodput
without hanging or corrupting payloads."""
import sys
from pathlib import Path

import pytest

sys.path.insert(0, str(Path(__file__).resolve().parents[1] / "scripts"))

from comm_bench import BACKENDS, bench_backend


@pytest.mark.parametrize("backend", BACKENDS)
def test_comm_bench_smoke(backend):
    if backend == "grpc":
        pytest.importorskip("grpc")
    iters, warmup = 5, 1
    row = bench_backend(backend, payload_mb=0.25, iters=iters, warmup=warmup)
    assert row["backend"] == backend
    assert row["rtt_ms_p50"] > 0
    assert row["throughput_mb_s"] > 0
    assert row["payload_mb"] == 0.25
    # ISSUE 2: the comm-layer perf floor is a CHECKED artifact — every
    # backend's counters must be non-zero and consistent with what the
    # bench actually moved. Sends: warmup+iters echoes + 1 warm + >=3
    # timed bulks (mirrors bench_backend's bulk loop); each bulk frame
    # carries the 0.25MB payload.
    n_bulk = 1 + max(3, iters // 5)
    payload_bytes = int(0.25 * 2**20)
    assert row["msgs_sent"] >= (warmup + iters) + n_bulk
    assert row["bytes_sent"] >= n_bulk * payload_bytes
    # the receive leg saw the same frames (echo replies ride the same
    # process-wide counters, so recv >= the bulk payload floor too)
    assert row["msgs_recv"] >= n_bulk
    assert row["bytes_recv"] >= n_bulk * payload_bytes
    assert row["publish_ms_p50"] is not None and row["publish_ms_p50"] > 0
    assert row["publish_ms_p99"] >= row["publish_ms_p50"]
