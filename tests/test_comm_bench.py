"""Comm-layer microbenchmark harness (scripts/comm_bench.py) — the analog
of the reference's grpc_benchmark tests (python/tests/grpc_benchmark/,
SURVEY §6 row 2): every transport measures echo latency and bulk goodput
without hanging or corrupting payloads."""
import sys
from pathlib import Path

import pytest

sys.path.insert(0, str(Path(__file__).resolve().parents[1] / "scripts"))

from comm_bench import BACKENDS, bench_backend


@pytest.mark.parametrize("backend", BACKENDS)
def test_comm_bench_smoke(backend):
    if backend == "grpc":
        pytest.importorskip("grpc")
    row = bench_backend(backend, payload_mb=0.25, iters=5, warmup=1)
    assert row["backend"] == backend
    assert row["rtt_ms_p50"] > 0
    assert row["throughput_mb_s"] > 0
    assert row["payload_mb"] == 0.25
