"""FedLLM slice (BASELINE.md workload 5): transformer + LoRA + sequence
parallelism. Ring/Ulysses attention must equal dense causal attention;
federated LoRA must train adapters only; the (silos, seq) round must match
the flat engine exactly (same batching, same rngs)."""
import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
try:  # newer jax exports shard_map at the top level
    from jax import shard_map
except ImportError:  # pragma: no cover — jax <= 0.4.x
    from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from fedml_tpu.config import TrainArgs
from fedml_tpu.llm import (
    TransformerLM, count_params, federated_lora, lora_apply_fn, lora_init,
    lora_merge, make_fedllm_seq_round, shard_fedllm_data,
)
from fedml_tpu.core.algorithm import ServerState
from fedml_tpu.ops import tree as tu
from fedml_tpu.parallel.mesh import make_mesh
from fedml_tpu.parallel.round import build_round_fn
from fedml_tpu.parallel.seq import (
    dense_causal_attention, ring_attention, ulysses_attention,
)

VOCAB = 32


def _qkv(seed, b=2, t=32, h=4, d=8):
    rs = np.random.RandomState(seed)
    return tuple(jnp.asarray(rs.randn(b, t, h, d).astype(np.float32))
                 for _ in range(3))


def _seq_mesh(n, name="seq"):
    return Mesh(np.array(jax.devices()[:n]), (name,))


def test_ring_attention_matches_dense():
    q, k, v = _qkv(0)
    ref = dense_causal_attention(q, k, v)
    mesh = _seq_mesh(8)
    f = shard_map(
        functools.partial(ring_attention, axis_name="seq"),
        mesh=mesh, in_specs=P(None, "seq"), out_specs=P(None, "seq"))
    out = jax.jit(f)(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ulysses_attention_matches_dense():
    q, k, v = _qkv(1)
    ref = dense_causal_attention(q, k, v)
    mesh = _seq_mesh(4)
    f = shard_map(
        functools.partial(ulysses_attention, axis_name="seq"),
        mesh=mesh, in_specs=P(None, "seq"), out_specs=P(None, "seq"))
    out = jax.jit(f)(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.slow
def test_ring_attention_grads_match_dense():
    q, k, v = _qkv(2, t=16)
    mesh = _seq_mesh(4)
    ring = shard_map(
        functools.partial(ring_attention, axis_name="seq"),
        mesh=mesh, in_specs=P(None, "seq"), out_specs=P(None, "seq"))
    g_ref = jax.grad(lambda *a: dense_causal_attention(*a).sum(), argnums=(0, 1, 2))(q, k, v)
    g_ring = jax.grad(lambda *a: ring(*a).sum(), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ref, g_ring):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=2e-4, atol=2e-4)


def _tiny_lm(**kw):
    cfg = dict(vocab_size=VOCAB, d_model=32, n_layers=2, n_heads=4, d_ff=64)
    cfg.update(kw)
    return TransformerLM(**cfg)


def test_transformer_causality():
    model = _tiny_lm()
    params = model.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32))["params"]
    toks = jnp.asarray(np.random.RandomState(0).randint(0, VOCAB, (1, 8)))
    logits = model.apply({"params": params}, toks)
    toks2 = toks.at[0, 5].set((toks[0, 5] + 3) % VOCAB)
    logits2 = model.apply({"params": params}, toks2)
    # positions < 5 see no difference; position >= 5 does
    np.testing.assert_allclose(np.asarray(logits[0, :5]),
                               np.asarray(logits2[0, :5]), atol=1e-5)
    assert float(jnp.abs(logits[0, 5:] - logits2[0, 5:]).max()) > 1e-4


def test_lora_zero_init_is_identity_and_counts():
    model = _tiny_lm()
    params = model.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32))["params"]
    adapters = lora_init(jax.random.key(1), params, rank=4)
    merged = lora_merge(params, adapters)
    toks = jnp.zeros((2, 8), jnp.int32)
    np.testing.assert_allclose(
        np.asarray(model.apply({"params": merged}, toks)),
        np.asarray(model.apply({"params": params}, toks)), atol=1e-6)
    # adapters are a small fraction of the base
    assert count_params(adapters) < 0.25 * count_params(params)


def _lm_task(n_clients=4, s=8, t=16, seed=0):
    """Learnable toy LM: next token = (token + 1) mod VOCAB."""
    rs = np.random.RandomState(seed)
    starts = rs.randint(0, VOCAB, (n_clients, s, 1))
    seqs = (starts + np.arange(t + 1)) % VOCAB
    return {
        "x": seqs[:, :, :-1].astype(np.int32),
        "y": seqs[:, :, 1:].astype(np.int32),
        "mask": np.ones((n_clients, s), np.float32),
    }


def test_federated_lora_flat_trains_adapters_only():
    model = _tiny_lm()
    base = model.init(jax.random.key(0), jnp.zeros((1, 16), jnp.int32))["params"]
    t = TrainArgs(epochs=1, batch_size=4, learning_rate=0.5)
    alg, adapters = federated_lora(model, base, t, jax.random.key(1), rank=4)
    data = _lm_task()
    n = data["x"].shape[0]
    round_fn = build_round_fn(alg, mesh=None)
    st = alg.server_init(adapters, None)
    ids = jnp.arange(n)
    weights = jnp.full((n,), 8.0)
    losses = []
    for r in range(8):
        out = round_fn(st, jnp.zeros((n,)),
                       {k: jnp.asarray(v) for k, v in data.items()},
                       ids, weights, jax.random.fold_in(jax.random.key(2), r),
                       None)
        st = out.server_state
        losses.append(float(out.metrics["train_loss"]))
    assert losses[-1] < losses[0] * 0.8, losses
    # the trained state is adapters-shaped, not base-shaped
    assert set(st.params.keys()) == set(
        lora_init(jax.random.key(1), base, rank=4).keys())


@pytest.mark.slow
def test_fedllm_seq_round_matches_flat():
    """(silos=2, seq=4) ring-attention round == flat engine round, exactly:
    same rngs, same batch composition, sum-CE/psum == batch-mean grads."""
    model = _tiny_lm()
    base = model.init(jax.random.key(0), jnp.zeros((1, 16), jnp.int32))["params"]
    t = TrainArgs(epochs=1, batch_size=8, learning_rate=0.5)
    alg, adapters = federated_lora(model, base, t, jax.random.key(1), rank=4)
    data = _lm_task(n_clients=2)
    n = data["x"].shape[0]
    ids = jnp.arange(n)
    weights = jnp.full((n,), 8.0)
    rng = jax.random.key(7)

    flat_round = build_round_fn(alg, mesh=None)
    st_flat = alg.server_init(jax.tree.map(jnp.array, adapters), None)
    flat_out = flat_round(st_flat, jnp.zeros((n,)),
                          {k: jnp.asarray(v) for k, v in data.items()},
                          ids, weights, rng, None)

    mesh = make_mesh({"silos": 2, "seq": 4})
    seq_round = make_fedllm_seq_round(model, base, t, mesh)
    st_seq = ServerState(jax.tree.map(jnp.array, adapters), None,
                         jnp.int32(0), None)
    hdata = shard_fedllm_data(data, mesh)
    new_st, metrics = seq_round(st_seq, base, hdata, ids, weights, rng)

    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-4, atol=5e-5),
        flat_out.server_state.params, new_st.params)


def test_fedllm_seq_round_converges():
    model = _tiny_lm()
    base = model.init(jax.random.key(0), jnp.zeros((1, 16), jnp.int32))["params"]
    t = TrainArgs(epochs=1, batch_size=4, learning_rate=0.5)
    alg, adapters = federated_lora(model, base, t, jax.random.key(1), rank=4)
    data = _lm_task(n_clients=2)
    mesh = make_mesh({"silos": 2, "seq": 4})
    seq_round = make_fedllm_seq_round(model, base, t, mesh)
    st = ServerState(jax.tree.map(jnp.array, adapters), None, jnp.int32(0), None)
    hdata = shard_fedllm_data(data, mesh)
    ids = jnp.arange(2)
    weights = jnp.full((2,), 8.0)
    losses = []
    for r in range(6):
        st, m = seq_round(st, base, hdata, ids, weights,
                          jax.random.fold_in(jax.random.key(3), r))
        losses.append(float(m["train_loss"]))
    assert losses[-1] < losses[0] * 0.8, losses


def test_fedllm_ulysses_round_converges():
    model = _tiny_lm()
    base = model.init(jax.random.key(0), jnp.zeros((1, 16), jnp.int32))["params"]
    t = TrainArgs(epochs=1, batch_size=4, learning_rate=0.5)
    alg, adapters = federated_lora(model, base, t, jax.random.key(1), rank=4)
    data = _lm_task(n_clients=2)
    mesh = make_mesh({"silos": 2, "seq": 4})
    seq_round = make_fedllm_seq_round(model, base, t, mesh, attn="ulysses")
    st = ServerState(jax.tree.map(jnp.array, adapters), None, jnp.int32(0), None)
    hdata = shard_fedllm_data(data, mesh)
    st, m = seq_round(st, base, hdata, jnp.arange(2), jnp.full((2,), 8.0),
                      jax.random.key(4))
    assert np.isfinite(float(m["train_loss"]))
