"""Dataset-format loaders (reference: data/cifar10/data_loader.py pickle
batches, LEAF json for femnist/shakespeare)."""
import json
import pickle

import numpy as np
import pytest

import fedml_tpu
from fedml_tpu.data import loader as dl


def _cfg(dataset, cache, **train):
    tr = {"client_num_in_total": 3, "client_num_per_round": 3,
          "batch_size": 8, "epochs": 1}
    tr.update(train)
    return fedml_tpu.init(config={
        "data_args": {"dataset": dataset, "data_cache_dir": str(cache)},
        "train_args": tr,
    })


def test_cifar10_pickle_batches(tmp_path):
    d = tmp_path / "cifar-10-batches-py"
    d.mkdir()
    rs = np.random.RandomState(0)
    for i in range(1, 6):
        blob = {b"data": rs.randint(0, 256, (20, 3072), dtype=np.uint8)
                .astype(np.uint8),
                b"labels": rs.randint(0, 10, 20).tolist()}
        (d / f"data_batch_{i}").write_bytes(pickle.dumps(blob))
    (d / "test_batch").write_bytes(pickle.dumps(
        {b"data": rs.randint(0, 256, (30, 3072), dtype=np.uint8),
         b"labels": rs.randint(0, 10, 30).tolist()}))
    ds = dl.load(_cfg("cifar10", tmp_path))
    assert not getattr(ds, "synthetic", False)
    assert ds.x_train.shape[2:] == (32, 32, 3)
    assert ds.x_test.shape == (30, 32, 32, 3)
    assert 0.0 <= ds.x_train.max() <= 1.0


def test_femnist_leaf_json(tmp_path):
    d = tmp_path / "femnist"
    rs = np.random.RandomState(1)
    for split, per in (("train", 12), ("test", 4)):
        (d / split).mkdir(parents=True)
        users = [f"u{i}" for i in range(3)]
        blob = {"users": users, "user_data": {
            u: {"x": rs.rand(per, 784).tolist(),
                "y": rs.randint(0, 62, per).tolist()} for u in users}}
        (d / split / "all_data.json").write_text(json.dumps(blob))
    ds = dl.load(_cfg("femnist", tmp_path))
    assert not getattr(ds, "synthetic", False)
    assert ds.num_clients == 3
    assert ds.x_train.shape[2:] == (28, 28, 1)
    assert ds.num_classes == 62


def test_shakespeare_leaf_json(tmp_path):
    d = tmp_path / "shakespeare"
    rs = np.random.RandomState(2)
    text = "to be or not to be that is the question " * 4
    for split, per in (("train", 6), ("test", 2)):
        (d / split).mkdir(parents=True)
        users = ["romeo", "juliet"]
        blob = {"users": users, "user_data": {
            u: {"x": [text[i:i + 80] for i in range(per)],
                "y": [text[i + 80] for i in range(per)]} for u in users}}
        (d / split / "all_data.json").write_text(json.dumps(blob))
    ds = dl.load(_cfg("shakespeare", tmp_path, client_num_in_total=2,
                      client_num_per_round=2))
    assert not getattr(ds, "synthetic", False)
    assert ds.x_train.shape[-1] == 80          # token contexts
    assert ds.y_train.shape == ds.x_train.shape  # per-position targets
    # target = context shifted by one
    row = np.asarray(ds.x_train).reshape(-1, 80)[0]
    tgt = np.asarray(ds.y_train).reshape(-1, 80)[0]
    assert (tgt[:-1] == row[1:]).all()
    # id 0 is the reserved pad (nwp objective drops target 0): real chars —
    # including '\n', which was id 0 before the +1 vocab shift — never
    # encode to 0
    assert dl._encode_chars("\n a}").min() >= 1
    real = np.asarray(ds.mask_train) > 0
    assert np.asarray(ds.x_train)[real].min() >= 1


@pytest.mark.slow
def test_shakespeare_synthetic_fallback_trains_rnn(tmp_path):
    """No files -> int-token synthetic NWP data that a sequence model can
    actually learn through the public API."""
    cfg = _cfg("shakespeare", tmp_path / "empty", client_num_in_total=2,
               client_num_per_round=2, comm_round=3, learning_rate=0.5,
               federated_optimizer="FedAvg")
    cfg.data_args.extra["synthetic_samples_per_client"] = 32
    cfg.model_args.model = "transformer_lm"
    cfg.model_args.extra = {"d_model": 32, "n_layers": 1, "n_heads": 4,
                            "d_ff": 64}
    cfg.validation_args.frequency_of_the_test = 0
    hist = fedml_tpu.run_simulation(cfg)
    assert hist[-1]["train_loss"] < hist[0]["train_loss"]


def test_mesh_mapping_file(tmp_path):
    """Device-mapping file -> Mesh (reference gpu_mapping.yaml analog) and
    the config path through the Simulator."""
    import jax
    import pytest as _pytest

    import fedml_tpu
    from fedml_tpu.parallel.mesh import mesh_from_file
    from fedml_tpu.simulation.simulator import Simulator

    f = tmp_path / "mapping.yaml"
    f.write_text("mesh:\n  silos: 2\n  intra: -1\n")
    mesh = mesh_from_file(str(f))
    assert mesh.axis_names == ("silos", "intra")
    assert mesh.devices.shape == (2, len(jax.devices()) // 2)

    # explicit device order
    ids = [d.id for d in jax.devices()][::-1]
    f2 = tmp_path / "m2.yaml"
    f2.write_text("mesh:\n  clients: %d\ndevice_ids: %s\n"
                  % (len(ids), ids))
    mesh2 = mesh_from_file(str(f2))
    assert [d.id for d in mesh2.devices.ravel()] == ids

    with _pytest.raises(ValueError, match="mesh"):
        f3 = tmp_path / "bad.yaml"
        f3.write_text("nope: 1\n")
        mesh_from_file(str(f3))
    with _pytest.raises(ValueError, match="repeats device ids"):
        f4 = tmp_path / "dup.yaml"
        f4.write_text("mesh:\n  clients: 4\ndevice_ids: [0, 2, 2, 3]\n")
        mesh_from_file(str(f4))

    fc = tmp_path / "clients.yaml"
    fc.write_text("mesh:\n  clients: -1\n")
    cfg = fedml_tpu.init(config={
        "data_args": {"dataset": "synthetic",
                      "extra": {"synthetic_samples_per_client": 16}},
        "model_args": {"model": "lr"},
        "train_args": {"federated_optimizer": "FedAvg",
                       "client_num_in_total": 8, "client_num_per_round": 8,
                       "comm_round": 1, "epochs": 1, "batch_size": 8,
                       "learning_rate": 0.3},
        "device_args": {"extra": {"mesh_mapping_file": str(fc)}},
        "validation_args": {"frequency_of_the_test": 0},
        "comm_args": {"backend": "xla"},
    })
    sim = Simulator(cfg)
    assert sim.mesh is not None and sim.mesh.axis_names == ("clients",)
    m = sim.run_round(0)
    assert np.isfinite(m["train_loss"])


# -------------------------------------- folder-image / CSV formats (r4)
def _png(path, rs, shape=(16, 16, 3)):
    from PIL import Image

    path.parent.mkdir(parents=True, exist_ok=True)
    Image.fromarray(rs.randint(0, 255, shape, dtype=np.uint8)).save(path)


def test_imagenet_folder_format(tmp_path):
    """ImageNet-style class-folder tree round-trips (reference:
    data/ImageNet/data_loader.py ImageFolder semantics)."""
    rs = np.random.RandomState(0)
    root = tmp_path / "ILSVRC2012"
    for split, per in (("train", 6), ("val", 2)):
        for cname in ("n01", "n02", "n03"):
            for i in range(per):
                _png(root / split / cname / f"{i}.png", rs)
    cfg = _cfg("ILSVRC2012", tmp_path, client_num_in_total=2,
               client_num_per_round=2)
    ds = dl.load(cfg)
    assert not getattr(ds, "synthetic", False)
    assert ds.num_classes == 3
    assert ds.num_clients == 2
    assert ds.x_train.shape[2:] == (16, 16, 3)
    assert ds.x_test.shape[0] == 6          # 3 classes x 2 val images
    assert 0.0 <= ds.x_train.max() <= 1.0


def test_imagenet_folder_mixed_shapes_need_image_size(tmp_path):
    rs = np.random.RandomState(1)
    root = tmp_path / "ILSVRC2012"
    _png(root / "train" / "a" / "0.png", rs, (16, 16, 3))
    _png(root / "train" / "a" / "1.png", rs, (20, 20, 3))
    _png(root / "train" / "b" / "0.png", rs, (16, 16, 3))
    cfg = _cfg("ILSVRC2012", tmp_path, client_num_in_total=1,
               client_num_per_round=1)
    with pytest.raises(ValueError, match="image_size"):
        dl.load(cfg)
    cfg.data_args.extra["image_size"] = 16
    ds = dl.load(cfg)
    assert ds.x_train.shape[2:] == (16, 16, 3)


def test_landmarks_gld23k_csv_format(tmp_path):
    """gld23k mapping-CSV format: user_id/image_id/class rows, images at
    <cache>/images/<image_id>.jpg, one client per user (reference:
    data/Landmarks/data_loader.py:123-148, datasets.py:51)."""
    import csv

    rs = np.random.RandomState(2)
    rows = [("u_a", "0/aa", 0), ("u_a", "0/ab", 1), ("u_b", "1/ba", 1),
            ("u_b", "1/bb", 2), ("u_b", "1/bc", 0)]
    for _u, img, _c in rows:
        _png(tmp_path / "images" / f"{img}.jpg", rs)
    with open(tmp_path / "mini_gld_train_split.csv", "w", newline="") as f:
        w = csv.writer(f)
        w.writerow(["user_id", "image_id", "class"])
        w.writerows(rows)
    with open(tmp_path / "mini_gld_test.csv", "w", newline="") as f:
        w = csv.writer(f)
        w.writerow(["user_id", "image_id", "class"])
        w.writerow(["t", "0/aa", 2])
    cfg = _cfg("gld23k", tmp_path, client_num_in_total=2,
               client_num_per_round=2, batch_size=2)
    ds = dl.load(cfg)
    assert not getattr(ds, "synthetic", False)
    assert ds.num_clients == 2
    assert list(ds.counts) == [2, 3]        # natural per-user partition
    assert ds.num_classes == 3
    assert ds.x_test.shape[0] == 1


def test_tabular_csv_format(tmp_path):
    """UCI/lending_club-style tabular CSV: header + label column, features
    standardized, 80/20 split (reference: data/UCI, lending_club_dataset.py)."""
    rs = np.random.RandomState(3)
    n = 60
    x = rs.randn(n, 18) * 5 + 3
    y = (x[:, 0] > 3).astype(int)
    lines = ["f" + ",f".join(map(str, range(18))) + ",label"]
    for i in range(n):
        lines.append(",".join(f"{v:.4f}" for v in x[i]) + f",{y[i]}")
    (tmp_path / "SUSY.csv").write_text("\n".join(lines))
    cfg = _cfg("SUSY", tmp_path, client_num_in_total=3, client_num_per_round=3)
    ds = dl.load(cfg)
    assert not getattr(ds, "synthetic", False)
    assert ds.num_classes == 2
    assert ds.x_train.shape[-1] == 18
    assert ds.x_test.shape[0] == 12         # 20% holdout
    # standardized: feature means near 0 over train+test pool
    pooled = np.concatenate([
        np.asarray(ds.x_train).reshape(-1, 18)[
            np.asarray(ds.mask_train).reshape(-1) > 0],
        np.asarray(ds.x_test)])
    assert abs(pooled.mean()) < 0.2
    # synthetic fallback still honors the format's shape when files absent
    cfg2 = _cfg("SUSY", tmp_path / "nope", client_num_in_total=3,
                client_num_per_round=3)
    ds2 = dl.load(cfg2)
    assert ds2.synthetic and ds2.x_train.shape[-1] == 18


def test_landmarks_fewer_users_than_clients_raises(tmp_path):
    import csv

    rs = np.random.RandomState(5)
    _png(tmp_path / "images" / "only.jpg", rs)
    with open(tmp_path / "mini_gld_train_split.csv", "w", newline="") as f:
        w = csv.writer(f)
        w.writerow(["user_id", "image_id", "class"])
        w.writerow(["solo", "only", 0])
    cfg = _cfg("gld23k", tmp_path, client_num_in_total=3,
               client_num_per_round=3)
    with pytest.raises(ValueError, match="1 users"):
        dl.load(cfg)


def test_tabular_holdout_only_class_widens_head(tmp_path):
    """num_classes covers classes that land entirely in the 20% holdout."""
    rs = np.random.RandomState(3)
    # seed-0 permutation of 20 rows puts specific indices in the holdout;
    # rather than chase them, give class 2 to EVERY index the split can
    # pick: 4 holdout rows of a 20-row file -> try all seeds? Simpler:
    # construct so class 2 appears ONCE and check num_classes is 3 even if
    # that row lands in the holdout for this seed.
    n = 20
    x = rs.randn(n, 4)
    y = np.zeros(n, int)
    y[1::2] = 1
    y[7] = 2                      # single class-2 row
    lines = ["a,b,c,d,label"]
    lines += [",".join(f"{v:.3f}" for v in x[i]) + f",{y[i]}"
              for i in range(n)]
    (tmp_path / "SUSY.csv").write_text("\n".join(lines))
    cfg = _cfg("SUSY", tmp_path, client_num_in_total=2, client_num_per_round=2)
    ds = dl.load(cfg)
    assert ds.num_classes == 3


def test_token_npz_cache_version_rejects_preshift(tmp_path):
    """Round-4 advisor: a shakespeare.npz exported BEFORE the +1 vocab
    shift (id 0 became a reserved pad excluded from NWP loss) must be
    rejected loudly, not silently reinterpreted; a correctly-versioned
    cache loads."""
    rs = np.random.RandomState(0)
    x = rs.randint(1, 81, (60, 80)).astype(np.int64)
    y = np.roll(x, -1, axis=1)
    base = dict(x_train=x, y_train=y, x_test=x[:8], y_test=y[:8])

    # unversioned (pre-shift) cache -> loud rejection naming the fix
    np.savez(tmp_path / "shakespeare.npz", **base)
    with pytest.raises(ValueError, match="vocab version None.*expects 2"):
        dl.load(_cfg("shakespeare", tmp_path))

    # stale version -> same rejection
    np.savez(tmp_path / "shakespeare.npz", **base, vocab_version=1)
    with pytest.raises(ValueError, match="vocab version 1"):
        dl.load(_cfg("shakespeare", tmp_path))

    # current version -> loads, and the ids ride through unshifted
    np.savez(tmp_path / "shakespeare.npz", **base, vocab_version=2)
    ds = dl.load(_cfg("shakespeare", tmp_path))
    assert not ds.synthetic
    assert int(ds.y_train.max()) <= 80

    # image datasets are untouched by the version gate
    np.savez(tmp_path / "cifar10.npz",
             x_train=rs.randint(0, 255, (40, 32, 32, 3), np.uint8),
             y_train=rs.randint(0, 10, 40),
             x_test=rs.randint(0, 255, (8, 32, 32, 3), np.uint8),
             y_test=rs.randint(0, 10, 8))
    assert not dl.load(_cfg("cifar10", tmp_path)).synthetic
