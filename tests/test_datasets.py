"""Dataset-format loaders (reference: data/cifar10/data_loader.py pickle
batches, LEAF json for femnist/shakespeare)."""
import json
import pickle

import numpy as np
import pytest

import fedml_tpu
from fedml_tpu.data import loader as dl


def _cfg(dataset, cache, **train):
    tr = {"client_num_in_total": 3, "client_num_per_round": 3,
          "batch_size": 8, "epochs": 1}
    tr.update(train)
    return fedml_tpu.init(config={
        "data_args": {"dataset": dataset, "data_cache_dir": str(cache)},
        "train_args": tr,
    })


def test_cifar10_pickle_batches(tmp_path):
    d = tmp_path / "cifar-10-batches-py"
    d.mkdir()
    rs = np.random.RandomState(0)
    for i in range(1, 6):
        blob = {b"data": rs.randint(0, 256, (20, 3072), dtype=np.uint8)
                .astype(np.uint8),
                b"labels": rs.randint(0, 10, 20).tolist()}
        (d / f"data_batch_{i}").write_bytes(pickle.dumps(blob))
    (d / "test_batch").write_bytes(pickle.dumps(
        {b"data": rs.randint(0, 256, (30, 3072), dtype=np.uint8),
         b"labels": rs.randint(0, 10, 30).tolist()}))
    ds = dl.load(_cfg("cifar10", tmp_path))
    assert not getattr(ds, "synthetic", False)
    assert ds.x_train.shape[2:] == (32, 32, 3)
    assert ds.x_test.shape == (30, 32, 32, 3)
    assert 0.0 <= ds.x_train.max() <= 1.0


def test_femnist_leaf_json(tmp_path):
    d = tmp_path / "femnist"
    rs = np.random.RandomState(1)
    for split, per in (("train", 12), ("test", 4)):
        (d / split).mkdir(parents=True)
        users = [f"u{i}" for i in range(3)]
        blob = {"users": users, "user_data": {
            u: {"x": rs.rand(per, 784).tolist(),
                "y": rs.randint(0, 62, per).tolist()} for u in users}}
        (d / split / "all_data.json").write_text(json.dumps(blob))
    ds = dl.load(_cfg("femnist", tmp_path))
    assert not getattr(ds, "synthetic", False)
    assert ds.num_clients == 3
    assert ds.x_train.shape[2:] == (28, 28, 1)
    assert ds.num_classes == 62


def test_shakespeare_leaf_json(tmp_path):
    d = tmp_path / "shakespeare"
    rs = np.random.RandomState(2)
    text = "to be or not to be that is the question " * 4
    for split, per in (("train", 6), ("test", 2)):
        (d / split).mkdir(parents=True)
        users = ["romeo", "juliet"]
        blob = {"users": users, "user_data": {
            u: {"x": [text[i:i + 80] for i in range(per)],
                "y": [text[i + 80] for i in range(per)]} for u in users}}
        (d / split / "all_data.json").write_text(json.dumps(blob))
    ds = dl.load(_cfg("shakespeare", tmp_path, client_num_in_total=2,
                      client_num_per_round=2))
    assert not getattr(ds, "synthetic", False)
    assert ds.x_train.shape[-1] == 80          # token contexts
    assert ds.y_train.shape == ds.x_train.shape  # per-position targets
    # target = context shifted by one
    row = np.asarray(ds.x_train).reshape(-1, 80)[0]
    tgt = np.asarray(ds.y_train).reshape(-1, 80)[0]
    assert (tgt[:-1] == row[1:]).all()
    # id 0 is the reserved pad (nwp objective drops target 0): real chars —
    # including '\n', which was id 0 before the +1 vocab shift — never
    # encode to 0
    assert dl._encode_chars("\n a}").min() >= 1
    real = np.asarray(ds.mask_train) > 0
    assert np.asarray(ds.x_train)[real].min() >= 1


@pytest.mark.slow
def test_shakespeare_synthetic_fallback_trains_rnn(tmp_path):
    """No files -> int-token synthetic NWP data that a sequence model can
    actually learn through the public API."""
    cfg = _cfg("shakespeare", tmp_path / "empty", client_num_in_total=2,
               client_num_per_round=2, comm_round=3, learning_rate=0.5,
               federated_optimizer="FedAvg")
    cfg.data_args.extra["synthetic_samples_per_client"] = 32
    cfg.model_args.model = "transformer_lm"
    cfg.model_args.extra = {"d_model": 32, "n_layers": 1, "n_heads": 4,
                            "d_ff": 64}
    cfg.validation_args.frequency_of_the_test = 0
    hist = fedml_tpu.run_simulation(cfg)
    assert hist[-1]["train_loss"] < hist[0]["train_loss"]


def test_mesh_mapping_file(tmp_path):
    """Device-mapping file -> Mesh (reference gpu_mapping.yaml analog) and
    the config path through the Simulator."""
    import jax
    import pytest as _pytest

    import fedml_tpu
    from fedml_tpu.parallel.mesh import mesh_from_file
    from fedml_tpu.simulation.simulator import Simulator

    f = tmp_path / "mapping.yaml"
    f.write_text("mesh:\n  silos: 2\n  intra: -1\n")
    mesh = mesh_from_file(str(f))
    assert mesh.axis_names == ("silos", "intra")
    assert mesh.devices.shape == (2, len(jax.devices()) // 2)

    # explicit device order
    ids = [d.id for d in jax.devices()][::-1]
    f2 = tmp_path / "m2.yaml"
    f2.write_text("mesh:\n  clients: %d\ndevice_ids: %s\n"
                  % (len(ids), ids))
    mesh2 = mesh_from_file(str(f2))
    assert [d.id for d in mesh2.devices.ravel()] == ids

    with _pytest.raises(ValueError, match="mesh"):
        f3 = tmp_path / "bad.yaml"
        f3.write_text("nope: 1\n")
        mesh_from_file(str(f3))
    with _pytest.raises(ValueError, match="repeats device ids"):
        f4 = tmp_path / "dup.yaml"
        f4.write_text("mesh:\n  clients: 4\ndevice_ids: [0, 2, 2, 3]\n")
        mesh_from_file(str(f4))

    fc = tmp_path / "clients.yaml"
    fc.write_text("mesh:\n  clients: -1\n")
    cfg = fedml_tpu.init(config={
        "data_args": {"dataset": "synthetic",
                      "extra": {"synthetic_samples_per_client": 16}},
        "model_args": {"model": "lr"},
        "train_args": {"federated_optimizer": "FedAvg",
                       "client_num_in_total": 8, "client_num_per_round": 8,
                       "comm_round": 1, "epochs": 1, "batch_size": 8,
                       "learning_rate": 0.3},
        "device_args": {"extra": {"mesh_mapping_file": str(fc)}},
        "validation_args": {"frequency_of_the_test": 0},
        "comm_args": {"backend": "xla"},
    })
    sim = Simulator(cfg)
    assert sim.mesh is not None and sim.mesh.axis_names == ("clients",)
    m = sim.run_round(0)
    assert np.isfinite(m["train_loss"])
