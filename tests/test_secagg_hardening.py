"""Round-3 SecAgg hardening (advisor findings):
- routed shares are encrypted to their holder; the server retains nothing
- field-magnitude budget is validated at mask time instead of wrapping
- per-round mask keys are hash-derived, not additively salted
- wire frames declare their CRC trailer via magic (FT02), never sniffing
"""
import numpy as np
import pytest

from fedml_tpu.mpc.secagg import (
    SecAggClient, decrypt_share, derive_round_key, encrypt_share,
    secagg_roundtrip,
)


def test_share_encrypt_roundtrip_and_opacity():
    share = np.array([123456789], np.int64)
    sec = 987654321
    c = encrypt_share(share, sec, owner=1, holder=3, field="b")
    assert not np.array_equal(c, share)  # ciphertext != plaintext
    assert np.array_equal(
        decrypt_share(c, sec, owner=1, holder=3, field="b"), share)
    # wrong pair secret (the server's view) does not decrypt
    assert not np.array_equal(
        decrypt_share(c, sec + 1, owner=1, holder=3, field="b"), share)
    # pad is position-bound: swapping owner/holder changes the keystream
    assert not np.array_equal(
        decrypt_share(c, sec, owner=3, holder=1, field="b"), share)


def test_share_pads_domain_separated_per_field():
    """b and sk payloads for the same (owner, holder) must use different
    keystreams — one shared pad would leak c_b - c_sk = b_share - sk_share
    (a Shamir share of b_i - sk_i) to the routing server."""
    b = np.array([111], np.int64)
    sk = np.array([222], np.int64)
    sec = 42
    cb = encrypt_share(b, sec, owner=0, holder=1, field="b")
    csk = encrypt_share(sk, sec, owner=0, holder=1, field="sk")
    p = 2**31 - 1
    assert int((cb - csk) % p) != int((b - sk) % p)


def test_round_key_derivation_not_additive():
    # additive salting would make (seed, r+1) == (seed+1, r); hashing must not
    assert derive_round_key(10, 5) != derive_round_key(11, 4)
    assert derive_round_key(10, 5) != derive_round_key(10, 6)
    assert derive_round_key(10, 5) == derive_round_key(10, 5)


def test_mask_validates_field_budget():
    c = SecAggClient(0, num_clients=1000, threshold=3, q_bits=16, seed=0)
    big = np.full(4, 100.0)  # 100 * 1000 clients >> p/2^(q_bits+1) ~ 16k
    with pytest.raises(ValueError, match="field overflow"):
        c.mask(big, {})


def test_roundtrip_still_exact_after_key_derivation_change():
    vecs = [np.full(8, float(i + 1)) for i in range(4)]
    out = secagg_roundtrip(vecs, threshold=1)
    np.testing.assert_allclose(out, sum(vecs), atol=1e-3)
    out = secagg_roundtrip(vecs, threshold=1, drop=[2])
    np.testing.assert_allclose(out, vecs[0] + vecs[1] + vecs[3], atol=1e-3)


def test_server_never_retains_share_material():
    """E2E (loopback): after setup-share routing completes, the server's
    routing buffer must be gone — it cannot reconstruct anyone's b_i/sk_i."""
    from tests.test_secagg_comm import _run_secagg  # reuse the e2e driver

    server, *_ = _run_secagg(4, 2, "sa-hardening")
    assert server._route_buf is None
    assert not hasattr(server, "shares_for")


def test_frame_magic_declares_trailer():
    from fedml_tpu.comm.serialization import _MAGIC, _MAGIC_CRC, decode, encode
    from fedml_tpu.native import crc32c

    frame = encode({"x": np.arange(4, dtype=np.float32)})
    if crc32c(b"x") is None:
        assert frame[:4] == _MAGIC  # no native lib -> FT01, no trailer
    else:
        assert frame[:4] == _MAGIC_CRC
    # adversarial payload ending in the tag bytes must decode fine
    tricky = {"blob": np.frombuffer(b"ABCDC32C", dtype=np.uint8).copy()}
    got = decode(encode(tricky))
    assert bytes(got["blob"].tobytes()) == b"ABCDC32C"
