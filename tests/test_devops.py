"""Deploy recipes (reference: devops/dockerfile + devops/k8s) — CI-style
lint: no docker daemon in the test image, so validate structure statically."""
import ast
from pathlib import Path

import yaml

ROOT = Path(__file__).resolve().parent.parent


def test_dockerfile_structure():
    df = (ROOT / "devops" / "Dockerfile").read_text()
    lines = [l for l in df.splitlines() if l and not l.startswith("#")]
    assert lines[0].startswith("FROM python:")
    assert any(l.startswith("COPY fedml_tpu") for l in lines)
    assert any("pip install" in l for l in lines)
    # deps derive FROM pyproject.toml so the two cannot drift
    pip_line = next(l for l in lines if "pip install" in l)
    assert "pyproject.toml" in pip_line and "tomllib" in pip_line
    # the CPU mesh recipe the tests/conftest uses must be baked in
    assert any("xla_force_host_platform_device_count" in l for l in lines)
    assert any(l.startswith("CMD") for l in lines)


def test_k8s_worker_job_manifest():
    doc = yaml.safe_load(
        (ROOT / "devops" / "k8s" / "worker-agent-job.yaml").read_text())
    assert doc["kind"] == "Job" and doc["apiVersion"] == "batch/v1"
    c = doc["spec"]["template"]["spec"]["containers"][0]
    assert c["image"].startswith("fedml-tpu:")
    # the embedded worker bootstrap must be valid python referencing the
    # real agent APIs
    code = c["args"][0]
    ast.parse(code)
    for needle in ("WorkerAgent", "GrpcTransport", "FedCommManager",
                   "agent.announce()"):
        assert needle in code
    # gRPC port rule consistency with comm/grpc_transport.py BASE_PORT
    from fedml_tpu.comm.grpc_transport import BASE_PORT

    assert str(BASE_PORT) in yaml.dump(doc) or any(
        str(BASE_PORT) in str(e.get("value", ""))
        for e in c.get("env", []))
