"""Test conftest: force an 8-device virtual CPU mesh before JAX initializes.

Mirrors the reference's "multi-node without a cluster" CI strategy
(reference: python/tests/cross-silo/run_cross_silo.sh:1-28 fakes multi-node with
multi-process on one box); here we fake a TPU pod with
--xla_force_host_platform_device_count on CPU.
"""
import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

# The axon sitecustomize registers the remote-TPU backend at interpreter start
# and overrides JAX_PLATFORMS; force CPU after import (before first backend use).
jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices():
    return jax.devices()


@pytest.fixture(autouse=True)
def _isolate_recorder():
    """Tests share the PROCESS-GLOBAL event recorder (utils/events.py);
    snapshot its state (spans, metric rows, sinks, the exact-count summary
    aggregate) before each test and restore it after, so one test's
    telemetry can't satisfy — or pollute — another test's assertions.
    Background daemons a test failed to stop may append during restore;
    that's the same leak the fixture existed to contain, just one row of
    it."""
    from fedml_tpu.utils.events import recorder

    spans, metrics = list(recorder.spans), list(recorder.metrics)
    sinks = list(recorder.sinks)
    agg = {k: dict(v) for k, v in recorder.summary().items()}
    dropped = dict(recorder.dropped)
    dropped_rows = recorder.dropped_rows
    yield
    recorder.spans.clear()
    recorder.spans.extend(spans)
    recorder.metrics.clear()
    recorder.metrics.extend(metrics)
    recorder.sinks[:] = sinks
    with recorder._agg_lock:
        recorder._agg.clear()
        recorder._agg.update(agg)
        recorder.dropped.clear()
        recorder.dropped.update(dropped)
        recorder.dropped_rows = dropped_rows


@pytest.fixture(autouse=True)
def _isolate_xla_ledger():
    """The XLA cost/memory ledger (utils/xla_ledger.py, ISSUE 17) keeps
    process-global program/buffer dicts; snapshot and restore them so one
    test's captures can't satisfy another's assertions."""
    from fedml_tpu.utils import xla_ledger

    progs = xla_ledger.programs()
    bufs = xla_ledger.buffers()
    enabled = xla_ledger.enabled()
    yield
    with xla_ledger._lock:
        xla_ledger._programs.clear()
        xla_ledger._programs.update(progs)
        xla_ledger._buffers.clear()
        xla_ledger._buffers.update(bufs)
    xla_ledger.set_enabled(enabled)


@pytest.fixture(autouse=True)
def _isolate_flight_recorder():
    """The crash flight recorder (utils/postmortem.py, ISSUE 18) is a
    process-global ring + arm state; a test that arms it must not leave
    the spill thread pointed at its (deleted) tmp dir for the next test.
    Disarm and clear the rings afterwards; re-enable in case a test
    toggled it off."""
    from fedml_tpu.utils import postmortem as pm

    yield
    if pm.flight.armed_dir is not None:
        pm.flight.disarm()
    pm.flight._spans.clear()
    pm.flight._frames.clear()
    pm.flight.set_enabled(True)
    pm.flight.process = "main"


@pytest.fixture(autouse=True)
def _isolate_metrics_registry():
    """The recorder fixture above left the process-global MetricsRegistry
    (utils/metrics.py) shared across tests, so counter assertions (e.g.
    test_comm_bench's byte floors) could bleed across test order. Swap in a
    fresh registry per test — every writer resolves `metrics.registry` at
    call time, so in-flight instruments from daemons a previous test leaked
    keep writing into the OLD registry harmlessly — and restore the
    original afterwards."""
    from fedml_tpu.utils import metrics as mx

    prev = mx.registry
    mx.registry = mx.MetricsRegistry()
    yield
    mx.registry = prev
