"""Pallas flash attention (ops/flash_attention.py) — must equal dense
causal attention in values and gradients, and drop into TransformerLM."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fedml_tpu.llm import TransformerLM
from fedml_tpu.ops.flash_attention import flash_attention, flash_attn_fn
from fedml_tpu.parallel.seq import dense_causal_attention


def _qkv(seed, bh=4, t=128, d=32):
    rs = np.random.RandomState(seed)
    return tuple(jnp.asarray(rs.randn(bh, t, d).astype(np.float32))
                 for _ in range(3))


def _dense_bhtd(q, k, v):
    # dense reference expects [B, T, H, D]; fold BH into H with B=1
    to4 = lambda x: x[None].transpose(0, 2, 1, 3)     # [1, T, BH, D]
    out = dense_causal_attention(to4(q), to4(k), to4(v))
    return out.transpose(0, 2, 1, 3)[0]


def test_flash_matches_dense_values():
    q, k, v = _qkv(0)
    out = flash_attention(q, k, v, block_q=32, block_k=32)
    ref = _dense_bhtd(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_flash_matches_dense_uneven_blocks():
    q, k, v = _qkv(1, t=96)
    out = flash_attention(q, k, v, block_q=32, block_k=48)
    ref = _dense_bhtd(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_flash_grads_match_dense():
    q, k, v = _qkv(2, bh=2, t=64, d=16)

    def loss_flash(q, k, v):
        return (flash_attention(q, k, v, block_q=16, block_k=16) ** 2).sum()

    def loss_dense(q, k, v):
        return (_dense_bhtd(q, k, v) ** 2).sum()

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4)


@pytest.mark.slow
def test_transformer_with_flash_attention():
    """Same params, flash vs dense attention -> same logits; training step
    through the flash path stays finite."""
    dense_model = TransformerLM(vocab_size=32, d_model=64, n_layers=2,
                                n_heads=4, d_ff=128)
    flash_model = TransformerLM(vocab_size=32, d_model=64, n_layers=2,
                                n_heads=4, d_ff=128,
                                attn_fn=flash_attn_fn)
    toks = jnp.asarray(np.random.RandomState(0).randint(0, 32, (2, 64)),
                       jnp.int32)
    params = dense_model.init(jax.random.key(0), toks)["params"]
    ref = dense_model.apply({"params": params}, toks)
    out = flash_model.apply({"params": params}, toks)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=3e-4, atol=3e-4)

    def loss(p):
        logits = flash_model.apply({"params": p}, toks)
        import optax

        return optax.softmax_cross_entropy_with_integer_labels(
            logits, jnp.roll(toks, -1, 1)).mean()

    g = jax.grad(loss)(params)
    assert all(np.isfinite(np.asarray(l)).all() for l in jax.tree.leaves(g))


def test_pallas_bwd_matches_blocked_jax_oracle():
    """The pallas dQ/dK/dV kernels against the plain blocked-jax backward
    (`_blocked_bwd`) — same math, independent implementations."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from fedml_tpu.ops import flash_attention as fa

    k = jax.random.key(5)
    bh, t, d = 2, 64, 32
    q, kk, v = (jax.random.normal(jax.random.fold_in(k, i), (bh, t, d),
                                  jnp.float32) for i in range(3))
    do = jax.random.normal(jax.random.fold_in(k, 9), (bh, t, d), jnp.float32)
    o, lse_q = fa._flash_fwd(q, kk, v, 16, 16, True)
    got = fa._pallas_bwd(q, kk, v, o, lse_q, do, 16, 16, True)
    want = fa._blocked_bwd(q, kk, v, o, do, 16)
    for name, a, b in zip("qkv", got, want):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-4,
                                   err_msg=f"d{name}")


def test_tiny_sequence_auto_blocks():
    """T smaller than 8 must still run (auto blocks floor at 1, not 8)."""
    import jax
    import jax.numpy as jnp

    from fedml_tpu.ops.flash_attention import flash_attention

    q = jax.random.normal(jax.random.key(0), (1, 4, 8), jnp.float32)
    o = flash_attention(q, q, q, interpret=True)
    assert o.shape == q.shape
