"""Round-3 closers: torch engine adapter (engines.py), content-addressed
web3-style broker (comm/broker.py), off-box log shipping (utils/sinks.py).
"""
import uuid

import numpy as np
import pytest

from fedml_tpu.comm.broker import (
    ContentAddressedBroker, get_cas_broker, release_broker,
)


def _mk_data(seed, n=64, d=8, k=3):
    # one SHARED ground-truth task; per-seed silos draw different samples
    w = np.random.RandomState(42).randn(d, k)
    rs = np.random.RandomState(seed)
    x = rs.randn(n, d).astype(np.float32)
    y = np.argmax(x @ w, axis=1).astype(np.int64)
    return x, y


# ------------------------------------------------------ torch engine adapter
def _torch_model(d=8, k=3):
    import torch.nn as nn

    return nn.Sequential(nn.Linear(d, 16), nn.ReLU(), nn.Linear(16, k))


def test_torch_trainer_contract_and_learning():
    from fedml_tpu.engines import TorchSiloTrainer

    x, y = _mk_data(0)
    tr = TorchSiloTrainer(_torch_model(), x, y, lr=0.3, batch_size=16,
                          epochs=2, seed=1)
    params = tr.get_params()
    assert all(isinstance(v, np.ndarray) for v in params.values())
    losses = []
    for r in range(6):
        params, n, m = tr.train(params, r)
        losses.append(m["train_loss"])
    assert n == 64
    assert losses[-1] < losses[0] * 0.5, losses
    assert tr.evaluate(x, y)["test_acc"] > 0.9


def test_torch_silos_federate_through_jax_server():
    """Pure-torch silos federating through THIS framework's cross-silo
    server over the message layer — the multi-engine capability the
    reference's ml_engine_adapter provides (round-2 verdict gap)."""
    from fedml_tpu.comm import FedCommManager
    from fedml_tpu.comm.loopback import LoopbackTransport, release_router
    from fedml_tpu.cross_silo import FedServerManager
    from fedml_tpu.cross_silo.client import FedClientManager
    from fedml_tpu.engines import TorchSiloTrainer

    import torch

    torch.manual_seed(0)
    n_clients, rounds = 3, 4
    run_id = f"torch-fed-{uuid.uuid4().hex[:6]}"
    init = TorchSiloTrainer(_torch_model(), *_mk_data(99)).get_params()
    client_ids = list(range(1, n_clients + 1))
    server = FedServerManager(
        FedCommManager(LoopbackTransport(0, run_id), 0),
        client_ids=client_ids, init_params=init, num_rounds=rounds)
    clients = []
    for i, cid in enumerate(client_ids):
        tr = TorchSiloTrainer(_torch_model(), *_mk_data(i), lr=0.3,
                              batch_size=16, epochs=1, seed=10 + i)
        clients.append(FedClientManager(
            FedCommManager(LoopbackTransport(cid, run_id), cid), cid, tr))
    server.run(background=True)
    for c in clients:
        c.run(background=True)
        c.announce_ready()
    assert server.done.wait(timeout=120), "torch federation hung"
    release_router(run_id)
    # the federated global model beats the initial one on every silo's data
    final = TorchSiloTrainer(_torch_model(), *_mk_data(0))
    final.set_params(server.params)
    accs = [final.evaluate(*_mk_data(i))["test_acc"] for i in range(3)]
    assert min(accs) > 0.75, accs


# ------------------------------------------------- content-addressed broker
def test_cas_broker_dedup_and_integrity():
    b = ContentAddressedBroker()
    k1 = b.put_blob(b"model-bytes")
    k2 = b.put_blob(b"model-bytes")      # broadcast: same content
    assert k1 == k2                       # content-addressed
    assert len(b._blobs) == 1             # stored once (dedup)
    assert b.get_blob(k1) == b"model-bytes"   # first reader
    assert b.get_blob(k1) == b"model-bytes"   # second reader; now freed
    assert k1 not in b._blobs
    # tamper detection
    k3 = b.put_blob(b"payload")
    b._blobs[k3] = b"tampered"
    with pytest.raises(ValueError, match="hash verification"):
        b.get_blob(k3)


def test_broadcast_dedup_through_transport():
    """The claim that matters: broadcasting ONE payload to n receivers via
    the web3 backend stores ONE blob (frames are receiver-canonical; the
    envelope rides the topic message)."""
    import threading

    from fedml_tpu.comm import FedCommManager, Message
    from fedml_tpu.comm.manager import create_transport

    run = f"web3b-{uuid.uuid4().hex[:6]}"
    n = 3
    evs = [threading.Event() for _ in range(n)]
    got = [None] * n
    server = FedCommManager(create_transport("mqtt_web3", 0, run), 0)
    clients = []
    for i in range(1, n + 1):
        c = FedCommManager(create_transport("mqtt_web3", i, run), i)
        def make(idx):
            def h(msg):
                got[idx] = (msg.receiver_id, np.asarray(msg.get("w")))
                evs[idx].set()
            return h
        c.register_message_receive_handler("sync", make(i - 1))
        clients.append(c)
    server.run(background=True)
    payload = np.arange(30000, dtype=np.float32)
    cas = get_cas_broker(run)
    for i in range(1, n + 1):
        m = Message("sync", 0, i)
        m.add("w", payload)
        server.send_message(m)
    # one blob, refcounted n — BEFORE clients drain
    assert len(cas._blobs) == 1, len(cas._blobs)
    assert list(cas._refs.values()) == [n]
    for c in clients:
        c.run(background=True)
    for i, ev in enumerate(evs):
        assert ev.wait(timeout=10), f"client {i+1} never got the broadcast"
    for i in range(n):
        assert got[i][0] == i + 1   # envelope receiver restored per client
        np.testing.assert_array_equal(got[i][1], payload)
    assert len(cas._blobs) == 0     # all readers drained -> blob freed
    server.stop()
    for c in clients:
        c.stop()
    release_broker(run)


def test_web3_backend_transport_roundtrip():
    import threading

    from fedml_tpu.comm import FedCommManager, Message
    from fedml_tpu.comm.manager import create_transport

    run = f"web3-{uuid.uuid4().hex[:6]}"
    got = []
    ev = threading.Event()
    a = FedCommManager(create_transport("mqtt_web3", 0, run), 0)
    b = FedCommManager(create_transport("mqtt_web3", 1, run), 1)
    b.register_message_receive_handler(
        "m", lambda msg: (got.append(msg.get("w")), ev.set()))
    a.run(background=True)
    b.run(background=True)
    m = Message("m", 0, 1)
    m.add("w", np.arange(20000, dtype=np.float32))  # above blob threshold
    a.send_message(m)
    assert ev.wait(timeout=10)
    np.testing.assert_array_equal(got[0], np.arange(20000, dtype=np.float32))
    a.stop(); b.stop()
    cas = get_cas_broker(run)
    assert isinstance(cas, ContentAddressedBroker)
    release_broker(run)


# ------------------------------------------------------- log shipping leg
def test_broker_log_sink_ships_and_collects(tmp_path):
    from fedml_tpu.utils.sinks import BrokerLogSink, collect_logs

    bid = f"logs-{uuid.uuid4().hex[:6]}"
    sink = BrokerLogSink("runA", broker_id=bid, source="silo-3",
                         batch_size=3)
    for i in range(7):
        sink("metrics", {"round": i, "loss": 1.0 / (i + 1)})
    sink.flush()
    rows = collect_logs("runA", broker_id=bid, out_dir=str(tmp_path))
    assert len(rows) == 7
    assert rows[0]["source"] == "silo-3" and rows[6]["round"] == 6
    # file landed for the collector's archive
    assert (tmp_path / "runA.collected.jsonl").read_text().count("\n") == 7
    # drained: a second collect sees nothing
    assert collect_logs("runA", broker_id=bid) == []
    release_broker(bid)


def test_log_upload_via_config(tmp_path):
    import fedml_tpu
    from fedml_tpu.utils.sinks import collect_logs
    from fedml_tpu.utils.events import recorder

    bid = f"logs-{uuid.uuid4().hex[:6]}"
    cfg = fedml_tpu.init(config={
        "data_args": {"dataset": "synthetic",
                      "extra": {"synthetic_samples_per_client": 16}},
        "model_args": {"model": "lr"},
        "train_args": {"federated_optimizer": "FedAvg",
                       "client_num_in_total": 2, "client_num_per_round": 2,
                       "comm_round": 2, "epochs": 1, "batch_size": 8,
                       "learning_rate": 0.3},
        "validation_args": {"frequency_of_the_test": 0},
        "tracking_args": {"enable_tracking": True,
                          "log_file_dir": str(tmp_path),
                          "run_name": "shipit",
                          "extra": {"log_upload_broker": bid,
                                    "log_source": "host-1"}},
    })
    try:
        # the framework flushes buffered sinks at end-of-run — no user code
        fedml_tpu.run_simulation(cfg)
        rows = collect_logs("shipit", broker_id=bid)
        assert rows and all(r["source"] == "host-1" for r in rows)
    finally:
        recorder.sinks.clear()
        release_broker(bid)


# --------------------------------------------------------- tf engine adapter
def _tf_model(d=8, k=3):
    import tensorflow as tf

    return tf.keras.Sequential([
        tf.keras.layers.Dense(16, activation="relu", input_shape=(d,)),
        tf.keras.layers.Dense(k),
    ])


def _has_tf() -> bool:
    try:
        import tensorflow  # noqa: F401

        return True
    except Exception:
        return False


@pytest.mark.skipif(not _has_tf(), reason="tensorflow not installed")
@pytest.mark.slow
def test_tf_trainer_contract_and_learning():
    from fedml_tpu.engines import TFSiloTrainer

    x, y = _mk_data(0)
    tr = TFSiloTrainer(_tf_model(), x, y, lr=0.3, batch_size=16, epochs=3)
    p0 = tr.get_params()
    p1, n, m = tr.train(None, 0)
    assert n == 64 and m["train_loss"] > 0
    assert set(p1) == set(p0)
    # roundtrip: set_params restores exactly
    tr.set_params(p0)
    for a, b in zip(tr.get_params().values(), p0.values()):
        np.testing.assert_array_equal(a, b)
    # a few more rounds learn the task
    p = p1
    for r in range(1, 5):
        p, _, m = tr.train(p, r)
    tr.set_params(p)
    assert tr.evaluate(x, y)["test_acc"] > 0.8


@pytest.mark.skipif(not _has_tf(), reason="tensorflow not installed")
@pytest.mark.slow
def test_tf_silos_federate_through_jax_server():
    """Pure-TF silos federating through the cross-silo server over the
    message layer — same shape as the torch test; the server only ever
    tree-averages {name: ndarray} pytrees (reference:
    ml/engine/ml_engine_adapter.py:198 multi-engine dispatch)."""
    from fedml_tpu.comm import FedCommManager
    from fedml_tpu.comm.loopback import LoopbackTransport, release_router
    from fedml_tpu.cross_silo import FedServerManager
    from fedml_tpu.cross_silo.client import FedClientManager
    from fedml_tpu.engines import TFSiloTrainer

    n_clients, rounds = 3, 4
    run_id = f"tf-fed-{uuid.uuid4().hex[:6]}"
    init = TFSiloTrainer(_tf_model(), *_mk_data(99)).get_params()
    client_ids = list(range(1, n_clients + 1))
    server = FedServerManager(
        FedCommManager(LoopbackTransport(0, run_id), 0),
        client_ids=client_ids, init_params=init, num_rounds=rounds)
    clients = []
    for i, cid in enumerate(client_ids):
        tr = TFSiloTrainer(_tf_model(), *_mk_data(i), lr=0.3,
                           batch_size=16, epochs=1, seed=10 + i)
        clients.append(FedClientManager(
            FedCommManager(LoopbackTransport(cid, run_id), cid), cid, tr))
    server.run(background=True)
    for c in clients:
        c.run(background=True)
        c.announce_ready()
    assert server.done.wait(timeout=120), "tf federation hung"
    release_router(run_id)
    final = TFSiloTrainer(_tf_model(), *_mk_data(0))
    final.set_params(server.params)
    accs = [final.evaluate(*_mk_data(i))["test_acc"] for i in range(3)]
    assert min(accs) > 0.75, accs


@pytest.mark.skipif(not _has_tf(), reason="tensorflow not installed")
def test_tf_set_params_survives_sorted_dict_rebuild_10plus_vars():
    """Aggregators rebuild param dicts in sorted key order (jax.tree.map
    flattens dicts lexicographically); set_params must assign by KEY, so a
    model with >=10 variables round-trips through a sorted rebuild
    unchanged, and shape mismatches fail loudly instead of reshaping."""
    import tensorflow as tf

    from fedml_tpu.engines import TFSiloTrainer

    layers = [tf.keras.layers.Dense(6, activation="relu")
              for _ in range(5)] + [tf.keras.layers.BatchNormalization(),
                                    tf.keras.layers.Dense(3)]
    model = tf.keras.Sequential(layers)   # >=16 vars incl. BN moving stats
    x, y = _mk_data(0)
    tr = TFSiloTrainer(model, x, y)
    p = tr.get_params()
    assert len(p) >= 10
    sorted_rebuild = {k: p[k] for k in sorted(p)}   # what aggregation does
    tr.set_params(sorted_rebuild)
    for k, v in tr.get_params().items():
        np.testing.assert_array_equal(v, p[k])
    # loud failure on a transposed kernel
    bad = dict(p)
    k0 = next(k for k in bad if bad[k].ndim == 2 and
              bad[k].shape[0] != bad[k].shape[1])
    bad[k0] = bad[k0].T.copy()
    with pytest.raises(ValueError, match="shape mismatch"):
        tr.set_params(bad)
    # BN moving statistics ride the wire format (torch state_dict parity):
    # train moves them, and set_params restores the moved values exactly
    tr.set_params(p)
    p_trained, _, _ = tr.train(None, 0)
    bn_moved = any(
        not np.array_equal(a, b) and "v" in k
        for (k, a), b in zip(p_trained.items(), p.values())
        if a.ndim == 1)
    assert bn_moved
    tr2 = TFSiloTrainer(tf.keras.models.clone_model(model), x, y)
    tr2.set_params(p_trained)
    for a, b in zip(tr2.get_params().values(), p_trained.values()):
        np.testing.assert_array_equal(a, b)


# --------------------------------------------- architecture fingerprinting
def test_torch_arch_fingerprint_refuses_same_shape_different_model():
    """Round-4 verdict weak #6: two DIFFERENT architectures with matching
    variable counts and shapes must refuse to federate — the structural
    names in the wire format catch what shape checks cannot."""
    import torch
    import torch.nn as nn

    from fedml_tpu.engines import TorchSiloTrainer

    x, y = _mk_data(0)
    a = TorchSiloTrainer(_torch_model(), x, y)

    class Other(nn.Module):
        def __init__(self):
            super().__init__()
            self.fc1 = nn.Linear(8, 16)
            self.fc2 = nn.Linear(16, 3)

        def forward(self, z):
            return self.fc2(torch.relu(self.fc1(z)))

    b = TorchSiloTrainer(Other(), x, y)
    pa, pb = a.get_params(), b.get_params()
    # the silent-collision precondition: same leaf count, same shapes
    assert len(pa) == len(pb)
    assert sorted(v.shape for v in pa.values()) == \
        sorted(v.shape for v in pb.values())
    assert a.arch_fp != b.arch_fp
    with pytest.raises(ValueError, match="architecture mismatch"):
        b.set_params(pa)
    # the error names both architectures: the silo's own fingerprint and
    # layer names, and the incoming layer names
    try:
        b.set_params(pa)
    except ValueError as e:
        assert b.arch_fp in str(e)
        assert "fc1" in str(e) and "0.weight" in str(e)
    # same architecture still round-trips
    TorchSiloTrainer(_torch_model(), x, y).set_params(pa)


@pytest.mark.skipif(not _has_tf(), reason="tensorflow not installed")
def test_tf_arch_fingerprint_refuses_same_shape_different_model():
    """Same property for the TF adapter, whose index-prefixed keys were the
    easiest place to hit the collision: the normalized structural name now
    rides every wire key, set_params rejects a mismatch loudly, and
    process-global keras name uniquifiers do NOT break same-architecture
    federation."""
    import tensorflow as tf

    from fedml_tpu.engines import TFSiloTrainer

    class RenamedDense(tf.keras.layers.Dense):
        pass

    x, y = _mk_data(0)
    a = TFSiloTrainer(_tf_model(), x, y)
    b_model = tf.keras.Sequential([
        RenamedDense(16, activation="relu", input_shape=(8,)),
        tf.keras.layers.Dense(3),
    ])
    b = TFSiloTrainer(b_model, x, y)
    pa, pb = a.get_params(), b.get_params()
    assert len(pa) == len(pb)
    assert sorted(v.shape for v in pa.values()) == \
        sorted(v.shape for v in pb.values())
    assert a.arch_fp != b.arch_fp
    with pytest.raises(ValueError, match="architecture mismatch"):
        b.set_params(pa)
    # a SECOND same-architecture model in the same process gets uniquified
    # raw names ("dense_5/kernel") — normalization keeps the wire keys and
    # fingerprint identical, so real federation is unaffected
    a2 = TFSiloTrainer(_tf_model(), x, y)
    assert a2.arch_fp == a.arch_fp
    assert set(a2.get_params()) == set(pa)
    a2.set_params(pa)
    for got, want in zip(a2.get_params().values(), pa.values()):
        np.testing.assert_array_equal(got, want)


@pytest.mark.skipif(not _has_tf(), reason="tensorflow not installed")
def test_tf_legacy_index_only_keys_still_load():
    """Pre-r5 checkpoints/artifacts used index-only wire keys (v000...);
    they must keep loading (with a warning, shapes still checked) instead
    of failing as a bogus 'architecture mismatch'."""
    from fedml_tpu.engines import TFSiloTrainer

    x, y = _mk_data(0)
    tr = TFSiloTrainer(_tf_model(), x, y)
    p = tr.get_params()
    legacy = {f"v{i:03d}": v for i, (_k, v) in enumerate(
        sorted(p.items()))}
    tr.set_params(legacy)
    for got, want in zip(tr.get_params().values(),
                         [v for _k, v in sorted(p.items())]):
        np.testing.assert_array_equal(got, want)
    # legacy keys with a wrong shape still fail loudly
    bad = dict(legacy)
    k0 = next(k for k in bad if bad[k].ndim == 2)
    bad[k0] = bad[k0].T.copy()
    with pytest.raises(ValueError, match="shape mismatch"):
        tr.set_params(bad)


def test_normalize_var_paths_sibling_aware():
    """ADVICE.md last open item: keras uniquifier suffixes strip, but
    DELIBERATELY numbered sibling layers keep distinct (canonically
    renumbered) names — and two processes whose uniquifier counters differ
    still agree on every name."""
    from fedml_tpu.engines import _normalize_var_paths

    # deliberate siblings in one model: distinct names survive
    first = _normalize_var_paths(
        ["dense/kernel", "dense/bias", "dense_1/kernel", "dense_1/bias"])
    assert first == ["dense/kernel", "dense/bias",
                     "dense_1/kernel", "dense_1/bias"]
    # same model built later in a process that uniquified the names:
    # canonical renumbering makes the two silos agree exactly
    later = _normalize_var_paths(
        ["dense_7/kernel", "dense_7/bias", "dense_8/kernel", "dense_8/bias"])
    assert later == first
    # a lone uniquifier (no same-base sibling) still strips, nested too
    assert _normalize_var_paths(["sequential_1/dense_2/kernel:0"]) == \
        ["sequential/dense/kernel"]
    # sibling sets at different tree positions renumber independently
    assert _normalize_var_paths(
        ["a_3/dense_5/kernel", "a_3/dense_6/kernel", "b/dense_9/kernel"]) == \
        ["a/dense/kernel", "a/dense_1/kernel", "b/dense/kernel"]
