"""Real-data loading + mixed-precision training.

Covers VERDICT r1 item 2: the framework must show convergence on real data
(sklearn digits is the real dataset available offline) and provide a bf16
compute path with f32 master weights.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import fedml_tpu
from fedml_tpu.data import loader as data_loader
from fedml_tpu.models.hub import mixed_precision_apply
from fedml_tpu.simulation.simulator import Simulator


def _cfg(**train_overrides):
    train = {
        "federated_optimizer": "FedAvg",
        "client_num_in_total": 8, "client_num_per_round": 8,
        "comm_round": 10, "epochs": 2, "batch_size": 32,
        "learning_rate": 0.1,
    }
    train.update(train_overrides)
    return fedml_tpu.init(config={
        "data_args": {"dataset": "digits", "partition_method": "hetero",
                      "partition_alpha": 0.5},
        "model_args": {"model": "mlp"},
        "train_args": train,
        "validation_args": {"frequency_of_the_test": 0},
        "comm_args": {"backend": "sp"},
    })


def test_digits_is_real_data():
    ds = data_loader.load(_cfg())
    assert not ds.synthetic
    assert ds.num_classes == 10
    assert ds.x_train.shape[2:] == (8, 8, 1)
    # real digits: pixel intensities in [0,1], many distinct values
    assert 0.0 <= ds.x_train.min() and ds.x_train.max() <= 1.0
    assert len(np.unique(ds.y_test)) == 10


def test_synthetic_fallback_is_flagged():
    cfg = _cfg()
    cfg.data_args.dataset = "cifar100"  # no npz in the test environment
    ds = data_loader.load(cfg)
    assert ds.synthetic


def test_fedavg_converges_on_real_digits():
    sim = Simulator(_cfg())
    sim.run(10)
    acc = sim.evaluate()["test_acc"]
    assert acc > 0.7, f"digits non-IID FedAvg reached only {acc}"


def test_bf16_params_stay_f32_and_converges():
    sim = Simulator(_cfg(compute_dtype="bfloat16"))
    # master weights remain f32 even though compute is bf16
    dtypes = {a.dtype for a in jax.tree.leaves(sim.server_state.params)}
    assert dtypes == {jnp.dtype(jnp.float32)}
    sim.run(10)
    assert {a.dtype for a in jax.tree.leaves(sim.server_state.params)} == {
        jnp.dtype(jnp.float32)
    }
    acc = sim.evaluate()["test_acc"]
    assert acc > 0.7, f"bf16 digits FedAvg reached only {acc}"


def test_mixed_precision_apply_casts_compute():
    """The wrapper runs the network in bf16 but returns f32 logits, and
    gradients w.r.t. f32 params come back f32."""
    from fedml_tpu.models import hub

    model = hub.create("mlp", 10)
    params = hub.init_params(model, (8, 8, 1), jax.random.key(0))
    wrapped = mixed_precision_apply(model.apply, "bfloat16")
    x = jnp.ones((4, 8, 8, 1), jnp.float32)
    out = wrapped({"params": params}, x)
    assert out.dtype == jnp.float32

    g = jax.grad(lambda p: wrapped({"params": p}, x).sum())(params)
    assert all(a.dtype == jnp.float32 for a in jax.tree.leaves(g))
    # identity when dtype is f32
    f = model.apply
    assert mixed_precision_apply(f, "float32") is f
