"""Systematic comm-FSM interleaving tests (SURVEY §5.2 race detection;
round-2 verdict listed this as the remaining race-coverage gap).

JitterLoopbackTransport injects seeded per-send delays, varying message
ARRIVAL ORDER across participants (per-sender FIFO preserved — what real
transports guarantee) while the protocol math stays deterministic. Each
protocol must therefore produce BIT-EQUAL results under every seed; any
divergence is an interleaving bug (e.g. a handler mutating state it
shouldn't before a guard)."""
import uuid

import jax
import numpy as np
import pytest

from fedml_tpu.comm import FedCommManager
from fedml_tpu.comm.loopback import (
    JitterLoopbackTransport, LoopbackTransport, release_router,
)
from fedml_tpu.config import TrainArgs
from fedml_tpu.cross_silo import FedClientManager, FedServerManager
from fedml_tpu.cross_silo.secagg_manager import (
    SecAggClientManager, SecAggServerManager,
)
from fedml_tpu.cross_silo.trainer import SiloTrainer
from fedml_tpu.models import hub


def _mk_data(seed, n=48, d=8, k=3):
    rs = np.random.RandomState(seed)
    w = rs.randn(d, k)
    x = rs.randn(n, d).astype(np.float32)
    y = np.argmax(x @ w, axis=1).astype(np.int32)
    return x, y


def _transport(rank, run_id, seed):
    if seed is None:
        return LoopbackTransport(rank, run_id)
    return JitterLoopbackTransport(rank, run_id, seed=seed, max_delay=0.008)


def _run_secagg_jittered(seed, n_clients=4, rounds=2):
    model = hub.create("lr", 3)
    t = TrainArgs(epochs=1, batch_size=16, learning_rate=0.2)
    params_np = jax.tree.map(
        np.asarray, hub.init_params(model, (8,), jax.random.key(0)))
    client_ids = list(range(1, n_clients + 1))
    run_id = f"race-sa-{uuid.uuid4().hex[:6]}"
    server = SecAggServerManager(
        FedCommManager(_transport(0, run_id, seed), 0),
        client_ids=client_ids, init_params=params_np, num_rounds=rounds)
    clients = []
    for i, cid in enumerate(client_ids):
        tr = SiloTrainer(model.apply, t, *_mk_data(i), seed=100 + i)
        tr.train(params_np, 0)  # warm jit outside the protocol
        clients.append(SecAggClientManager(
            FedCommManager(_transport(cid, run_id, seed), cid), cid, tr,
            num_clients=n_clients, client_ids=client_ids))
    server.run(background=True)
    for c in clients:
        c.run(background=True)
        c.announce_ready()
    assert server.done.wait(timeout=180), f"seed={seed}: server hung"
    assert server.error is None, server.error
    release_router(run_id)
    return server.params


def _run_cross_silo_jittered(seed, n_clients=3, rounds=3):
    model = hub.create("lr", 3)
    t = TrainArgs(epochs=1, batch_size=16, learning_rate=0.2)
    params_np = jax.tree.map(
        np.asarray, hub.init_params(model, (8,), jax.random.key(0)))
    client_ids = list(range(1, n_clients + 1))
    run_id = f"race-cs-{uuid.uuid4().hex[:6]}"
    server = FedServerManager(
        FedCommManager(_transport(0, run_id, seed), 0),
        client_ids=client_ids, init_params=params_np, num_rounds=rounds)
    clients = []
    for i, cid in enumerate(client_ids):
        tr = SiloTrainer(model.apply, t, *_mk_data(i), seed=100 + i)
        tr.train(params_np, 0)
        clients.append(FedClientManager(
            FedCommManager(_transport(cid, run_id, seed), cid), cid, tr))
    server.run(background=True)
    for c in clients:
        c.run(background=True)
        c.announce_ready()
    assert server.done.wait(timeout=180), f"seed={seed}: server hung"
    release_router(run_id)
    return server.params


@pytest.mark.slow
def test_secagg_fsm_timing_independent():
    """pk exchange, encrypted share routing, masked upload, every-round
    collected unmask — all under shuffled arrival orders: results must be
    bit-equal to the jitter-free run for every seed."""
    baseline = _run_secagg_jittered(None)
    for seed in range(4):
        got = _run_secagg_jittered(seed)
        jax.tree.map(
            lambda a, b: np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b)), baseline, got)


@pytest.mark.slow
def test_cross_silo_fsm_timing_independent():
    baseline = _run_cross_silo_jittered(None)
    for seed in range(4):
        got = _run_cross_silo_jittered(seed)
        jax.tree.map(
            lambda a, b: np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b)), baseline, got)
