"""Serving (reference: python/fedml/serving/): jit-bucketed predictor,
HTTP /predict + /ready contract, LM greedy decoding, checkpoint serving."""
import json
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import fedml_tpu
from fedml_tpu.llm import TransformerLM
from fedml_tpu.models import hub
from fedml_tpu.serving import (
    FedMLInferenceRunner, GreedyLMPredictor, JaxPredictor,
    predictor_from_checkpoint, serve_simulator,
)
from fedml_tpu.simulation.simulator import Simulator


def _lr_setup():
    model = hub.create("lr", 3)
    params = hub.init_params(model, (8,), jax.random.key(0))
    return model, params


def test_jax_predictor_bucketing():
    model, params = _lr_setup()
    pred = JaxPredictor(model.apply, params)
    x = np.random.RandomState(0).randn(5, 8).astype(np.float32)
    out = pred.predict({"inputs": x.tolist()})
    assert len(out["predictions"]) == 5
    assert len(out["probabilities"]) == 5
    # padded bucket must not change real rows: compare to direct apply
    direct = np.argmax(np.asarray(
        model.apply({"params": params}, jnp.asarray(x))), -1)
    assert out["predictions"] == direct.tolist()


def _post(url, payload):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=30) as r:
        return json.loads(r.read())


def test_http_predict_and_ready_roundtrip():
    model, params = _lr_setup()
    runner = FedMLInferenceRunner(
        JaxPredictor(model.apply, params), port=0).start()
    try:
        base = f"http://127.0.0.1:{runner.port}"
        with urllib.request.urlopen(base + "/ready", timeout=10) as r:
            assert json.loads(r.read())["status"] == "Success"
        x = np.random.RandomState(1).randn(3, 8).astype(np.float32)
        out = _post(base + "/predict", {"inputs": x.tolist()})
        assert len(out["predictions"]) == 3
        # malformed input -> 400 with error payload, server stays alive
        try:
            _post(base + "/predict", {"wrong_key": 1})
            assert False, "expected HTTPError"
        except urllib.error.HTTPError as e:
            assert e.code == 400
            assert "error" in json.loads(e.read())
        out2 = _post(base + "/predict", {"inputs": x.tolist()})
        assert out2["predictions"] == out["predictions"]
    finally:
        runner.stop()


def test_greedy_lm_predictor():
    model = TransformerLM(vocab_size=16, d_model=32, n_layers=1, n_heads=4,
                          d_ff=64)
    params = model.init(jax.random.key(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    pred = GreedyLMPredictor(model, params, max_len=32,
                             detokenize=lambda ts: ",".join(map(str, ts)))
    out = pred.predict({"tokens": [1, 2, 3], "max_new_tokens": 4})
    assert len(out["generated_tokens"]) == 4
    assert out["generated_text"].count(",") == 3
    # deterministic
    out2 = pred.predict({"tokens": [1, 2, 3], "max_new_tokens": 4})
    assert out2["generated_tokens"] == out["generated_tokens"]


@pytest.mark.slow
def test_serve_trained_simulator_and_checkpoint(tmp_path):
    cfg = fedml_tpu.init(config={
        "data_args": {"dataset": "synthetic",
                      "extra": {"synthetic_samples_per_client": 16}},
        "model_args": {"model": "lr"},
        "train_args": {"federated_optimizer": "FedAvg",
                       "client_num_in_total": 4, "client_num_per_round": 4,
                       "comm_round": 2, "epochs": 1, "batch_size": 8,
                       "learning_rate": 0.1},
        "validation_args": {"frequency_of_the_test": 0},
    })
    sim = Simulator(cfg)
    sim.run(checkpoint_dir=str(tmp_path), checkpoint_every=1)
    runner = serve_simulator(sim, port=0)
    try:
        x = np.asarray(sim.dataset.x_test[:4], np.float32)
        out = _post(f"http://127.0.0.1:{runner.port}/predict",
                    {"inputs": x.tolist()})
        assert len(out["predictions"]) == 4
    finally:
        runner.stop()
    # the checkpoint route serves the same model
    pred = predictor_from_checkpoint(
        str(tmp_path), sim.apply_fn, sim.server_state)
    out2 = pred.predict({"inputs": x.tolist()})
    assert out2["predictions"] == out["predictions"]


# ------------------------------------------- framework-neutral export (r5)
def test_export_roundtrip_and_neutral_layout(tmp_path):
    """serving/export.py — the ONNX-conversion analog (reference:
    device_model_deployment.py:720). Round-trip: export -> plain-numpy
    readability (no jax in the loop) -> load_export restores the tree
    including bfloat16 leaves -> predictor_from_export serves it."""
    import json as _json

    from fedml_tpu.serving.export import (
        export_model, load_export, predictor_from_export,
    )

    model = hub.create("mlp", 3)
    params = hub.init_params(model, (8,), jax.random.key(0))
    # exercise the non-portable-dtype path: one bf16 leaf
    params["Dense_0"]["kernel"] = params["Dense_0"]["kernel"].astype(
        jnp.bfloat16)
    d = str(tmp_path / "export")
    manifest = export_model(d, params, model_name="mlp", num_classes=3,
                            input_shape=(8,))

    # LAYOUT CONTRACT: manifest.json + tensors.npz readable with plain
    # numpy/json — names, shapes, dtypes all self-describing
    with open(f"{d}/manifest.json") as f:
        m2 = _json.load(f)
    assert m2["format"] == "fedml-tpu-export/1"
    assert m2 == _json.loads(_json.dumps(manifest))
    with np.load(f"{d}/tensors.npz") as z:
        assert set(z.files) == set(m2["tensors"])
        for name, entry in m2["tensors"].items():
            arr = z[name]
            assert list(arr.shape) == entry["shape"]
            assert str(arr.dtype) == entry["dtype"]
            assert arr.flags["C_CONTIGUOUS"]
    # the bf16 leaf was stored widened and flagged
    e = m2["tensors"]["Dense_0/kernel"]
    assert e["dtype"] == "float32" and e["cast_from"] == "bfloat16"

    got, _ = load_export(d)
    assert got["Dense_0"]["kernel"].dtype == jnp.bfloat16
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(a, np.float32), np.asarray(b, np.float32)),
        params, got)

    x = np.random.RandomState(0).randn(4, 8).astype(np.float32)
    pred = predictor_from_export(d)
    ref = JaxPredictor(model.apply, params).predict({"inputs": x.tolist()})
    assert pred.predict({"inputs": x.tolist()})["predictions"] == \
        ref["predictions"]


def test_export_validation_fails_loudly(tmp_path):
    import json as _json

    from fedml_tpu.serving.export import (
        export_model, load_export, predictor_from_export,
    )

    model = hub.create("lr", 3)
    params = hub.init_params(model, (8,), jax.random.key(0))
    d = str(tmp_path / "exp")
    export_model(d, params)   # no model recipe: pure tensor interchange
    with pytest.raises(ValueError, match="no 'model' recipe"):
        predictor_from_export(d)
    # tampered manifest: drop a tensor entry
    with open(f"{d}/manifest.json") as f:
        m = _json.load(f)
    dropped = sorted(m["tensors"])[0]
    del m["tensors"][dropped]
    with open(f"{d}/manifest.json", "w") as f:
        _json.dump(m, f)
    with pytest.raises(ValueError, match="tensor set mismatch"):
        load_export(d)
    # wrong format tag
    m["format"] = "something-else/9"
    with open(f"{d}/manifest.json", "w") as f:
        _json.dump(m, f)
    with pytest.raises(ValueError, match="not a fedml-tpu-export"):
        load_export(d)


def test_start_replica_from_export(tmp_path):
    """Deploy-path wiring: a serve spec pointing at an export_dir brings up
    a live replica whose /predict serves the exported model — no other
    model keys in the spec (the manifest carries the recipe)."""
    from fedml_tpu.serving.export import export_model
    from fedml_tpu.serving.scheduler import start_replica

    model = hub.create("lr", 3)
    params = hub.init_params(model, (8,), jax.random.key(0))
    d = str(tmp_path / "exp")
    export_model(d, params, model_name="lr", num_classes=3, input_shape=(8,))
    rid, runner = start_replica({"export_dir": d, "port": 0})
    try:
        base = f"http://127.0.0.1:{runner.port}"
        x = np.random.RandomState(1).randn(3, 8).astype(np.float32)
        out = _post(base + "/predict", {"inputs": x.tolist()})
        ref = JaxPredictor(model.apply, params).predict(
            {"inputs": x.tolist()})
        assert out["predictions"] == ref["predictions"]
    finally:
        runner.stop()
