"""Compression transforms (reference test model: python/tests/security/* use
synthetic weight pytrees; same here)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fedml_tpu import compression as C


def _tree(rng=0):
    k = jax.random.key(rng)
    return {
        "w": jax.random.normal(jax.random.fold_in(k, 0), (32, 16)),
        "b": jax.random.normal(jax.random.fold_in(k, 1), (16,)),
    }


def test_topk_sparsity_and_values():
    t = _tree()
    out = C.topk_compress(t, ratio=0.1)
    for name, x in t.items():
        o = out[name]
        k = max(1, int(x.size * 0.1))
        assert int((o != 0).sum()) <= k
        # kept entries are exact copies
        nz = np.nonzero(np.asarray(o).ravel())
        assert np.allclose(np.asarray(o).ravel()[nz], np.asarray(x).ravel()[nz])


def test_eftopk_error_feedback_accumulates():
    t = _tree()
    res = jax.tree.map(jnp.zeros_like, t)
    sparse, res2 = C.eftopk_compress(t, res, ratio=0.1)
    # residual + sparse == original (lossless decomposition)
    for k in t:
        assert np.allclose(np.asarray(sparse[k] + res2[k]), np.asarray(t[k]), atol=1e-6)
    # second round: residual is carried in
    sparse3, _ = C.eftopk_compress(t, res2, ratio=0.1)
    assert not np.allclose(np.asarray(sparse3["w"]), np.asarray(sparse["w"]))


def test_randk_unbiased():
    t = {"w": jnp.ones((1000,))}
    outs = [C.randk_compress(t, 0.25, jax.random.key(i))["w"] for i in range(30)]
    mean = np.mean([np.asarray(o) for o in outs], axis=0)
    assert abs(mean.mean() - 1.0) < 0.15  # unbiased estimator


def test_quantize_bounded_error():
    t = _tree()
    out = C.quantize_compress(t, bits=8)
    for k in t:
        scale = float(jnp.max(jnp.abs(t[k])))
        assert np.max(np.abs(np.asarray(out[k] - t[k]))) <= scale / 2**7 + 1e-6


def test_qsgd_unbiased():
    t = {"w": jnp.full((500,), 0.5)}
    outs = [C.qsgd_compress(t, 4, jax.random.key(i))["w"] for i in range(50)]
    mean = np.mean([np.asarray(o) for o in outs], axis=0)
    assert abs(mean.mean() - 0.5) < 0.05


def test_wire_roundtrip():
    v = np.random.RandomState(0).randn(256).astype(np.float32)
    enc = C.encode_sparse(v, 0.1)
    dec = C.decode_sparse(enc)
    assert dec.shape == v.shape
    nz = np.nonzero(dec)
    assert np.allclose(dec[nz], v[nz])
    assert len(nz[0]) == max(1, int(256 * 0.1))


# The wire codec plane (comm/codec.py) rides encode_sparse/decode_sparse
# for every compressed training frame (ISSUE 14), which makes these edge
# cases load-bearing rather than theoretical.
def test_encode_sparse_keep_all_ratio_one():
    v = np.random.RandomState(1).randn(50).astype(np.float32)
    enc = C.encode_sparse(v, 1.0)
    assert enc["idx"].size == 50
    np.testing.assert_array_equal(C.decode_sparse(enc), v)


def test_encode_sparse_zero_size_leaf():
    enc = C.encode_sparse(np.zeros(0, np.float32), 0.5)
    assert enc["n"] == 0 and enc["idx"].size == 0
    assert C.decode_sparse(enc).size == 0


def test_encode_sparse_refuses_non_finite():
    v = np.asarray([1.0, np.nan, 2.0], np.float32)
    with pytest.raises(ValueError, match="non-finite"):
        C.encode_sparse(v, 0.5)
    with pytest.raises(ValueError, match="non-finite"):
        C.encode_sparse(np.asarray([np.inf, 1.0]), 0.5)


def test_decode_sparse_validates_frames():
    enc = C.encode_sparse(np.arange(8, dtype=np.float32), 0.5)
    bad = {**enc, "idx": np.asarray(enc["idx"], np.int64) + 100}
    with pytest.raises(ValueError, match="out of range"):
        C.decode_sparse(bad)
    with pytest.raises(ValueError, match="malformed"):
        C.decode_sparse({**enc, "val": np.zeros(enc["val"].size + 1,
                                                np.float32)})


def test_sparse_tree_int_bool_leaves_ride_dense():
    tree = {
        "w": np.random.RandomState(2).randn(6, 4).astype(np.float32),
        "steps": np.arange(5, dtype=np.int32),
        "flags": np.asarray([True, False, True]),
    }
    enc = C.encode_sparse_tree(tree, 0.25)
    dec = C.decode_sparse_tree(enc, tree)
    # discrete state survives exactly — magnitude top-k never touched it
    np.testing.assert_array_equal(dec["steps"], tree["steps"])
    np.testing.assert_array_equal(np.asarray(dec["flags"], bool),
                                  tree["flags"])
    # float leaf sparsified with exact kept values
    nz = np.nonzero(dec["w"])
    np.testing.assert_allclose(dec["w"][nz], tree["w"][nz])


def test_registry_dispatch():
    assert C.make_compression_transform("none") is None
    f = C.make_compression_transform("topk", ratio=0.5)
    t = _tree()
    out = f(t, jax.random.key(0))
    assert out["w"].shape == t["w"].shape
    with pytest.raises(ValueError):
        C.make_compression_transform("bogus")


def test_eftopk_wrapped_algorithm_runs_and_learns():
    """eftopk rides the engine's client-state mechanism (residuals scattered
    back each round) — config 'compression: eftopk' must now work end-to-end."""
    import fedml_tpu
    cfg = fedml_tpu.init(config={
        "data_args": {"dataset": "synthetic"},
        "model_args": {"model": "lr"},
        "train_args": {
            "federated_optimizer": "FedAvg", "client_num_in_total": 8,
            "client_num_per_round": 8, "comm_round": 12, "epochs": 1,
            "batch_size": 16, "learning_rate": 0.1,
            "compression": "eftopk", "compression_ratio": 0.25,
        },
        "comm_args": {"backend": "sp"},
    })
    hist = fedml_tpu.run_simulation(cfg)
    assert hist[-1]["test_acc"] > 0.6, hist[-1]

    from fedml_tpu.algorithms import build_algorithm
    from fedml_tpu.compression import wrap_algorithm_with_eftopk
    import pytest as _pt
    alg = build_algorithm("SCAFFOLD", lambda *a: None,
                          cfg.train_args, 8, 8)
    with _pt.raises(ValueError, match="structured"):
        wrap_algorithm_with_eftopk(alg, 0.25)
