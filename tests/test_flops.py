"""Analytical FLOP counter (utils/flops.py) — exactness on known shapes.

The round-2 verdict flagged MFU 1.089 (>1.0) from cost-analysis
extrapolation; these tests pin the replacement's semantics: exact matmul/conv
counts, scan trip-count multiplication, and fwd:bwd ratios in the expected
range, so the bench numerator is auditable arithmetic rather than a
measurement.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fedml_tpu.utils.flops import analytic_flops, tpu_spec_peak_tflops


def test_dense_matmul_exact():
    a = jnp.zeros((8, 32))
    b = jnp.zeros((32, 16))
    assert analytic_flops(lambda x, y: x @ y, a, b) == 2 * 8 * 32 * 16


def test_conv_exact():
    # NHWC 3x3 SAME conv: 2 * B*H*W*Cout * (3*3*Cin)
    x = jnp.zeros((2, 8, 8, 4))
    k = jnp.zeros((3, 3, 4, 16))
    f = lambda x, k: jax.lax.conv_general_dilated(
        x, k, (1, 1), "SAME", dimension_numbers=("NHWC", "HWIO", "NHWC"))
    assert analytic_flops(f, x, k) == 2 * 2 * 8 * 8 * 16 * 3 * 3 * 4


def test_grouped_conv_exact():
    # depthwise: feature_group_count = Cin -> one input channel per group
    x = jnp.zeros((2, 8, 8, 4))
    k = jnp.zeros((3, 3, 1, 4))
    f = lambda x, k: jax.lax.conv_general_dilated(
        x, k, (1, 1), "SAME", feature_group_count=4,
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
    assert analytic_flops(f, x, k) == 2 * 2 * 8 * 8 * 4 * 3 * 3 * 1


def test_scan_multiplies_by_length():
    w = jnp.zeros((16, 16))
    xs = jnp.zeros((5, 8, 16))

    def step(c, x):
        return c, x @ w

    f = lambda xs: jax.lax.scan(step, 0.0, xs)
    assert analytic_flops(f, xs) == 5 * (2 * 8 * 16 * 16)


def test_jit_and_grad_ratio():
    # grad-of-matmul-chain costs ~3x forward (dx and dw each cost one matmul
    # per layer); elementwise relu is excluded by design.
    w1, w2 = jnp.zeros((32, 64)), jnp.zeros((64, 8))
    x = jnp.zeros((16, 32))

    def loss(w1, w2):
        h = jax.nn.relu(x @ w1)
        return jnp.sum((h @ w2) ** 2)

    fwd = analytic_flops(loss, w1, w2)
    bwd = analytic_flops(jax.jit(jax.grad(loss, argnums=(0, 1))), w1, w2)
    assert fwd == 2 * 16 * 32 * 64 + 2 * 16 * 64 * 8
    assert 2.0 <= bwd / fwd <= 3.01


def test_remat_recompute_counted():
    w = jnp.zeros((32, 32))
    x = jnp.zeros((8, 32))

    def loss(w):
        h = jax.checkpoint(lambda w: jax.nn.relu(x @ w))(w)
        return jnp.sum(h ** 2)

    plain = analytic_flops(jax.grad(lambda w: jnp.sum(jax.nn.relu(x @ w) ** 2)), w)
    remat = analytic_flops(jax.grad(loss), w)
    assert remat >= plain  # recompute is executed work -> counted

def test_round_program_flops_positive_and_bounded():
    """The actual bench numerator: trace a full FedAvg round program and
    check the count sits within sane analytic bounds for the model."""
    import fedml_tpu
    from fedml_tpu.simulation.simulator import Simulator

    n_clients, shard, batch = 4, 8, 4
    cfg = fedml_tpu.init(config={
        "data_args": {"dataset": "cifar10", "extra": {
            "synthetic_samples_per_client": shard}},
        "model_args": {"model": "cnn"},
        "train_args": {
            "federated_optimizer": "FedAvg",
            "client_num_in_total": n_clients, "client_num_per_round": n_clients,
            "comm_round": 1, "epochs": 1, "batch_size": batch,
            "learning_rate": 0.1},
        "comm_args": {"backend": "sp"},
    })
    sim = Simulator(cfg)
    ids = jnp.arange(n_clients)
    w = jnp.ones((n_clients,), jnp.float32)
    rng = jax.random.key(0)
    flops = analytic_flops(
        sim.round_fn, sim.server_state, sim.client_states, sim.data,
        ids, w, rng, sim.hook_state)
    # forward matmul/conv flops for one batch of this CNN on 32x32x3 inputs
    conv1 = 2 * batch * 32 * 32 * 32 * (3 * 3 * 3)
    conv2 = 2 * batch * 16 * 16 * 64 * (3 * 3 * 32)
    d1 = 2 * batch * (8 * 8 * 64) * 128
    d2 = 2 * batch * 128 * 10
    fwd_batch = conv1 + conv2 + d1 + d2
    # training steps scan over the padded shard (pack_client_shards)
    steps = (sim.dataset.shard_size // batch) * n_clients
    lo, hi = 2.0 * fwd_batch * steps, 3.5 * fwd_batch * steps
    assert lo <= flops <= hi, (flops, lo, hi)


def test_spec_peak_lookup():
    class Fake:
        device_kind = "TPU v5 lite"

    assert tpu_spec_peak_tflops(Fake()) == 197.0

    class Unknown:
        device_kind = "cpu"

    assert tpu_spec_peak_tflops(Unknown()) is None
