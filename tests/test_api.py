"""Python API parity (fedml_tpu/api.py vs reference api/__init__.py:26-242):
cluster lifecycle, job launch/status/stop, build, model registry + deploy,
profile, diagnosis — all local-first."""
import json
import os

import numpy as np
import pytest

import fedml_tpu.api as api


@pytest.fixture()
def registry(tmp_path, monkeypatch):
    monkeypatch.setattr(api, "_REGISTRY", str(tmp_path / "models"))
    monkeypatch.setattr(api, "_PROFILE", str(tmp_path / "profile.json"))
    return tmp_path


def test_cluster_and_job_lifecycle(registry):
    cluster = api.cluster_start(n_workers=2, resources={"devices": 1,
                                                        "mem_mb": 64,
                                                        "tags": []})
    try:
        st = api.cluster_status(cluster)
        assert len(st["workers"]) == 2
        spec = {"type": "simulation", "requirements": {}, "config": {
            "data_args": {"dataset": "synthetic",
                          "extra": {"synthetic_samples_per_client": 16}},
            "model_args": {"model": "lr"},
            "train_args": {"federated_optimizer": "FedAvg",
                           "client_num_in_total": 2,
                           "client_num_per_round": 2, "comm_round": 1,
                           "epochs": 1, "batch_size": 8,
                           "learning_rate": 0.3},
            "validation_args": {"frequency_of_the_test": 0}}}
        jid = api.launch_job(spec, cluster=cluster)
        j = cluster.master.wait(jid, timeout=300)
        assert j.status == "FINISHED"
        assert api.run_status(jid, cluster) == "FINISHED"
        assert any(r["job_id"] == jid for r in api.run_list(cluster))
    finally:
        assert api.cluster_stop(cluster)


def test_run_stop_cancels_queued_job(registry):
    cluster = api.cluster_start(n_workers=0)   # nothing to run jobs
    try:
        jid = api.launch_job({"type": "python", "entry": "x",
                              "requirements": {}}, cluster=cluster)
        assert api.run_stop(jid, cluster)
        assert api.run_status(jid, cluster) == "STOPPED"
    finally:
        cluster.stop()


def test_model_registry_and_deploy(registry):
    rng = np.random.RandomState(0)
    params = {"Dense_0": {"kernel": rng.randn(4, 3).astype(np.float32),
                          "bias": np.zeros(3, np.float32)}}
    d = api.model_create("toy-lr", model="lr", params=params, num_classes=3)
    assert os.path.isdir(d)
    assert "toy-lr" in api.model_list()
    # params round-trip through the registry
    got = api._load_registered("toy-lr")["params"]
    np.testing.assert_array_equal(got["Dense_0"]["kernel"],
                                  params["Dense_0"]["kernel"])

    cluster = api.cluster_start(n_workers=1, resources={"devices": 1,
                                                        "mem_mb": 64,
                                                        "tags": []})
    try:
        dep = api.model_deploy("toy-lr", cluster, n_replicas=1, timeout=60)
        reps = dep.ready_replicas()
        assert reps, "deploy produced no ready replica"
        import urllib.request

        req = urllib.request.Request(
            reps[0].endpoint + "/predict",
            data=json.dumps({"inputs": [[0.1, 0.2, 0.3, 0.4]]}).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=30) as r:
            assert "predictions" in json.loads(r.read())
    finally:
        cluster.stop()
    assert api.model_delete("toy-lr")
    assert api.model_list() == []


def test_build_and_package(registry, tmp_path):
    src = tmp_path / "job"
    src.mkdir()
    (src / "main.py").write_text("print('x')\n")
    pkg = api.fedml_build(str(src), entry_point="main.py",
                          dest_folder=str(tmp_path / "dist"))
    assert os.path.isfile(pkg)
    api.model_create("pkgme", model="lr")
    mp = api.model_package("pkgme", dest_folder=str(tmp_path / "dist"))
    assert os.path.isfile(mp)


def test_profile_and_diagnosis(registry):
    prof = api.fedml_login("k-123")
    assert prof["mode"] == "local" and os.path.exists(api._PROFILE)
    assert api.logout() and not os.path.exists(api._PROFILE)
    # subset probes: the API contract is exercised without paying the full
    # ~30s battery a second time in tier-1 (test_cli_platform runs it once)
    rep = api.fedml_diagnosis(only=["jax", "wire_codec",
                                    "loopback_transport"])
    assert rep["checks"]["loopback_transport"]["ok"]
    assert "chaos_smoke" not in rep["checks"]
