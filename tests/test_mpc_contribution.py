"""MPC (SecAgg/LightSecAgg) + contribution assessors (reference test model:
python/tests/contribution_assessor/test_loo.py, core/mpc usage in
cross_silo/{secagg,lightsecagg})."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fedml_tpu import mpc
from fedml_tpu.contribution import (
    ContributionAssessorManager, GTGShapley, leave_one_out, mr_shapley,
    subset_aggregate,
)


# ------------------------------------------------------------------ finite
def test_quantize_roundtrip():
    x = np.array([1.5, -2.25, 0.0, 100.125])
    assert np.allclose(mpc.dequantize(mpc.quantize(x)), x)


def test_modular_inv():
    p = mpc.DEFAULT_PRIME
    for a in [2, 12345, p - 2]:
        assert (a * mpc.modular_inv(a, p)) % p == 1


def test_shamir_share_reconstruct():
    rng = np.random.default_rng(0)
    secret = rng.integers(0, mpc.DEFAULT_PRIME, 16, dtype=np.int64)
    shares = mpc.shamir_share(secret, n=5, t=2, rng=rng)
    # any 3 shares reconstruct
    rec = mpc.shamir_reconstruct(shares[[0, 2, 4]], [0, 2, 4])
    assert (rec == secret).all()
    rec2 = mpc.shamir_reconstruct(shares[[1, 3, 4]], [1, 3, 4])
    assert (rec2 == secret).all()
    # 2 shares give garbage (information-theoretic hiding)
    bad = mpc.shamir_reconstruct(shares[[0, 1]], [0, 1])
    assert not (bad == secret).all()


def test_lcc_encode_decode():
    p = mpc.DEFAULT_PRIME
    rng = np.random.default_rng(1)
    X = rng.integers(0, p, (3, 8), dtype=np.int64)  # K=3 chunks
    alpha = np.arange(1, 6, dtype=np.int64)         # N=5 eval points
    beta = np.arange(6, 9, dtype=np.int64)
    enc = mpc.lcc_encode(X, alpha, beta, p)
    dec = mpc.lcc_decode(enc[[0, 2, 4]], alpha[[0, 2, 4]], beta, p)
    assert (dec == X).all()


# ------------------------------------------------------------------ secagg
def test_secagg_no_dropout():
    rng = np.random.RandomState(0)
    vecs = [rng.randn(32) for _ in range(4)]
    agg = mpc.secagg_roundtrip(vecs, threshold=1)
    assert np.allclose(agg, np.sum(vecs, axis=0), atol=1e-3)


def test_secagg_with_dropout():
    rng = np.random.RandomState(1)
    vecs = [rng.randn(16) for _ in range(5)]
    agg = mpc.secagg_roundtrip(vecs, threshold=2, drop=[1, 3])
    expect = vecs[0] + vecs[2] + vecs[4]
    assert np.allclose(agg, expect, atol=1e-3)


def test_secagg_masked_vectors_hide_input():
    c = mpc.SecAggClient(0, 2, 1, seed=0)
    peer = mpc.SecAggClient(1, 2, 1, seed=1)
    x = np.ones(8)
    y = c.mask(x, {0: c.public_key(), 1: peer.public_key()})
    assert not np.allclose(mpc.dequantize(y), x, atol=1.0)  # masked


def test_lightsecagg_no_dropout():
    rng = np.random.RandomState(2)
    vecs = [rng.randn(20) for _ in range(4)]
    agg = mpc.lightsecagg_roundtrip(vecs, K=2, T=1)
    assert np.allclose(agg, np.sum(vecs, axis=0), atol=1e-3)


def test_lightsecagg_with_dropout():
    rng = np.random.RandomState(3)
    vecs = [rng.randn(12) for _ in range(5)]
    agg = mpc.lightsecagg_roundtrip(vecs, K=2, T=1, drop=[4])
    assert np.allclose(agg, np.sum(vecs[:4], axis=0), atol=1e-3)


def test_lightsecagg_too_many_dropouts():
    vecs = [np.ones(4) for _ in range(4)]
    with pytest.raises(ValueError):
        mpc.lightsecagg_roundtrip(vecs, K=2, T=1, drop=[0, 1, 2])


# ------------------------------------------------------------- contribution
def _toy_problem(m=4):
    """Utility = negative distance of aggregate to target; client 0 carries
    the target direction, client m-1 is useless."""
    target = jnp.ones(8)
    stacked = {"w": jnp.stack(
        [target] + [0.5 * target] * (m - 2) + [jnp.zeros(8)])}
    weights = jnp.ones(m)

    def utility(aggtree):
        return -jnp.linalg.norm(aggtree["w"] - target)

    return stacked, weights, utility


def test_subset_aggregate_mask():
    stacked = {"w": jnp.asarray([[2.0], [4.0], [6.0]])}
    agg = subset_aggregate(stacked, jnp.ones(3), jnp.asarray([1.0, 0.0, 1.0]))
    assert float(agg["w"][0]) == 4.0


def test_loo_ranks_clients():
    stacked, w, util = _toy_problem()
    loo = leave_one_out(stacked, w, [10, 11, 12, 13], util)
    assert loo[10] > loo[13]  # target-carrier beats zero-contributor


def test_mr_shapley_exact_ranks():
    stacked, w, util = _toy_problem()
    sv = mr_shapley(stacked, w, [0, 1, 2, 3], util)
    assert sv[0] > sv[1] >= sv[2] > sv[3]


def test_gtg_converges_and_ranks():
    stacked, w, util = _toy_problem()
    gtg = GTGShapley(seed=0, convergence_criteria=0.2, last_k=4)
    sv = gtg.run(stacked, w, [0, 1, 2, 3], util,
                 acc_last_round=-10.0, acc_aggregated=-1.0)
    assert sv[0] > sv[3]


def test_gtg_round_truncation():
    stacked, w, util = _toy_problem()
    gtg = GTGShapley()
    sv = gtg.run(stacked, w, [0, 1, 2, 3], util,
                 acc_last_round=0.5, acc_aggregated=0.5)
    assert all(v == 0.0 for v in sv.values())


def test_manager_dispatch_and_final_assignment():
    stacked, w, util = _toy_problem()
    mgr = ContributionAssessorManager("LOO")
    mgr.run(stacked, w, [0, 1, 2, 3], util, round_idx=0)
    mgr.run(stacked, w, [0, 1, 2, 3], util, round_idx=1)
    final = mgr.get_final_contribution_assignment()
    assert final[0] > final[3]
    with pytest.raises(ValueError):
        ContributionAssessorManager("bogus").run(stacked, w, [0], util)
