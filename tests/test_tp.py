"""Tensor parallelism for the LLM (llm/tp.py): TP forward must equal the
single-placement forward, params must actually be sharded, training must
work, and TP must compose with federated LoRA (sharded base, replicated
adapters)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from fedml_tpu.llm import TransformerLM, lora_init, lora_merge
from fedml_tpu.llm.tp import (
    make_tp_forward, make_tp_train_step, shard_params_tp, tp_param_specs,
)
from fedml_tpu.parallel.mesh import make_mesh

VOCAB = 32


def _model():
    return TransformerLM(vocab_size=VOCAB, d_model=64, n_layers=2,
                         n_heads=4, d_ff=128)


def _toks(n=8, t=16, seed=0):
    rs = np.random.RandomState(seed)
    starts = rs.randint(0, VOCAB, (n, 1))
    seqs = (starts + np.arange(t + 1)) % VOCAB
    return (jnp.asarray(seqs[:, :-1], jnp.int32),
            jnp.asarray(seqs[:, 1:], jnp.int32))


def test_tp_specs_cover_megatron_layout():
    model = _model()
    params = model.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32))["params"]
    specs = tp_param_specs(params)
    b0 = specs["block_0"]
    assert b0["wq"]["kernel"] == P(None, "tp")
    assert b0["wo"]["kernel"] == P("tp", None)
    assert b0["w_down"]["kernel"] == P("tp", None)
    assert specs["block_0"]["w_up"]["kernel"] == P(None, "tp")
    # norms replicated
    flat = jax.tree_util.tree_leaves_with_path(specs)
    assert any(s == P() for _p, s in flat)


def test_tp_forward_matches_unsharded():
    model = _model()
    params = model.init(jax.random.key(0), jnp.zeros((1, 16), jnp.int32))["params"]
    x, _ = _toks()
    ref = model.apply({"params": params}, x)

    mesh = make_mesh({"dp": 2, "tp": 4})
    tp_params = shard_params_tp(params, mesh)
    # kernels are genuinely distributed
    wq = tp_params["block_0"]["wq"]["kernel"]
    assert len(wq.sharding.device_set) == 8
    fwd = make_tp_forward(model, mesh)
    out = fwd(tp_params, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.slow
def test_tp_train_step_decreases_loss():
    model = _model()
    params = model.init(jax.random.key(1), jnp.zeros((1, 16), jnp.int32))["params"]
    mesh = make_mesh({"dp": 2, "tp": 4})
    tp_params = shard_params_tp(params, mesh)
    step = make_tp_train_step(model, mesh, lr=0.5)
    x, y = _toks(n=16)
    losses = []
    for _ in range(10):
        tp_params, loss = step(tp_params, x, y)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.7, losses
    # params remain TP-sharded after updates
    wq = tp_params["block_0"]["wq"]["kernel"]
    assert len(wq.sharding.device_set) == 8


def test_tp_base_with_replicated_lora_adapters():
    """The FedLLM composition: frozen base TP-sharded, LoRA adapters
    replicated; the merged forward must equal the unsharded merged
    forward."""
    model = _model()
    base = model.init(jax.random.key(0), jnp.zeros((1, 16), jnp.int32))["params"]
    adapters = lora_init(jax.random.key(1), base, rank=4)
    # make adapters nonzero so the merge actually matters
    adapters = jax.tree.map(lambda a: a + 0.01, adapters)
    x, _ = _toks(seed=2)
    ref = model.apply({"params": lora_merge(base, adapters)}, x)

    mesh = make_mesh({"dp": 2, "tp": 4})
    tp_base = shard_params_tp(base, mesh)

    @jax.jit
    def fwd(b, a, toks):
        return model.apply({"params": lora_merge(b, a)}, toks)

    out = fwd(tp_base, adapters, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_tp_shards_scan_layers_and_int8_base():
    """TP specs understand the stacked scan-layers layout AND the int8
    quantized base: the 7B-on-a-pod composition — scanned [L,...] params
    Megatron-split on their trailing dims, quantized {"q","s"} leaves
    sharded like the kernels they store — produces the same logits as the
    unsharded quantized model."""
    import numpy as np

    from fedml_tpu.llm.quant import dequantize_tree, quantize_tree_int8
    from fedml_tpu.llm.tp import shard_params_tp, tp_param_specs
    from fedml_tpu.llm.transformer import TransformerLM
    from fedml_tpu.parallel.mesh import make_mesh

    # dims large enough that block kernels cross the int8 size threshold
    V, D, L, H, FF, T = 64, 64, 3, 4, 256, 16
    model = TransformerLM(vocab_size=V, d_model=D, n_layers=L, n_heads=H,
                          d_ff=FF, scan_layers=True)
    base = model.init(jax.random.key(0),
                      jnp.zeros((1, T), jnp.int32))["params"]
    qbase = quantize_tree_int8(base)

    specs = tp_param_specs(qbase)
    # stacked col kernel shards its dout (axis 2), row its din (axis 1)
    assert str(specs["blocks"]["wq"]["kernel"]["q"]) == \
        str(jax.sharding.PartitionSpec(None, None, "tp"))
    assert str(specs["blocks"]["w_down"]["kernel"]["q"]) == \
        str(jax.sharding.PartitionSpec(None, "tp", None))
    # col scales shard dout; row scales replicate
    assert "tp" in str(specs["blocks"]["wq"]["kernel"]["s"])
    assert "tp" not in str(specs["blocks"]["w_down"]["kernel"]["s"])

    mesh = make_mesh({"dp": 2, "tp": 4})
    qtp = shard_params_tp(qbase, mesh)

    # forward over the dequantized TP base == unsharded dequantized model
    x = jnp.asarray(np.random.RandomState(0).randint(0, V, (4, T)),
                    jnp.int32)

    @jax.jit
    def fwd_q(qp, tokens):
        return model.apply({"params": dequantize_tree(qp, jnp.float32)},
                           tokens)

    ref = model.apply(
        {"params": dequantize_tree(qbase, jnp.float32)}, x)
    got = fwd_q(qtp, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=2e-4, rtol=2e-3)
    # sharded leaves really are distributed over tp
    q_leaf = qtp["blocks"]["wq"]["kernel"]["q"]
    assert "tp" in str(q_leaf.sharding.spec)
