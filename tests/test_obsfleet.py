"""Fleet observability (ISSUE 18): label-set exposition round-trips,
cross-process metric federation, clock-corrected trace merging, per-link
comm telemetry, and the crash flight recorder.

Everything here is CPU-fast and jax-free: the exposition layer is pure
string work, the collector takes an injected fetch, the comm tests ride
the in-process loopback transport, and the one subprocess test only
imports numpy-level fedml_tpu."""
import json
import os
import signal
import subprocess
import sys
import time
import urllib.request

import pytest

from fedml_tpu.utils import metrics as mx
from fedml_tpu.utils import postmortem as pm
from fedml_tpu.utils.events import recorder
from fedml_tpu.utils.obsfleet import (
    FleetCollector, announce, fleet_sums, install_registration,
    merge_traces, validate_obs_fleet, verify_merged_order,
)
from fedml_tpu.utils.prometheus import (
    format_labels, parse_labels, parse_prometheus, render_prometheus,
    series_key, split_by_label, split_series_key,
)


# ----------------------------------------------------- exposition + labels
_SNAP = {
    "counters": {"fed.rounds": 3},
    "gauges": {"fed.round.current": 2.0},
    "histograms": {"fed.round_s": {"count": 3, "sum": 0.75,
                                   "edges": [0.1, 0.5],
                                   "counts": [1, 2, 0]}},
}

# the pre-label format, byte for byte: satellite 1's compatibility pin —
# adding label support must not move a single character of label-less
# output (dashboards and the golden tests scrape this exact text)
_GOLDEN = """\
# HELP fed_rounds_total fedml_tpu counter fed.rounds
# TYPE fed_rounds_total counter
fed_rounds_total 3
# HELP fed_round_current fedml_tpu gauge fed.round.current
# TYPE fed_round_current gauge
fed_round_current 2
# HELP fed_round_s fedml_tpu histogram fed.round_s
# TYPE fed_round_s histogram
fed_round_s_bucket{le="0.1"} 1
fed_round_s_bucket{le="0.5"} 3
fed_round_s_bucket{le="+Inf"} 3
fed_round_s_sum 0.75
fed_round_s_count 3
"""


class TestExpositionLabels:
    def test_labelless_output_byte_identical_golden(self):
        assert render_prometheus(_SNAP) == _GOLDEN

    def test_label_ordering_sorted_le_last(self):
        s = format_labels({"le": "0.5", "b": "2", "a": "1"})
        assert s == '{a="1",b="2",le="0.5"}'

    def test_label_escaping_roundtrip(self):
        ugly = {"path": 'a"b\\c\nd', "plain": "ok"}
        inner = format_labels(ugly)[1:-1]
        assert parse_labels(inner) == ugly

    def test_series_key_split_inverse(self):
        key = series_key("comm.bytes", {"process": "p0", "dir": "tx"})
        base, lbls = split_series_key(key)
        assert (base, lbls) == ("comm.bytes", {"process": "p0",
                                               "dir": "tx"})
        assert split_series_key("plain_name") == ("plain_name", {})

    def test_labeled_render_parse_fixpoint(self):
        text = render_prometheus(_SNAP, labels={"process": "p0"})
        parsed = parse_prometheus(text)
        assert parsed["counters"]['fed_rounds_total{process="p0"}'] == 3
        # fixpoint: a parsed snapshot re-renders to the same parse
        assert parse_prometheus(render_prometheus(parsed)) == parsed

    def test_split_by_label_inverts_aggregation(self):
        text_a = render_prometheus(_SNAP, labels={"process": "a"})
        text_b = render_prometheus(_SNAP, labels={"process": "b"})
        merged = parse_prometheus(text_a + text_b)
        per = split_by_label(merged, "process")
        assert set(per) == {"a", "b"}
        bare = parse_prometheus(render_prometheus(_SNAP))
        assert per["a"] == bare and per["b"] == bare

    @pytest.mark.parametrize("text,frag", [
        ("fed_x_total 1\nnot a sample", "line 2"),
        ('fed_x{a=b} 1', "malformed"),
        ('fed_x{a="b} 1', "malformed"),
        ("# TYPE h histogram\n"
         'h_bucket{le="1"} 5\nh_bucket{le="+Inf"} 3\nh_count 3\nh_sum 0',
         "non-monotonic"),
        ("# TYPE h histogram\nh_bucket{le=\"1\"} 2\nh_count 2\nh_sum 0",
         "missing"),
        ("# TYPE h histogram\n"
         'h_bucket{le="1"} 2\nh_bucket{le="+Inf"} 2\nh_count 5\nh_sum 0',
         "count"),
    ])
    def test_malformed_exposition_is_loud(self, text, frag):
        with pytest.raises(ValueError, match=frag):
            parse_prometheus(text)


# --------------------------------------------------------- FleetCollector
def _expo(counters):
    return render_prometheus(
        {"counters": counters, "gauges": {}, "histograms": {}})


class TestFleetCollector:
    def test_scrape_aggregate_split_roundtrip(self):
        texts = {"http://a/metrics": render_prometheus(_SNAP),
                 "http://b/metrics": _expo({"fed.rounds": 7})}
        coll = FleetCollector({"a": "http://a/metrics",
                               "b": "http://b/metrics"},
                              fetch=lambda u: texts[u])
        assert coll.scrape_once() == {"a": True, "b": True}
        per = split_by_label(parse_prometheus(coll.aggregated_text()))
        assert set(per) == {"a", "b"}
        assert per["a"]["counters"]["fed_rounds_total"] == 3
        assert per["b"]["counters"]["fed_rounds_total"] == 7
        assert per["a"]["histograms"]["fed_round_s"]["count"] == 3

    def test_fleet_sums_equal_sum_of_per_process_scrapes(self):
        snap_a = parse_prometheus(render_prometheus(_SNAP))
        snap_b = parse_prometheus(render_prometheus(_SNAP))
        texts = {"http://a/metrics": render_prometheus(_SNAP),
                 "http://b/metrics": render_prometheus(_SNAP)}
        coll = FleetCollector({"a": "http://a/metrics",
                               "b": "http://b/metrics"},
                              fetch=lambda u: texts[u])
        coll.scrape_once()
        sums = coll.fleet_snapshot()["sums"]
        # pinned: the fleet column IS the sum of the per-process scrapes
        assert sums == fleet_sums({"a": snap_a, "b": snap_b})
        assert sums["counters"]["fed_rounds_total"] == 6
        h = sums["histograms"]["fed_round_s"]
        assert h["count"] == 6 and h["sum"] == 1.5
        assert h["buckets"][-1] == (float("inf"), 6.0)

    def test_failed_scrape_keeps_snapshot_and_marks_stale(self):
        texts = {"http://a/metrics": _expo({"fed.rounds": 1})}
        fail = [False]

        def fetch(url):
            if fail[0]:
                raise OSError("connection refused")
            return texts[url]

        coll = FleetCollector({"a": "http://a/metrics"}, fetch=fetch)
        assert coll.scrape_once() == {"a": True}
        assert not coll.fleet_snapshot()["processes"]["a"]["stale"]
        fail[0] = True
        assert coll.scrape_once() == {"a": False}
        ent = coll.fleet_snapshot()["processes"]["a"]
        assert ent["stale"] and "refused" in ent["error"]
        # last-good snapshot survives the failure for the columns
        assert ent["snapshot"]["counters"]["fed_rounds_total"] == 1

    def test_never_scraped_process_is_stale_with_reason(self):
        coll = FleetCollector({"ghost": "http://ghost/metrics"},
                              fetch=lambda u: _expo({}))
        ent = coll.fleet_snapshot()["processes"]["ghost"]
        assert ent["stale"] and ent["error"] == "never scraped"

    def test_http_serve_metrics_and_fleet(self):
        texts = {"http://a/metrics": _expo({"fed.rounds": 5})}
        coll = FleetCollector({"a": "http://a/metrics"},
                              fetch=lambda u: texts[u])
        coll.scrape_once()
        exp = coll.serve(port=0)
        try:
            with urllib.request.urlopen(exp.url, timeout=5) as r:
                body = r.read().decode()
            per = split_by_label(parse_prometheus(body))
            assert per["a"]["counters"]["fed_rounds_total"] == 5
            fleet_url = exp.url.rsplit("/", 1)[0] + "/fleet"
            with urllib.request.urlopen(fleet_url, timeout=5) as r:
                doc = json.loads(r.read())
            assert doc["processes"]["a"]["ok"]
            assert doc["sums"]["counters"]["fed_rounds_total"] == 5
        finally:
            coll.stop()

    def test_registration_over_loopback(self):
        from fedml_tpu.comm import FedCommManager
        from fedml_tpu.comm.loopback import LoopbackTransport, \
            release_router

        run = "obsfleet-reg"
        a = FedCommManager(LoopbackTransport(0, run), 0)
        b = FedCommManager(LoopbackTransport(1, run), 1)
        coll = FleetCollector()
        install_registration(a, coll)
        a.run(background=True)
        b.run(background=True)
        try:
            announce(b, "rank1", "http://127.0.0.1:9999/metrics")
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline and "rank1" not in \
                    coll.roster():
                time.sleep(0.01)
            assert coll.roster() == {
                "rank1": "http://127.0.0.1:9999/metrics"}
        finally:
            a.stop()
            b.stop()
            release_router(run)

    def test_validate_obs_fleet_rejects_garbage(self):
        ok = {"roster": {"a": "http://a/metrics"}, "port": 0,
              "interval_s": 1.0, "timeout_s": 2.0, "stale_after_s": 5.0}
        assert validate_obs_fleet(ok) is ok
        for bad in ({"rooster": {}},
                    {"roster": {"a": 1}},
                    {"port": 70000},
                    {"port": True},
                    {"interval_s": -1},
                    {"stale_after_s": float("nan")}):
            with pytest.raises(ValueError):
                validate_obs_fleet(bad)


# -------------------------------------------------- per-link comm telemetry
class TestLinkTelemetry:
    def _pair(self, run):
        from fedml_tpu.comm import FedCommManager, ReliableTransport
        from fedml_tpu.comm.loopback import LoopbackTransport
        from fedml_tpu.comm.reliable import RetryPolicy

        policy = RetryPolicy(ack_timeout_s=5.0)
        a = FedCommManager(
            ReliableTransport(LoopbackTransport(0, run), policy), 0)
        b = FedCommManager(
            ReliableTransport(LoopbackTransport(1, run), policy), 1)
        return a, b

    def test_link_bytes_and_ack_echo_rtt(self):
        from fedml_tpu.comm import Message
        from fedml_tpu.comm.loopback import release_router

        run = "obsfleet-rtt"
        a, b = self._pair(run)
        got = []
        b.register_message_receive_handler(
            "m", lambda m: got.append(m.get("i")))
        a.run(background=True)
        b.run(background=True)
        try:
            for i in range(5):
                a.send_message(Message("m", 0, 1).add("i", i))
            assert a.transport.flush(10) and not a.transport.failed
        finally:
            a.stop()
            b.stop()
            release_router(run)
        snap = mx.snapshot()
        assert snap["counters"]["comm.link.0.1.bytes"] > 0
        # every acked data frame yields one same-clock RTT sample
        rtt = snap["histograms"]["comm.link.0.1.rtt_ms"]
        assert rtt["count"] >= 5
        assert rtt["p99"] is not None

    def test_link_telemetry_toggle_is_honored(self):
        from fedml_tpu.comm import Message
        from fedml_tpu.comm.base import set_link_telemetry
        from fedml_tpu.comm.loopback import release_router

        run = "obsfleet-rtt-off"
        a, b = self._pair(run)
        a.run(background=True)
        b.run(background=True)
        set_link_telemetry(False)
        try:
            a.send_message(Message("m", 0, 1).add("i", 0))
            assert a.transport.flush(10)
        finally:
            set_link_telemetry(True)
            a.stop()
            b.stop()
            release_router(run)
        snap = mx.snapshot()
        assert not any(k.startswith("comm.link.")
                       for k in snap["counters"])
        assert not any(k.startswith("comm.link.")
                       for k in snap["histograms"])

    def test_link_table_joins_spans_and_instruments(self):
        from fedml_tpu.utils.attribution import link_table, \
            render_link_table

        att = {"totals": {"wall_s": 2.0,
                          "transport_by_link": {"0->1": 0.5}}}
        snap = {"counters": {"comm.link.0.1.bytes": 4096,
                             "comm.link.1.0.bytes": 128},
                "histograms": {"comm.link.0.1.rtt_ms": {
                    "count": 9, "sum": 18.0, "p50": 1.5, "p99": 4.0}}}
        rows = {r["link"]: r for r in link_table(att, snap)}
        # one row per link seen by EITHER surface
        assert set(rows) == {"0->1", "1->0"}
        assert rows["0->1"] == {"link": "0->1", "transport_s": 0.5,
                                "share": 0.25, "bytes": 4096,
                                "rtt_ms_p50": 1.5, "rtt_ms_p99": 4.0,
                                "rtt_count": 9}
        assert rows["1->0"]["bytes"] == 128
        assert rows["1->0"]["rtt_ms_p50"] is None
        text = render_link_table(att, snap)
        assert "0->1" in text and "4096" in text and "1.50ms" in text


# ------------------------------------------------------------ trace merge
def _trace(tmp_path, name, events):
    p = tmp_path / f"{name}.trace.json"
    p.write_text(json.dumps({"traceEvents": events}))
    return str(p)


def _send(ts, span_id, peer, dur=10):
    return {"ph": "X", "name": f"comm.send.ping", "ts": ts, "dur": dur,
            "pid": 0, "tid": 1, "args": {"span_id": span_id,
                                         "receiver": peer}}


def _handle(ts, parent_id, dur=10):
    return {"ph": "X", "name": f"comm.handle.ping", "ts": ts, "dur": dur,
            "pid": 0, "tid": 2, "args": {"parent_id": parent_id}}


class TestMergeTraces:
    def test_midpoint_offset_recovery_and_flows(self, tmp_path):
        # B's trace clock runs 100_000 µs ahead of A's. One message each
        # way: a→b bounds the offset above (100_500), b→a below (99_800);
        # the midpoint estimate is 100_150 µs.
        pa = _trace(tmp_path, "A", [
            _send(1000, "sA1", 1),
            _handle(5000, "sB1"),
        ])
        pb = _trace(tmp_path, "B", [
            _handle(101500, "sA1"),
            _send(104800, "sB1", 0),
        ])
        out = str(tmp_path / "merged.trace.json")
        res = merge_traces([("A", pa), ("B", pb)], out_path=out)
        assert res["pairs"] == 2 and res["flows"] == 2
        assert res["clamped"] == 0
        assert res["offsets_us"] == [0.0, 100150.0]
        assert res["clock_skew_ms"] == {"A->B": 100.15}
        assert mx.snapshot()["gauges"]["obs.clock_skew_ms.A.B"] == 100.15
        doc = json.load(open(out))
        assert verify_merged_order(doc) == 0
        # per-process pid lanes with the input names
        lanes = {ev["pid"]: ev["args"]["name"] for ev in
                 doc["traceEvents"]
                 if ev.get("ph") == "M" and ev["name"] == "process_name"}
        assert lanes == {0: "A", 1: "B"}
        assert doc["otherData"]["clock_skew_ms"] == {"A->B": 100.15}

    def test_infeasible_constraints_clamp_but_never_reorder(self, tmp_path):
        # lower bound (99_800) above upper bound (99_100): no offset can
        # satisfy both directions — the midpoint leaves each recv 350 µs
        # before its send, and the invariant wins by clamping both.
        pa = _trace(tmp_path, "A", [
            _send(1000, "sA1", 1),
            _handle(5000, "sB1"),
        ])
        pb = _trace(tmp_path, "B", [
            _handle(100100, "sA1"),
            _send(104800, "sB1", 0),
        ])
        res = merge_traces([("A", pa), ("B", pb)])
        assert res["clamped"] == 2
        assert verify_merged_order(res["trace"]) == 0

    def test_one_direction_uses_tight_bound(self, tmp_path):
        pa = _trace(tmp_path, "A", [_send(1000, "sA1", 1)])
        pb = _trace(tmp_path, "B", [_handle(101500, "sA1")])
        res = merge_traces([("A", pa), ("B", pb)])
        assert res["offsets_us"] == [0.0, 100500.0]
        assert verify_merged_order(res["trace"]) == 0

    def test_unpaired_processes_merge_uncorrected(self, tmp_path):
        pa = _trace(tmp_path, "A", [_send(1000, "sA1", 1)])
        pb = _trace(tmp_path, "B", [{"ph": "X", "name": "train",
                                     "ts": 50, "dur": 5, "pid": 0,
                                     "tid": 0, "args": {}}])
        res = merge_traces([("A", pa), ("B", pb)])
        assert res["flows"] == 0 and res["offsets_us"] == [0.0, 0.0]
        assert {"A", "B"} == set(res["processes"])


# -------------------------------------------------------- flight recorder
_SIGTERM_CHILD = """
import sys, time
from fedml_tpu.utils import postmortem as pm
from fedml_tpu.utils.events import recorder
pm.arm(sys.argv[1], process="victim")
with recorder.span("victim.final"):
    pass
print("ready", flush=True)
time.sleep(30)
"""


class TestFlightRecorder:
    def test_ring_captures_spans_frames_and_metric_deltas(self, tmp_path):
        pm.flight.arm(str(tmp_path), process="p0",
                      install_handlers=False)
        with recorder.span("obsfleet.test.step"):
            pass
        pm.note_frame("send", "grad", 0, 1, 128, {"seq": 7})
        mx.inc("fed.test.obsfleet", 2)
        doc = pm.flight.snapshot("probe")
        assert doc["last_span"] == "obsfleet.test.step"
        assert doc["process"] == "p0"
        f = [fr for fr in doc["frames"] if fr["type"] == "grad"]
        assert f and f[0]["bytes"] == 128
        assert f[0]["headers"] == {"seq": 7}
        # deltas are vs the arm-time baseline, not absolute counters
        assert doc["metric_deltas"]["fed.test.obsfleet"] == 2

    def test_flush_writes_postmortem_with_reason(self, tmp_path):
        pm.flight.arm(str(tmp_path), process="p0",
                      install_handlers=False)
        with recorder.span("obsfleet.final"):
            pass
        path = pm.flight.flush("manual")
        assert path == str(tmp_path / "postmortem.json")
        doc = pm.load_postmortem(str(tmp_path))
        assert doc["reason"] == "manual"
        assert doc["last_span"] == "obsfleet.final"
        assert mx.snapshot()["counters"]["obs.postmortem.flushes"] == 1

    def test_inflight_spill_survives_as_hard_kill(self, tmp_path):
        pm.flight.spill_every_s = 0.05
        try:
            pm.flight.arm(str(tmp_path), process="p0",
                          install_handlers=False)
            with recorder.span("obsfleet.spilled"):
                pass
            deadline = time.monotonic() + 5
            path = tmp_path / "postmortem.json"
            while time.monotonic() < deadline and not path.exists():
                time.sleep(0.02)
            assert path.exists(), "spill cadence never wrote"
            doc = pm.load_postmortem(str(tmp_path))
            # an inflight spill reads back as a hard kill: the process
            # never reached a graceful flush
            assert doc["reason"].startswith("hard-kill")
        finally:
            pm.flight.spill_every_s = 1.0

    def test_record_kill_flushes_when_armed(self, tmp_path):
        pm.flight.arm(str(tmp_path), process="silo1",
                      install_handlers=False)
        assert pm.record_kill("rank1")
        doc = pm.load_postmortem(str(tmp_path))
        assert doc["reason"] == "kill:rank1"
        assert mx.snapshot()["counters"]["obs.postmortem.kills"] == 1

    def test_disabled_ring_appends_nothing(self, tmp_path):
        pm.flight.set_enabled(False)
        with recorder.span("obsfleet.invisible"):
            pass
        pm.flight.set_enabled(True)
        doc = pm.flight.snapshot("probe")
        assert all(s.get("name") != "obsfleet.invisible"
                   for s in doc["spans"])

    def test_load_postmortem_absent_or_corrupt_is_none(self, tmp_path):
        assert pm.load_postmortem(str(tmp_path)) is None
        (tmp_path / "postmortem.json").write_text("{not json")
        assert pm.load_postmortem(str(tmp_path)) is None

    @pytest.mark.skipif(sys.platform == "win32", reason="posix signals")
    def test_sigterm_flushes_postmortem_in_real_process(self, tmp_path):
        env = dict(os.environ)
        env["PYTHONPATH"] = os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))) + os.pathsep + env.get(
            "PYTHONPATH", "")
        env.setdefault("JAX_PLATFORMS", "cpu")
        proc = subprocess.Popen(
            [sys.executable, "-c", _SIGTERM_CHILD, str(tmp_path)],
            stdout=subprocess.PIPE, env=env, text=True)
        try:
            assert proc.stdout.readline().strip() == "ready"
            proc.send_signal(signal.SIGTERM)
            proc.wait(timeout=15)
        finally:
            if proc.poll() is None:
                proc.kill()
        doc = pm.load_postmortem(str(tmp_path))
        assert doc is not None and doc["reason"] == "sigterm"
        assert doc["process"] == "victim"
        assert doc["last_span"] == "victim.final"
