"""Round-block execution (parallel/round.py build_block_fn + the simulator's
pipelined blocked driver): K federated rounds scanned inside ONE XLA program
must be indistinguishable — history, final params, client_states, DP epsilon —
from K per-round dispatches, and the block program must compile exactly once
across a multi-block run (a retrace per block would pay back the dispatch
savings with interest)."""
import jax
import numpy as np

import fedml_tpu
from fedml_tpu.simulation.simulator import Simulator


def _cfg(backend="sp", **train_overrides):
    d = {
        "common_args": {"training_type": "simulation", "random_seed": 0},
        "data_args": {"dataset": "synthetic", "partition_method": "hetero",
                      "partition_alpha": 0.5,
                      "extra": {"synthetic_samples_per_client": 32}},
        "model_args": {"model": "lr"},
        "train_args": {
            "federated_optimizer": "FedAvg",
            "client_num_in_total": 8,
            "client_num_per_round": 4,
            "comm_round": 12,
            "epochs": 1,
            "batch_size": 8,
            "learning_rate": 0.1,
            **train_overrides,
        },
        "validation_args": {"frequency_of_the_test": 0},
        "comm_args": {"backend": backend},
    }
    return fedml_tpu.init(config=d)


def _assert_histories_match(h_ref, h_blk):
    assert len(h_ref) == len(h_blk)
    for a, b in zip(h_ref, h_blk):
        assert set(a) == set(b), f"row keys differ: {set(a)} vs {set(b)}"
        assert a["round"] == b["round"]
        for k in a:
            np.testing.assert_allclose(
                a[k], b[k], rtol=2e-5, atol=1e-6,
                err_msg=f"history[{a['round']}][{k}] diverged")


def _assert_trees_match(t_ref, t_blk, rtol=2e-5, atol=1e-6):
    ref, blk = jax.device_get(t_ref), jax.device_get(t_blk)
    for a, b in zip(jax.tree.leaves(ref), jax.tree.leaves(blk)):
        np.testing.assert_allclose(a, b, rtol=rtol, atol=atol)


def _run_pair(backend="sp", rounds_per_block=4, **overrides):
    """Run the identical workload per-round and blocked; return both sims."""
    ref = Simulator(_cfg(backend=backend, **overrides))
    ref.run()
    blk = Simulator(_cfg(backend=backend, **overrides,
                         extra={"rounds_per_block": rounds_per_block,
                                **overrides.get("extra", {})}))
    blk.run()
    return ref, blk


def test_k4_block_matches_per_round_sp():
    """K=4 on the single-device path: bit-compatible history + final state."""
    ref, blk = _run_pair(backend="sp", rounds_per_block=4)
    assert blk.block_fn is not None, "blocked run never used the block fn"
    _assert_histories_match(ref.history, blk.history)
    _assert_trees_match(ref.server_state.params, blk.server_state.params)
    _assert_trees_match(ref.client_states, blk.client_states)


def test_k4_block_matches_per_round_xla_padded_with_eval_cadence():
    """The hard case: 8-device mesh with pad rounds (5 sampled clients pad to
    8), stateful clients (SCAFFOLD control variates scatter back through the
    scan), and an eval cadence (6) that K=4 does not divide — so the run
    mixes full blocks with per-round ragged pieces around eval barriers."""
    over = dict(federated_optimizer="SCAFFOLD",
                client_num_in_total=12, client_num_per_round=5)
    ref = Simulator(_cfg(backend="xla", **over))
    assert ref.mesh is not None and ref.mesh.devices.size == 8
    ref.cfg.validation_args.frequency_of_the_test = 6
    ref.run()
    cfg_b = _cfg(backend="xla", extra={"rounds_per_block": 4}, **over)
    cfg_b.validation_args.frequency_of_the_test = 6
    blk = Simulator(cfg_b)
    blk.run()
    assert blk.block_fn is not None, "blocked run never used the block fn"
    # eval rows land on the same rounds in both runs
    assert [r["round"] for r in ref.history if "test_acc" in r] == \
           [r["round"] for r in blk.history if "test_acc" in r]
    _assert_histories_match(ref.history, blk.history)
    _assert_trees_match(ref.server_state.params, blk.server_state.params)
    _assert_trees_match(ref.client_states, blk.client_states)


def test_block_dp_epsilon_matches_per_round():
    """The DP accountant advances once per round in blocked mode too: every
    history row's epsilon matches the per-round run at the same composition
    count, and the noise itself (rng-driven, inside the program) is
    identical."""
    dp = {"dp_args": {"enable_dp": True, "dp_solution_type": "ldp",
                      "epsilon": 0.9, "delta": 1e-5, "clipping_norm": 1.0}}
    ref = Simulator(fedml_tpu.init(config={**_raw(), **dp}))
    ref.run()
    raw_b = _raw()
    raw_b["train_args"]["extra"] = {"rounds_per_block": 4}
    blk = Simulator(fedml_tpu.init(config={**raw_b, **dp}))
    blk.run()
    assert all("dp_epsilon" in r for r in blk.history)
    _assert_histories_match(ref.history, blk.history)
    _assert_trees_match(ref.server_state.params, blk.server_state.params)


def _raw():
    return {
        "common_args": {"training_type": "simulation", "random_seed": 0},
        "data_args": {"dataset": "synthetic",
                      "extra": {"synthetic_samples_per_client": 32}},
        "model_args": {"model": "lr"},
        "train_args": {
            "federated_optimizer": "FedAvg",
            "client_num_in_total": 8, "client_num_per_round": 8,
            "comm_round": 8, "epochs": 1, "batch_size": 8,
            "learning_rate": 0.1,
        },
        "validation_args": {"frequency_of_the_test": 0},
        "comm_args": {"backend": "sp"},
    }


def test_health_stats_do_not_change_training():
    """The in-jit per-client health stats (ISSUE 3) are observation-only:
    a run with health_stats=False produces EXACTLY (rtol=0) the history and
    final params of the default-on run — the health arrays are extra
    outputs, never inputs."""
    on = Simulator(_cfg())             # health_stats defaults to on
    on.run()
    off = Simulator(_cfg(extra={"health_stats": False}))
    off.run()
    assert len(on.history) == len(off.history)
    for a, b in zip(on.history, off.history):
        assert a == b, f"history diverged at round {a['round']}"
    _assert_trees_match(on.server_state.params, off.server_state.params,
                        rtol=0, atol=0)


def test_health_block_equivalence_and_single_transfer_shape():
    """Acceptance pin (ISSUE 3): with health enabled (the default), blocked
    K=4 and per-round runs still produce identical history/params/
    client_states — the existing equivalence suite runs health-on already;
    this pin additionally checks the health arrays themselves ride the
    metrics transfer with the right shape and sane values in BOTH engines,
    on the 8-device mesh with pad rounds (5 sampled -> 8 slots)."""
    import jax.numpy as jnp

    over = dict(client_num_in_total=12, client_num_per_round=5)
    ref, blk = _run_pair(backend="xla", rounds_per_block=4, **over)
    _assert_histories_match(ref.history, blk.history)
    _assert_trees_match(ref.server_state.params, blk.server_state.params)
    # both trackers saw every round
    assert ref.health is not None and blk.health is not None
    assert ref.health.rounds_seen == blk.health.rounds_seen == 12
    # the health arrays really are per-slot [m] outputs of the jitted round
    ids, weights = ref._pad_ids(ref.sample_clients(0))
    out = ref.round_fn(
        ref.server_state, ref.client_states, ref.data,
        jnp.asarray(ids), jnp.asarray(weights),
        jax.random.fold_in(jax.random.key(0), 99), ref.hook_state)
    h = jax.device_get(out.metrics["health"])
    assert set(h) == {"update_norm", "cosine", "loss_delta"}
    for v in h.values():
        assert v.shape == (len(ids),)
    assert np.all(h["update_norm"] >= 0)
    assert np.all(np.abs(h["cosine"]) <= 1.0 + 1e-5)


def test_k1_uses_per_round_driver():
    """rounds_per_block=1 must reduce to today's behavior exactly: the
    blocked driver is never entered and the block fn is never built."""
    cfg = _cfg(extra={"rounds_per_block": 1})
    sim = Simulator(cfg)
    sim.run()
    assert sim.block_fn is None
    ref = Simulator(_cfg())
    ref.run()
    _assert_histories_match(ref.history, sim.history)


def test_block_knobs_validated_at_config_load():
    """A typo'd rounds_per_block fails at init, not as a shape error K
    rounds into a run."""
    import pytest

    for bad in (0, -3, 2.5, "eight"):
        with pytest.raises(ValueError, match="rounds_per_block"):
            _cfg(extra={"rounds_per_block": bad})
    with pytest.raises(ValueError, match="block_pipeline_depth"):
        _cfg(extra={"block_pipeline_depth": 0})
    _cfg(extra={"rounds_per_block": 8, "block_pipeline_depth": 3})  # ok


def test_block_fn_compiles_once_across_blocks():
    """Retrace guard: a 12-round K=4 run is 3 block dispatches of ONE
    compiled program. Re-running the warm simulator (same shapes, stacked
    [K, m] schedule rebuilt from fresh numpy arrays each block) must record
    ZERO new backend compiles via jax._src.monitoring — any shape- or
    weak-type-driven retrace would show up here."""
    from jax._src import monitoring
    from jax._src.dispatch import BACKEND_COMPILE_EVENT

    sim = Simulator(_cfg(extra={"rounds_per_block": 4}))
    sim.run()              # cold run: compiles the block program once
    compiles = []

    def listener(event, duration, **kw):
        if event == BACKEND_COMPILE_EVENT:
            compiles.append(event)

    monitoring.register_event_duration_secs_listener(listener)
    try:
        sim.run()          # 3 more K=4 blocks through the warm caches
    finally:
        monitoring._unregister_event_duration_listener_by_callback(listener)
    assert not compiles, (
        f"block fn retraced: {len(compiles)} backend compiles during a "
        "warm multi-block run (expected 0)")
