"""Federated analytics (reference: python/fedml/fa/). Each task's federated
result must equal the centralized computation on the pooled data; TrieHH
must discover the true heavy hitters; the cross-silo runtime must agree
with the simulator."""
import numpy as np
import pytest

from fedml_tpu.fa import FA_TASKS, FASimulator, run_fa_cross_silo


def _numeric_clients(seed=0, n_clients=5, per=200):
    rs = np.random.RandomState(seed)
    return [rs.randn(per) * (i + 1) + i for i in range(n_clients)]


def test_avg_matches_centralized():
    data = _numeric_clients()
    sim = FASimulator("avg", data)
    out = sim.run()
    pooled = np.concatenate(data)
    np.testing.assert_allclose(out, pooled.mean(), rtol=1e-9)


def test_frequency_estimation_matches_centralized():
    rs = np.random.RandomState(1)
    data = [rs.randint(0, 7, 300) for _ in range(4)]
    out = FASimulator("frequency_estimation", data).run()
    pooled = np.concatenate(data)
    for v in range(7):
        np.testing.assert_allclose(
            out[str(v)], (pooled == v).mean(), atol=1e-12)


def test_union_and_intersection():
    data = [[1, 2, 3, 4], [3, 4, 5], [4, 3, 9]]
    assert FASimulator("union", data).run() == sorted(
        {str(v) for v in [1, 2, 3, 4, 5, 9]})
    assert FASimulator("intersection", data).run() == ["3", "4"]


def test_k_percentile_histogram():
    data = _numeric_clients(seed=2)
    pooled = np.concatenate(data)
    out = FASimulator("k_percentile", data, k=75.0, lo=-50, hi=50,
                      bins=4096).run()
    true = np.percentile(pooled, 75.0)
    assert abs(out - true) < 0.1, (out, true)


def test_triehh_finds_heavy_hitters():
    """Two dominant words across clients; the trie must grow to contain
    them and not the rare noise words."""
    rs = np.random.RandomState(3)
    vocab_heavy = ["sunshine", "moonlight"]
    vocab_rare = ["aardvark", "zephyr", "quixote", "bramble"]
    clients = []
    for _ in range(10):
        words = (vocab_heavy * 100
                 + [vocab_rare[rs.randint(len(vocab_rare))] for _ in range(4)])
        rs.shuffle(words)
        clients.append(words)
    sim = FASimulator("triehh", clients, num_rounds=12, epsilon=8.0)
    out = sim.run()
    full_words = [w for w in out if w in vocab_heavy]
    assert set(full_words) == set(vocab_heavy), out
    assert not any(w in out for w in vocab_rare), out


def test_fa_cross_silo_matches_simulator():
    data = [[1, 2, 3], [2, 3, 4], [3, 4, 5]]
    server = run_fa_cross_silo("frequency_estimation", data)
    sim_out = FASimulator("frequency_estimation", data).run()
    assert server.result == sim_out
    assert len(server.history) == 1


def test_fa_cross_silo_triehh_matches_simulator():
    """Stochastic task parity: both runtimes must subsample identically
    (same (seed, round, data-index) rng identity)."""
    words = [["the"] * 200 + ["and"] * 160 + ["xylophone"] for _ in range(6)]
    sim_out = FASimulator("triehh", words, num_rounds=8, epsilon=8.0).run()
    server = run_fa_cross_silo("triehh", words, num_rounds=8, epsilon=8.0)
    assert server.result == sim_out
    assert "the" in sim_out and "and" in sim_out


def test_fa_cross_silo_avg():
    data = _numeric_clients(n_clients=3, per=50)
    server = run_fa_cross_silo("avg", data)
    pooled = np.concatenate(data)
    np.testing.assert_allclose(server.result, pooled.mean(), rtol=1e-9)


def test_unknown_task_errors():
    with pytest.raises(KeyError, match="fa_task"):
        FA_TASKS.get("bogus_task")
