"""FedMLRunner dispatch + CLI (reference: python/fedml/runner.py:19,
cli/cli.py)."""
import json
import subprocess
import sys

import numpy as np
import pytest

import fedml_tpu
from fedml_tpu.runner import FedMLRunner


def _cfg(**common):
    c = {
        "common_args": {"training_type": "simulation", **common},
        "data_args": {"dataset": "synthetic",
                      "extra": {"synthetic_samples_per_client": 16}},
        "model_args": {"model": "lr"},
        "train_args": {"federated_optimizer": "FedAvg",
                       "client_num_in_total": 4, "client_num_per_round": 4,
                       "comm_round": 2, "epochs": 1, "batch_size": 8,
                       "learning_rate": 0.1},
        "validation_args": {"frequency_of_the_test": 0},
    }
    return fedml_tpu.init(config=c)


def test_runner_simulation_dispatch():
    runner = FedMLRunner(_cfg())
    from fedml_tpu.simulation.simulator import Simulator

    assert isinstance(runner.runner, Simulator)
    hist = runner.run()
    assert len(hist) == 2


def test_runner_async_dispatch():
    cfg = _cfg()
    cfg.train_args.extra["async"] = True
    from fedml_tpu.simulation.async_simulator import AsyncSimulator

    assert isinstance(FedMLRunner(cfg).runner, AsyncSimulator)


def test_runner_centralized_dispatch():
    cfg = _cfg(training_type="centralized")
    from fedml_tpu.centralized import CentralizedTrainer

    assert isinstance(FedMLRunner(cfg).runner, CentralizedTrainer)


def test_runner_fa_dispatch():
    cfg = _cfg()
    cfg.train_args.extra["fa_task"] = "avg"
    data = [np.arange(10.0), np.arange(10.0) + 1]
    runner = FedMLRunner(cfg, dataset=data)
    out = runner.run()
    np.testing.assert_allclose(out, np.concatenate(
        [np.arange(10.0), np.arange(10.0) + 1]).mean())


def test_runner_cross_silo_roles():
    from fedml_tpu.cross_silo import FedClientManager, FedServerManager
    from fedml_tpu.models import hub

    cfg = _cfg(training_type="cross_silo")
    cfg.train_args.client_num_in_total = 2
    model = hub.create("lr", 3)
    srv = FedMLRunner(cfg, model=model, role="server", rank=0,
                      input_shape=(8,))
    assert isinstance(srv.runner, FedServerManager)
    rs = np.random.RandomState(0)
    x = rs.randn(32, 8).astype(np.float32)
    y = rs.randint(0, 3, 32).astype(np.int32)
    cli = FedMLRunner(cfg, dataset=(x, y), model=model, role="client",
                      rank=1)
    assert isinstance(cli.runner, FedClientManager)


def test_runner_unknown_type_raises():
    cfg = _cfg()
    cfg.common_args.training_type = "weird"
    with pytest.raises(ValueError, match="no runner"):
        FedMLRunner(cfg)


def test_cli_version_and_env():
    out = subprocess.run(
        [sys.executable, "-m", "fedml_tpu", "version"],
        capture_output=True, text=True, cwd="/root/repo")
    assert out.returncode == 0 and "fedml_tpu" in out.stdout
    out = subprocess.run(
        [sys.executable, "-m", "fedml_tpu", "env"],
        capture_output=True, text=True, cwd="/root/repo")
    assert out.returncode == 0
    info = json.loads(out.stdout)
    assert "jax" in info and "devices" in info


@pytest.mark.slow
def test_cli_run_simulation(tmp_path):
    cfg_yaml = tmp_path / "cfg.yaml"
    cfg_yaml.write_text("""
common_args:
  training_type: simulation
  random_seed: 0
data_args:
  dataset: synthetic
model_args:
  model: lr
train_args:
  federated_optimizer: FedAvg
  client_num_in_total: 2
  client_num_per_round: 2
  comm_round: 2
  epochs: 1
  batch_size: 8
  learning_rate: 0.1
validation_args:
  frequency_of_the_test: 0
""")
    out = subprocess.run(
        [sys.executable, "-m", "fedml_tpu", "run", "--cf", str(cfg_yaml)],
        capture_output=True, text=True, cwd="/root/repo")
    assert out.returncode == 0, out.stderr[-2000:]
    row = json.loads(out.stdout.strip().splitlines()[-1])
    assert row["round"] == 1
