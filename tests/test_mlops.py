"""MLOps facade + sys-perf monitor (reference: core/mlops/__init__.py
event/log API, mlops_device_perfs.py sampling loops)."""
import json
import time

import fedml_tpu
from fedml_tpu import mlops
from fedml_tpu.utils.events import recorder
from fedml_tpu.utils.sysperf import SysPerfMonitor, sample_sysperf


def test_sample_sysperf_fields():
    row = sample_sysperf()
    assert row["rss_mb"] > 0
    assert 0 <= row["host_mem_pct"] <= 100
    assert row["threads"] >= 1


def test_sysperf_monitor_emits_rows():
    n0 = len(recorder.metrics)
    mon = SysPerfMonitor(interval=0.1).start()
    time.sleep(0.45)
    mon.stop()
    rows = [m for m in recorder.metrics[n0:] if "sysperf" in m]
    assert len(rows) >= 2
    assert rows[0]["sysperf"]["rss_mb"] > 0


def test_mlops_facade_end_to_end(tmp_path):
    cfg = fedml_tpu.init(config={
        "tracking_args": {"enable_tracking": True,
                          "log_file_dir": str(tmp_path),
                          "run_name": "mlops-test",
                          "extra": {"sysperf_interval": 0.2}},
    })
    n_sinks = len(recorder.sinks)
    n0 = len(recorder.metrics)
    mlops.init(cfg)
    try:
        with mlops.event("train", round=1):
            time.sleep(0.01)
        mlops.event("comm", event_started=True)
        time.sleep(0.01)
        mlops.event("comm", event_started=False)
        mlops.log({"acc": 0.5})
        mlops.log_round_info(10, 3)
        import logging

        logging.getLogger("fedml_tpu.test").info("hello log daemon")
        time.sleep(0.3)   # let sysperf tick
    finally:
        mlops.finish()
        del recorder.sinks[n_sinks:]

    rows = recorder.metrics[n0:]
    assert any(r.get("acc") == 0.5 for r in rows)
    assert any(r.get("round_index") == 3 for r in rows)
    assert any(r.get("event") == "comm" and r["duration"] > 0 for r in rows)
    assert any("sysperf" in r for r in rows)
    # runtime log file captured the logging output
    logtxt = (tmp_path / "mlops-test.log").read_text()
    assert "hello log daemon" in logtxt
    # events jsonl sink got the rows too
    events = (tmp_path / "mlops-test.events.jsonl").read_text().splitlines()
    kinds = {json.loads(l)["kind"] for l in events}
    assert {"span", "metrics"} <= kinds
    # idempotent init/finish
    mlops.finish()


def test_system_stats_facade():
    assert mlops.system_stats()["rss_mb"] > 0
